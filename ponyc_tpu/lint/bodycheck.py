"""Behaviour-body source analyzer — pure-AST rules R6–R9.

≙ the reference compiler's SYNTACTIC body checks: safeto.c proves
sendability and the verify stage (src/libponyc/verify/fun.c) walks
every method body before codegen. The probe-based graph rules (R0–R5)
need a trace; an entire class of defects dies *at* the trace — Python
control flow on traced values surfaces as an opaque
TracerBoolConversionError stack, non-static send counts as shape
errors — or worse, traces fine and silently corrupts semantics (host
I/O runs once at trace time, an in-place ``st`` mutation is dropped by
a rebuilt return dict). This module catches that class at DEFINITION
time with file:line:col findings, by walking the behaviour's AST:

  R6  traced-value control flow: ``if``/``while``/ternary/``and``/
      ``or``/``not``/chained comparison/``assert``/iteration branching
      on a state field or behaviour argument — the trace cannot
      branch; use ``when=`` masks, ``jnp.where``, ``&``/``|``/``~``.
                                                              [error]
  R7  non-static effect sites: ``self.send``/``spawn``/``exit``/
      ``yield_``/blob ops under loops whose trip count is not a
      trace-time constant or inside nested (lax-body) functions
      [error/warning]; behaviour bodies that can fall off the end —
      or ``return`` bare — instead of returning the state dict
      on every path.                                    [error]
  R8  state-key discipline: ``st["key"]`` reads/writes and return-dict
      keys checked against the type's declared annotations with
      did-you-mean for typos [error]; return dicts that drop declared
      fields [error]; writes to Val/immutable-declared fields
      [warning]; in-place ``st`` mutations dropped by a rebuilt
      return dict [warning]; assignment to ``self.<attr>`` [error].
  R9  host impurity & linear handles: ``print``/``open``/``time.*``/
      ``np.random``/``random`` calls, ``global``/``nonlocal``, and
      mutation of captured mutable globals inside a traced body (they
      run ONCE, at trace) [warning]; a forward dataflow pass flagging
      Iso/Blob handles used again after being passed to ``self.send``
      / ``blob_free`` — the use-after-move check the trace can only
      catch dynamically — and writes to val (frozen) blobs. [error]

Everything here is ``ast`` only — NO JAX, NO tracing, and no import of
the target: `check_source`/`check_path` analyse files that do not even
import (missing deps, broken top level). `check_types` runs the same
rules over already-imported actor types via `inspect.getsource`, which
is how lint_types/lint_module/lint_program/Program.lint pick R6–R9 up.
Analysis is a single linear walk per behaviour — well under 100 ms per
module. HOST behaviours run real Python: R6/R9 do not apply and loop
rules are skipped; the return-path and state-key rules still do.
"""

from __future__ import annotations

import ast
import dataclasses
import difflib
import os
import textwrap
from typing import (Callable, Dict, List, Optional, Sequence, Set,
                    Tuple)

from .rules import Finding, line_suppressed, sort_findings

# Annotation root name → capability mode (the AST-side mirror of
# ops.pack.cap_mode; kept string-only so this module never imports
# JAX). Ref is an actor ref (tag-like wiring, freely aliased).
_CAP_BY_NAME = {"Iso": "iso", "Trn": "trn", "Mut": "ref", "Val": "val",
                "Box": "box", "Tag": "tag", "Blob": "iso",
                "BlobVal": "val"}
_IMMUTABLE_ROOTS = {"Val", "BlobVal", "Box"}
_LINEAR_ROOTS = {"Iso", "Blob"}          # moved-unique handles

# Context effect methods whose per-dispatch count/flags must be
# trace-time static (the engine pads to declared budgets).
_EFFECTS = {"send", "spawn", "spawn_sync", "exit", "yield_", "destroy",
            "error_int", "blob_alloc", "blob_free"}
# Context calls returning traced values.
_TRACED_CALLS = {"spawn", "spawn_sync", "blob_alloc", "blob_get",
                 "blob_length", "blob_freeze"}
# Builtins whose call is host I/O (runs once, at trace).
_IMPURE_BUILTINS = {"print", "open", "input", "breakpoint"}
# Attribute roots whose calls are host-impure in a traced body.
_IMPURE_MODULES = {"time", "random"}
# Mutating container methods (closure-capture mutation detection).
_MUTATORS = {"append", "add", "extend", "insert", "remove", "pop",
             "clear", "update", "setdefault", "discard", "popitem",
             "appendleft", "write"}
# Static tracer metadata attributes (reading them does NOT produce a
# traced value — .ndim/.shape feed Python-level shape arithmetic).
_STATIC_ATTRS = {"ndim", "shape", "dtype", "size", "itemsize"}


@dataclasses.dataclass
class BehaviourBody:
    """One behaviour's AST + typed-parameter view, however obtained
    (parsed from a file, or inspect.getsource of a live function)."""

    name: str
    node: ast.FunctionDef
    file: Optional[str]
    arg_caps: Dict[str, Optional[str]]    # param name → cap mode
    ignore: Tuple[str, ...] = ()          # behaviour-level LINT_IGNORE


@dataclasses.dataclass
class TypeBody:
    """One actor type's source-level view for the body rules."""

    name: str
    host: bool
    file: Optional[str]
    fields: Optional[Dict[str, str]]      # None = unknown (can't check)
    immutable: Set[str]                   # Val/Box-declared field names
    ignore: Tuple[str, ...]               # type-level LINT_IGNORE
    behaviours: List[BehaviourBody]


# A resolver maps (type name, behaviour name) → that behaviour's
# parameter cap modes, or None when the target is unknown. It decides
# whether a send MOVES its payload (iso parameter) — path mode
# resolves within the parsed files, types mode through fn globals.
Resolver = Callable[[str, str], Optional[Tuple[Optional[str], ...]]]


def _ann_root(node) -> str:
    """Root name of an annotation AST: Ref["Sink"] → Ref, pack.Iso →
    Iso, VecF32[8] → VecF32."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return "?"


def _deco_name(d) -> str:
    if isinstance(d, ast.Call):
        d = d.func
    if isinstance(d, ast.Attribute):
        return d.attr
    if isinstance(d, ast.Name):
        return d.id
    return ""


def _str_tuple(node) -> Tuple[str, ...]:
    """A (constant) tuple/list of strings from an AST value, else ()."""
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for el in node.elts:
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                out.append(el.value)
        return tuple(out)
    return ()


def _attr_chain(node) -> Tuple[str, ...]:
    """x.y.z → ("x", "y", "z"); () when the base is not a Name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return ()


# ---------------------------------------------------------------------------
# AST extraction: actor classes + behaviours from a parsed module


def _class_is_actor(cls: ast.ClassDef) -> Tuple[bool, bool]:
    """(is actor type, fields complete). Fields are complete when the
    class derives them only from its own annotations (@actor decorator
    or direct Actor base); other bases may contribute inherited fields
    the AST cannot see."""
    for d in cls.decorator_list:
        if _deco_name(d) == "actor":
            return True, True
    base_names = [_ann_root(b) for b in cls.bases]
    if "Actor" in base_names:
        return True, len(base_names) == 1
    for kw in cls.keywords:
        if kw.arg == "metaclass" and _ann_root(kw.value) == "ActorTypeMeta":
            return True, not cls.bases
    return False, False


def _behaviour_from_ast(item: ast.FunctionDef,
                        file: Optional[str]) -> Optional[BehaviourBody]:
    deco = None
    for d in item.decorator_list:
        if _deco_name(d) in ("behaviour", "be"):
            deco = d
            break
    if deco is None:
        return None
    ignore: Tuple[str, ...] = ()
    if isinstance(deco, ast.Call):
        for kw in deco.keywords:
            if kw.arg == "lint_ignore":
                ignore = _str_tuple(kw.value)
    params = item.args.args
    if len(params) < 2:
        return None                      # malformed; probe rules report
    arg_caps = {}
    for p in params[2:]:
        root = _ann_root(p.annotation) if p.annotation is not None else ""
        arg_caps[p.arg] = _CAP_BY_NAME.get(root)
    return BehaviourBody(name=item.name, node=item, file=file,
                         arg_caps=arg_caps, ignore=ignore)


def parse_module(src: str, filename: str = "<string>"
                 ) -> Tuple[List[TypeBody], Set[str]]:
    """All actor types in a module's SOURCE (no import), plus the
    module-level mutable-container globals (list/dict/set literals)
    the impurity rule watches for closure mutation. Nested classes
    (actors defined inside functions) are found too."""
    tree = ast.parse(src, filename=filename)
    mutable_globals: Set[str] = set()
    for s in tree.body:
        if isinstance(s, ast.Assign) and isinstance(
                s.value, (ast.List, ast.Dict, ast.Set, ast.ListComp,
                          ast.DictComp, ast.SetComp)):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    mutable_globals.add(t.id)
    types: List[TypeBody] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        is_actor, complete = _class_is_actor(node)
        if not is_actor:
            continue
        fields: Dict[str, str] = {}
        immutable: Set[str] = set()
        host = False
        ignore: Tuple[str, ...] = ()
        behaviours: List[BehaviourBody] = []
        for item in node.body:
            if isinstance(item, ast.AnnAssign) and isinstance(
                    item.target, ast.Name):
                fname = item.target.id
                if fname.startswith("_") or fname.isupper():
                    continue
                root = _ann_root(item.annotation)
                fields[fname] = root
                if root in _IMMUTABLE_ROOTS:
                    immutable.add(fname)
            elif isinstance(item, ast.Assign):
                for t in item.targets:
                    if not isinstance(t, ast.Name):
                        continue
                    if t.id == "HOST" and isinstance(
                            item.value, ast.Constant):
                        host = bool(item.value.value)
                    elif t.id == "LINT_IGNORE":
                        ignore = _str_tuple(item.value)
            elif isinstance(item, ast.FunctionDef):
                bb = _behaviour_from_ast(item, filename)
                if bb is not None:
                    behaviours.append(bb)
        types.append(TypeBody(
            name=node.name, host=host, file=filename,
            fields=fields if complete else None, immutable=immutable,
            ignore=ignore, behaviours=behaviours))
    return types, mutable_globals


# ---------------------------------------------------------------------------
# The analyzer: one forward walk per behaviour body


class _Env:
    """Forward dataflow state: taintedness (traced-value provenance),
    moved linear handles, live linear/val handle names."""

    __slots__ = ("tainted", "moved", "linear", "vals")

    def __init__(self, tainted=(), linear=(), vals=()):
        self.tainted: Set[str] = set(tainted)
        self.moved: Dict[str, int] = {}       # name → line of the move
        self.linear: Set[str] = set(linear)
        self.vals: Set[str] = set(vals)

    def clone(self) -> "_Env":
        e = _Env(self.tainted, self.linear, self.vals)
        e.moved = dict(self.moved)
        return e

    def merge_branches(self, a: "_Env", b: "_Env") -> None:
        """Join two exclusive branches: taint unions (either branch may
        have produced the value), moves INTERSECT (only a move on every
        path is a definite move — no false positives on `if c: send(p)
        else: send(p)`)."""
        self.tainted = a.tainted | b.tainted
        self.linear = a.linear | b.linear
        self.vals = a.vals | b.vals
        self.moved = {k: v for k, v in a.moved.items() if k in b.moved}

    def absorb(self, a: "_Env") -> None:
        """Join a maybe-executed block (loop body, try handler) back:
        taint unions, moves only if already moved here too."""
        self.tainted |= a.tainted
        self.linear |= a.linear
        self.vals |= a.vals


class _Analyzer:
    def __init__(self, tb: TypeBody, bb: BehaviourBody,
                 resolver: Optional[Resolver],
                 mutable_globals: Set[str]):
        self.tb = tb
        self.bb = bb
        self.resolver = resolver
        self.mutable_globals = set(mutable_globals)
        self.findings: List[Finding] = []
        params = bb.node.args.args
        self.self_name = params[0].arg
        self.st_name = params[1].arg
        self.loops: List[Tuple[str, bool]] = []   # (kind, static)
        self.nested = 0
        self.mutations: List[int] = []            # st[k]= lines
        self.drop_returns: List[int] = []         # returns not carrying st
        self.bare_returns: List[ast.Return] = []
        self.locals: Set[str] = {p.arg for p in params}
        self.local_imports: Dict[str, str] = {}   # alias → module root

    # -- reporting --
    def flag(self, rule: str, severity: str, node, message: str) -> None:
        self.findings.append(Finding(
            rule, severity, self.tb.name, self.bb.name, message,
            file=self.bb.file, line=getattr(node, "lineno", None),
            col=(getattr(node, "col_offset", None) or 0) + 1))

    # -- entry --
    def run(self) -> List[Finding]:
        env = _Env(tainted={self.st_name, *self.bb.arg_caps},
                   linear={a for a, cap in self.bb.arg_caps.items()
                           if cap == "iso"},
                   vals={a for a, cap in self.bb.arg_caps.items()
                         if cap == "val"})
        self.walk(self.bb.node.body, env)
        # R7: every path must return the state dict.
        if not _always_terminates(self.bb.node.body):
            self.flag("R7", "error", self.bb.node,
                      "behaviour can fall off the end without returning "
                      "the state dict — every path must `return st` (or "
                      "the updated dict)")
        for r in self.bare_returns:
            self.flag("R7", "error", r,
                      "behaviour returns no state dict on this path — "
                      "`return st` (the engine needs the full state "
                      "back every dispatch)")
        # R8: in-place mutations dropped by a rebuilt return dict.
        for mline in self.mutations:
            for rline in self.drop_returns:
                self.flag("R8", "warning", _Loc(mline),
                          f"in-place st mutation here is dropped by the "
                          f"return at line {rline}, which rebuilds the "
                          "state dict without **st — fold the update "
                          "into the returned dict")
        return self.findings

    # -- statements --
    def walk(self, stmts: Sequence[ast.stmt], env: _Env) -> None:
        for s in stmts:
            self.stmt(s, env)

    def stmt(self, s: ast.stmt, env: _Env) -> None:  # noqa: C901
        if isinstance(s, ast.Expr):
            self.expr(s.value, env)
        elif isinstance(s, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            self.assign(s, env)
        elif isinstance(s, ast.Return):
            if self.nested == 0:
                if s.value is None or (isinstance(s.value, ast.Constant)
                                       and s.value.value is None):
                    self.bare_returns.append(s)
                else:
                    self.check_return(s, env)
            if s.value is not None:
                self.expr(s.value, env)
        elif isinstance(s, ast.If):
            if self.expr(s.test, env) and not self.tb.host:
                self.flag("R6", "error", s,
                          "Python `if` on a traced value — the trace "
                          "cannot branch (TracerBoolConversionError); "
                          "mask effects with when= or select with "
                          "jnp.where")
            a, b = env.clone(), env.clone()
            self.walk(s.body, a)
            self.walk(s.orelse, b)
            env.merge_branches(a, b)
        elif isinstance(s, ast.While):
            if self.expr(s.test, env) and not self.tb.host:
                self.flag("R6", "error", s,
                          "`while` on a traced value — the trace cannot "
                          "branch; use lax.while_loop (or rethink: "
                          "behaviours re-dispatch via self.send)")
            self.loops.append(("while", False))
            body_env = env.clone()
            self.walk(s.body, body_env)
            env.absorb(body_env)
            self.loops.pop()
            self.walk(s.orelse, env)
        elif isinstance(s, ast.For):
            it_tainted = self.expr(s.iter, env)
            if it_tainted and not self.tb.host:
                self.flag("R6", "error", s,
                          "`for` over a traced value — iteration/"
                          "range() on a tracer fails at trace; use "
                          "lax.fori_loop or a static range")
            self._bind_target(s.target, it_tainted, env)
            self.loops.append(("for", not it_tainted))
            body_env = env.clone()
            self.walk(s.body, body_env)
            env.absorb(body_env)
            self.loops.pop()
            self.walk(s.orelse, env)
        elif isinstance(s, ast.Assert):
            if self.expr(s.test, env) and not self.tb.host:
                self.flag("R6", "error", s,
                          "assert on a traced value — the trace cannot "
                          "branch; use a when=-masked self.error_int "
                          "(errors are values here)")
            if s.msg is not None:
                self.expr(s.msg, env)
        elif isinstance(s, (ast.Global, ast.Nonlocal)):
            if not self.tb.host:
                kind = ("global" if isinstance(s, ast.Global)
                        else "nonlocal")
                self.flag("R9", "warning", s,
                          f"`{kind} {', '.join(s.names)}` in a traced "
                          "behaviour body — the rebind happens ONCE at "
                          "trace, not per dispatch; keep per-actor "
                          "state in st")
            self.locals.update(s.names)
        elif isinstance(s, ast.Try):
            body_env = env.clone()
            self.walk(s.body, body_env)
            env.absorb(body_env)
            for h in s.handlers:
                h_env = env.clone()
                self.walk(h.body, h_env)
                env.absorb(h_env)
            self.walk(s.orelse, env)
            self.walk(s.finalbody, env)
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            for item in s.items:
                self.expr(item.context_expr, env)
                if item.optional_vars is not None:
                    self._bind_target(item.optional_vars, False, env)
            self.walk(s.body, env)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # Nested defs are usually lax loop/cond bodies: their
            # params are traced; effects inside them trace ONCE.
            self.locals.add(s.name)
            inner = env.clone()
            inner.tainted |= {p.arg for p in s.args.args}
            self.nested += 1
            self.walk(s.body, inner)
            self.nested -= 1
        elif isinstance(s, ast.Delete):
            for t in s.targets:
                if isinstance(t, ast.Name):
                    env.tainted.discard(t.id)
                    env.moved.pop(t.id, None)
                    env.linear.discard(t.id)
        elif isinstance(s, (ast.Import, ast.ImportFrom)):
            for alias in s.names:
                bound = (alias.asname or alias.name).split(".")[0]
                self.locals.add(bound)
                if isinstance(s, ast.Import):
                    self.local_imports[bound] = alias.name.split(".")[0]
        elif isinstance(s, (ast.Pass, ast.Break, ast.Continue)):
            pass
        elif isinstance(s, ast.Raise):
            if s.exc is not None:
                self.expr(s.exc, env)
        else:                            # match etc: visit expressions
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    self.expr(child, env)

    def _bind_target(self, target, tainted: bool, env: _Env) -> None:
        """(Re)bind assignment/loop targets: clears old provenance."""
        if isinstance(target, ast.Name):
            self.locals.add(target.id)
            env.moved.pop(target.id, None)
            env.linear.discard(target.id)
            env.vals.discard(target.id)
            (env.tainted.add if tainted
             else env.tainted.discard)(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind_target(el, tainted, env)
        elif isinstance(target, ast.Starred):
            self._bind_target(target.value, tainted, env)

    def assign(self, s, env: _Env) -> None:
        value = s.value
        vt = self.expr(value, env) if value is not None else False
        targets = s.targets if isinstance(s, ast.Assign) else [s.target]
        for t in targets:
            if isinstance(t, ast.Name):
                if isinstance(s, ast.AugAssign):
                    if t.id in env.moved:
                        self._use_after_move(t, env)
                    if vt:
                        env.tainted.add(t.id)
                    self.locals.add(t.id)
                    continue
                self._bind_target(t, vt, env)
                if self._is_linear_rhs(value, env):
                    env.linear.add(t.id)
                if self._is_val_rhs(value, env):
                    env.vals.add(t.id)
            elif isinstance(t, ast.Subscript):
                base = t.value
                if isinstance(base, ast.Name) and base.id == self.st_name:
                    self.check_st_key(t, write=True)
                    self.mutations.append(t.lineno)
                elif (isinstance(base, ast.Name)
                      and base.id in self.mutable_globals
                      and base.id not in self.locals
                      and not self.tb.host):
                    self.flag("R9", "warning", t,
                              f"write into captured mutable global "
                              f"{base.id!r} — runs ONCE at trace, not "
                              "per dispatch; keep per-actor state in st")
                else:
                    self.expr(base, env)
                    self.expr(t.slice, env)
            elif isinstance(t, ast.Attribute):
                if (isinstance(t.value, ast.Name)
                        and t.value.id == self.self_name):
                    self.flag("R8", "error", t,
                              f"assignment to self.{t.attr} — `self` is "
                              "the per-dispatch Context, not the actor; "
                              "actor state lives in the st dict "
                              "(declare a field annotation)")
                else:
                    self.expr(t.value, env)
            else:
                self._bind_target(t, vt, env)

    def _is_linear_rhs(self, value, env: _Env) -> bool:
        if isinstance(value, ast.Call):
            chain = _attr_chain(value.func)
            return (len(chain) == 2 and chain[0] == self.self_name
                    and chain[1] == "blob_alloc")
        return isinstance(value, ast.Name) and value.id in env.linear

    def _is_val_rhs(self, value, env: _Env) -> bool:
        if isinstance(value, ast.Call):
            chain = _attr_chain(value.func)
            return (len(chain) == 2 and chain[0] == self.self_name
                    and chain[1] == "blob_freeze")
        return isinstance(value, ast.Name) and value.id in env.vals

    # -- expressions (returns: is the value traced?) --
    def expr(self, node, env: _Env) -> bool:  # noqa: C901
        if node is None:
            return False
        if isinstance(node, ast.Name):
            if node.id in env.moved and isinstance(node.ctx, ast.Load):
                self._use_after_move(node, env)
            return node.id in env.tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if (isinstance(node.value, ast.Name)
                    and node.value.id == self.self_name):
                return node.attr == "actor_id"
            base_t = self.expr(node.value, env)
            return base_t and node.attr not in _STATIC_ATTRS
        if isinstance(node, ast.Subscript):
            self.check_st_key(node, write=False)
            return self.expr(node.value, env) | self.expr(node.slice, env)
        if isinstance(node, ast.Call):
            return self.call(node, env)
        if isinstance(node, ast.BoolOp):
            ts = [self.expr(v, env) for v in node.values]
            if any(ts) and not self.tb.host:
                op = "and" if isinstance(node.op, ast.And) else "or"
                self.flag("R6", "error", node,
                          f"`{op}` on a traced value calls bool() at "
                          "trace — combine masks with & / | instead")
            return any(ts)
        if isinstance(node, ast.UnaryOp):
            t = self.expr(node.operand, env)
            if t and isinstance(node.op, ast.Not) and not self.tb.host:
                self.flag("R6", "error", node,
                          "`not` on a traced value calls bool() at "
                          "trace — use ~ on the mask")
            return t
        if isinstance(node, ast.Compare):
            ts = [self.expr(node.left, env)]
            ts += [self.expr(c, env) for c in node.comparators]
            if len(node.ops) > 1 and any(ts) and not self.tb.host:
                self.flag("R6", "error", node,
                          "chained comparison on traced values expands "
                          "to `and` (bool() at trace) — split into two "
                          "compares joined with &")
            return any(ts)
        if isinstance(node, ast.IfExp):
            tt = self.expr(node.test, env)
            if tt and not self.tb.host:
                self.flag("R6", "error", node,
                          "ternary on a traced condition — the trace "
                          "cannot branch; use jnp.where(cond, a, b)")
            bt = self.expr(node.body, env)
            ot = self.expr(node.orelse, env)
            return tt or bt or ot
        if isinstance(node, ast.BinOp):
            return self.expr(node.left, env) | self.expr(node.right, env)
        if isinstance(node, ast.Dict):
            self.check_state_dict(node, env)
            t = False
            for k, v in zip(node.keys, node.values):
                t |= self.expr(k, env) if k is not None else False
                t |= self.expr(v, env)
            return t
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any([self.expr(el, env) for el in node.elts])
        if isinstance(node, ast.Starred):
            return self.expr(node.value, env)
        if isinstance(node, ast.JoinedStr):
            return any([self.expr(v, env) for v in node.values])
        if isinstance(node, ast.FormattedValue):
            return self.expr(node.value, env)
        if isinstance(node, ast.NamedExpr):
            t = self.expr(node.value, env)
            self._bind_target(node.target, t, env)
            return t
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                             ast.GeneratorExp)):
            t = False
            inner = env.clone()
            for gen in node.generators:
                gt = self.expr(gen.iter, inner)
                if gt and not self.tb.host:
                    self.flag("R6", "error", gen.iter,
                              "comprehension over a traced value — "
                              "iteration on a tracer fails at trace")
                self._bind_target(gen.target, gt, inner)
                for cond in gen.ifs:
                    self.expr(cond, inner)
                t |= gt
            if isinstance(node, ast.DictComp):
                t |= self.expr(node.key, inner)
                t |= self.expr(node.value, inner)
            else:
                t |= self.expr(node.elt, inner)
            return t
        if isinstance(node, ast.Lambda):
            inner = env.clone()
            inner.tainted |= {p.arg for p in node.args.args}
            self.nested += 1
            self.expr(node.body, inner)
            self.nested -= 1
            return False
        # Anything else: conservative union over child expressions.
        return any([self.expr(c, env) for c in ast.iter_child_nodes(node)
                    if isinstance(c, ast.expr)])

    # -- calls --
    def call(self, node: ast.Call, env: _Env) -> bool:  # noqa: C901
        func = node.func
        func_t = self.expr(func, env)
        arg_ts = [self.expr(a, env) for a in node.args]
        kw_ts = [self.expr(kw.value, env) for kw in node.keywords]
        tainted = func_t or any(arg_ts) or any(kw_ts)
        chain = _attr_chain(func)
        # st.get("key") reads obey key discipline too.
        if (len(chain) == 2 and chain[0] == self.st_name
                and chain[1] == "get" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            self._key_check(node.args[0].value, node, write=False)
        if (len(chain) == 2 and chain[0] == self.self_name):
            return self._ctx_call(node, chain[1], env, tainted)
        if not self.tb.host:
            self._impurity(node, func, chain, env)
        return tainted

    def _ctx_call(self, node: ast.Call, method: str, env: _Env,
                  tainted: bool) -> bool:
        if method in _EFFECTS:
            self._effect_site(node, method)
        if method == "send" and len(node.args) >= 2:
            self._apply_moves(node, node.args[1], node.args[2:], env)
        elif method in ("spawn", "spawn_sync") and node.args:
            self._apply_moves(node, node.args[0], node.args[1:], env)
        elif method == "blob_free" and node.args:
            a = node.args[0]
            if isinstance(a, ast.Name):
                if a.id in env.vals:
                    self.flag("R9", "error", node,
                              f"blob_free({a.id}) on a frozen (val) "
                              "blob — shared payloads have no owner to "
                              "free them; the GC mark pass reclaims "
                              "them")
                env.moved[a.id] = node.lineno
                env.linear.discard(a.id)
        elif method == "blob_set" and node.args:
            a = node.args[0]
            if isinstance(a, ast.Name) and a.id in env.vals:
                self.flag("R9", "error", node,
                          f"blob_set({a.id}, …) writes to a frozen "
                          "(val) blob — shared-immutable payloads "
                          "cannot be written (≙ val's deny-write)")
        elif method == "blob_freeze" and node.args:
            a = node.args[0]
            if isinstance(a, ast.Name):
                env.linear.discard(a.id)
                env.vals.add(a.id)
        return method in _TRACED_CALLS or (
            method in ("blob_get", "blob_length")) or (
            tainted and method not in _EFFECTS)

    def _effect_site(self, node: ast.Call, method: str) -> None:
        if self.tb.host:
            return
        if self.nested:
            self.flag("R7", "warning", node,
                      f"self.{method} inside a nested function — if "
                      "this is a lax loop/cond body it traces ONCE, "
                      "not per iteration; effect counts must be "
                      "trace-time static")
            return
        for kind, static in self.loops:
            if static:
                continue
            if kind == "while":
                self.flag("R7", "warning", node,
                          f"self.{method} under a `while` loop — the "
                          "per-dispatch effect count must be a "
                          "trace-time constant (the engine pads to "
                          "the declared budget); unroll a static "
                          "range or mask with when=")
            else:
                self.flag("R7", "error", node,
                          f"self.{method} under a loop whose trip "
                          "count depends on a traced value — the send/"
                          "spawn count cannot be static; emit a fixed "
                          "number of when=-masked effects instead")
            return

    def _apply_moves(self, node: ast.Call, bexpr, payload,
                     env: _Env) -> None:
        """Sending a payload MOVES it when it rides an iso parameter
        (or the value is a linear handle and the target is unknown) —
        ≙ Context._send_checks' move rule, run statically."""
        caps = self._resolve_caps(bexpr)
        for i, a in enumerate(payload):
            if not isinstance(a, ast.Name):
                continue
            is_linear = a.id in env.linear
            if caps is not None and i < len(caps):
                want = caps[i]
                moves = want == "iso" or (want is not None and is_linear)
            else:
                moves = is_linear
            if moves and a.id not in env.moved:
                env.moved[a.id] = node.lineno
                env.linear.discard(a.id)

    def _resolve_caps(self, bexpr) -> Optional[Tuple[Optional[str], ...]]:
        """`Type.behaviour` AST → the target's parameter cap modes."""
        chain = _attr_chain(bexpr)
        if len(chain) < 2 or self.resolver is None:
            return None
        return self.resolver(chain[-2], chain[-1])

    def _use_after_move(self, node: ast.Name, env: _Env) -> None:
        self.flag("R9", "error", node,
                  f"use-after-move: {node.id!r} was moved at line "
                  f"{env.moved[node.id]} (an Iso/Blob payload send or "
                  "blob_free is a move) and may not be used again this "
                  "dispatch")
        env.moved.pop(node.id, None)     # one finding per move

    # -- R9 impurity --
    def _impurity(self, node: ast.Call, func, chain, env: _Env) -> None:
        if isinstance(func, ast.Name):
            if (func.id in _IMPURE_BUILTINS
                    and func.id not in self.locals):
                self.flag("R9", "warning", node,
                          f"{func.id}() in a traced behaviour body "
                          "runs ONCE, at trace time — behaviours are "
                          "pure traced functions; use a HOST actor "
                          "for I/O")
            return
        if not chain:
            return
        root = self.local_imports.get(chain[0], chain[0])
        if chain[0] in self.locals and chain[0] not in self.local_imports:
            return
        if root in _IMPURE_MODULES:
            self.flag("R9", "warning", node,
                      f"{'.'.join(chain)}() is host-impure in a traced "
                      "body — it runs once at trace, not per dispatch "
                      "(wall clocks and host RNG have no device "
                      "meaning; seed traced RNG through state)")
        elif (root in ("np", "numpy", "jax") and len(chain) > 2
                and chain[1] == "random"):
            self.flag("R9", "warning", node,
                      f"{'.'.join(chain)}() draws host randomness at "
                      "trace time — every dispatch replays the SAME "
                      "draw; thread a traced RNG through state "
                      "instead")
        elif (root in self.mutable_globals
                and chain[-1] in _MUTATORS):
            self.flag("R9", "warning", node,
                      f"mutating captured global {root!r} inside a "
                      "traced body — the mutation happens once at "
                      "trace, not per dispatch; keep per-actor state "
                      "in st")

    # -- R8 state keys --
    def check_st_key(self, sub: ast.Subscript, write: bool) -> None:
        if not (isinstance(sub.value, ast.Name)
                and sub.value.id == self.st_name):
            return
        sl = sub.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str):
            self._key_check(sl.value, sub, write=write)

    def _key_check(self, key: str, node, write: bool) -> None:
        fields = self.tb.fields
        if fields is None:
            return
        if key not in fields:
            hint = difflib.get_close_matches(key, fields, n=1)
            did = f" — did you mean {hint[0]!r}?" if hint else ""
            self.flag("R8", "error", node,
                      f"state dict has no declared field {key!r}{did} "
                      f"(declared: {', '.join(sorted(fields)) or 'none'})")
        elif write and key in self.tb.immutable:
            self.flag("R8", "warning", node,
                      f"write to {key!r}, declared "
                      f"{fields[key]} (shared-immutable) — val fields "
                      "freeze their payload; rebinding the field "
                      "defeats the declared immutability")

    def check_state_dict(self, node: ast.Dict, env: _Env) -> None:
        """`{**st, "key": v}` splats obey key discipline."""
        if not any(k is None and isinstance(v, ast.Name)
                   and v.id == self.st_name
                   for k, v in zip(node.keys, node.values)):
            return
        for k in node.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                self._key_check(k.value, k, write=True)

    def check_return(self, s: ast.Return, env: _Env) -> None:
        v = s.value
        if isinstance(v, ast.Name) and v.id == self.st_name:
            return                       # carries st (and mutations)
        if not isinstance(v, ast.Dict):
            return                       # unknown carrier: no claim
        splats = [val for k, val in zip(v.keys, v.values) if k is None]
        has_st = any(isinstance(sp, ast.Name) and sp.id == self.st_name
                     for sp in splats)
        if has_st:
            return                       # {**st, ...}: checked as Dict
        if splats:
            return                       # {**other}: can't see through
        keys = {k.value for k in v.keys
                if isinstance(k, ast.Constant)
                and isinstance(k.value, str)}
        for k in v.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                self._key_check(k.value, k, write=True)
        if self.tb.fields is not None:
            missing = sorted(set(self.tb.fields) - keys)
            if missing:
                self.flag("R8", "error", v,
                          "returned state dict drops declared "
                          f"field(s) {', '.join(missing)} — the engine "
                          "packs the FULL state every dispatch; add "
                          "them or splat **st")
        if self.mutations:
            self.drop_returns.append(s.lineno)


class _Loc:
    """A minimal lineno carrier for findings at a remembered line."""

    def __init__(self, lineno: int):
        self.lineno = lineno
        self.col_offset = 0


def _always_terminates(stmts: Sequence[ast.stmt]) -> bool:
    """Does every path through these statements return/raise?
    (≙ the reference's method-body completeness check in verify/fun.c
    — here 'complete' means the state dict comes back.)"""
    for s in stmts:
        if isinstance(s, (ast.Return, ast.Raise)):
            return True
        if isinstance(s, ast.If):
            if (s.orelse and _always_terminates(s.body)
                    and _always_terminates(s.orelse)):
                return True
        elif isinstance(s, ast.Try):
            if _always_terminates(s.finalbody):
                return True
            blocks = [list(s.body) + list(s.orelse)]
            blocks += [h.body for h in s.handlers]
            if all(_always_terminates(b) for b in blocks):
                return True
        elif isinstance(s, (ast.With, ast.AsyncWith)):
            if _always_terminates(s.body):
                return True
        elif isinstance(s, ast.While):
            # `while True` with no break never falls through.
            if (isinstance(s.test, ast.Constant) and s.test.value
                    and not any(isinstance(n, ast.Break)
                                for n in ast.walk(s))):
                return True
    return False


# ---------------------------------------------------------------------------
# Entry points


def check_type_bodies(types: Sequence[TypeBody],
                      mutable_globals: Set[str] = frozenset(),
                      resolver: Optional[Resolver] = None
                      ) -> List[Finding]:
    """Run R6–R9 over already-extracted TypeBody views."""
    if resolver is None:
        world = {tb.name: tb for tb in types}

        def resolver(tname, bname):      # noqa: F811
            tb = world.get(tname)
            if tb is None:
                return None
            for bb in tb.behaviours:
                if bb.name == bname:
                    return tuple(bb.arg_caps.values())
            return None
    findings: List[Finding] = []
    for tb in types:
        for bb in tb.behaviours:
            findings += _Analyzer(tb, bb, resolver,
                                  set(mutable_globals)).run()
    return findings


def _apply_declared_suppressions(findings: Sequence[Finding],
                                 types: Sequence[TypeBody],
                                 src_lines: Dict[str, List[str]]
                                 ) -> List[Finding]:
    """Drop findings suppressed by LINT_IGNORE (type- or behaviour-
    level) or a trailing ``# lint: ignore[...]`` comment."""
    by_type = {tb.name: tb for tb in types}
    out = []
    for f in findings:
        tb = by_type.get(f.type_name)
        if tb is not None:
            if f.rule in tb.ignore:
                continue
            bb = next((b for b in tb.behaviours
                       if b.name == f.behaviour), None)
            if bb is not None and f.rule in bb.ignore:
                continue
        lines = src_lines.get(f.file or "")
        if (lines and f.line and f.line <= len(lines)
                and line_suppressed(f, lines[f.line - 1])):
            continue
        out.append(f)
    return out


def check_source(src: str, filename: str = "<string>",
                 include_suppressed: bool = False) -> List[Finding]:
    """Lint one module's SOURCE — no import, no JAX. Unparseable
    source yields a single R0 finding at the syntax error."""
    try:
        types, mutable_globals = parse_module(src, filename)
    except SyntaxError as e:
        return [Finding("R0", "error", os.path.basename(filename),
                        None, f"file does not parse: {e.msg}",
                        file=filename, line=e.lineno,
                        col=(e.offset or 0))]
    findings = check_type_bodies(types, mutable_globals)
    if not include_suppressed:
        findings = _apply_declared_suppressions(
            findings, types, {filename: src.splitlines()})
    return sort_findings(findings)


def iter_python_files(path: str) -> List[str]:
    """`path` itself if a file, else every *.py under it (sorted,
    skipping hidden and __pycache__ directories)."""
    if os.path.isfile(path):
        return [path]
    out = []
    for root, dirs, files in os.walk(path):
        dirs[:] = sorted(d for d in dirs
                         if not d.startswith((".", "__pycache__")))
        for f in sorted(files):
            if f.endswith(".py") and not f.startswith("."):
                out.append(os.path.join(root, f))
    return out


def check_paths(paths: Sequence[str], include_suppressed: bool = False
                ) -> Tuple[List[Finding], int, int]:
    """Lint files/directories (pure AST — the files need not import).
    Returns (findings, n actor types seen, n behaviours seen)."""
    findings: List[Finding] = []
    n_types = n_beh = 0
    for path in paths:
        for file in iter_python_files(path):
            with open(file, "r", encoding="utf-8") as fh:
                src = fh.read()
            try:
                types, _ = parse_module(src, file)
                n_types += len(types)
                n_beh += sum(len(t.behaviours) for t in types)
            except SyntaxError:
                pass
            findings += check_source(
                src, file, include_suppressed=include_suppressed)
    return sort_findings(findings), n_types, n_beh


def check_path(path: str) -> List[Finding]:
    return check_paths([path])[0]


# -- live actor types (the lint_types/lint_module/lint_program hook) --


def _cap_of_spec(spec) -> Optional[str]:
    from ..ops import pack               # lazy: path mode stays AST-only
    return pack.cap_mode(spec)


def _type_body_of(atype) -> Optional[TypeBody]:
    """Build a TypeBody for a live actor type via inspect.getsource.
    None when no behaviour source is recoverable (exec'd classes)."""
    import inspect
    fields = {}
    immutable = set()
    for fname, spec in atype.field_specs.items():
        fields[fname] = getattr(spec, "__name__", "?")
        if _cap_of_spec(spec) in ("val", "box"):
            immutable.add(fname)
    behaviours = []
    for bdef in atype.behaviour_defs:
        try:
            lines, start = inspect.getsourcelines(bdef.fn)
            fnode = ast.parse(
                textwrap.dedent("".join(lines))).body[0]
        except (OSError, TypeError, SyntaxError, IndexError):
            continue
        if not isinstance(fnode, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        ast.increment_lineno(fnode, start - 1)
        if len(fnode.args.args) < 2:
            continue
        arg_caps = {n: _cap_of_spec(s)
                    for n, s in zip(bdef.arg_names, bdef.arg_specs)}
        behaviours.append(BehaviourBody(
            name=bdef.name, node=fnode,
            file=getattr(bdef, "source_file", None),
            arg_caps=arg_caps,
            ignore=tuple(getattr(bdef, "lint_ignore", ()) or ())))
    if not behaviours:
        return None
    return TypeBody(
        name=atype.__name__, host=bool(getattr(atype, "HOST", False)),
        file=getattr(behaviours[0], "file", None), fields=fields,
        immutable=immutable,
        ignore=tuple(str(r) for r in
                     getattr(atype, "LINT_IGNORE", ()) or ()),
        behaviours=behaviours)


def check_types(*atypes, include_suppressed: bool = False
                ) -> List[Finding]:
    """R6–R9 over live actor types (classes, not files): same rules,
    source recovered via inspect; send-move resolution sees the passed
    world plus each behaviour's module globals."""
    from ..api import ActorTypeMeta
    tbs: List[TypeBody] = []
    by_name: Dict[str, object] = {}
    fn_globals: List[dict] = []
    seen_globals: Set[int] = set()
    for at in atypes:
        by_name[at.__name__] = at
        tb = _type_body_of(at)
        if tb is not None:
            tbs.append(tb)
        for bdef in at.behaviour_defs:
            g = getattr(bdef.fn, "__globals__", None)
            if g is not None and id(g) not in seen_globals:
                seen_globals.add(id(g))
                fn_globals.append(g)

    def resolver(tname, bname):
        at = by_name.get(tname)
        if at is None:
            for g in fn_globals:
                cand = g.get(tname)
                if isinstance(cand, ActorTypeMeta):
                    at = cand
                    break
        if not isinstance(at, ActorTypeMeta):
            return None
        for bdef in at.behaviour_defs:
            if bdef.name == bname:
                return tuple(_cap_of_spec(s) for s in bdef.arg_specs)
        return None

    mutable_globals: Set[str] = set()
    for g in fn_globals:
        for name, val in g.items():
            if isinstance(val, (list, dict, set, bytearray)):
                mutable_globals.add(name)
    findings = check_type_bodies(tbs, mutable_globals, resolver)
    if not include_suppressed:
        src_lines: Dict[str, List[str]] = {}
        for tb in tbs:
            for bb in tb.behaviours:
                if bb.file and bb.file not in src_lines:
                    try:
                        with open(bb.file, "r", encoding="utf-8") as fh:
                            src_lines[bb.file] = fh.read().splitlines()
                    except OSError:
                        pass
        findings = _apply_declared_suppressions(findings, tbs, src_lines)
    return sort_findings(findings)
