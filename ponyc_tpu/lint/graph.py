"""The program-wide message-flow graph.

Nodes are (actor type, behaviour) pairs; edges are the send/spawn
sites the probe observed, carrying their kind ("send" — a message to an
existing ref; "spawn"/"spawn_sync" — a constructor delivery to a fresh
slot) and the when-mask constness (True = unconditional, False =
provably dead, None = data-dependent).

≙ the reference's reach pass over the whole program's call graph
(src/libponyc/reach/reach.c walks Main's create transitively and prunes
everything unreached; paint.c then colours only the survivors). The
rules passes (rules.py) run reachability, SCC/cycle, and budget
analyses over this graph.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Set, Tuple

from .facts import BehaviourFacts, TypeFacts

Node = Tuple[str, str]          # (type name, behaviour name)


@dataclasses.dataclass(frozen=True)
class Edge:
    """One send/spawn SITE (not aggregated: two unconditional sends to
    the same target are two edges — multiplicity matters for R4)."""

    src: Node
    dst: Node
    kind: str                   # "send" | "spawn" | "spawn_sync"
    when: Optional[bool]        # constness of the mask at the site
    external: bool              # dst type is outside the analysed world

    @property
    def delivers(self) -> bool:
        """Can this edge ever deliver a message? (when=False sites are
        provably dead; external targets dead-letter.)"""
        return self.when is not False and not self.external


class FlowGraph:
    """Message-flow graph over an analysed world of TypeFacts."""

    def __init__(self, types: Dict[str, TypeFacts]):
        self.types = types
        self.nodes: Dict[Node, BehaviourFacts] = {}
        self.edges: List[Edge] = []
        for tf in types.values():
            for bf in tf.behaviours:
                self.nodes[bf.node] = bf
        for tf in types.values():
            for bf in tf.behaviours:
                for fact in bf.sends:
                    dst = (fact.dst_type, fact.dst_behaviour)
                    self.edges.append(Edge(
                        src=bf.node, dst=dst, kind=fact.kind,
                        when=fact.when,
                        external=fact.dst_type not in types))
        self.out_edges: Dict[Node, List[Edge]] = {n: [] for n in self.nodes}
        for e in self.edges:
            self.out_edges[e.src].append(e)

    # -- reachability (≙ reach.c's transitive walk from Main) --
    def reachable(self, roots: Iterable[Node]) -> Set[Node]:
        seen: Set[Node] = set()
        stack = [r for r in roots if r in self.nodes]
        while stack:
            n = stack.pop()
            if n in seen:
                continue
            seen.add(n)
            for e in self.out_edges.get(n, ()):
                if e.delivers and e.dst in self.nodes and e.dst not in seen:
                    stack.append(e.dst)
        return seen

    # -- strongly connected components (iterative Tarjan) --
    def sccs(self, edge_ok) -> List[List[Node]]:
        """SCCs of the subgraph of edges where edge_ok(e); singleton
        components are included only when they carry a self-loop (so
        every returned component contains a cycle)."""
        adj: Dict[Node, List[Node]] = {n: [] for n in self.nodes}
        selfloop: Set[Node] = set()
        for e in self.edges:
            if not edge_ok(e) or e.external or e.dst not in self.nodes:
                continue
            adj[e.src].append(e.dst)
            if e.src == e.dst:
                selfloop.add(e.src)
        index: Dict[Node, int] = {}
        low: Dict[Node, int] = {}
        on_stack: Set[Node] = set()
        stack: List[Node] = []
        out: List[List[Node]] = []
        counter = [0]

        for start in self.nodes:
            if start in index:
                continue
            work = [(start, iter(adj[start]))]
            index[start] = low[start] = counter[0]
            counter[0] += 1
            stack.append(start)
            on_stack.add(start)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append((nxt, iter(adj[nxt])))
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1 or comp[0] in selfloop:
                        out.append(comp)
        return out

    # -- helpers for the rules --
    def spawn_target_types(self) -> Set[str]:
        """Types some live spawn/spawn_sync site creates (when!=False)."""
        return {e.dst[0] for e in self.edges
                if e.kind in ("spawn", "spawn_sync")
                and e.when is not False}

    def edges_between(self, src: Node, members: Set[Node], edge_ok):
        return [e for e in self.edges
                if e.src == src and e.dst in members and edge_ok(e)
                and not e.external]
