"""Whole-program static-analysis (lint) pass.

≙ the reference compiler's whole-program stages: reach/paint prune and
analyse the complete call graph before codegen (src/libponyc/reach/),
and the capability type system proves data-race freedom at compile
time (type/cap.c, safeto.c, alias.c). This port's per-behaviour verify
pass (verify.py) sees one behaviour at a time; the lint pass assembles
every behaviour's probe facts into a program-wide MESSAGE-FLOW GRAPH
(nodes = (type, behaviour); edges = send/spawn sites with when-mask
constness) and runs rule passes over it — reachability, dead-letter,
capability/race, amplification/overflow, and budget feasibility
(rules.py documents R0–R5).

Everything runs on jax.eval_shape probe traces only — no compilation;
linting a full program costs milliseconds. Exactly the ahead-of-time
structural checking actor-on-accelerator systems lean on because
device-side introspection is expensive (CAF's OpenCL actors, PGAS
actors — PAPERS.md): a bad send should fail HERE, not surface as a
silent dead-letter counter deep inside a jitted step.

Three surfaces:

  python -m ponyc_tpu lint mymodule [--json] [--roots A.go,B.tick]
      CLI over a module's actor types (exit 0 = clean).

  from ponyc_tpu.lint import lint_program, lint_types, lint_module
      findings = lint_program(runtime.program)
      findings = lint_types(A, B, roots=[A.go])

  verify.verify_program(program) runs lint_program and raises
      VerifyError on error-severity findings; docgen.document(program)
      marks unreachable/dead-letter behaviours.

Roots (host inject sites): without any declared roots, lint assumes
the host may inject messages into ANY behaviour — R1 reachability and
the rooted R2 sub-rule stay quiet. Declare roots to tighten:
``LINT_ROOTS = ("go",)`` on an actor type (its own behaviours),
``LINT_ROOTS = (A.go, "B.tick")`` at module level, or ``roots=`` /
``--roots``. Net/timer callback behaviours are inject sites too —
list them.

Suppressions, finest first: a trailing ``# lint: ignore[R6]`` (or
bare ``# lint: ignore``) comment on the finding's source line;
``@behaviour(lint_ignore=("R6", ...))`` on one behaviour;
``LINT_IGNORE = ("R4", ...)`` on the actor type. All three are
honoured by the graph rules (R0–R5) and the body rules (R6–R9) alike.

The body rules (bodycheck.py) also run standalone over FILES — pure
AST, no JAX, no import of the target: ``check_source``/``check_path``
/ ``python -m ponyc_tpu lint some_dir/`` lint files that do not even
import.
"""

from __future__ import annotations

import linecache
from typing import Dict, List, Optional, Sequence, Tuple

from ..api import ActorTypeMeta, BehaviourDef
from . import bodycheck
from .bodycheck import check_path, check_paths, check_source
from .facts import BehaviourFacts, TypeFacts, gather
from .graph import Edge, FlowGraph, Node
from .rules import (SEVERITIES, Finding, line_suppressed, run_rules,
                    sort_findings)

__all__ = [
    "Finding", "FlowGraph", "Edge", "Node", "BehaviourFacts",
    "TypeFacts", "SEVERITIES", "lint_types", "lint_module",
    "lint_program", "format_findings", "findings_to_json",
    "findings_to_github", "gather", "bodycheck", "check_path",
    "check_paths", "check_source",
]


def _resolve_roots(roots, types: Dict[str, TypeFacts]
                   ) -> Optional[List[Node]]:
    """Explicit roots + LINT_ROOTS declarations → node list (None if no
    roots anywhere: un-rooted mode, every behaviour injectable)."""
    nodes: List[Node] = []
    for r in roots or ():
        if isinstance(r, BehaviourDef):
            nodes.append((r.actor_type.__name__, r.name))
        elif isinstance(r, str):
            tname, _, bname = r.partition(".")
            if not bname:
                raise ValueError(
                    f"lint root {r!r}: expected 'Type.behaviour'")
            nodes.append((tname, bname))
        elif isinstance(r, (tuple, list)) and len(r) == 2:
            nodes.append((str(r[0]), str(r[1])))
        else:
            raise TypeError(
                f"lint root {r!r}: pass a behaviour (A.go), a "
                "'Type.behaviour' string, or a (type, behaviour) pair")
    for tf in types.values():
        for bname in tf.roots_declared:
            nodes.append((tf.name, bname))
    if not nodes:
        return None
    known = {(tf.name, bf.behaviour)
             for tf in types.values() for bf in tf.behaviours}
    for n in nodes:
        if n not in known:
            raise ValueError(
                f"lint root {n[0]}.{n[1]} names no behaviour in the "
                "analysed program")
    return nodes


def _suppress(findings: Sequence[Finding],
              types: Dict[str, TypeFacts]
              ) -> Tuple[List[Finding], List[Finding]]:
    """Split into (active, suppressed): the subject type's LINT_IGNORE
    tuple, the behaviour's own lint_ignore, and trailing
    ``# lint: ignore[...]`` comments on the finding's source line."""
    active, muted = [], []
    for f in findings:
        tf = types.get(f.type_name)
        if tf is not None and f.rule in tf.ignore:
            muted.append(f)
            continue
        bf = None
        if tf is not None and f.behaviour is not None:
            bf = next((b for b in tf.behaviours
                       if b.behaviour == f.behaviour), None)
        if bf is not None and f.rule in bf.ignore:
            muted.append(f)
            continue
        if f.file and f.line and line_suppressed(
                f, linecache.getline(f.file, f.line)):
            muted.append(f)
            continue
        active.append(f)
    return active, muted


def lint_types(*atypes: ActorTypeMeta, roots=None, msg_words: int = 8,
               default_max_sends: int = 2,
               include_suppressed: bool = False) -> List[Finding]:
    """Lint a world of concrete actor types: the probe-fact graph
    rules (R0–R5) plus the pure-AST behaviour-body rules (R6–R9,
    bodycheck.py). `roots` (optional): behaviours the host injects
    into — BehaviourDefs, 'Type.behaviour' strings, or (type,
    behaviour) pairs; merged with any LINT_ROOTS class declarations.
    Returns findings sorted most severe first; suppressed findings
    (LINT_IGNORE / lint_ignore / line comments) are dropped unless
    `include_suppressed`."""
    types = gather(atypes, msg_words=msg_words,
                   default_max_sends=default_max_sends)
    g = FlowGraph(types)
    findings = run_rules(g, _resolve_roots(roots, types))
    findings = sort_findings(
        findings + bodycheck.check_types(*atypes,
                                         include_suppressed=True))
    if include_suppressed:
        return findings
    active, _ = _suppress(findings, types)
    return active


def lint_module(mod, roots=None,
                include_suppressed: bool = False) -> List[Finding]:
    """Lint every concrete actor type defined at a module's top level
    (generic templates are skipped — only reifications have layouts).
    Honours a module-level ``LINT_ROOTS`` unless `roots` overrides it.
    Raises ValueError if the module has no concrete actor types."""
    from ..api import Actor
    atypes = []
    for v in vars(mod).values():
        if (isinstance(v, ActorTypeMeta) and v is not Actor
                and not getattr(v, "_type_params", ())
                and v not in atypes):
            atypes.append(v)
    if not atypes:
        raise ValueError(
            f"no concrete actor types at the top level of "
            f"{getattr(mod, '__name__', mod)!r}")
    if roots is None:
        roots = getattr(mod, "LINT_ROOTS", None)
    return lint_types(*atypes, roots=roots,
                      include_suppressed=include_suppressed)


def lint_program(program, roots=None,
                 include_suppressed: bool = False) -> List[Finding]:
    """Lint a built Program's whole world (host cohorts included as
    graph nodes), probing with the program's own msg_words/max_sends
    resolution so facts match what the engine runs."""
    return lint_types(*(c.atype for c in program.cohorts), roots=roots,
                      msg_words=program.opts.msg_words,
                      default_max_sends=program.opts.max_sends,
                      include_suppressed=include_suppressed)


def format_findings(findings: Sequence[Finding]) -> str:
    """Human-readable report, one finding per line."""
    return "\n".join(str(f) for f in findings)


def findings_to_json(findings: Sequence[Finding]) -> str:
    """Machine-diffable report: one JSON object per line with stable
    keys {rule, severity, type, behaviour, message, file, line}
    (file/line null when unknown)."""
    return "\n".join(f.json_line() for f in findings)


def findings_to_github(findings: Sequence[Finding]) -> str:
    """GitHub Actions annotations, one ``::warning file=…,line=…::``
    command per finding (the CLI's ``--format github``)."""
    return "\n".join(f.github_line() for f in findings)
