"""Flight recorder + stall watchdog — the runtime's always-on black box
(PROFILE.md §11; ≙ the fork's runtime-analysis/telemetry machinery run
in the always-on, crash-evidence posture a serving runtime needs, not
the opt-in profiling one).

The per-behaviour profiler (PR 4) and causal tracing (PR 6) made the
runtime *introspectable*; nothing made it *operable*: a wedged window
produced no diagnosis, and the `jax.devices()` init hang silently
degraded three BENCH rounds to CPU before anything recorded why. Two
host-side pieces fix that:

- **FlightRecorder** — a bounded ring retaining the last
  ``RuntimeOptions(flight_windows)`` retired windows (the control
  scalars the run loop ALREADY fetched per retire: aux flags, counters,
  ticks/budget, host gap, controller snapshot), plus bounded rings of
  runtime events (GC passes, coded errors) and recent host-cohort mail.
  Recording is a deque append of host ints — negligible, and nothing
  here feeds the traced step: at analysis=0 the step jaxpr stays
  bit-identical to a recorder-free build (tests/test_metrics.py
  asserts it PR-4 style). The ring dumps as a structured postmortem
  (``<analysis_path>.postmortem.json`` + human text on stderr) on
  crash, on SIGQUIT, on a watchdog trip, and on
  ``Runtime.stop(postmortem=True)``.

- **Watchdog** — a monitor thread that knows the pipelined run loop's
  phases (backend-init / dispatching / in-flight / host-work /
  quiescent / idle) via the cheap epoch stamps runtime.py writes at
  every transition (one tuple assignment). A phase that makes no
  progress stamp within ``RuntimeOptions(watchdog_s)`` — scaled by the
  PR 5 controller's current/initial window ratio, so a legitimately
  grown window is not misread as a stall — trips: the flight recorder
  dumps, a one-line doctor diagnosis lands on stderr, and the main
  thread is interrupted so Runtime.run()/start() raise an int-coded
  ``errors.PonyStallError`` instead of hanging forever. Quiescent/idle
  phases never trip (a runtime waiting on external events is healthy).

``python -m ponyc_tpu doctor --postmortem FILE`` renders a dump into a
diagnosis (``diagnose_postmortem`` below); bench.py embeds
``probe_postmortem`` evidence in every ``tpu_init_error`` BENCH json.
"""

from __future__ import annotations

import collections
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

POSTMORTEM_VERSION = 1

# Ring capacities for the non-window lanes: small fixed bounds — the
# recorder must never grow with run length.
EVENT_RING = 128
HOST_MAIL_RING = 32

# Phases the watchdog arms on. "quiescent" (waiting on external events)
# and "idle" (no run() in progress) are healthy steady states.
ARMED_PHASES = frozenset({"backend-init", "dispatching", "in-flight",
                          "host-work"})

# Deadline multiplier for COLD device phases (backend init and the
# first window before any retire): the first dispatch pays trace + XLA
# compile — tens of seconds is legitimate there (PROFILE.md §4b's
# 11.8 s warmup) and must not read as a stall under a deadline sized
# for steady-state windows. The observed init hang was 90 s+, so a
# few-second watchdog still catches it comfortably.
COLD_FACTOR = 10.0


def env_snapshot() -> Dict[str, Any]:
    """Probed-environment snapshot for postmortems: accelerator-related
    env vars (secret-filtered), libtpu importability, device nodes —
    the block that makes a backend-init failure diagnosable from the
    record alone (ROADMAP item 2's first sub-task, now shared by
    bench.py's tpu_env_details and every flight-recorder dump)."""
    import importlib.util
    env = {k: v for k, v in sorted(os.environ.items())
           if k.startswith(("TPU", "JAX", "LIBTPU", "PJRT", "XLA"))
           and "KEY" not in k and "TOKEN" not in k and "SECRET" not in k}
    details: Dict[str, Any] = {
        "env": env,
        "libtpu_importable":
            importlib.util.find_spec("libtpu") is not None}
    for dev in ("/dev/accel0", "/dev/vfio"):
        details[f"dev:{dev}"] = os.path.exists(dev)
    return details


class FlightRecorder:
    """Per-runtime bounded black box. All writers run on the run-loop
    thread (window/gc/host-mail records) or the main thread; dump() may
    additionally run on the watchdog thread — deque appends and
    wholesale reads are safe under the GIL, and a postmortem taken
    mid-append only ever misses the newest record."""

    def __init__(self, rt, capacity: int = 64):
        self.rt = rt
        self.windows: collections.deque = collections.deque(
            maxlen=max(1, int(capacity)))
        self.events: collections.deque = collections.deque(
            maxlen=EVENT_RING)
        self.host_mail: collections.deque = collections.deque(
            maxlen=HOST_MAIL_RING)
        self.t0 = time.time()
        self.last_dump: Optional[str] = None    # newest postmortem path
        self.dumps = 0

    # -- recording (hot-ish path: host ints only, one deque append) --
    def window(self, step: int, ticks: int, budget: int, gap_us: float,
               pipelined: bool, aux) -> None:
        """One retired window's facts. `aux` is the already-fetched
        host-side StepAux (numpy scalars) — the recorder converts, the
        run loop pays no extra device traffic."""
        self.windows.append({
            "t_ms": round((time.time() - self.t0) * 1e3, 3),
            "step": int(step), "ticks": int(ticks),
            "budget": int(budget), "gap_us": round(float(gap_us), 1),
            "pipelined": bool(pipelined),
            "processed": int(aux.n_processed) & 0xFFFFFFFF,
            "delivered": int(aux.n_delivered) & 0xFFFFFFFF,
            "occ_sum": int(aux.occ_sum), "occ_max": int(aux.occ_max),
            "qw_p99": int(aux.qw_p99),
            "muted_now": int(aux.n_muted_now),
            "flags": {
                "device_pending": bool(aux.device_pending),
                "host_pending": bool(aux.host_pending),
                "exit": bool(aux.exit_flag),
                "any_muted": bool(aux.any_muted),
                "spill_overflow": bool(aux.spill_overflow),
                "spawn_fail": bool(aux.spawn_fail),
                "blob_fail": bool(aux.blob_fail),
                "blob_budget_fail": bool(aux.blob_budget_fail),
            },
        })

    def event(self, kind: str, **fields) -> None:
        """A runtime event (gc pass, coded error, watchdog arm/trip)."""
        self.events.append({
            "t_ms": round((time.time() - self.t0) * 1e3, 3),
            "step": int(getattr(self.rt, "steps_run", 0)),
            "kind": kind, **fields})

    def mail(self, actor_id: int, behaviour: str) -> None:
        """One host-cohort dispatch (the 'recent host mail' lane)."""
        self.host_mail.append({
            "t_ms": round((time.time() - self.t0) * 1e3, 3),
            "step": int(getattr(self.rt, "steps_run", 0)),
            "actor": int(actor_id), "behaviour": behaviour})

    # -- snapshotting / dumping --
    def postmortem(self, reason: str, **extra) -> Dict[str, Any]:
        """The structured dump: reason + the rings + runtime/host facts.
        Everything in it is JSON-serialisable host state — building it
        never touches the device (a postmortem of a wedged device must
        not block on the device)."""
        rt = self.rt
        import dataclasses
        ctrl = getattr(rt, "_controller", None)
        wd = getattr(rt, "_watchdog", None)
        phase, epoch, t = getattr(rt, "_wd_stamp", ("?", 0, 0.0))
        pm: Dict[str, Any] = {
            "version": POSTMORTEM_VERSION,
            "reason": reason,
            "time": time.time(),
            "uptime_s": round(time.time() - self.t0, 3),
            "pid": os.getpid(),
            "steps_run": int(getattr(rt, "steps_run", 0)),
            "phase": {"name": phase, "epoch": int(epoch),
                      "age_s": round(max(0.0, time.monotonic() - t), 3)
                      if t else None},
            "windows": list(self.windows),
            "events": list(self.events),
            "host_mail": list(self.host_mail),
            "queues": {"inject": len(getattr(rt, "_inject_q", ())),
                       "fast": len(getattr(rt, "_host_fast_q", ()))},
            "totals": {k: int(v)
                       for k, v in getattr(rt, "totals", {}).items()},
            "errors": [{"class": cls, "code": int(code), "count": int(n)}
                       for (cls, code), n in sorted(
                           getattr(rt, "_error_counts", {}).items())],
            # Durable-worlds evidence (ISSUE 8): where the newest
            # restorable checkpoint lives — the first thing an operator
            # (or the supervisor) needs from a crash dump.
            "checkpoint": (rt._ckpt.info()
                           if getattr(rt, "_ckpt", None) is not None
                           else None),
            "controller": (None if ctrl is None else {
                **ctrl.snapshot(),
                "recent": ctrl.recent_decisions()}),
            # Serving front door (ISSUE 9): shed rate / queue depth /
            # admission limit / egress backlog — the overload half of
            # a service postmortem (None when no Server is attached).
            "serving": (rt._serve.stats()
                        if getattr(rt, "_serve", None) is not None
                        else None),
            "watchdog": (None if wd is None else wd.snapshot()),
            # Measured device costs (ISSUE 19): the costs.capture memo
            # when the observatory ran — a host attribute, present so a
            # crash dump states what the executables actually cost,
            # not just what the model claimed. None pre-capture (and on
            # every pre-PR-19 postmortem: readers must .get()).
            "measured": getattr(rt, "_costs", None),
            "options": dataclasses.asdict(rt.opts)
            if getattr(rt, "opts", None) is not None else {},
            "env": env_snapshot(),
        }
        pm.update(extra)
        return pm

    def dump(self, reason: str, path: Optional[str] = None,
             out=None, **extra) -> Tuple[str, str]:
        """Write ``<analysis_path>.postmortem.json`` (or `path`) and
        print the human rendering to stderr (or `out`). Returns
        (path, text). Never raises — a failing dump on the way down
        must not mask the original crash."""
        pm = self.postmortem(reason, **extra)
        if path is None:
            path = self.rt.opts.analysis_path + ".postmortem.json"
        text = render_postmortem(pm)
        try:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(pm, f, indent=1)
            os.replace(tmp, path)    # readers never see a half dump
        except OSError as e:
            text += f"\n(postmortem file write failed: {e})"
            path = ""
        try:
            print(text, file=out or sys.stderr)
        except Exception:      # noqa: BLE001 — closed stderr on teardown
            pass
        self.last_dump = path or None
        self.dumps += 1
        return path, text


# ---- the stall watchdog ---------------------------------------------------

class Watchdog(threading.Thread):
    """Monitor thread converting a silent hang into evidence + an
    int-coded error. Reads only host attributes (the phase stamp tuple,
    the controller's window int) — it can observe a runtime whose
    device is wedged solid."""

    def __init__(self, rt, deadline_s: float):
        super().__init__(name="pony-tpu-watchdog", daemon=True)
        self.rt = rt
        self.deadline_s = float(deadline_s)
        self.tripped: Optional[Dict[str, Any]] = None
        self._stop = threading.Event()
        self._main_ident = threading.main_thread().ident

    def effective_deadline(self, phase: Optional[str] = None) -> float:
        """The configured deadline scaled by (a) how far the adaptive
        controller has grown the window past its initial value — a
        1024-tick window legitimately takes longer than the 4-tick one
        the deadline was calibrated against — and (b) COLD_FACTOR for
        device phases before the first retire (trace + XLA compile)."""
        base = self.deadline_s
        ctrl = getattr(self.rt, "_controller", None)
        loaded = int(getattr(self.rt, "_qi_loaded", 0) or 0)
        if ctrl is not None and loaded > 0:
            base *= max(1.0, ctrl.window / loaded)
        if phase in ("backend-init", "dispatching", "in-flight") \
                and int(getattr(self.rt, "_rl_windows", 0)) == 0:
            base *= COLD_FACTOR
        return base

    def check(self, now: Optional[float] = None) -> Optional[Dict[str, Any]]:
        """One deadline evaluation (pure in the stamp + clock): the trip
        record when the armed phase's stamp is older than the effective
        deadline, else None. Exposed for tests — the thread loop below
        is just this on a timer."""
        now = time.monotonic() if now is None else now
        phase, epoch, t = getattr(self.rt, "_wd_stamp", ("idle", 0, now))
        if phase not in ARMED_PHASES:
            return None
        deadline = self.effective_deadline(phase)
        age = now - t
        if age <= deadline:
            return None
        return {"phase": phase, "epoch": int(epoch),
                "age_s": round(age, 3),
                "deadline_s": round(deadline, 3),
                "configured_s": self.deadline_s}

    def snapshot(self) -> Dict[str, Any]:
        return {"deadline_s": self.deadline_s,
                "effective_deadline_s": round(self.effective_deadline(), 3),
                "tripped": self.tripped}

    def run(self) -> None:
        poll = max(0.01, min(0.25, self.deadline_s / 4.0))
        while not self._stop.wait(poll):
            trip = self.check()
            if trip is not None:
                self.trip(trip)
                return

    def trip(self, info: Dict[str, Any]) -> None:
        """Dump the postmortem, diagnose on stderr, interrupt the main
        thread so run()/start() convert the pending KeyboardInterrupt
        into PonyStallError. A truly wedged C call (a hung backend
        never returning) cannot be unblocked host-side — the dump on
        disk is the value there; the interrupt lands the moment the
        call (or the signal mask across the donation region) yields."""
        self.tripped = info
        fr = getattr(self.rt, "_flight", None)
        path = ""
        if fr is not None:
            fr.event("watchdog_trip", **info)
            path, _ = fr.dump(
                reason=f"watchdog: phase {info['phase']!r} made no "
                       f"progress for {info['age_s']}s "
                       f"(deadline {info['deadline_s']}s)")
            info["postmortem"] = path
        print("ponyc_tpu doctor: STALLED — phase "
              f"{info['phase']!r} silent for {info['age_s']}s "
              f"(deadline {info['deadline_s']}s); postmortem: "
              f"{path or '(unwritten)'}", file=sys.stderr)
        try:
            import signal
            signal.pthread_kill(self._main_ident, signal.SIGINT)
        except (AttributeError, ValueError, OSError, TypeError):
            import _thread
            _thread.interrupt_main()

    def close(self) -> None:
        self._stop.set()


# ---- postmortem rendering / diagnosis -------------------------------------

def load_postmortem(path: str) -> Dict[str, Any]:
    with open(path) as f:
        pm = json.load(f)
    if not isinstance(pm, dict) or "reason" not in pm:
        raise ValueError(f"{path}: not a ponyc_tpu postmortem "
                         "(no 'reason' field)")
    return pm


def probe_postmortem(timeline: List[Dict[str, Any]],
                     env: Optional[Dict[str, Any]] = None
                     ) -> Dict[str, Any]:
    """A flight-recorder-shaped postmortem for a failure BEFORE any
    runtime exists: the TPU backend-init probe (bench.py). `timeline`
    is the probe attempts — [{attempt, t_s, timeout_s, error}] — the
    stall evidence every CPU-fallback BENCH round must carry."""
    last = timeline[-1]["error"] if timeline else None
    return {
        "version": POSTMORTEM_VERSION,
        "reason": "tpu_init_failed",
        "time": time.time(),
        "pid": os.getpid(),
        "phase": {"name": "backend-init", "epoch": 0,
                  "age_s": round(sum(a.get("t_s", 0.0)
                                     for a in timeline), 1)},
        "probe_timeline": timeline,
        "last_error": last,
        "env": env if env is not None else env_snapshot(),
    }


def _fmt_flags(flags: Dict[str, Any]) -> str:
    up = [k for k, v in (flags or {}).items() if v]
    return ",".join(up) if up else "-"


def render_postmortem(pm: Dict[str, Any]) -> str:
    """Human text of a postmortem dict — what dump() prints to stderr
    and `doctor --postmortem` shows under its one-line verdict."""
    lines = ["=== ponyc_tpu flight-recorder postmortem ==="]
    lines.append(f"reason: {pm.get('reason', '?')}")
    ph = pm.get("phase") or {}
    lines.append(f"phase: {ph.get('name', '?')} "
                 f"(age {ph.get('age_s', '?')}s, "
                 f"epoch {ph.get('epoch', '?')})  "
                 f"steps_run={pm.get('steps_run', '?')}  "
                 f"pid={pm.get('pid', '?')}")
    q = pm.get("queues") or {}
    if q:
        lines.append(f"queues: inject={q.get('inject', 0)} "
                     f"fast={q.get('fast', 0)}")
    errs = pm.get("errors") or []
    for e in errs:
        lines.append(f"error: {e['class']} (code {e['code']}) "
                     f"x{e['count']}")
    ck = pm.get("checkpoint")
    if ck and ck.get("path"):
        lines.append(
            f"restorable from: {ck['path']} (age {ck.get('age_s', '?')}s,"
            f" seq {ck.get('seq', '?')}, checksum "
            f"{'ok' if ck.get('verified') else 'unverified'})")
    elif ck is not None:
        lines.append("restorable from: (no checkpoint written yet)")
    ctrl = pm.get("controller")
    if ctrl:
        lines.append(f"controller: window={ctrl.get('window')} "
                     f"state={ctrl.get('state')} "
                     f"grows={ctrl.get('grows')} "
                     f"shrinks={ctrl.get('shrinks')}")
    wins = pm.get("windows") or []
    if wins:
        lines.append(f"last {len(wins)} windows (newest last):")
        for w in wins[-8:]:
            lines.append(
                f"  step={w['step']} ticks={w['ticks']}/{w['budget']} "
                f"gap={w['gap_us']}us occ={w['occ_sum']} "
                f"qw_p99={w['qw_p99']} flags={_fmt_flags(w['flags'])}")
    srv = pm.get("serving")
    if srv:
        sh = srv.get("shed") or {}
        lines.append(
            f"serving: frames={srv.get('frames')} "
            f"accepted={srv.get('accepted')} "
            f"replied={srv.get('replied')} "
            f"shed={srv.get('shed_total')} "
            f"(rate {srv.get('shed_rate')}; "
            + ", ".join(f"{k}={v}" for k, v in sorted(sh.items()))
            + f") queue={srv.get('queue')} "
            f"inflight={srv.get('inflight')} "
            f"admit_limit={(srv.get('admission') or {}).get('limit')} "
            f"net_pending={srv.get('net_pending_bytes')}B"
            + (" DRAINING" if srv.get("draining") else ""))
    mail = pm.get("host_mail") or []
    if mail:
        lines.append("recent host mail: " + ", ".join(
            f"a{m['actor']}.{m['behaviour']}" for m in mail[-6:]))
    # Measured device costs (ISSUE 19) — absent on pre-capture runs and
    # every pre-PR-19 postmortem: .get() everything, render nothing
    # rather than crash the crash report.
    meas = pm.get("measured") or {}
    for exe, rec in sorted((meas.get("executables") or {}).items()):
        if not isinstance(rec, dict) or rec.get("error"):
            continue
        bits = []
        if rec.get("flops") is not None:
            bits.append(f"flops={rec['flops']:.3g}")
        if rec.get("bytes_accessed") is not None:
            bits.append(f"bytes={rec['bytes_accessed']:.3g}")
        if rec.get("peak_bytes") is not None:
            bits.append(f"peak={rec['peak_bytes']}B")
        if bits:
            lines.append(f"measured [{exe}] "
                         f"({meas.get('backend', '?')}): "
                         + " ".join(bits))
    div = meas.get("model_divergence") or {}
    if div.get("ratio") is not None:
        verdict = ("DIVERGED" if div.get("diverged") else "ok")
        lines.append(
            f"model vs measured: {div.get('modelled_bytes')} vs "
            f"{div.get('measured_bytes')} B/msg "
            f"(ratio {div['ratio']}, tol {div.get('tolerance')}) "
            f"-> {verdict}")
    tl = pm.get("probe_timeline")
    if tl:
        lines.append(f"backend probe attempts: {len(tl)}")
        for a in tl[-4:]:
            lines.append(f"  attempt {a.get('attempt')}: "
                         f"timeout={a.get('timeout_s')}s "
                         f"error={a.get('error')}")
    env = pm.get("env") or {}
    if env:
        lines.append(f"env: libtpu_importable="
                     f"{env.get('libtpu_importable')} "
                     + " ".join(f"{k}={v}" for k, v in
                                sorted((env.get('env') or {}).items())))
    return "\n".join(lines)


def diagnose_postmortem(pm: Dict[str, Any]) -> Tuple[str, str]:
    """(one_line_verdict, detail_text) for a postmortem — the doctor's
    reading. The one-liner is what bench.py prints when a TPU init
    failure downgrades a round, and what the CLI leads with."""
    reason = str(pm.get("reason", "?"))
    ph = pm.get("phase") or {}
    wins = pm.get("windows") or []
    last = wins[-1] if wins else None
    if reason == "tpu_init_failed":
        tl = pm.get("probe_timeline") or []
        line = (f"STALLED: TPU backend init failed after "
                f"{len(tl)} probe attempt(s) over "
                f"{ph.get('age_s', '?')}s — last error: "
                f"{pm.get('last_error') or '?'}")
    elif reason.startswith("watchdog"):
        hint = ""
        if ph.get("name") == "in-flight":
            hint = " (device never retired the window: backend hang " \
                   "or a runaway in-window loop)"
        elif ph.get("name") == "host-work":
            hint = " (a host behaviour, poller or GC pass is stuck)"
        elif ph.get("name") == "backend-init":
            hint = " (jax backend init hang — probe the accelerator " \
                   "in a subprocess: platforms.probe_accelerator)"
        line = (f"STALLED: {reason}{hint}")
    elif (pm.get("errors") or []):
        e = pm["errors"][-1]
        line = (f"CRASHED: {e['class']} (code {e['code']}) at step "
                f"{pm.get('steps_run', '?')}")
        if last is not None and last["flags"].get("spill_overflow"):
            line += " — spill overflow: raise spill_cap/mailbox_cap " \
                    "or lower overload_threshold"
    elif reason.startswith(("SIGQUIT", "manual", "stop")):
        line = (f"SNAPSHOT: {reason} at step {pm.get('steps_run', '?')} "
                f"(phase {ph.get('name', '?')}) — no failure recorded")
    else:
        line = f"CRASHED: {reason} at step {pm.get('steps_run', '?')}"
    if last is not None and int(last.get("occ_max", 0)) > 0 \
            and "STALLED" in line:
        line += (f"; {last['occ_sum']} message(s) still queued "
                 f"(deepest {last['occ_max']})")
    srv = pm.get("serving")
    if srv and line.startswith(("STALLED", "CRASHED")):
        # Serving-aware verdict (ISSUE 9): was the front door shedding
        # (edge held) and how much reply backlog died with the world?
        line += (f"; serving: shed_rate={srv.get('shed_rate')} "
                 f"inflight={srv.get('inflight')} "
                 f"net_pending={srv.get('net_pending_bytes')}B")
    ck = pm.get("checkpoint")
    if ck and ck.get("path") and line.startswith(("STALLED", "CRASHED")):
        # The doctor's recovery pointer: what the supervisor would
        # restore from (`python -m ponyc_tpu supervise`, supervise.py).
        line += f" — restorable from {ck['path']}"
    return line, render_postmortem(pm)
