"""JAX version compatibility shims.

The repo targets the jax_graft toolchain image, whose pinned JAX moves
APIs between releases. Every site that depends on a moved symbol goes
through here so a version bump is one edit, not a grep.

Currently shimmed:

- ``shard_map``: ``jax.shard_map`` (new spelling, with ``check_vma``)
  vs ``jax.experimental.shard_map.shard_map`` (JAX <= 0.4.x, with
  ``check_rep``). Both disable the replication/VMA check the engine's
  shard-divergent cond predicates would trip.
"""

from __future__ import annotations

import jax


def shard_map(fn, *, mesh, in_specs, out_specs):
    """Version-portable ``shard_map`` with replication checking off.

    The engine's per-shard step uses shard-divergent ``lax.cond``
    predicates (idle cohorts, pressure paths) that the static
    replication checker rejects; both spellings of the checker flag
    (``check_vma`` new, ``check_rep`` old) are therefore disabled.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=False)
