"""Measured, not modelled — device-cost capture (ISSUE 19; the
observability substrate ROADMAP item 2's real-silicon speed run
dispatches on).

Every megakernel claim so far (window fusion, the 2.0x bytes/msg diet)
is interpret-mode or *modelled*: ops/megakernel.modelled_bytes_per_msg
prices a ring record from the layout alone. This module pulls the
numbers XLA itself reports for the REAL executables — the Halide
push-memory paper's discipline (PAPERS.md): HBM traffic is measured
before/after staging a pipeline, never assumed — and the
resource-consumption-preserving actors→Haskell translation's posture of
cost accounting attributed per construct rather than per opaque binary:

- ``capture(rt)`` — AOT-lower + compile the runtime's actual step and
  pipelined-window executables and record ``cost_analysis()`` (flops,
  bytes accessed) and ``memory_analysis()`` (argument/output/temp/peak
  bytes) per executable. Works on CPU and TPU: CPU's memory_analysis
  may be absent and every field degrades to None, never raises. The
  capture never touches the traced step itself, so the step jaxpr is
  bit-identical with the observatory on or off.
- ``record_move_probe(opts)`` — the measured twin of the modelled
  bytes/msg: compile the canonical one-record-per-actor ring move and
  read its bytes/message back from XLA's cost analysis.
- ``divergence(modelled, measured)`` — the loud ``model_divergence``
  flag: when the model and the measurement disagree past a threshold,
  the BENCH json, /metrics and the flight-recorder postmortem all say
  so (a silent model is how three rounds of A/B machinery rotted).

The ``measured`` block these compose (``measured_block(rt)``) rides
every BENCH json next to the modelled bytes/msg, and ``bench.py
--xprof`` / ``Runtime.profile_device(windows=N)`` wrap real retired
windows in a ``jax.profiler`` trace for op-level wall attribution.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Optional

COST_VERSION = 1

# Relative disagreement past which modelled and measured bytes/msg are
# flagged as diverged: |measured - modelled| / modelled > tolerance.
# 0.5 is deliberately loose — the model prices the packed-record layout,
# XLA's accounting includes fusion/layout slop; the flag exists to catch
# the model being WRONG (2x+), not to litigate rounding.
DIVERGENCE_TOLERANCE = 0.5


# ---------------------------------------------------------------------------
# per-executable extraction (tolerant across jax versions and backends)

def _cost_dict(compiled) -> Dict[str, Optional[float]]:
    """Normalise ``compiled.cost_analysis()`` — a dict on some
    jax/backends, a one-element list of dicts on others, None where the
    backend reports nothing — into {flops, bytes_accessed,
    transcendentals}, all Optional floats."""
    out: Dict[str, Optional[float]] = {
        "flops": None, "bytes_accessed": None, "transcendentals": None}
    try:
        ca = compiled.cost_analysis()
    except Exception:                       # noqa: BLE001 — degrade
        return out
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict):
        return out
    for key, name in (("flops", "flops"),
                      ("bytes accessed", "bytes_accessed"),
                      ("transcendentals", "transcendentals")):
        v = ca.get(key)
        if v is not None:
            out[name] = float(v)
    return out


def _memory_dict(compiled) -> Dict[str, Optional[int]]:
    """Normalise ``compiled.memory_analysis()`` (CompiledMemoryStats;
    None on backends that don't report) into plain ints. ``peak_bytes``
    is the executable's device working set: arguments + outputs + temps
    + generated code (the HBM a window actually pins)."""
    out: Dict[str, Optional[int]] = {
        "argument_bytes": None, "output_bytes": None,
        "temp_bytes": None, "alias_bytes": None,
        "generated_code_bytes": None, "peak_bytes": None}
    try:
        ma = compiled.memory_analysis()
    except Exception:                       # noqa: BLE001
        return out
    if ma is None:
        return out
    for attr, name in (("argument_size_in_bytes", "argument_bytes"),
                       ("output_size_in_bytes", "output_bytes"),
                       ("temp_size_in_bytes", "temp_bytes"),
                       ("alias_size_in_bytes", "alias_bytes"),
                       ("generated_code_size_in_bytes",
                        "generated_code_bytes")):
        v = getattr(ma, attr, None)
        if v is not None:
            out[name] = int(v)
    known = [out[k] for k in ("argument_bytes", "output_bytes",
                              "temp_bytes", "generated_code_bytes")
             if out[k] is not None]
    # Donated (aliased) argument pages are the same physical HBM as the
    # outputs they alias — count them once.
    if known:
        out["peak_bytes"] = int(sum(known) - (out["alias_bytes"] or 0))
    return out


def capture_compiled(compiled) -> Dict[str, Any]:
    """The measured record of one compiled executable."""
    rec: Dict[str, Any] = dict(_cost_dict(compiled))
    rec.update(_memory_dict(compiled))
    return rec


# ---------------------------------------------------------------------------
# runtime capture: the REAL step/window executables

def capture(rt, force: bool = False) -> Dict[str, Any]:
    """Cost/memory analysis of the runtime's actual executables,
    memoized on ``rt._costs``. AOT ``lower().compile()`` with the
    runtime's canonical dispatch argument shapes — one extra compile
    per executable (the persistent XLA disk cache absorbs the repeat on
    warm starts); lowering never executes, so the world does not
    advance and donation does not consume ``rt.state``."""
    cached = getattr(rt, "_costs", None)
    if cached is not None and not force:
        return cached
    if rt.state is None:
        raise RuntimeError("call start() first")
    import jax
    import jax.numpy as jnp
    import numpy as np
    inj_t, inj_w = rt._empty_inject
    execs: Dict[str, Any] = {}
    try:
        step_c = rt._step.lower(rt.state, inj_t, inj_w).compile()
        execs["step"] = capture_compiled(step_c)
    except Exception as e:                  # noqa: BLE001 — record, go on
        execs["step"] = {"error": f"{type(e).__name__}: {e}"}
    try:
        win_c = rt._multi_g.lower(
            rt.state, inj_t, inj_w, jnp.int32(1), np.bool_(True),
            rt._zero_aux).compile()
        execs["window"] = capture_compiled(win_c)
    except Exception as e:                  # noqa: BLE001
        execs["window"] = {"error": f"{type(e).__name__}: {e}"}
    out = {
        "version": COST_VERSION,
        "backend": jax.default_backend(),
        "delivery": rt.opts.delivery,
        "executables": execs,
    }
    rt._costs = out
    return out


# ---------------------------------------------------------------------------
# the measured twin of the modelled bytes/msg

_PROBE_CACHE: Dict[tuple, Dict[str, Any]] = {}


def record_move_probe(opts, n: int = 4096) -> Dict[str, Any]:
    """Measure what XLA actually charges to move one mailbox ring
    record per actor: compile ``record + 1`` over a [record_words, n]
    int32 plane (a read of every record word + a write of every record
    word — the unpacked delivery move) and divide the executable's
    reported bytes accessed by the 2n record-planes it touches. On a
    clean-payload workload this lands on the model's
    ``unpacked_bytes = 4 * record_words`` (tests assert the tolerance);
    a model/layout drift shows up as divergence."""
    import jax
    import jax.numpy as jnp

    from .ops.megakernel import record_words
    w1 = record_words(opts)
    # The probe depends only on (record_words, n, backend) — memoize
    # per process so repeated measured_block calls pay one compile.
    key = (w1, n, jax.default_backend())
    hit = _PROBE_CACHE.get(key)
    if hit is not None:
        return dict(hit)
    table = jnp.zeros((w1, n), jnp.int32)
    compiled = jax.jit(lambda t: t + 1).lower(table).compile()
    rec = capture_compiled(compiled)
    ba = rec.get("bytes_accessed")
    per_msg = (float(ba) / n / 2.0) if ba else None
    out = {"record_words": w1, "n": n,
           "bytes_accessed": ba, "bytes_per_msg": per_msg}
    _PROBE_CACHE[key] = out
    return dict(out)


def divergence(modelled_bytes: float, measured_bytes: Optional[float],
               tolerance: float = DIVERGENCE_TOLERANCE,
               ) -> Dict[str, Any]:
    """The model-vs-measurement verdict: relative error of the measured
    bytes/msg against the modelled one, flagged past ``tolerance``.
    Unknown measurement (backend reported nothing) is honest: ratio
    None, diverged False — absence of evidence is not divergence."""
    if not measured_bytes or not modelled_bytes:
        return {"modelled_bytes": modelled_bytes,
                "measured_bytes": measured_bytes,
                "ratio": None, "tolerance": tolerance, "diverged": False}
    ratio = float(measured_bytes) / float(modelled_bytes)
    diverged = abs(ratio - 1.0) > tolerance
    return {"modelled_bytes": float(modelled_bytes),
            "measured_bytes": float(measured_bytes),
            "ratio": round(ratio, 4), "tolerance": tolerance,
            "diverged": bool(diverged)}


def measured_block(rt, modelled: Optional[Dict[str, Any]] = None,
                   tolerance: float = DIVERGENCE_TOLERANCE,
                   quiet: bool = False) -> Dict[str, Any]:
    """The standing ``measured`` block every BENCH json carries: the
    real executables' cost/memory analysis, the record-move probe, the
    modelled bytes/msg it is judged against, and the loud
    ``model_divergence`` verdict."""
    from .ops.megakernel import escape_rate_state, modelled_bytes_per_msg
    cap = dict(capture(rt))
    if modelled is None:
        esc = escape_rate_state(rt.state) if rt.state is not None else 0.0
        modelled = modelled_bytes_per_msg(rt.opts, esc)
    probe = record_move_probe(rt.opts)
    div = divergence(modelled["unpacked_bytes"], probe["bytes_per_msg"],
                     tolerance)
    cap["record_probe"] = probe
    cap["modelled"] = modelled
    cap["model_divergence"] = div
    rt._costs = cap   # metrics /metrics + flight postmortem read this
    if div["diverged"] and not quiet:
        print(f"ponyc_tpu costs: MODEL DIVERGENCE — modelled "
              f"{div['modelled_bytes']:.1f} B/msg vs measured "
              f"{div['measured_bytes']:.1f} B/msg "
              f"(ratio {div['ratio']}, tolerance {tolerance}): "
              "the bytes/msg model no longer matches what XLA charges",
              file=sys.stderr)
    return cap


# ---------------------------------------------------------------------------
# perf-regression scoreboard (python -m ponyc_tpu perf [--check])
#
# bench.py appends one flattened row per run to BENCH_HISTORY.jsonl;
# the committed BENCH_r*.json round records are ingested too (their
# driver wrapper format: {"n", "cmd", "rc", "tail", "parsed"} with the
# bench stdout json under "parsed"). The scoreboard compares like with
# like — a CPU-fallback round must not read as a "regression" from the
# last TPU round, and a 256-actor smoke must not be judged against a
# 1M-actor headline — so rows group by (metric, unit, platform,
# actors) and --check gates the newest row of each group against the
# best earlier row of the SAME group.

# vs_baseline at the driver-set north star: 10x message-ubench over
# the 32-core CPU estimate (bench.CPU32_BASELINE_MSGS_PER_SEC).
NORTH_STAR_VS_BASELINE = 10.0

# Run-to-run noise allowance for --check: a group's newest value may
# sit this fraction below the group's best without failing the gate.
PERF_TOLERANCE = 0.2


def flatten_result(parsed: Dict[str, Any], source: str,
                   ) -> Optional[Dict[str, Any]]:
    """One scoreboard row from a bench result json (the `parsed` body,
    not the driver wrapper); None when it carries no headline number
    (a failed round). Also accepts rows already flattened by
    bench.history_entry (they have no 'detail')."""
    if not isinstance(parsed, dict) or parsed.get("value") is None:
        return None
    detail = parsed.get("detail") or {}
    measured = parsed.get("measured") or {}
    step = (measured.get("executables") or {}).get("step") or {}
    div = measured.get("model_divergence") or {}
    return {
        "source": source,
        "time": parsed.get("time"),
        "metric": parsed.get("metric"),
        "unit": parsed.get("unit"),
        "value": float(parsed["value"]),
        "vs_baseline": parsed.get("vs_baseline"),
        "platform": detail.get("platform", parsed.get("platform")),
        "delivery": detail.get("delivery", parsed.get("delivery")),
        "actors": detail.get("actors", parsed.get("actors")),
        "tpu_init_error": bool(detail.get("tpu_init_error")
                               or parsed.get("tpu_init_error")),
        "measured_step_bytes": step.get(
            "bytes_accessed", parsed.get("measured_step_bytes")),
        "model_divergence": bool(div.get(
            "diverged", parsed.get("model_divergence"))),
        "divergence_ratio": div.get(
            "ratio", parsed.get("divergence_ratio")),
    }


def load_history(root: str = ".", history_path: Optional[str] = None,
                 ) -> list:
    """Every scoreboard row on disk, oldest first: the committed
    BENCH_r*.json round records (sorted by round), then the
    BENCH_HISTORY.jsonl trail in append order. Unreadable files and
    rows degrade to skipped, never raise — the scoreboard must render
    whatever survives."""
    import glob
    import json
    import os
    rows = []
    for path in sorted(glob.glob(os.path.join(root, "BENCH_r*.json"))):
        try:
            with open(path) as f:
                obj = json.load(f)
        except (OSError, ValueError):
            continue
        parsed = obj.get("parsed") if isinstance(obj, dict) else None
        if parsed is None and isinstance(obj, dict) and "value" in obj:
            parsed = obj           # a bare bench json, no wrapper
        row = flatten_result(parsed, os.path.basename(path)) \
            if parsed else None
        if row is not None:
            rows.append(row)
    if history_path is None:
        history_path = os.path.join(root, "BENCH_HISTORY.jsonl")
    try:
        with open(history_path) as f:
            lines = f.readlines()
    except OSError:
        lines = []
    for i, line in enumerate(lines):
        try:
            obj = json.loads(line)
        except ValueError:
            continue
        row = flatten_result(obj, f"history[{i}]")
        if row is not None:
            rows.append(row)
    return rows


def group_key(row: Dict[str, Any]) -> tuple:
    return (row.get("metric"), row.get("unit"),
            row.get("platform"), row.get("actors"))


def perf_check(rows: list, tolerance: float = PERF_TOLERANCE,
               ) -> Dict[str, Any]:
    """The regression gate: per comparable group, the newest row must
    not sit more than `tolerance` below the group's best earlier row;
    any row's model_divergence flag is a failure in its own right
    (measured reality disagreeing with the model is exactly what the
    observatory exists to catch). Returns {"ok", "regressions",
    "divergent", "groups"}."""
    groups: Dict[tuple, list] = {}
    for row in rows:
        groups.setdefault(group_key(row), []).append(row)
    regressions, report = [], []
    for key, grp in groups.items():
        best = max(grp, key=lambda r: r["value"])
        latest = grp[-1]
        floor = best["value"] * (1.0 - tolerance)
        regressed = len(grp) >= 2 and latest is not best \
            and latest["value"] < floor
        rec = {"key": key, "n": len(grp),
               "best": best["value"], "best_source": best["source"],
               "latest": latest["value"],
               "latest_source": latest["source"],
               "floor": round(floor, 1), "regressed": regressed}
        report.append(rec)
        if regressed:
            regressions.append(rec)
    divergent = [r for r in rows if r.get("model_divergence")]
    return {"ok": not regressions and not divergent,
            "regressions": regressions, "divergent": divergent,
            "groups": report}


def render_perf(rows: list, check: Optional[Dict[str, Any]] = None,
                ) -> str:
    """The human scoreboard: the trajectory row by row, per-group
    best-so-far, distance to the north star, and the --check verdict
    when one ran."""
    if not rows:
        return ("perf: no history found (run bench.py — every run "
                "appends to BENCH_HISTORY.jsonl; committed "
                "BENCH_r*.json rounds are read too)")
    lines = ["=== ponyc_tpu perf scoreboard ==="]
    for row in rows:
        bits = [f"{row['value']:>14,.1f} {row.get('unit') or ''}",
                f"x{row['vs_baseline']}" if row.get("vs_baseline")
                is not None else "x?",
                f"{row.get('platform') or '?'}/"
                f"{row.get('delivery') or '?'}",
                f"actors={row.get('actors') or '?'}"]
        if row.get("tpu_init_error"):
            bits.append("TPU-FALLBACK")
        if row.get("model_divergence"):
            bits.append("MODEL-DIVERGED")
        lines.append(f"  {row['source']:<18} " + "  ".join(bits))
    best = max(rows, key=lambda r: r["value"])
    lines.append(f"best so far: {best['value']:,.1f} "
                 f"{best.get('unit') or ''} ({best['source']}, "
                 f"{best.get('platform')}/{best.get('delivery')})")
    vsb = best.get("vs_baseline")
    if vsb:
        lines.append(
            f"north star:  vs_baseline {NORTH_STAR_VS_BASELINE} "
            f"(10x CPU32) — best is {vsb} "
            f"({100.0 * float(vsb) / NORTH_STAR_VS_BASELINE:.1f}% "
            "of target)")
    if check is not None:
        for rec in check["regressions"]:
            key = rec["key"]
            lines.append(
                f"REGRESSION [{key[2]}/actors={key[3]}]: latest "
                f"{rec['latest']:,.1f} ({rec['latest_source']}) is "
                f"below floor {rec['floor']:,.1f} (best "
                f"{rec['best']:,.1f} from {rec['best_source']})")
        for row in check["divergent"]:
            lines.append(
                f"MODEL DIVERGENCE [{row['source']}]: measured/"
                f"modelled bytes ratio {row.get('divergence_ratio')}")
        lines.append("check: " + ("OK" if check["ok"] else "FAIL"))
    return "\n".join(lines)
