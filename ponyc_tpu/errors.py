"""Int-coded errors — ≙ the fork's error machinery
(pony.h:610-665 pony_try/pony_error/pony_error_int/pony_error_code/
pony_error_loc; lang/posix_except.c + except_try_catch.ll underneath).

The fork replaced Pony's bare `error` with errors that carry an int
code and a source location, caught by `try ... else` and queryable via
`__error_code()`. The TPU framework's three surfaces:

- **Host behaviours** raise PonyError(code): the dispatch loop catches
  it, records the code, and the actor continues with its next message —
  exactly a behaviour-local `try ... else` that logs (a Pony behaviour
  cannot leak errors; the unwind stops at the dispatch boundary).
- **Host driver code** uses pony_try() to get the (ok, value_or_code)
  shape of the reference's pony_try (pony.h:610).
- **Device behaviours** call ctx.error_int(code, when=...) — errors are
  values under vmap; the latest code lands in the per-actor
  `last_error` column and the n_errors counter (api.py).

Locations: PonyError captures the raise site (≙ pony_error_loc's
file/line), surfaced in logs and pony_try results.
"""

from __future__ import annotations

import traceback
from typing import Any, Callable, Tuple


class PonyError(Exception):
    """≙ pony_error_int: an error that is a value with an int code."""

    def __init__(self, code: int = 1, message: str = ""):
        super().__init__(message or f"error {code}")
        self.code = int(code)
        # ≙ pony_error_loc: the raise site.
        stack = traceback.extract_stack(limit=3)
        frame = stack[0] if stack else None
        self.loc = (f"{frame.filename}:{frame.lineno}" if frame else "?")


def pony_try(fn: Callable, *args, **kw) -> Tuple[bool, Any]:
    """≙ pony_try (pony.h:610): run fn; (True, result) on success,
    (False, error_code) when it raises PonyError."""
    try:
        return True, fn(*args, **kw)
    except PonyError as e:
        return False, e.code
