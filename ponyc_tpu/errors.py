"""Int-coded errors — ≙ the fork's error machinery
(pony.h:610-665 pony_try/pony_error/pony_error_int/pony_error_code/
pony_error_loc; lang/posix_except.c + except_try_catch.ll underneath).

The fork replaced Pony's bare `error` with errors that carry an int
code and a source location, caught by `try ... else` and queryable via
`__error_code()`. The TPU framework's three surfaces:

- **Host behaviours** raise PonyError(code): the dispatch loop catches
  it, records the code, and the actor continues with its next message —
  exactly a behaviour-local `try ... else` that logs (a Pony behaviour
  cannot leak errors; the unwind stops at the dispatch boundary).
- **Host driver code** uses pony_try() to get the (ok, value_or_code)
  shape of the reference's pony_try (pony.h:610).
- **Device behaviours** call ctx.error_int(code, when=...) — errors are
  values under vmap; the latest code lands in the per-actor
  `last_error` column and the n_errors counter (api.py).

Locations: PonyError captures the raise site (≙ pony_error_loc's
file/line), surfaced in logs and pony_try results.
"""

from __future__ import annotations

import os
import traceback
from typing import Any, Callable, Tuple

_PKG_DIR = os.path.dirname(os.path.abspath(__file__))


def caller_loc(skip_pkg: bool = True) -> str:
    """file:line of the nearest stack frame OUTSIDE the ponyc_tpu
    package (≙ pony_error_loc pointing at user code). Shared by
    PonyError and Context.error_int so raise-site attribution lives
    once — helpers like stdlib Fact/Assert and error_int itself never
    claim the location."""
    for frame in reversed(traceback.extract_stack()):
        fn = os.path.abspath(frame.filename)
        if not skip_pkg or not fn.startswith(_PKG_DIR + os.sep):
            return f"{frame.filename}:{frame.lineno}"
    return "?"


# --- stable runtime error codes (≙ the fork's int-coded errors made a
# runtime-wide contract): every runtime error CLASS carries one fixed
# int, exposed on the exception (`.code`), as the metrics label
# `pony_tpu_errors_total{class=...,code=...}` (metrics.py) and in
# flight-recorder postmortems (flight.py), so operators and alert rules
# match on a number that never drifts with a message rewrite. The table
# is documented in README "Operating it" — codes are append-only. ---
ERROR_CODES = {
    "PonyError": 1,           # behaviour-level error (default user code;
    #   PonyError instances carry their own caller-chosen code)
    "SpillOverflowError": 2,     # runtime.py — bounded spill exceeded
    "SpawnCapacityError": 3,     # runtime.py — device spawn found no slot
    "BlobCapacityError": 4,      # runtime.py — blob pool/budget exhausted
    "CapabilityError": 5,        # hostmem.py — capability discipline
    "VerifyError": 6,            # verify.py — behaviour budget violation
    "PonyStallError": 7,         # this file — watchdog-declared stall
    "SnapshotCorruptError": 8,   # serialise.py — checkpoint failed its
    #   checksum/structure verification (truncated/bit-flipped file)
    "SnapshotFormatError": 9,    # serialise.py — snapshot written by an
    #   unknown FUTURE format version (loud, never a silent drop)
    "SnapshotGeometryError": 10,  # serialise.py — a geometry-changing
    #   restore found occupancy that does not fit the new layout
    "PoisonError": 11,           # supervise.py — deterministic poison:
    #   the same coded error at the same world position twice; the
    #   supervisor refuses to restart-loop on it
    "FrameError": 12,            # serve.py — malformed ingress frame
    #   (bad length prefix / non-word body); doubles as the wire
    #   BADFRAME reply status of the serving front door
    "ServeBusyError": 13,        # serve.py — admission shed at the
    #   edge (overload, drain, or a choked slow-consumer connection);
    #   doubles as the wire BUSY reply status — clients back off
    "ServeDeadlineError": 14,    # serve.py — a request's deadline
    #   expired before the device could serve it; the wire DEADLINE
    #   reply status
}


def error_code(exc) -> int:
    """Stable int code of a runtime exception: the instance's own
    `.code` when it carries one (PonyError), else the class table above
    walked up the MRO; 0 = not a coded runtime error."""
    code = getattr(exc, "code", None)
    if isinstance(code, int):
        return code
    for klass in type(exc).__mro__:
        c = ERROR_CODES.get(klass.__name__)
        if c is not None:
            return c
    return 0


class PonyStallError(RuntimeError):
    """The stall watchdog (flight.py) declared the runtime wedged: a
    run-loop phase (backend init, a dispatched window, host work)
    exceeded its deadline with no progress stamp. Carries the tripped
    phase and the postmortem path the watchdog wrote — the structured
    replacement for the silent forever-hang (ISSUE 7 / the
    `jax.devices()` init hang that degraded BENCH r03–r05)."""

    code = ERROR_CODES["PonyStallError"]

    def __init__(self, message: str = "", phase: str = "?",
                 postmortem: str = ""):
        super().__init__(message or f"runtime stalled in phase {phase!r}")
        self.phase = phase
        self.postmortem = postmortem


class PonyError(Exception):
    """≙ pony_error_int: an error that is a value with an int code."""

    def __init__(self, code: int = 1, message: str = ""):
        super().__init__(message or f"error {code}")
        self.code = int(code)
        # ≙ pony_error_loc: the nearest user-code raise site (so Fact/
        # Assert and other in-package helpers attribute to their caller).
        self.loc = caller_loc()


def pony_try(fn: Callable, *args, **kw) -> Tuple[bool, Any]:
    """≙ pony_try (pony.h:610): run fn; (True, result) on success,
    (False, error_code) when it raises PonyError."""
    try:
        return True, fn(*args, **kw)
    except PonyError as e:
        return False, e.code


# --- device error-site registry (≙ the fork's __error_loc token,
# DIVERGENCE.md "Retrieve the source location where an error occurred").
# Each trace-time ctx.error_int() call site registers its Python
# file:line here once; the device carries only the i32 site id (+1; 0 =
# no error), and Runtime.last_error_loc() resolves it back to a string —
# the same "C-string table on the side" performance choice the fork
# made for __error_loc.
_device_error_sites: list = ["?"]     # id 0 = no/unknown site


def register_error_site(loc: str) -> int:
    """Intern a trace-time error site, returning its id (>= 1)."""
    try:
        return _device_error_sites.index(loc)
    except ValueError:
        _device_error_sites.append(loc)
        return len(_device_error_sites) - 1


def error_site(site_id: int) -> str:
    """Resolve a site id from the last_error_loc column."""
    if 0 <= site_id < len(_device_error_sites):
        return _device_error_sites[site_id]
    return "?"
