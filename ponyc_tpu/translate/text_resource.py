"""Text resources → string-constant modules.

≙ translate_text_resource.c (82 LoC): md/txt/json files in a package
become Pony string constants. Here: a module exposing TEXT (and, for
.json, DATA = parsed object) so resources ship inside the package the
same way.
"""

from __future__ import annotations

import json


def translate_text_resource(text: str, *, name: str = "resource.txt") -> str:
    lines = [
        f'"""Resource generated from {name} by ponyc_tpu.translate."""',
        "",
        f"TEXT = {text!r}",
        "",
    ]
    if name.lower().endswith(".json"):
        try:
            json.loads(text)
            lines.extend(["import json", "", "DATA = json.loads(TEXT)", ""])
        except ValueError:
            pass
    return "\n".join(lines)
