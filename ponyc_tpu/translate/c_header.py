"""C header → ctypes FFI wrapper module.

≙ translate_c_header.c (1055 LoC): the fork parses a C header dropped in
a Pony package and emits a Pony class whose methods wrap the `@`-FFI
calls with the right parameter/return types. The Python twin parses
function prototypes, enums and #define constants and emits a module that
binds the functions on a ctypes.CDLL with argtypes/restype filled in —
the host side of FFI, exactly where the reference's output sits.

Deliberately the same scope as the reference: a pragmatic recursive
regex-less scanner for declaration-level C (prototypes, enums, numeric
defines, typedefs to primitives). Function pointers, macros with
arguments and nested structs are skipped with a comment, as the fork
skips what it can't translate.
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

# C type → (ctypes expression, needs_import) — pointer types handled
# separately.
_PRIM = {
    "void": None,
    "char": "ctypes.c_char",
    "signed char": "ctypes.c_byte",
    "unsigned char": "ctypes.c_ubyte",
    "short": "ctypes.c_short",
    "unsigned short": "ctypes.c_ushort",
    "int": "ctypes.c_int",
    "unsigned": "ctypes.c_uint",
    "unsigned int": "ctypes.c_uint",
    "long": "ctypes.c_long",
    "unsigned long": "ctypes.c_ulong",
    "long long": "ctypes.c_longlong",
    "unsigned long long": "ctypes.c_ulonglong",
    "float": "ctypes.c_float",
    "double": "ctypes.c_double",
    "size_t": "ctypes.c_size_t",
    "ssize_t": "ctypes.c_ssize_t",
    "int8_t": "ctypes.c_int8",
    "uint8_t": "ctypes.c_uint8",
    "int16_t": "ctypes.c_int16",
    "uint16_t": "ctypes.c_uint16",
    "int32_t": "ctypes.c_int32",
    "uint32_t": "ctypes.c_uint32",
    "int64_t": "ctypes.c_int64",
    "uint64_t": "ctypes.c_uint64",
    "bool": "ctypes.c_bool",
    "_Bool": "ctypes.c_bool",
    "intptr_t": "ctypes.c_ssize_t",
    "uintptr_t": "ctypes.c_size_t",
}


def _strip_comments(text: str) -> str:
    text = re.sub(r"/\*.*?\*/", " ", text, flags=re.S)
    return re.sub(r"//[^\n]*", " ", text)


def _ctype_of(decl: str, typedefs: Dict[str, str]) -> Optional[str]:
    d = " ".join(decl.replace("const", " ").replace("volatile", " ")
                 .replace("struct", " ").split())
    ptr = d.count("*")
    d = d.replace("*", " ").strip()
    d = typedefs.get(d, d)
    if ptr:
        base = _PRIM.get(d)
        if d in ("char",):
            return "ctypes.c_char_p" if ptr == 1 else "ctypes.c_void_p"
        if base is None or ptr > 1:
            return "ctypes.c_void_p"
        return f"ctypes.POINTER({base})"
    return _PRIM.get(d, "MISSING" if d else None)


_FUNC_RE = re.compile(
    r"(?:extern\s+)?([A-Za-z_][\w\s\*]*?)\s+\**\s*"
    r"([A-Za-z_]\w*)\s*\(([^()]*)\)\s*;", re.S)
_DEFINE_RE = re.compile(
    r"#define\s+([A-Za-z_]\w*)\s+"
    r"(-?(?:0[xX][0-9a-fA-F]+|\d+\.?\d*(?:[eE][+-]?\d+)?))\s*$",
    re.M)
_ENUM_RE = re.compile(
    r"enum\s*([A-Za-z_]\w*)?\s*\{([^}]*)\}", re.S)
_TYPEDEF_RE = re.compile(
    r"typedef\s+((?:unsigned\s+|signed\s+|long\s+|short\s+)*[A-Za-z_]\w*)"
    r"\s+([A-Za-z_]\w*)\s*;")


def parse_header(text: str):
    """Return (functions, constants, skipped). functions:
    [(name, ret_ctype|None, [(argname, ctype)])]."""
    text = _strip_comments(text)
    constants: List[Tuple[str, str]] = []
    for m in _DEFINE_RE.finditer(text):
        constants.append((m.group(1), m.group(2)))
    for m in _ENUM_RE.finditer(text):
        val = 0
        for item in m.group(2).split(","):
            item = item.strip()
            if not item:
                continue
            if "=" in item:
                k, v = (s.strip() for s in item.split("=", 1))
                try:
                    val = int(v, 0)
                except ValueError:
                    continue
            else:
                k = item
            constants.append((k, str(val)))
            val += 1
    typedefs: Dict[str, str] = {}
    for m in _TYPEDEF_RE.finditer(text):
        typedefs[m.group(2)] = m.group(1)

    functions = []
    skipped: List[str] = []
    body = re.sub(r"#[^\n]*", " ", text)          # drop remaining cpp
    for m in _FUNC_RE.finditer(body):
        rtype, name, argstr = m.group(1).strip(), m.group(2), m.group(3)
        if "(" in rtype or name in ("if", "while", "for", "return",
                                    "sizeof", "switch"):
            continue
        ret = _ctype_of(rtype, typedefs)
        if ret == "MISSING":
            skipped.append(f"{name}: unknown return type {rtype!r}")
            continue
        args: List[Tuple[str, str]] = []
        ok = True
        argstr = argstr.strip()
        if argstr not in ("", "void"):
            for i, a in enumerate(argstr.split(",")):
                a = a.strip()
                if a == "...":
                    ok = False
                    skipped.append(f"{name}: variadic")
                    break
                am = re.match(r"(.+?)([A-Za-z_]\w*)?\s*$", a)
                decl = am.group(1) if am else a
                aname = (am.group(2) if am and am.group(2) else f"a{i}")
                if am and am.group(2) and _ctype_of(
                        am.group(2), typedefs) not in (None, "MISSING"):
                    # trailing word was actually part of the type
                    decl, aname = a, f"a{i}"
                ct = _ctype_of(decl, typedefs)
                if ct in (None, "MISSING"):
                    ok = False
                    skipped.append(f"{name}: unsupported arg {a!r}")
                    break
                args.append((aname, ct))
        if ok:
            functions.append((name, ret, args))
    return functions, constants, skipped


def translate_c_header(text: str, *, name: str = "header.h") -> str:
    """Emit a Python module binding the header's functions over ctypes
    (≙ translate_c_header emitting the Pony wrapper class,
    translate_c_header.c:956)."""
    functions, constants, skipped = parse_header(text)
    lines = [
        f'"""FFI bindings generated from {name} by ponyc_tpu.translate.',
        "",
        "Call bind(path_or_cdll) once, then use the module-level wrappers.",
        '"""',
        "",
        "import ctypes",
        "",
        "_lib = None",
        "",
        "",
        "def bind(lib):",
        '    """Attach a ctypes.CDLL (or path) and type every function."""',
        "    global _lib",
        "    _lib = (lib if isinstance(lib, ctypes.CDLL)",
        "            else ctypes.CDLL(lib))",
    ]
    for fname, ret, args in functions:
        ats = ", ".join(ct for _, ct in args)
        lines.append(f"    _lib.{fname}.argtypes = [{ats}]")
        lines.append(f"    _lib.{fname}.restype = "
                     f"{ret if ret else 'None'}")
    lines.append("    return _lib")
    lines.append("")
    for cname, cval in constants:
        lines.append(f"{cname} = {cval}")
    if constants:
        lines.append("")
    for fname, ret, args in functions:
        argnames = ", ".join(a for a, _ in args)
        lines.append("")
        lines.append(f"def {fname}({argnames}):")
        lines.append(f"    return _lib.{fname}({argnames})")
    if skipped:
        lines.append("")
        lines.append("# skipped declarations (≙ the fork skipping what it")
        lines.append("# cannot translate):")
        for s in skipped:
            lines.append(f"#   {s}")
    return "\n".join(lines) + "\n"
