"""JSON Schema → typed Python classes (and actor field specs).

≙ translate_json_schema.c (1182 LoC): the fork turns `.schema.json`
files in a package into Pony classes with typed fields and JSON
(de)serialisation. The Python twin emits dataclasses with from_dict/
to_dict/from_json/to_json, nested object/array support, and — the
TPU-specific addition — an `ACTOR_FIELDS` table mapping flat int/number/
boolean properties to this framework's I32/F32/Bool field annotations so
a schema can seed a device actor type's state layout.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List


def _class_name(s: str) -> str:
    parts = [p for p in
             s.replace("-", " ").replace("_", " ").replace(".", " ").split()]
    return "".join(p.capitalize() for p in parts) or "Root"


def _py_type(prop: Dict[str, Any], name: str,
             classes: List[str]) -> str:
    t = prop.get("type")
    if t == "string":
        return "str"
    if t == "integer":
        return "int"
    if t == "number":
        return "float"
    if t == "boolean":
        return "bool"
    if t == "array":
        inner = _py_type(prop.get("items", {}), name + "Item", classes)
        return f"List[{inner}]"
    if t == "object" or "properties" in prop:
        cname = _class_name(prop.get("title", name))
        _emit_class(cname, prop, classes)
        return cname
    return "Any"


def _default_for(tp: str) -> str:
    return {"str": '""', "int": "0", "float": "0.0", "bool": "False"}.get(
        tp, "None" if not tp.startswith("List[") else
        "field(default_factory=list)")


def _emit_class(cname: str, schema: Dict[str, Any],
                classes: List[str]) -> None:
    props = schema.get("properties", {})
    required = set(schema.get("required", []))
    lines = ["@dataclass", f"class {cname}:"]
    doc = schema.get("description")
    if doc:
        lines.append(f'    """{doc}"""')
    field_lines = []
    conv_from = []
    conv_to = []
    actor_fields = []
    for pname, prop in props.items():
        tp = _py_type(prop, _class_name(pname), classes)
        dflt = "" if pname in required else f" = {_default_for(tp)}"
        field_lines.append(f"    {pname}: {tp}{dflt}")
        if tp in ("int", "bool", "float"):
            spec = {"int": "I32", "bool": "Bool", "float": "F32"}[tp]
            actor_fields.append(f'        "{pname}": {spec!r},')
        if tp in ("str", "int", "float", "bool", "Any"):
            conv_from.append(
                f'            {pname}=d.get("{pname}"'
                + (")" if pname in required
                   else f", {_default_for(tp)})"))
            conv_to.append(f'            "{pname}": self.{pname},')
        elif tp.startswith("List["):
            inner = tp[5:-1]
            if inner in ("str", "int", "float", "bool", "Any"):
                conv_from.append(
                    f'            {pname}=list(d.get("{pname}", [])),')
                conv_to.append(f'            "{pname}": '
                               f"list(self.{pname}),")
            else:
                conv_from.append(
                    f'            {pname}=[{inner}.from_dict(x) '
                    f'for x in d.get("{pname}", [])],')
                conv_to.append(f'            "{pname}": '
                               f"[x.to_dict() for x in self.{pname}],")
        else:
            conv_from.append(
                f'            {pname}={tp}.from_dict('
                f'd.get("{pname}", {{}})),')
            conv_to.append(f'            "{pname}": '
                           f"self.{pname}.to_dict(),")
    if not field_lines:
        field_lines.append("    pass")
    lines.extend(field_lines)
    # fix missing comma normalisation for required scalars
    conv_from = [c if c.endswith(",") else c + "," for c in conv_from]
    lines.append("")
    lines.append("    @classmethod")
    lines.append("    def from_dict(cls, d):")
    lines.append(f"        return cls(")
    lines.extend(conv_from)
    lines.append("        )")
    lines.append("")
    lines.append("    def to_dict(self):")
    lines.append("        return {")
    lines.extend(conv_to)
    lines.append("        }")
    lines.append("")
    lines.append("    @classmethod")
    lines.append("    def from_json(cls, text):")
    lines.append("        return cls.from_dict(json.loads(text))")
    lines.append("")
    lines.append("    def to_json(self):")
    lines.append("        return json.dumps(self.to_dict())")
    if actor_fields:
        lines.append("")
        lines.append("    # flat scalar fields usable as device-actor")
        lines.append("    # state specs (ponyc_tpu I32/F32/Bool):")
        lines.append("    ACTOR_FIELDS = {")
        lines.extend(actor_fields)
        lines.append("    }")
    classes.append("\n".join(lines))


def translate_json_schema(text: str, *, name: str = "x.schema.json") -> str:
    schema = json.loads(text)
    classes: List[str] = []
    root = _class_name(schema.get("title", name.split(".")[0]))
    _emit_class(root, schema, classes)
    header = [
        f'"""Classes generated from {name} by ponyc_tpu.translate."""',
        "",
        "import json",
        "from dataclasses import dataclass, field",
        "from typing import Any, List",
        "",
        "",
    ]
    return "\n".join(header) + "\n\n\n".join(classes) + "\n"
