"""Supervised auto-recovery: restart a crashed/stalled world from its
newest intact checkpoint.

≙ a Pony deployment's process supervisor (systemd/Erlang-style
restart-on-failure), made runtime-aware: the reference has nothing to
restore INTO — a restarted Pony binary starts cold. Here the world is a
single restorable pytree (serialise.py), so the supervisor closes the
loop ROADMAP item 5 names: a coded runtime error (errors.ERROR_CODES —
including the PR 7 watchdog's code-7 PonyStallError) or an unclean
process death (SIGKILL, OOM) is answered by restoring the newest intact
ring checkpoint (falling back past corrupt ones, serialise.newest_intact)
and resuming, with bounded retries and exponential backoff.

The poison rule: a failure that reproduces DETERMINISTICALLY — the same
error code at the same world position twice in a row, with no forward
progress between the attempts — must not be restart-looped (restoring
the same world and replaying the same poison message forever). The
supervisor raises the coded ``PoisonError`` instead, carrying both
failures as evidence.

Two modes share one class:

- **in-process** — ``Supervisor(build=make_rt, prefix=...)``:
  ``build()`` returns a STARTED runtime; the supervisor restores the
  newest intact checkpoint into it (or calls ``seed`` when starting
  cold), runs it, and on a coded failure builds a fresh runtime and
  tries again. The wedged/stalled old runtime is stopped best-effort
  and abandoned — recovery never depends on it.
- **subprocess** — ``Supervisor(argv=[...], prefix=...)`` (the
  ``python -m ponyc_tpu supervise <script>`` CLI): the child is
  restarted on any nonzero/killed exit with ``PONY_TPU_RESTORE``
  pointing at the newest intact checkpoint; the script opts in by
  calling ``supervise.maybe_restore(rt)`` after ``start()`` and
  seeding only when it returns None. Forward progress between
  attempts is measured by the checkpoint ring's newest sequence
  number (a child that advances the ring is not poisoned).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from . import serialise
from .errors import ERROR_CODES, error_code

RESTORE_ENV = "PONY_TPU_RESTORE"


class PoisonError(RuntimeError):
    """Deterministic poison: the same coded failure at the same world
    position twice in a row — restarting would loop forever, so the
    supervisor refuses. Carries the repeated failure record."""

    code = ERROR_CODES["PoisonError"]

    def __init__(self, message: str, failure: Optional[Dict] = None):
        super().__init__(message)
        self.failure = failure or {}


def maybe_restore(rt, prefix: Optional[str] = None) -> Optional[str]:
    """The supervised-script hook: restore from ``$PONY_TPU_RESTORE``
    (set by a supervising parent) or, with a `prefix`, from the newest
    intact ring checkpoint. Returns the restored path, or None (start
    cold and seed). Call right after ``start()``, BEFORE seeding."""
    path = os.environ.get(RESTORE_ENV) or ""
    if not path and prefix:
        path = serialise.newest_intact(prefix) or ""
    if not path:
        return None
    serialise.restore(rt, path)
    return path


class Supervisor:
    """Run a workload under restart-from-checkpoint supervision.

    Parameters
    ----------
    build: () -> Runtime — in-process mode; a STARTED runtime per
        attempt. The supervisor restores/seeds and calls ``run()``.
    argv: command list — subprocess mode (mutually exclusive with
        `build`); restarted with ``PONY_TPU_RESTORE`` exported.
    prefix: the checkpoint ring prefix recovery reads
        (``RuntimeOptions.checkpoint_path``).
    seed: (rt) -> None — called only when an attempt starts COLD
        (no intact checkpoint); the workload-injection site.
    retries: restart budget (total restarts, not attempts).
    backoff_s / backoff_max_s: exponential backoff between restarts.
    """

    def __init__(self, build: Optional[Callable[[], Any]] = None, *,
                 argv: Optional[Sequence[str]] = None,
                 prefix: str,
                 seed: Optional[Callable[[Any], None]] = None,
                 retries: int = 5,
                 backoff_s: float = 0.25,
                 backoff_max_s: float = 30.0,
                 sleep: Callable[[float], None] = time.sleep):
        if (build is None) == (argv is None):
            raise ValueError("exactly one of build= (in-process) or "
                             "argv= (subprocess) is required")
        self.build = build
        self.argv = list(argv) if argv is not None else None
        self.prefix = prefix
        self.seed = seed
        self.retries = int(retries)
        self.backoff_s = float(backoff_s)
        self.backoff_max_s = float(backoff_max_s)
        self._sleep = sleep
        self.failures: List[Dict[str, Any]] = []   # evidence trail
        self.restarts = 0
        self.restored_from: Optional[str] = None   # newest attempt's

    # -- shared policy --
    def _backoff(self, attempt: int) -> float:
        return min(self.backoff_max_s,
                   self.backoff_s * (2.0 ** max(0, attempt - 1)))

    def _record(self, **failure) -> Dict[str, Any]:
        failure["t"] = time.time()
        self.failures.append(failure)
        return failure

    def _poison_check(self) -> None:
        """Same code at the same position twice IN A ROW → poison."""
        if len(self.failures) < 2:
            return
        a, b = self.failures[-2], self.failures[-1]
        if (a.get("code"), a.get("position")) \
                == (b.get("code"), b.get("position")):
            raise PoisonError(
                f"deterministic poison: error code {b.get('code')} at "
                f"world position {b.get('position')!r} twice in a row "
                "— refusing to restart-loop (fix the workload or "
                "delete the poisoned checkpoint ring)", failure=b)

    def run(self) -> int:
        """Supervise to completion; returns the workload's exit code.
        Raises PoisonError on deterministic poison, or re-raises the
        last coded error once the retry budget is exhausted."""
        if self.build is not None:
            return self._run_inprocess()
        return self._run_subprocess()

    # -- in-process mode --
    def _run_inprocess(self) -> int:
        attempt = 0
        while True:
            rt = self.build()
            restored = None
            path = serialise.newest_intact(
                self.prefix, log=lambda m: print(
                    f"supervise: {m}", file=sys.stderr))
            if path is not None:
                try:
                    serialise.restore(rt, path)
                    restored = path
                except (serialise.SnapshotCorruptError,
                        serialise.FingerprintMismatch,
                        serialise.SnapshotGeometryError) as e:
                    print(f"supervise: restore of {path} failed ({e}); "
                          "starting cold", file=sys.stderr)
            self.restored_from = restored
            if restored is None and self.seed is not None:
                self.seed(rt)
            try:
                code = rt.run()
                rt.stop()
                return code
            except Exception as e:               # noqa: BLE001
                c = error_code(e)
                if c == 0:
                    raise          # not a coded runtime error: not ours
                self._record(code=c, cls=type(e).__name__,
                             position=int(getattr(rt, "steps_run", -1)),
                             message=str(e), restored=restored)
                try:
                    rt.stop()
                except Exception:                # noqa: BLE001
                    pass           # a wedged runtime may not tear down
                self._poison_check()
                attempt += 1
                if attempt > self.retries:
                    raise
                self.restarts += 1
                print(f"supervise: attempt {attempt}/{self.retries} — "
                      f"{type(e).__name__} (code {c}) at step "
                      f"{self.failures[-1]['position']}; restarting "
                      f"after {self._backoff(attempt):.2f}s",
                      file=sys.stderr)
                self._sleep(self._backoff(attempt))

    # -- subprocess mode --
    def _ring_seq(self) -> int:
        ckpts = serialise.list_checkpoints(self.prefix)
        return ckpts[-1][0] if ckpts else -1

    def _run_subprocess(self) -> int:
        attempt = 0
        while True:
            path = serialise.newest_intact(
                self.prefix, log=lambda m: print(
                    f"supervise: {m}", file=sys.stderr)) or ""
            env = dict(os.environ)
            if path:
                env[RESTORE_ENV] = path
            else:
                env.pop(RESTORE_ENV, None)
            self.restored_from = path or None
            p = subprocess.run(self.argv, env=env)
            if p.returncode == 0:
                return 0
            # Position for the poison rule: the ring's newest sequence
            # number — a child that wrote new checkpoints made forward
            # progress, so an identical exit code is NOT the same
            # failure (the fault moved).
            self._record(code=p.returncode, cls="subprocess",
                         position=self._ring_seq(), restored=path or None)
            self._poison_check()
            attempt += 1
            if attempt > self.retries:
                return p.returncode
            self.restarts += 1
            how = ("killed by signal " + str(-p.returncode)
                   if p.returncode < 0 else "coded exit")
            print(f"supervise: attempt {attempt}/{self.retries} — child "
                  f"exited {p.returncode} ({how}); restarting after "
                  f"{self._backoff(attempt):.2f}s from the newest "
                  "intact checkpoint", file=sys.stderr)
            self._sleep(self._backoff(attempt))
