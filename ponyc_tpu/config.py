"""Runtime options — TPU-native equivalent of the reference's runtime flag
system (reference: src/libponyrt/sched/start.c:75-94 parses --ponymaxthreads/
minthreads/noscale/suspendthreshold/cdinterval/gcinitial/gcfactor/noyield/
noblock/analysis/mainthread/pin/pinasio; src/libponyrt/options/options.c is
the shared getopt-ish parser).

On a TPU there are no scheduler *threads* to scale; the analogous knobs are
the static shapes of the device-resident actor world: mailbox capacity,
per-step drain batch, maximum sends per behaviour invocation, spill-buffer
capacity, and the cadence of host-side bookkeeping (quiescence checks ≙ the
CNF/ACK protocol interval, cycle-detection interval ≙ --ponycdinterval).

Flags are accepted both programmatically (RuntimeOptions(...)), from the
environment (PONY_TPU_<NAME>), and from argv (--pony<name> value), mirroring
how the reference strips --pony* flags from argv before the app sees them
(start.c:185-261).
"""

from __future__ import annotations

import dataclasses
import os
import sys
from typing import List, Optional, Union


@dataclasses.dataclass(frozen=True)
class RuntimeOptions:
    """Static configuration of a runtime instance (≙ opt_t in start.c).

    Everything here is a *trace-time constant*: changing any field re-traces
    the dispatch step (XLA programs have static shapes).
    """

    # --- mailbox / message geometry (≙ messageq.c + actor.c batch) ---
    mailbox_cap: int = 64          # per-actor ring capacity (power of two)
    msg_words: int = 6             # payload words per message (int32 lanes)
    batch: int = 8                 # default msgs drained per actor per step
    #   (reference default batch is 100 msgs per *scheduler run*
    #    (actor.c:20); a TPU "step" is much finer-grained, so the default
    #    is lower; per-type override via `BATCH` class attr ≙ the fork's
    #    lazily-initialised batch hint fn, actor.c:417-422.)
    max_sends: int = 2             # default max ctx.send() calls per behaviour

    # --- backpressure (≙ actor.c:1103-1235, scheduler.c:1478-1635) ---
    overload_threshold: float = 0.75   # occupancy fraction that marks an
    #   actor OVERLOADED (reference: failing to drain within one batch,
    #   actor.c:369-381; occupancy is the steady-state TPU analog)
    unmute_threshold: float = 0.25     # occupancy fraction under which a
    #   muting receiver releases its senders (hysteresis)
    spill_cap: int = 4096          # device overflow-spill entries (≙ the
    #   unbounded pool-backed queues of the reference; bounded here because
    #   XLA shapes are static — overflow beyond this raises)
    mute_age_limit: int = 32       # consecutive muted ticks before a
    #   sender is force-released (the lockstep deadlock-breaker for
    #   mutual-mute cycles/chains — see state.mute_age; short enough to
    #   bound stall time, long enough that ordinary backpressure mutes
    #   release via recovery, not aging). Aging only fires when every
    #   congested muter is itself muted/dead (a true deadlock); a live
    #   runnable muter with queue/spill evidence holds the mute.
    #   <= 0 disables aging entirely (exact reference mute semantics,
    #   which can deadlock on mutual-mute cycles — documented
    #   divergence opt-out).
    mute_slots: int = 4            # muting-receiver refs tracked per sender
    #   (≙ mutemap.c's receiver-set + actor.h mute counters: unmute only
    #   when *every* tracked muting receiver recovers; refs hash into
    #   ref%K slots, and a collision sets a sticky overflow bit that
    #   defers release until the whole shard is quiet — conservative,
    #   never an early unmute)

    # --- lifecycle / quiescence (≙ scheduler.c:303-480 CNF/ACK) ---
    quiesce_interval: Union[int, str] = "auto"  # max ticks fused into
    #   one device dispatch (engine.build_multi_step); the window
    #   self-terminates on host work / exit / fatal flags, so this
    #   bounds only how long the device may run *uninterrupted* — raise
    #   to amortise dispatch overhead, lower to tighten max_steps
    #   granularity. "auto" (default): the run loop sizes the window
    #   ADAPTIVELY (runtime/controller.py — the fork's adaptive
    #   scheduler sleeping): grow geometrically while windows run their
    #   full budget with zero host attention, shrink multiplicatively
    #   when host events cut windows short or the on-device queue-wait
    #   p99 climbs, bounded by quiesce_interval_min/max; the initial
    #   window resolves through the tuning cache (a previous run's
    #   converged value). An explicit int fixes the window (no
    #   adaptation) — the pre-adaptive behaviour.
    quiesce_interval_min: int = 4  # adaptive window lower bound (the
    #   shrink floor; also the smallest useful fused window — below
    #   this, per-dispatch overhead dominates any workload)
    quiesce_interval_max: int = 1024  # adaptive window upper bound:
    #   caps host-event reaction latency (an in-flight window cannot be
    #   interrupted) and max_steps overshoot granularity
    pipeline: bool = True          # pipelined host bridge: dispatch
    #   window k+1 behind in-flight window k (tick 0 gated ON DEVICE by
    #   window k's aux — engine.build_multi_step_gated) and start a
    #   non-blocking host copy of window k's control scalars at dispatch
    #   time, so outbox drain / host behaviours / the analysis writer
    #   overlap device compute instead of serialising against it. False
    #   restores the fully synchronous fetch-then-dispatch loop (the
    #   differential oracle: tests/test_run_loop.py proves the two agree
    #   message-for-message)
    cd_interval: int = 128         # steps between cycle-detector scans
    #   (≙ --ponycdinterval default 100ms, start.c:206)
    gc_initial: int = 1 << 14      # host-heap bytes allocated since the
    #   last collection that trigger one early (≙ --ponygcinitial
    #   2^14, start.c:204-209 — growth-triggered GC, heap.c:603-806)
    gc_factor: float = 2.0         # next-trigger growth multiplier over
    #   live bytes after a collection (≙ --ponygcfactor 2.0)
    noblock: bool = False          # ≙ --ponynoblock: disable cycle detection
    gc_max_iters: int = 0          # reachability-trace hop cap (0 = run to
    #   fixpoint); if hit, that GC round collects nothing (safe)
    noyield: bool = False          # ≙ --ponynoyield: ignore yield hints
    max_steps: Optional[int] = None  # safety valve for tests

    # --- host bridge (≙ asio/) ---
    inject_slots: int = 256        # host→device injected msgs per step
    host_out_slots: int = 256      # device→host delivered msgs per step
    pin: int = -1                  # ≙ --ponypin: pin the host driver
    #   thread to this core (-1 = unpinned); the TPU analog of pinning
    #   scheduler threads — keeps the dispatch loop off noisy cores
    pin_asio: int = -1             # ≙ --ponypinasio: pin the native
    #   event-loop thread to this core (-1 = unpinned)

    # --- analysis / telemetry (≙ --ponyanalysis, analysis.c) ---
    analysis: int = 0              # 0 off, 1 summary, 2 window CSV,
    #   3 = 2 + per-EVENT rows (mute/unmute/overload/spawn/destroy/error
    #   transitions recorded on device in a bounded ring, drained to
    #   <analysis_path>.events.csv at window boundaries — ≙ the fork's
    #   per-event rows, analysis.c:587-692; costs one compaction per
    #   busy tick while enabled)
    analysis_path: str = "/tmp/pony_tpu.analytics.csv"
    analysis_events: int = 4096    # device event-ring entries per shard
    #   (level 3); overflow between two drains drops and counts
    analysis_flush_ms: int = 200   # writer-thread flush cadence: rows
    #   batch and flush when the queue drains or this many ms pass,
    #   whichever first (flush-per-row serialised the writer under
    #   level-3 event bursts); 0 = flush after every batch
    # --- causal message tracing (PROFILE.md §10; ≙ the fork's per-event
    # analysis following one message send→dispatch, analysis.c:587-692 —
    # here a sampled TRACE CONTEXT rides every message: mailbox ring
    # slots gain (trace_id, parent_span) side lanes, dispatch records a
    # span per traced message in a bounded device ring, and every send/
    # spawn the behaviour performs inherits the context. Active only
    # when BOTH analysis >= 3 and trace_sample > 0; otherwise every
    # trace lane is zero-length and the step jaxpr is bit-identical to
    # a tracer-free build (tests/test_tracing.py asserts it). ---
    trace_sample: int = 0          # 0 = off; N >= 1 samples one in N
    #   host injections (send()); 1 traces every injection. Sampling is
    #   deterministic under trace_seed (a counter hash, not wall clock),
    #   so identical runs trace identical messages. Explicit ids via
    #   send(..., trace=...) are always traced regardless of N.
    trace_slots: int = 4096        # device span-ring entries per shard;
    #   overflow between two drains drops spans and counts them
    #   (state.span_dropped) — raise for deep fan-outs
    trace_seed: int = 0            # sampling-hash seed (determinism knob)
    pallas: Union[bool, str] = False   # route the dispatch mailbox drain
    #   through the Pallas kernel (ops/mailbox_kernel.py) instead of the
    #   XLA select-chain; interpret-mode on CPU. "auto" adds the kernel
    #   as a calibrated variant (tuning.py) where the program's cohorts
    #   are block-aligned; the measured winner is used.
    pallas_fused: Union[bool, str] = False  # fuse drain + behaviour +
    #   outbox into ONE Pallas kernel per eligible cohort
    #   (ops/fused_dispatch.py: no sync-construction/blob pool; others
    #   fall back to the XLA path). The north-star dispatch kernel;
    #   "auto" = calibrate it against the XLA path at start() and keep
    #   the winner (tuning.py).
    host_fastpath: bool = True     # host-sender → host-target messages
    #   bypass the device mailbox table: they queue host-side and
    #   dispatch at host boundaries (≙ the main-thread scheduler's
    #   inject_main lane, scheduler.c:47,179-190 — main-thread actors
    #   message each other without crossing schedulers). Per-sender-pair
    #   FIFO is preserved (a host sender's messages to a host receiver
    #   ALL take this lane; device senders all take the device lane);
    #   lifts the host-plane ceiling ~the device-window cost per hop
    #   (benchmarks.md "host-bridge ceiling"). False restores the
    #   everything-through-the-device-table path.
    host_fastpath_budget: int = 100_000  # max fast-lane dispatches per
    #   host boundary; leftovers keep the loop busy (starvation guard so
    #   a host ping-pong cannot lock out device progress)
    dispatch_gating: bool = False  # skip a behaviour's planar evaluation
    #   under a scalar lax.cond when no lane's current batch slot selects
    #   it (engine scan_body). Semantics-identical (behaviours are
    #   lane-local by contract); pays one any-reduction + branch per
    #   (slot, behaviour) to avoid evaluating cold behaviours — the
    #   countermeasure to the planar-dispatch heterogeneity cliff
    #   (profiling/_hetero.py measures; the reference's switch is O(1),
    #   genfun.c). Off by default until measured on the real chip.
    delivery: str = "plan"         # delivery formulation (delivery.py):
    #   "plan"   — cached stable-sort plan + permutation gathers (skips
    #              the sort when traffic shape repeats);
    #   "cosort" — one stable multi-operand lax.sort per tick that moves
    #              the payload with the key (no plan, no gathers; wins
    #              where arbitrary lane gathers lower poorly);
    #   "pallas_mega" — the persistent fused window megakernel
    #              (ops/megakernel.py, PROFILE.md §14): the WHOLE gated
    #              window — delivery gather, mailbox drain, dispatch,
    #              profiler lanes — runs as one Pallas kernel with the
    #              in-window while as a kernel-internal loop, and ring
    #              records cross the kernel boundary packed into int16
    #              lanes + an int32 escape plane (the mailbox bandwidth
    #              diet). Plan-formulation delivery semantics,
    #              bit-equivalent by construction; ineligible programs
    #              (mesh shards > 1, pallas/pallas_fused forced on)
    #              fall back to the XLA spelling.
    #   "auto"   — calibrate the formulations at Runtime.start() by
    #              timing a short in-executable fused window per
    #              formulation on the program's real cohort shapes and
    #              keep the faster one (tuning.py; the decision
    #              persists in the tuning cache so steady-state starts
    #              skip calibration; pallas_mega joins the candidates
    #              on TPU, or under PONY_TPU_MEGA_AUTO=1 elsewhere).
    debug_checks: bool = False     # run Runtime.check_invariants() at
    #   every aux fetch (≙ the reference's debug-build queue checkers,
    #   actor.c:57-92; costly — test/debug only)

    # --- operational observability (flight recorder / stall watchdog /
    # metrics export — PROFILE.md §11; ≙ the fork's always-on
    # runtime-analysis posture). All three are HOST-side: none feeds the
    # traced step, so with metrics_port=None and analysis=0 the step
    # jaxpr is bit-identical to a build without them (tests assert). ---
    flight_windows: int = 64       # flight-recorder ring: how many
    #   retired-window records (control scalars the run loop already
    #   fetched, controller decisions, GC stats, recent host mail) the
    #   always-on black box retains for the crash/SIGQUIT/watchdog
    #   postmortem (Runtime.stop(postmortem=True) dumps it on demand)
    watchdog_s: Optional[float] = None  # stall-watchdog deadline in
    #   seconds (None = off): a monitor thread trips when a run-loop
    #   phase (backend init, a dispatched window, host work) makes no
    #   progress stamp for this long — scaled up by the adaptive
    #   controller's current window / initial window ratio so a
    #   legitimately grown window is not misread as a stall. A trip
    #   writes the flight-recorder postmortem and converts the silent
    #   hang into an int-coded errors.PonyStallError
    metrics_port: Optional[int] = None  # serve Prometheus text at
    #   /metrics and a JSON health verdict at /healthz on
    #   127.0.0.1:<port> via a stdlib-only HTTP thread (None = off,
    #   0 = ephemeral port — read it back from rt._metrics.port).
    #   Scrapes never touch the device: they render the snapshot the
    #   run loop last pushed at a window boundary (the same
    #   non-blocking posture as the analysis writer)
    cost_capture: bool = False     # measured device-cost capture
    #   (costs.py, ISSUE 19): at start(), AOT-compile the runtime's
    #   real step/window executables and record their
    #   cost_analysis()/memory_analysis() (bytes accessed, flops, peak
    #   HBM) next to the modelled bytes/msg — one extra compile per
    #   executable at start (the XLA disk cache absorbs the repeat).
    #   HOST-side: the traced step never sees it, so the step jaxpr is
    #   bit-identical with capture on or off. Off, the same capture is
    #   available on demand via Runtime.measured_costs()

    # --- durable worlds (serialise.py Checkpointer + supervise.py;
    # ≙ nothing in the reference — Pony has no built-in checkpoint/
    # restore (SURVEY.md §5); the TPU runtime's single-pytree world
    # makes one cheap. All three knobs are HOST-side: the traced step
    # never sees them, so the step jaxpr is bit-identical with
    # checkpointing on or off (tests/test_durability.py asserts). ---
    checkpoint_every_s: Optional[float] = None  # periodic crash-safe
    #   checkpoint cadence in seconds (None = off): the run loop
    #   snapshots the whole world at the next quiescent window boundary
    #   once this much time has passed — capture (device→host copy,
    #   started async) runs on the run-loop thread; compression,
    #   checksumming and the fsync+atomic-rename write ride a
    #   background writer thread behind the next in-flight window
    #   (Runtime.checkpoint_stats() records both costs, PROFILE.md §12)
    checkpoint_path: str = ""      # checkpoint ring file PREFIX; files
    #   land as <prefix>-<seq>.ckpt with the newest `checkpoint_keep`
    #   retained. "" = derive <analysis_path>.ckpt
    checkpoint_keep: int = 3       # how many ring snapshots to retain
    #   (the supervisor falls back past corrupt ones, so > 1 is the
    #   crash-safety margin; old files beyond K are deleted)

    # --- autotuning / caches (tuning.py; ≙ nothing in the reference —
    # its dispatch is one fixed O(1) switch, genfun.c; ours has
    # formulation choices whose winner is hardware- and shape-dependent,
    # so the runtime measures instead of a human with a scratch script:
    # PROFILE.md §6) ---
    tuning_cache: str = "auto"     # on-disk decision cache for "auto"
    #   option values, keyed by (platform, jax version, cohort layout,
    #   geometry). "auto" = $PONY_TPU_TUNING_CACHE or
    #   ~/.cache/ponyc_tpu/tuning; "off" disables (recalibrate every
    #   start); any other value = explicit directory.
    compile_cache: str = "auto"    # jax persistent compilation cache
    #   (attacks the measured 11.8 s warmup, PROFILE.md §4b). Same
    #   spelling: "auto" = $PONY_TPU_COMPILE_CACHE or
    #   ~/.cache/ponyc_tpu/xla; "off" leaves jax.config untouched.
    tuning_ticks: int = 0          # in-executable ticks per calibration
    #   window (lax.fori_loop trip count — the only methodology
    #   PROFILE.md §4b trusts; per-call timings carry an ~11 ms launch
    #   floor). 0 = auto-size from the synthetic workload's sustain.
    tuning_repeats: int = 3        # timed windows per variant (the
    #   median is kept; the first, compile-bearing window never counts)

    # --- device blob pool (≙ rich message payloads: pony_alloc_msg +
    # actor-heap objects riding messages, pony.h:332-360 / genfun.c.
    # Messages carry a blob HANDLE (i32, mode iso — moved-unique); the
    # words live device-resident in a [blob_words, shards*blob_slots]
    # pool, so payloads larger than msg_words never round-trip the
    # host. 0 = disabled (all blob plumbing compiles away). ---
    blob_slots: int = 0            # pool slots PER SHARD; handles carry
    #   (generation, global slot id) — ops/pack.py encoding. On a mesh a
    #   blob MIGRATES with its routed message (engine._route); host
    #   injections bypass routing, so host payloads should allocate on
    #   the receiver's shard (Runtime.blob_store(near=...)) — an
    #   undereferenceable arrival reads null and counts in
    #   rt.counter("n_blob_remote")
    blob_words: int = 0            # i32 words per blob slot (the pool's
    #   uniform width; ctx.blob_alloc records each blob's logical length)

    # --- sharding (≙ the scale axis the reference lacks; SURVEY §2.4) ---
    mesh_shards: int = 1           # actor-axis shards (1 = single chip)
    route_bucket: int = 0          # per-destination all_to_all bucket
    #   entries. 0 = auto-size (state.layout_sizes): covers the worst
    #   case one-shard emission up to 4 shards; beyond that (or with an
    #   explicit smaller value) a saturated link parks messages in the
    #   route spill and mutes senders — backpressure, not loss

    def __post_init__(self):
        if self.mailbox_cap & (self.mailbox_cap - 1):
            raise ValueError("mailbox_cap must be a power of two")
        if self.msg_words < 1:
            raise ValueError("msg_words must be >= 1")
        if self.batch < 1:
            raise ValueError("batch must be >= 1")
        if self.delivery not in ("plan", "cosort", "pallas_mega",
                                 "auto"):
            raise ValueError("delivery must be 'plan', 'cosort', "
                             "'pallas_mega' or 'auto'")
        if isinstance(self.quiesce_interval, str):
            if self.quiesce_interval != "auto":
                raise ValueError(
                    "quiesce_interval must be a positive int or 'auto'")
        elif self.quiesce_interval < 1:
            raise ValueError("quiesce_interval must be >= 1")
        if self.quiesce_interval_min < 1 \
                or self.quiesce_interval_max < self.quiesce_interval_min:
            raise ValueError(
                "need 1 <= quiesce_interval_min <= quiesce_interval_max")
        for name in ("pallas", "pallas_fused"):
            v = getattr(self, name)
            if not (v is True or v is False or v == "auto"):
                raise ValueError(f"{name} must be True, False or 'auto'")
        if self.tuning_repeats < 1:
            raise ValueError("tuning_repeats must be >= 1")
        if self.tuning_ticks < 0:
            raise ValueError("tuning_ticks must be >= 0 (0 = auto)")
        if self.analysis_flush_ms < 0:
            raise ValueError("analysis_flush_ms must be >= 0")
        if self.trace_sample < 0:
            raise ValueError(
                "trace_sample must be >= 0 (0 = off, N = 1-in-N)")
        if self.trace_slots < 1:
            raise ValueError("trace_slots must be >= 1")
        if self.flight_windows < 1:
            raise ValueError("flight_windows must be >= 1")
        if self.watchdog_s is not None and not self.watchdog_s > 0:
            raise ValueError("watchdog_s must be > 0 seconds (None = off)")
        if self.metrics_port is not None \
                and not 0 <= self.metrics_port < 65536:
            raise ValueError(
                "metrics_port must be in [0, 65535] (0 = ephemeral, "
                "None = off)")
        if self.checkpoint_every_s is not None \
                and not self.checkpoint_every_s > 0:
            raise ValueError(
                "checkpoint_every_s must be > 0 seconds (None = off)")
        if self.checkpoint_keep < 1:
            raise ValueError("checkpoint_keep must be >= 1")
        if self.blob_slots < 0 or self.blob_words < 0:
            raise ValueError("blob_slots/blob_words must be >= 0")
        if (self.blob_slots > 0) != (self.blob_words > 0):
            raise ValueError(
                "blob_slots and blob_words enable the blob pool together "
                "(both > 0) or not at all (both 0)")
        if self.blob_slots * max(1, self.mesh_shards) >= 1 << 20:
            raise ValueError(
                "shards x blob_slots must stay below 2^20 (handle "
                "encoding reserves the high bits for the slot "
                "generation; ops/pack.py BLOB_GEN_SHIFT)")

    @property
    def tracing(self) -> bool:
        """Causal tracing active: both the analysis level and the
        sampling knob must opt in (PROFILE.md §10)."""
        return self.analysis >= 3 and self.trace_sample > 0

    @property
    def trace_lanes(self) -> int:
        """Extra word rows every in-flight message carries when tracing
        is on: (trace_id, parent_span). 0 when off — inject buffers,
        spill tables and outbox entries keep the tracer-free width."""
        return 2 if self.tracing else 0

    @property
    def overload_occ(self) -> int:
        return max(1, int(self.mailbox_cap * self.overload_threshold))

    @property
    def unmute_occ(self) -> int:
        return max(0, int(self.mailbox_cap * self.unmute_threshold))


_FLAG_TYPES = {f.name: f.type for f in dataclasses.fields(RuntimeOptions)}

# bool-or-"auto" tri-state flags: bare flag spells True, "auto" survives
# coercion (everything else parses like a bool).
_TRISTATE = ("pallas", "pallas_fused")

# int-or-"auto" flags ("auto" survives coercion, anything else is int).
_INT_OR_AUTO = ("quiesce_interval",)


def _is_boolish(name: str) -> bool:
    return name in _TRISTATE or _FLAG_TYPES[name] in ("bool", bool)


def _coerce(name: str, raw: str):
    ty = _FLAG_TYPES[name]
    if name in _TRISTATE:
        return "auto" if raw.lower() == "auto" else (
            raw.lower() in ("1", "true", "yes", "on", ""))
    if name in _INT_OR_AUTO:
        return "auto" if raw.lower() == "auto" else int(raw)
    if ty in ("bool", bool):
        return raw.lower() in ("1", "true", "yes", "on", "")
    if ty in ("int", int, "Optional[int]", Optional[int]):
        return int(raw)
    if ty in ("float", float, "Optional[float]", Optional[float]):
        return float(raw)
    return raw


def options_from_env(base: Optional[RuntimeOptions] = None) -> RuntimeOptions:
    """Read PONY_TPU_* environment overrides (≙ start.c env handling)."""
    base = base or RuntimeOptions()
    overrides = {}
    for name in _FLAG_TYPES:
        raw = os.environ.get("PONY_TPU_" + name.upper())
        if raw is not None:
            overrides[name] = _coerce(name, raw)
    return dataclasses.replace(base, **overrides)


def strip_runtime_flags(argv: Optional[List[str]] = None,
                        base: Optional[RuntimeOptions] = None):
    """Parse and remove --pony* flags from argv, returning (opts, rest).

    ≙ pony_init's argv filtering (start.c:185-261): the application never
    sees runtime flags. Accepted spellings: --pony_mailbox_cap 64,
    --ponymailboxcap=64 (underscores optional).
    """
    argv = list(sys.argv if argv is None else argv)
    canon = {name.replace("_", ""): name for name in _FLAG_TYPES}
    rest, overrides, i = [], {}, 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--pony"):
            body = a[6:].lstrip("_")
            if "=" in body:
                key, raw = body.split("=", 1)
            else:
                key, raw = body, None
            key = key.replace("_", "")
            if key in canon:
                name = canon[key]
                if raw is None:
                    if _is_boolish(name):
                        raw = "true"
                    else:
                        i += 1
                        if i >= len(argv):
                            raise ValueError(f"missing value for flag {a}")
                        raw = argv[i]
                overrides[name] = _coerce(name, raw)
                i += 1
                continue
        rest.append(a)
        i += 1
    base = options_from_env(base)
    return dataclasses.replace(base, **overrides), rest


def auto_fields(opts: RuntimeOptions) -> List[str]:
    """Option fields whose value is the "auto" sentinel — the set the
    tuner (tuning.py) must resolve to concrete values before the engine
    traces (the engine only ever sees concrete formulations)."""
    return [n for n in ("delivery", "pallas", "pallas_fused")
            if getattr(opts, n) == "auto"]
