"""Delivery/dispatch autotuner: measured machinery replacing the manual
A/B campaign (PROFILE.md §4c–4e → §6).

The engine has formulation choices with no shape- or hardware-independent
winner: delivery as a cached stable-sort plan + permutation gathers
("plan") vs one multi-operand co-sort ("cosort"); the mailbox drain as an
XLA select-chain vs a Pallas kernel (`pallas`); dispatch as planar XLA vs
the fused Pallas kernel (`pallas_fused`). CAF's OpenCL actor backend
reached the same conclusion for behaviour offload (Wahlster et al.,
arXiv:1709.07781 — the runtime must pick the execution configuration
per workload), as did Halide's schedule search (arXiv:2105.12858): the
choice is a measurement, not a design constant.

So ``RuntimeOptions(delivery="auto")`` (and ``pallas="auto"`` /
``pallas_fused="auto"``) defers the choice to ``Runtime.start()``:

1. enumerate the eligible concrete variants (`variants`);
2. time each on a synthetic busy workload built from the program's REAL
   cohort shapes (`make_workload`) with a `lax.fori_loop` window over
   the real step (`engine.build_forced_window`) — in-executable ticks
   divided by trip count, the only methodology PROFILE.md §4b trusts
   (per-call timings carry an ~11 ms launch floor through the tunnel);
3. pick the minimum (`decide`) and record the full table;
4. persist the decision in an on-disk cache keyed by (platform, jax
   version, cohort layout, geometry) so steady-state starts skip
   calibration entirely (`load_cached`/`store_cached`).

Semantics are untouched by construction: calibration runs on throwaway
copies of the state, and the only thing "auto" changes is which already-
equivalence-tested formulation executes (tests/test_differential.py and
tests/test_delivery_modes.py are the oracle that they agree).

The synthetic workload seeds every device mailbox full of the cohort's
first behaviour and parks a full receiver-spill aimed at one victim
actor, so both the dispatch path (planar evaluation of every behaviour)
and the delivery path (full-width sort + rebuild, with real accepted
messages every tick) stay busy for the whole window. The measured regime
re-sorts every tick (spill contents shift), i.e. it prices "plan" at its
cache-MISS cost — conservative for plan, exact for cosort; the recorded
table says so.

Also here: `enable_compile_cache` wires jax's persistent compilation
cache (the 11.8 s measured warmup, PROFILE.md §4b) for Runtime/bench.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import statistics
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from .config import RuntimeOptions, auto_fields

# Option fields a variant may override — the tuner must never touch a
# field that changes Program layout or state shapes (the calibration
# template and the runtime's real jitted step share both).
VARIANT_FIELDS = ("delivery", "pallas", "pallas_fused")


# ---------------------------------------------------------------------------
# cache locations


def _cache_dir(setting: str, env: str, leaf: str) -> Optional[str]:
    """Resolve a cache-dir option ("auto"/"off"/path) against its env
    override. Returns None when disabled."""
    if setting == "off":
        return None
    if setting in ("", "auto"):
        setting = os.environ.get(env, "")
        if setting.lower() in ("off", "0"):
            return None
        if not setting:
            setting = os.path.join(os.path.expanduser("~"), ".cache",
                                   "ponyc_tpu", leaf)
    return setting


def tuning_cache_dir(opts: RuntimeOptions) -> Optional[str]:
    return _cache_dir(opts.tuning_cache, "PONY_TPU_TUNING_CACHE", "tuning")


def compile_cache_dir(opts: RuntimeOptions) -> Optional[str]:
    return _cache_dir(opts.compile_cache, "PONY_TPU_COMPILE_CACHE", "xla")


_compile_cache_on: Optional[str] = None


def enable_compile_cache(setting: str = "auto") -> Optional[str]:
    """Point jax's persistent compilation cache at a directory (default
    ~/.cache/ponyc_tpu/xla, $PONY_TPU_COMPILE_CACHE overrides, "off"
    disables). Returns the directory in use, or None. Idempotent;
    best-effort — an older jax without the knobs leaves config
    untouched rather than failing the start.

    CPU guard: on the CPU backend this jaxlib's cache round-trip is
    UNSOUND for the engine's donated while-loop executables — reloaded
    executables corrupt runtime state (observed on jaxlib 0.4.37:
    tests/test_host_api_fuzz.py invariant violations and fatal aborts
    the moment a cached step/gc executable is reused, at default cache
    thresholds too). The warmup this cache attacks (11.8 s, PROFILE.md
    §4b) lives on the accelerator anyway, so CPU keeps the cache off
    unless PONY_TPU_COMPILE_CACHE_FORCE=1 (for re-testing the bug on
    newer jaxlibs)."""
    global _compile_cache_on
    path = _cache_dir(setting, "PONY_TPU_COMPILE_CACHE", "xla")
    if path is None:
        return None
    import jax
    try:
        platform = jax.devices()[0].platform
    except Exception:                 # noqa: BLE001 — no backend at all
        return None
    if platform == "cpu" and os.environ.get(
            "PONY_TPU_COMPILE_CACHE_FORCE", "0") != "1":
        return None
    if _compile_cache_on == path:
        return path
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # Warmup is THE metric here (11.8 s measured, PROFILE.md §4b):
        # cache every executable, not just slow-to-compile ones.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except (AttributeError, ValueError, OSError):
        return None
    _compile_cache_on = path
    return path


# ---------------------------------------------------------------------------
# variant enumeration


def pallas_cohort_ok(rows: int) -> bool:
    """The drain/fused kernels' block-alignment precondition
    (ops.mailbox_kernel / ops.fused_dispatch LANE_BLOCK)."""
    from .ops import mailbox_kernel as mk
    return rows <= mk.LANE_BLOCK or rows % mk.LANE_BLOCK == 0


def pallas_eligible(program) -> bool:
    """Some device cohort would actually route its drain through the
    Pallas kernel (engine falls back silently otherwise — a variant
    that falls back everywhere is the baseline wearing a costume)."""
    return any(ch.behaviours and pallas_cohort_ok(ch.local_capacity)
               for ch in program.device_cohorts)


def fused_eligible(program, opts: RuntimeOptions) -> bool:
    """Some device cohort satisfies the fused kernel's structural
    preconditions (ops.fused_dispatch.eligible: behaviours present, no
    blob pool, block-aligned rows, no synchronous construction —
    discovered via the verify pass's probe tracing, the same facts the
    engine's own probe finds)."""
    from . import verify
    for ch in program.device_cohorts:
        if not ch.behaviours:
            continue
        if opts.blob_slots > 0 and ch.uses_blobs:
            continue
        if not pallas_cohort_ok(ch.local_capacity):
            continue
        if any(verify.behaviour_effects(
                b, ch.atype, msg_words=opts.msg_words,
                default_max_sends=opts.max_sends).sync_spawns
               for b in ch.behaviours):
            continue
        return True
    return False


def mega_eligible(program, opts: RuntimeOptions) -> bool:
    """Whether delivery="auto" should time the window megakernel
    (ops/megakernel.py): structurally eligible AND worth measuring on
    this backend (on CPU the kernel only runs in interpret mode — a
    correctness vehicle, never a perf winner — so auto skips it there
    unless PONY_TPU_MEGA_AUTO=1; bench.py sets that so every BENCH
    json's A/B table carries the variant)."""
    from .ops import megakernel
    return megakernel.auto_enumerable(program, opts)


def variants(program, opts: RuntimeOptions) -> List[Tuple[str, Dict]]:
    """Ordered (name, overrides) candidates for the opts' "auto" fields.
    The first entry is the baseline (plan / kernels off); `decide`
    breaks ties toward earlier entries, so noise can never flip a dead
    heat away from the safe default."""
    deliveries = (["plan", "cosort"]
                  + (["pallas_mega"] if mega_eligible(program, opts)
                     else [])
                  if opts.delivery == "auto" else [opts.delivery])
    pallas_vals = ([False, True]
                   if opts.pallas == "auto" and pallas_eligible(program)
                   else [False if opts.pallas == "auto" else opts.pallas])
    fused_vals = ([False, True]
                  if (opts.pallas_fused == "auto"
                      and fused_eligible(program, opts))
                  else [False if opts.pallas_fused == "auto"
                        else opts.pallas_fused])
    out: List[Tuple[str, Dict]] = []
    for f in fused_vals:
        for p in pallas_vals:
            for d in deliveries:
                if opts.delivery == "auto" and d == "pallas_mega" \
                        and (p or f):
                    # The megakernel IS the fused form of both nested
                    # kernels — combining them would nest pallas_calls,
                    # so auto never enumerates the combination. (A
                    # FIXED delivery="pallas_mega" with a kernel forced
                    # on stays listed: megakernel.eligible rejects it
                    # and the engine falls back to the XLA spelling.)
                    continue
                name = d + ("+pallas" if p else "") + ("+fused" if f else "")
                out.append((name, {"delivery": d, "pallas": p,
                                   "pallas_fused": f}))
    return out


def decide(table: Dict[str, Optional[float]],
           order: Optional[List[str]] = None) -> Optional[str]:
    """The winning variant: minimum tick_ms, exact ties broken toward
    the earlier entry in `order` (insertion order by default — the
    baseline). Entries with None (variant failed to build/run) never
    win. Deterministic given the table — the property the tests pin."""
    order = list(table.keys()) if order is None else order
    best = None
    for name in order:
        t = table.get(name)
        if t is None:
            continue
        if best is None or t < table[best]:
            best = name
    return best


# ---------------------------------------------------------------------------
# the decision-table key


def tuning_key(program, opts: RuntimeOptions) -> Dict[str, Any]:
    """Everything the decision legitimately depends on — backend,
    compiler version, cohort layout, geometry — and nothing it doesn't
    (actor field VALUES don't change op shapes). Same key ⇒ the cached
    winner transfers."""
    import jax
    dev = jax.devices()[0]
    cohorts = [
        {"type": ch.atype.__name__, "capacity": int(ch.capacity),
         "batch": int(ch.batch), "max_sends": int(ch.max_sends),
         "msg_words": int(ch.msg_words),
         "behaviours": len(ch.behaviours),
         "host": bool(ch.host), "blobs": bool(ch.uses_blobs)}
        for ch in program.cohorts]
    geometry = {f: getattr(opts, f) for f in (
        "mailbox_cap", "msg_words", "batch", "max_sends", "spill_cap",
        "inject_slots", "mesh_shards", "route_bucket", "mute_slots",
        "dispatch_gating", "blob_slots", "blob_words")}
    return {
        # v2: delivery="pallas_mega" joined the variant space (the
        # window megakernel, ops/megakernel.py) — v1 records predate it
        # and must recalibrate rather than transfer a two-way decision
        # into a three-way race.
        "v": 2,
        "platform": dev.platform,
        "device_kind": getattr(dev, "device_kind", dev.platform),
        "jax": jax.__version__,
        "auto": sorted(auto_fields(opts)),
        "fixed": {f: getattr(opts, f) for f in VARIANT_FIELDS
                  if getattr(opts, f) != "auto"},
        "geometry": geometry,
        "cohorts": cohorts,
    }


def cache_path(cache_dir: str, key: Dict[str, Any]) -> str:
    blob = json.dumps(key, sort_keys=True).encode()
    return os.path.join(cache_dir,
                        hashlib.sha256(blob).hexdigest()[:24] + ".json")


def load_cached(cache_dir: Optional[str],
                key: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """The cached record for `key`, or None on miss/corruption (a
    corrupt file recalibrates — and is then overwritten — rather than
    erroring a start)."""
    if cache_dir is None:
        return None
    path = cache_path(cache_dir, key)
    try:
        with open(path) as f:
            rec = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(rec, dict) or rec.get("key") != key \
            or not isinstance(rec.get("chosen"), dict):
        return None
    return rec


def store_cached(cache_dir: Optional[str], key: Dict[str, Any],
                 record: Dict[str, Any]) -> Optional[str]:
    """Best-effort persist (atomic rename; an unwritable cache dir never
    fails the start). Returns the path written, or None."""
    if cache_dir is None:
        return None
    path = cache_path(cache_dir, key)
    try:
        os.makedirs(cache_dir, exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
    except OSError:
        return None
    return path


# ---------------------------------------------------------------------------
# the synthetic calibration workload


def make_workload(program, opts: RuntimeOptions, state):
    """A throwaway busy state on the program's REAL cohort shapes.

    Built from the fresh post-start() state (all-zero mailboxes) by
    sharding-preserving array ops:

    - every device-cohort actor is alive with a FULL mailbox of its
      cohort's first behaviour (zero args) — the dispatch path runs its
      full planar cost while those drain (`ceil(cap/batch)` ticks), and
      the outbox keeps delivery's sort at full static width every tick;
    - the receiver spill is parked full, aimed at one victim actor
      (the first device cohort's row 0) — each tick the victim drains
      `batch` and delivery re-accepts `batch` spill entries, so REAL
      accepted messages flow through the sort/rebuild/pressure paths
      for ~spill_cap/batch sustained ticks, far past any window length
      the tuner uses.

    Values are garbage by design; the state is never installed — "auto"
    may change speed only, never semantics.
    """
    import jax.numpy as jnp

    cap = opts.mailbox_cap
    p = program.shards
    nl = program.n_local
    victim = None
    mask_local = np.zeros((nl,), bool)
    for ch in program.device_cohorts:
        mask_local[ch.local_start:ch.local_stop] = True
        if victim is None and ch.behaviours:
            victim = ch
    if not mask_local.any():
        return None, 0
    mask = jnp.asarray(np.tile(mask_local, p))

    new_buf = dict(state.buf)
    for ch in program.device_cohorts:
        gid0 = ch.behaviours[0].global_id if ch.behaviours else -7
        new_buf[ch.atype.__name__] = \
            state.buf[ch.atype.__name__].at[:, 0, :].set(jnp.int32(gid0))

    kw = dict(
        buf=new_buf,
        alive=state.alive | mask,
        tail=jnp.where(mask, jnp.int32(cap), state.tail),
    )
    sustain = max(1, cap // max(1, opts.batch))
    if victim is not None:
        vgid = victim.behaviours[0].global_id
        kw.update(
            dspill_tgt=state.dspill_tgt * 0 + jnp.int32(victim.local_start),
            dspill_sender=state.dspill_sender * 0 - 1,
            dspill_words=state.dspill_words.at[0, :].set(jnp.int32(vgid)),
            dspill_count=state.dspill_count * 0 + jnp.int32(opts.spill_cap),
        )
        sustain = max(sustain, opts.spill_cap // max(1, victim.batch))
    return dataclasses.replace(state, **kw), sustain


# ---------------------------------------------------------------------------
# calibration + resolution


def _window_ticks(opts: RuntimeOptions, sustain: int) -> int:
    if opts.tuning_ticks > 0:
        return opts.tuning_ticks
    return max(2, min(16, sustain))


def calibrate(program, opts: RuntimeOptions, mesh, state,
              names_overrides: List[Tuple[str, Dict]],
              ) -> Tuple[Dict[str, Optional[float]], Dict[str, Any]]:
    """Time every candidate on the synthetic workload. Returns
    ({name: tick_ms or None}, detail) — a variant that fails to
    build/run records None and the error string instead of failing the
    start (e.g. an unmeasured Mosaic lowering on a new backend)."""
    import jax
    import jax.numpy as jnp
    from .runtime import engine

    template, sustain = make_workload(program, opts, state)
    detail: Dict[str, Any] = {"errors": {}}
    table: Dict[str, Optional[float]] = {}
    if template is None:          # host-only program: nothing to measure
        for name, _ov in names_overrides:
            table[name] = None
        detail["skipped"] = "no device cohorts"
        return table, detail

    k = _window_ticks(opts, sustain)
    repeats = opts.tuning_repeats
    w1 = 1 + opts.msg_words + opts.trace_lanes
    slots = opts.inject_slots
    empty_inject = (jnp.full((slots,), -1, jnp.int32),
                    jnp.zeros((w1, slots), jnp.int32))
    limit = jnp.int32(k)
    detail.update(ticks_per_window=k, repeats=repeats,
                  sustain_ticks=int(sustain))

    for name, overrides in names_overrides:
        vopts = dataclasses.replace(opts, **overrides)
        try:
            fn = engine.jit_forced_window(program, vopts, mesh)
            t0 = time.perf_counter()
            out = fn(jax.tree.map(jnp.copy, template), *empty_inject,
                     limit)
            jax.block_until_ready(out)
            compile_s = time.perf_counter() - t0
            times = []
            for _ in range(repeats):
                st_in = jax.tree.map(jnp.copy, template)
                jax.block_until_ready(st_in)
                t0 = time.perf_counter()
                out = fn(st_in, *empty_inject, limit)
                jax.block_until_ready(out)
                times.append(time.perf_counter() - t0)
            table[name] = 1e3 * statistics.median(times) / k
            detail.setdefault("compile_s", {})[name] = round(compile_s, 3)
        except Exception as e:            # noqa: BLE001 — variant, not start
            table[name] = None
            detail["errors"][name] = f"{type(e).__name__}: {e}"[:500]
    return table, detail


# ---------------------------------------------------------------------------
# adaptive quiesce-window resolution (runtime/controller.py)
#
# quiesce_interval="auto" is resolved through the SAME on-disk cache
# machinery as the formulation autos, but with its own record (keyed by
# the layout key + a field marker + the clamp bounds): the stored value
# is not a measured tick_ms winner, it is the window the adaptive
# controller CONVERGED to on a previous run of this layout — the run
# loop re-adapts from there instead of from a cold default, and a
# steady workload's second run starts at its steady state.


def quiesce_key(program, opts: RuntimeOptions) -> Dict[str, Any]:
    key = tuning_key(program, opts)
    key["field"] = "quiesce_interval"
    key["bounds"] = [int(opts.quiesce_interval_min),
                     int(opts.quiesce_interval_max)]
    # The formulation autos' own resolution state is irrelevant to the
    # window record (and would needlessly split the cache by it).
    key.pop("auto", None)
    key.pop("fixed", None)
    return key


# Cold-start initial window when the cache has no converged value: the
# pre-adaptive fixed default, clamped into the configured bounds.
DEFAULT_QUIESCE_INTERVAL = 64


def resolve_quiesce_interval(program, opts: RuntimeOptions,
                             ) -> Tuple[int, Dict[str, Any]]:
    """Concrete initial window for quiesce_interval="auto": the cached
    converged value for this layout, else the clamped default. Returns
    (initial, record) — the record rides Runtime.tuning_record into the
    bench JSON."""
    lo, hi = opts.quiesce_interval_min, opts.quiesce_interval_max
    clamp = lambda v: min(hi, max(lo, int(v)))         # noqa: E731
    record: Dict[str, Any] = {"bounds": [lo, hi]}
    cdir = tuning_cache_dir(opts)
    key = quiesce_key(program, opts)
    cached = load_cached(cdir, key)
    if cached is not None and isinstance(
            cached["chosen"].get("quiesce_interval"), int):
        v = clamp(cached["chosen"]["quiesce_interval"])
        record.update(source="cache", initial=v,
                      cache_path=cache_path(cdir, key))
        return v, record
    v = clamp(DEFAULT_QUIESCE_INTERVAL)
    record.update(source="default", initial=v)
    return v, record


def store_quiesce_interval(program, opts: RuntimeOptions,
                           window: int) -> Optional[str]:
    """Persist a converged adaptive window for this layout (called by
    the run loop when the controller reaches steady state; best-effort
    like every cache write)."""
    cdir = tuning_cache_dir(opts)
    if cdir is None:
        return None
    key = quiesce_key(program, opts)
    return store_cached(cdir, key, {
        "key": key, "chosen": {"quiesce_interval": int(window)},
        "winner": f"window={int(window)}",
        "written_unix": time.time()})


def resolve(program, opts: RuntimeOptions, mesh, state,
            ) -> Tuple[RuntimeOptions, Dict[str, Any]]:
    """Turn "auto" option values into concrete ones: cache hit →
    cached winner; miss → calibrate, decide, persist. Returns
    (concrete opts, decision record). The record rides into bench.py's
    JSON so every bench doubles as the A/B campaign's lab notebook."""
    autos = auto_fields(opts)
    if not autos:
        return opts, {"source": "none", "chosen": {}, "table": {}}

    cands = variants(program, opts)
    baseline = cands[0]
    record: Dict[str, Any] = {
        "auto": autos,
        "variants": [n for n, _ in cands],
        "table": {},
        "detail": {},
    }

    if len(cands) == 1:
        # Nothing eligible beyond the baseline (e.g. pallas_fused="auto"
        # on an all-ineligible program): decide without measuring.
        name, overrides = baseline
        record.update(source="default", chosen=overrides, winner=name)
        return dataclasses.replace(opts, **overrides), record

    key = tuning_key(program, opts)
    cdir = tuning_cache_dir(opts)
    record["cache_dir"] = cdir
    cached = load_cached(cdir, key)
    if cached is not None:
        record.update(source="cache", chosen=cached["chosen"],
                      winner=cached.get("winner"),
                      table=cached.get("table", {}),
                      cache_path=cache_path(cdir, key))
        return dataclasses.replace(opts, **cached["chosen"]), record

    table, detail = calibrate(program, opts, mesh, state, cands)
    winner = decide(table, order=[n for n, _ in cands])
    if winner is None:
        winner = baseline[0]
    overrides = dict(cands)[winner]
    record.update(source="calibrated", chosen=overrides, winner=winner,
                  table={n: (None if t is None else round(t, 4))
                         for n, t in table.items()},
                  detail=detail)
    stored = store_cached(cdir, key, {
        "key": key, "chosen": overrides, "winner": winner,
        "table": record["table"], "detail": detail,
        "written_unix": time.time()})
    if stored:
        record["cache_path"] = stored
    return dataclasses.replace(opts, **overrides), record
