"""Program-build plugins — ≙ the reference's compiler plugin system
(src/libponyc/plugin/plugin.c: dlopen'd shared objects exposing init/
final/help/parse_options/visit hooks that run inside the pass
pipeline).

Here the "compiler" is the Program build, so plugins are Python objects
(or modules) with the same hook shape, loaded by import path:

    class MyPlugin:
        name = "my-plugin"
        def init(self, program): ...                 # ≙ plugin init
        def visit_cohort(self, program, cohort): ... # ≙ AST visit hook
        def finalize(self, program): ...             # ≙ pre-codegen
        def help(self) -> str: ...
        def parse_options(self, argv) -> list: ...   # consume own flags

    plugins.load("mypkg.myplugin")       # import path (≙ dlopen path)
    plugins.register(MyPlugin())         # or an instance directly

Program.finalize() runs the hooks for every registered plugin: init
once, visit_cohort per cohort, finalize last — the same three-phase
shape as plugin.c:27-40.
"""

from __future__ import annotations

import importlib
from typing import Any, List


class PluginError(RuntimeError):
    pass


_registry: List[Any] = []


def register(plugin: Any) -> Any:
    """Register a plugin instance for subsequent Program builds."""
    for hook in ("init", "visit_cohort", "finalize"):
        fn = getattr(plugin, hook, None)
        if fn is not None and not callable(fn):
            raise PluginError(f"plugin hook {hook} is not callable")
    _registry.append(plugin)
    return plugin


def load(import_path: str) -> Any:
    """Load a plugin by module path (≙ --plugin=path dlopen). The module
    must expose PLUGIN (instance) or Plugin (class)."""
    mod = importlib.import_module(import_path)
    plug = getattr(mod, "PLUGIN", None)
    if plug is None:
        cls = getattr(mod, "Plugin", None)
        if cls is None:
            raise PluginError(
                f"{import_path} exposes neither PLUGIN nor Plugin")
        plug = cls()
    return register(plug)


def unregister_all() -> None:
    _registry.clear()


def active() -> List[Any]:
    return list(_registry)


def parse_options(argv: List[str]) -> List[str]:
    """Let every plugin strip its own flags (≙ plugin parse_options)."""
    for p in _registry:
        fn = getattr(p, "parse_options", None)
        if fn is not None:
            argv = list(fn(argv))
    return argv


def help_text() -> str:
    out = []
    for p in _registry:
        fn = getattr(p, "help", None)
        if fn is not None:
            out.append(f"{getattr(p, 'name', type(p).__name__)}: {fn()}")
    return "\n".join(out)


def run_build_hooks(program) -> None:
    """Called by Program.finalize() after layout is frozen."""
    for p in _registry:
        fn = getattr(p, "init", None)
        if fn is not None:
            fn(program)
    for p in _registry:
        fn = getattr(p, "visit_cohort", None)
        if fn is not None:
            for cohort in program.cohorts:
                fn(program, cohort)
    for p in _registry:
        fn = getattr(p, "finalize", None)
        if fn is not None:
            fn(program)
