"""Load generator + chaos/soak harness for the serving front door
(serve.py; ROADMAP item 4's "the claim needs a number").

Speaks the serve.py wire protocol (length-prefixed i32-word frames)
over plain sockets — no runtime, no JAX — so it can hammer a server
from a thread, a subprocess, or another machine. Two jobs:

- **Measurement** (`run_load`): N connections drive closed-loop
  pipelined request streams (depth outstanding per connection —
  offered load = conns × depth concurrent requests), match every
  reply to its request, verify the value (the default service's
  2*x+1), and record per-request end-to-end latency. The returned
  stats block is the `serving` BENCH record's raw material: p50/p99
  latency of OK replies, shed counts by status, goodput.

- **Chaos** (knobs below, composable): connection churn
  (`churn_every`), bursty arrivals (`burst`/`burst_pause_s`), slow
  consumers (`slow_read_s` delays reads while writes continue,
  building egress backpressure), malformed frames (`malform_every`),
  and mid-request kill (`kill_after` closes the socket with requests
  outstanding). Every knob is client-side misbehaviour the front door
  must absorb without wedging the world (tests/test_serve.py and the
  soak half of `bench.py --serve-smoke` drive them).

CLI: ``python -m ponyc_tpu.loadgen HOST PORT [--conns N] [--depth D]
[--requests K] [--deadline-ms MS] [--duration S] [...chaos flags]`` —
prints the stats block as one JSON object.
"""

from __future__ import annotations

import json
import socket
import struct
import sys
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from .serve import (ST_BADFRAME, ST_BUSY, ST_DEADLINE, ST_OK, Framer,
                    encode_request)

_HDR = struct.Struct(">I")


def default_value(x: int) -> int:
    """The default ServeWorker.handle contract: value = 2*x+1, i32
    wraparound (device arithmetic is int32)."""
    return int(np.int32(2 * np.int32(x) + 1))


class _ConnStats:
    __slots__ = ("sent", "ok", "busy", "deadline", "badframe", "other",
                 "bad_value", "unanswered", "reconnects", "killed",
                 "lat_us", "malformed_sent")

    def __init__(self):
        self.sent = 0
        self.ok = 0
        self.busy = 0
        self.deadline = 0
        self.badframe = 0
        self.other = 0
        self.bad_value = 0
        self.unanswered = 0
        self.reconnects = 0
        self.killed = 0
        self.malformed_sent = 0
        self.lat_us: List[int] = []


def _connect(host: str, port: int, *, rcvbuf: Optional[int] = None,
             timeout_s: float = 10.0) -> socket.socket:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    if rcvbuf:
        s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, int(rcvbuf))
    s.settimeout(timeout_s)
    s.connect((host, port))
    s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
    return s


def _drive_conn(host: str, port: int, st: _ConnStats, *,
                requests: int, depth: int, deadline_ms: int,
                payload_of, value_of, duration_s: Optional[float],
                churn_every: Optional[int], burst: Optional[int],
                burst_pause_s: float, slow_read_s: float,
                malform_every: Optional[int],
                kill_after: Optional[int], retry_busy: bool,
                busy_backoff_s: float, stop_on_busy: bool,
                stop: threading.Event, timeout_s: float) -> None:
    """One connection's closed-loop driver: keep `depth` requests
    outstanding; read replies inline. Chaos knobs mutate the schedule.
    Requests left outstanding at EOF/timeout count as unanswered —
    the drain test's "zero lost replies" assertion reads exactly
    this."""
    t_end = time.monotonic() + duration_s if duration_s else None
    framer = Framer(max_words=64)
    outstanding: Dict[int, tuple] = {}      # rid → (x, t_sent, retries)
    rid = 1
    issued = 0          # distinct requests issued (retries don't count)
    sock: Optional[socket.socket] = None
    last_progress = time.monotonic()   # newest send or parsed reply: a
    #   server that stops replying (wedged world) must not spin the
    #   closed loop forever — timeout_s of zero progress ends the run

    def reconnect():
        nonlocal sock
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass
            st.reconnects += 1
        sock = _connect(host, port, timeout_s=timeout_s)

    def read_some() -> bool:
        """One recv; dispatch every whole reply frame. False on EOF."""
        try:
            data = sock.recv(65536)
        except socket.timeout:
            return True
        except OSError:
            return False
        if not data:
            return False
        if slow_read_s:
            time.sleep(slow_read_s)
        nonlocal last_progress
        for words in framer.feed(data):
            last_progress = time.monotonic()
            r, status = int(words[0]), int(words[1])
            ent = outstanding.pop(r, None)
            if status == ST_OK:
                st.ok += 1
                if ent is not None:
                    x, t0, _ = ent
                    st.lat_us.append(int((time.monotonic() - t0) * 1e6))
                    if value_of is not None \
                            and int(words[2]) != value_of(x):
                        st.bad_value += 1
            elif status == ST_BUSY:
                st.busy += 1
                if stop_on_busy:
                    # A BUSY is the server saying "back off" (drain or
                    # overload): treat it as the end of this run — the
                    # drain test's way of quiescing the offered load.
                    stop.set()
                if retry_busy and ent is not None and not stop.is_set():
                    x, _, n = ent
                    if n < 64:
                        time.sleep(0.002 * (1 << min(n, 5)))
                        send_one(x, retry_of=(r, n + 1))
                elif busy_backoff_s:
                    # Well-behaved overload client: back off instead
                    # of turning every shed into an instant resend.
                    time.sleep(busy_backoff_s)
            elif status == ST_DEADLINE:
                st.deadline += 1
            elif status == ST_BADFRAME:
                st.badframe += 1
            else:
                st.other += 1
        return True

    def send_one(x: int, retry_of=None) -> bool:
        nonlocal rid, issued, last_progress
        last_progress = time.monotonic()
        r = rid
        rid += 1
        n_retries = 0 if retry_of is None else retry_of[1]
        try:
            sock.sendall(encode_request(r, deadline_ms, payload_of(x)))
        except OSError:
            return False
        st.sent += 1
        if retry_of is None:
            issued += 1
        outstanding[r] = (x, time.monotonic(), n_retries)
        return True

    try:
        reconnect()
        x = 0
        while not stop.is_set():
            if t_end is not None and time.monotonic() > t_end:
                break
            if t_end is None and issued >= requests:
                # Everything issued: fall through to the BOUNDED tail
                # drain below (a server that stopped replying — e.g. a
                # wedged world — must not hang the client forever).
                break
            if outstanding \
                    and time.monotonic() - last_progress > timeout_s:
                break              # zero progress for timeout_s: bail
            # Chaos: abrupt mid-request kill.
            if kill_after is not None and issued >= kill_after:
                st.killed += 1
                st.unanswered += len(outstanding)
                outstanding.clear()
                try:
                    sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                    struct.pack("ii", 1, 0))  # RST
                except OSError:
                    pass
                sock.close()
                return
            # Chaos: connection churn — clean close + fresh connect.
            if churn_every and issued and issued % churn_every == 0 \
                    and not outstanding:
                reconnect()
                framer = Framer(max_words=64)
            # Fill the pipeline (bursty: send `burst` then pause).
            budget = depth - len(outstanding)
            if burst:
                budget = min(budget, burst)
            sent_now = 0
            while budget > 0 and (t_end is not None
                                  or issued < requests):
                if malform_every and st.sent \
                        and st.sent % malform_every == 0:
                    st.malformed_sent += 1
                    try:   # 3-byte body: not a word multiple
                        sock.sendall(_HDR.pack(3) + b"\x00\x00\x00")
                    except OSError:
                        break
                    # The server replies BADFRAME(-1) and CLOSES.
                    read_some()
                    reconnect()
                    framer = Framer(max_words=64)
                    st.unanswered += len(outstanding)
                    outstanding.clear()
                    continue
                if not send_one(x):
                    break
                x += 1
                budget -= 1
                sent_now += 1
            if burst and sent_now:
                time.sleep(burst_pause_s)
            if not read_some():
                # Server closed the connection (drain end, choke kill).
                st.unanswered += len(outstanding)
                outstanding.clear()
                if t_end is not None and not stop.is_set() \
                        and time.monotonic() < t_end:
                    try:
                        reconnect()
                        framer = Framer(max_words=64)
                        continue
                    except OSError:
                        break
                break
        # Drain the tail: collect replies for whatever is outstanding.
        t_tail = time.monotonic() + min(5.0, timeout_s)
        while outstanding and time.monotonic() < t_tail:
            if not read_some():
                break
        st.unanswered += len(outstanding)
    except OSError:
        st.unanswered += len(outstanding)
    finally:
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass


def _percentile(sorted_vals: List[int], q: float) -> int:
    if not sorted_vals:
        return 0
    return sorted_vals[min(len(sorted_vals) - 1,
                           int(len(sorted_vals) * q))]


def run_load(host: str, port: int, *, conns: int = 4, depth: int = 8,
             requests: int = 100, deadline_ms: int = 0,
             duration_s: Optional[float] = None,
             payload_of=None, value_of=default_value,
             churn_every: Optional[int] = None,
             burst: Optional[int] = None, burst_pause_s: float = 0.05,
             slow_read_s: float = 0.0,
             malform_every: Optional[int] = None,
             kill_after: Optional[int] = None,
             retry_busy: bool = False, busy_backoff_s: float = 0.0,
             stop_on_busy: bool = False,
             stop: Optional[threading.Event] = None,
             timeout_s: float = 10.0) -> Dict[str, Any]:
    """Drive `conns` concurrent connections; returns the aggregated
    stats block. `requests` is per connection (ignored when
    `duration_s` runs the soak by wall clock). `payload_of(x)` builds
    the request payload words (default: the 1-word default service);
    `value_of(x)` verifies OK replies (None skips verification)."""
    payload_of = payload_of or (lambda x: [x])
    stop = stop or threading.Event()
    stats = [_ConnStats() for _ in range(conns)]
    t0 = time.monotonic()
    threads = [threading.Thread(
        target=_drive_conn, args=(host, port, st),
        kwargs=dict(requests=requests, depth=depth,
                    deadline_ms=deadline_ms, payload_of=payload_of,
                    value_of=value_of, duration_s=duration_s,
                    churn_every=churn_every, burst=burst,
                    burst_pause_s=burst_pause_s,
                    slow_read_s=slow_read_s,
                    malform_every=malform_every,
                    kill_after=kill_after, retry_busy=retry_busy,
                    busy_backoff_s=busy_backoff_s,
                    stop_on_busy=stop_on_busy,
                    stop=stop, timeout_s=timeout_s),
        daemon=True) for st in stats]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = max(1e-9, time.monotonic() - t0)
    lat = sorted(u for st in stats for u in st.lat_us)
    agg = {k: sum(getattr(st, k) for st in stats)
           for k in ("sent", "ok", "busy", "deadline", "badframe",
                     "other", "bad_value", "unanswered", "reconnects",
                     "killed", "malformed_sent")}
    shed = agg["busy"] + agg["deadline"]
    return {
        **agg,
        "conns": conns,
        "depth": depth,
        "elapsed_s": round(elapsed, 3),
        "goodput_rps": round(agg["ok"] / elapsed, 1),
        "offered_rps": round(agg["sent"] / elapsed, 1),
        "shed_rate": round(shed / max(1, agg["sent"]), 4),
        "p50_us": _percentile(lat, 0.50),
        "p99_us": _percentile(lat, 0.99),
        "answered": agg["ok"] + agg["busy"] + agg["deadline"]
        + agg["badframe"] + agg["other"],
    }


def soak(host: str, port: int, *, duration_s: float = 10.0,
         conns: int = 8, depth: int = 16,
         deadline_ms: int = 0) -> Dict[str, Any]:
    """Chaos soak: a steady measured stream PLUS one churning client,
    one bursty client, one slow consumer, one malformed-frame sender
    and one mid-request killer, all riding the same server for
    `duration_s`. Returns {"steady": stats, "chaos": stats} — the
    steady half is the number that matters (the front door must keep
    serving it while the chaos half misbehaves)."""
    stop = threading.Event()
    out: Dict[str, Any] = {}

    def steady():
        out["steady"] = run_load(
            host, port, conns=conns, depth=depth,
            deadline_ms=deadline_ms, duration_s=duration_s, stop=stop)

    def chaos():
        out["chaos"] = run_load(
            host, port, conns=5, depth=4, requests=1 << 30,
            duration_s=duration_s, churn_every=20, burst=4,
            burst_pause_s=0.02, slow_read_s=0.002, malform_every=97,
            kill_after=None, value_of=None, stop=stop)

    ts = [threading.Thread(target=steady, daemon=True),
          threading.Thread(target=chaos, daemon=True)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(duration_s + 30.0)
    stop.set()
    return out


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(prog="ponyc_tpu.loadgen")
    ap.add_argument("host")
    ap.add_argument("port", type=int)
    ap.add_argument("--conns", type=int, default=4)
    ap.add_argument("--depth", type=int, default=8)
    ap.add_argument("--requests", type=int, default=100)
    ap.add_argument("--deadline-ms", type=int, default=0)
    ap.add_argument("--duration", type=float, default=None)
    ap.add_argument("--churn-every", type=int, default=None)
    ap.add_argument("--burst", type=int, default=None)
    ap.add_argument("--slow-read", type=float, default=0.0)
    ap.add_argument("--malform-every", type=int, default=None)
    ap.add_argument("--kill-after", type=int, default=None)
    ap.add_argument("--retry-busy", action="store_true")
    ap.add_argument("--busy-backoff", type=float, default=0.0)
    ap.add_argument("--soak", action="store_true",
                    help="run the composed chaos soak instead")
    args = ap.parse_args(argv)
    if args.soak:
        res = soak(args.host, args.port,
                   duration_s=args.duration or 10.0,
                   conns=args.conns, depth=args.depth,
                   deadline_ms=args.deadline_ms)
    else:
        res = run_load(args.host, args.port, conns=args.conns,
                       depth=args.depth, requests=args.requests,
                       deadline_ms=args.deadline_ms,
                       duration_s=args.duration,
                       churn_every=args.churn_every, burst=args.burst,
                       slow_read_s=args.slow_read,
                       malform_every=args.malform_every,
                       kill_after=args.kill_after,
                       retry_busy=args.retry_busy,
                       busy_backoff_s=args.busy_backoff)
    print(json.dumps(res))
    return 0


if __name__ == "__main__":
    sys.exit(main())
