"""Sorted-segment primitives used by batched message delivery.

The delivery problem (scatter-append K messages into N ring buffers while
preserving per-sender order and respecting capacity) is solved the
XLA-friendly way: stable sort by target, compute each entry's *rank within
its target segment* with a prefix max, then one scatter. These helpers are
shared by single-chip delivery and the per-shard delivery inside shard_map.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def stable_sort_by(keys: jnp.ndarray):
    """Return the permutation that stably sorts int32 keys ascending."""
    return jnp.argsort(keys, stable=True)


def segment_ranks(sorted_keys: jnp.ndarray) -> jnp.ndarray:
    """Given keys already sorted ascending, return each element's index
    within its run of equal keys. [3,3,5,5,5,9] → [0,1,0,1,2,0]."""
    n = sorted_keys.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    is_start = jnp.concatenate(
        [jnp.ones((1,), jnp.bool_), sorted_keys[1:] != sorted_keys[:-1]])
    seg_start = jax.lax.associative_scan(
        jnp.maximum, jnp.where(is_start, idx, 0))
    return idx - seg_start


def counts_by_key(keys: jnp.ndarray, weights: jnp.ndarray,
                  num_buckets: int) -> jnp.ndarray:
    """Scatter-add weights into num_buckets by key; out-of-range keys drop."""
    out = jnp.zeros((num_buckets,), weights.dtype)
    return out.at[keys].add(weights, mode="drop")


def compact_mask(mask: jnp.ndarray, cap: int):
    """Stable-compact True entries to the front, truncated/padded to cap.

    Returns (perm[cap], valid[cap], total_true). perm indexes the original
    array; entries beyond total_true are padding (valid=False). Order of the
    selected entries is preserved (stable sort on ~mask).
    """
    total = jnp.sum(mask.astype(jnp.int32))
    perm = jnp.argsort(~mask, stable=True)
    perm = perm[:cap]
    valid = jnp.arange(cap, dtype=jnp.int32) < total
    return perm, valid, total
