"""Persistent fused window megakernel + the mailbox bandwidth diet.

≙ the whole of ponyint_actor_run's visit — message pop, behaviour
dispatch, GC bookkeeping (src/libponyrt/actor/actor.c:383-664) — as ONE
resident device kernel, where the rest of the engine runs it as a chain
of XLA passes with an HBM round-trip between each.

Two ideas, one module:

1. **The megakernel** (`build_mega_window`): the entire gated window —
   delivery gather → mailbox drain → behaviour dispatch → profiler
   lanes → GC-mark bookkeeping inside the step — executes as one
   `pl.pallas_call` whose body runs the in-window `while` as a
   KERNEL-INTERNAL loop. Today's formulation re-materialises the
   `[cap, w1, N]` mailbox block once per phase per tick
   (ops/mailbox_kernel.py for the drain, ops/fused_dispatch.py for
   dispatch, delivery.py's sort/rebuild, engine.profile_lanes —
   each a separate XLA fusion boundary); here the whole tick body and
   the whole window live inside one kernel scope, so the compiler sees
   a single dataflow region over the mailbox tiles instead of N
   HBM-bounded passes (the Halide "push memory" argument,
   arXiv 2105.12858; actor semantics survive bulk-kernel execution per
   the OpenCL-Actors result, arXiv 1709.07781).

   The kernel body reuses the REAL `engine.build_step` closure and the
   REAL window `while` condition (`engine.aux_go`) — equivalence with
   the XLA scan path is by construction, and the differential/FIFO
   corpora (tests/test_differential.py, tests/test_fifo.py) pin it
   bit-for-bit in interpret mode. On a backend where the Mosaic
   lowering of some contained op is unsupported, the tuner's per-
   variant error capture (tuning.calibrate) records the failure and
   the variant self-disqualifies — `delivery="pallas_mega"` can never
   break a start, only lose a race.

2. **The bandwidth diet** (`pack_words`/`unpack_words`): mailbox ring
   records, spill words and trace lanes are int32, but behaviour ids
   and most payload words are small. Records cross the kernel boundary
   packed as an int16 lane plane plus an int32 ESCAPE plane: a word
   that fits int16 (and is not the reserved sentinel) travels in 2
   bytes; the rare wide word travels via the escape plane. The codec
   is LOSSLESS for every int32 value (the sentinel itself is escaped),
   so packing can never change semantics — only bytes moved. Modelled
   hot-path bytes per message drop from 4·w1 to w1·(2 + 4·esc_rate):
   2.0× at a zero escape rate, ≥ 1.8× while fewer than ~5.5% of words
   escape (`modelled_bytes_per_msg`; bench.py records the measured
   escape rate of every run in the BENCH json `kernel` block, and
   PROFILE.md §14 carries the bytes-moved/tick table).

Single-shard only (`eligible`): under a mesh the window's psum votes
cross shards mid-tick, which a single-device kernel scope cannot
express — sharded programs fall back to the XLA formulation (same
semantics; delivery="pallas_mega" behaves as "plan" there).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.experimental import pallas as pl

from .mailbox_kernel import interpret_mode

# The escaped sentinel: int16 min. A packed word equal to ESC means
# "read the escape plane". -32768 itself FITS int16 but collides with
# the sentinel, so it is escaped too — the codec is total on int32.
ESC = -32768


# ---------------------------------------------------------------------------
# the record codec (jnp + np twins — serialise.py packs snapshots with
# the numpy spelling, the kernel boundary uses the jax one)


def pack_words(w: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """int32 words → (int16 lane plane, int32 escape plane). Lossless:
    `unpack_words(*pack_words(w)) == w` for every int32 value."""
    w = w.astype(jnp.int32)
    lo = w.astype(jnp.int16)
    fits = (lo.astype(jnp.int32) == w) & (lo != jnp.int16(ESC))
    lo16 = jnp.where(fits, lo, jnp.int16(ESC))
    esc32 = jnp.where(fits, jnp.int32(0), w)
    return lo16, esc32


def unpack_words(lo16: jnp.ndarray, esc32: jnp.ndarray) -> jnp.ndarray:
    return jnp.where(lo16 == jnp.int16(ESC), esc32,
                     lo16.astype(jnp.int32))


def pack_words_np(w: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    w = np.asarray(w, np.int32)
    lo = w.astype(np.int16)
    fits = (lo.astype(np.int32) == w) & (lo != np.int16(ESC))
    lo16 = np.where(fits, lo, np.int16(ESC)).astype(np.int16)
    esc32 = np.where(fits, np.int32(0), w).astype(np.int32)
    return lo16, esc32


def unpack_words_np(lo16: np.ndarray, esc32: np.ndarray) -> np.ndarray:
    return np.where(lo16 == np.int16(ESC), esc32,
                    lo16.astype(np.int32)).astype(np.int32)


def escape_rate(arrays) -> float:
    """Fraction of int32 words that need the escape plane (wide values
    plus the sentinel collision) across `arrays` — the measured input
    to the bytes-per-message model."""
    total = 0
    escaped = 0
    for a in arrays:
        a = np.asarray(a)
        if a.size == 0 or a.dtype != np.int32:
            continue
        lo = a.astype(np.int16)
        fits = (lo.astype(np.int32) == a) & (lo != np.int16(ESC))
        total += a.size
        escaped += int(a.size - np.count_nonzero(fits))
    return escaped / total if total else 0.0


def escape_rate_state(state) -> float:
    """Measured escape rate over the live word tables (mailbox rings +
    spill words) of an RtState — what bench.py records per run."""
    arrs = list(state.buf.values()) + [state.dspill_words,
                                       state.rspill_words]
    arrs += list(state.trace_buf.values())
    return escape_rate([np.asarray(a) for a in arrs])


def record_words(opts) -> int:
    """Ring-record width in words: behaviour id + payload + trace
    lanes (state.py: w1 = 1 + msg_words + trace_lanes)."""
    return 1 + opts.msg_words + getattr(opts, "trace_lanes", 0)


def modelled_bytes_per_msg(opts, esc_rate: float = 0.0) -> Dict[str, Any]:
    """The bandwidth-diet model: hot-path bytes per ring record,
    unpacked (4 bytes/word) vs packed (2 bytes/word + the escape plane
    fetched at the measured escape rate). The acceptance bar is
    ratio ≥ 1.8, which holds while esc_rate ≤ ~5.5%."""
    w1 = record_words(opts)
    unpacked = 4.0 * w1
    packed = w1 * (2.0 + 4.0 * float(esc_rate))
    return {
        "record_words": w1,
        "unpacked_bytes": unpacked,
        "packed_bytes": round(packed, 3),
        "ratio": round(unpacked / packed, 3),
        "escape_rate": round(float(esc_rate), 6),
    }


# ---------------------------------------------------------------------------
# eligibility


def eligible(program, opts) -> bool:
    """Structural preconditions of the megakernel: one shard (the
    window's mesh psum votes cannot cross a single kernel's scope),
    some device cohort to run, and the nested Pallas kernels OFF
    (a pallas_call inside the megakernel's scope would nest kernels —
    the megakernel IS the fused form of both)."""
    if program.shards != 1:
        return False
    if getattr(opts, "pallas", False) is True:
        return False
    if getattr(opts, "pallas_fused", False) is True:
        return False
    return any(ch.behaviours for ch in program.device_cohorts)


def auto_enumerable(program, opts) -> bool:
    """Whether delivery="auto" should TIME the megakernel as a variant.
    On a real TPU: whenever eligible. On CPU the kernel only runs in
    interpret mode — a test vehicle, never a perf winner — so auto
    skips it unless PONY_TPU_MEGA_AUTO=1 (bench.py sets it: every
    BENCH json's A/B table carries the variant; the unit suite's many
    auto-starts don't pay an extra window compile)."""
    import os
    if not eligible(program, opts):
        return False
    if jax.default_backend() == "tpu":
        return True
    return os.environ.get("PONY_TPU_MEGA_AUTO", "0") == "1"


# ---------------------------------------------------------------------------
# pytree <-> kernel-operand marshalling
#
# The kernel's I/O is the flattened (state, aux) pytree. Per leaf:
#   - zero-size leaves bypass the kernel (no bytes to move; pallas
#     rejects 0-sized blocks) and are reconstituted outside;
#   - word-table leaves (mailbox rings, spill words, trace lanes —
#     state.PACKED_WORD_FIELDS) cross as (int16, int32-escape) pairs:
#     the bandwidth diet applied exactly where the bytes are;
#   - bool leaves cross as int32 (TPU-friendly lane dtype);
#   - scalars cross as [1] vectors (0-d refs don't block).


class _Role(NamedTuple):
    kind: str            # "bypass" | "packed" | "plain"
    shape: Tuple[int, ...]
    dtype: Any
    was_bool: bool
    was_scalar: bool


def _word_table_mask(state) -> List[bool]:
    """Flattened-leaf mask marking the packable int32 word tables,
    aligned with jax.tree.flatten(state)."""
    import dataclasses
    from ..runtime.state import PACKED_WORD_FIELDS
    mask = jax.tree.map(lambda _: False, state)
    kw = {}
    for f in PACKED_WORD_FIELDS:
        v = getattr(state, f)
        kw[f] = ({k: True for k in v} if isinstance(v, dict) else True)
    mask = dataclasses.replace(mask, **kw)
    return jax.tree_util.tree_leaves(mask)


def _roles(leaves, packed_mask) -> List[_Role]:
    out = []
    for leaf, packed in zip(leaves, packed_mask):
        shape = tuple(leaf.shape)
        size = int(np.prod(shape)) if shape else 1
        if size == 0:
            out.append(_Role("bypass", shape, leaf.dtype, False, False))
        elif packed and leaf.dtype == jnp.int32:
            out.append(_Role("packed", shape, leaf.dtype, False, False))
        else:
            out.append(_Role("plain", shape, leaf.dtype,
                             leaf.dtype == jnp.bool_, shape == ()))
    return out


def _encode(leaves, roles) -> List[jnp.ndarray]:
    ops: List[jnp.ndarray] = []
    for leaf, role in zip(leaves, roles):
        if role.kind == "bypass":
            continue
        if role.kind == "packed":
            lo16, esc32 = pack_words(leaf)
            ops.append(lo16)
            ops.append(esc32)
            continue
        a = leaf
        if role.was_bool:
            a = a.astype(jnp.int32)
        if role.was_scalar:
            a = a.reshape(1)
        ops.append(a)
    return ops


def _operand_structs(roles) -> List[jax.ShapeDtypeStruct]:
    out = []
    for role in roles:
        if role.kind == "bypass":
            continue
        if role.kind == "packed":
            out.append(jax.ShapeDtypeStruct(role.shape, jnp.int16))
            out.append(jax.ShapeDtypeStruct(role.shape, jnp.int32))
            continue
        shape = (1,) if role.was_scalar else role.shape
        dtype = jnp.int32 if role.was_bool else role.dtype
        out.append(jax.ShapeDtypeStruct(shape, dtype))
    return out


def _decode_refs(refs, roles) -> List[jnp.ndarray]:
    """Kernel-side: read operand refs back into the original leaves."""
    leaves: List[jnp.ndarray] = []
    i = 0
    for role in roles:
        if role.kind == "bypass":
            leaves.append(jnp.zeros(role.shape, role.dtype))
            continue
        if role.kind == "packed":
            lo16 = refs[i][...]
            esc32 = refs[i + 1][...]
            i += 2
            leaves.append(unpack_words(lo16, esc32))
            continue
        a = refs[i][...]
        i += 1
        if role.was_scalar:
            a = a.reshape(())
        if role.was_bool:
            a = a.astype(jnp.bool_)
        leaves.append(a)
    return leaves


def _write_refs(refs, roles, leaves) -> None:
    """Kernel-side: write result leaves to the output refs."""
    i = 0
    for leaf, role in zip(leaves, roles):
        if role.kind == "bypass":
            continue
        if role.kind == "packed":
            lo16, esc32 = pack_words(leaf)
            refs[i][...] = lo16
            refs[i + 1][...] = esc32
            i += 2
            continue
        a = leaf
        if role.was_bool:
            a = a.astype(jnp.int32)
        if role.was_scalar:
            a = a.reshape(1)
        refs[i][...] = a
        i += 1


def _decode_outputs(outs, roles) -> List[jnp.ndarray]:
    """Host-side: kernel outputs back into result leaves."""
    leaves: List[jnp.ndarray] = []
    i = 0
    for role in roles:
        if role.kind == "bypass":
            leaves.append(jnp.zeros(role.shape, role.dtype))
            continue
        if role.kind == "packed":
            leaves.append(unpack_words(outs[i], outs[i + 1]))
            i += 2
            continue
        a = outs[i]
        i += 1
        if role.was_scalar:
            a = a.reshape(())
        if role.was_bool:
            a = a.astype(jnp.bool_)
        leaves.append(a)
    return leaves


# ---------------------------------------------------------------------------
# the megakernel window


def build_mega_window(program, opts, step, go_fn, *, forced: bool = False):
    """The gated window (engine.build_multi_step_gated's contract) as
    ONE persistent Pallas kernel; `forced=True` builds the tuner's
    unconditional fori_loop spelling (engine.build_forced_window)
    instead, so calibration times the kernel on the same trip count as
    every other variant.

    `step` is the REAL engine.build_step closure and `go_fn` the REAL
    engine.aux_go — the kernel-internal loop is the same computation
    the XLA path runs, so bit-equivalence is by construction.

    Signature (both spellings): (st, inject_tgt, inject_words, limit,
    force, prev_aux) → (state, last_aux, ticks_run).
    """
    interpret = interpret_mode()

    def window(st, inject_tgt, inject_words, limit, force, prev_aux):
        if forced:
            def fbody(_i, carry):
                s, _aux = carry
                return step(s, inject_tgt, inject_words)

            stf, auxf = lax.fori_loop(0, limit, fbody, (st, prev_aux))
            return stf, auxf, jnp.asarray(limit, jnp.int32)

        def cond(carry):
            _st, aux, i = carry
            first = i == 0
            return (first & (force | go_fn(aux))) | \
                (~first & (i < limit) & go_fn(aux))

        def body(carry):
            s, _aux, i = carry
            first = i == 0
            it = jnp.where(first, inject_tgt, jnp.int32(-1))
            iw = jnp.where(first, inject_words, jnp.int32(0))
            s2, aux2 = step(s, it, iw)
            return (s2, aux2, i + 1)

        return lax.while_loop(cond, body, (st, prev_aux, jnp.int32(0)))

    def mega(st, inject_tgt, inject_words, limit, force, prev_aux):
        limit = jnp.asarray(limit, jnp.int32)
        force = jnp.asarray(force, jnp.bool_)
        args = (st, inject_tgt, inject_words, limit, force, prev_aux)
        in_leaves, in_tree = jax.tree_util.tree_flatten(args)
        packed_mask = _word_table_mask(st)
        # Non-state args never pack: pad the mask to the flat arity.
        packed_mask = packed_mask + [False] * (len(in_leaves)
                                               - len(packed_mask))
        in_roles = _roles(in_leaves, packed_mask)

        out_struct = jax.eval_shape(window, *args)
        out_leaves, out_tree = jax.tree_util.tree_flatten(out_struct)
        out_mask = _word_table_mask(out_struct[0])
        out_mask = out_mask + [False] * (len(out_leaves) - len(out_mask))
        out_roles = _roles(out_leaves, out_mask)

        # Pallas forbids kernels that close over array constants (the
        # step closure bakes the program's routing/layout tables in as
        # literals). Stage the window to a jaxpr ONCE, hand its consts
        # to the kernel as ordinary operands, and replay the jaxpr
        # inside the kernel scope — the whole window body becomes kernel
        # dataflow with no captured arrays.
        def flat_window(*leaves):
            a = jax.tree_util.tree_unflatten(in_tree, leaves)
            return tuple(jax.tree_util.tree_leaves(window(*a)))

        closed = jax.make_jaxpr(flat_window)(*in_leaves)
        consts = [jnp.asarray(c) for c in closed.consts]
        const_roles = _roles(consts, [False] * len(consts))

        def n_operands(roles):
            return sum(0 if r.kind == "bypass"
                       else (2 if r.kind == "packed" else 1)
                       for r in roles)

        n_const = n_operands(const_roles)
        n_in = n_operands(in_roles)

        def kernel(*refs):
            cvals = _decode_refs(refs[:n_const], const_roles)
            leaves = _decode_refs(refs[n_const:n_const + n_in], in_roles)
            res = jax.core.eval_jaxpr(closed.jaxpr, cvals, *leaves)
            _write_refs(refs[n_const + n_in:], out_roles, list(res))

        outs = pl.pallas_call(
            kernel,
            out_shape=_operand_structs(out_roles),
            interpret=interpret,
        )(*(_encode(consts, const_roles) + _encode(in_leaves, in_roles)))
        res_leaves = _decode_outputs(list(outs), out_roles)
        return jax.tree_util.tree_unflatten(out_tree, res_leaves)

    return mega
