"""Pallas TPU kernel for the mailbox drain (the dispatch gather).

≙ the hot half of ponyint_actor_run's message pop loop
(src/libponyrt/actor/actor.c:383-549, messageq.c pops) — and the kernel
BASELINE.json's north star names ("behaviour dispatch ... as a
vmapped/Pallas kernel").

The XLA path (engine._ring_take) drains `batch` ring slots per actor
with a static select chain per slot: `batch` separate fusions over the
[cap, w1, N] mailbox block, each re-reading the block from HBM when the
fusion boundary falls badly. This kernel makes the blocking explicit:
one grid step pulls a [cap, w1, LANE] tile of the (planar, actor-minor —
state.py layout note) mailbox table into VMEM ONCE and emits all
`batch` message planes and validity masks from it.

Gating: `RuntimeOptions.pallas` (off by default until measured ≥ the
XLA path on the real chip; `interpret=True` runs the same kernel on CPU
for the test suite). No per-lane gather is used anywhere — ring-slot
selection is a static select chain over the small `cap` axis, which is
the TPU-legal formulation (dynamic per-lane indexing does not lower).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


LANE_BLOCK = 1024        # actors per grid step (multiple of 128 lanes)


def _drain_kernel(head_ref, nrun_ref, buf_ref, msgs_ref, valid_ref, *,
                  cap: int, batch: int):
    head = head_ref[:]                        # [1, LB]
    nrun = nrun_ref[:]                        # [1, LB]
    for k in range(batch):
        slot = (head + k) % cap               # [1, LB]
        out = buf_ref[0]                      # [w1, LB]
        for c in range(1, cap):
            out = jnp.where(slot == c, buf_ref[c], out)
        msgs_ref[k] = out
        valid_ref[k] = (nrun > k).astype(jnp.int32)[0]


@functools.partial(jax.jit, static_argnames=("batch", "interpret"))
def drain_msgs(buf, head, n_run, *, batch: int, interpret: bool = False):
    """All actors' next `batch` messages in one pass over the mailbox.

    buf: [cap, w1, N] int32 (planar); head, n_run: [N] int32.
    Returns (msgs [batch, w1, N] int32, valids [batch, N] bool).
    N must be a multiple of LANE_BLOCK (cohort capacities are padded by
    the caller; engine cohorts fall back to the XLA path otherwise).
    """
    cap, w1, n = buf.shape
    lb = min(LANE_BLOCK, n)
    assert n % lb == 0, (n, lb)
    grid = (n // lb,)
    kernel = functools.partial(_drain_kernel, cap=cap, batch=batch)
    msgs, valid = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, lb), lambda i: (0, i)),
            pl.BlockSpec((1, lb), lambda i: (0, i)),
            pl.BlockSpec((cap, w1, lb), lambda i: (0, 0, i)),
        ],
        out_specs=[
            pl.BlockSpec((batch, w1, lb), lambda i: (0, 0, i)),
            pl.BlockSpec((batch, lb), lambda i: (0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, w1, n), jnp.int32),
            jax.ShapeDtypeStruct((batch, n), jnp.int32),
        ],
        interpret=interpret,
    )(head[None, :], n_run[None, :], buf)
    return msgs, valid.astype(jnp.bool_)


def use_pallas(opts) -> bool:
    """Whether the engine should route dispatch through this kernel."""
    return bool(getattr(opts, "pallas", False))


def interpret_mode() -> bool:
    """Interpret on non-TPU backends so the suite exercises the kernel."""
    return jax.default_backend() != "tpu"
