"""Message payload packing — TPU equivalent of the reference compiler's
behaviour message pack/unpack (reference: src/libponyc/codegen/genfun.c emits
a pony_msg_t subtype per behaviour and packs arguments into it; the dispatch
switch unpacks them).

Here every message on the wire is a fixed vector of int32 words:
``[behaviour_id, arg0, arg1, ...]``. Typed arguments (f32, i32, bool,
ActorRef) are bitcast into words according to the behaviour's signature
annotations, and bitcast back at dispatch. Keeping the transport monomorphic
is what lets mailboxes live as one dense [N, cap, words] HBM array.
"""

from __future__ import annotations

import jax.numpy as jnp


class I32:
    """Marker annotation: 32-bit signed integer argument."""


class F32:
    """Marker annotation: 32-bit float argument (bitcast into an i32 lane)."""


class Bool:
    """Marker annotation: boolean argument."""


class Ref:
    """Marker annotation: actor reference (global actor id, i32)."""


_MARKERS = (I32, F32, Bool, Ref)


def normalize_annotation(ann):
    """Map a user annotation to one of the marker classes."""
    if ann in _MARKERS:
        return ann
    if ann in (int, jnp.int32, "int", "I32", "i32"):
        return I32
    if ann in (float, jnp.float32, "float", "F32", "f32"):
        return F32
    if ann in (bool, jnp.bool_, "bool", "Bool"):
        return Bool
    if ann in ("Ref", "ActorRef"):
        return Ref
    raise TypeError(f"unsupported behaviour argument annotation: {ann!r}")


def pack_arg(ann, value):
    """Encode one argument into an int32 word (trace-time, scalar)."""
    if ann is F32:
        return jnp.asarray(value, jnp.float32).view(jnp.int32)
    if ann is Bool:
        return jnp.asarray(value, jnp.bool_).astype(jnp.int32)
    return jnp.asarray(value, jnp.int32)


def unpack_arg(ann, word):
    """Decode one int32 word back to its annotated type."""
    if ann is F32:
        return word.view(jnp.float32)
    if ann is Bool:
        return word.astype(jnp.bool_)
    return word


def pack_args(specs, values, msg_words):
    """Pack positional args into a [msg_words] (or planar [msg_words, R])
    int32 array, zero padded. Args may mix trace-time constants (scalars)
    with [R]-lane vectors — the planar engine evaluates behaviours on all
    R actors of a cohort at once — so words broadcast to a common shape
    before stacking on the (small, major) word axis."""
    if len(values) != len(specs):
        raise TypeError(f"behaviour takes {len(specs)} args, got {len(values)}")
    if len(specs) > msg_words:
        raise TypeError(
            f"behaviour needs {len(specs)} payload words but msg_words="
            f"{msg_words}; raise RuntimeOptions.msg_words")
    words = [pack_arg(a, v) for a, v in zip(specs, values)]
    words += [jnp.int32(0)] * (msg_words - len(words))
    if len(words) > 1:
        words = jnp.broadcast_arrays(*words)
    return jnp.stack(words)


def unpack_args(specs, words):
    """Inverse of pack_args; returns a tuple of typed scalars."""
    return tuple(unpack_arg(a, words[i]) for i, a in enumerate(specs))
