"""Message payload packing — TPU equivalent of the reference compiler's
behaviour message pack/unpack (reference: src/libponyc/codegen/genfun.c emits
a pony_msg_t subtype per behaviour and packs arguments into it; the dispatch
switch unpacks them).

Here every message on the wire is a fixed vector of int32 words:
``[behaviour_id, arg0, arg1, ...]``. Typed arguments (f32, i32, bool,
ActorRef) are bitcast into words according to the behaviour's signature
annotations, and bitcast back at dispatch. Keeping the transport monomorphic
is what lets mailboxes live as one dense [N, cap, words] HBM array.
"""

from __future__ import annotations

import jax.numpy as jnp


class I32:
    """Marker annotation: 32-bit signed integer argument."""


class F32:
    """Marker annotation: 32-bit float argument (bitcast into an i32 lane)."""


class Bool:
    """Marker annotation: boolean argument."""


class U32:
    """Marker annotation: 32-bit unsigned integer (bit-reinterpreted into
    the i32 message word; behaviours receive a uint32 array).

    ≙ the reference's builtin numerics breadth (packages/builtin U8..U128,
    I8..I128): the widths offered here are the ones TPU device compute
    handles honestly without 64-bit emulation — U32/U16/U8/I16/I8 ride a
    single i32 word each; 64/128-bit integer types are host-side Python
    ints (arbitrary precision), a documented divergence."""


class I16:
    """Marker annotation: 16-bit signed integer (wraps to i16 range)."""


class U16:
    """Marker annotation: 16-bit unsigned integer."""


class I8:
    """Marker annotation: 8-bit signed integer (wraps to i8 range)."""


class U8:
    """Marker annotation: 8-bit unsigned integer."""


# Single source of truth for the narrow/unsigned single-word specs:
# marker -> (jnp dtype, numpy dtype name). runtime.py's host pack path
# derives its numpy map from this.
_NARROW_JNP = {U32: jnp.uint32, I16: jnp.int16, U16: jnp.uint16,
               I8: jnp.int8, U8: jnp.uint8}


_NARROW_NP_CACHE = None


def narrow_np_map():
    global _NARROW_NP_CACHE
    if _NARROW_NP_CACHE is None:
        import numpy as _np
        _NARROW_NP_CACHE = {
            m: _np.dtype(dt.dtype if hasattr(dt, "dtype") else dt).type
            for m, dt in _NARROW_JNP.items()}
    return _NARROW_NP_CACHE


class _RefTo:
    """A typed actor-reference annotation: Ref[SomeActor].

    ≙ the reference type system's *typed* actor references — the compiler
    knows every ref's receiving type (type/cap.c, type/subtype.c) and
    rejects sends the type can't receive (expr/call.c). Here the
    sendability checker (see api.Context.send and Runtime.send) enforces
    the same wiring rule at trace/build time instead of badmsg-ing at
    runtime. `target` may be the actor class or its name (forward ref)."""

    __slots__ = ("target",)

    def __init__(self, target):
        self.target = target

    @property
    def target_name(self) -> str:
        t = self.target
        return t if isinstance(t, str) else t.__name__

    @property
    def __name__(self) -> str:      # for structural fingerprints
        return f"Ref[{self.target_name}]"

    def __repr__(self):
        return self.__name__


class Ref:
    """Marker annotation: actor reference (global actor id, i32).

    Bare `Ref` is untyped (gradual — no wiring check); `Ref[SomeActor]`
    is typed and send/spawn wiring is verified (see _RefTo)."""

    def __class_getitem__(cls, item):
        return _RefTo(item)


class TypeParam:
    """A type parameter for generic actor types (≙ the reference's
    formal type parameters; reify.c substitutes them at instantiation).

        T = TypeParam("T")

        @actor
        class Cell:
            value: T
            @behaviour
            def put(self, st, v: T): ...

        IntCell = Cell[I32]        # reified (api.ActorTypeMeta)

    A generic (unreified) actor type cannot be declared/spawned — its
    layout is unknown until every parameter is substituted, exactly as
    the reference only code-gens reified types (reach.c walks concrete
    reifications)."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = str(name)

    @property
    def __name__(self) -> str:
        return self.name

    def __repr__(self):
        return f"TypeParam({self.name!r})"

    # Identity is the NAME (two TypeParam("A") spellings are the same
    # formal parameter — ≙ the reference resolving type params by name
    # within a type's scope).
    def __eq__(self, other):
        return isinstance(other, TypeParam) and other.name == self.name

    def __hash__(self):
        return hash(("TypeParam", self.name))


def substitute(spec, mapping):
    """Reification: replace TypeParams inside a spec (reify.c's type
    substitution, flattened to this framework's spec grammar)."""
    if isinstance(spec, TypeParam):
        try:
            return mapping[spec]
        except KeyError:
            raise TypeError(
                f"unbound type parameter {spec.name!r}") from None
    if isinstance(spec, _RefTo) and isinstance(spec.target, TypeParam):
        got = mapping.get(spec.target)
        if got is None:
            raise TypeError(f"unbound type parameter "
                            f"{spec.target.name!r} in {spec!r}")
        # Ref[T] reifies to a typed ref of the argument, which must
        # itself be an actor type (or its name).
        if isinstance(got, _RefTo):
            return got
        if isinstance(got, str) or isinstance(got, type):
            return _RefTo(got)
        raise TypeError(
            f"Ref[{spec.target.name}] needs an actor type argument, "
            f"got {got!r}")
    return spec


def type_params_of(specs) -> tuple:
    """Ordered first-appearance TypeParams across an iterable of specs."""
    seen = []
    for spec in specs:
        p = None
        if isinstance(spec, TypeParam):
            p = spec
        elif isinstance(spec, _RefTo) and isinstance(spec.target, TypeParam):
            p = spec.target
        if p is not None and p not in seen:
            seen.append(p)
    return tuple(seen)


class _CapSpec:
    """Host-payload capability annotation — the full six-cap lattice of
    the reference (src/libponyc/type/cap.c:1, safeto.c:1, alias.c:1,
    viewpoint.c:1):

    - ``Iso`` — moved-unique (read+write, no aliases): the message MOVES
      the payload; the sender provably loses access. Trace-time
      discipline (api.Context.send + engine.eval_behaviour) rejects
      aliased moves (same handle sent twice in one dispatch),
      use-after-move, and retained-after-move (returning a moved handle
      in state). Dynamically, HostHeap handles are move-only (unbox
      consumes) and in-flight handles reject peek/unbox.
    - ``Trn`` — transition (write-unique, read-aliasable): one writer;
      read-only ``Box`` views may alias it. NOT sendable. A store into
      a Trn/Mut/Val slot CONSUMES it (≙ `consume` — trn→val is Pony's
      freeze); a store into Box/Tag aliases it.
    - ``Mut`` — locally mutable, freely aliasable within the actor
      (≙ Pony's ``ref``; renamed here because `Ref` is this framework's
      actor-reference annotation). NOT sendable.
    - ``Val`` — shared-immutable: anyone may read (peek), nobody may
      take ownership (unbox rejects); aliasing freely allowed. Sendable.
    - ``Box`` — read-only view (≙ box): may read, never write; the
      local "either val or ref underneath" window. NOT sendable.
    - ``Tag`` — opaque address: identity/forwarding only; peek AND
      unbox reject. Sendable.

    Only {iso, val, tag} may cross an actor boundary (message/ctor
    parameters) — exactly the reference's CAP_SEND set
    (type/cap.c:90, safeto.c). The wire word is a HostHeap handle
    (i32); the mode governs the trace-time move/alias discipline and
    the dynamic handle rules (hostmem.py)."""

    __slots__ = ("mode",)

    _NAMES = {"iso": "Iso", "trn": "Trn", "ref": "Mut", "val": "Val",
              "box": "Box", "tag": "Tag"}

    def __init__(self, mode: str):
        self.mode = mode

    @property
    def __name__(self) -> str:
        return self._NAMES[self.mode]

    def __repr__(self):
        return self.__name__


Iso = _CapSpec("iso")
Trn = _CapSpec("trn")
Mut = _CapSpec("ref")      # ≙ Pony `ref` (the name Ref is taken by actor refs)
Val = _CapSpec("val")
Box = _CapSpec("box")
Tag = _CapSpec("tag")


class _BlobSpec(_CapSpec):
    """Device blob handle annotation (``Blob`` / ``BlobVal``).

    ≙ the reference's rich message payloads that live on an ACTOR HEAP
    and ride messages by pointer (pony_alloc_msg + gc trace,
    pony.h:332-360; genfun.c packs a pony_msg_t per behaviour) — here
    the "heap" is the device-resident blob pool
    (RuntimeOptions.blob_slots × blob_words, runtime/state.py) and the
    "pointer" is a global blob handle (i32; -1 = null).

    ``Blob`` (mode iso): exactly ONE owner; sending the handle is a
    MOVE (the full trace-time move/alias discipline of Iso applies);
    the owner reads/writes/frees it via ctx.blob_* (api.Context).

    ``BlobVal`` (mode val, ≙ Pony's ubiquitous `String val`/`Array
    val` payloads): shared-immutable after ctx.blob_freeze(h) — the
    handle aliases freely (one dispatch may send it to MANY readers),
    writes and frees reject at trace, and the slot is reclaimed by the
    GC mark pass when no live field/message references it. Across a
    mesh, a val blob COPIES with each routed message (readers on other
    shards get their own immutable replica; each shard's sweep
    collects its copy) where an iso blob MOVES.

    Unlike Iso/Val HostHeap handles (host round-trip to touch), blob
    words are readable INSIDE device behaviours."""

    @property
    def __name__(self) -> str:          # noqa: A003
        return "Blob" if self.mode == "iso" else "BlobVal"


Blob = _BlobSpec("iso")
BlobVal = _BlobSpec("val")


def is_blob(ann) -> bool:
    """Is this annotation a device blob handle (either mode)?"""
    return isinstance(ann, _BlobSpec)


def is_blob_val(ann) -> bool:
    """Is this a shared-immutable (val) blob annotation?"""
    return isinstance(ann, _BlobSpec) and ann.mode == "val"


def null_word(ann) -> int:
    """The "no value" word for a spec: -1 for actor refs and blob
    handles (0 is a real id for both), 0 otherwise."""
    return -1 if (is_ref(ann) or is_blob(ann)) else 0


# Blob handle encoding: low bits = global pool slot, high bits = the
# slot's GENERATION at alloc time (state.blob_gen, bumped per alloc).
# A handle whose generation mismatches its slot's current one is dead —
# a stale/forged reference to a recycled slot reads null instead of the
# new owner's words (ABA protection; wraps after 2^10 reuses, so a
# handle held across exactly k*1024 reuses of its slot could
# false-validate — documented, not defended). Works on np and jnp ints;
# -1 decodes to an out-of-range slot, so null handles stay invalid.
BLOB_GEN_SHIFT = 20          # pool addressing: shards*blob_slots < 2^20
BLOB_GEN_MASK = 0x3FF        # 10 generation bits


def blob_slot(h):
    return h & ((1 << BLOB_GEN_SHIFT) - 1)


def blob_gen_of(h):
    return (h >> BLOB_GEN_SHIFT) & BLOB_GEN_MASK


def blob_handle(slot, gen):
    return ((gen & BLOB_GEN_MASK) << BLOB_GEN_SHIFT) | slot

# ≙ TK_CAP_SEND {iso, val, tag} (type/cap.c:90): the caps a value may
# carry across an actor boundary.
SENDABLE_CAPS = frozenset(("iso", "val", "tag"))


def cap_mode(ann):
    """'iso'/'trn'/'ref'/'val'/'box'/'tag' for capability specs, else
    None."""
    return ann.mode if isinstance(ann, _CapSpec) else None


def cap_sendable(mode) -> bool:
    """May a value of this mode ride a message parameter?
    (≙ safeto.c sendability; None = uncapped word, always fine.)"""
    return mode is None or mode in SENDABLE_CAPS


def cap_alias(mode):
    """The capability of an ALIAS of a value (≙ cap_aliasing with
    TK_ALIASED, type/alias.c): iso aliases as tag (the unique original
    keeps its rights), trn aliases as box (write-uniqueness preserved),
    everything else aliases as itself."""
    return {"iso": "tag", "trn": "box"}.get(mode, mode)


def viewpoint(origin, field):
    """Viewpoint adaptation origin▷field (≙ cap_view_upper,
    type/cap.c:581-711, concrete caps, non-ephemeral): the capability a
    reader holding `origin` sees when reading a `field`-capped slot.
    Returns None when the origin cannot read at all (tag origin)."""
    if origin is None or field is None:
        return field             # gradual: uncapped side ⇒ no adaptation
    if origin == "tag":
        return None              # can't see through a tag (cap.c:588-596)
    if field == "tag":
        return "tag"             # a tag is always seen as a tag
    if origin == "iso":
        return {"iso": "iso", "val": "val"}.get(field, "tag")
    if origin == "trn":
        return {"iso": "iso", "trn": "trn", "val": "val"}.get(field, "box")
    if origin == "ref":
        return field             # ref▷T = T
    if origin == "val":
        return "val"
    if origin == "box":
        return {"iso": "tag", "val": "val"}.get(field, "box")
    raise ValueError(f"unknown capability mode {origin!r}")


def concrete_null_handle(a) -> bool:
    """True when `a` is a CONCRETE non-positive value — the blessed
    'no handle' sentinels (0/-1, hostmem.py). These are exempt from the
    iso-move discipline: CPython interns small ints, so two -1 literals
    share id() and would otherwise trip a spurious aliased-move."""
    try:
        return int(a) <= 0
    except Exception:                     # noqa: BLE001 — traced/vector
        return False


# The store lattice (≙ is_cap_sub_cap, type/cap.c:59-160, all six
# caps): a value of mode SRC may be stored where DST is declared when
# SRC's rights cover DST's. Unique caps store as MOVES (consume):
# iso^ is sub of everything; trn^ of everything but iso (cap.c:99-113,
# trn→val being Pony's freeze). The alias caps follow the sub chains
# ref <: box, val <: box, box <: tag exactly (cap.c:115-160; super tag
# always true, cap.c:73-74).
_CAP_STORE_OK = {
    "iso": {"iso", "trn", "ref", "val", "box", "tag"},   # moved (iso^)
    "trn": {"trn", "ref", "val", "box", "tag"},          # moved (trn^)
    "ref": {"ref", "box", "tag"},
    "val": {"val", "box", "tag"},
    "box": {"box", "tag"},
    "tag": {"tag"},
}

# The dst caps whose store CONSUMES a unique src (ownership/write
# rights transfer): everything that grants more than read-alias rights.
# A trn stored into box/tag merely aliases (read view / address) and
# the original stays writable — ≙ trn <: box needing no consume.
CONSUMING_DSTS = frozenset(("iso", "trn", "ref", "val"))


def cap_store_ok(src_mode, dst_mode) -> bool:
    """May a value of src_mode be stored into a dst_mode slot?
    Unknown provenance (None) is gradual — allowed."""
    if src_mode is None or dst_mode is None:
        return True
    return dst_mode in _CAP_STORE_OK[src_mode]


class CapMoves:
    """Trace-time iso-move discipline (≙ the consume/alias analysis of
    type/alias.c + safeto.c, re-expressed at the trace boundary).

    Tracks moved iso payloads by tracer identity, like pack.RefTypes:
    directly-forwarded values are checked; derived values (jnp.where,
    arithmetic) are untyped again — gradual, never breaks array code."""

    __slots__ = ("_moved",)

    def __init__(self):
        self._moved = {}          # id(obj) → (obj, where-description)

    def move(self, obj, where: str):
        ent = self._moved.get(id(obj))
        if ent is not None:
            raise TypeError(
                f"capability: iso payload moved twice (aliased move) — "
                f"first by {ent[1]}, again by {where}; an iso is "
                "moved-unique (send it once, or box it Val for sharing)")
        self._moved[id(obj)] = (obj, where)

    def was_moved(self, obj):
        ent = self._moved.get(id(obj))
        return ent[1] if ent is not None else None


class _VecSpec:
    """A fixed-width vector argument: VecF32[k] / VecI32[k].

    ≙ the reference's rich message payloads (pony_alloc_msg + per-type
    serialise trace, pony.h:332-360): a Pony message carries arbitrary
    object payloads; here small arrays ride INSIDE the fixed message
    words (k consecutive int32 lanes, bitcast for floats) — the
    TPU-idiomatic equivalent, since mailboxes are one dense static-shape
    table. Behaviours receive the argument as a [k, ...lanes] planar
    block (actor lanes minor — reduce over axis 0 for per-actor dots/
    norms)."""

    __slots__ = ("base", "n")

    def __init__(self, base, n: int):
        self.base = base
        self.n = int(n)
        if self.n < 1:
            raise TypeError("vector width must be >= 1")

    @property
    def __name__(self) -> str:
        return f"Vec{self.base.__name__}[{self.n}]"

    def __repr__(self):
        return self.__name__


class VecF32:
    """Annotation: [k] float32 vector payload — VecF32[k]."""

    def __class_getitem__(cls, n):
        return _VecSpec(F32, n)


class VecI32:
    """Annotation: [k] int32 vector payload — VecI32[k]."""

    def __class_getitem__(cls, n):
        return _VecSpec(I32, n)


def spec_width(ann) -> int:
    """Payload words an argument occupies."""
    return ann.n if isinstance(ann, _VecSpec) else 1


def is_ref(ann) -> bool:
    return ann is Ref or isinstance(ann, _RefTo)


def ref_target(ann):
    """The declared target type name of a typed ref, else None."""
    return ann.target_name if isinstance(ann, _RefTo) else None


class RefTypes:
    """Trace-time provenance map: traced-array object → declared ref type.

    Typed refs stay PLAIN int32 arrays (so every jnp op works untouched);
    the type tag rides on the tracer's *identity*. A behaviour that
    forwards st['out'] or a Ref[T] argument unchanged keeps its type; any
    derived value (jnp.where, arithmetic) is simply untyped again —
    checking is gradual, and can never break user array code.

    Entries hold a strong reference to the tagged object so its id cannot
    be recycled within the trace."""

    __slots__ = ("_m",)

    def __init__(self):
        self._m = {}          # id(obj) → (obj, target_name)

    def tag(self, obj, target_name):
        if target_name is not None:
            self._m[id(obj)] = (obj, target_name)
        return obj

    def lookup(self, obj):
        ent = self._m.get(id(obj))
        return ent[1] if ent is not None else None


class CapTypes(RefTypes):
    """Capability provenance map — the cap half of RefTypes, same
    identity-keyed mechanics (tag/lookup over id with strong pinning):
    values that arrived through an Iso/Val/Tag-annotated parameter or
    field carry their mode, so stores and parameter passes check
    against the declared mode (cap_store_ok)."""


_MARKERS = (I32, F32, Bool, Ref, U32, I16, U16, I8, U8)


def normalize_annotation(ann):
    """Map a user annotation to a marker class (or typed-ref / vector /
    capability instance)."""
    if isinstance(ann, (_RefTo, _VecSpec, _CapSpec, TypeParam)):
        return ann
    if isinstance(ann, str) and ann in ("Iso", "Trn", "Mut", "Val",
                                        "Box", "Tag", "Blob", "BlobVal"):
        return {"Iso": Iso, "Trn": Trn, "Mut": Mut, "Val": Val,
                "Box": Box, "Tag": Tag, "Blob": Blob,
                "BlobVal": BlobVal}[ann]
    if ann in _MARKERS:
        return ann
    if isinstance(ann, str) and ann.endswith("]"):
        for prefix, base in (("VecF32[", F32), ("VecI32[", I32)):
            if ann.startswith(prefix):
                try:
                    n = int(ann[len(prefix):-1])
                except ValueError:
                    break    # symbolic width → the TypeError below, which
                    #          names the annotation (string annotations
                    #          can't resolve module constants)
                return _VecSpec(base, n)
    if ann in (int, jnp.int32, "int", "I32", "i32"):
        return I32
    if ann in (float, jnp.float32, "float", "F32", "f32"):
        return F32
    if ann in (bool, jnp.bool_, "bool", "Bool"):
        return Bool
    narrow_alias = {"U32": U32, "u32": U32, jnp.uint32: U32,
                    "I16": I16, "i16": I16, jnp.int16: I16,
                    "U16": U16, "u16": U16, jnp.uint16: U16,
                    "I8": I8, "i8": I8, jnp.int8: I8,
                    "U8": U8, "u8": U8, jnp.uint8: U8}
    try:
        if ann in narrow_alias:
            return narrow_alias[ann]
    except TypeError:
        pass                       # unhashable annotation → fall through
    if ann in ("Ref", "ActorRef"):
        return Ref
    if isinstance(ann, str) and ann.startswith("Ref[") and ann.endswith("]"):
        return _RefTo(ann[4:-1].strip().strip("'\""))
    raise TypeError(f"unsupported behaviour argument annotation: {ann!r}")


def pack_arg(ann, value):
    """Encode one argument into an int32 word (trace-time, scalar)."""
    if ann is F32:
        return jnp.asarray(value, jnp.float32).view(jnp.int32)
    if ann is Bool:
        return jnp.asarray(value, jnp.bool_).astype(jnp.int32)
    if ann in _NARROW_JNP:
        dt = _NARROW_JNP[ann]
        # Out-of-range CONCRETE values must WRAP to the declared width
        # (jnp.asarray(value, dt) would raise OverflowError under
        # NumPy 2) — wrap them host-side through int64; traced values are
        # already i32-width, where astype wraps natively.
        if not hasattr(value, "aval"):
            import numpy as _np
            value = _np.asarray(value, _np.int64).astype(
                narrow_np_map()[ann])
        v = jnp.asarray(value).astype(dt)
        if dt is jnp.uint32:
            return v.view(jnp.int32)     # bit-reinterpret, value preserved
        return v.astype(jnp.int32)       # widen (sign/zero extend)
    return jnp.asarray(value, jnp.int32)


def unpack_arg(ann, word):
    """Decode one int32 word back to its annotated type. (Typed-ref args
    stay plain arrays; the caller tags them in a RefTypes map.)"""
    if ann is F32:
        return word.view(jnp.float32)
    if ann is Bool:
        return word.astype(jnp.bool_)
    if ann in _NARROW_JNP:
        dt = _NARROW_JNP[ann]
        if dt is jnp.uint32:
            return word.view(jnp.uint32)
        return word.astype(dt)           # truncate back to declared width
    return word


def pack_args(specs, values, msg_words):
    """Pack positional args into a [msg_words] (or planar [msg_words, R])
    int32 array, zero padded. Args may mix trace-time constants (scalars)
    with [R]-lane vectors — the planar engine evaluates behaviours on all
    R actors of a cohort at once — and VecF32/VecI32 args contribute
    their k words as a block; everything broadcasts to a common lane
    shape before concatenating on the (small, major) word axis."""
    if len(values) != len(specs):
        raise TypeError(f"behaviour takes {len(specs)} args, got {len(values)}")
    total = sum(spec_width(a) for a in specs)
    if total > msg_words:
        raise TypeError(
            f"behaviour needs {total} payload words but msg_words="
            f"{msg_words}; raise RuntimeOptions.msg_words")
    parts = []
    for a, v in zip(specs, values):
        if isinstance(a, _VecSpec):
            dt = jnp.float32 if a.base is F32 else jnp.int32
            arr = jnp.asarray(v, dt)
            if arr.ndim == 0 or arr.shape[0] != a.n:
                raise TypeError(
                    f"argument for {a.__name__} must have leading dim "
                    f"{a.n}, got shape {arr.shape}")
            parts.append(arr.view(jnp.int32) if a.base is F32
                         else arr.astype(jnp.int32))
        else:
            w = pack_arg(a, v)
            parts.append(w.reshape((1,) + w.shape))
    lanes = jnp.broadcast_shapes(*(p.shape[1:] for p in parts)) \
        if parts else ()
    # Align trailing (lane) axes before broadcasting, so a trace-time
    # constant vector (shape [k]) can ride next to lane-varying args
    # (shape [k', R]): [k] → [k, 1, ...] → [k, R, ...].
    parts = [jnp.broadcast_to(
        p.reshape(p.shape[:1] + (1,) * (len(lanes) - (p.ndim - 1))
                  + p.shape[1:]),
        p.shape[:1] + lanes) for p in parts]
    if total < msg_words:
        parts.append(jnp.zeros((msg_words - total,) + lanes, jnp.int32))
    return jnp.concatenate(parts, axis=0)


def unpack_args(specs, words):
    """Inverse of pack_args; scalars per spec, [k, ...lanes] blocks for
    vector specs."""
    out = []
    off = 0
    for a in specs:
        if isinstance(a, _VecSpec):
            blk = words[off:off + a.n]
            out.append(blk.view(jnp.float32) if a.base is F32 else blk)
            off += a.n
        else:
            out.append(unpack_arg(a, words[off]))
            off += 1
    return tuple(out)
