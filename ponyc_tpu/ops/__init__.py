"""Low-level ops: payload packing, segment primitives, and the Pallas
kernels for the dispatch/delivery hot path — mailbox_kernel (drain),
fused_dispatch (drain+behaviour+outbox), megakernel (the whole gated
window in one persistent kernel + the int16/escape-plane record
codec, PROFILE.md §14)."""
