"""Low-level ops: payload packing, segment primitives, (later) Pallas
kernels for the dispatch/delivery hot path."""
