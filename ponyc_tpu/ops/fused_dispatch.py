"""Fused Pallas dispatch kernel: drain + behaviour + outbox in ONE pass.

≙ the whole of ponyint_actor_run's hot loop (src/libponyrt/actor/
actor.c:383-549) for one cohort — message pop, dispatch into the
behaviour body, and the send path's message construction — executed as
a single TPU kernel over lane blocks. This is the kernel BASELINE.json's
north star names ("actor state + mailboxes laid out struct-of-arrays in
HBM and behaviour dispatch run as a vmapped/Pallas kernel"): one grid
step pulls a [cap, w1, LB] mailbox tile and the cohort's state lanes
into VMEM ONCE, iterates the batch slots in-register, evaluates the
(traced, planar) behaviour body on the lanes, and writes the new state,
outbox planes and head advance — where the XLA path makes `batch`
separate select-chain passes over the mailbox block plus materialised
scan intermediates.

Eligibility (checked by `eligible()` — everything else falls back to
the XLA path, same semantics):
  - no SYNC-construction across the cohort's behaviours (its per-site
    field-value packaging is host-assembled). destroy(), error_int()
    AND device spawns ARE hosted: destroy/error flags ride out as lane
    planes exactly like exit, and spawns take reservation planes in /
    claim planes out with a per-lane used-counter walk (round 5).
    Multi-behaviour cohorts are fine: the kernel evaluates every
    behaviour on the lanes and selects per lane by message id, exactly
    like the XLA scan;
  - behaviour body uses only elementwise/lane ops. This is the API
    contract anyway — a behaviour describes ONE actor's reaction, so
    lane-crossing ops (reductions over the cohort) have no defined
    meaning in either formulation; under the fused kernel they would
    additionally see only their 1024-lane grid block. Not statically
    detectable, hence contract + documentation, like vmap's own
    semantics.

Gating: `RuntimeOptions.pallas_fused` (off by default until measured on
the real chip; interpret mode exercises the kernel on CPU in the suite).
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


LANE_BLOCK = 1024


def eligible(cohort, effects, opts) -> bool:
    """Structural + trace-discovered preconditions for the fused path.
    destroy/error AND device spawns are hosted (reservation planes ride
    in, claim planes ride out — ≙ pony_create from a behaviour,
    actor.c:688-734); only synchronous construction still needs the XLA
    path (its per-site field-value packaging is host-assembled)."""
    return (len(cohort.behaviours) >= 1
            and not effects["sync_init"])


def _slim_branch(bdef, field_specs, field_dtypes, msg_words, ms, lanes,
                 spawn_sites=(), spawn_meta=None):
    """The planar behaviour evaluator for eligible cohorts: the SAME
    shared core as the XLA path (engine.eval_behaviour — one
    implementation, so the two formulations cannot drift), emitting
    exit/yield/destroy/error lane planes plus per-(target, site) spawn
    claim planes; only the sync-construction packaging eligibility
    excludes is absent."""

    def branch(st, payload, ids_vec, resv_k):
        from ..runtime.engine import eval_behaviour
        ctx, st2, tgts, words = eval_behaviour(
            bdef, st, payload, ids_vec, msg_words=msg_words,
            field_specs=field_specs, field_dtypes=field_dtypes,
            lanes=lanes, max_sends=ms, spawn_resv=resv_k,
            spawn_meta=spawn_meta)
        b = jnp.bool_
        bc = lambda v, d: jnp.broadcast_to(       # noqa: E731
            jnp.asarray(v, d), (lanes,))
        claims = []
        for tname, n in spawn_sites:
            got = [bc(g, jnp.int32)
                   for g in ctx.spawn_claims.get(tname, [])]
            got += [jnp.full((lanes,), -1, jnp.int32)] * (n - len(got))
            claims.append(got)
        return (st2, tgts, words,
                bc(ctx.exit_flag, b), bc(ctx.exit_code, jnp.int32),
                bc(ctx.yield_flag, b),
                bc(ctx.destroy_flag, b),
                bc(ctx.error_flag, b), bc(ctx.error_code, jnp.int32),
                bc(ctx.error_loc, jnp.int32),
                claims, bc(ctx.spawn_fail, b))

    return branch


def build_fused_dispatch(bdefs, *, base_gid: int, field_names: Sequence[str],
                         field_dtypes, field_specs, batch: int, cap: int,
                         msg_words: int, ms: int, rows: int,
                         noyield: bool, interpret: bool,
                         msg_words_in: int = None,
                         spawn_sites=(), spawn_meta=None,
                         spawn_dispatches: int = 1):
    """Returns fn(fields_tuple, buf, head, n_run, ids, resv_tuple) →
    (new_fields_tuple, out_tgt [batch*ms*rows], out_words [w1, b*ms*rows],
    new_head [rows], nproc [rows], nbad [rows], ef [rows], ec [rows],
    ds [rows], erf [rows], erc [rows], erl [rows],
    claims_tuple (per spawn target: [batch*sites, rows]), sfail [rows])
    with EXACTLY the XLA path's semantics (engine busy_fn ordering:
    entry (k, m, r) flattens k-major, then send slot, then lane; exit =
    first wins, error = latest wins, destroy ORs across the batch;
    spawn reservations walk the SPAWN_DISPATCHES axis by a per-lane
    `used` counter, exhausted budget → sticky spawn_fail).

    msg_words is the OUTBOX width (program-wide max); msg_words_in the
    cohort's own mailbox width (per-type pony_msg_t, genfun.c) — the
    mailbox tile read is [cap, 1+msg_words_in, LB]. resv_tuple holds,
    per spawn target (spawn_sites order), a [sd*sites, rows] int32
    reservation plane block."""
    if msg_words_in is None:
        msg_words_in = msg_words
    w1 = 1 + msg_words
    w1_in = 1 + msg_words_in
    sd = spawn_dispatches
    lb = min(LANE_BLOCK, rows)
    assert rows % lb == 0, (rows, lb)
    nf = len(field_names)
    n_sp = len(spawn_sites)
    branches = [_slim_branch(b, field_specs, field_dtypes, msg_words, ms,
                             lb, spawn_sites=spawn_sites,
                             spawn_meta=spawn_meta) for b in bdefs]
    nb = len(branches)

    def kernel(head_ref, nrun_ref, ids_ref, *refs):
        field_refs = refs[:nf]
        buf_ref = refs[nf]
        resv_refs = refs[nf + 1:nf + 1 + n_sp]
        o0 = nf + 1 + n_sp
        out_field_refs = refs[o0:o0 + nf]
        after = refs[o0 + nf:]
        # Output order MUST mirror out_specs: fields, outbox, claims,
        # then the lane planes.
        if ms:
            tgt_ref, words_ref = after[0], after[1]
            after = after[2:]
        else:                         # send-less cohort: no outbox planes
            tgt_ref = words_ref = None
        claims_refs = after[:n_sp]
        (nh_ref, np_ref, nb_ref, ef_ref, ec_ref, ds_ref, erf_ref,
         erc_ref, erl_ref, sf_ref) = after[n_sp:]
        head = head_ref[0]
        nrun = nrun_ref[0]
        ids = ids_ref[0]
        st = {name: field_refs[i][0]
              for i, name in enumerate(field_names)}
        stopped = jnp.zeros((lb,), jnp.bool_)
        ef = jnp.zeros((lb,), jnp.bool_)
        ec = jnp.zeros((lb,), jnp.int32)
        dstr = jnp.zeros((lb,), jnp.bool_)
        erf = jnp.zeros((lb,), jnp.bool_)
        erc = jnp.zeros((lb,), jnp.int32)
        erl = jnp.zeros((lb,), jnp.int32)
        sfail = jnp.zeros((lb,), jnp.bool_)
        used = jnp.zeros((lb,), jnp.int32)
        nproc = jnp.zeros((lb,), jnp.int32)
        nbad = jnp.zeros((lb,), jnp.int32)
        consumed = jnp.zeros((lb,), jnp.int32)
        for k in range(batch):
            slot = (head + k) % cap
            msg = buf_ref[0]                     # [w1_in, LB]
            for c in range(1, cap):
                msg = jnp.where((slot == c)[None, :], buf_ref[c], msg)
            valid = (nrun > k)
            do_any = valid & ~stopped
            local = msg[0] - base_gid
            in_range = (local >= 0) & (local < nb)
            do = do_any & in_range
            # This slot's spawn reservations: the `used` counter walks
            # the SPAWN_DISPATCHES axis exactly like the XLA scan —
            # exhausted budget yields -1 refs (sticky spawn_fail, never
            # a double claim).
            resv_k = {}
            for si, (tname, n_sites) in enumerate(spawn_sites):
                rr = resv_refs[si]               # [sd*sites, LB]
                sel = jnp.full((n_sites, lb), -1, jnp.int32)
                for d in range(sd):
                    blk = jnp.concatenate(
                        [rr[d * n_sites + s][None, :]
                         for s in range(n_sites)])
                    sel = jnp.where((used == d)[None, :], blk, sel)
                resv_k[tname] = sel
            # Evaluate every behaviour on the lanes, select per lane by
            # its message id — the same planar select the XLA scan does.
            acc_tgt = [jnp.full((lb,), -1, jnp.int32)
                       for _ in range(ms)]
            acc_words = [jnp.zeros((w1, lb), jnp.int32)
                         for _ in range(ms)]
            acc_claims = [[jnp.full((lb,), -1, jnp.int32)
                           for _ in range(n)] for _, n in spawn_sites]
            slot_sf = jnp.zeros((lb,), jnp.bool_)
            for j, branch in enumerate(branches):
                take = do & (local == j)
                (st2, tgts, words, bef, bec, byf, bds, berf, berc,
                 berl, bclm, bsf) = branch(st, msg[1:], ids, resv_k)
                for i, name in enumerate(field_names):
                    st[name] = jnp.where(take, st2[name], st[name])
                for m in range(ms):
                    acc_tgt[m] = jnp.where(take, tgts[m], acc_tgt[m])
                    acc_words[m] = jnp.where(take[None, :], words[m],
                                             acc_words[m])
                for si in range(n_sp):
                    for s in range(len(acc_claims[si])):
                        acc_claims[si][s] = jnp.where(
                            take, bclm[si][s], acc_claims[si][s])
                slot_sf = jnp.where(take, bsf, slot_sf)
                new_ef = take & bef
                ec = jnp.where(new_ef & ~ef, bec, ec)
                ef = ef | new_ef
                dstr = dstr | (take & bds)
                # Error: the LATEST error's code/loc wins (the XLA
                # scan's jnp.where(erf_n, ...) ordering).
                n_err = take & berf
                erc = jnp.where(n_err, berc, erc)
                erl = jnp.where(n_err, berl, erl)
                erf = erf | n_err
                if not noyield:
                    stopped = stopped | (take & byf)
            for m in range(ms):
                tgt_ref[k * ms + m] = acc_tgt[m]
                for w in range(w1):
                    words_ref[(k * ms + m) * w1 + w] = acc_words[m][w]
            # Claims out (plane k*sites+s ≙ the XLA [batch, sites, rows]
            # stack) + the used-counter walk (a failed WANTED spawn
            # advances the window too, like the scan's sf_n | claims).
            spawned = slot_sf
            for si in range(n_sp):
                n_sites = len(acc_claims[si])
                for s in range(n_sites):
                    claims_refs[si][k * n_sites + s] = acc_claims[si][s]
                    spawned = spawned | (acc_claims[si][s] >= 0)
            used = used + spawned.astype(jnp.int32)
            sfail = sfail | slot_sf
            nproc = nproc + do.astype(jnp.int32)
            nbad = nbad + (do_any & ~in_range).astype(jnp.int32)
            consumed = consumed + do_any.astype(jnp.int32)
        for i in range(nf):
            out_field_refs[i][0] = st[field_names[i]]
        nh_ref[0] = head + consumed
        np_ref[0] = nproc
        nb_ref[0] = nbad
        ef_ref[0] = ef.astype(jnp.int32)
        ec_ref[0] = ec
        ds_ref[0] = dstr.astype(jnp.int32)
        erf_ref[0] = erf.astype(jnp.int32)
        erc_ref[0] = erc
        erl_ref[0] = erl
        sf_ref[0] = sfail.astype(jnp.int32)

    @functools.partial(jax.jit)
    def run(fields, buf, head, n_run, ids, resv=()):
        grid = (rows // lb,)
        in_specs = (
            [pl.BlockSpec((1, lb), lambda i: (0, i))] * 3
            + [pl.BlockSpec((1, lb), lambda i: (0, i))] * nf
            + [pl.BlockSpec((cap, w1_in, lb), lambda i: (0, 0, i))]
            + [pl.BlockSpec((sd * n, lb), lambda i: (0, i))
               for _, n in spawn_sites])
        outbox_specs = ([pl.BlockSpec((batch * ms, lb),
                                      lambda i: (0, i)),
                         pl.BlockSpec((batch * ms * w1, lb),
                                      lambda i: (0, i))] if ms else [])
        outbox_shape = ([jax.ShapeDtypeStruct((batch * ms, rows),
                                              jnp.int32),
                         jax.ShapeDtypeStruct((batch * ms * w1, rows),
                                              jnp.int32)] if ms else [])
        claims_specs = [pl.BlockSpec((batch * n, lb), lambda i: (0, i))
                        for _, n in spawn_sites]
        claims_shape = [jax.ShapeDtypeStruct((batch * n, rows), jnp.int32)
                        for _, n in spawn_sites]
        out_specs = (
            [pl.BlockSpec((1, lb), lambda i: (0, i))] * nf
            + outbox_specs + claims_specs
            + [pl.BlockSpec((1, lb), lambda i: (0, i))] * 10)
        out_shape = (
            [jax.ShapeDtypeStruct((1, rows), fields[i].dtype)
             for i in range(nf)]
            + outbox_shape + claims_shape
            + [jax.ShapeDtypeStruct((1, rows), jnp.int32)] * 10)
        outs = pl.pallas_call(
            kernel, grid=grid, in_specs=in_specs, out_specs=out_specs,
            out_shape=out_shape, interpret=interpret,
        )(head[None, :], n_run[None, :], ids[None, :],
          *[f[None, :] for f in fields], buf, *resv)
        new_fields = tuple(outs[i][0] for i in range(nf))
        e = batch * ms * rows
        if ms:
            tgt = outs[nf]                   # [batch*ms, rows]
            words = outs[nf + 1]             # [batch*ms*w1, rows]
            after = outs[nf + 2:]
            # Flatten to the engine's entry order: (k, m, lane) with
            # lanes minor — words regroup to [w1, batch*ms*rows] planar.
            out_tgt = tgt.reshape(e)
            out_words = words.reshape(batch * ms, w1, rows)
            out_words = jnp.moveaxis(out_words, 1, 0).reshape(w1, e)
        else:
            after = outs[nf:]
            out_tgt = jnp.full((e,), -1, jnp.int32)
            out_words = jnp.zeros((w1, e), jnp.int32)
        claims_out = tuple(after[:n_sp])
        rest_out = after[n_sp:]
        (new_head, nproc, nbad, ef, ec, ds, erf, erc, erl, sf) = (
            o[0] for o in rest_out)
        return (new_fields, out_tgt, out_words, new_head, nproc, nbad,
                ef.astype(jnp.bool_), ec, ds.astype(jnp.bool_),
                erf.astype(jnp.bool_), erc, erl, claims_out,
                sf.astype(jnp.bool_))

    return run
