"""Host object heap: rich payloads referenced from int32 messages.

≙ the reference's per-actor heaps + ORCA ownership transfer for message
payloads (src/libponyrt/mem/heap.c; gc/gc.c send/recv object handlers):
a Pony message carries a *pointer* into some actor's heap and ORCA moves
the reference count with it. Device mailboxes here are fixed int32 words,
so host-side objects (socket buffers, strings, arbitrary Python values)
live in this handle table and messages carry the handle.

Handles carry a REFERENCE CAPABILITY (≙ src/libponyc/type/cap.c:1,
safeto.c:1 — the qualifiers that make a payload sendable):

- ``iso`` (default, ``box``): moved-unique. `unbox` consumes the handle
  (≙ Pony's `consume` on an iso send — the sender provably loses
  access, so no GC protocol is needed at all). Sending it through an
  ``Iso``-annotated parameter marks it in-flight: peek/unbox before the
  receiver takes delivery is use-after-send, and a second send is an
  aliased move — both raise. Delivery to a HOST actor completes the
  move (receive()); a handle sent into the DEVICE world stays
  in-flight for the host until some device actor forwards it back to a
  host receiver — the host gave it away, which is exactly the
  discipline.
- ``val`` (``box_val``): shared-immutable. Anyone may `peek`; `unbox`
  (taking ownership) is rejected; aliasing is free. Collected by the
  tracing GC when unreachable.
- ``tag`` (``box_tag``): opaque address. Identity/forwarding only —
  both `peek` and `unbox` are rejected.

Accounting mirrors the reference's USE_MEMTRACK counters
(scheduler.h:52-66): boxed/unboxed/live and peak-live are queryable.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Set


class CapabilityError(TypeError):
    """A handle was used against its capability mode (≙ the compile
    errors cap.c/safeto.c raise; dynamic here because host code is
    Python)."""


class HostHeap:
    """Handle table with per-handle capability modes (iso/val/tag).

    Handles are positive int32s; 0/-1 never issued (they collide with the
    framework's "empty word" / "no ref" conventions)."""

    def __init__(self):
        self._objs: Dict[int, Any] = {}
        self._sizes: Dict[int, int] = {}
        self._modes: Dict[int, str] = {}
        self._in_flight: Set[int] = set()
        self._next = 1
        self.boxed = 0
        self.unboxed = 0
        self.peak_live = 0
        # Growth accounting (≙ the per-actor heap's used/next_gc fields,
        # mem/heap.c:603-806): the runtime's run loop triggers an early
        # collection when bytes_since_gc outgrows its threshold
        # (RuntimeOptions.gc_initial / gc_factor), exactly the
        # growth-triggered cadence of the reference. Sizes are shallow
        # (sys.getsizeof) — an accounting signal, not an allocator.
        self.bytes_live = 0
        self.bytes_since_gc = 0

    _MISSING = object()

    @staticmethod
    def _approx_size(obj: Any) -> int:
        try:
            return max(1, sys.getsizeof(obj))
        except TypeError:
            return 64

    def box(self, obj: Any, mode: str = "iso") -> int:
        if mode not in ("iso", "val", "tag"):
            raise ValueError(f"unknown capability mode {mode!r}")
        h = self._next
        self._next += 1
        if self._next >= 2**31:         # wrap, skipping live handles
            self._next = 1
        while self._next in self._objs:
            self._next += 1
        self._objs[h] = obj
        self._modes[h] = mode
        sz = self._approx_size(obj)
        self._sizes[h] = sz
        self.bytes_live += sz
        self.bytes_since_gc += sz
        self.boxed += 1
        self.peak_live = max(self.peak_live, len(self._objs))
        return h

    def box_val(self, obj: Any) -> int:
        """Box as shared-immutable (≙ val)."""
        return self.box(obj, mode="val")

    def box_tag(self, obj: Any) -> int:
        """Box as opaque address (≙ tag)."""
        return self.box(obj, mode="tag")

    def mode(self, handle: int) -> str:
        return self._modes[int(handle)]

    def unbox(self, handle: int) -> Any:
        """Take ownership (the handle dies). KeyError on double-take —
        the dynamic cousin of Pony rejecting use-after-send of an iso.
        Only iso handles can be unboxed: val is shared-immutable (peek),
        tag is opaque."""
        h = int(handle)
        m = self._modes.get(h)
        if m == "val":
            raise CapabilityError(
                f"capability: handle {h} is val (shared-immutable) — "
                "peek it; ownership never moves")
        if m == "tag":
            raise CapabilityError(
                f"capability: handle {h} is tag (opaque address) — "
                "it cannot be read or unboxed")
        if h in self._in_flight:
            raise CapabilityError(
                f"capability: use-after-send — iso handle {h} is in "
                "flight to its receiver")
        obj = self._objs.pop(h)
        self._modes.pop(h, None)
        self.bytes_live -= self._sizes.pop(h, 0)
        self.unboxed += 1
        return obj

    def peek(self, handle: int) -> Any:
        h = int(handle)
        m = self._modes.get(h)
        if m == "tag" and h in self._objs:
            raise CapabilityError(
                f"capability: handle {h} is tag (opaque address) — "
                "identity only, no reads")
        if h in self._in_flight:
            raise CapabilityError(
                f"capability: use-after-send — iso handle {h} is in "
                "flight to its receiver")
        return self._objs[h]

    def send_iso(self, handle: int) -> None:
        """Mark an iso handle in flight (called by the runtime when a
        handle rides an ``Iso``-annotated message parameter). A second
        send of an in-flight handle is an aliased move."""
        h = int(handle)
        if h not in self._objs:
            raise KeyError(
                f"capability: iso handle {h} does not exist (already "
                "moved or never boxed)")
        m = self._modes.get(h)
        if m != "iso":
            return                       # val/tag ride freely
        if h in self._in_flight:
            raise CapabilityError(
                f"capability: aliased move — iso handle {h} is already "
                "in flight; an iso is moved-unique (box_val to share)")
        self._in_flight.add(h)

    def receive(self, handle: int) -> None:
        """Delivery completed: the receiver may now peek/unbox."""
        self._in_flight.discard(int(handle))

    def drop(self, handle: int) -> None:
        h = int(handle)
        if self._objs.pop(h, HostHeap._MISSING) is not HostHeap._MISSING:
            self.bytes_live -= self._sizes.pop(h, 0)
            self._modes.pop(h, None)
            self._in_flight.discard(h)
            self.unboxed += 1

    @property
    def live(self) -> int:
        return len(self._objs)

    def stats(self) -> Dict[str, int]:
        return {"boxed": self.boxed, "unboxed": self.unboxed,
                "live": self.live, "peak_live": self.peak_live,
                "bytes_live": self.bytes_live,
                "bytes_since_gc": self.bytes_since_gc}
