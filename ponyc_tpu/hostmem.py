"""Host object heap: rich payloads referenced from int32 messages.

≙ the reference's per-actor heaps + ORCA ownership transfer for message
payloads (src/libponyrt/mem/heap.c; gc/gc.c send/recv object handlers):
a Pony message carries a *pointer* into some actor's heap and ORCA moves
the reference count with it. Device mailboxes here are fixed int32 words,
so host-side objects (socket buffers, strings, arbitrary Python values)
live in this handle table and messages carry the handle.

Handles carry a REFERENCE CAPABILITY (≙ src/libponyc/type/cap.c:1,
safeto.c:1 — the qualifiers that make a payload sendable):

- ``iso`` (default, ``box``): moved-unique. `unbox` consumes the handle
  (≙ Pony's `consume` on an iso send — the sender provably loses
  access, so no GC protocol is needed at all). Sending it through an
  ``Iso``-annotated parameter marks it in-flight: peek/unbox before the
  receiver takes delivery is use-after-send, and a second send is an
  aliased move — both raise. Delivery to a HOST actor completes the
  move (receive()); a handle sent into the DEVICE world stays
  in-flight for the host until some device actor forwards it back to a
  host receiver — the host gave it away, which is exactly the
  discipline.
- ``val`` (``box_val``): shared-immutable. Anyone may `peek`; `unbox`
  (taking ownership) is rejected; aliasing is free. Collected by the
  tracing GC when unreachable.
- ``tag`` (``box_tag``): opaque address. Identity/forwarding only —
  both `peek` and `unbox` are rejected.

Accounting mirrors the reference's USE_MEMTRACK counters
(scheduler.h:52-66): boxed/unboxed/live and peak-live are queryable.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Set

from .errors import ERROR_CODES


class CapabilityError(TypeError):
    """A handle was used against its capability mode (≙ the compile
    errors cap.c/safeto.c raise; dynamic here because host code is
    Python)."""

    code = ERROR_CODES["CapabilityError"]


class HandleRef:
    """An explicit in-object reference to another heap handle.

    Host objects are arbitrary Python values, so a slot holding a plain
    int is AMBIGUOUS — it may be data or may happen to equal a live
    handle id. peek_field() therefore only follows slots that are
    explicitly HandleRef-wrapped (≙ the reference knowing statically
    which fields are object references — gentrace.c's per-type trace
    fns); everything else reads as plain data."""

    __slots__ = ("handle",)

    def __init__(self, handle: int):
        self.handle = int(handle)

    def __repr__(self):
        return f"HandleRef({self.handle})"

    def __eq__(self, other):
        return isinstance(other, HandleRef) and other.handle == self.handle

    def __hash__(self):
        return hash(("HandleRef", self.handle))


class HostHeap:
    """Handle table with per-handle capability modes — all six of the
    reference's caps (iso/trn/ref/val/box/tag; src/libponyc/type/cap.c).
    The local-only caps (trn/ref ≙ ``Mut``/box) never ride messages
    (sendability is enforced at behaviour declaration, api.py) but
    govern host-side reads/writes/aliases:

    - read (`peek`): every mode but tag;
    - write (`poke`): iso, trn, ref — the write-rights caps;
    - take ownership (`unbox`): iso, trn (consume); ref is refused
      because unknown ref aliases may exist (ref is freely aliasable);
    - alias (`view`): a new handle to the same object at a mode covered
      by the ALIAS of the source's mode (alias.c: iso aliases as tag,
      trn as box) — e.g. box views of a trn, tag views of anything;
    - viewpoint-composed field read (`peek_field`): reading a slot of a
      host object through an origin handle re-caps the result with
      origin▷field (cap_view_upper, type/cap.c:581-711);
    - `freeze` (≙ consume-to-val) and `recover_iso` (≙ recover block)
      move along the lattice where the table can prove it safe.

    Handles are positive int32s; 0/-1 never issued (they collide with the
    framework's "empty word" / "no ref" conventions)."""

    _READABLE = ("iso", "trn", "ref", "val", "box")
    _WRITABLE = ("iso", "trn", "ref")

    def __init__(self):
        self._objs: Dict[int, Any] = {}
        self._sizes: Dict[int, int] = {}
        self._modes: Dict[int, str] = {}
        self._in_flight: Set[int] = set()
        self._root: Dict[int, int] = {}      # view handle → root handle
        self._views: Dict[int, Set[int]] = {}  # root → live view handles
        self._next = 1
        self.boxed = 0
        self.unboxed = 0
        self.peak_live = 0
        # Growth accounting (≙ the per-actor heap's used/next_gc fields,
        # mem/heap.c:603-806): the runtime's run loop triggers an early
        # collection when bytes_since_gc outgrows its threshold
        # (RuntimeOptions.gc_initial / gc_factor), exactly the
        # growth-triggered cadence of the reference. Sizes are shallow
        # (sys.getsizeof) — an accounting signal, not an allocator.
        self.bytes_live = 0
        self.bytes_since_gc = 0

    _MISSING = object()

    @staticmethod
    def _approx_size(obj: Any) -> int:
        try:
            return max(1, sys.getsizeof(obj))
        except TypeError:
            return 64

    def box(self, obj: Any, mode: str = "iso") -> int:
        if mode not in ("iso", "trn", "ref", "val", "box", "tag"):
            raise ValueError(f"unknown capability mode {mode!r}")
        h = self._next
        self._next += 1
        if self._next >= 2**31:         # wrap, skipping live handles
            self._next = 1
        while self._next in self._objs:
            self._next += 1
        self._objs[h] = obj
        self._modes[h] = mode
        sz = self._approx_size(obj)
        self._sizes[h] = sz
        self.bytes_live += sz
        self.bytes_since_gc += sz
        self.boxed += 1
        self.peak_live = max(self.peak_live, len(self._objs))
        return h

    def box_val(self, obj: Any) -> int:
        """Box as shared-immutable (≙ val)."""
        return self.box(obj, mode="val")

    def box_tag(self, obj: Any) -> int:
        """Box as opaque address (≙ tag)."""
        return self.box(obj, mode="tag")

    def mode(self, handle: int) -> str:
        return self._modes[int(handle)]

    def unbox(self, handle: int) -> Any:
        """Take ownership (the handle dies; ≙ consume). KeyError on
        double-take — the dynamic cousin of Pony rejecting
        use-after-send of an iso. Only the ownership-unique modes can
        be unboxed: iso and trn. ref is freely aliasable so unknown
        aliases may exist; val is shared-immutable (peek); box is a
        borrowed view; tag is opaque. Live read-views of a consumed
        trn stay readable (Pony: consume moves the owner, outstanding
        box aliases still see the object)."""
        h = int(handle)
        m = self._modes.get(h)
        if m == "val":
            raise CapabilityError(
                f"capability: handle {h} is val (shared-immutable) — "
                "peek it; ownership never moves")
        if m == "tag":
            raise CapabilityError(
                f"capability: handle {h} is tag (opaque address) — "
                "it cannot be read or unboxed")
        if m in ("ref", "box"):
            raise CapabilityError(
                f"capability: handle {h} is {m} — a freely-aliased "
                "local cap cannot be consumed (unknown aliases may "
                "exist); recover_iso() first if it is unaliased")
        if h in self._in_flight:
            raise CapabilityError(
                f"capability: use-after-send — iso handle {h} is in "
                "flight to its receiver")
        obj = self._objs.pop(h)
        self._modes.pop(h, None)
        self._unlink_view(h)
        self.bytes_live -= self._sizes.pop(h, 0)
        self.unboxed += 1
        return obj

    def _unlink_view(self, h: int) -> None:
        root = self._root.pop(h, None)
        if root is not None:
            self._views.get(root, set()).discard(h)

    def poke(self, handle: int, obj: Any) -> None:
        """Checked WRITE: replace the handle's object. Allowed only for
        the write-rights caps (iso/trn/ref — ≙ cap_send/write columns of
        cap.c); val/box/tag refuse. The one-writer property of trn holds
        structurally: box views carry no poke rights."""
        h = int(handle)
        m = self._modes.get(h)
        if h not in self._objs:
            raise KeyError(f"handle {h} does not exist")
        if m not in self._WRITABLE:
            raise CapabilityError(
                f"capability: handle {h} is {m} — no write rights "
                "(only iso/trn/ref may poke)")
        if h in self._in_flight:
            raise CapabilityError(
                f"capability: use-after-send — handle {h} is in flight")
        # Writes through ANY writable alias land on the shared object:
        # resolve to the root and re-point the root plus every live view
        # (a view handle's own entry would otherwise silently diverge
        # from its siblings). Bytes are accounted on the root only.
        root = self._root.get(h, h)
        if root in self._objs:       # root may have been consumed; never
            self._objs[root] = obj   # resurrect it — views carry on alone
            sz = self._approx_size(obj)
            self.bytes_live += sz - self._sizes.get(root, 0)
            self.bytes_since_gc += sz
            self._sizes[root] = sz
        for v in self._views.get(root, ()):
            self._objs[v] = obj
        self._objs[h] = obj          # h is the root or one of its views

    def view(self, handle: int, mode: str = "box") -> int:
        """Create an ALIAS handle of the same object at `mode`. Legal
        when `mode` is covered by the alias of the source's cap
        (alias.c: alias(iso)=tag, alias(trn)=box, else itself) — e.g.
        box views of trn/ref, val views of val, tag views of anything
        readable. The view is a separate handle; dropping it never
        frees the object."""
        from .ops import pack
        h = int(handle)
        if h not in self._objs:
            raise KeyError(f"handle {h} does not exist")
        if h in self._in_flight:
            raise CapabilityError(
                f"capability: use-after-send — handle {h} is in flight")
        src = self._modes.get(h)
        aliased = pack.cap_alias(src)
        if not pack.cap_store_ok(aliased, mode):
            raise CapabilityError(
                f"capability: a {src} handle aliases as {aliased} "
                f"(alias.c) — it cannot be viewed as {mode}")
        return self._register_view(h, mode)

    def _register_view(self, h: int, mode: str) -> int:
        """Mint a view handle of `h`'s object at `mode`, linked to the
        root for alias tracking; views share the object's bytes."""
        v = self.box(self._objs[h], mode=mode)
        root = self._root.get(h, h)
        self._root[v] = root
        self._views.setdefault(root, set()).add(v)
        self.bytes_live -= self._sizes[v]
        self.bytes_since_gc -= self._sizes[v]
        self._sizes[v] = 0
        return v

    def peek_field(self, origin: int, key: Any):
        """Viewpoint-composed field read (≙ cap_view_upper,
        type/cap.c:581-711): read slot `key` of the object behind
        `origin` (mapping key or attribute). If the slot holds an
        explicit `HandleRef`, the result is a VIEW of that handle
        re-capped origin▷field; a composition with no read rights (tag
        origin) refuses. Every other value — including a plain int that
        happens to equal a live handle id — returns as data; reading it
        only needs the origin to be readable at all."""
        from .ops import pack
        o = int(origin)
        om = self._modes.get(o)
        if o not in self._objs:
            raise KeyError(f"handle {o} does not exist")
        if om == "tag":
            raise CapabilityError(
                f"capability: origin handle {o} is tag — cannot read "
                "fields through a tag (cap_view_upper, cap.c:588-596)")
        if o in self._in_flight:
            raise CapabilityError(
                f"capability: use-after-send — handle {o} is in flight")
        obj = self._objs[o]
        try:
            value = obj[key]
        except (TypeError, KeyError, IndexError):
            value = getattr(obj, key)
        if not isinstance(value, HandleRef):
            return value
        fh = value.handle
        if fh not in self._objs:
            raise KeyError(f"field {key!r} references dead handle {fh}")
        fm = self._modes.get(fh)
        seen = pack.viewpoint(om, fm)
        if seen is None:
            raise CapabilityError(
                f"capability: {om}▷{fm} is unreadable (cap_view_upper)")
        # A field READ binds an ALIAS of the viewpoint-adapted cap
        # (Pony: `x = obj.f` has type alias(origin▷field), alias.c) —
        # never a second owner: iso▷iso reads as tag, trn▷trn as box.
        # Consuming a field's unique value is a store/take, not a peek.
        return self._register_view(fh, pack.cap_alias(seen))

    def freeze(self, handle: int) -> int:
        """Consume to val (≙ `consume x` into a val — trn→val is Pony's
        freeze; iso→val the sendable downgrade). ref freezes only when
        the table has issued no live views of it (the dynamic stand-in
        for recover's no-aliases proof). Returns the same handle,
        re-capped val; existing read-views stay valid."""
        h = int(handle)
        m = self._modes.get(h)
        if h not in self._objs:
            raise KeyError(f"handle {h} does not exist")
        if h in self._in_flight:
            raise CapabilityError(
                f"capability: use-after-send — handle {h} is in flight")
        if m in ("val",):
            return h
        if m in ("box", "tag"):
            raise CapabilityError(
                f"capability: handle {h} is {m} — a borrowed view/"
                "address cannot be frozen (no ownership)")
        if m == "ref" and self._views.get(self._root.get(h, h)):
            raise CapabilityError(
                f"capability: ref handle {h} has live views — freeze "
                "needs an unaliased original (≙ recover)")
        self._modes[h] = "val"
        return h

    def recover_iso(self, handle: int) -> int:
        """Lift to iso (≙ a recover block's cap lift): legal for trn/ref
        with no live views (the table's proof of unaliasedness); iso is
        a no-op. val/box/tag refuse — shared or borrowed rights can
        never become unique again."""
        h = int(handle)
        m = self._modes.get(h)
        if h not in self._objs:
            raise KeyError(f"handle {h} does not exist")
        if h in self._in_flight:
            raise CapabilityError(
                f"capability: use-after-send — handle {h} is in flight")
        if m == "iso":
            return h
        if m not in ("trn", "ref"):
            raise CapabilityError(
                f"capability: handle {h} is {m} — only trn/ref lift to "
                "iso under recover (cap.c ephemeral lifts)")
        if self._views.get(self._root.get(h, h)):
            raise CapabilityError(
                f"capability: handle {h} has live views — recover needs "
                "an unaliased original")
        self._modes[h] = "iso"
        return h

    def peek(self, handle: int) -> Any:
        h = int(handle)
        m = self._modes.get(h)
        if m == "tag" and h in self._objs:
            raise CapabilityError(
                f"capability: handle {h} is tag (opaque address) — "
                "identity only, no reads")
        if h in self._in_flight:
            raise CapabilityError(
                f"capability: use-after-send — iso handle {h} is in "
                "flight to its receiver")
        return self._objs[h]

    def send_iso(self, handle: int) -> None:
        """Mark an iso handle in flight (called by the runtime when a
        handle rides an ``Iso``-annotated message parameter). A second
        send of an in-flight handle is an aliased move."""
        h = int(handle)
        if h not in self._objs:
            raise KeyError(
                f"capability: iso handle {h} does not exist (already "
                "moved or never boxed)")
        m = self._modes.get(h)
        if m != "iso":
            return                       # val/tag ride freely
        if h in self._in_flight:
            raise CapabilityError(
                f"capability: aliased move — iso handle {h} is already "
                "in flight; an iso is moved-unique (box_val to share)")
        self._in_flight.add(h)

    def receive(self, handle: int) -> None:
        """Delivery completed: the receiver may now peek/unbox."""
        self._in_flight.discard(int(handle))

    def drop(self, handle: int) -> None:
        h = int(handle)
        if self._objs.pop(h, HostHeap._MISSING) is not HostHeap._MISSING:
            self.bytes_live -= self._sizes.pop(h, 0)
            self._modes.pop(h, None)
            self._in_flight.discard(h)
            self._unlink_view(h)
            self.unboxed += 1

    @property
    def live(self) -> int:
        return len(self._objs)

    def stats(self) -> Dict[str, int]:
        return {"boxed": self.boxed, "unboxed": self.unboxed,
                "live": self.live, "peak_live": self.peak_live,
                "bytes_live": self.bytes_live,
                "bytes_since_gc": self.bytes_since_gc}
