"""Host object heap: rich payloads referenced from int32 messages.

≙ the reference's per-actor heaps + ORCA ownership transfer for message
payloads (src/libponyrt/mem/heap.c; gc/gc.c send/recv object handlers):
a Pony message carries a *pointer* into some actor's heap and ORCA moves
the reference count with it. Device mailboxes here are fixed int32 words,
so host-side objects (socket buffers, strings, arbitrary Python values)
live in this handle table and messages carry the handle.

Ownership is *move* semantics — `unbox` consumes the handle — which is
exactly Pony's `iso` send (the common case for network buffers: the
sender provably loses access, so no GC protocol is needed at all). Use
`peek` for read-only access without consuming, `drop` to discard.

Accounting mirrors the reference's USE_MEMTRACK counters
(scheduler.h:52-66): boxed/unboxed/live and peak-live are queryable.
"""

from __future__ import annotations

import sys
from typing import Any, Dict


class HostHeap:
    """Handle table with move-on-unbox semantics (≙ iso message payloads).

    Handles are positive int32s; 0/-1 never issued (they collide with the
    framework's "empty word" / "no ref" conventions)."""

    def __init__(self):
        self._objs: Dict[int, Any] = {}
        self._sizes: Dict[int, int] = {}
        self._next = 1
        self.boxed = 0
        self.unboxed = 0
        self.peak_live = 0
        # Growth accounting (≙ the per-actor heap's used/next_gc fields,
        # mem/heap.c:603-806): the runtime's run loop triggers an early
        # collection when bytes_since_gc outgrows its threshold
        # (RuntimeOptions.gc_initial / gc_factor), exactly the
        # growth-triggered cadence of the reference. Sizes are shallow
        # (sys.getsizeof) — an accounting signal, not an allocator.
        self.bytes_live = 0
        self.bytes_since_gc = 0

    _MISSING = object()

    @staticmethod
    def _approx_size(obj: Any) -> int:
        try:
            return max(1, sys.getsizeof(obj))
        except TypeError:
            return 64

    def box(self, obj: Any) -> int:
        h = self._next
        self._next += 1
        if self._next >= 2**31:         # wrap, skipping live handles
            self._next = 1
        while self._next in self._objs:
            self._next += 1
        self._objs[h] = obj
        sz = self._approx_size(obj)
        self._sizes[h] = sz
        self.bytes_live += sz
        self.bytes_since_gc += sz
        self.boxed += 1
        self.peak_live = max(self.peak_live, len(self._objs))
        return h

    def unbox(self, handle: int) -> Any:
        """Take ownership (the handle dies). KeyError on double-take —
        the dynamic cousin of Pony rejecting use-after-send of an iso."""
        obj = self._objs.pop(int(handle))
        self.bytes_live -= self._sizes.pop(int(handle), 0)
        self.unboxed += 1
        return obj

    def peek(self, handle: int) -> Any:
        return self._objs[int(handle)]

    def drop(self, handle: int) -> None:
        if self._objs.pop(int(handle), HostHeap._MISSING) \
                is not HostHeap._MISSING:
            self.bytes_live -= self._sizes.pop(int(handle), 0)
            self.unboxed += 1

    @property
    def live(self) -> int:
        return len(self._objs)

    def stats(self) -> Dict[str, int]:
        return {"boxed": self.boxed, "unboxed": self.unboxed,
                "live": self.live, "peak_live": self.peak_live,
                "bytes_live": self.bytes_live,
                "bytes_since_gc": self.bytes_since_gc}
