"""Base64 encode/decode — ≙ the reference's `packages/encode/base64/`
(base64.pony: encode/decode with configurable 62nd/63rd characters,
optional padding and line breaks; encode_url/decode_url; encode_pem /
encode_mime presets).

A from-scratch implementation (6-bit chunking over a configurable
alphabet), not a re-export of the host base64 module, so the at62/at63/
pad/linelen knobs match the reference exactly.
"""

from __future__ import annotations

from typing import Union

__all__ = ["Base64"]

_STD = "ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789"


def _as_bytes(data: Union[bytes, bytearray, str]) -> bytes:
    return data.encode() if isinstance(data, str) else bytes(data)


class Base64:
    """≙ base64.pony Base64 primitive."""

    @staticmethod
    def encode(data, at62: str = "+", at63: str = "/", pad: str = "=",
               linelen: int = 0, linesep: str = "\r\n") -> str:
        raw = _as_bytes(data)
        table = _STD + at62 + at63
        out = []
        for i in range(0, len(raw), 3):
            chunk = raw[i:i + 3]
            bits = int.from_bytes(chunk + b"\x00" * (3 - len(chunk)), "big")
            n_out = len(chunk) + 1
            for j in range(4):
                if j < n_out:
                    out.append(table[(bits >> (18 - 6 * j)) & 0x3F])
                elif pad:
                    out.append(pad)
        s = "".join(out)
        if linelen > 0:
            s = linesep.join(s[i:i + linelen]
                             for i in range(0, len(s), linelen))
            if s:
                s += linesep
        return s

    @staticmethod
    def encode_pem(data) -> str:
        """64-char lines (≙ base64.pony:27-32)."""
        return Base64.encode(data, linelen=64)

    @staticmethod
    def encode_mime(data) -> str:
        """76-char lines (≙ base64.pony:33-38)."""
        return Base64.encode(data, linelen=76)

    @staticmethod
    def encode_url(data, pad: bool = False) -> str:
        """URL-safe alphabet -_ with optional padding
        (≙ base64.pony:39-49)."""
        return Base64.encode(data, at62="-", at63="_",
                             pad="=" if pad else "")

    @staticmethod
    def decode(data: Union[str, bytes], at62: str = "+", at63: str = "/",
               pad_char: str = "=") -> bytes:
        """≙ base64.pony decode: whitespace tolerated, anything else
        raises ValueError (≙ Pony error)."""
        s = data.decode() if isinstance(data, (bytes, bytearray)) else data
        table = {c: i for i, c in enumerate(_STD + at62 + at63)}
        bits = 0
        nbits = 0
        out = bytearray()
        for ch in s:
            if ch in " \t\r\n" or ch == pad_char:
                continue
            if ch not in table:
                raise ValueError(f"invalid base64 character {ch!r}")
            bits = (bits << 6) | table[ch]
            nbits += 6
            if nbits >= 8:
                nbits -= 8
                out.append((bits >> nbits) & 0xFF)
        return bytes(out)

    @staticmethod
    def decode_url(data: Union[str, bytes]) -> bytes:
        return Base64.decode(data, at62="-", at63="_")
