"""Promises — ≙ packages/promises (promise.pony).

Pony's Promise[A] is an actor: fulfil/reject once, `next`-chaining
creates derived promises, `join`/`select` combine, and timeouts reject.
Here promises are host-side (they coordinate work across actors and the
host driver; device actors communicate by messages, not futures), with
the same surface:

    p = Promise(rt)
    p.next(lambda v: v * 2).next(print)
    p.fulfil(21)

An actor can fulfil a promise from a behaviour by sending the promise's
`fulfil_ref` a message — promises register themselves as bridgeable
sinks via `Promise.behaviour_sink` (a HOST actor type owning them).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional


class PromiseRejected(Exception):
    pass


class Promise:
    """Write-once async value (≙ promises/promise.pony)."""

    def __init__(self, rt=None):
        self.rt = rt
        self._lock = threading.Lock()
        self._event = threading.Event()
        self._value: Any = None
        self._rejected = False
        self._cbs: List[Callable] = []
        self._ecbs: List[Callable] = []

    # -- write side (once; later calls no-op, ≙ promise idempotence) --
    def fulfil(self, value: Any = None) -> "Promise":
        with self._lock:
            if self._event.is_set():
                return self
            self._value = value
            self._event.set()
            cbs, self._cbs, self._ecbs = self._cbs, [], []
        for cb in cbs:
            cb(value)
        return self

    def reject(self, reason: Any = None) -> "Promise":
        with self._lock:
            if self._event.is_set():
                return self
            self._rejected = True
            self._value = reason
            self._event.set()
            ecbs, self._cbs, self._ecbs = self._ecbs, [], []
        for cb in ecbs:
            cb(reason)
        return self

    # -- read side --
    def next(self, fulfilled: Callable, rejected: Optional[Callable] = None
             ) -> "Promise":
        """Chain (≙ Promise.next[B]): returns the derived promise."""
        out = Promise(self.rt)

        def on_ok(v):
            try:
                out.fulfil(fulfilled(v))
            except Exception as ex:         # noqa: BLE001 — chain rejects
                out.reject(ex)

        def on_err(r):
            if rejected is not None:
                try:
                    out.fulfil(rejected(r))
                    return
                except Exception as ex:     # noqa: BLE001
                    out.reject(ex)
                    return
            out.reject(r)

        with self._lock:
            if not self._event.is_set():
                self._cbs.append(on_ok)
                self._ecbs.append(on_err)
                return out
        if self._rejected:
            on_err(self._value)
        else:
            on_ok(self._value)
        return out

    def done(self) -> bool:
        return self._event.is_set()

    def value(self, timeout: Optional[float] = None) -> Any:
        """Block the *host* until resolved. If the promise's runtime is
        supplied, drive it while waiting (an actor program that must run
        for the promise to resolve can't be blocked on)."""
        deadline = None if timeout is None else time.time() + timeout
        while not self._event.is_set():
            if self.rt is not None:
                self.rt.run(max_steps=8)
            else:
                self._event.wait(0.01)
            if deadline is not None and time.time() > deadline:
                raise TimeoutError("promise timeout")
        if self._rejected:
            raise PromiseRejected(self._value)
        return self._value

    def timeout(self, seconds: float) -> "Promise":
        """Reject after `seconds` if unresolved (≙ promise timeout via
        Timers in the reference examples)."""
        def arm():
            time.sleep(seconds)
            self.reject(TimeoutError(f"timeout {seconds}s"))
        threading.Thread(target=arm, daemon=True).start()
        return self


def join(promises: List[Promise], rt=None) -> Promise:
    """Fulfil with the list of all values (≙ Promises.join); reject on
    the first rejection."""
    out = Promise(rt)
    n = len(promises)
    if n == 0:
        return out.fulfil([])
    results: List[Any] = [None] * n
    remaining = [n]
    lock = threading.Lock()

    def make(i):
        def ok(v):
            with lock:
                results[i] = v
                remaining[0] -= 1
                last = remaining[0] == 0
            if last:
                out.fulfil(list(results))
        return ok

    for i, p in enumerate(promises):
        p.next(make(i), out.reject)
    return out


def select(promises: List[Promise], rt=None) -> Promise:
    """First resolution wins (≙ Promises.select)."""
    out = Promise(rt)
    for p in promises:
        p.next(out.fulfil, out.reject)
    return out


class Custodian:
    """Collects disposables and disposes them all at once
    (≙ packages/bureaucracy/custodian.pony)."""

    def __init__(self):
        self._items: List[Any] = []

    def apply(self, disposable) -> None:
        self._items.append(disposable)

    def dispose(self) -> None:
        for it in reversed(self._items):
            for meth in ("dispose", "close", "stop"):
                fn = getattr(it, meth, None)
                if callable(fn):
                    fn()
                    break
        self._items.clear()
