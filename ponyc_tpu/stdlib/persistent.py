"""Persistent (immutable, structurally-shared) collections.

≙ the reference's `packages/collections/persistent/`:
  Map  — 32-way hash-array-mapped trie (persistent/map.pony,
         persistent/_map_node.pony: Entries/bitmap nodes, 5-bit hash
         chunks, collision buckets at max depth)
  Vec  — 32-way radix-balanced trie with tail optimisation
         (persistent/vec.pony, persistent/_vec_node.pony)
  List — cons list (persistent/list.pony)
  Set  — membership Map (persistent/set.pony)

These are genuine structural-sharing implementations, not dict copies:
update cost is O(log32 n) nodes, and old versions stay valid — which is
exactly what host-side behaviours want when they return a new state from
an old one without copying the world.
"""

from __future__ import annotations

from typing import Any, Iterator, Optional, Tuple

_BITS = 5
_WIDTH = 1 << _BITS          # 32-way nodes, as the reference (_bits.pony)
_MASK = _WIDTH - 1
_MAX_LEVEL = 12              # 64-bit hash / 5 bits, capped like map.pony


def _popcount(x: int) -> int:
    return bin(x).count("1")


class _MapNode:
    """Bitmap-compressed HAMT node (≙ _MapNode in _map_node.pony).

    `bitmap` marks which of the 32 slots are present; `slots` holds, per
    present slot, either a (key, value) leaf, a nested _MapNode, or a
    list of (key, value) pairs (collision bucket at max depth)."""

    __slots__ = ("bitmap", "slots")

    def __init__(self, bitmap: int = 0, slots: Tuple = ()):
        self.bitmap = bitmap
        self.slots = slots

    def _pos(self, bit: int) -> int:
        return _popcount(self.bitmap & (bit - 1))

    def get(self, h: int, level: int, key):
        bit = 1 << ((h >> (level * _BITS)) & _MASK)
        if not (self.bitmap & bit):
            raise KeyError(key)
        slot = self.slots[self._pos(bit)]
        if isinstance(slot, _MapNode):
            return slot.get(h, level + 1, key)
        if isinstance(slot, list):
            for k, v in slot:
                if k == key:
                    return v
            raise KeyError(key)
        k, v = slot
        if k == key:
            return v
        raise KeyError(key)

    def update(self, h: int, level: int, key, value) -> Tuple["_MapNode", int]:
        """Return (new node, size delta)."""
        idx = (h >> (level * _BITS)) & _MASK
        bit = 1 << idx
        pos = self._pos(bit)
        if not (self.bitmap & bit):
            slots = self.slots[:pos] + ((key, value),) + self.slots[pos:]
            return _MapNode(self.bitmap | bit, slots), 1
        slot = self.slots[pos]
        if isinstance(slot, _MapNode):
            child, d = slot.update(h, level + 1, key, value)
            return self._with(pos, child), d
        if isinstance(slot, list):
            for i, (k, _v) in enumerate(slot):
                if k == key:
                    bucket = slot[:i] + [(key, value)] + slot[i + 1:]
                    return self._with(pos, bucket), 0
            return self._with(pos, slot + [(key, value)]), 1
        k0, v0 = slot
        if k0 == key:
            return self._with(pos, (key, value)), 0
        # Leaf conflict: push both one level down (≙ _map_node.pony's
        # sub-node creation), or open a collision bucket at max depth.
        if level + 1 >= _MAX_LEVEL:
            return self._with(pos, [(k0, v0), (key, value)]), 1
        sub = _MapNode()
        h0 = _hash(k0)
        sub, _ = sub.update(h0, level + 1, k0, v0)
        sub, _ = sub.update(h, level + 1, key, value)
        return self._with(pos, sub), 1

    def remove(self, h: int, level: int, key) -> Optional["_MapNode"]:
        """Return the new node, or None if key absent (caller keeps self)."""
        bit = 1 << ((h >> (level * _BITS)) & _MASK)
        if not (self.bitmap & bit):
            return None
        pos = self._pos(bit)
        slot = self.slots[pos]
        if isinstance(slot, _MapNode):
            child = slot.remove(h, level + 1, key)
            if child is None:
                return None
            if child.bitmap == 0:
                return self._drop(pos, bit)
            return self._with(pos, child)
        if isinstance(slot, list):
            for i, (k, _v) in enumerate(slot):
                if k == key:
                    bucket = slot[:i] + slot[i + 1:]
                    if len(bucket) == 1:
                        return self._with(pos, bucket[0])
                    return self._with(pos, bucket)
            return None
        if slot[0] == key:
            return self._drop(pos, bit)
        return None

    def _with(self, pos: int, slot) -> "_MapNode":
        slots = self.slots[:pos] + (slot,) + self.slots[pos + 1:]
        return _MapNode(self.bitmap, slots)

    def _drop(self, pos: int, bit: int) -> "_MapNode":
        slots = self.slots[:pos] + self.slots[pos + 1:]
        return _MapNode(self.bitmap & ~bit, slots)

    def iter_items(self) -> Iterator[Tuple[Any, Any]]:
        for slot in self.slots:
            if isinstance(slot, _MapNode):
                yield from slot.iter_items()
            elif isinstance(slot, list):
                yield from slot
            else:
                yield slot


def _hash(key) -> int:
    return hash(key) & 0xFFFFFFFFFFFFFFFF


class Map:
    """Persistent hash map (≙ persistent/map.pony).

    map(k) → value (raises KeyError ≙ Pony `error`); update/remove return
    NEW maps; the old one is untouched."""

    __slots__ = ("_root", "_size")

    def __init__(self, _root: Optional[_MapNode] = None, _size: int = 0):
        self._root = _root or _MapNode()
        self._size = _size

    @classmethod
    def of(cls, pairs) -> "Map":
        m = cls()
        for k, v in (pairs.items() if isinstance(pairs, dict) else pairs):
            m = m.update(k, v)
        return m

    def __call__(self, key):
        return self._root.get(_hash(key), 0, key)

    __getitem__ = __call__

    def get_or_else(self, key, default=None):
        try:
            return self(key)
        except KeyError:
            return default

    def contains(self, key) -> bool:
        try:
            self(key)
            return True
        except KeyError:
            return False

    __contains__ = contains

    def update(self, key, value) -> "Map":
        root, d = self._root.update(_hash(key), 0, key, value)
        return Map(root, self._size + d)

    def remove(self, key) -> "Map":
        """≙ map.pony remove: error (KeyError) when absent."""
        root = self._root.remove(_hash(key), 0, key)
        if root is None:
            raise KeyError(key)
        return Map(root, self._size - 1)

    def size(self) -> int:
        return self._size

    __len__ = size

    def keys(self):
        for k, _v in self._root.iter_items():
            yield k

    def values(self):
        for _k, v in self._root.iter_items():
            yield v

    def pairs(self):
        yield from self._root.iter_items()

    items = pairs
    __iter__ = keys

    def concat(self, pairs) -> "Map":
        m = self
        for k, v in pairs:
            m = m.update(k, v)
        return m


class Set:
    """Persistent set over Map (≙ persistent/set.pony)."""

    __slots__ = ("_map",)

    def __init__(self, _map: Optional[Map] = None):
        self._map = _map or Map()

    @classmethod
    def of(cls, items) -> "Set":
        s = cls()
        for x in items:
            s = s.add(x)
        return s

    def add(self, value) -> "Set":
        return Set(self._map.update(value, True))

    def remove(self, value) -> "Set":
        return Set(self._map.remove(value))

    def contains(self, value) -> bool:
        return self._map.contains(value)

    __contains__ = contains

    def size(self) -> int:
        return self._map.size()

    __len__ = size

    def __iter__(self):
        return self._map.keys()

    def union(self, other: "Set") -> "Set":
        s = self
        for x in other:
            s = s.add(x)
        return s

    def intersect(self, other: "Set") -> "Set":
        s = Set()
        for x in self:
            if x in other:
                s = s.add(x)
        return s

    def difference(self, other: "Set") -> "Set":
        s = self
        for x in other:
            if x in s:
                s = s.remove(x)
        return s


class _VecNode:
    """Radix-trie node for Vec (≙ _vec_node.pony)."""

    __slots__ = ("children",)

    def __init__(self, children: Tuple = ()):
        self.children = children


class Vec:
    """Persistent vector: 32-way radix trie + tail (≙ persistent/vec.pony).

    push/pop/update return new vectors in O(log32 n); apply/`vec[i]` is
    O(log32 n) with the hot suffix served from the tail block."""

    __slots__ = ("_root", "_tail", "_size", "_depth")

    def __init__(self, _root=None, _tail: Tuple = (), _size: int = 0,
                 _depth: int = 0):
        self._root = _root or _VecNode()
        self._tail = _tail
        self._size = _size
        self._depth = _depth

    @classmethod
    def of(cls, items) -> "Vec":
        v = cls()
        for x in items:
            v = v.push(x)
        return v

    def size(self) -> int:
        return self._size

    __len__ = size

    def _tail_offset(self) -> int:
        return (self._size - len(self._tail))

    def __call__(self, i: int):
        if not (0 <= i < self._size):
            raise IndexError(i)
        if i >= self._tail_offset():
            return self._tail[i - self._tail_offset()]
        node = self._root
        for level in range(self._depth, 0, -1):
            node = node.children[(i >> (level * _BITS)) & _MASK]
        return node.children[i & _MASK]

    __getitem__ = __call__

    def update(self, i: int, value) -> "Vec":
        if not (0 <= i < self._size):
            raise IndexError(i)
        if i >= self._tail_offset():
            j = i - self._tail_offset()
            tail = self._tail[:j] + (value,) + self._tail[j + 1:]
            return Vec(self._root, tail, self._size, self._depth)

        def go(node: _VecNode, level: int) -> _VecNode:
            idx = (i >> (level * _BITS)) & _MASK
            if level == 0:
                ch = node.children[:idx] + (value,) + node.children[idx + 1:]
                return _VecNode(ch)
            sub = go(node.children[idx], level - 1)
            ch = node.children[:idx] + (sub,) + node.children[idx + 1:]
            return _VecNode(ch)

        return Vec(go(self._root, self._depth), self._tail, self._size,
                   self._depth)

    def push(self, value) -> "Vec":
        if len(self._tail) < _WIDTH:
            return Vec(self._root, self._tail + (value,), self._size + 1,
                       self._depth)
        # Tail full: sink it into the trie, start a fresh tail.
        root, depth = self._push_tail()
        return Vec(root, (value,), self._size + 1, depth)

    def _push_tail(self):
        leaf = _VecNode(self._tail)
        tail_idx = self._size - _WIDTH      # first index of the sunk tail
        if self._size == _WIDTH:            # trie empty so far
            return leaf, 0
        if tail_idx == _WIDTH << (self._depth * _BITS):
            # Root overflow: new root one level up.
            root = _VecNode((self._root,) + (self._new_path(
                self._depth, leaf),))
            return root, self._depth + 1

        def go(node: _VecNode, level: int) -> _VecNode:
            idx = (tail_idx >> (level * _BITS)) & _MASK
            if level == 1:
                ch = node.children[:idx] + (leaf,) + node.children[idx + 1:]
                return _VecNode(ch)
            if idx < len(node.children):
                sub = go(node.children[idx], level - 1)
                ch = (node.children[:idx] + (sub,)
                      + node.children[idx + 1:])
            else:
                sub = self._new_path(level - 1, leaf)
                ch = node.children + (sub,)
            return _VecNode(ch)

        return go(self._root, self._depth), self._depth

    @staticmethod
    def _new_path(levels: int, leaf: _VecNode) -> _VecNode:
        node = leaf
        for _ in range(levels):
            node = _VecNode((node,))
        return node

    def pop(self) -> Tuple["Vec", Any]:
        """≙ vec.pony pop: error (IndexError) on empty."""
        if self._size == 0:
            raise IndexError("pop from empty Vec")
        last = self(self._size - 1)
        if len(self._tail) > 1 or self._size == 1:
            return (Vec(self._root, self._tail[:-1], self._size - 1,
                        self._depth), last)
        # Tail exhausts: lift the last leaf back out as the tail.
        new_size = self._size - 1
        start = new_size - _WIDTH
        node = self._root
        for level in range(self._depth, 0, -1):
            node = node.children[(start >> (level * _BITS)) & _MASK]
        new_tail = node.children

        def strip(node: _VecNode, level: int) -> Optional[_VecNode]:
            idx = (start >> (level * _BITS)) & _MASK
            if level == 1:
                ch = node.children[:idx]
            else:
                sub = strip(node.children[idx], level - 1)
                ch = node.children[:idx] + ((sub,) if sub else ())
            return _VecNode(ch) if ch else None

        root = (strip(self._root, self._depth)
                if self._depth else None) or _VecNode()
        depth = self._depth
        if depth and len(root.children) == 1 \
                and isinstance(root.children[0], _VecNode):
            root = root.children[0]
            depth -= 1
        return Vec(root, new_tail, new_size, depth), last

    def __iter__(self):
        for i in range(self._size):
            yield self(i)

    def concat(self, items) -> "Vec":
        v = self
        for x in items:
            v = v.push(x)
        return v


class List:
    """Persistent cons list (≙ persistent/list.pony): prepend is O(1),
    old lists remain valid."""

    __slots__ = ("_head", "_tail", "_size")

    def __init__(self, _head=None, _tail: Optional["List"] = None,
                 _size: int = 0):
        self._head = _head
        self._tail = _tail
        self._size = _size

    @classmethod
    def of(cls, items) -> "List":
        lst = cls()
        for x in reversed(list(items)):
            lst = lst.prepend(x)
        return lst

    def is_empty(self) -> bool:
        return self._size == 0

    def size(self) -> int:
        return self._size

    __len__ = size

    def head(self):
        if self._size == 0:
            raise IndexError("head of empty List")
        return self._head

    def tail(self) -> "List":
        if self._size == 0:
            raise IndexError("tail of empty List")
        return self._tail

    def prepend(self, value) -> "List":
        return List(value, self, self._size + 1)

    def __iter__(self):
        node = self
        while node._size:
            yield node._head
            node = node._tail

    def reverse(self) -> "List":
        return List.of(reversed(list(self)))

    def map(self, fn) -> "List":
        return List.of(fn(x) for x in self)

    def filter(self, fn) -> "List":
        return List.of(x for x in self if fn(x))

    def fold(self, fn, acc):
        for x in self:
            acc = fn(acc, x)
        return acc
