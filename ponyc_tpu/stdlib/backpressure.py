"""Backpressure — ≙ packages/backpressure (Backpressure.apply/release +
ApplyReleaseBackpressureAuth/auth.pony).

The reference package lets an actor tell the runtime "send to me slower"
when it experiences pressure the runtime cannot observe — a stalled
socket, a saturated external queue (packages/backpressure/
backpressure.pony module docs; the runtime side is
pony_apply_backpressure / pony_release_backpressure,
src/libponyrt/actor/actor.c:1137-1162). Here the runtime side is the
`pressured` actor column: senders to a pressured actor mute at delivery
time and release after release() once occupancy also recovers
(delivery.py mute triggers; engine.py unmute pass).

Mirrors the reference's capability-security shape: calling apply/release
requires an `ApplyReleaseBackpressureAuth` token derived from the
runtime's root authority (≙ auth.pony deriving from AmbientAuth), so a
library can be granted *only* this power.

    from ponyc_tpu.stdlib import backpressure as bp
    auth = bp.ApplyReleaseBackpressureAuth(rt.ambient_auth())
    bp.apply(auth, actor_id)
    ...
    bp.release(auth, actor_id)
"""

from __future__ import annotations


class ApplyReleaseBackpressureAuth:
    """Capability token for apply/release (≙ backpressure/auth.pony)."""

    def __init__(self, ambient):
        from ..runtime.runtime import AmbientAuth
        if not isinstance(ambient, AmbientAuth):
            raise TypeError(
                "ApplyReleaseBackpressureAuth requires the runtime's "
                "ambient authority (rt.ambient_auth())")
        self._rt = ambient._rt


def apply(auth: ApplyReleaseBackpressureAuth, actor_id) -> None:
    """≙ Backpressure.apply(auth): mark `actor_id` under pressure."""
    if not isinstance(auth, ApplyReleaseBackpressureAuth):
        raise TypeError("apply requires an ApplyReleaseBackpressureAuth")
    auth._rt.apply_backpressure(actor_id)


def release(auth: ApplyReleaseBackpressureAuth, actor_id) -> None:
    """≙ Backpressure.release(auth): clear the pressure mark."""
    if not isinstance(auth, ApplyReleaseBackpressureAuth):
        raise TypeError("release requires an ApplyReleaseBackpressureAuth")
    auth._rt.release_backpressure(actor_id)
