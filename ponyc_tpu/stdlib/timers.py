"""Timers — ≙ packages/time (Timers actor + Timer/TimerNotify).

The reference's Timers actor multiplexes Timer objects over one ASIO
timer subscription; notify objects get apply/cancel callbacks and a
Timer can limit its firing count. Here the native timerfd loop (bridge)
already multiplexes; this module provides the stdlib-shaped surface:

    timers = Timers(rt)
    t = timers.timer(owner, MyActor.tick, interval_s=0.05, count=10)
    timers.after(owner, MyActor.fire, 0.2)     # one-shot
    timers.cancel(t)

Each firing sends the behaviour `(kind=1, arg=n_expiries, flags=0)` —
the uniform asio event signature (bridge).
"""

from __future__ import annotations

from typing import Dict, Optional

from .. import native
from ..api import BehaviourDef


class Timers:
    """Timer hub (≙ time/Timers actor)."""

    def __init__(self, rt):
        self.rt = rt
        self.bridge = rt.attach_bridge()
        self._live: Dict[int, dict] = {}

    def timer(self, owner: int, bdef: BehaviourDef, interval_s: float, *,
              first_s: Optional[float] = None, count: int = 0,
              noisy: bool = True) -> int:
        """Fire `bdef(kind, arg, flags)` on `owner` every interval_s;
        count > 0 cancels after that many firings (≙ Timer._count)."""
        if not isinstance(bdef, BehaviourDef) or bdef.global_id is None:
            raise TypeError("timer needs a program-registered behaviour")
        if len(bdef.arg_specs) != 3:
            raise TypeError(
                f"{bdef} must take (kind, arg, flags) — the uniform asio "
                "event signature")
        rec = {"owner": int(owner), "bdef": bdef, "count": int(count),
               "fired": 0, "sid": None}

        def on_fire(ev, rec=rec):
            sid = rec["sid"]
            if sid not in self._live:
                return                       # cancelled, event in flight
            n = max(1, ev.arg)
            if rec["count"] > 0:
                n = min(n, rec["count"] - rec["fired"])
                rec["fired"] += n
            self.rt.send(rec["owner"], rec["bdef"], native.TIMER, n, 0)
            if rec["count"] > 0 and rec["fired"] >= rec["count"]:
                self.cancel(sid)

        sid = self.bridge.timer_callback(
            on_fire, interval_s, first_s=first_s,
            oneshot=count == 1, noisy=noisy)
        rec["sid"] = sid
        self._live[sid] = rec
        return sid

    def after(self, owner: int, bdef: BehaviourDef, delay_s: float,
              *, noisy: bool = True) -> int:
        """One-shot convenience (≙ a count-1 Timer)."""
        return self.timer(owner, bdef, delay_s, first_s=delay_s, count=1,
                          noisy=noisy)

    def cancel(self, timer_id: int) -> bool:
        """≙ Timers.cancel → TimerNotify.cancel."""
        self._live.pop(timer_id, None)
        return self.bridge.unsubscribe(timer_id)

    def dispose(self) -> None:
        for sid in list(self._live):
            self.cancel(sid)
