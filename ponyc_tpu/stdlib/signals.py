"""Signals — ≙ packages/signals (SignalHandler actor + SignalNotify +
Sig name table, over the runtime's ASIO signal events,
src/libponyrt/asio/epoll.c:54-133).

The reference's SignalHandler subscribes an ASIO signal event owned by
the handler actor; each delivery invokes the SignalNotify, and a `wait`
handler keeps the runtime alive (noisy subscription). The TPU twin
rides the native epoll loop's signalfd-style subscription (bridge +
native/src/asio.cc) and delivers the uniform `(kind, arg, flags)` asio
message to an owning actor:

    from ponyc_tpu.stdlib import signals
    h = signals.SignalHandler(rt, owner_id, MyActor.on_event,
                              signals.Sig.term(), wait=True)
    h.raise_()            # ≙ SignalHandler.raise()
    h.dispose()

`wait=True` maps to a noisy subscription (≙ the reference's wait flag
keeping quiescence off until disposal).
"""

from __future__ import annotations

import os
import signal as _signal


class Sig:
    """Signal numbers by name (≙ packages/signals/sig.pony)."""

    @staticmethod
    def hup() -> int: return int(_signal.SIGHUP)

    @staticmethod
    def int_() -> int: return int(_signal.SIGINT)

    @staticmethod
    def quit() -> int: return int(_signal.SIGQUIT)

    @staticmethod
    def usr1() -> int: return int(_signal.SIGUSR1)

    @staticmethod
    def usr2() -> int: return int(_signal.SIGUSR2)

    @staticmethod
    def alrm() -> int: return int(_signal.SIGALRM)

    @staticmethod
    def term() -> int: return int(_signal.SIGTERM)

    @staticmethod
    def chld() -> int: return int(_signal.SIGCHLD)

    @staticmethod
    def cont() -> int: return int(_signal.SIGCONT)

    @staticmethod
    def winch() -> int: return int(_signal.SIGWINCH)


class SignalHandler:
    """Listen for one signal and deliver it to an owning actor as the
    uniform asio behaviour message (≙ signals/signal_handler.pony)."""

    def __init__(self, rt, owner: int, bdef, sig: int, *,
                 wait: bool = False):
        self._rt = rt
        self._sig = int(sig)
        self._bridge = rt.attach_bridge()
        self._sid = self._bridge.signal(int(owner), bdef, self._sig,
                                        noisy=wait)

    def raise_(self) -> None:
        """Raise the signal on this process (≙ SignalHandler.raise)."""
        os.kill(os.getpid(), self._sig)

    def dispose(self) -> None:
        """Unsubscribe (≙ SignalHandler.dispose); a waiting handler
        stops keeping the runtime alive."""
        if self._sid is not None:
            self._bridge.unsubscribe(self._sid)
            self._sid = None
