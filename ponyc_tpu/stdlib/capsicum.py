"""Capsicum — ≙ packages/capsicum (Cap + CapRights0).

The reference models FreeBSD capsicum rights as a bit set built from a
FileCaps set and applied to a file descriptor (cap_rights.pony:
create/from/set/unset/merge/remove/contains/limit). Linux TPU hosts
have no capsicum syscall, so `limit()` degrades to a no-op success the
same way the reference's does on non-FreeBSD (`ifdef "capsicum"` —
cap_rights.pony:70-78 compiles to `true` elsewhere). The rights
algebra — the part programs actually branch on — is fully implemented.

    from ponyc_tpu.stdlib.capsicum import Cap, CapRights
    r = CapRights.from_caps({"read", "seek"})
    r.set(Cap.write())
    r.contains(other)
    r.limit(fd)          # no-op True on Linux, as on non-FreeBSD Pony
"""

from __future__ import annotations


class Cap:
    """Individual capsicum right bits (≙ capsicum/cap.pony primitives;
    values are symbolic — the algebra, not the FreeBSD ABI)."""
    _next = [0]
    _names = {}

    @classmethod
    def _bit(cls, name: str) -> int:
        if name not in cls._names:
            cls._names[name] = 1 << cls._next[0]
            cls._next[0] += 1
        return cls._names[name]

    @classmethod
    def read(cls): return cls._bit("read")
    @classmethod
    def write(cls): return cls._bit("write")
    @classmethod
    def seek(cls): return cls._bit("seek")
    @classmethod
    def mmap(cls): return cls._bit("mmap")
    @classmethod
    def creat(cls): return cls._bit("creat")
    @classmethod
    def event(cls): return cls._bit("event")
    @classmethod
    def fchmod(cls): return cls._bit("fchmod")
    @classmethod
    def fchown(cls): return cls._bit("fchown")
    @classmethod
    def fstat(cls): return cls._bit("fstat")
    @classmethod
    def fsync(cls): return cls._bit("fsync")
    @classmethod
    def ftruncate(cls): return cls._bit("ftruncate")
    @classmethod
    def linkat(cls): return cls._bit("linkat")
    @classmethod
    def symlinkat(cls): return cls._bit("symlinkat")
    @classmethod
    def lookup(cls): return cls._bit("lookup")
    @classmethod
    def mkdirat(cls): return cls._bit("mkdirat")
    @classmethod
    def unlinkat(cls): return cls._bit("unlinkat")
    @classmethod
    def renameat(cls): return cls._bit("renameat")


# FileCaps-name → Cap bits (≙ CapRights0.from's FileCaps mapping).
_FILECAPS = {
    "create": ("creat",),
    "chmod": ("fchmod",),
    "chown": ("fchown",),
    "link": ("linkat", "symlinkat"),
    "lookup": ("lookup",),
    "mkdir": ("mkdirat",),
    "read": ("read",),
    "remove": ("unlinkat",),
    "rename": ("renameat",),
    "seek": ("seek", "mmap"),
    "stat": ("fstat",),
    "sync": ("fsync",),
    "truncate": ("ftruncate",),
    "write": ("write",),
}


class CapRights:
    """A mutable rights set (≙ capsicum/cap_rights.pony CapRights0)."""

    def __init__(self):
        self._bits = 0

    @classmethod
    def from_caps(cls, caps) -> "CapRights":
        """Build from FileCaps-style names (≙ CapRights0.from)."""
        r = cls()
        for name in caps:
            for capname in _FILECAPS.get(name, ()):
                r._bits |= Cap._bit(capname)
        return r

    def set(self, cap: int) -> "CapRights":
        self._bits |= cap
        return self

    def unset(self, cap: int) -> "CapRights":
        self._bits &= ~cap
        return self

    def merge(self, that: "CapRights") -> "CapRights":
        self._bits |= that._bits
        return self

    def remove(self, that: "CapRights") -> "CapRights":
        self._bits &= ~that._bits
        return self

    def clear(self) -> "CapRights":
        self._bits = 0
        return self

    def contains(self, that: "CapRights") -> bool:
        """True when every right in `that` is in this set
        (≙ CapRights0.contains)."""
        return (that._bits & ~self._bits) == 0

    def limit(self, fd: int) -> bool:
        """Apply to a descriptor. No capsicum on Linux → success no-op,
        exactly the reference's non-FreeBSD compile (cap_rights.pony:
        70-78)."""
        return True
