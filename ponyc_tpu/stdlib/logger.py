"""Severity-gated logging — ≙ packages/logger.

The reference's Logger[A] evaluates its log-level guard *at the call
site* (so formatting work is skipped below threshold) and funnels
output through an OutStream actor. Same shape: a Logger with a level
gate whose `call`-style guard skips formatting, writing through a
host sink (stderr by default, or any file-like / File object).

    log = Logger(WARN)
    if log(INFO):                   # cheap guard, message not built
        log.log(f"expensive {x}")
    log.warn("something odd")       # guard + log in one
"""

from __future__ import annotations

import sys
import time
from typing import Any, Callable, Optional

FINE, INFO, WARN, ERROR = 0, 1, 2, 3
_NAMES = {FINE: "FINE", INFO: "INFO", WARN: "WARN", ERROR: "ERROR"}


def _default_formatter(level: int, msg: str, loc: Optional[str]) -> str:
    ts = time.strftime("%H:%M:%S")
    where = f" {loc}" if loc else ""
    return f"{ts} {_NAMES.get(level, '?')}{where}: {msg}"


class Logger:
    """≙ logger/logger.pony: level guard + formatter + out stream."""

    def __init__(self, level: int = WARN, *, out=None,
                 formatter: Callable = _default_formatter):
        self.level = level
        self.out = out if out is not None else sys.stderr
        self.formatter = formatter

    def __call__(self, level: int) -> bool:
        """The guard (≙ Logger.apply): true if `level` would emit."""
        return level >= self.level

    def log(self, msg: Any, level: int = INFO,
            loc: Optional[str] = None) -> bool:
        if not self(level):
            return False
        line = self.formatter(level, str(msg), loc)
        w = getattr(self.out, "print", None)
        if callable(w):                       # files.File sink
            w(line)
        else:
            print(line, file=self.out)
        return True

    def fine(self, msg: Any) -> bool:
        return self.log(msg, FINE)

    def info(self, msg: Any) -> bool:
        return self.log(msg, INFO)

    def warn(self, msg: Any) -> bool:
        return self.log(msg, WARN)

    def error(self, msg: Any) -> bool:
        return self.log(msg, ERROR)
