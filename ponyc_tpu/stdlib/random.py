"""Randomness for behaviours — ≙ packages/random.

The reference ships splittable xoroshiro/xorshift generators whose state
lives in each actor's fields. The TPU idiom is *counter-based* hashing
(threefry, what jax.random uses): a behaviour derives an independent
sample from (seed, actor_id, step, draw-index) with pure arithmetic — no
per-actor generator state to store, no sequential dependence to break
vectorisation. Device-side helpers are trace-safe and vmap over the
cohort for free.

    @behaviour
    def jump(self, st, step: I32):
        r = random.uniform(self.actor_id, step)        # f32 in [0,1)
        k = random.randint(self.actor_id, step, 0, 64, draw=1)
        ...

Host-side, `Rand` mirrors the reference's object API (next/int/real)
for driver code and tests.
"""

from __future__ import annotations

import jax.numpy as jnp

_DEFAULT_SEED = 0x5DEECE66


def _mix(a, b):
    """One 64→32 threefry-ish mixing round pair on i32 lanes (cheap,
    statistically fine for actor workloads; swap for jax.random in
    cryptographic contexts)."""
    a = jnp.asarray(a, jnp.uint32)
    b = jnp.asarray(b, jnp.uint32)
    x = a * jnp.uint32(0x9E3779B9) + b
    x = (x ^ (x >> 16)) * jnp.uint32(0x85EBCA6B)
    x = (x ^ (x >> 13)) * jnp.uint32(0xC2B2AE35)
    return x ^ (x >> 16)


def bits(actor_id, step, draw: int = 0, seed: int = _DEFAULT_SEED):
    """32 uniform bits per (actor, step, draw) — the counter-based core."""
    h = _mix(jnp.asarray(seed, jnp.uint32), actor_id)
    h = _mix(h, step)
    return _mix(h, jnp.asarray(draw, jnp.uint32))


def uniform(actor_id, step, draw: int = 0, seed: int = _DEFAULT_SEED):
    """f32 in [0, 1) (≙ Random.real)."""
    return (bits(actor_id, step, draw, seed) >> 8).astype(
        jnp.float32) * jnp.float32(1.0 / (1 << 24))


def randint(actor_id, step, lo, hi, draw: int = 0,
            seed: int = _DEFAULT_SEED):
    """i32 in [lo, hi) (≙ Random.int)."""
    span = jnp.asarray(hi - lo, jnp.uint32)
    return (jnp.asarray(lo, jnp.int32)
            + (bits(actor_id, step, draw, seed) % span).astype(jnp.int32))


class Rand:
    """Sequential host-side generator with the reference's object API
    (packages/random/random.pony: next/int/real/shuffle)."""

    def __init__(self, seed: int = _DEFAULT_SEED):
        self._s = seed & 0xFFFFFFFF
        self._i = 0

    def next(self) -> int:
        self._i += 1
        x = (self._s + self._i * 0x9E3779B9) & 0xFFFFFFFF
        x = ((x ^ (x >> 16)) * 0x85EBCA6B) & 0xFFFFFFFF
        x = ((x ^ (x >> 13)) * 0xC2B2AE35) & 0xFFFFFFFF
        return x ^ (x >> 16)

    def int(self, n: int) -> int:
        return self.next() % n

    def real(self) -> float:
        return (self.next() >> 8) / float(1 << 24)

    def shuffle(self, xs) -> None:
        for i in range(len(xs) - 1, 0, -1):
            j = self.int(i + 1)
            xs[i], xs[j] = xs[j], xs[i]
