"""Bureaucracy — ≙ packages/bureaucracy (Custodian + Registrar).

Custodian collects things to shut down together (custodian.pony);
Registrar is a name → value directory whose lookups return promises
(registrar.pony). Both are *bookkeeping* actors in the reference —
host-side state with asynchronous lookups — so the TPU twin keeps them
host-resident (the main-thread-actor pattern: engine.py module docs)
with stdlib.promises for the async lookup surface.

    cust = Custodian()
    cust.apply(conn)                      # anything with dispose()
    cust.apply_actor(rt, aid, T.dispose)  # device/host actor behaviour
    cust.dispose()

    reg = Registrar()
    reg.update("db", pool)
    reg.apply("db").next(lambda v: ...)   # promise, ≙ registrar lookup
    reg.remove("db", pool)
"""

from __future__ import annotations

from typing import Any, Dict, List

from .promises import Promise


class Custodian:
    """Dispose a set of things at once (≙ bureaucracy/custodian.pony:
    dispose() disposes every actor in the set, then clears it)."""

    def __init__(self):
        self._items: List[Any] = []

    def apply(self, disposable) -> "Custodian":
        """Add something with a dispose()/close()/stop() method."""
        self._items.append(("obj", disposable))
        return self

    def apply_actor(self, rt, actor_id: int, bdef, *args) -> "Custodian":
        """Add an actor: dispose() sends `bdef(*args)` to it (the
        reference's set holds `DisposableActor tag` refs and sends
        dispose() — here the behaviour is explicit)."""
        self._items.append(("actor", (rt, int(actor_id), bdef, args)))
        return self

    def dispose(self) -> None:
        for kind, it in reversed(self._items):
            if kind == "actor":
                rt, aid, bdef, args = it
                rt.send(aid, bdef, *args)
            else:
                for meth in ("dispose", "close", "stop"):
                    fn = getattr(it, meth, None)
                    if callable(fn):
                        fn()
                        break
        self._items.clear()


class Registrar:
    """Name → value directory with promise-based lookup
    (≙ bureaucracy/registrar.pony)."""

    def __init__(self, rt=None):
        self._rt = rt
        self._map: Dict[str, Any] = {}

    def update(self, key: str, value) -> None:
        """Add or change a mapping (≙ Registrar.update)."""
        self._map[key] = value

    def remove(self, key: str, value) -> None:
        """Remove only if `key` still maps to `value`
        (≙ Registrar.remove's guarded removal)."""
        if self._map.get(key) is value:
            del self._map[key]

    def apply(self, key: str) -> Promise:
        """Lookup by name: a promise fulfilled with the value, or
        rejected if absent (≙ Registrar.apply returning Promise[A])."""
        p = Promise(self._rt)
        if key in self._map:
            p.fulfil(self._map[key])
        else:
            p.reject()
        return p
