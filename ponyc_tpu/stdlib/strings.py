"""String utilities — ≙ the reference's `packages/strings/`
(common_prefix.pony)."""

from __future__ import annotations

from typing import Iterable

__all__ = ["CommonPrefix"]


class CommonPrefix:
    """Longest common prefix of a sequence of strings
    (≙ common_prefix.pony: CommonPrefix(["doable"; "doing"]) == "do")."""

    def __new__(cls, data: Iterable) -> str:
        strs = [s if isinstance(s, str) else str(s) for s in data]
        if not strs:
            return ""
        prefix = strs[0]
        for s in strs[1:]:
            while not s.startswith(prefix):
                prefix = prefix[:-1]
                if not prefix:
                    return ""
        return prefix
