"""Assert — ≙ packages/assert (Assert: debug-only, Fact: always-on).

Both raise a Pony `error` on failure after printing the message to
stderr (assert.pony); here that's errors.PonyError so `pony_try`
catches them like any behaviour error. Assert follows the same debug
configuration as stdlib.debug (`__debug__` / PONY_TPU_DEBUG).

    from ponyc_tpu.stdlib.assertion import Assert, Fact
    Fact(x > 0, "x must be positive")     # always checked
    Assert(invariant(), "debug check")    # compiled away under -O
"""

from __future__ import annotations

import sys

from ..errors import PonyError
from .debug import _enabled


def Fact(test: bool, msg: str = "") -> None:
    """Always-enabled assertion (≙ assert.pony `primitive Fact`)."""
    if not test:
        if msg:
            print(msg, file=sys.stderr)
            sys.stderr.flush()
        raise PonyError(1, msg or "Fact failed")


def Assert(test: bool, msg: str = "") -> None:
    """Debug-only assertion (≙ assert.pony `primitive Assert`:
    `ifdef debug then Fact(...)`)."""
    if _enabled():
        Fact(test, msg)
