"""ANSI terminal support — ≙ the reference's `packages/term/`
(ansi.pony codes; readline.pony's line editing is host-side input and
maps to Python's input()/readline, documented divergence).

ANSI is a primitive namespace of escape-code constructors, exactly the
reference's surface: colors, bright variants, bold/underline/blink/
reverse, reset, cursor movement, erase, and terminal size.
"""

from __future__ import annotations

import os
import shutil
from typing import Tuple

__all__ = ["ANSI"]

_ESC = "\x1b["


class ANSI:
    """≙ ansi.pony ANSI primitive."""

    @staticmethod
    def up(n: int = 1) -> str:
        return f"{_ESC}{n}A" if n else ""

    @staticmethod
    def down(n: int = 1) -> str:
        return f"{_ESC}{n}B" if n else ""

    @staticmethod
    def right(n: int = 1) -> str:
        return f"{_ESC}{n}C" if n else ""

    @staticmethod
    def left(n: int = 1) -> str:
        return f"{_ESC}{n}D" if n else ""

    @staticmethod
    def cursor(x: int = 0, y: int = 0) -> str:
        return f"{_ESC}{y};{x}H"

    @staticmethod
    def clear() -> str:
        return f"{_ESC}2J"

    @staticmethod
    def erase() -> str:
        """Erase to the left of the cursor (≙ ansi.pony erase)."""
        return f"{_ESC}1K"

    @staticmethod
    def reset() -> str:
        return f"{_ESC}0m"

    @staticmethod
    def bold(state: bool = True) -> str:
        return f"{_ESC}1m" if state else f"{_ESC}22m"

    @staticmethod
    def underline(state: bool = True) -> str:
        return f"{_ESC}4m" if state else f"{_ESC}24m"

    @staticmethod
    def blink(state: bool = True) -> str:
        return f"{_ESC}5m" if state else f"{_ESC}25m"

    @staticmethod
    def reverse(state: bool = True) -> str:
        return f"{_ESC}7m" if state else f"{_ESC}27m"

    @staticmethod
    def size() -> Tuple[int, int]:
        """(rows, columns), env-overridable (≙ ansi.pony size)."""
        try:
            cols = int(os.environ.get("COLUMNS", ""))
            rows = int(os.environ.get("LINES", ""))
            return rows, cols
        except ValueError:
            ts = shutil.get_terminal_size()
            return ts.lines, ts.columns


def _add_colors():
    base = {"black": 0, "red": 1, "green": 2, "yellow": 3, "blue": 4,
            "magenta": 5, "cyan": 6, "white": 7, "grey": None}

    for name, idx in base.items():
        if name == "grey":
            fg, bg = f"{_ESC}90m", f"{_ESC}100m"
        else:
            fg, bg = f"{_ESC}{30 + idx}m", f"{_ESC}{40 + idx}m"
        setattr(ANSI, name, staticmethod(lambda s=fg: s))
        setattr(ANSI, name + "_bg", staticmethod(lambda s=bg: s))
        if idx is not None:
            bright_fg = f"{_ESC}{90 + idx}m"
            bright_bg = f"{_ESC}{100 + idx}m"
            setattr(ANSI, "bright_" + name,
                    staticmethod(lambda s=bright_fg: s))
            setattr(ANSI, "bright_" + name + "_bg",
                    staticmethod(lambda s=bright_bg: s))


_add_colors()
