"""ANSI terminal support — ≙ the reference's `packages/term/`:

- ``ANSI`` — escape-code constructors (≙ ansi.pony): colors, bright
  variants, bold/underline/blink/reverse, reset, cursor movement,
  erase, terminal size.
- ``ANSINotify`` / ``ANSITerm`` — interactive INPUT (≙ ansi_notify.pony,
  ansi_term.pony): an escape-sequence state machine over raw input
  bytes (CSI/SS3 arrows, home/end/insert/delete/page keys, fn keys,
  modifier encodings) dispatching to a notify object; wired to stdin
  through the bridge's fd subscription (≙ lang/stdfd.c feeding the
  stdin actor), or fed bytes directly (tests, embedders).
- ``ReadlineNotify`` / ``Readline`` — line editing (≙ readline.pony,
  readline_notify.pony): edit buffer with cursor movement, emacs-style
  control keys, history (optionally persisted), tab completion, and a
  Promise-driven prompt protocol: each finished line is handed to
  ``notify.apply(line, promise)``; fulfilling the promise sets the next
  prompt, rejecting it closes the terminal.
"""

from __future__ import annotations

import os
import shutil
from typing import List, Optional, Tuple

from .promises import Promise

__all__ = ["ANSI", "ANSINotify", "ANSITerm", "Readline",
           "ReadlineNotify", "attach_stdin"]

_ESC = "\x1b["


class ANSI:
    """≙ ansi.pony ANSI primitive."""

    @staticmethod
    def up(n: int = 1) -> str:
        return f"{_ESC}{n}A" if n else ""

    @staticmethod
    def down(n: int = 1) -> str:
        return f"{_ESC}{n}B" if n else ""

    @staticmethod
    def right(n: int = 1) -> str:
        return f"{_ESC}{n}C" if n else ""

    @staticmethod
    def left(n: int = 1) -> str:
        return f"{_ESC}{n}D" if n else ""

    @staticmethod
    def cursor(x: int = 0, y: int = 0) -> str:
        return f"{_ESC}{y};{x}H"

    @staticmethod
    def clear() -> str:
        return f"{_ESC}2J"

    @staticmethod
    def erase() -> str:
        """Erase to the left of the cursor (≙ ansi.pony erase)."""
        return f"{_ESC}1K"

    @staticmethod
    def reset() -> str:
        return f"{_ESC}0m"

    @staticmethod
    def bold(state: bool = True) -> str:
        return f"{_ESC}1m" if state else f"{_ESC}22m"

    @staticmethod
    def underline(state: bool = True) -> str:
        return f"{_ESC}4m" if state else f"{_ESC}24m"

    @staticmethod
    def blink(state: bool = True) -> str:
        return f"{_ESC}5m" if state else f"{_ESC}25m"

    @staticmethod
    def reverse(state: bool = True) -> str:
        return f"{_ESC}7m" if state else f"{_ESC}27m"

    @staticmethod
    def size() -> Tuple[int, int]:
        """(rows, columns), env-overridable (≙ ansi.pony size)."""
        try:
            cols = int(os.environ.get("COLUMNS", ""))
            rows = int(os.environ.get("LINES", ""))
            return rows, cols
        except ValueError:
            ts = shutil.get_terminal_size()
            return ts.lines, ts.columns


class ANSINotify:
    """Receive parsed input from an ANSITerm (≙ ansi_notify.pony).
    Override the keys you care about; every hook defaults to no-op."""

    def apply(self, term: "ANSITerm", byte: int) -> None:
        """A plain input byte (printable or control)."""

    def up(self, ctrl=False, alt=False, shift=False) -> None: ...
    def down(self, ctrl=False, alt=False, shift=False) -> None: ...
    def left(self, ctrl=False, alt=False, shift=False) -> None: ...
    def right(self, ctrl=False, alt=False, shift=False) -> None: ...
    def delete(self, ctrl=False, alt=False, shift=False) -> None: ...
    def insert(self, ctrl=False, alt=False, shift=False) -> None: ...
    def home(self, ctrl=False, alt=False, shift=False) -> None: ...
    def end_key(self, ctrl=False, alt=False, shift=False) -> None: ...
    def page_up(self, ctrl=False, alt=False, shift=False) -> None: ...
    def page_down(self, ctrl=False, alt=False, shift=False) -> None: ...
    def fn_key(self, i, ctrl=False, alt=False, shift=False) -> None: ...
    def prompt(self, term: "ANSITerm", value: str) -> None: ...
    def size(self, rows: int, cols: int) -> None: ...
    def closed(self) -> None: ...


# Escape-parser states (≙ the _EscapeState primitives of ansi_term.pony;
# the machine itself is the standard VT100/xterm CSI/SS3 grammar).
_ES_NONE, _ES_START, _ES_SS3, _ES_CSI = range(4)

# CSI final letters → notify hook name (standard xterm keymap).
_CSI_LETTER = {ord("A"): "up", ord("B"): "down", ord("C"): "right",
               ord("D"): "left", ord("H"): "home", ord("F"): "end_key"}
# CSI `<n>~` numbers → hook name (vt220 keymap).
_CSI_TILDE = {1: "home", 2: "insert", 3: "delete", 4: "end_key",
              5: "page_up", 6: "page_down", 7: "home", 8: "end_key"}
# CSI `<n>~` function-key numbers (vt220: 11-15, 17-21, 23-24 → F1-F12).
_CSI_FN = {11: 1, 12: 2, 13: 3, 14: 4, 15: 5, 17: 6, 18: 7, 19: 8,
           20: 9, 21: 10, 23: 11, 24: 12}
# SS3 finals (application keypad): arrows, home/end, PF1-PF4.
_SS3 = {ord("A"): ("up", 0), ord("B"): ("down", 0),
        ord("C"): ("right", 0), ord("D"): ("left", 0),
        ord("H"): ("home", 0), ord("F"): ("end_key", 0),
        ord("P"): ("fn_key", 1), ord("Q"): ("fn_key", 2),
        ord("R"): ("fn_key", 3), ord("S"): ("fn_key", 4)}


class ANSITerm:
    """Parses ANSI escape codes from an input byte stream and dispatches
    to an ANSINotify (≙ the ANSITerm actor of ansi_term.pony).

    Feed bytes with ``apply(data)`` — from the bridge's stdin fd
    subscription (``attach_stdin``) or directly (tests, embedders).
    """

    def __init__(self, notify: ANSINotify, out=None):
        self._notify = notify
        self._out = out
        self._state = _ES_NONE
        self._params: List[int] = []
        self._num = 0
        self._have_num = False
        self._closed = False
        self._dispose_hooks: List = []
        self.size()

    def add_dispose_hook(self, fn) -> None:
        """Run `fn()` when this terminal is disposed, whatever the close
        path (EOF, ctrl-d, rejected prompt) — tty-mode restoration and
        fd unsubscription hang here (attach_stdin)."""
        self._dispose_hooks.append(fn)

    # -- input (≙ `be apply(data: Array[U8] iso)`) --
    def apply(self, data: bytes) -> None:
        if self._closed:
            return
        for b in bytes(data):
            self._byte(b)

    def _byte(self, b: int) -> None:
        if self._state == _ES_NONE:
            if b == 0x1B:
                self._state = _ES_START
                self._params, self._num, self._have_num = [], 0, False
            else:
                self._notify.apply(self, b)
            return
        if self._state == _ES_START:
            if b == ord("["):
                self._state = _ES_CSI
            elif b == ord("O"):
                self._state = _ES_SS3
            else:
                # Bare ESC followed by a plain byte: deliver both.
                self._state = _ES_NONE
                self._notify.apply(self, 0x1B)
                self._byte(b)
            return
        if self._state == _ES_SS3:
            self._state = _ES_NONE
            ent = _SS3.get(b)
            if ent is not None:
                name, fn = ent
                if name == "fn_key":
                    self._notify.fn_key(fn)
                else:
                    getattr(self._notify, name)()
            return
        # _ES_CSI: params are digits separated by ';', then a final byte.
        if ord("0") <= b <= ord("9"):
            self._num = self._num * 10 + (b - ord("0"))
            self._have_num = True
            return
        if b == ord(";"):
            self._params.append(self._num if self._have_num else 0)
            self._num, self._have_num = 0, False
            return
        if self._have_num:
            self._params.append(self._num)
        self._state = _ES_NONE
        # xterm modifier encoding: second parameter = 1 + bitfield
        # (1=shift, 2=alt, 4=ctrl).
        mod = (self._params[1] - 1) if len(self._params) > 1 else 0
        shift, alt, ctrl = bool(mod & 1), bool(mod & 2), bool(mod & 4)
        if b == ord("~"):
            n = self._params[0] if self._params else 0
            if n in _CSI_FN:
                self._notify.fn_key(_CSI_FN[n], ctrl, alt, shift)
            elif n in _CSI_TILDE:
                getattr(self._notify, _CSI_TILDE[n])(ctrl, alt, shift)
            return
        name = _CSI_LETTER.get(b)
        if name is not None:
            getattr(self._notify, name)(ctrl, alt, shift)

    # -- control surface (≙ ANSITerm.prompt/size/dispose) --
    def prompt(self, value: str) -> None:
        self._notify.prompt(self, value)

    def size(self) -> None:
        rows, cols = ANSI.size()
        self._notify.size(rows, cols)

    def dispose(self) -> None:
        if not self._closed:
            self._closed = True
            self._notify.closed()
            hooks, self._dispose_hooks = self._dispose_hooks, []
            for fn in hooks:
                try:
                    fn()
                except Exception:        # noqa: BLE001 — best-effort
                    pass

    @property
    def closed(self) -> bool:
        return self._closed


class ReadlineNotify:
    """Receives finished lines (≙ readline_notify.pony). The next
    prompt is set by fulfilling the promise; rejecting it stops input."""

    def apply(self, line: str, prompt: Promise) -> None:
        """Handle one finished line."""

    def tab(self, line: str) -> List[str]:
        """Return tab-completion possibilities for `line`."""
        return []


class Readline(ANSINotify):
    """Line editing, history, and tab completion (≙ readline.pony).

    Pass as the notify of an ANSITerm; write output (prompt echo,
    cursor redraws) to `out` (any .write(str)+.flush() object)."""

    def __init__(self, notify: ReadlineNotify, out, path: Optional[str]
                 = None, maxlen: int = 0):
        import codecs
        self._notify = notify
        self._out = out
        self._path = path
        self._maxlen = maxlen
        self._history: List[str] = []
        self._edit = ""
        self._cur_prompt = ""
        self._cur_line = 0        # history cursor
        self._pos = 0             # cursor position within _edit
        self._blocked = True      # begins blocked until a prompt is set
        # UTF-8 input arrives byte-at-a-time; buffer multi-byte
        # sequences so 'é' inserts ONE character with correct cursor
        # math (the reference round-trips raw bytes; a Python str edit
        # buffer must decode).
        self._u8 = codecs.getincrementaldecoder("utf-8")(errors="replace")
        self._load_history()

    # ---- ANSINotify hooks ----
    def apply(self, term: ANSITerm, byte: int) -> None:
        if self._blocked:
            return
        if byte == 0x01:                       # ctrl-a
            self.home()
        elif byte == 0x02:                     # ctrl-b
            self.left()
        elif byte == 0x04:                     # ctrl-d
            if not self._edit:
                self._out.write("\n")
                term.dispose()
            else:
                self.delete()
        elif byte == 0x05:                     # ctrl-e
            self.end_key()
        elif byte == 0x06:                     # ctrl-f
            self.right()
        elif byte in (0x08, 0x7F):             # ctrl-h / backspace
            self._backspace()
        elif byte == 0x09:                     # tab
            self._tab()
        elif byte in (0x0A, 0x0D):             # LF / CR
            self._dispatch(term)
        elif byte == 0x0B:                     # ctrl-k: kill to end
            self._edit = self._edit[:self._pos]
            self._refresh()
        elif byte == 0x0E:                     # ctrl-n
            self.down()
        elif byte == 0x10:                     # ctrl-p
            self.up()
        elif byte == 0x15:                     # ctrl-u: kill line
            self._edit, self._pos = "", 0
            self._refresh()
        elif byte >= 0x20:                     # printable: insert
            ch = self._u8.decode(bytes([byte]))
            if ch:                             # complete codepoint(s)
                self._edit = (self._edit[:self._pos] + ch
                              + self._edit[self._pos:])
                self._pos += len(ch)
                self._refresh()

    def up(self, ctrl=False, alt=False, shift=False) -> None:
        if self._cur_line > 0:
            self._cur_line -= 1
            self._edit = self._history[self._cur_line]
            self._pos = len(self._edit)
            self._refresh()

    def down(self, ctrl=False, alt=False, shift=False) -> None:
        if self._cur_line < len(self._history) - 1:
            self._cur_line += 1
            self._edit = self._history[self._cur_line]
        else:
            self._cur_line = len(self._history)
            self._edit = ""
        self._pos = len(self._edit)
        self._refresh()

    def left(self, ctrl=False, alt=False, shift=False) -> None:
        if self._pos > 0:
            self._pos -= 1
            self._refresh()

    def right(self, ctrl=False, alt=False, shift=False) -> None:
        if self._pos < len(self._edit):
            self._pos += 1
            self._refresh()

    def home(self, ctrl=False, alt=False, shift=False) -> None:
        self._pos = 0
        self._refresh()

    def end_key(self, ctrl=False, alt=False, shift=False) -> None:
        self._pos = len(self._edit)
        self._refresh()

    def delete(self, ctrl=False, alt=False, shift=False) -> None:
        if self._pos < len(self._edit):
            self._edit = (self._edit[:self._pos]
                          + self._edit[self._pos + 1:])
            self._refresh()

    def prompt(self, term: ANSITerm, value: str) -> None:
        self._cur_prompt = value
        self._blocked = False
        self._edit, self._pos = "", 0
        self._cur_line = len(self._history)
        self._refresh()

    def closed(self) -> None:
        self._save_history()
        self._notify_closed()

    def _notify_closed(self) -> None:
        closed = getattr(self._notify, "closed", None)
        if callable(closed):
            closed()

    # ---- internals (≙ readline.pony private fns) ----
    def _backspace(self) -> None:
        if self._pos > 0:
            self._edit = (self._edit[:self._pos - 1]
                          + self._edit[self._pos:])
            self._pos -= 1
            self._refresh()

    def _tab(self) -> None:
        options = list(self._notify.tab(self._edit[:self._pos]))
        if len(options) == 1:
            self._edit = options[0] + self._edit[self._pos:]
            self._pos = len(options[0])
            self._refresh()
        elif len(options) > 1:
            # Show the candidates, then redraw the line under them.
            self._out.write("\n" + "  ".join(options) + "\n")
            self._refresh()

    def _dispatch(self, term: ANSITerm) -> None:
        line = self._edit
        self._out.write("\n")
        self._blocked = True
        self._edit, self._pos = "", 0
        if line:
            if self._maxlen and len(self._history) >= self._maxlen:
                self._history.pop(0)
            self._history.append(line)
            self._cur_line = len(self._history)
        p = Promise()
        p.next(lambda new_prompt: self.prompt(term, str(new_prompt)),
               rejected=lambda _r: term.dispose())
        self._notify.apply(line, p)

    def _refresh(self) -> None:
        # Redraw: CR, erase line right of cursor start, prompt + edit,
        # then park the cursor (≙ readline.pony _refresh_line).
        move_back = len(self._edit) - self._pos
        out = ("\r" + f"{_ESC}0K" + self._cur_prompt + self._edit
               + (ANSI.left(move_back) if move_back else ""))
        self._out.write(out)
        flush = getattr(self._out, "flush", None)
        if callable(flush):
            flush()

    def _load_history(self) -> None:
        if not self._path:
            return
        try:
            with open(self._path, "r", encoding="utf-8") as f:
                self._history = [ln.rstrip("\n") for ln in f
                                 if ln.rstrip("\n")]
            if self._maxlen:
                self._history = self._history[-self._maxlen:]
        except OSError:
            pass
        self._cur_line = len(self._history)

    def _save_history(self) -> None:
        if not self._path:
            return
        try:
            with open(self._path, "w", encoding="utf-8") as f:
                for ln in self._history:
                    f.write(ln + "\n")
        except OSError:
            pass


def attach_stdin(rt, term: ANSITerm, *, noisy: bool = True) -> int:
    """Wire an ANSITerm to real stdin through the runtime's bridge
    (≙ the stdin actor fed by lang/stdfd.c): raw bytes arrive at
    ``term.apply`` at host poll boundaries. Puts the tty in cbreak mode
    when stdin is a terminal — restored on EVERY close path (EOF,
    ctrl-d, rejected prompt, interpreter exit) via the terminal's
    dispose hooks + atexit. Returns the subscription id."""
    import atexit
    import sys

    bridge = rt.attach_bridge()
    fd = sys.stdin.fileno()
    restore = None
    if os.isatty(fd):
        try:
            import termios
            import tty
            old = termios.tcgetattr(fd)
            tty.setcbreak(fd)
            done = []

            def restore():
                if not done:             # idempotent
                    done.append(True)
                    termios.tcsetattr(fd, termios.TCSADRAIN, old)
            atexit.register(restore)
        except (ImportError, OSError):
            restore = None

    def on_ready(_ev):
        try:
            data = os.read(fd, 1024)
        except OSError:
            data = b""
        if data:
            term.apply(data)
        else:
            term.dispose()

    sid = bridge.fd_callback(fd, on_ready, noisy=noisy)

    def cleanup():
        if restore is not None:
            restore()
        bridge.unsubscribe(sid)
    term.add_dispose_hook(cleanup)
    return sid


def _add_colors():
    base = {"black": 0, "red": 1, "green": 2, "yellow": 3, "blue": 4,
            "magenta": 5, "cyan": 6, "white": 7, "grey": None}

    for name, idx in base.items():
        if name == "grey":
            fg, bg = f"{_ESC}90m", f"{_ESC}100m"
        else:
            fg, bg = f"{_ESC}{30 + idx}m", f"{_ESC}{40 + idx}m"
        setattr(ANSI, name, staticmethod(lambda s=fg: s))
        setattr(ANSI, name + "_bg", staticmethod(lambda s=bg: s))
        if idx is not None:
            bright_fg = f"{_ESC}{90 + idx}m"
            bright_bg = f"{_ESC}{100 + idx}m"
            setattr(ANSI, "bright_" + name,
                    staticmethod(lambda s=bright_fg: s))
            setattr(ANSI, "bright_" + name + "_bg",
                    staticmethod(lambda s=bright_bg: s))


_add_colors()
