"""Buffered byte-stream reading/writing — ≙ the reference's
`packages/buffered/` (reader.pony, writer.pony).

Reader accumulates incoming chunks (e.g. TCP segments) without copying
until a read spans chunks; reads raise IncompleteError (≙ Pony `error`)
when not enough data has arrived, leaving the buffer intact so the
caller can retry after the next append — the exact protocol-decoder
workflow packages/net code uses.

Writer accumulates typed big/little-endian writes and hands back the
chunk list (`done()`), ready for a writev-style scatter send.
"""

from __future__ import annotations

import struct
from typing import List, Union

__all__ = ["Reader", "Writer", "IncompleteError"]


class IncompleteError(Exception):
    """Not enough buffered data (≙ Pony `error` from Reader.read_*)."""


class Reader:
    """≙ buffered/reader.pony."""

    def __init__(self):
        self._chunks: List[bytes] = []
        self._size = 0
        self._offset = 0          # consumed prefix of _chunks[0]

    def size(self) -> int:
        return self._size

    def clear(self) -> None:
        self._chunks = []
        self._size = 0
        self._offset = 0

    def append(self, data: Union[bytes, bytearray, str]) -> None:
        if isinstance(data, str):
            data = data.encode()
        if data:
            self._chunks.append(bytes(data))
            self._size += len(data)

    def skip(self, n: int) -> None:
        if n > self._size:
            raise IncompleteError(n)
        self._take(n)

    def block(self, n: int) -> bytes:
        """Read exactly n bytes (≙ reader.pony block)."""
        if n > self._size:
            raise IncompleteError(n)
        return self._take(n)

    def read_until(self, sep: int) -> bytes:
        """Bytes up to (excluding) separator byte; separator consumed."""
        idx = self._find(sep)
        if idx < 0:
            raise IncompleteError(sep)
        out = self._take(idx)
        self._take(1)
        return out

    def line(self) -> str:
        r"""One text line, \n or \r\n terminated (≙ reader.pony line)."""
        idx = self._find(0x0A)
        if idx < 0:
            raise IncompleteError("line")
        raw = self._take(idx)
        self._take(1)
        if raw.endswith(b"\r"):
            raw = raw[:-1]
        return raw.decode()

    def peek_u8(self, offset: int = 0) -> int:
        if offset >= self._size:
            raise IncompleteError(offset)
        pos = self._offset + offset
        for ch in self._chunks:
            if pos < len(ch):
                return ch[pos]
            pos -= len(ch)
        raise IncompleteError(offset)

    # -- typed reads: u8..u64 / i8..i64 / f32 / f64, be + le --
    def _take(self, n: int) -> bytes:
        out = bytearray()
        need = n
        while need:
            ch = self._chunks[0]
            avail = len(ch) - self._offset
            take = min(avail, need)
            out += ch[self._offset:self._offset + take]
            need -= take
            self._offset += take
            if self._offset == len(ch):
                self._chunks.pop(0)
                self._offset = 0
        self._size -= n
        return bytes(out)

    def _find(self, byte: int) -> int:
        pos = 0
        off = self._offset
        for ch in self._chunks:
            idx = ch.find(byte, off)
            if idx >= 0:
                return pos + idx - off
            pos += len(ch) - off
            off = 0
        return -1


class Writer:
    """≙ buffered/writer.pony: typed appends, chunk-list output."""

    def __init__(self):
        self._chunks: List[bytes] = []
        self._size = 0

    def size(self) -> int:
        return self._size

    def write(self, data: Union[bytes, bytearray, str]) -> "Writer":
        if isinstance(data, str):
            data = data.encode()
        if data:
            self._chunks.append(bytes(data))
            self._size += len(data)
        return self

    def writev(self, chunks) -> "Writer":
        for c in chunks:
            self.write(c)
        return self

    def done(self) -> List[bytes]:
        """Hand back the accumulated chunks and reset (≙ writer done)."""
        out = self._chunks
        self._chunks = []
        self._size = 0
        return out


def _add_numeric(fmt: str, name: str, size: int):
    def read_be(self: Reader) -> Union[int, float]:
        return struct.unpack(">" + fmt, self.block(size))[0]

    def read_le(self: Reader) -> Union[int, float]:
        return struct.unpack("<" + fmt, self.block(size))[0]

    def peek_be(self: Reader, offset: int = 0):
        if offset + size > self.size():
            raise IncompleteError(name)
        b = bytes(self.peek_u8(offset + i) for i in range(size))
        return struct.unpack(">" + fmt, b)[0]

    def write_be(self: Writer, v) -> Writer:
        return self.write(struct.pack(">" + fmt, v))

    def write_le(self: Writer, v) -> Writer:
        return self.write(struct.pack("<" + fmt, v))

    setattr(Reader, name + "_be", read_be)
    setattr(Reader, name + "_le", read_le)
    setattr(Reader, "peek_" + name + "_be", peek_be)
    setattr(Writer, name + "_be", write_be)
    setattr(Writer, name + "_le", write_le)
    if size == 1:
        setattr(Reader, name, read_be)
        setattr(Writer, name, write_be)


for _fmt, _name, _size in [("B", "u8", 1), ("b", "i8", 1),
                           ("H", "u16", 2), ("h", "i16", 2),
                           ("I", "u32", 4), ("i", "i32", 4),
                           ("Q", "u64", 8), ("q", "i64", 8),
                           ("f", "f32", 4), ("d", "f64", 8)]:
    _add_numeric(_fmt, _name, _size)
