"""Package resolution with safe-package capability control.

≙ the reference's `use` resolution + safe packages
(src/libponyc/pkg/package.c:615-630, 685-692: `--safe pkg1:pkg2`
records a safe list, and any package NOT on it gets `allow_ffi =
false` — i.e. unlisted packages lose the right to touch the OS).
Python imports subsume the *mechanics* of `use`; this module restores
the *capability control*: when a safe list is active, `use()` refuses
to hand out the FFI-reaching packages (the ones built on
ponyc_tpu.native / OS syscalls) unless they are listed.

    from ponyc_tpu.stdlib import pkg
    pkg.set_safe_packages(["files"])      # ≙ ponyc --safe files
    files = pkg.use("files")              # listed: ok
    json  = pkg.use("json")               # pure package: always ok
    net   = pkg.use("net")                # PermissionError

The list also comes from the environment (PONY_TPU_SAFE=files:net) and
from the CLI driver (`python -m ponyc_tpu run --safe files:net app.py`),
mirroring how the reference's flag reaches package.c. Unrestricted by
default, exactly like ponyc without --safe.

This is voluntary-discipline capability control, like every ambient-auth
token in this stdlib (files.FilesAuth, AmbientAuth): Python can always
`import` around it, just as Pony code could link around a missing FFI
right only by recompiling — the gate is for the code you run, not the
code you wrote maliciously.
"""

from __future__ import annotations

import importlib
import os
from typing import Iterable, List, Optional

# Packages whose implementation reaches the OS/native layer (the
# moral equivalent of containing FFI; package.c's allow_ffi subjects).
FFI_PACKAGES = frozenset(
    {"net", "files", "process", "signals", "timers", "term"})

# Package name → import path (the `use` search path, collapsed to the
# stdlib map in stdlib/__init__.py's docstring).
_RESOLVE = {
    "assertion": "ponyc_tpu.stdlib.assertion",
    "assert": "ponyc_tpu.stdlib.assertion",
    "backpressure": "ponyc_tpu.stdlib.backpressure",
    "buffered": "ponyc_tpu.stdlib.buffered",
    "bureaucracy": "ponyc_tpu.stdlib.bureaucracy",
    "capsicum": "ponyc_tpu.stdlib.capsicum",
    "cli": "ponyc_tpu.stdlib.cli",
    "collections": "ponyc_tpu.stdlib.collections",
    "persistent": "ponyc_tpu.stdlib.persistent",
    "debug": "ponyc_tpu.stdlib.debug",
    "encode": "ponyc_tpu.stdlib.encode",
    "base64": "ponyc_tpu.stdlib.encode",
    "format": "ponyc_tpu.stdlib.format",
    "ini": "ponyc_tpu.stdlib.ini",
    "itertools": "ponyc_tpu.stdlib.itertools",
    "json": "ponyc_tpu.stdlib.json",
    "logger": "ponyc_tpu.stdlib.logger",
    "math": "ponyc_tpu.stdlib.math",
    "promises": "ponyc_tpu.stdlib.promises",
    "random": "ponyc_tpu.stdlib.random",
    "serialise": "ponyc_tpu.stdlib.serialise",
    "strings": "ponyc_tpu.stdlib.strings",
    "term": "ponyc_tpu.stdlib.term",
    "timers": "ponyc_tpu.stdlib.timers",
    "signals": "ponyc_tpu.stdlib.signals",
    "net": "ponyc_tpu.net",
    "files": "ponyc_tpu.files",
    "process": "ponyc_tpu.process",
    "ponytest": "ponyc_tpu.testing",
    "testing": "ponyc_tpu.testing",
    "ponybench": "ponyc_tpu.benching",
    "benching": "ponyc_tpu.benching",
}

_safe: Optional[frozenset] = None       # None = unrestricted


def set_safe_packages(names: Optional[Iterable[str]]) -> None:
    """Activate (or clear, with None) the safe list — ≙ --safe.
    An EMPTY list is maximal restriction: no FFI package resolves."""
    global _safe
    _safe = None if names is None else frozenset(names)


def _active_safe() -> Optional[frozenset]:
    if _safe is not None:
        return _safe
    env = os.environ.get("PONY_TPU_SAFE")
    if env is not None:
        return frozenset(p for p in env.split(":") if p)
    return None


def safe_packages() -> Optional[List[str]]:
    s = _active_safe()
    return sorted(s) if s is not None else None


def use(name: str):
    """Resolve a package by its reference name (≙ `use "name"`),
    enforcing the safe list for FFI-reaching packages."""
    target = _RESOLVE.get(name)
    if target is None:
        raise ImportError(
            f"unknown package {name!r} (≙ 'package not found' from use "
            f"resolution); known: {', '.join(sorted(_RESOLVE))}")
    safe = _active_safe()
    if safe is not None and name in FFI_PACKAGES and name not in safe:
        raise PermissionError(
            f"package {name!r} reaches the OS and is not on the safe "
            f"list {sorted(safe)} (≙ allow_ffi=false, "
            "package.c:624-629); add it via set_safe_packages / "
            "PONY_TPU_SAFE / --safe")
    return importlib.import_module(target)
