"""Standard-library actor utilities — the TPU framework's counterpart of
the reference's packages/ tree (SURVEY.md §2.3).

The reference ships ~31 Pony packages. Their capabilities map here as:

  builtin            → the core framework (api/runtime/engine)
  collections        → stdlib.collections (Flags/Range/heaps/RingBuffer/
                       Sort/Reverse/List) + stdlib.persistent
                       (HAMT Map, trie Vec, cons List, Set)
  json               → stdlib.json (recursive-descent JsonDoc with
                       line-reported errors)
  cli                → stdlib.cli (CommandSpec/OptionSpec/ArgSpec typed
                       parser with sub-commands, help, env fallback)
  buffered           → stdlib.buffered (Reader/Writer chunked codecs)
  encode/base64      → stdlib.encode (configurable-alphabet Base64)
  format             → stdlib.format (Format int/float/string specs)
  itertools          → stdlib.itertools (Iter combinators)
  ini                → stdlib.ini (streaming notify parser + IniMap)
  term               → stdlib.term (ANSI codes)
  strings            → stdlib.strings (CommonPrefix)
  math               → stdlib.math (Fibonacci)
  net                → ponyc_tpu.net (native socket layer underneath)
  files              → ponyc_tpu.files (capability-checked)
  process            → ponyc_tpu.process
  time (Timers)      → stdlib.timers (bridge timerfd underneath)
  promises           → stdlib.promises
  random             → stdlib.random (counter-based threefry so vmapped
                       behaviours draw independent streams — the TPU
                       idiom replacing packages/random's splittable
                       xoroshiro)
  logger             → stdlib.logger (severity-gated, host-side)
  backpressure       → stdlib.backpressure (programmatic apply/release
                       with ApplyReleaseBackpressureAuth) on top of the
                       automatic mute/unmute machinery
  serialise          → ponyc_tpu.serialise
  ponytest           → ponyc_tpu.testing
  ponybench          → ponyc_tpu.benching
  signals            → stdlib.signals (SignalHandler/Sig) over
                       bridge.signal / bridge.sigterm_dump
  options            → config.strip_runtime_flags (runtime flags) +
                       stdlib.cli (application flags)
  bureaucracy        → stdlib.bureaucracy (Custodian incl. actor
                       dispose sends, Registrar with promise lookup)
  capsicum           → stdlib.capsicum (Cap/CapRights algebra; limit()
                       no-ops on Linux as on non-FreeBSD Pony) +
                       files.FilesAuth capability chain
  debug              → stdlib.debug (Debug.out/err, compiled away
                       unless debug-configured) + analysis dumps
  assert             → stdlib.assertion (Assert/Fact raising PonyError)
                       + config.debug_checks invariants (device)
  builtin_test,
  stdlib/_test       → tests/ (the aggregated suite IS the stdlib test
                       binary; conftest runs every package's tests)
"""

from . import (assertion, backpressure, buffered, bureaucracy,  # noqa
               capsicum, cli, collections, debug, encode, format, ini,
               itertools, json, logger, math, persistent, promises,
               random, signals, strings, term, timers)  # noqa: F401
