"""Standard-library actor utilities — the TPU framework's counterpart of
the reference's packages/ tree (SURVEY.md §2.3).

The reference ships ~31 Pony packages. Their capabilities map here as:

  builtin            → the core framework (api/runtime/engine)
  collections, math,
  itertools, format  → Python builtins / numpy / jax.numpy (the host
                       language already provides them; device-side state
                       is fixed-width columns by design)
  net                → ponyc_tpu.net (native socket layer underneath)
  files              → ponyc_tpu.files (capability-checked)
  process            → ponyc_tpu.process
  time (Timers)      → stdlib.timers (bridge timerfd underneath)
  promises           → stdlib.promises
  random             → stdlib.random (counter-based threefry so vmapped
                       behaviours draw independent streams — the TPU
                       idiom replacing packages/random's splittable
                       xoroshiro)
  logger             → stdlib.logger (severity-gated, host-side)
  backpressure       → Runtime mute/unmute machinery (automatic) +
                       queue_depth introspection
  serialise          → ponyc_tpu.serialise
  ponytest           → ponyc_tpu.testing
  ponybench          → ponyc_tpu.benching
  signals            → bridge.signal / bridge.sigterm_dump
  cli/options        → config.strip_runtime_flags + argparse (host)
  buffered, encode,
  ini, json, strings → Python stdlib equivalents (host-side text/bytes)
  bureaucracy        → stdlib.promises.Custodian
  capsicum           → files.FilesAuth capability chain
"""

from . import logger, promises, random, timers  # noqa: F401
