"""Standard-library actor utilities — the TPU framework's counterpart of
the reference's packages/ tree (SURVEY.md §2.3).

The reference ships ~31 Pony packages. Their capabilities map here as:

  builtin            → the core framework (api/runtime/engine)
  collections        → stdlib.collections (Flags/Range/heaps/RingBuffer/
                       Sort/Reverse/List) + stdlib.persistent
                       (HAMT Map, trie Vec, cons List, Set)
  json               → stdlib.json (recursive-descent JsonDoc with
                       line-reported errors)
  cli                → stdlib.cli (CommandSpec/OptionSpec/ArgSpec typed
                       parser with sub-commands, help, env fallback)
  buffered           → stdlib.buffered (Reader/Writer chunked codecs)
  encode/base64      → stdlib.encode (configurable-alphabet Base64)
  format             → stdlib.format (Format int/float/string specs)
  itertools          → stdlib.itertools (Iter combinators)
  ini                → stdlib.ini (streaming notify parser + IniMap)
  term               → stdlib.term (ANSI codes)
  strings            → stdlib.strings (CommonPrefix)
  math               → stdlib.math (Fibonacci)
  net                → ponyc_tpu.net (native socket layer underneath)
  files              → ponyc_tpu.files (capability-checked)
  process            → ponyc_tpu.process
  time (Timers)      → stdlib.timers (bridge timerfd underneath)
  promises           → stdlib.promises
  random             → stdlib.random (counter-based threefry so vmapped
                       behaviours draw independent streams — the TPU
                       idiom replacing packages/random's splittable
                       xoroshiro)
  logger             → stdlib.logger (severity-gated, host-side)
  backpressure       → Runtime mute/unmute machinery (automatic) +
                       queue_depth introspection
  serialise          → ponyc_tpu.serialise
  ponytest           → ponyc_tpu.testing
  ponybench          → ponyc_tpu.benching
  signals            → bridge.signal / bridge.sigterm_dump
  options            → config.strip_runtime_flags (runtime flags) +
                       stdlib.cli (application flags)
  bureaucracy        → stdlib.promises.Custodian
  capsicum           → files.FilesAuth capability chain
  debug              → stdlib.logger + analysis SIGTERM dumps
  assert             → ponyc_tpu.testing asserts (host) +
                       config.debug_checks invariants (device)
  builtin_test,
  stdlib/_test       → tests/ (the aggregated suite IS the stdlib test
                       binary; conftest runs every package's tests)
"""

from . import (buffered, cli, collections, encode, format, ini,  # noqa
               itertools, json, logger, math, persistent, promises,
               random, strings, term, timers)  # noqa: F401
