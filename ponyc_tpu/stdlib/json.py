"""JSON document handling — ≙ the reference's `packages/json/`
(json_doc.pony, json_type.pony, _json_print.pony).

A hand-rolled recursive-descent parser (NOT a thin wrapper over the host
json module) so the API matches the reference's:

  doc = JsonDoc()
  doc.parse(src)          # raises JsonParseError; parse_report() has
                          # (line, message) like json_doc.pony:62-67
  doc.data                # None | bool | int | float | str |
                          # JsonArray | JsonObject
  doc.string(indent="  ", pretty_print=True)

JsonObject/JsonArray wrap a dict/list `data` field, as the Pony classes
do (json_type.pony:8-118). Integers stay ints and floats floats, the
reference's I64/F64 split.
"""

from __future__ import annotations

from typing import Any, List, Optional, Tuple

__all__ = ["JsonDoc", "JsonObject", "JsonArray", "JsonParseError"]


class JsonParseError(ValueError):
    """≙ Pony `error` raised from JsonDoc.parse; details via
    parse_report()."""

    def __init__(self, line: int, msg: str):
        super().__init__(f"line {line}: {msg}")
        self.line = line
        self.msg = msg


class JsonArray:
    """≙ json_type.pony JsonArray: a `data` list of json values."""

    def __init__(self, data: Optional[List[Any]] = None):
        self.data = data if data is not None else []

    def string(self, indent: str = "", pretty_print: bool = False) -> str:
        return _print_value(self, indent, pretty_print, 0)

    def __eq__(self, other):
        return isinstance(other, JsonArray) and self.data == other.data

    def __repr__(self):
        return f"JsonArray({self.data!r})"


class JsonObject:
    """≙ json_type.pony JsonObject: a `data` dict of json values."""

    def __init__(self, data: Optional[dict] = None):
        self.data = data if data is not None else {}

    def string(self, indent: str = "", pretty_print: bool = False) -> str:
        return _print_value(self, indent, pretty_print, 0)

    def __eq__(self, other):
        return isinstance(other, JsonObject) and self.data == other.data

    def __repr__(self):
        return f"JsonObject({self.data!r})"


def _escape(s: str) -> str:
    out = ['"']
    for ch in s:
        if ch == '"':
            out.append('\\"')
        elif ch == "\\":
            out.append("\\\\")
        elif ch == "\b":
            out.append("\\b")
        elif ch == "\f":
            out.append("\\f")
        elif ch == "\n":
            out.append("\\n")
        elif ch == "\r":
            out.append("\\r")
        elif ch == "\t":
            out.append("\\t")
        elif ord(ch) < 0x20:
            out.append(f"\\u{ord(ch):04x}")
        else:
            out.append(ch)
    out.append('"')
    return "".join(out)


def _print_value(v, indent: str, pretty: bool, level: int) -> str:
    """≙ _json_print.pony: compact by default, pretty with an indent
    string repeated per nesting level."""
    pad = indent * (level + 1) if pretty else ""
    end_pad = indent * level if pretty else ""
    nl = "\n" if pretty else ""
    sep = ", " if not pretty else ","
    if v is None:
        return "null"
    if v is True:
        return "true"
    if v is False:
        return "false"
    if isinstance(v, int):
        return str(v)
    if isinstance(v, float):
        if v != v or v in (float("inf"), float("-inf")):
            return "null"      # JSON has no NaN/Inf; match strictness
        s = repr(v)
        return s
    if isinstance(v, str):
        return _escape(v)
    if isinstance(v, JsonArray):
        if not v.data:
            return "[]"
        items = [_print_value(x, indent, pretty, level + 1) for x in v.data]
        if pretty:
            body = ("," + nl).join(pad + it for it in items)
            return "[" + nl + body + nl + end_pad + "]"
        return "[" + sep.join(items) + "]"
    if isinstance(v, JsonObject):
        if not v.data:
            return "{}"
        items = [
            _escape(k) + ": " + _print_value(x, indent, pretty, level + 1)
            for k, x in v.data.items()]
        if pretty:
            body = ("," + nl).join(pad + it for it in items)
            return "{" + nl + body + nl + end_pad + "}"
        return "{" + sep.join(items) + "}"
    raise TypeError(f"not a json value: {v!r}")


class JsonDoc:
    """≙ json_doc.pony JsonDoc: parse / string round-trip with error
    line reporting."""

    def __init__(self):
        self.data: Any = None
        self._src = ""
        self._pos = 0
        self._line = 1
        self._err: Tuple[int, str] = (0, "")

    # -- printing --
    def string(self, indent: str = "", pretty_print: bool = False) -> str:
        return _print_value(self.data, indent, pretty_print, 0)

    # -- parsing --
    def parse(self, source: str) -> None:
        self._src = source
        self._pos = 0
        self._line = 1
        self._err = (0, "")
        try:
            self.data = self._parse_value("top level")
            self._skip_ws()
            if self._pos < len(self._src):
                self._error("expected end of data, found junk")
        except JsonParseError:
            raise

    def parse_report(self) -> Tuple[int, str]:
        """(line, message) of the last parse error
        (≙ json_doc.pony:62-67)."""
        return self._err

    def _error(self, msg: str):
        self._err = (self._line, msg)
        raise JsonParseError(self._line, msg)

    def _skip_ws(self):
        src = self._src
        while self._pos < len(src) and src[self._pos] in " \t\r\n":
            if src[self._pos] == "\n":
                self._line += 1
            self._pos += 1

    def _peek(self, context: str) -> str:
        self._skip_ws()
        if self._pos >= len(self._src):
            self._error(f"unexpected end of data while parsing {context}")
        return self._src[self._pos]

    def _parse_value(self, context: str) -> Any:
        ch = self._peek(context)
        if ch == "{":
            return self._parse_object()
        if ch == "[":
            return self._parse_array()
        if ch == '"':
            return self._parse_string(context)
        if ch in "-0123456789":
            return self._parse_number()
        if ch.isalpha():
            return self._parse_keyword()
        self._error(f"invalid character {ch!r} while parsing {context}")

    def _parse_keyword(self) -> Any:
        src = self._src
        start = self._pos
        while self._pos < len(src) and src[self._pos].isalpha():
            self._pos += 1
        word = src[start:self._pos]
        if word == "true":
            return True
        if word == "false":
            return False
        if word == "null":
            return None
        self._error(f"invalid keyword {word!r}")

    def _parse_number(self) -> Any:
        src = self._src
        start = self._pos
        if src[self._pos] == "-":
            self._pos += 1
        digits0 = self._pos
        while self._pos < len(src) and src[self._pos].isdigit():
            self._pos += 1
        if self._pos == digits0:
            self._error("invalid number: no digits")
        is_float = False
        if self._pos < len(src) and src[self._pos] == ".":
            is_float = True
            self._pos += 1
            d = self._pos
            while self._pos < len(src) and src[self._pos].isdigit():
                self._pos += 1
            if self._pos == d:
                self._error("invalid number: no digits after decimal point")
        if self._pos < len(src) and src[self._pos] in "eE":
            is_float = True
            self._pos += 1
            if self._pos < len(src) and src[self._pos] in "+-":
                self._pos += 1
            d = self._pos
            while self._pos < len(src) and src[self._pos].isdigit():
                self._pos += 1
            if self._pos == d:
                self._error("invalid number: no digits in exponent")
        text = src[start:self._pos]
        return float(text) if is_float else int(text)

    def _parse_object(self) -> JsonObject:
        self._pos += 1                       # consume '{'
        obj = JsonObject()
        if self._peek("object") == "}":
            self._pos += 1
            return obj
        while True:
            if self._peek("object key") != '"':
                self._error("expected string object key")
            key = self._parse_string("object key")
            if self._peek("object") != ":":
                self._error("expected ':' after object key")
            self._pos += 1
            obj.data[key] = self._parse_value(f'object value for "{key}"')
            ch = self._peek("object")
            if ch == ",":
                self._pos += 1
                continue
            if ch == "}":
                self._pos += 1
                return obj
            self._error("expected ',' or '}' in object")

    def _parse_array(self) -> JsonArray:
        self._pos += 1                       # consume '['
        arr = JsonArray()
        if self._peek("array") == "]":
            self._pos += 1
            return arr
        while True:
            arr.data.append(self._parse_value("array element"))
            ch = self._peek("array")
            if ch == ",":
                self._pos += 1
                continue
            if ch == "]":
                self._pos += 1
                return arr
            self._error("expected ',' or ']' in array")

    def _parse_string(self, context: str) -> str:
        assert self._src[self._pos] == '"'
        self._pos += 1
        src = self._src
        out: List[str] = []
        while True:
            if self._pos >= len(src):
                self._error(f"unterminated string in {context}")
            ch = src[self._pos]
            if ch == '"':
                self._pos += 1
                return "".join(out)
            if ch == "\n":
                self._error(f"unterminated string in {context}")
            if ch == "\\":
                out.append(self._parse_escape(context))
                continue
            out.append(ch)
            self._pos += 1

    def _parse_escape(self, context: str) -> str:
        self._pos += 1                       # consume backslash
        src = self._src
        if self._pos >= len(src):
            self._error(f"unterminated escape in {context}")
        ch = src[self._pos]
        self._pos += 1
        simple = {'"': '"', "\\": "\\", "/": "/", "b": "\b", "f": "\f",
                  "n": "\n", "r": "\r", "t": "\t"}
        if ch in simple:
            return simple[ch]
        if ch == "u":
            code = self._parse_unicode_digits(context)
            if 0xD800 <= code <= 0xDBFF:
                # High surrogate: must pair (≙ json_doc.pony:311-342).
                if (self._pos + 1 < len(src) and src[self._pos] == "\\"
                        and src[self._pos + 1] == "u"):
                    self._pos += 2
                    low = self._parse_unicode_digits(context)
                    if not (0xDC00 <= low <= 0xDFFF):
                        self._error("invalid low surrogate in \\u escape")
                    code = (0x10000 + ((code - 0xD800) << 10)
                            + (low - 0xDC00))
                else:
                    self._error("lone high surrogate in \\u escape")
            elif 0xDC00 <= code <= 0xDFFF:
                self._error("lone low surrogate in \\u escape")
            return chr(code)
        self._error(f"invalid escape \\{ch}")

    def _parse_unicode_digits(self, context: str) -> int:
        src = self._src
        if self._pos + 4 > len(src):
            self._error(f"unterminated \\u escape in {context}")
        hexd = src[self._pos:self._pos + 4]
        try:
            code = int(hexd, 16)
        except ValueError:
            self._error(f"invalid \\u escape digits {hexd!r}")
        self._pos += 4
        return code
