"""Number/string formatting — ≙ the reference's `packages/format/`
(format.pony, format_spec.pony, prefix_spec.pony, align.pony,
_format_int.pony, _format_float.pony).

Format.apply(value, fmt=..., prefix=..., width=, precision=, align=,
fill=) with the reference's spec vocabulary expressed as module
constants: FormatHex / FormatHexBare / FormatHexSmall / FormatBinary /
FormatOctal / FormatExp / FormatFix / FormatGeneral, AlignLeft /
AlignRight / AlignCenter, PrefixSign / PrefixSpace / PrefixDefault.
"""

from __future__ import annotations

__all__ = [
    "Format", "FormatDefault", "FormatBinary", "FormatBinaryBare",
    "FormatOctal", "FormatOctalBare", "FormatHex", "FormatHexBare",
    "FormatHexSmall", "FormatHexSmallBare", "FormatExp", "FormatExpLarge",
    "FormatFix", "FormatFixLarge", "FormatGeneral", "FormatGeneralLarge",
    "AlignLeft", "AlignRight", "AlignCenter",
    "PrefixDefault", "PrefixSign", "PrefixSpace",
]

# format specs (≙ format_spec.pony primitives)
FormatDefault = "default"
FormatBinary = "binary"            # 0b1010
FormatBinaryBare = "binary_bare"   # 1010
FormatOctal = "octal"              # 0o777
FormatOctalBare = "octal_bare"
FormatHex = "hex"                  # 0xFF (capitals)
FormatHexBare = "hex_bare"
FormatHexSmall = "hex_small"       # 0xff
FormatHexSmallBare = "hex_small_bare"
FormatExp = "exp"                  # 1.0e+03
FormatExpLarge = "exp_large"       # 1.0E+03
FormatFix = "fix"                  # 1000.00
FormatFixLarge = "fix_large"
FormatGeneral = "general"
FormatGeneralLarge = "general_large"

# alignment (≙ align.pony)
AlignLeft = "left"
AlignRight = "right"
AlignCenter = "center"

# sign prefix (≙ prefix_spec.pony)
PrefixDefault = "prefix_default"   # '-' only
PrefixSign = "prefix_sign"         # always +/-
PrefixSpace = "prefix_space"       # ' ' for positive


_INT_BASES = {
    FormatBinary: (2, "0b", False), FormatBinaryBare: (2, "", False),
    FormatOctal: (8, "0o", False), FormatOctalBare: (8, "", False),
    FormatHex: (16, "0x", True), FormatHexBare: (16, "", True),
    FormatHexSmall: (16, "0x", False), FormatHexSmallBare: (16, "", False),
}

_DIGITS = "0123456789abcdefghijklmnopqrstuvwxyz"


def _int_to_base(n: int, base: int) -> str:
    if n == 0:
        return "0"
    out = []
    while n:
        out.append(_DIGITS[n % base])
        n //= base
    return "".join(reversed(out))


class Format:
    """≙ format.pony Format primitive. Call Format(...) or
    Format.apply(...); Format.int / Format.float are the typed
    entry points (≙ _format_int.pony / _format_float.pony)."""

    def __new__(cls, value, **kw):
        return cls.apply(value, **kw)

    @staticmethod
    def apply(value, fmt: str = FormatDefault, prefix: str = PrefixDefault,
              precision: int = -1, width: int = 0, align: str = AlignLeft,
              fill: str = " ") -> str:
        if isinstance(value, bool):
            s = "true" if value else "false"
        elif isinstance(value, int):
            return Format.int(value, fmt, prefix, precision, width, align,
                              fill)
        elif isinstance(value, float):
            return Format.float(value, fmt, prefix, precision, width,
                                align, fill)
        else:
            s = str(value)
            if 0 <= precision < len(s):
                s = s[:precision]
        return Format._pad(s, width, align, fill)

    @staticmethod
    def int(value: int, fmt: str = FormatDefault,
            prefix: str = PrefixDefault, precision: int = -1,
            width: int = 0, align: str = AlignRight,
            fill: str = " ") -> str:
        neg = value < 0
        mag = -value if neg else value
        if fmt in _INT_BASES:
            base, base_prefix, upper = _INT_BASES[fmt]
            digits = _int_to_base(mag, base)
            if upper:
                digits = digits.upper()
        else:
            base_prefix = ""
            digits = str(mag)
        if precision >= 0:
            digits = digits.rjust(precision, "0")
        sign = "-" if neg else (
            "+" if prefix == PrefixSign else
            " " if prefix == PrefixSpace else "")
        return Format._pad(sign + base_prefix + digits, width, align, fill)

    @staticmethod
    def float(value: float, fmt: str = FormatDefault,
              prefix: str = PrefixDefault, precision: int = 6,
              width: int = 0, align: str = AlignRight,
              fill: str = " ") -> str:
        if precision < 0:
            precision = 6
        if fmt in (FormatExp, FormatExpLarge):
            s = f"{value:.{precision}e}"
            if fmt == FormatExpLarge:
                s = s.upper()
        elif fmt in (FormatFix, FormatFixLarge):
            s = f"{value:.{precision}f}"
        elif fmt in (FormatGeneral, FormatGeneralLarge):
            s = f"{value:.{precision}g}"
            if fmt == FormatGeneralLarge:
                s = s.upper()
        else:
            s = repr(float(value))
        if value >= 0:
            if prefix == PrefixSign:
                s = "+" + s
            elif prefix == PrefixSpace:
                s = " " + s
        return Format._pad(s, width, align, fill)

    @staticmethod
    def _pad(s: str, width: int, align: str, fill: str) -> str:
        if len(s) >= width:
            return s
        pad = width - len(s)
        if align == AlignRight:
            return fill * pad + s
        if align == AlignCenter:
            left = pad // 2
            return fill * left + s + fill * (pad - left)
        return s + fill * pad
