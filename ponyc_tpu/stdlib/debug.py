"""Debug — ≙ packages/debug (Debug.out/Debug.err, compiled away unless
the binary was built with `ponyc -d`).

The reference prints only in debug-configured builds (debug.pony
`ifdef debug`). The build-flag analog here is `python -O`: `Debug`
prints only when `__debug__` is true (no -O), or when forced on via
PONY_TPU_DEBUG=1 — mirroring how a Pony program's debug prints follow
the compile configuration, not a runtime log level (that's stdlib
logger's job).

    from ponyc_tpu.stdlib.debug import Debug
    Debug("seen unless -O")
    Debug(["a", "b"], sep="/")
    Debug.err("to stderr")
"""

from __future__ import annotations

import os
import sys


def _enabled() -> bool:
    env = os.environ.get("PONY_TPU_DEBUG")
    if env is not None:
        return env not in ("", "0", "false")
    return __debug__


class _Debug:
    """Callable primitive (≙ debug/debug.pony `primitive Debug`)."""

    def __call__(self, msg, sep: str = ", ", stream=None) -> None:
        """Print a single value or a sequence joined by `sep`
        (≙ Debug.apply's Stringable | ReadSeq[Stringable])."""
        if not _enabled():
            return
        out = stream or sys.stdout
        if isinstance(msg, (list, tuple)):
            print(sep.join(str(m) for m in msg), file=out)
        else:
            print(msg, file=out)
        out.flush()

    def out(self, msg, sep: str = ", ") -> None:
        self(msg, sep, sys.stdout)

    def err(self, msg, sep: str = ", ") -> None:
        self(msg, sep, sys.stderr)


Debug = _Debug()
