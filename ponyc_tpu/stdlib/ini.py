"""INI parsing — ≙ the reference's `packages/ini/` (ini.pony streaming
parser + ini_map.pony convenience).

Streaming notify-style parser: `Ini.apply(lines, notify)` calls
notify.apply(section, key, value) / add_section(section) /
errors(lineno, err) and returns False if any error was reported —
matching the reference's error-as-return-value contract. IniMap builds
the {section: {key: value}} dict in one call.
"""

from __future__ import annotations

from typing import Dict, Iterable

__all__ = ["Ini", "IniMap", "IniNotify",
           "IniIncompleteSection", "IniNoDelimiter"]

# error kinds (≙ ini.pony primitives)
IniIncompleteSection = "incomplete section"
IniNoDelimiter = "no delimiter"


class IniNotify:
    """Callback surface (≙ ini.pony IniNotify interface). Return False
    from any hook to stop parsing."""

    def apply(self, section: str, key: str, value: str) -> bool:
        return True

    def add_section(self, section: str) -> bool:
        return True

    def errors(self, line: int, err: str) -> bool:
        return True


class Ini:
    """≙ ini.pony Ini primitive."""

    @staticmethod
    def apply(lines: Iterable[str], notify: IniNotify) -> bool:
        section = ""
        ok = True
        for lineno, raw in enumerate(lines, 1):
            line = raw.strip()
            if not line or line[0] in ";#":
                continue
            if line[0] == "[":
                end = line.find("]", 1)
                if end < 0:
                    ok = False
                    if not notify.errors(lineno, IniIncompleteSection):
                        return False
                    continue
                section = line[1:end]
                if not notify.add_section(section):
                    return ok
                continue
            delim = line.find("=")
            if delim < 0:
                delim = line.find(":")
            if delim < 0:
                ok = False
                if not notify.errors(lineno, IniNoDelimiter):
                    return False
                continue
            key = line[:delim].strip()
            value = line[delim + 1:].strip()
            # Strip a trailing comment from the value (≙ ini.pony's
            # value comment handling).
            for cchar in (";", "#"):
                ci = value.find(cchar)
                if ci >= 0:
                    value = value[:ci].rstrip()
            if not notify.apply(section, key, value):
                return ok
        return ok


class IniMap:
    """≙ ini_map.pony: parse into {section: {key: value}}; raises
    ValueError on malformed input (≙ Pony error)."""

    @staticmethod
    def apply(lines: Iterable[str]) -> Dict[str, Dict[str, str]]:
        out: Dict[str, Dict[str, str]] = {}
        errors = []

        class N(IniNotify):
            def apply(self, section, key, value):
                out.setdefault(section, {})[key] = value
                return True

            def add_section(self, section):
                out.setdefault(section, {})
                return True

            def errors(self, line, err):
                errors.append((line, err))
                return False

        if not Ini.apply(lines, N()):
            line, err = errors[0]
            raise ValueError(f"line {line}: {err}")
        return out
