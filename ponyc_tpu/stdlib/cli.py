"""Command-line interface package — ≙ the reference's `packages/cli/`
(command_spec.pony, command_parser.pony, command.pony, command_help.pony,
env_vars.pony).

Typed option/arg specs with defaults, short names, sub-commands, an
auto-generated `help` command, environment-variable fallback, and a
parser that reports errors as values (SyntaxError-style strings), not
exceptions — matching the reference's `(Command | CommandHelp |
SyntaxError)` result union.

    spec = CommandSpec.parent("tool", "My tool", options=[
        OptionSpec.bool("verbose", "Noisy output", short="v",
                        default=False)])
    spec.add_command(CommandSpec.leaf("run", "Run it", args=[
        ArgSpec.string("target", "What to run")]))
    spec.add_help()
    cmd = CommandParser(spec).parse(["tool", "run", "x"])  # or CommandHelp
                                                           # or CliSyntaxError
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

__all__ = ["CommandSpec", "OptionSpec", "ArgSpec", "Command", "CommandHelp",
           "CliSyntaxError", "CommandParser", "EnvVars"]


class CliSyntaxError:
    """A parse failure as a *value* (≙ cli's SyntaxError class — Pony
    returns it from parse rather than raising)."""

    def __init__(self, token: str, msg: str):
        self.token = token
        self.msg = msg

    def string(self) -> str:
        return f"Error: {self.msg} at: '{self.token}'"

    def __repr__(self):
        return self.string()


class _Spec:
    def __init__(self, name: str, descr: str, typ: str, default: Any,
                 required: bool, short: Optional[str]):
        if not name or not name[0].isalpha():
            raise ValueError(f"invalid name {name!r}")  # ≙ _assertName
        self.name = name
        self.descr = descr
        self.typ = typ                  # bool | string | i64 | u64 | f64 |
        #                                 string_seq
        self.default = default
        self.required = required
        self.short = short

    def _convert(self, raw: str):
        if self.typ == "bool":
            if raw.lower() in ("true", "1", ""):
                return True
            if raw.lower() in ("false", "0"):
                return False
            raise ValueError(f"invalid bool {raw!r}")
        if self.typ in ("i64", "u64"):
            v = int(raw, 0)
            if self.typ == "u64" and v < 0:
                raise ValueError(f"negative value {raw!r} for u64")
            return v
        if self.typ == "f64":
            return float(raw)
        return raw


def _make_ctors(cls, seq_types=("string_seq",)):
    # As in the reference (command_spec.pony bool/string/i64/u64/f64
    # constructors take `default': (A | None) = None`): omitting the
    # default makes the option/arg REQUIRED; pass default= to make it
    # optional.
    for typ in ("bool", "string", "i64", "u64", "f64"):
        def ctor(name, descr="", short=None, default=None, required=False,
                 _t=typ):
            return cls(name, descr, _t, default,
                       required or default is None, short)
        setattr(cls, typ, staticmethod(ctor))
    for typ in seq_types:
        def seq_ctor(name, descr="", short=None, _t=typ):
            return cls(name, descr, _t, (), False, short)
        setattr(cls, typ, staticmethod(seq_ctor))


class OptionSpec(_Spec):
    """≙ command_spec.pony OptionSpec: typed --name/-s option."""

    def requires_arg(self) -> bool:
        return self.typ != "bool"

    def help_string(self) -> str:
        s = f"-{self.short}, " if self.short else "    "
        s += f"--{self.name}"
        if self.requires_arg():
            s += "=<" + self.typ + ">"
        return f"  {s:28s} {self.descr}"


class ArgSpec(_Spec):
    """≙ command_spec.pony ArgSpec: typed positional argument."""

    def __init__(self, name, descr, typ, default, required, short=None):
        super().__init__(name, descr, typ, default, required, None)

    def help_string(self) -> str:
        return f"  <{self.name}:{self.typ}>  {self.descr}"


_make_ctors(OptionSpec)
_make_ctors(ArgSpec)


class CommandSpec:
    """≙ command_spec.pony CommandSpec: a leaf takes args; a parent takes
    sub-commands. `add_help()` installs the auto help command/option."""

    def __init__(self, name: str, descr: str, options: Sequence[OptionSpec],
                 is_leaf: bool, args: Sequence[ArgSpec] = ()):
        if not name or not all(c.isalnum() or c in "-_" for c in name):
            raise ValueError(f"invalid command name {name!r}")
        self.name_ = name
        self.descr_ = descr
        self.options_: Dict[str, OptionSpec] = {o.name: o for o in options}
        self.commands_: Dict[str, CommandSpec] = {}
        self.args_: List[ArgSpec] = list(args)
        self._leaf = is_leaf
        self._help_name: Optional[str] = None

    # -- constructors (≙ new parent / new leaf) --
    @classmethod
    def parent(cls, name: str, descr: str = "",
               options: Sequence[OptionSpec] = (),
               commands: Sequence["CommandSpec"] = ()) -> "CommandSpec":
        s = cls(name, descr, options, is_leaf=False)
        for c in commands:
            s.add_command(c)
        return s

    @classmethod
    def leaf(cls, name: str, descr: str = "",
             options: Sequence[OptionSpec] = (),
             args: Sequence[ArgSpec] = ()) -> "CommandSpec":
        return cls(name, descr, options, is_leaf=True, args=args)

    def add_command(self, cmd: "CommandSpec") -> None:
        if self._leaf:
            raise ValueError("cannot add a sub-command to a leaf")
        self.commands_[cmd.name_] = cmd

    def add_help(self, hname: str = "help", descr: str = "") -> None:
        self._help_name = hname
        self.options_[hname] = OptionSpec.bool(
            hname, descr or "Print help and exit", short="h", default=False)
        if not self._leaf:
            self.commands_[hname] = CommandSpec.leaf(
                hname, descr or "Print help for a command",
                args=[ArgSpec.string("command", "", default="")])

    def is_leaf(self) -> bool:
        return self._leaf

    def is_parent(self) -> bool:
        return not self._leaf

    def name(self) -> str:
        return self.name_

    def descr(self) -> str:
        return self.descr_

    def help_string(self) -> str:
        parts = [self.name_]
        if self.options_:
            parts.append("[<options>]")
        if self.commands_:
            parts.append("<command>")
        for a in self.args_:
            parts.append(f"<{a.name}>")
        return " ".join(parts)


class Command:
    """A successfully parsed invocation (≙ command.pony): full_name is
    "tool/sub"; options and args are name→typed-value dicts."""

    def __init__(self, spec: CommandSpec, full_name: str,
                 options: Dict[str, Any], args: Dict[str, Any]):
        self.spec = spec
        self._full = full_name
        self.options = options
        self.args = args

    def full_name(self) -> str:
        return self._full

    def option(self, name: str):
        return self.options[name]

    def arg(self, name: str):
        return self.args[name]


class CommandHelp:
    """≙ command_help.pony: renders usage/options/commands for a spec."""

    def __init__(self, spec: CommandSpec, path: List[CommandSpec]):
        self.spec = spec
        self.path = path

    def help_string(self) -> str:
        lines = ["usage: " + " ".join(
            s.help_string() for s in self.path + [self.spec])]
        if self.spec.descr_:
            lines += ["", self.spec.descr_]
        if self.spec.options_:
            lines += ["", "Options:"]
            lines += [o.help_string() for o in self.spec.options_.values()]
        if self.spec.commands_:
            lines += ["", "Commands:"]
            lines += [f"  {c.name_:16s} {c.descr_}"
                      for c in self.spec.commands_.values()]
        if self.spec.args_:
            lines += ["", "Args:"]
            lines += [a.help_string() for a in self.spec.args_]
        return "\n".join(lines) + "\n"


class EnvVars:
    """≙ env_vars.pony: TOOL_OPTNAME=value environment fallback for
    options not given on the command line."""

    def __init__(self, env: Dict[str, str], prefix: str = ""):
        self.env = env
        self.prefix = prefix

    def lookup(self, cmd_name: str, opt_name: str) -> Optional[str]:
        key = (self.prefix or cmd_name).upper() + "_" + \
            opt_name.upper().replace("-", "_")
        return self.env.get(key)


class CommandParser:
    """≙ command_parser.pony: returns Command | CommandHelp |
    CliSyntaxError (never raises on user input)."""

    def __init__(self, spec: CommandSpec, envs: Optional[EnvVars] = None):
        self.spec = spec
        self.envs = envs

    def parse(self, argv: Sequence[str]):
        # argv[0] is the program name/path and is not validated (the
        # reference parses from argv[1:] the same way).
        return self._parse(self.spec, list(argv[1:]), [], {},
                           self.spec.name_)

    def _parse(self, spec: CommandSpec, tokens: List[str],
               path: List[CommandSpec], opts: Dict[str, Any],
               full_name: str):
        options = dict(opts)
        args: Dict[str, Any] = {}
        arg_i = 0
        seen: set = set()
        args_only = False
        in_scope: Dict[str, OptionSpec] = {}
        for s in path + [spec]:
            in_scope.update(s.options_)
        while tokens:
            tok = tokens.pop(0)
            if tok == "--" and not args_only:
                args_only = True
                continue
            if not args_only and tok.startswith("--"):
                err = self._parse_long(in_scope, tok[2:], tokens, options, seen)
                if err is not None:
                    return err
                continue
            if not args_only and tok.startswith("-") and len(tok) > 1:
                err = self._parse_short(in_scope, tok[1:], tokens,
                                        options, seen)
                if err is not None:
                    return err
                continue
            # A bare token: sub-command (parent) or positional (leaf).
            if spec.is_parent():
                sub = spec.commands_.get(tok)
                if sub is None:
                    return CliSyntaxError(tok, "unknown command")
                if sub.name_ == spec._help_name:
                    # `tool help [cmd]`
                    target = tokens.pop(0) if tokens else ""
                    return self._help_for(spec, target, path)
                return self._parse(sub, tokens, path + [spec], options,
                                   full_name + "/" + sub.name_)
            if arg_i >= len(spec.args_):
                return CliSyntaxError(tok, "too many positional arguments")
            aspec = spec.args_[arg_i]
            if aspec.typ.endswith("_seq"):
                prev = args.get(aspec.name, ())
                args[aspec.name] = tuple(prev) + (tok,)
                continue          # a trailing seq arg soaks up the rest
            try:
                args[aspec.name] = aspec._convert(tok)
            except ValueError as e:
                return CliSyntaxError(tok, str(e))
            arg_i += 1

        hname = spec._help_name or self.spec._help_name
        if hname and options.get(hname):
            return CommandHelp(spec, path)
        if spec.is_parent():
            return CommandHelp(spec, path)   # parent with no sub-command

        # Env-var fallback, then defaults — over the whole spec chain
        # (ancestor options stay available under a sub-command, as the
        # reference's parser keeps parent options in scope);
        # missing required → error.
        chain_opts: Dict[str, OptionSpec] = {}
        for s in path + [spec]:
            chain_opts.update(s.options_)
        for o in chain_opts.values():
            if o.name in options:
                continue
            raw = self.envs.lookup(self.spec.name_, o.name) \
                if self.envs else None
            if raw is not None:
                try:
                    options[o.name] = o._convert(raw)
                    continue
                except ValueError as e:
                    return CliSyntaxError(raw, str(e))
            if o.typ.endswith("_seq"):
                options[o.name] = tuple(o.default or ())
            elif o.default is not None:
                options[o.name] = o.default
            elif o.required:
                return CliSyntaxError(o.name,
                                      "missing value for required option")
        for i, a in enumerate(spec.args_):
            if a.name in args:
                continue
            if a.typ.endswith("_seq"):
                args[a.name] = ()
            elif a.default is not None and not a.required:
                args[a.name] = a.default
            else:
                return CliSyntaxError(a.name,
                                      "missing value for required argument")
        return Command(spec, full_name, options, args)

    def _help_for(self, spec: CommandSpec, target: str,
                  path: List[CommandSpec]):
        if not target:
            return CommandHelp(spec, path)
        sub = spec.commands_.get(target)
        if sub is None:
            return CliSyntaxError(target, "unknown command")
        return CommandHelp(sub, path + [spec])

    def _parse_long(self, in_scope, body: str, tokens, options, seen):
        name, eq, raw = body.partition("=")
        o = in_scope.get(name)
        if o is None:
            return CliSyntaxError("--" + name, "unknown option")
        if not eq:
            if o.requires_arg():
                if not tokens:
                    return CliSyntaxError("--" + name,
                                          "missing value for option")
                raw = tokens.pop(0)
            else:
                raw = "true"
        return self._set_opt(o, raw, options, seen)

    def _parse_short(self, in_scope, body: str, tokens, options, seen):
        # -abc = -a -b -c; the last short may take a value: -n5 or -n 5.
        i = 0
        while i < len(body):
            ch = body[i]
            o = next((o for o in in_scope.values() if o.short == ch),
                     None)
            if o is None:
                return CliSyntaxError("-" + ch, "unknown short option")
            if o.requires_arg():
                raw = body[i + 1:]
                if not raw:
                    if not tokens:
                        return CliSyntaxError("-" + ch,
                                              "missing value for option")
                    raw = tokens.pop(0)
                return self._set_opt(o, raw, options, seen)
            err = self._set_opt(o, "true", options, seen)
            if err is not None:
                return err
            i += 1
        return None

    def _set_opt(self, o: OptionSpec, raw: str, options, seen):
        try:
            v = o._convert(raw)
        except ValueError as e:
            return CliSyntaxError(raw, str(e))
        if o.typ.endswith("_seq"):
            prev = options.get(o.name, ())
            options[o.name] = tuple(prev) + (v,)
        else:
            if o.name in seen:
                return CliSyntaxError("--" + o.name,
                                      "option given more than once")
            options[o.name] = v
        seen.add(o.name)
        return None
