"""Math package — ≙ the reference's `packages/math/` (fibonacci.pony:
an Iterator producing the Fibonacci sequence)."""

from __future__ import annotations

__all__ = ["Fibonacci"]


class Fibonacci:
    """Fibonacci iterator (≙ fibonacci.pony). Either iterate, or call
    Fibonacci.apply(n) for the n-th number."""

    def __init__(self):
        self._a, self._b = 0, 1

    def has_next(self) -> bool:
        return True

    def next(self) -> int:
        out = self._a
        self._a, self._b = self._b, self._a + self._b
        return out

    def __iter__(self):
        while True:
            yield self.next()

    @staticmethod
    def apply(n: int) -> int:
        a, b = 0, 1
        for _ in range(n):
            a, b = b, a + b
        return a
