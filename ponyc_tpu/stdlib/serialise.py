"""Selective object-graph serialisation — the `packages/serialise`
surface over single host-object graphs.

≙ the reference's pony_serialise/pony_deserialise
(src/libponyrt/gc/serialise.c:33-47): trace ONE object graph into a
flat offset-encoded buffer (an object map de-duplicates shared
sub-objects and breaks cycles — serialise.c's `ponyint_serialise_object`
table), and reconstruct it elsewhere. The stdlib surface mirrors
`packages/serialise/serialise.pony`: capability tokens gate the
operations (`SerialiseAuth` / `DeserialiseAuth` / `OutputSerialisedAuth`
≙ the auth values minted from AmbientAuth), `Serialised` is the carrier.

The graph walker honours HOST-HEAP references: a `HandleRef(h)` inside
the graph pulls the referenced HostHeap object into the buffer —
capability-aware (hostmem.py):

- iso handles are CONSUMED into the buffer (the move rides the
  serialisation, exactly like an iso send);
- val handles are peeked and copied (shared-immutable);
- tag handles refuse (opaque addresses have no readable content).

Deserialisation re-boxes embedded handle targets as FRESH iso handles.
The world-checkpoint subsystem (ponyc_tpu/serialise.py) snapshots the
entire runtime; this module is its selective, per-message sibling — the
IPC/payload use case the reference built serialise.c for.

Format: a record table, each record one object, references by record
index (offset-encoding). NOT pickle: only the closed set of types below
deserialises, so a hostile buffer can name no arbitrary classes.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, List, Optional

from ..hostmem import CapabilityError, HostHeap

FORMAT_VERSION = 1
MAGIC = b"PTSG"          # Pony-Tpu Serialised Graph


class SerialiseAuth:
    """Capability token for serialisation (≙ SerialiseAuth,
    packages/serialise/serialise.pony — minted from AmbientAuth; here
    constructing it IS the ambient grant, the same trust model as the
    stdlib's capsicum rights)."""


class DeserialiseAuth:
    """Capability token for deserialisation."""


class OutputSerialisedAuth:
    """Capability token for extracting the raw bytes."""


class HandleRef:
    """A reference to a HostHeap object embedded in a serialisable
    graph (≙ a traced pointer field; serialise.c follows it via the
    per-type trace fn)."""

    __slots__ = ("handle",)

    def __init__(self, handle: int):
        self.handle = int(handle)

    def __repr__(self):
        return f"HandleRef({self.handle})"

    def __eq__(self, other):
        return isinstance(other, HandleRef) and other.handle == self.handle

    def __hash__(self):
        return hash(("HandleRef", self.handle))


class SerialiseError(TypeError):
    """Graph contains an unserialisable object (≙ serialise.c aborting
    on a type without serialise hooks)."""


# Record type tags (closed set — deserialisation can only ever build
# these, never arbitrary classes).
_T_NONE, _T_BOOL, _T_INT, _T_FLOAT, _T_STR, _T_BYTES = range(6)
_T_LIST, _T_TUPLE, _T_DICT, _T_SET, _T_HANDLE = range(6, 11)


class Serialised:
    """A serialised object graph (≙ the Serialised class of
    packages/serialise): create from a live graph, output bytes, or
    apply to get a fresh copy back."""

    def __init__(self, auth: SerialiseAuth, obj: Any,
                 heap: Optional[HostHeap] = None):
        if not isinstance(auth, SerialiseAuth):
            raise TypeError("serialise requires a SerialiseAuth token")
        self._records: List[Any] = []
        self._index: Dict[int, int] = {}   # id(obj) → record idx
        self._keep: List[Any] = []         # pin ids during the walk
        self._heap = heap
        self._consume: List[int] = []      # iso handles to move on success
        self._walk(obj)
        # Iso moves COMMIT only after the whole walk succeeded: a failed
        # serialisation must leave the caller's heap untouched (peek
        # during the walk, consume at the end).
        for h in self._consume:
            heap.unbox(h)
        self._bytes: Optional[bytes] = None

    # ---- construction from bytes (receiver side) ----
    @classmethod
    def from_bytes(cls, data: bytes) -> "Serialised":
        self = cls.__new__(cls)
        if data[:4] != MAGIC:
            raise SerialiseError("not a serialised graph (bad magic)")
        ver, n = struct.unpack_from("<II", data, 4)
        if ver != FORMAT_VERSION:
            raise SerialiseError(f"format {ver} != {FORMAT_VERSION}")
        try:
            records = json.loads(data[12:].decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise SerialiseError(f"corrupt graph buffer: {e}") from None
        if not isinstance(records, list) or len(records) != n:
            raise SerialiseError("corrupt graph buffer: record count")
        self._records = records
        self._index = {}
        self._keep = []
        self._heap = None
        self._bytes = bytes(data)
        return self

    # ---- the graph walk (≙ ponyint_serialise_object, serialise.c) ----
    def _walk(self, obj: Any) -> int:
        # De-dup shared sub-objects AND break cycles: the record index
        # is reserved before children are walked (serialise.c reserves
        # the offset in its object map the same way).
        key = id(obj)
        if key in self._index and not isinstance(
                obj, (int, float, bool, str, bytes, type(None))):
            return self._index[key]
        idx = len(self._records)
        self._records.append(None)        # reserve
        if not isinstance(obj, (int, float, bool, str, bytes, type(None))):
            self._index[key] = idx
            self._keep.append(obj)        # pin so id() stays unique
        if obj is None:
            rec = [_T_NONE]
        elif isinstance(obj, bool):
            rec = [_T_BOOL, int(obj)]
        elif isinstance(obj, int):
            rec = [_T_INT, str(obj)]      # arbitrary precision via str
        elif isinstance(obj, float):
            rec = [_T_FLOAT, struct.pack("<d", obj).hex()]
        elif isinstance(obj, str):
            rec = [_T_STR, obj]
        elif isinstance(obj, bytes):
            rec = [_T_BYTES, obj.hex()]
        elif isinstance(obj, list):
            rec = [_T_LIST, [self._walk(x) for x in obj]]
        elif isinstance(obj, tuple):
            rec = [_T_TUPLE, [self._walk(x) for x in obj]]
        elif isinstance(obj, set):
            rec = [_T_SET, [self._walk(x) for x in sorted(
                obj, key=repr)]]
        elif isinstance(obj, dict):
            items = []
            for k, v in obj.items():
                items.append([self._walk(k), self._walk(v)])
            rec = [_T_DICT, items]
        elif isinstance(obj, HandleRef):
            # ≙ following a traced pointer into another actor's heap:
            # pull the referenced object INTO the buffer, honouring its
            # capability (hostmem.py).
            if self._heap is None:
                raise SerialiseError(
                    "graph contains HandleRef but no heap was given")
            mode = self._heap.mode(obj.handle)
            if mode == "tag":
                raise CapabilityError(
                    f"capability: handle {obj.handle} is tag (opaque) — "
                    "its content cannot be serialised")
            if mode == "iso":
                # Two HandleRefs to one iso in a single graph alias a
                # moved value — exactly what iso forbids.
                if obj.handle in self._consume:
                    raise CapabilityError(
                        f"capability: aliased move — iso handle "
                        f"{obj.handle} is referenced twice in one graph")
                self._consume.append(obj.handle)
            target = self._heap.peek(obj.handle)   # move commits at end
            rec = [_T_HANDLE, self._walk(target)]
        else:
            raise SerialiseError(
                f"unserialisable object in graph: {type(obj).__name__} "
                "(supported: None/bool/int/float/str/bytes/list/tuple/"
                "set/dict/HandleRef)")
        self._records[idx] = rec
        return idx

    # ---- output (≙ Serialised.output, OutputSerialisedAuth) ----
    def output(self, auth: OutputSerialisedAuth) -> bytes:
        if not isinstance(auth, OutputSerialisedAuth):
            raise TypeError("output requires an OutputSerialisedAuth token")
        if self._bytes is None:
            body = json.dumps(self._records,
                              separators=(",", ":")).encode("utf-8")
            self._bytes = MAGIC + struct.pack(
                "<II", FORMAT_VERSION, len(self._records)) + body
        return self._bytes

    # ---- apply (≙ Serialised.apply, DeserialiseAuth) ----
    def apply(self, auth: DeserialiseAuth,
              heap: Optional[HostHeap] = None) -> Any:
        if not isinstance(auth, DeserialiseAuth):
            raise TypeError("apply requires a DeserialiseAuth token")
        if not self._records:
            raise SerialiseError("empty graph")
        built: Dict[int, Any] = {}

        def build(idx: int) -> Any:
            if idx in built:
                return built[idx]
            rec = self._records[idx]
            t = rec[0]
            if t == _T_NONE:
                val = None
            elif t == _T_BOOL:
                val = bool(rec[1])
            elif t == _T_INT:
                val = int(rec[1])
            elif t == _T_FLOAT:
                val = struct.unpack("<d", bytes.fromhex(rec[1]))[0]
            elif t == _T_STR:
                val = rec[1]
            elif t == _T_BYTES:
                val = bytes.fromhex(rec[1])
            elif t == _T_LIST:
                val = []
                built[idx] = val          # pre-register: cycles resolve
                val.extend(build(i) for i in rec[1])
                return val
            elif t == _T_TUPLE:
                val = tuple(build(i) for i in rec[1])
            elif t == _T_SET:
                val = {build(i) for i in rec[1]}
            elif t == _T_DICT:
                val = {}
                built[idx] = val
                for k_i, v_i in rec[1]:
                    val[build(k_i)] = build(v_i)
                return val
            elif t == _T_HANDLE:
                if heap is None:
                    raise SerialiseError(
                        "graph contains a handle target but no heap was "
                        "given to re-box it")
                val = HandleRef(heap.box(build(rec[1])))   # fresh iso
            else:
                raise SerialiseError(f"unknown record tag {t}")
            built[idx] = val
            return val

        return build(0)


def serialise_to_handle(auth: SerialiseAuth, obj: Any,
                        heap: HostHeap) -> int:
    """One-call helper for the payload use case: serialise `obj` and box
    the bytes as a fresh iso handle, ready to ride an ``Iso`` message
    parameter."""
    data = Serialised(auth, obj, heap=heap).output(OutputSerialisedAuth())
    return heap.box(data)


def deserialise_from_handle(auth: DeserialiseAuth, handle: int,
                            heap: HostHeap) -> Any:
    """Receiver-side twin: unbox the bytes handle (consuming it) and
    rebuild the graph."""
    data = heap.unbox(handle)
    if not isinstance(data, (bytes, bytearray)):
        raise SerialiseError("handle does not hold serialised bytes")
    return Serialised.from_bytes(bytes(data)).apply(auth, heap=heap)
