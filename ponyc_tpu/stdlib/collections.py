"""Mutable collections — ≙ the reference's `packages/collections/`
(flag.pony, range.pony, heap.pony, ring_buffer.pony, sort.pony,
reverse.pony, list.pony/list_node.pony, map.pony/set.pony).

Python's dict/list/set already cover Map/List/Set for host-side code, so
this module implements the pieces Python *lacks* with the reference's
semantics: typed bit-flag sets, Pony-style numeric ranges (including the
infinite-range rule), binary heaps with both polarities, a fixed-size
ring buffer whose indices keep counting up (exactly the mailbox
discipline the device runtime uses), in-place quicksort, and a reversing
iterator. Persistent (immutable) variants live in stdlib.persistent.
"""

from __future__ import annotations

import math as _math
from typing import Generic, Iterable, Iterator, List as _List, \
    Optional, Sequence, TypeVar

__all__ = ["Flags", "Range", "MinHeap", "MaxHeap", "BinaryHeap",
           "RingBuffer", "Sort", "Reverse", "ListNode", "List"]

T = TypeVar("T")


class Flags:
    """Typed bit-flag set (≙ flag.pony Flags[A, B]): values are single
    bits; set/unset/union/intersect keep a packed integer `value`."""

    def __init__(self, value: int = 0):
        self._value = int(value)

    def value(self) -> int:
        return self._value

    def __call__(self, flag: int) -> bool:
        return (self._value & flag) == flag

    def all_(self) -> "Flags":
        self._value = ~0
        return self

    def clear(self) -> "Flags":
        self._value = 0
        return self

    def set(self, flag: int) -> "Flags":
        self._value |= flag
        return self

    def unset(self, flag: int) -> "Flags":
        self._value &= ~flag
        return self

    def flip(self, flag: int) -> "Flags":
        self._value ^= flag
        return self

    def union(self, other: "Flags") -> "Flags":
        return Flags(self._value | other._value)

    __or__ = union

    def intersect(self, other: "Flags") -> "Flags":
        return Flags(self._value & other._value)

    __and__ = intersect

    def difference(self, other: "Flags") -> "Flags":
        return Flags(self._value ^ other._value)

    __xor__ = difference

    def remove(self, other: "Flags") -> "Flags":
        return Flags(self._value & ~other._value)

    def __eq__(self, other):
        return isinstance(other, Flags) and self._value == other._value

    def __lt__(self, other):      # proper subset (≙ flag.pony lt)
        return (self._value != other._value
                and (self._value & other._value) == self._value)

    def __le__(self, other):
        return (self._value & other._value) == self._value


class Range:
    """`[min, max)` with step `inc` (≙ range.pony, including its edge
    rule: a step of 0, a step moving away from max, or any non-finite
    float parameter makes the range INFINITE, not empty)."""

    def __init__(self, min_: float, max_: float, inc: float = 1):
        self._min = min_
        self._max = max_
        self._inc = inc
        self._idx = 0
        forward = (min_ < max_) and (inc > 0)
        backward = (min_ > max_) and (inc < 0)
        infinite = False
        for v in (min_, max_, inc):
            if isinstance(v, float) and not _math.isfinite(v):
                infinite = True
        if inc == 0 or (min_ != max_ and not (forward or backward)):
            infinite = True
        self._infinite = infinite
        self._empty = (min_ == max_) and not infinite

    def is_infinite(self) -> bool:
        return self._infinite

    def has_next(self) -> bool:
        if self._infinite:
            return True
        if self._empty:
            return False
        cur = self._min + self._idx * self._inc
        return cur < self._max if self._inc > 0 else cur > self._max

    def next(self):
        cur = self._min + self._idx * self._inc
        self._idx += 1
        return cur

    def __iter__(self) -> Iterator:
        while self.has_next():
            yield self.next()

    def rewind(self) -> None:
        self._idx = 0


class BinaryHeap(Generic[T]):
    """Array-backed binary heap (≙ heap.pony BinaryHeap with
    MinHeapPriority / MaxHeapPriority primitives)."""

    def __init__(self, greater: bool = False):
        self._data: _List[T] = []
        self._greater = greater

    def _before(self, a, b) -> bool:
        return a > b if self._greater else a < b

    def size(self) -> int:
        return len(self._data)

    __len__ = size

    def peek(self) -> T:
        if not self._data:
            raise IndexError("peek on empty heap")
        return self._data[0]

    def push(self, value: T) -> None:
        d = self._data
        d.append(value)
        i = len(d) - 1
        while i > 0:
            parent = (i - 1) >> 1
            if self._before(d[i], d[parent]):
                d[i], d[parent] = d[parent], d[i]
                i = parent
            else:
                break

    def append(self, values: Iterable[T]) -> None:
        for v in values:
            self.push(v)

    def pop(self) -> T:
        d = self._data
        if not d:
            raise IndexError("pop on empty heap")
        top = d[0]
        last = d.pop()
        if d:
            d[0] = last
            i = 0
            n = len(d)
            while True:
                lo = i
                for c in (2 * i + 1, 2 * i + 2):
                    if c < n and self._before(d[c], d[lo]):
                        lo = c
                if lo == i:
                    break
                d[i], d[lo] = d[lo], d[i]
                i = lo
        return top

    def clear(self) -> None:
        self._data = []

    def values(self) -> _List[T]:
        return list(self._data)


def MinHeap() -> BinaryHeap:
    return BinaryHeap(greater=False)


def MaxHeap() -> BinaryHeap:
    return BinaryHeap(greater=True)


class RingBuffer(Generic[T]):
    """Fixed-size ring whose indices keep counting up, so `apply(i)`
    fails for values that have fallen off (≙ ring_buffer.pony — and the
    same monotonic head/tail discipline as the device mailbox table,
    runtime/state.py)."""

    def __init__(self, length: int):
        self._cap = max(1, length)
        self._data: _List[Optional[T]] = [None] * self._cap
        self._tail = 0                 # next index to write (total pushed)

    def head(self) -> int:
        if self._tail == 0:
            raise IndexError("empty ring")
        return max(0, self._tail - self._cap)

    def size(self) -> int:
        return min(self._tail, self._cap)

    def space(self) -> int:
        return self._cap

    def __call__(self, i: int) -> T:
        if i >= self._tail or i < max(0, self._tail - self._cap):
            raise IndexError(i)
        return self._data[i % self._cap]

    apply = __call__

    def push(self, value: T) -> bool:
        """True if an old value was overwritten (≙ push returns Bool)."""
        overwrote = self._tail >= self._cap
        self._data[self._tail % self._cap] = value
        self._tail += 1
        return overwrote

    def clear(self) -> None:
        self._data = [None] * self._cap
        self._tail = 0


class Sort:
    """In-place quicksort (≙ sort.pony Sort / SortBy primitives)."""

    @staticmethod
    def apply(array: _List, lo: int = 0, hi: Optional[int] = None) -> _List:
        if hi is None:
            hi = len(array) - 1
        if lo < hi:
            p = Sort._partition(array, lo, hi, lambda x: x)
            Sort.apply(array, lo, p)
            Sort.apply(array, p + 1, hi)
        return array

    @staticmethod
    def by(array: _List, key, lo: int = 0,
           hi: Optional[int] = None) -> _List:
        if hi is None:
            hi = len(array) - 1
        if lo < hi:
            p = Sort._partition(array, lo, hi, key)
            Sort.by(array, key, lo, p)
            Sort.by(array, key, p + 1, hi)
        return array

    @staticmethod
    def _partition(a: _List, lo: int, hi: int, key) -> int:
        pivot = key(a[(lo + hi) // 2])
        i, j = lo - 1, hi + 1
        while True:
            i += 1
            while key(a[i]) < pivot:
                i += 1
            j -= 1
            while key(a[j]) > pivot:
                j -= 1
            if i >= j:
                return j
            a[i], a[j] = a[j], a[i]


class Reverse:
    """Reversed Range-style counter (≙ reverse.pony: Reverse(10, 2, 2)
    yields 10, 8, 6, 4, 2)."""

    def __init__(self, max_: float, min_: float, dec: float = 1):
        self._max = max_
        self._min = min_
        self._dec = abs(dec)
        self._idx = 0

    def has_next(self) -> bool:
        if self._dec == 0:
            return True          # mirror Range's infinite rule
        return self._max - self._idx * self._dec >= self._min

    def next(self):
        cur = self._max - self._idx * self._dec
        self._idx += 1
        return cur

    def __iter__(self):
        while self.has_next():
            yield self.next()


class ListNode(Generic[T]):
    """Doubly-linked-list node (≙ list_node.pony): nodes are first-class
    and can be unlinked/relinked without touching values."""

    def __init__(self, value: T = None):
        self.value = value
        self._list: Optional["List"] = None
        self._prev: Optional["ListNode"] = None
        self._next: Optional["ListNode"] = None

    def prev(self) -> Optional["ListNode[T]"]:
        return self._prev

    def next(self) -> Optional["ListNode[T]"]:
        return self._next

    def remove(self) -> None:
        lst = self._list
        if lst is None:
            return
        if self._prev is not None:
            self._prev._next = self._next
        else:
            lst._head = self._next
        if self._next is not None:
            self._next._prev = self._prev
        else:
            lst._tail = self._prev
        lst._size -= 1
        self._list = self._prev = self._next = None


class List(Generic[T]):
    """Doubly-linked list over ListNode (≙ list.pony)."""

    def __init__(self, items: Sequence[T] = ()):
        self._head: Optional[ListNode] = None
        self._tail: Optional[ListNode] = None
        self._size = 0
        for x in items:
            self.push(x)

    def size(self) -> int:
        return self._size

    __len__ = size

    def head(self) -> ListNode[T]:
        if self._head is None:
            raise IndexError("empty list")
        return self._head

    def tail(self) -> ListNode[T]:
        if self._tail is None:
            raise IndexError("empty list")
        return self._tail

    def push(self, value: T) -> ListNode[T]:        # append
        node = ListNode(value)
        node._list = self
        node._prev = self._tail
        if self._tail is not None:
            self._tail._next = node
        else:
            self._head = node
        self._tail = node
        self._size += 1
        return node

    def unshift(self, value: T) -> ListNode[T]:     # prepend
        node = ListNode(value)
        node._list = self
        node._next = self._head
        if self._head is not None:
            self._head._prev = node
        else:
            self._tail = node
        self._head = node
        self._size += 1
        return node

    def pop(self) -> T:
        node = self.tail()
        node.remove()
        return node.value

    def shift(self) -> T:
        node = self.head()
        node.remove()
        return node.value

    def __iter__(self) -> Iterator[T]:
        node = self._head
        while node is not None:
            yield node.value
            node = node._next

    def nodes(self) -> Iterator[ListNode[T]]:
        node = self._head
        while node is not None:
            nxt = node._next
            yield node
            node = nxt

    def __contains__(self, value: T) -> bool:
        return any(v == value for v in self)
