"""Iterator combinators — ≙ the reference's `packages/itertools/`
(iter.pony's Iter class): a fluent, lazy pipeline over any iterator.

    Iter(range(10)).filter(lambda x: x % 2 == 0).map(str).collect()

Python generators make each combinator a few lines, but the *surface* is
the reference's: chain, repeat_value, all/any, collect, count, cycle,
dedup, enum, filter, filter_map, find, flat_map, fold, interleave, last,
map, nth, run, skip, skip_while, step_by, take, take_while, unique, zip.
"""

from __future__ import annotations

import itertools as _it
from typing import Any, Callable, Iterable, Iterator, Optional

__all__ = ["Iter"]


class Iter:
    """≙ iter.pony Iter[A]."""

    _NONE = object()        # sentinel: no peeked value buffered

    def __init__(self, it: Iterable):
        self._it = iter(it)
        self._peeked = Iter._NONE

    # -- constructors --
    @staticmethod
    def chain(iters: Iterable[Iterable]) -> "Iter":
        return Iter(_it.chain.from_iterable(iters))

    @staticmethod
    def repeat_value(value) -> "Iter":
        return Iter(_it.repeat(value))

    # -- protocol --
    def __iter__(self) -> Iterator:
        while True:
            try:
                yield self.next()
            except StopIteration:
                return

    def has_next(self) -> bool:
        if self._peeked is not Iter._NONE:
            return True
        try:
            self._peeked = next(self._it)
        except StopIteration:
            return False
        return True

    def next(self):
        if self._peeked is not Iter._NONE:
            v = self._peeked
            self._peeked = Iter._NONE
            return v
        return next(self._it)

    __next__ = next         # Iter is itself a Python iterator

    # -- terminal ops --
    def all(self, f: Callable[[Any], bool]) -> bool:
        return all(f(x) for x in self)

    def any(self, f: Callable[[Any], bool]) -> bool:
        return any(f(x) for x in self)

    def collect(self, coll: Optional[list] = None) -> list:
        coll = coll if coll is not None else []
        coll.extend(self)
        return coll

    def count(self) -> int:
        return sum(1 for _ in self)

    def find(self, f: Callable[[Any], bool], n: int = 1):
        """The n-th element satisfying f; raises IndexError (≙ error)."""
        seen = 0
        for x in self:
            if f(x):
                seen += 1
                if seen == n:
                    return x
        raise IndexError("find: no match")

    def fold(self, acc, f: Callable[[Any, Any], Any]):
        for x in self:
            acc = f(acc, x)
        return acc

    def last(self):
        out = _SENTINEL = object()
        for out in self:
            pass
        if out is _SENTINEL:
            raise IndexError("last of empty Iter")
        return out

    def nth(self, n: int):
        """1-based n-th element (≙ iter.pony nth); IndexError past end."""
        for i, x in enumerate(self, 1):
            if i == n:
                return x
        raise IndexError(n)

    def run(self, on_error: Optional[Callable[[], None]] = None) -> None:
        """Drain the iterator for its effects (≙ iter.pony run)."""
        try:
            for _ in self:
                pass
        except Exception:
            if on_error is not None:
                on_error()
            else:
                raise

    # -- combinators (all lazy) --
    def _wrap(self, gen) -> "Iter":
        return Iter(gen)

    def cycle(self) -> "Iter":
        return self._wrap(_it.cycle(self))

    def dedup(self) -> "Iter":
        """Drop *all* duplicates, keeping first occurrence
        (≙ iter.pony dedup — hash-set based, unlike unique)."""
        def gen():
            seen = set()
            for x in self:
                if x not in seen:
                    seen.add(x)
                    yield x
        return self._wrap(gen())

    def enum(self) -> "Iter":
        return self._wrap(((i, x) for i, x in enumerate(self)))

    def filter(self, f) -> "Iter":
        return self._wrap((x for x in self if f(x)))

    def filter_map(self, f) -> "Iter":
        return self._wrap((y for x in self
                           if (y := f(x)) is not None))

    def flat_map(self, f) -> "Iter":
        return self._wrap((y for x in self for y in f(x)))

    def interleave(self, other: Iterable) -> "Iter":
        def gen():
            a, b = self, iter(other)
            while True:
                stop = 0
                for src in (a, b):
                    try:
                        yield next(src)
                    except StopIteration:
                        stop += 1
                if stop == 2:
                    return
        return self._wrap(gen())

    def map(self, f) -> "Iter":
        return self._wrap((f(x) for x in self))

    def skip(self, n: int) -> "Iter":
        return self._wrap(_it.islice(self, n, None))

    def skip_while(self, f) -> "Iter":
        return self._wrap(_it.dropwhile(f, self))

    def step_by(self, n: int) -> "Iter":
        return self._wrap(_it.islice(self, 0, None, max(1, n)))

    def take(self, n: int) -> "Iter":
        return self._wrap(_it.islice(self, n))

    def take_while(self, f) -> "Iter":
        return self._wrap(_it.takewhile(f, self))

    def unique(self) -> "Iter":
        """Drop *consecutive* duplicates (≙ iter.pony unique)."""
        def gen():
            prev = object()
            for x in self:
                if x != prev:
                    yield x
                prev = x
        return self._wrap(gen())

    def zip(self, *others: Iterable) -> "Iter":
        return self._wrap(zip(self, *map(iter, others)))
