"""Causal message tracing — the host half (PROFILE.md §10).

≙ the reference's per-event analysis rows following ONE message from
send to dispatch (analysis.c:587-692) and the DTrace scripts stitching
USDT probes into causal timelines (SURVEY §5): here the device threads
a sampled (trace_id, parent_span) context through mailbox ring side
lanes (runtime/state.py), dispatch records one SPAN per traced message
in a bounded device ring (engine.trace_span_lanes), and every send or
spawn the behaviour performs inherits the context — so an injection's
whole causal fan-out (inject → behaviour → fan-out → quiescence) is
reconstructable after the fact, per message, not per aggregate.

This module owns everything that happens off-device:

  - `Tracer` — per-runtime host bookkeeping: deterministic sampling
    (a counter hash under `trace_seed` — identical runs trace identical
    messages), host root spans for injections, host spans for
    host-cohort dispatches, and the span-ring drain;
  - `reassemble` — span records → causal trees, with per-trace
    critical-path latency in device ticks;
  - `perfetto_events` — span slices + flow arrows (sender → receiver)
    in Chrome-trace JSON, merged into `analysis.chrome_trace` output;
  - one-line JSON span records (`span_jsonl_line` / `load_spans`) —
    the `<analysis_path>.spans.jsonl` stream the level-2 writer thread
    appends to;
  - `format_trace` — the text rendering `python -m ponyc_tpu trace
    --tree` prints.

Span record layout (the device ring's rows, state.span_data; host
spans use the same tuple shape): (trace_id, span_id, parent_span,
behaviour, actor, enqueue_tick, dispatch_tick, retire_tick). Device
span ids are EVEN (>= 2, allocated from a per-shard monotonic counter,
unique across shards); host span ids are ODD (>= 1); 0 = "no parent".
Tick invariants the tests pin: enqueue <= dispatch <= retire, and a
child span's enqueue tick is >= its parent's dispatch tick (the send
that created it happened inside the parent's dispatch).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Dict, List, Optional, Tuple

# Device span-ring rows (state.span_data's leading axis).
SPAN_ROWS = 8
(ROW_TRACE, ROW_SPAN, ROW_PARENT, ROW_BEH, ROW_ACTOR,
 ROW_ENQ, ROW_DISP, ROW_RETIRE) = range(SPAN_ROWS)

# Knuth multiplicative hash constant for the deterministic sampler.
_HASH_MUL = 2654435761


@dataclasses.dataclass
class Span:
    """One reassembled span (a behaviour dispatch, or the host-side
    injection/host-dispatch that rooted or continued the trace)."""
    trace_id: int
    span_id: int
    parent: int
    beh: str            # "Type.behaviour", "inject", or "gid:<n>"
    actor: int          # global actor id; -1 = host
    enq: int            # enqueue tick (delivery stamp / host step)
    disp: int           # dispatch tick
    retire: int         # retire tick (dispatch completed)
    children: List["Span"] = dataclasses.field(default_factory=list)


class Tracer:
    """Host-side trace bookkeeping for one Runtime (created at start()
    when opts.tracing). Collects HOST spans (injection roots and
    host-cohort dispatches) and drains the DEVICE span ring; `spans`
    accumulates both as plain tuples in span-record order."""

    def __init__(self, sample_n: int, seed: int = 0,
                 beh_names: Optional[List[str]] = None):
        self.sample_n = int(sample_n)
        self.seed = int(seed)
        self.beh_names = list(beh_names or [])
        self.spans: List[Tuple[int, ...]] = []   # SPAN_ROWS-tuples with
        #   the behaviour column RESOLVED to a name at append time
        self.dropped = 0          # device span-ring drops seen so far
        self._n_sends = 0         # sampling counter (deterministic)
        self._next_trace = 1
        self._next_host_span = 1  # odd ids: 1, 3, 5, ...
        self._roots: Dict[int, int] = {}   # trace_id -> root span id
        self._fresh: List[Tuple[int, ...]] = []  # spans since last flush

    # ---- sampling / span allocation (host side) ----
    def sample(self) -> bool:
        """Deterministic 1-in-N decision for the next injection: a
        counter hash under the seed, so a fixed (seed, send sequence)
        always traces the same messages — no wall clock, no RNG state
        shared with user code."""
        c = self._n_sends
        self._n_sends += 1
        if self.sample_n <= 0:
            return False
        h = (c * _HASH_MUL + self.seed) & 0x7FFFFFFF
        return h % self.sample_n == 0

    def _host_span_id(self) -> int:
        sid = self._next_host_span
        self._next_host_span += 2          # stay odd: device ids are even
        return sid

    def _record(self, rec: Tuple[int, ...]) -> None:
        self.spans.append(rec)
        self._fresh.append(rec)

    def begin(self, step: int, trace_id: Optional[int] = None
              ) -> Tuple[int, int]:
        """Open a trace with a host ROOT span (the injection itself):
        returns (trace_id, root_span_id). An explicit trace_id lets the
        caller (bridge/ingress tier) tie an external request id to the
        device spans; ids collide harmlessly (one merged tree)."""
        if trace_id is None:
            tid = self._next_trace
            self._next_trace += 1
        else:
            tid = int(trace_id)
            self._next_trace = max(self._next_trace, tid + 1)
        sid = self._roots.get(tid)
        if sid is None:
            sid = self._host_span_id()
            self._roots[tid] = sid
            self._record((tid, sid, 0, "inject", -1,
                          int(step), int(step), int(step)))
        return tid, sid

    def root_span(self, trace_id: int, step: int) -> int:
        """Get-or-create the root span of an explicit trace id."""
        return self.begin(step, trace_id)[1]

    def host_span(self, trace_id: int, parent: int, beh: Any,
                  actor: int, step: int) -> int:
        """Record a HOST-cohort dispatch span (the main-thread-scheduler
        analog of a device span) and return its id, for propagation
        into the sends the host behaviour performs."""
        sid = self._host_span_id()
        self._record((int(trace_id), sid, int(parent),
                      self._beh_name(beh), int(actor),
                      int(step), int(step), int(step)))
        return sid

    def _beh_name(self, beh: Any) -> str:
        if isinstance(beh, str):
            return beh
        g = int(beh)
        if 0 <= g < len(self.beh_names):
            return self.beh_names[g]
        return f"gid:{g}"

    # ---- device span ring ----
    def drain(self, rt) -> int:
        """Fetch and reset the device span ring (the Analysis window
        hook and Runtime.traces() both call this; ≙ the analysis thread
        draining the fork's event queue). Returns spans drained."""
        import dataclasses as _dc

        import jax.numpy as jnp
        import numpy as np

        st = rt.state
        if st is None or st.span_data.size == 0:
            return 0
        counts = np.asarray(rt._fetch(st.span_count))
        dropped = int(np.asarray(rt._fetch(st.span_dropped)).sum())
        if dropped > self.dropped:
            self.dropped = dropped
        if counts.sum() == 0:
            return 0
        data = np.asarray(rt._fetch(st.span_data))     # [ROWS, P*TS]
        ts_cap = rt.opts.trace_slots
        n = 0
        for shard, cnt in enumerate(counts):
            seg = data[:, shard * ts_cap: shard * ts_cap + int(cnt)]
            for i in range(seg.shape[1]):
                self._record((int(seg[ROW_TRACE, i]),
                              int(seg[ROW_SPAN, i]),
                              int(seg[ROW_PARENT, i]),
                              self._beh_name(int(seg[ROW_BEH, i])),
                              int(seg[ROW_ACTOR, i]),
                              int(seg[ROW_ENQ, i]),
                              int(seg[ROW_DISP, i]),
                              int(seg[ROW_RETIRE, i])))
                n += 1
        fkey = rt._freelist_key
        rt.state = _dc.replace(st,
                               span_count=jnp.zeros_like(st.span_count))
        rt._freelist_key = fkey        # count reset frees no slots
        return n

    def take_fresh(self) -> List[Tuple[int, ...]]:
        """Spans recorded since the last call (the writer thread's
        feed for the .spans.jsonl stream)."""
        out, self._fresh = self._fresh, []
        return out


# ---- reassembly -----------------------------------------------------------

def reassemble(spans) -> Dict[int, Dict[str, Any]]:
    """Span records (tuples or dicts) → causal trees, one per trace id:

        {trace_id: {"roots": [Span...],        # parentless spans
                    "spans": {span_id: Span},
                    "n_spans": int,
                    "latency": int,            # critical-path ticks
                    "critical_path": [str]}}   # beh names root→leaf

    Latency = max retire tick − min enqueue tick over the trace (the
    end-to-end number ROADMAP item 4's ingress tier needs). The
    critical path follows children to the latest-retiring leaf. Orphan
    spans (parent not drained yet / ring overflow) become roots, so a
    partially-drained trace still renders."""
    traces: Dict[int, Dict[int, Span]] = {}
    for rec in spans:
        if isinstance(rec, dict):
            s = Span(rec["trace"], rec["span"], rec["parent"],
                     rec["beh"], rec["actor"], rec["enq"], rec["disp"],
                     rec["retire"])
        else:
            s = Span(*rec[:SPAN_ROWS])
        traces.setdefault(s.trace_id, {})[s.span_id] = s
    out: Dict[int, Dict[str, Any]] = {}
    for tid, by_id in traces.items():
        roots = []
        for s in by_id.values():
            p = by_id.get(s.parent)
            if p is not None and p is not s:
                p.children.append(s)
            else:
                roots.append(s)
        for s in by_id.values():
            s.children.sort(key=lambda c: (c.enq, c.span_id))
        roots.sort(key=lambda c: (c.enq, c.span_id))
        lat = (max(s.retire for s in by_id.values())
               - min(s.enq for s in by_id.values()))
        out[tid] = {"roots": roots, "spans": by_id,
                    "n_spans": len(by_id), "latency": int(lat),
                    "critical_path": _critical_path(roots)}
    return out


def _critical_path(roots: List[Span]) -> List[str]:
    """Behaviour names along the chain to the latest-retiring leaf.
    Iterative (explicit stack): a traced chain can be thousands of
    spans deep — one per hop — which would blow Python's recursion
    limit."""
    if not roots:
        return []
    best_ret, best_leaf = -(1 << 62), None
    parent: Dict[int, Optional[Span]] = {}
    stack = [(r, None) for r in roots]
    while stack:
        s, par = stack.pop()
        parent[id(s)] = par
        if s.retire > best_ret or best_leaf is None:
            best_ret, best_leaf = s.retire, s
        for c in s.children:
            stack.append((c, s))
    path: List[str] = []
    s = best_leaf
    while s is not None:
        path.append(s.beh)
        s = parent[id(s)]
    return path[::-1]


def consistent(tree: Dict[str, Any]) -> bool:
    """The acceptance predicate: every span has enq <= disp <= retire
    and every child's enqueue tick >= its parent's dispatch tick (the
    send happened inside the parent's dispatch)."""
    for s in tree["spans"].values():
        if not (s.enq <= s.disp <= s.retire):
            return False
        for c in s.children:
            if c.enq < s.disp:
                return False
    return True


# ---- serialisation --------------------------------------------------------

def span_jsonl_line(rec) -> str:
    """One span record as a one-line JSON object (the .spans.jsonl
    format; also what `trace --tree` reads back)."""
    t, s, p, beh, actor, enq, disp, ret = rec[:SPAN_ROWS]
    return json.dumps({"trace": int(t), "span": int(s), "parent": int(p),
                       "beh": beh, "actor": int(actor), "enq": int(enq),
                       "disp": int(disp), "retire": int(ret)},
                      separators=(",", ":"))


def load_spans(path: str) -> List[Dict[str, Any]]:
    """Read a .spans.jsonl stream (blank/truncated tail lines skipped —
    the writer thread may be mid-append)."""
    out = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return out


# ---- Perfetto export ------------------------------------------------------

def perfetto_events(spans, pid: int = 2) -> List[Dict[str, Any]]:
    """Span slices + flow arrows as Chrome-trace events, on a DEVICE-
    TICK timebase (1 tick = 1 µs in the rendered timeline — spans are
    tick-stamped on device; the window CSV's wall-clock tracks live in
    their own process). One thread lane per actor, labelled via
    thread_name metadata (the satellite: Perfetto must not show bare
    tids); flow 's'/'f' pairs (id = child span id) draw the
    sender→receiver arrows the acceptance criteria name."""
    trees = reassemble(spans)
    out: List[Dict[str, Any]] = [
        {"ph": "M", "pid": pid, "name": "process_name",
         "args": {"name": "ponyc_tpu traces (device ticks)"}},
        {"ph": "M", "pid": pid, "name": "process_sort_index",
         "args": {"sort_index": 10}},
    ]
    tids: Dict[int, int] = {}

    def tid_of(actor: int) -> int:
        t = tids.get(actor)
        if t is None:
            t = tids[actor] = len(tids) + 1
            out.append({"ph": "M", "pid": pid, "tid": t,
                        "name": "thread_name",
                        "args": {"name": ("host inject" if actor < 0
                                          else f"actor {actor}")}})
        return t

    for tree in trees.values():
        for s in tree["spans"].values():
            t = tid_of(s.actor)
            ts = float(s.disp)
            dur = float(max(s.retire - s.disp, 1))
            out.append({"ph": "X", "pid": pid, "tid": t, "ts": ts,
                        "dur": dur, "name": s.beh,
                        "args": {"trace": s.trace_id, "span": s.span_id,
                                 "parent": s.parent, "enq": s.enq}})
            if s.parent > 0 and s.parent in tree["spans"]:
                p = tree["spans"][s.parent]
                out.append({"ph": "s", "pid": pid,
                            "tid": tid_of(p.actor), "id": s.span_id,
                            "ts": float(p.disp),
                            "name": f"msg {p.beh}->{s.beh}"})
                out.append({"ph": "f", "pid": pid, "tid": t, "bp": "e",
                            "id": s.span_id, "ts": ts,
                            "name": f"msg {p.beh}->{s.beh}"})
    return out


# ---- text rendering -------------------------------------------------------

def format_trace(tid: int, tree: Dict[str, Any]) -> str:
    """One trace as an indented causal tree (the `trace --tree` view)."""
    lines = [f"trace {tid}: {tree['n_spans']} span(s), "
             f"latency {tree['latency']} tick(s), critical path "
             + " -> ".join(tree["critical_path"])]
    stack = [(r, 0) for r in reversed(tree["roots"])]
    while stack:                      # explicit stack: deep chains
        s, depth = stack.pop()
        who = "host" if s.actor < 0 else f"a{s.actor}"
        lines.append("  " * (depth + 1)
                     + f"{s.beh} [{who}] enq={s.enq} disp={s.disp} "
                       f"retire={s.retire} span={s.span_id}")
        for c in reversed(s.children):
            stack.append((c, depth + 1))
    return "\n".join(lines)
