"""Documentation generator — ≙ the reference's docgen pass
(src/libponyc/pass/docgen.c: walks the typechecked AST and emits a
mkdocs tree of packages/types/methods with docstrings).

Here the unit is an actor Program (or any module of actor types): emit
markdown with one section per actor type — scheduling hints, state
fields with dtypes, behaviours with typed signatures and docstrings,
spawn budgets — plus the program-level dispatch table.

    from ponyc_tpu import docgen
    md = docgen.document(program)            # or document_types(A, B)
    docgen.write_tree(program, "docs/")      # one file per type + index
"""

from __future__ import annotations

import inspect
import os
from typing import List

from .api import ActorTypeMeta
from .ops import pack


_SPEC_NAMES = {pack.I32: "I32", pack.F32: "F32", pack.Bool: "Bool",
               pack.Ref: "Ref"}


def _sig(bdef) -> str:
    args = ", ".join(
        f"{n}: {_SPEC_NAMES.get(s, getattr(s, '__name__', '?'))}"
        for n, s in zip(bdef.arg_names, bdef.arg_specs))
    return f"{bdef.name}({args})"


def document_type(atype: ActorTypeMeta, lint_notes=None) -> str:
    """Markdown for one actor type (≙ doc_entity in docgen.c).

    `lint_notes` (optional): {behaviour name or None: [note, ...]} from
    the whole-program lint pass — type-level notes (key None) render
    under the hints line, behaviour notes under each signature (the
    unreachable/dead-letter marks, ≙ docgen flagging pruned entities).
    """
    lint_notes = lint_notes or {}
    lines: List[str] = [f"## actor {atype.__name__}", ""]
    doc = inspect.getdoc(atype)
    if doc:
        lines += [doc, ""]
    hints = []
    if atype.HOST:
        hints.append("HOST (runs host-side)")
    if atype.BATCH:
        hints.append(f"BATCH={atype.BATCH}")
    if atype.PRIORITY:
        hints.append(f"PRIORITY={atype.PRIORITY}")
    if getattr(atype, "SPAWNS", None):
        sp = ", ".join(
            f"{k if isinstance(k, str) else k.__name__}×{v}"
            for k, v in atype.SPAWNS.items())
        hints.append(f"SPAWNS({sp})")
    if hints:
        lines += ["*" + "; ".join(hints) + "*", ""]
    for note in lint_notes.get(None, ()):
        lines += [f"> **lint:** {note}", ""]
    if atype.field_specs:
        lines += ["| field | type |", "|---|---|"]
        for fname, spec in atype.field_specs.items():
            lines.append(f"| {fname} | {_SPEC_NAMES.get(spec, getattr(spec, '__name__', '?'))} |")
        lines.append("")
    for bdef in atype.behaviour_defs:
        lines.append(f"### be {_sig(bdef)}")
        lines.append("")
        # Effect marks (the verify pass, ≙ Pony's `?` partial mark in
        # generated docs): discovered by probe tracing; generic
        # templates and trace failures degrade to no marks.
        try:
            from .verify import behaviour_effects
            marks = behaviour_effects(bdef, atype).marks()
            if marks:
                lines += [f"*effects: {marks}*", ""]
        except Exception:                    # noqa: BLE001 — doc only
            pass
        for note in lint_notes.get(bdef.name, ()):
            lines += [f"> **lint:** {note}", ""]
        bdoc = inspect.getdoc(bdef.fn)
        if bdoc:
            lines += [bdoc, ""]
    return "\n".join(lines)


def document_types(*atypes: ActorTypeMeta, title: str = "Actors") -> str:
    parts = [f"# {title}", ""]
    for t in atypes:
        parts.append(document_type(t))
    return "\n".join(parts)


def _lint_notes_by_type(program, roots=None):
    """{type name: {behaviour or None: [note, ...]}} from the lint
    pass — unreachable (R1) / dead-letter (R2) and the body-rule
    findings (R6–R9, with their file:line) become doc marks. Doc
    generation must never fail on an unlintable program."""
    notes: dict = {}
    try:
        from .lint import lint_program
        for f in lint_program(program, roots=roots):
            where = (f" ({os.path.basename(f.file)}:{f.line})"
                     if f.file and f.line else "")
            notes.setdefault(f.type_name, {}).setdefault(
                f.behaviour, []).append(f"{f.rule} [{f.severity}] "
                                        f"{f.message}{where}")
    except Exception:                        # noqa: BLE001 — doc only
        pass
    return notes


def document(program, title: str = "Program", lint: bool = True,
             lint_roots=None) -> str:
    """Full program docs incl. the dispatch table (≙ docgen emitting the
    whole package tree after reach/paint assigned vtable slots). With
    `lint=True` the whole-program lint findings render as per-type /
    per-behaviour marks (unreachable, dead-letter, …); pass
    `lint_roots` to enable the rooted rules (see ponyc_tpu.lint)."""
    parts = [f"# {title}", "",
             f"{program.total} actor slots over {program.shards} "
             f"shard(s); {len(program.behaviour_table)} behaviours.", ""]
    parts += ["| gid | behaviour | cohort |", "|---|---|---|"]
    for gid, bdef in enumerate(program.behaviour_table):
        parts.append(f"| {gid} | {_sig(bdef)} | "
                     f"{bdef.actor_type.__name__} |")
    parts.append("")
    notes = _lint_notes_by_type(program, lint_roots) if lint else {}
    for cohort in program.cohorts:
        parts.append(document_type(cohort.atype,
                                   notes.get(cohort.atype.__name__)))
    return "\n".join(parts)


def write_tree(program, out_dir: str, title: str = "Program",
               lint: bool = True, lint_roots=None) -> List[str]:
    """One markdown file per type + an index (≙ the mkdocs tree)."""
    os.makedirs(out_dir, exist_ok=True)
    written = []
    notes = _lint_notes_by_type(program, lint_roots) if lint else {}
    index = [f"# {title}", "", "## Types", ""]
    for cohort in program.cohorts:
        name = cohort.atype.__name__
        path = os.path.join(out_dir, f"{name}.md")
        with open(path, "w") as f:
            f.write(document_type(cohort.atype, notes.get(name)))
        index.append(f"- [{name}]({name}.md)")
        written.append(path)
    idx = os.path.join(out_dir, "index.md")
    with open(idx, "w") as f:
        f.write("\n".join(index) + "\n")
    written.append(idx)
    return written
