"""Narrow/unsigned behaviour-argument widths (≙ packages/builtin numeric
breadth U8..U32/I8..I32; 64/128-bit stay host-side Python ints — the
documented TPU divergence, ops/pack.py U32 docstring)."""

import numpy as np
import pytest

from ponyc_tpu import (Bool, I8, I16, I32, Ref, Runtime, RuntimeOptions,
                       U8, U16, U32, actor, behaviour)


@actor
class NumSink:
    total: I32
    last_u32_lo: I32      # u32 value mod 2^16 (fits an i32 column)

    @behaviour
    def take(self, st, a: U32, b: I16, c: U16, d: I8, e: U8, f: Bool):
        # a arrives as uint32; narrow ints arrive at their declared
        # widths; compute mixes them into an i32 accumulator.
        lo = (a % np.uint32(65536)).astype("int32")
        acc = (lo + b.astype("int32") + c.astype("int32")
               + d.astype("int32") + e.astype("int32")
               + f.astype("int32"))
        return {**st, "total": st["total"] + acc, "last_u32_lo": lo}


@actor
class HostNum:
    HOST = True
    got: I32

    @behaviour
    def take(self, st, a: U32, d: I8):
        # host behaviours receive plain Python ints at declared widths
        assert isinstance(a, int) and a >= 0
        return {**st, "got": a % 1000 + d}


def _rt():
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=4, msg_words=6,
                                max_sends=1, spill_cap=64,
                                inject_slots=16))
    rt.declare(NumSink, 2).declare(HostNum, 1).start()
    return rt


def test_device_narrow_widths_roundtrip():
    rt = _rt()
    s = rt.spawn(NumSink)
    # u32 above 2^31; narrow values that wrap
    rt.send(s, NumSink.take, 3_000_000_007, -5, 65535, -128, 255, True)
    rt.run()
    st = rt.state_of(s)
    lo = 3_000_000_007 % 65536
    assert st["last_u32_lo"] == lo
    assert st["total"] == lo - 5 + 65535 - 128 + 255 + 1


def test_narrow_wrap_semantics():
    rt = _rt()
    s = rt.spawn(NumSink)
    # out-of-range inputs wrap to their declared width (≙ Pony's
    # fixed-width integer wrap): 70000 as I16 -> 70000-65536 = 4464
    rt.send(s, NumSink.take, 2**32 + 7, 70000, 70000, 130, 300, False)
    rt.run()
    st = rt.state_of(s)
    assert st["last_u32_lo"] == 7
    assert st["total"] == (7 + (70000 - 65536) + (70000 - 65536)
                           + (130 - 256) + (300 - 256))


def test_host_actor_receives_widened_ints():
    rt = _rt()
    h = rt.spawn(HostNum)
    rt.send(h, HostNum.take, 4_000_000_123, -3)
    rt.run()
    assert rt.state_of(h)["got"] == 4_000_000_123 % 1000 - 3


def test_narrow_marker_rejected_as_field():
    with pytest.raises(TypeError, match="message-argument types"):
        @actor
        class Bad:  # noqa: F811
            big: U32
