"""Flight recorder + stall watchdog tests (PROFILE.md §11): the
always-on bounded black box, postmortem dumps on stop/crash/SIGQUIT,
the watchdog converting a deliberately wedged run into a structured
postmortem + int-coded PonyStallError, stable error codes, and the
`doctor --postmortem` CLI."""

import json
import os
import subprocess
import sys
import time

import pytest

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu import flight
from ponyc_tpu.errors import ERROR_CODES, PonyError, PonyStallError, \
    error_code
from ponyc_tpu.models import ring

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _opts(**kw):
    base = dict(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8)
    base.update(kw)
    return RuntimeOptions(**base)


# ------------------------------------------------------ recorder basics

def test_recorder_always_on_and_bounded(tmp_path):
    """The black box exists on every runtime (no opt-in), records one
    entry per retired window, and its rings stay bounded."""
    path = str(tmp_path / "an.csv")
    rt, ids = ring.build(8, _opts(flight_windows=4, analysis_path=path))
    assert rt._flight is not None           # always-on
    rt.send(int(ids[0]), ring.RingNode.token, 200)
    rt.run()
    fr = rt._flight
    assert 1 <= len(fr.windows) <= 4        # bounded by flight_windows
    w = fr.windows[-1]
    assert set(w) >= {"t_ms", "step", "ticks", "budget", "gap_us",
                      "pipelined", "processed", "delivered", "occ_sum",
                      "occ_max", "qw_p99", "flags"}
    assert w["flags"]["exit"]               # ring exits at hops==1
    assert w["processed"] == 200
    rt.stop()


def test_recorder_gc_events_and_host_mail(tmp_path):
    @actor
    class HostEcho:
        n: I32
        HOST = True

        @behaviour
        def ping(self, st, v: I32):
            return {**st, "n": st["n"] + v}

    rt = Runtime(_opts(analysis_path=str(tmp_path / "an.csv")))
    rt.declare(HostEcho, 2).start()
    h = rt.spawn(HostEcho)
    rt.send(h, HostEcho.ping, 3)
    rt.run()
    rt.release([h])
    rt.gc()
    fr = rt._flight
    kinds = [e["kind"] for e in fr.events]
    assert "gc" in kinds
    assert any(m["behaviour"] == "HostEcho.ping" for m in fr.host_mail)
    rt.stop()


def test_stop_postmortem_dump_roundtrip(tmp_path, capfd):
    """Runtime.stop(postmortem=True) writes a valid structured dump
    (atomic .postmortem.json) and prints the human text; the file
    loads back through the doctor's reader."""
    path = str(tmp_path / "an.csv")
    rt, ids = ring.build(8, _opts(analysis_path=path))
    rt.send(int(ids[0]), ring.RingNode.token, 30)
    rt.run()
    rt.stop(postmortem=True)
    pm_path = path + ".postmortem.json"
    assert rt._flight.last_dump == pm_path
    pm = flight.load_postmortem(pm_path)
    assert pm["version"] == flight.POSTMORTEM_VERSION
    assert pm["reason"].startswith("stop")
    assert pm["steps_run"] == rt.steps_run
    assert pm["windows"] and pm["options"]["mailbox_cap"] == 8
    assert pm["phase"]["name"] == "idle"
    err = capfd.readouterr().err
    assert "flight-recorder postmortem" in err
    line, detail = flight.diagnose_postmortem(pm)
    assert line.startswith("SNAPSHOT")
    assert "windows" in detail


def test_crash_dump_on_fatal_run_error(tmp_path):
    """Any exceptional run() exit dumps the black box with the reason
    and the coded-error evidence."""

    @actor
    class Bad:
        n: I32
        HOST = True

        @behaviour
        def boom(self, st, v: I32):
            raise ValueError("kaboom")

    path = str(tmp_path / "an.csv")
    rt = Runtime(_opts(analysis_path=path))
    rt.declare(Bad, 2).start()
    b = rt.spawn(Bad)
    rt.send(b, Bad.boom, 1)
    with pytest.raises(ValueError, match="kaboom"):
        rt.run()
    pm = flight.load_postmortem(path + ".postmortem.json")
    assert pm["reason"].startswith("crash: ValueError")
    line, _ = flight.diagnose_postmortem(pm)
    assert line.startswith("CRASHED")


# ---------------------------------------------------------- error codes

def test_error_code_table_is_stable():
    """The code table is operational API (metrics labels, postmortems,
    alert rules): pin it."""
    assert ERROR_CODES == {
        "PonyError": 1, "SpillOverflowError": 2,
        "SpawnCapacityError": 3, "BlobCapacityError": 4,
        "CapabilityError": 5, "VerifyError": 6, "PonyStallError": 7,
        # Durable worlds (ISSUE 8) — codes are append-only.
        "SnapshotCorruptError": 8, "SnapshotFormatError": 9,
        "SnapshotGeometryError": 10, "PoisonError": 11,
        # Serving front door (ISSUE 9) — wire reply statuses too.
        "FrameError": 12, "ServeBusyError": 13,
        "ServeDeadlineError": 14}


def test_error_classes_expose_codes():
    from ponyc_tpu.hostmem import CapabilityError
    from ponyc_tpu.runtime.runtime import (BlobCapacityError,
                                           SpawnCapacityError,
                                           SpillOverflowError)
    from ponyc_tpu.verify import VerifyError
    assert SpillOverflowError.code == 2
    assert SpawnCapacityError.code == 3
    assert BlobCapacityError.code == 4
    assert CapabilityError.code == 5
    assert VerifyError.code == 6
    assert PonyStallError.code == 7
    assert error_code(SpillOverflowError("x")) == 2
    assert error_code(PonyError(42)) == 42        # instance code wins
    assert error_code(PonyError()) == 1
    assert error_code(ValueError("x")) == 0       # not a runtime error


def test_fatal_errors_count_for_metrics(tmp_path):
    """A fatal aux flag raise lands in rt._error_counts — the
    pony_tpu_errors_total{class=,code=} label source."""
    from ponyc_tpu.runtime.engine import zero_aux
    rt, _ids = ring.build(8, _opts(analysis_path=str(tmp_path / "a.csv")))
    from ponyc_tpu.runtime.runtime import SpillOverflowError
    a = zero_aux()._replace(spill_overflow=True)
    with pytest.raises(SpillOverflowError):
        rt._fatal_checks(a)
    assert rt._error_counts[("SpillOverflowError", 2)] == 1
    assert any(e["kind"] == "error" for e in rt._flight.events)
    rt.stop()


# ------------------------------------------------------------- watchdog

def test_watchdog_check_pure():
    """Deadline evaluation against synthetic phase stamps: armed phases
    trip past the (scaled) deadline, healthy phases never do."""
    rt, _ids = ring.build(8, _opts(watchdog_s=1.0))
    wd = rt._watchdog
    try:
        now = time.monotonic()
        # warm runtime: flush the cold-phase grace
        rt._rl_windows = 5
        rt._wd_stamp = ("host-work", 7, now - 0.5)
        assert wd.check(now) is None            # within deadline
        rt._wd_stamp = ("host-work", 8, now - 1.5)
        trip = wd.check(now)
        assert trip is not None and trip["phase"] == "host-work"
        assert trip["age_s"] >= 1.5 and trip["deadline_s"] == 1.0
        # quiescent/idle never trip, however old the stamp
        for phase in ("quiescent", "idle"):
            rt._wd_stamp = (phase, 9, now - 1e6)
            assert wd.check(now) is None
        # controller growth scales the deadline (window 4x initial)
        rt._wd_stamp = ("in-flight", 10, now - 1.5)
        rt._controller.window = rt._qi_loaded * 4
        assert wd.check(now) is None            # 4x deadline now
        rt._wd_stamp = ("in-flight", 11, now - 4.5)
        assert wd.check(now) is not None
    finally:
        rt.stop()


def test_watchdog_cold_phase_grace():
    """The first window's trace+compile must not read as a stall: cold
    device phases get COLD_FACTOR x deadline."""
    rt, _ids = ring.build(8, _opts(watchdog_s=1.0))
    wd = rt._watchdog
    try:
        now = time.monotonic()
        assert rt._rl_windows == 0              # nothing retired yet
        rt._wd_stamp = ("dispatching", 1, now - 2.0)
        assert wd.check(now) is None            # < 10s cold deadline
        rt._wd_stamp = ("dispatching", 2, now - 11.0)
        assert wd.check(now) is not None        # even cold has a limit
        # host-work never gets the cold grace (no compile there)
        rt._wd_stamp = ("host-work", 3, now - 2.0)
        assert wd.check(now) is not None
    finally:
        rt.stop()


def test_watchdog_quiet_run_never_trips(tmp_path):
    """A normal run with a tight-ish deadline completes untripped."""
    rt, ids = ring.build(8, _opts(watchdog_s=5.0,
                                  analysis_path=str(tmp_path / "a.csv")))
    rt.send(int(ids[0]), ring.RingNode.token, 50)
    assert rt.run() == 0
    assert rt._watchdog.tripped is None
    rt.stop()
    assert rt._watchdog is None                 # stop() reaps the thread


STALL_SCRIPT = """
import json, sys, time
sys.path.insert(0, {root!r})
from ponyc_tpu.platforms import force_cpu
force_cpu()
from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.errors import PonyStallError

@actor
class Wedge:
    n: I32
    HOST = True

    @behaviour
    def jam(self, st, v: I32):
        time.sleep(600)            # the deliberate stall
        return st

rt = Runtime(RuntimeOptions(
    mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
    watchdog_s=0.6, analysis_path={apath!r}))
rt.declare(Wedge, 2).start()
w = rt.spawn(Wedge)
rt.send(w, Wedge.jam, 1)
t0 = time.monotonic()
try:
    rt.run()
    print("NO-RAISE")
except PonyStallError as e:
    print(json.dumps({{"code": e.code, "phase": e.phase,
                      "postmortem": e.postmortem,
                      "elapsed_s": round(time.monotonic() - t0, 1)}}))
    sys.exit(42)
"""


def test_watchdog_trips_wedged_run_subprocess(tmp_path):
    """ACCEPTANCE: a deliberately wedged run is converted by the
    watchdog into a structured postmortem + int-coded PonyStallError
    within the deadline — instead of the silent forever-hang."""
    apath = str(tmp_path / "stall.csv")
    code = STALL_SCRIPT.format(root=ROOT, apath=apath)
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 42, (p.returncode, p.stdout, p.stderr)
    out = json.loads(p.stdout.strip().splitlines()[-1])
    assert out["code"] == ERROR_CODES["PonyStallError"] == 7
    assert out["phase"] == "host-work"
    # "within the deadline": the stall lasted 600s, the conversion took
    # seconds (deadline 0.6s + trip poll + signal delivery + unwind).
    assert out["elapsed_s"] < 60
    # The watchdog's postmortem is on disk and structurally valid.
    pm = flight.load_postmortem(out["postmortem"])
    assert pm["reason"].startswith("watchdog")
    assert pm["watchdog"]["tripped"]["phase"] == "host-work"
    assert any(e["kind"] == "watchdog_trip" for e in pm["events"])
    line, _ = flight.diagnose_postmortem(pm)
    assert line.startswith("STALLED")
    assert "host behaviour" in line            # the phase hint
    assert "STALLED" in p.stderr               # loud on the way down


SIGQUIT_SCRIPT = """
import os, signal, sys
sys.path.insert(0, {root!r})
from ponyc_tpu.platforms import force_cpu
force_cpu()
from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour

@actor
class Poker:
    n: I32
    HOST = True

    @behaviour
    def poke(self, st, v: I32):
        os.kill(os.getpid(), signal.SIGQUIT)   # operator hits ^\\
        self.exit(0, when=v <= 0)
        self.send(self.actor_id, Poker.poke, v - 1, when=v > 0)
        return st

rt = Runtime(RuntimeOptions(
    mailbox_cap=8, batch=1, max_sends=2, msg_words=1,
    analysis_path={apath!r}))
rt.declare(Poker, 2).start()
p = rt.spawn(Poker)
rt.send(p, Poker.poke, 2)
code = rt.run()
print("EXIT", code, "DUMPS", rt._flight.dumps)
sys.exit(code)
"""


def test_sigquit_dumps_and_continues(tmp_path):
    """SIGQUIT mid-run dumps the flight recorder and the run carries on
    to its normal exit (dump-and-continue, unlike SIGTERM)."""
    apath = str(tmp_path / "sq.csv")
    code = SIGQUIT_SCRIPT.format(root=ROOT, apath=apath)
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, (p.returncode, p.stdout, p.stderr)
    assert "EXIT 0" in p.stdout
    assert "DUMPS 3" in p.stdout               # one per SIGQUIT
    pm = flight.load_postmortem(apath + ".postmortem.json")
    assert pm["reason"] == "SIGQUIT"
    assert "flight-recorder postmortem" in p.stderr


# -------------------------------------------------- probe postmortems

def test_probe_postmortem_and_diagnosis():
    """The backend-init evidence bench.py embeds on tpu_init_error."""
    tl = [{"attempt": 1, "timeout_s": 180.0, "t_s": 180.2,
           "error": "jax.devices() did not return within 180s "
                    "(backend init hang)"},
          {"attempt": 2, "timeout_s": 300.0, "t_s": 12.0,
           "error": "probe exited rc=1"}]
    pm = flight.probe_postmortem(tl, {"env": {}, "libtpu_importable": False})
    json.dumps(pm)                              # must serialise
    assert pm["reason"] == "tpu_init_failed"
    assert pm["phase"]["name"] == "backend-init"
    assert pm["probe_timeline"] == tl
    line, detail = flight.diagnose_postmortem(pm)
    assert line.startswith("STALLED: TPU backend init failed after 2")
    assert "probe exited rc=1" in line
    assert "attempt 1" in detail


# ----------------------------------------------------------- doctor CLI

def test_doctor_cli_postmortem(tmp_path, capsys):
    from ponyc_tpu.__main__ import main as cli_main
    path = str(tmp_path / "an.csv")
    rt, ids = ring.build(8, _opts(analysis_path=path))
    rt.send(int(ids[0]), ring.RingNode.token, 20)
    rt.run()
    rt.stop(postmortem=True)
    capsys.readouterr()
    # A plain snapshot diagnoses healthy (exit 0).
    assert cli_main(["doctor", "--postmortem",
                     path + ".postmortem.json"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("SNAPSHOT")
    assert "flight-recorder postmortem" in out
    # A stall postmortem exits 1.
    stall = rt._flight.postmortem("watchdog: phase 'in-flight' made no "
                                  "progress for 9.0s (deadline 3.0s)")
    spath = str(tmp_path / "stall.json")
    json.dump(stall, open(spath, "w"))
    assert cli_main(["doctor", "--postmortem", spath]) == 1
    assert capsys.readouterr().out.startswith("STALLED")


def test_doctor_cli_bench_json_wrapper(tmp_path, capsys):
    """`doctor --postmortem BENCH.json` reads the nested probe
    evidence a CPU-fallback bench round embeds."""
    from ponyc_tpu.__main__ import main as cli_main
    tl = [{"attempt": 1, "timeout_s": 60.0, "t_s": 60.0,
           "error": "backend init hang"}]
    bench_json = {"metric": "x", "value": 1,
                  "postmortem": flight.probe_postmortem(tl, {"env": {}})}
    path = str(tmp_path / "BENCH_r99.json")
    json.dump(bench_json, open(path, "w"))
    assert cli_main(["doctor", "--postmortem", path]) == 1
    assert "TPU backend init failed" in capsys.readouterr().out


def test_doctor_cli_usage_errors(tmp_path):
    from ponyc_tpu.__main__ import main as cli_main
    assert cli_main(["doctor"]) == 2                    # no target
    assert cli_main(["doctor", "--postmortem"]) == 2    # missing file
    assert cli_main(["doctor", "--postmortem",
                     str(tmp_path / "absent.json")]) == 2
    bad = str(tmp_path / "bad.json")
    open(bad, "w").write("{}")
    assert cli_main(["doctor", "--postmortem", bad]) == 2


def test_watchdog_option_validation():
    with pytest.raises(ValueError, match="watchdog_s"):
        RuntimeOptions(watchdog_s=0.0)
    with pytest.raises(ValueError, match="flight_windows"):
        RuntimeOptions(flight_windows=0)
