"""Model-family correctness: fan-in backpressure conservation, gups xor
conservation, n-body against a NumPy all-pairs oracle (≙ the reference's
examples doubling as its de-facto runtime integration tests, SURVEY.md §4).
"""

import numpy as np

from ponyc_tpu import RuntimeOptions
from ponyc_tpu.models import fanin, gups, nbody


def test_fanin_backpressure_conserves_messages():
    n_prod, items = 16, 32
    rt = fanin.run(n_producers=n_prod, items_each=items)
    agg_total = rt.cohort_state(fanin.Aggregator)["total"][0]
    assert agg_total == n_prod * items          # nothing lost, nothing dup'd
    assert rt.counter("n_mutes") > 0            # backpressure actually fired
    assert rt.counter("n_rejected") > 0         # spill path exercised
    assert rt.exit_code == 0


def test_fanin_producers_actually_muted_midway():
    # Tight mailbox: the aggregator (batch=1) can't keep up with 16
    # producers; at some point most producers must be muted.
    rt = fanin.run(n_producers=16, items_each=16,
                   opts=RuntimeOptions(mailbox_cap=4, batch=1, msg_words=1,
                                       spill_cap=128))
    assert rt.cohort_state(fanin.Aggregator)["total"][0] == 16 * 16
    assert rt.counter("n_mutes") >= 8


def test_gups_xor_conservation():
    # xor of all cell values == xor of all values sent (xor is an
    # order-insensitive group op, so delivery order can't hide bugs).
    rt = gups.run(table_size=512, n_updaters=16, updates_each=16)
    upd = rt.cohort_state(gups.Updater)
    assert (upd["done"] == 16).all()
    cells = rt.cohort_state(gups.TableCell)["value"]
    # Replay the PRNG on host to get the expected xor stream.
    import numpy as np
    x = np.asarray(
        np.random.default_rng(7).integers(1, 2**31 - 1, 16), np.int32)
    expect = np.int32(0)
    for _ in range(16):
        x = (x ^ (x << 13)).astype(np.int32)
        x = (x ^ ((x >> 17) & 0x7FFF)).astype(np.int32)
        x = (x ^ (x << 5)).astype(np.int32)
        expect ^= np.bitwise_xor.reduce(x)
    got = np.bitwise_xor.reduce(cells.astype(np.int32))
    assert got == expect


def test_nbody_matches_all_pairs_oracle():
    n = 24
    rt = nbody.run_round(n_bodies=n)
    st = rt.cohort_state(nbody.Body)
    assert (st["seen"] == n - 1).all()
    ax, ay = nbody.reference_accels(st["x"], st["y"], st["m"])
    np.testing.assert_allclose(st["ax"], ax, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(st["ay"], ay, rtol=2e-4, atol=2e-5)


def test_gups_opt_batched_updates():
    # ≙ examples/gups_opt: K updates per dispatch; same xor-conservation
    # oracle, K× the per-tick throughput.
    import numpy as np
    rt = gups.run_opt(table_size=512, n_updaters=8, ticks_each=4)
    upd = rt.cohort_state(gups.OptUpdater)
    K = gups.OptUpdater.K
    assert (upd["done"] == 4 * K).all()
    cells = rt.cohort_state(gups.TableCell)["value"]
    x = np.asarray(
        np.random.default_rng(11).integers(1, 2**31 - 1, 8), np.int32)
    expect = np.int32(0)
    for _ in range(4 * K):
        x = (x ^ (x << 13)).astype(np.int32)
        x = (x ^ ((x >> 17) & 0x7FFF)).astype(np.int32)
        x = (x ^ (x << 5)).astype(np.int32)
        expect ^= np.bitwise_xor.reduce(x)
    assert np.bitwise_xor.reduce(cells) == expect


def test_ubench_multi_ping_sustains_n_times_pings():
    """`pings` in-flight messages per pinger (≙ the reference's
    --initial-pings, examples/message-ubench/main.pony default 5) sustain
    exactly N*pings dispatches per tick with no overflow."""
    from ponyc_tpu.models import ubench
    n, p = 128, 4
    opts = RuntimeOptions(mailbox_cap=4, batch=p, max_sends=1, msg_words=1,
                          spill_cap=128, inject_slots=8)
    rt, ids = ubench.build(n, opts, pings=p)
    ubench.seed_all(rt, ids, hops=1 << 30, pings=p)
    st, inj = rt.state, rt._empty_inject
    for _ in range(6):
        st, aux = rt._step(st, *inj)
    rt.state = st
    assert rt.counter("n_processed") == 6 * n * p
    assert not bool(aux.spill_overflow)
    assert not bool(aux.n_muted_now)


def test_mandelbrot_matches_numpy_oracle():
    """Escape-time bytes from the Worker cohort equal the NumPy oracle
    (≙ examples/mandelbrot computing PBM bitmap bytes in Worker actors)."""
    from ponyc_tpu.models import mandelbrot
    w = h = 32
    got = mandelbrot.render(w, h)
    want = mandelbrot.reference_bytes(w, h)
    assert got.shape == want.shape == (h, w // 8)
    assert (got == want).all()
    assert 0 < int(want.sum()), "image must not be empty"
