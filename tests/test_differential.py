"""Randomized differential testing: the device engine vs a sequential
Python oracle of actor semantics.

≙ the role the aggregated stdlib test binary plays for the reference
(packages/stdlib/_test.pony) — broad behavioural coverage — plus the
layer the reference lacks (SURVEY.md §4): direct scheduler/delivery
semantics checks. Message outcomes here are commutative (per-actor sums
and counts), so the terminal state is schedule-independent: ANY correct
scheduler — the reference's work-stealing M:N, our lockstep ticks, the
oracle's sequential walk — must produce identical columns. Tiny mailbox
caps force the spill → mute → unmute machinery; the mesh variants force
routing and cross-shard spill; both delivery formulations must agree.
"""

import numpy as np
import pytest

from ponyc_tpu import (Blob, I32, Ref, Runtime, RuntimeOptions, actor,
                       behaviour)


@actor
class Walker:
    """Token walk over a random functional graph: receive v, accumulate,
    forward v-1 to this actor's fixed successor while v > 0."""
    acc: I32
    hits: I32
    nxt: Ref["Walker"]

    MAX_SENDS = 1

    @behaviour
    def step(self, st, v: I32):
        self.send(st["nxt"], Walker.step, v - 1, when=v > 0)
        return {**st, "acc": st["acc"] + v, "hits": st["hits"] + 1}


@actor
class HostLog:
    """Host-resident termination counter: Walkers report each chain's
    end (v == 0 arrivals), so the randomized harness also exercises the
    device→host drain path."""
    HOST = True
    ends: I32
    total: I32

    @behaviour
    def done(self, st, tail: I32):
        return {**st, "ends": st["ends"] + 1, "total": st["total"] + tail}


@actor
class WalkerH:
    """Walker variant that reports chain termination to a host actor."""
    acc: I32
    nxt: Ref["WalkerH"]
    log: Ref["HostLog"]

    MAX_SENDS = 2

    @behaviour
    def step(self, st, v: I32):
        self.send(st["nxt"], WalkerH.step, v - 1, when=v > 0)
        self.send(st["log"], HostLog.done, st["acc"] + v, when=v == 0)
        return {**st, "acc": st["acc"] + v}


@actor
class Splitter:
    """Receive v: accumulate, and while v > 0 send v-1 to BOTH a Walker
    and another Splitter (bounded binary fan-out — message count grows
    then dies; exercises bursts far above mailbox capacity)."""
    acc: I32
    w_ref: Ref["Walker"]
    s_ref: Ref["Splitter"]

    MAX_SENDS = 2

    @behaviour
    def burst(self, st, v: I32):
        self.send(st["w_ref"], Walker.step, v - 1, when=v > 0)
        self.send(st["s_ref"], Splitter.burst, v - 2, when=v > 1)
        return {**st, "acc": st["acc"] + v}


def oracle(n_w, n_s, w_nxt, s_w, s_s, seeds):
    """Sequential simulator with unbounded FIFO queues (the reference's
    semantics modulo scheduling, which the commutative outcome erases)."""
    from collections import deque
    w_acc = np.zeros(n_w, np.int64)
    w_hits = np.zeros(n_w, np.int64)
    s_acc = np.zeros(n_s, np.int64)
    q = deque(seeds)                       # ('w'|'s', idx, v)
    while q:
        kind, i, v = q.popleft()
        if kind == "w":
            w_acc[i] += v
            w_hits[i] += 1
            if v > 0:
                q.append(("w", w_nxt[i], v - 1))
        else:
            s_acc[i] += v
            if v > 0:
                q.append(("w", s_w[i], v - 1))
            if v > 1:
                q.append(("s", s_s[i], v - 2))
    return w_acc, w_hits, s_acc


def run_device(n_w, n_s, w_nxt, s_w, s_s, seeds, opts):
    rt = Runtime(opts)
    rt.declare(Walker, n_w).declare(Splitter, n_s)
    rt.start()
    wids = rt.spawn_many(Walker, n_w)
    sids = rt.spawn_many(Splitter, n_s)
    rt.set_fields(Walker, wids, nxt=wids[np.asarray(w_nxt)])
    rt.set_fields(Splitter, sids, w_ref=wids[np.asarray(s_w)],
                  s_ref=sids[np.asarray(s_s)])
    for kind, i, v in seeds:
        if kind == "w":
            rt.send(int(wids[i]), Walker.step, v)
        else:
            rt.send(int(sids[i]), Splitter.burst, v)
    assert rt.run(max_steps=300_000) == 0, "must quiesce"
    # Slot order == spawn order; a mesh rounds capacity up to a shard
    # multiple, so slice to the actually-spawned rows.
    wst = rt.cohort_state(Walker)
    sst = rt.cohort_state(Splitter)
    assert not np.asarray(rt.state.muted).any(), "terminal world unmuted"
    return (wst["acc"][:n_w].astype(np.int64),
            wst["hits"][:n_w].astype(np.int64),
            sst["acc"][:n_s].astype(np.int64))


def _case(seed, n_w=24, n_s=8, n_seeds=10, vmax=14):
    rng = np.random.default_rng(seed)
    w_nxt = rng.integers(0, n_w, n_w)
    s_w = rng.integers(0, n_w, n_s)
    s_s = rng.integers(0, n_s, n_s)
    seeds = []
    for _ in range(n_seeds):
        if rng.random() < 0.6:
            seeds.append(("w", int(rng.integers(0, n_w)),
                          int(rng.integers(1, vmax))))
        else:
            seeds.append(("s", int(rng.integers(0, n_s)),
                          int(rng.integers(2, vmax))))
    return w_nxt, s_w, s_s, seeds


CONFIGS = [
    ("tiny-cap-forces-spill", dict(mailbox_cap=2, batch=1, msg_words=1,
                                   max_sends=2, spill_cap=512,
                                   inject_slots=16)),
    ("cosort", dict(mailbox_cap=4, batch=2, msg_words=1, max_sends=2,
                    spill_cap=512, inject_slots=16, delivery="cosort")),
    ("mesh4", dict(mailbox_cap=4, batch=2, msg_words=1, max_sends=2,
                   spill_cap=1024, inject_slots=32, mesh_shards=4,
                   quiesce_interval=2)),
    ("mesh4-tiny-bucket", dict(mailbox_cap=2, batch=1, msg_words=1,
                               max_sends=2, spill_cap=2048,
                               inject_slots=32, mesh_shards=4,
                               route_bucket=8, quiesce_interval=1)),
    ("fused-kernel", dict(mailbox_cap=4, batch=2, msg_words=1,
                          max_sends=2, spill_cap=512, inject_slots=16,
                          pallas_fused=True)),
    # PR 11: the whole gated window as ONE persistent Pallas kernel
    # (ops/megakernel.py, interpret mode on CPU) — must match the
    # sequential oracle exactly, like every XLA formulation above.
    ("pallas-mega", dict(mailbox_cap=2, batch=1, msg_words=1,
                         max_sends=2, spill_cap=512, inject_slots=16,
                         delivery="pallas_mega")),
]


def test_host_reporting_matches_oracle():
    """Chains terminate into a HOST actor; end-count and tail sums must
    match a sequential oracle exactly (device→host drain under random
    traffic, tiny caps)."""
    seed, n_w = 31, 20
    rng = np.random.default_rng(seed)
    w_nxt = rng.integers(0, n_w, n_w)
    starts = [(int(rng.integers(0, n_w)), int(rng.integers(1, 12)))
              for _ in range(8)]
    # oracle: walk each chain; on v==0 arrival, record acc_after + 0
    acc = np.zeros(n_w, np.int64)
    ends = 0
    tails = 0
    from collections import deque
    q = deque([("w", i, v) for i, v in starts])
    while q:
        _, i, v = q.popleft()
        acc[i] += v
        if v > 0:
            q.append(("w", int(w_nxt[i]), v - 1))
        else:
            ends += 1
            tails += int(acc[i])
    # NOTE: tails depends on acc-at-arrival order, which IS schedule
    # dependent — compare only the schedule-independent outputs.
    rt = Runtime(RuntimeOptions(mailbox_cap=2, batch=1, msg_words=2,
                                max_sends=2, spill_cap=512,
                                inject_slots=16))
    rt.declare(WalkerH, n_w).declare(HostLog, 1).start()
    wids = rt.spawn_many(WalkerH, n_w)
    log = rt.spawn(HostLog)
    rt.set_fields(WalkerH, wids, nxt=wids[np.asarray(w_nxt)],
                  log=np.full(n_w, log))
    for i, v in starts:
        rt.send(int(wids[i]), WalkerH.step, v)
    assert rt.run(max_steps=100_000) == 0
    wst = rt.cohort_state(WalkerH)
    assert (wst["acc"].astype(np.int64) == acc).all()
    assert rt.state_of(log)["ends"] == ends == len(starts)


def test_uneven_cohorts_on_mesh_match_oracle():
    """Cohort sizes NOT divisible by the shard count (capacity rounds up;
    the padded rows must stay inert and slot-order reads must slice
    clean)."""
    n_w, n_s = 37, 11                  # neither divides 4
    w_nxt, s_w, s_s, seeds = _case(51, n_w, n_s)
    want = oracle(n_w, n_s, w_nxt, s_w, s_s, seeds)
    got = run_device(n_w, n_s, w_nxt, s_w, s_s, seeds, RuntimeOptions(
        mailbox_cap=2, batch=1, msg_words=1, max_sends=2, spill_cap=2048,
        inject_slots=32, mesh_shards=4, quiesce_interval=2))
    for g, w in zip(got, want):
        assert (g == w).all()


@pytest.mark.parametrize("name,okw", CONFIGS, ids=[c[0] for c in CONFIGS])
@pytest.mark.parametrize("seed", [7, 23])
def test_device_matches_oracle(name, okw, seed):
    n_w, n_s = 24, 8
    w_nxt, s_w, s_s, seeds = _case(seed, n_w, n_s)
    want = oracle(n_w, n_s, w_nxt, s_w, s_s, seeds)
    got = run_device(n_w, n_s, w_nxt, s_w, s_s, seeds,
                     RuntimeOptions(**okw))
    for g, w, what in zip(got, want, ("w_acc", "w_hits", "s_acc")):
        assert (g == w).all(), (
            name, seed, what, np.nonzero(g != w)[0][:5], g.sum(), w.sum())


def test_multi_behaviour_dispatch_matches_oracle():
    """Three behaviours of different arities on one type under random
    traffic: per-lane behaviour-id selection across batch slots (the
    lax.switch-equivalent path the single-behaviour configs never
    exercise). Commutative outputs compared exactly; acc only for
    actors untouched by the non-commutative behaviour."""
    from collections import deque

    @actor
    class Tri:
        acc: I32
        count: I32
        nxt: Ref["Tri"]

        MAX_SENDS = 2

        @behaviour
        def add(self, st, v: I32):
            self.send(st["nxt"], Tri.add, v - 2, when=v > 2)
            return {**st, "acc": st["acc"] + v,
                    "count": st["count"] + 1}

        @behaviour
        def mul2_then_ping(self, st, v: I32, flag: I32):
            self.send(st["nxt"], Tri.ping, when=flag > 0)
            return {**st, "acc": st["acc"] * 2 + v,
                    "count": st["count"] + 1}

        @behaviour
        def ping(self, st):
            return {**st, "count": st["count"] + 1}

    def oracle(n, nxt, seeds):
        acc = np.zeros(n, np.int64)
        cnt = np.zeros(n, np.int64)
        q = deque(seeds)
        while q:
            op, i, args = q.popleft()
            if op == "add":
                v, = args
                acc[i] += v
                cnt[i] += 1
                if v > 2:
                    q.append(("add", int(nxt[i]), (v - 2,)))
            elif op == "mul":
                v, flag = args
                acc[i] = acc[i] * 2 + v
                cnt[i] += 1
                if flag > 0:
                    q.append(("ping", int(nxt[i]), ()))
            else:
                cnt[i] += 1
        return acc, cnt

    for seed, mode in ((501, "plan"), (506, "cosort")):
        rng = np.random.default_rng(seed)
        n = int(rng.integers(10, 40))
        nxt = rng.integers(0, n, n)
        seeds = []
        for _ in range(10):
            r = rng.random()
            i = int(rng.integers(0, n))
            if r < 0.5:
                seeds.append(("add", i, (int(rng.integers(1, 12)),)))
            elif r < 0.85:
                seeds.append(("mul", i, (int(rng.integers(0, 5)),
                                         int(rng.integers(0, 2)))))
            else:
                seeds.append(("ping", i, ()))
        want_acc, want_cnt = oracle(n, nxt, seeds)
        mul_targets = {i for op, i, _ in seeds if op == "mul"}
        rt = Runtime(RuntimeOptions(mailbox_cap=2, batch=1, msg_words=2,
                                    max_sends=2, spill_cap=1024,
                                    inject_slots=16, delivery=mode))
        rt.declare(Tri, n).start()
        ids = rt.spawn_many(Tri, n)
        rt.set_fields(Tri, ids, nxt=ids[np.asarray(nxt)])
        for op, i, args in seeds:
            b = {"add": Tri.add, "mul": Tri.mul2_then_ping,
                 "ping": Tri.ping}[op]
            rt.send(int(ids[i]), b, *args)
        assert rt.run(max_steps=100_000) == 0
        st = rt.cohort_state(Tri)
        assert (st["count"][:n].astype(np.int64) == want_cnt).all()
        for i in range(n):
            if i not in mul_targets:
                assert int(st["acc"][i]) == int(want_acc[i])


@actor
class BlobWalker:
    """Walker whose token carries a one-word device BLOB: each hop reads
    the word, frees the incoming blob, and (while v > 0) allocates a
    FRESH blob carrying word+1 for the successor — ownership cannot be
    conditionally forwarded-or-freed (both are trace-time moves), so
    conditional routing re-allocates; this is also the harder test:
    alloc/free churn and slot recycling on every hop."""
    acc: I32
    nxt: Ref["BlobWalker"]

    MAX_SENDS = 1
    MAX_BLOBS = 1
    BLOB_DISPATCHES = 1
    BATCH = 1

    @behaviour
    def step(self, st, v: I32, h: Blob):
        w0 = self.blob_get(h, 0)
        self.blob_free(h)
        go = v > 0
        h2 = self.blob_alloc(length=1, when=go)
        self.blob_set(h2, 0, w0 + 1, when=go)
        self.send(st["nxt"], BlobWalker.step, v - 1, h2, when=go)
        return {**st, "acc": st["acc"] + w0}


def run_blob_chain(seed, opts_kw, n=None, n_starts=6, vmax=10,
                   expect_moves=False):
    """One randomized blob-chain world vs the sequential oracle
    (shared by the pytest cases below and tests/hunt.py --blob): random
    functional graph, random seeds; every hop reads + frees + re-allocs
    the token blob, chains cross shards freely (migration)."""
    rng = np.random.default_rng(seed)
    n = n or int(rng.integers(8, 40))
    nxt = rng.integers(0, n, n)

    def oracle_blob(seeds):
        from collections import deque
        acc = np.zeros(n, np.int64)
        q = deque(seeds)                   # (idx, v, word)
        while q:
            i, v, w = q.popleft()
            acc[i] += w
            if v > 0:
                q.append((int(nxt[i]), v - 1, w + 1))
        return acc

    seeds = [(int(rng.integers(0, n)), int(rng.integers(1, vmax)),
              int(rng.integers(0, 50))) for _ in range(n_starts)]
    want = oracle_blob(seeds)
    opts = RuntimeOptions(msg_words=3, blob_slots=256, blob_words=2,
                          **opts_kw)
    rt = Runtime(opts)
    rt.declare(BlobWalker, n).start()
    ids = rt.spawn_many(BlobWalker, n, acc=0)
    rt.set_fields(BlobWalker, ids, nxt=ids[np.asarray(nxt)])
    for i, v, w in seeds:
        # Host injections don't route, so allocate on the seed's shard;
        # after that, chains cross shards freely — blobs MIGRATE with
        # the routed messages (engine._route).
        h = rt.blob_store([w], near=int(ids[i]))
        rt.send(int(ids[i]), BlobWalker.step, v, h)
    assert rt.run(max_steps=100_000) == 0
    st = rt.cohort_state(BlobWalker)
    assert (st["acc"][:n].astype(np.int64) == want).all(), (
        st["acc"][:n], want)
    assert rt.blobs_in_use == 0            # every chain end freed its blob
    assert rt.counter("n_blob_remote") == 0    # nothing arrived dead
    if expect_moves:
        assert rt.counter("n_blob_moved") > 0  # chains DID cross shards
    return rt


@pytest.mark.parametrize("mode,shards,bucket", [
    ("plan", 1, 0), ("cosort", 1, 0), ("plan", 2, 0),
    # Tiny route bucket: blob-carrying messages PARK in the route spill
    # and migrate only when the retry actually ships — the
    # spilled-blobs-stay-local invariant under congestion.
    ("plan", 2, 2)])
def test_blob_chain_matches_oracle(mode, shards, bucket):
    run_blob_chain(77, dict(mailbox_cap=2, batch=1, max_sends=1,
                            spill_cap=1024, inject_slots=16,
                            delivery=mode, mesh_shards=shards,
                            route_bucket=bucket),
                   n=16, expect_moves=shards > 1)
