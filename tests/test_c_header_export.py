"""C header EXPORT (translate/c_header_export.py) — the outbound half
of FFI (≙ genheader.c:256): the emitted header must compile under g++
and agree with the program's actual ids and layouts."""

import subprocess
import tempfile
import os

from ponyc_tpu import (F32, I32, Iso, Ref, Runtime, RuntimeOptions,  # noqa
                       VecF32, actor, behaviour)
from ponyc_tpu.translate import export_header, write_header


@actor
class Sensor:
    hub: Ref["Hub"]
    reading: F32

    @behaviour
    def sample(self, st, v: F32, seq: I32):
        self.send(st["hub"], Hub.collect, v, when=seq >= 0)
        return {**st, "reading": v}

    @behaviour
    def rewire(self, st, h: Ref["Hub"]):
        return {**st, "hub": h}


@actor
class Hub:
    total: F32
    MAX_SENDS = 0

    @behaviour
    def collect(self, st, v: F32):
        return {**st, "total": st["total"] + v}

    @behaviour
    def calibrate(self, st, coeffs: VecF32[3], blob: Iso):
        return st


def _build():
    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                          msg_words=4, inject_slots=8)
    rt = Runtime(opts)
    rt.declare(Sensor, 4).declare(Hub, 2).start()
    return rt, opts


def test_header_reflects_program_abi():
    rt, opts = _build()
    text = export_header(rt.program, opts)
    gid = {b.actor_type.__name__ + "." + b.name: b.global_id
           for b in rt.program.behaviour_table}
    assert f"PONYC_TPU_GID_SENSOR_SAMPLE = {gid['Sensor.sample']}" in text
    assert f"PONYC_TPU_GID_HUB_COLLECT = {gid['Hub.collect']}" in text
    assert "#define PONYC_TPU_MSG_WORDS 4" in text
    assert "#define PONYC_TPU_HUB_MSG_WORDS 4" in text      # Vec3 + Iso
    assert "#define PONYC_TPU_SENSOR_MSG_WORDS 2" in text   # F32 + I32
    assert "float coeffs[3];" in text
    assert "Iso host-heap handle" in text
    assert "Ref[Hub] actor id" in text


def test_header_compiles_under_gpp():
    rt, opts = _build()
    with tempfile.TemporaryDirectory() as d:
        h = write_header(rt.program, opts, os.path.join(d, "prog.h"))
        main = os.path.join(d, "main.cc")
        gid = {b.actor_type.__name__ + "." + b.name: b.global_id
               for b in rt.program.behaviour_table}
        with open(main, "w") as f:
            f.write(f'''
#include "prog.h"
#include <cstdio>
int main() {{
  struct ponyc_tpu_Sensor_sample_args a;
  a.v = 1.5f; a.seq = 7;
  struct ponyc_tpu_msg m;
  m.behaviour_id = PONYC_TPU_GID_SENSOR_SAMPLE;
  static_assert(PONYC_TPU_GID_SENSOR_SAMPLE == {gid['Sensor.sample']},
                "gid");
  static_assert(PONYC_TPU_SENSOR_SAMPLE_ARG_WORDS == 2, "width");
  static_assert(PONYC_TPU_HUB_CALIBRATE_ARG_WORDS == 4, "vec+iso");
  std::printf("%d %d\\n", m.behaviour_id, a.seq);
  return 0;
}}
''')
        exe = os.path.join(d, "a.out")
        r = subprocess.run(["g++", "-std=c++17", "-Wall", "-Werror",
                            main, "-o", exe],
                           capture_output=True, text=True)
        assert r.returncode == 0, r.stderr
        out = subprocess.run([exe], capture_output=True, text=True)
        assert out.stdout.split() == [str(gid["Sensor.sample"]), "7"]


def test_narrow_ints_occupy_full_words():
    """Every one-word spec is a full int32 wire word (pack.spec_width
    widens narrow ints) — the struct layout must agree so memcpy into
    ponyc_tpu_msg.words is mechanical (round-5 review regression)."""
    from ponyc_tpu import I16, U8

    @actor
    class Narrowed:
        x: I32

        @behaviour
        def put(self, st, a: I16, b: U8):
            return st

    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                          msg_words=2, inject_slots=8)
    rt = Runtime(opts)
    rt.declare(Narrowed, 1).start()
    text = export_header(rt.program, opts)
    assert "int32_t /* i16 value range */ a;" in text
    assert "int32_t /* u8 value range */ b;" in text
    assert "int16_t" not in text and "int8_t" not in text
    assert "#define PONYC_TPU_NARROWED_PUT_ARG_WORDS 2" in text
