"""Child processes and file system (≙ packages/process and
packages/files integration tests under ponytest)."""

import pytest

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.files import Directory, File, FilePath


@actor
class Collector:
    HOST = True
    n_out: I32
    n_err: I32
    code: I32
    done: I32

    @behaviour
    def on_stdout(self, st, proc: I32, data: I32, n: I32):
        chunk = self.rt.heap.unbox(data)
        self.rt.heap.box(chunk)  # re-box so the test can inspect later
        return {**st, "n_out": st["n_out"] + n}

    @behaviour
    def on_stderr(self, st, proc: I32, data: I32, n: I32):
        self.rt.heap.drop(data)
        return {**st, "n_err": st["n_err"] + n}

    @behaviour
    def on_exit(self, st, proc: I32, code: I32):
        self.exit(0)
        return {**st, "code": code, "done": 1}


def _mk():
    rt = Runtime(RuntimeOptions(mailbox_cap=16, batch=4, max_sends=2,
                                msg_words=4, inject_slots=32))
    rt.declare(Collector, 1)
    return rt.start()


def test_process_echo_collects_output_and_exit():
    rt = _mk()
    procs = rt.attach_processes()
    owner = rt.spawn(Collector)
    procs.spawn("/bin/sh", ["sh", "-c", "echo hello-child; exit 7"],
                owner, on_stdout=Collector.on_stdout,
                on_stderr=Collector.on_stderr, on_exit=Collector.on_exit)
    rt.run(max_steps=4000)
    st = rt.state_of(owner)
    assert st["done"] == 1
    assert st["code"] == 7
    assert st["n_out"] == len(b"hello-child\n")
    rt.stop()


def test_process_stdin_roundtrip_and_stderr():
    rt = _mk()
    procs = rt.attach_processes()
    owner = rt.spawn(Collector)
    pid = procs.spawn("/bin/sh", ["sh", "-c", "cat; echo oops >&2"],
                      owner, on_stdout=Collector.on_stdout,
                      on_stderr=Collector.on_stderr,
                      on_exit=Collector.on_exit)
    procs.write(pid, b"pass-through-bytes")
    procs.close_stdin(pid)
    rt.run(max_steps=4000)
    st = rt.state_of(owner)
    assert st["done"] == 1 and st["code"] == 0
    assert st["n_out"] == len(b"pass-through-bytes")
    assert st["n_err"] == len(b"oops\n")
    rt.stop()


def test_process_kill_reports_signal():
    rt = _mk()
    procs = rt.attach_processes()
    owner = rt.spawn(Collector)
    pid = procs.spawn("/bin/sh", ["sh", "-c", "sleep 30"],
                      owner, on_stdout=Collector.on_stdout,
                      on_stderr=Collector.on_stderr,
                      on_exit=Collector.on_exit)
    procs.kill(pid, 9)
    rt.run(max_steps=4000)
    st = rt.state_of(owner)
    assert st["code"] == 256 + 9
    rt.stop()


# ---- files (≙ packages/files) ----

def test_filepath_capability_discipline(tmp_path):
    rt = _mk()
    root = rt.files_auth()
    base = FilePath(root, str(tmp_path))
    sub = base.join("inner/deeper")
    assert sub.mkdir()
    assert sub.is_dir()
    # join cannot escape its parent capability
    with pytest.raises(PermissionError):
        base.join("../escape")
    with pytest.raises(PermissionError):
        FilePath("not-an-auth", "/etc")     # type: ignore
    rt.stop()


def test_file_write_read_seek(tmp_path):
    rt = _mk()
    fp = FilePath(rt.files_auth(), str(tmp_path)).join("log.txt")
    with File(fp, "w+b") as f:
        f.print("line one").print("line two").flush()
        assert f.size() == len(b"line one\nline two\n")
        f.seek_start(5)
        assert f.position() == 5
    with File(fp, "rb") as f:
        assert f.lines()[:2] == [b"line one", b"line two"]
    assert fp.is_file() and fp.exists()
    assert fp.info().st_size == 18
    rt.stop()


def test_directory_walk_and_remove(tmp_path):
    rt = _mk()
    base = FilePath(rt.files_auth(), str(tmp_path))
    d = Directory(base)
    sub = d.mkdir("pkg")
    sub.open_file("a.txt").write(b"a").dispose()
    sub.open_file("b.txt").write(b"b").dispose()
    assert sub.entries() == ["a.txt", "b.txt"]
    walked = {fp.path: (dirs, files) for fp, dirs, files in d.walk()}
    assert base.path in walked and walked[base.path][0] == ["pkg"]
    assert walked[base.join("pkg").path][1] == ["a.txt", "b.txt"]
    assert base.join("pkg").remove()
    assert not base.join("pkg").exists()
    rt.stop()
