"""End-to-end ring semantics (≙ examples/ring + the causal-order guarantee
exercised implicitly by every Pony program)."""

import numpy as np

from ponyc_tpu import RuntimeOptions
from ponyc_tpu.models import ring


def test_single_token_full_circle():
    n, hops = 64, 256
    rt = ring.run(n_nodes=n, hops=hops)
    st = rt.cohort_state(ring.RingNode)
    # hops messages were dispatched in total, spread over the ring.
    assert st["passes"].sum() == hops
    # Token moved uniformly: first (hops % n) nodes saw one extra pass.
    base = hops // n
    extra = hops % n
    expect = np.full(n, base)
    expect[:extra] += 1
    assert (st["passes"] == expect).all()
    assert rt.exit_code == 0


def test_multiple_tokens():
    n, hops, toks = 32, 96, 4
    rt = ring.run(n_nodes=n, hops=hops, n_tokens=toks)
    st = rt.cohort_state(ring.RingNode)
    assert st["passes"].sum() == hops * toks


def test_quiescent_termination_without_exit():
    # A message chain that just stops → runtime must terminate by
    # quiescence detection (≙ CNF/ACK), not ctx.exit.
    from ponyc_tpu import Runtime, actor, behaviour, I32, Ref

    @actor
    class Hopper:
        next_ref: Ref
        seen: I32

        @behaviour
        def hop(self, st, n: I32):
            self.send(st["next_ref"], Hopper.hop, n - 1, when=n > 0)
            return {**st, "seen": st["seen"] + 1}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                msg_words=1))
    rt.declare(Hopper, 8)
    rt.start()
    ids = rt.spawn_many(Hopper, 8)
    rt.set_fields(Hopper, ids, next_ref=np.roll(ids, -1))
    rt.send(int(ids[0]), Hopper.hop, 20)
    code = rt.run(max_steps=500)
    assert code == 0
    st = rt.cohort_state(Hopper)
    assert st["seen"].sum() == 21  # n=20 down to n=0 inclusive
    assert rt.steps_run < 500      # actually quiesced, not timed out
