"""TCP/UDP over real loopback sockets (≙ the reference's de-facto net
integration tests: packages/net/_test.pony runs listener+connection pairs
over 127.0.0.1 under ponytest)."""

import numpy as np

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour


@actor
class EchoServer:
    HOST = True
    n_conns: I32
    n_bytes: I32

    @behaviour
    def on_accept(self, st, conn: I32):
        return {**st, "n_conns": st["n_conns"] + 1}

    @behaviour
    def on_data(self, st, conn: I32, data: I32, n: I32):
        payload = self.rt.heap.unbox(data)
        self.rt.net.send(conn, payload.upper())
        return {**st, "n_bytes": st["n_bytes"] + n}

    @behaviour
    def on_closed(self, st, conn: I32):
        return st


@actor
class EchoClient:
    HOST = True
    conn: I32
    ok: I32

    @behaviour
    def on_connect(self, st, conn: I32, err: I32):
        assert err == 0, err
        self.rt.net.send(conn, b"hello actors")
        return {**st, "conn": conn}

    @behaviour
    def on_data(self, st, conn: I32, data: I32, n: I32):
        reply = self.rt.heap.unbox(data)
        ok = 1 if reply == b"HELLO ACTORS" else -1
        self.rt.net.close(conn)
        self.exit(0 if ok == 1 else 3)
        return {**st, "ok": ok}

    @behaviour
    def on_closed(self, st, conn: I32):
        return st


def _mk(*types):
    rt = Runtime(RuntimeOptions(mailbox_cap=16, batch=4, max_sends=2,
                                msg_words=4, inject_slots=32))
    for t in types:
        rt.declare(t, 2)
    return rt.start()


def test_tcp_echo_roundtrip():
    rt = _mk(EchoServer, EchoClient)
    net = rt.attach_net()
    srv = rt.spawn(EchoServer)
    cli = rt.spawn(EchoClient)
    lid = net.listen_tcp("127.0.0.1", 0, srv,
                         on_accept=EchoServer.on_accept,
                         on_data=EchoServer.on_data,
                         on_closed=EchoServer.on_closed)
    port = net.listen_port(lid)
    assert port > 0
    net.connect_tcp("127.0.0.1", port, cli,
                    on_connect=EchoClient.on_connect,
                    on_data=EchoClient.on_data,
                    on_closed=EchoClient.on_closed)
    code = rt.run(max_steps=4000)
    assert code == 0
    assert rt.state_of(cli)["ok"] == 1
    assert rt.state_of(srv)["n_conns"] == 1
    assert rt.state_of(srv)["n_bytes"] == len(b"hello actors")
    net.close_all()
    rt.stop()
    # All payload handles were consumed (move semantics, no leaks).
    assert rt.heap.live == 0


@actor
class Gram:
    HOST = True
    got: I32
    port_seen: I32

    @behaviour
    def on_datagram(self, st, sock: I32, data: I32, n: I32):
        payload, host, port = self.rt.heap.unbox(data)
        assert host in ("127.0.0.1", "::1", "::ffff:127.0.0.1")
        if payload == b"ping":
            # reply to the sender's ephemeral port
            self.rt.net.sendto(sock, b"pong", host, port)
            return {**st, "got": st["got"] + 1, "port_seen": port}
        self.exit(0)
        return {**st, "got": st["got"] + 1}


def test_udp_ping_pong():
    rt = _mk(Gram)
    net = rt.attach_net()
    a = rt.spawn(Gram)
    b = rt.spawn(Gram)
    ua = net.udp_bind("127.0.0.1", 0, a, on_datagram=Gram.on_datagram)
    ub = net.udp_bind("127.0.0.1", 0, b, on_datagram=Gram.on_datagram)
    pa = net.listen_port(ua)
    net.sendto(ub, b"ping", "127.0.0.1", pa)   # b → a, a replies pong → b
    code = rt.run(max_steps=4000)
    assert code == 0
    assert rt.state_of(a)["got"] == 1
    assert rt.state_of(b)["got"] == 1
    net.close_all()
    rt.stop()


def test_large_transfer_with_write_buffering():
    # Push well past the kernel buffer so the host-side outbuf + write
    # re-arming path actually engages (≙ pending writes in packages/net).
    blob = bytes(range(256)) * 4096   # 1 MiB

    @actor
    class Sink:
        HOST = True
        total: I32

        @behaviour
        def on_accept(self, st, conn: I32):
            return st

        @behaviour
        def on_data(self, st, conn: I32, data: I32, n: I32):
            self.rt.heap.drop(data)
            t = st["total"] + n
            self.exit(0, when=t >= len(blob))
            return {**st, "total": t}

        @behaviour
        def on_closed(self, st, conn: I32):
            return st

    @actor
    class Blaster:
        HOST = True

        @behaviour
        def on_connect(self, st, conn: I32, err: I32):
            assert err == 0
            self.rt.net.send(conn, blob)
            return st

        @behaviour
        def on_data(self, st, conn: I32, data: I32, n: I32):
            return st

        @behaviour
        def on_closed(self, st, conn: I32):
            return st

    rt = _mk(Sink, Blaster)
    net = rt.attach_net()
    sink = rt.spawn(Sink)
    blaster = rt.spawn(Blaster)
    lid = net.listen_tcp("127.0.0.1", 0, sink,
                         on_accept=Sink.on_accept, on_data=Sink.on_data,
                         on_closed=Sink.on_closed)
    net.connect_tcp("127.0.0.1", net.listen_port(lid), blaster,
                    on_connect=Blaster.on_connect,
                    on_data=Blaster.on_data, on_closed=Blaster.on_closed)
    code = rt.run(max_steps=20000)
    assert code == 0
    assert rt.state_of(sink)["total"] == len(blob)
    net.close_all()
    rt.stop()


def test_tcp_connection_churn_conserves_bytes():
    """Many concurrent loopback connections each echoing several chunks:
    byte-exact conservation, all accepts seen, no payload-handle leaks
    (≙ packages/net tests running listener+connection fleets under
    ponytest)."""
    import time

    CHUNKS, N = 3, 12
    MSG = b"x" * 700

    @actor
    class ChSrv:
        HOST = True
        n_conns: I32
        n_bytes: I32

        @behaviour
        def on_accept(self, st, conn: I32):
            return {**st, "n_conns": st["n_conns"] + 1}

        @behaviour
        def on_data(self, st, conn: I32, data: I32, n: I32):
            payload = self.rt.heap.unbox(data)
            self.rt.net.send(conn, payload)
            return {**st, "n_bytes": st["n_bytes"] + n}

        @behaviour
        def on_closed(self, st, conn: I32):
            return st

    @actor
    class ChCli:
        HOST = True
        conn: I32
        got: I32
        done: I32

        @behaviour
        def on_connect(self, st, conn: I32, err: I32):
            assert err == 0, err
            self.rt.net.send(conn, MSG)
            return {**st, "conn": conn, "got": 0}

        @behaviour
        def on_data(self, st, conn: I32, data: I32, n: I32):
            self.rt.heap.unbox(data)
            got = st["got"] + n
            if got >= len(MSG) * CHUNKS:
                self.rt.net.close(conn)
                return {**st, "got": got, "done": 1}
            if got % len(MSG) == 0:
                self.rt.net.send(conn, MSG)
            return {**st, "got": got}

        @behaviour
        def on_closed(self, st, conn: I32):
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=32, batch=8, max_sends=2,
                                msg_words=4, inject_slots=128))
    rt.declare(ChSrv, 1).declare(ChCli, N)
    rt.start()
    net = rt.attach_net()
    srv = rt.spawn(ChSrv)
    lid = net.listen_tcp("127.0.0.1", 0, srv, on_accept=ChSrv.on_accept,
                         on_data=ChSrv.on_data, on_closed=ChSrv.on_closed)
    port = net.listen_port(lid)
    clis = [rt.spawn(ChCli) for _ in range(N)]
    for c in clis:
        net.connect_tcp("127.0.0.1", port, c, on_connect=ChCli.on_connect,
                        on_data=ChCli.on_data, on_closed=ChCli.on_closed)
    deadline = time.time() + 60
    while time.time() < deadline:
        rt.run(max_steps=200)
        if sum(rt.state_of(c)["done"] for c in clis) == N:
            break
        time.sleep(0.01)
    assert sum(rt.state_of(c)["done"] for c in clis) == N
    assert rt.state_of(srv)["n_bytes"] == N * CHUNKS * len(MSG)
    assert rt.state_of(srv)["n_conns"] == N
    net.close_all()
    rt.stop()
    assert rt.heap.live == 0, rt.heap.live
