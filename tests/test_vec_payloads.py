"""Device-side vector message payloads (VecF32[k] / VecI32[k]).

≙ the reference's rich message payloads: pony_alloc_msg carries
arbitrary object graphs (pony.h:332-360, gc/serialise.c); here small
arrays ride inside the fixed message words — k consecutive int32 lanes,
float bitcast — which is the static-shape TPU equivalent (state.py's
dense mailbox table stays one array).
"""

import numpy as np
import pytest

from ponyc_tpu import (F32, I32, Ref, Runtime, RuntimeOptions, VecF32,
                       VecI32, actor, behaviour)
from ponyc_tpu.models import nbody


def test_vecf32_roundtrip_device():
    @actor
    class Accum:
        s0: F32
        s1: F32
        s2: F32
        n: I32

        @behaviour
        def add(self, st, v: VecF32[3], scale: F32):
            return {**st,
                    "s0": st["s0"] + v[0] * scale,
                    "s1": st["s1"] + v[1] * scale,
                    "s2": st["s2"] + v[2] * scale,
                    "n": st["n"] + 1}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=2, max_sends=1,
                                msg_words=4, inject_slots=16))
    rt.declare(Accum, 1).start()
    a = rt.spawn(Accum)
    rt.send(a, Accum.add, [1.5, -2.25, 0.125], 2.0)
    rt.send(a, Accum.add, np.asarray([0.5, 0.5, 0.5]), 1.0)
    assert rt.run() == 0
    st = rt.state_of(a)
    assert st["n"] == 2
    assert st["s0"] == pytest.approx(1.5 * 2 + 0.5)
    assert st["s1"] == pytest.approx(-2.25 * 2 + 0.5)
    assert st["s2"] == pytest.approx(0.125 * 2 + 0.5)


def test_veci32_and_forwarding():
    @actor
    class Hop:
        out: Ref
        a: I32
        b: I32
        MAX_SENDS = 1

        @behaviour
        def fwd(self, st, v: VecI32[2], hops: I32):
            # Forward the same vector block onward (payload pass-through).
            self.send(st["out"], Hop.fwd, v, hops - 1, when=hops > 0)
            return {**st, "a": v[0], "b": v[1]}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                msg_words=3, inject_slots=8))
    rt.declare(Hop, 3).start()
    ids = rt.spawn_many(Hop, 3)
    rt.set_fields(Hop, ids, out=np.roll(ids, -1))
    rt.send(int(ids[0]), Hop.fwd, [7, -9], 2)
    assert rt.run(max_steps=16) == 0
    for i in range(3):
        st = rt.state_of(int(ids[i]))
        assert (st["a"], st["b"]) == (7, -9)


def test_vec_width_overflow_raises():
    with pytest.raises(TypeError, match="payload words"):
        @actor
        class Big:
            x: I32

            @behaviour
            def b(self, st, v: VecF32[9]):
                return st

        rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                    msg_words=4, inject_slots=8))
        rt.declare(Big, 1).start()
        a = rt.spawn(Big)
        rt.send(a, Big.b, [0.0] * 9)


def test_vec_wrong_length_raises():
    @actor
    class T:
        x: I32

        @behaviour
        def b(self, st, v: VecF32[3]):
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                msg_words=4, inject_slots=8))
    rt.declare(T, 1).start()
    a = rt.spawn(T)
    with pytest.raises(TypeError, match="elements"):
        rt.send(a, T.b, [1.0, 2.0])


def test_nbody_float_vectors_device_side():
    n = 64
    rt = nbody.run_round(n)
    st = rt.cohort_state(nbody.Body)
    assert (st["seen"] == n - 1).all()     # every body saw every other
    ax, ay = nbody.reference_accels(st["x"], st["y"], st["m"])
    np.testing.assert_allclose(st["ax"], ax, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(st["ay"], ay, rtol=2e-4, atol=2e-5)


def test_constant_vec_beside_lane_varying_arg():
    # A trace-time-constant vector literal must broadcast next to a
    # lane-varying scalar (regression: pack_args trailing-axis alignment).
    import jax.numpy as jnp

    @actor
    class T:
        out: Ref
        n: I32
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: VecF32[2], n: I32):
            self.send(st["out"], T.go, jnp.asarray([1.0, 2.0]),
                      n - 1, when=n > 1)
            return {**st, "n": n}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                msg_words=3, inject_slots=8))
    rt.declare(T, 2).start()
    a, b = rt.spawn_many(T, 2)
    rt.set_fields(T, [a, b], out=np.asarray([b, a]))
    rt.send(int(a), T.go, [0.0, 0.0], 2)
    assert rt.run(max_steps=8) == 0
    assert rt.state_of(int(b))["n"] == 1      # got the forwarded hop


def test_vec_payloads_survive_spill_and_retry():
    """VecF32 messages forced through the rejection spill (cap-2 sink,
    16 flooding sources) re-deliver bit-exactly — the spill stores raw
    words, so float payload integrity is end-to-end (≙ rich message
    payloads surviving queue pressure, pony_alloc_msg + messageq)."""
    import jax.numpy as jnp
    import numpy as np

    from ponyc_tpu import (F32, I32, Ref, Runtime, RuntimeOptions,
                           VecF32, actor, behaviour)

    @actor
    class VSink:
        sx: F32
        sy: F32
        n: I32
        BATCH = 1

        @behaviour
        def take(self, st, v: VecF32[3], scale: F32):
            return {**st, "sx": st["sx"] + v[0] * scale,
                    "sy": st["sy"] + v[1] + v[2], "n": st["n"] + 1}

    @actor
    class VSrc:
        out: Ref[VSink]
        left: I32
        MAX_SENDS = 2

        @behaviour
        def go(self, st, _: I32):
            alive = st["left"] > 0
            k = st["left"].astype("float32")
            self.send(st["out"], VSink.take,
                      jnp.stack([k, k * 0.5, -k]), 2.0, when=alive)
            self.send(self.actor_id, VSrc.go, 0, when=st["left"] > 1)
            return {**st, "left": st["left"] - 1}

    n_src, items = 16, 25
    rt = Runtime(RuntimeOptions(mailbox_cap=2, batch=1, msg_words=4,
                                max_sends=2, spill_cap=512,
                                inject_slots=32))
    rt.declare(VSrc, n_src).declare(VSink, 1).start()
    sink = rt.spawn(VSink)
    srcs = rt.spawn_many(VSrc, n_src, out=sink, left=items)
    rt.bulk_send(srcs, VSrc.go, np.zeros(n_src, np.int64))
    assert rt.run(max_steps=60_000) == 0
    st = rt.state_of(sink)
    want_sx = n_src * 2.0 * sum(range(1, items + 1))
    want_sy = n_src * sum(k * 0.5 - k for k in range(1, items + 1))
    assert st["n"] == n_src * items
    assert abs(st["sx"] - want_sx) < 1e-3
    assert abs(st["sy"] - want_sy) < 1e-3
    assert rt.counter("n_rejected") > 0, "spill path must engage"
