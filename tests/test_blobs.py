"""Device blob pool: rich message payloads without host round-trips.

≙ the reference's actor-heap message payloads — pony_alloc_msg packs a
per-behaviour pony_msg_t subtype (src/libponyc/codegen/genfun.c) whose
pointer fields reference objects on the sending actor's heap
(src/libponyrt/mem/heap.c); ORCA moves ownership with the message. Here
the heap is the device-resident pool (RuntimeOptions.blob_slots ×
blob_words, runtime/state.py), the pointer is a global i32 handle with
mode iso (ops.pack.Blob), and the move discipline is the trace-time
capability checker. v1 scoped semantics under test here:

  - alloc/write/read/free via ctx.blob_* (api.BlobPoolView);
  - sending a handle as a Blob parameter MOVES it (use-after-move and
    free-then-use reject at build);
  - pool exhaustion raises BlobCapacityError host-side (sticky flag);
  - per-dispatch alloc budget = MAX_BLOBS (exceeding rejects at build);
  - on a mesh a blob MIGRATES with its routed message (fresh local
    slot + generation at the receiver, engine._route; n_blob_moved);
    host injections bypass routing — allocate near the receiver;
  - the host side allocates/reads via Runtime.blob_store/blob_fetch.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from ponyc_tpu import (Actor, Blob, BlobCapacityError, I32, Ref, Runtime,
                       RuntimeOptions, actor, behaviour)

OPTS = dict(mailbox_cap=4, batch=2, max_sends=1, msg_words=2,
            inject_slots=8, blob_slots=16, blob_words=8)


@actor
class Producer(Actor):
    out: Ref["Consumer"]
    MAX_BLOBS = 1
    MAX_SENDS = 1

    @behaviour
    def go(self, st, n: I32):
        h = self.blob_alloc(length=4)
        for i in range(4):
            self.blob_set(h, i, n * 10 + i)
        self.send(st["out"], Consumer.take, h)
        return st


@actor
class Consumer(Actor):
    total: I32
    seen: I32

    @behaviour
    def take(self, st, h: Blob):
        s = jnp.int32(0)
        for i in range(4):
            s = s + self.blob_get(h, i)
        st["total"] = st["total"] + s
        st["seen"] = st["seen"] + self.blob_length(h)
        self.blob_free(h)
        return st


def _world(**kw):
    rt = Runtime(RuntimeOptions(**{**OPTS, **kw}))
    rt.declare(Producer, 4).declare(Consumer, 4).start()
    c = rt.spawn(Consumer, total=0, seen=0)
    p = rt.spawn(Producer, out=c)
    return rt, p, c


def test_alloc_write_move_read_free_roundtrip():
    rt, p, c = _world()
    rt.send(p, Producer.go, 7)
    rt.run(max_steps=10)
    st = rt.state_of(c)
    assert st["total"] == 70 + 71 + 72 + 73
    assert st["seen"] == 4                      # blob_length(h)
    assert rt.counter("n_blob_alloc") == 1
    assert rt.counter("n_blob_free") == 1
    assert rt.blobs_in_use == 0
    assert rt.counter("n_blob_remote") == 0


def test_slots_recycle_through_free():
    rt, p, c = _world()
    # 8 sequential messages through a 16-slot pool with free() each time:
    # never exhausts, every alloc gets a slot.
    for k in range(8):
        rt.send(p, Producer.go, k)
        rt.run(max_steps=6)
    assert rt.counter("n_blob_alloc") == 8
    assert rt.counter("n_blob_free") == 8
    assert rt.blobs_in_use == 0
    assert rt.state_of(c)["total"] == sum(
        sum(k * 10 + i for i in range(4)) for k in range(8))


def test_pool_exhaustion_raises():
    @actor
    class Leaker(Actor):
        n: I32
        MAX_BLOBS = 1

        @behaviour
        def leak(self, st):
            self.blob_alloc()                   # never freed
            return st

    rt = Runtime(RuntimeOptions(**{**OPTS, "blob_slots": 2}))
    rt.declare(Leaker, 4).start()
    a = rt.spawn(Leaker, n=0)
    for _ in range(3):
        rt.send(a, Leaker.leak)
    with pytest.raises(BlobCapacityError):
        rt.run(max_steps=10)


def test_pool_exhaustion_message_names_blob_slots():
    # The POOL-exhaustion error must point at blob_slots, never at
    # BLOB_DISPATCHES (they were conflated under one sticky flag once).
    @actor
    class Leaker(Actor):
        n: I32
        MAX_BLOBS = 1

        @behaviour
        def leak(self, st):
            self.blob_alloc()
            return st

    rt = Runtime(RuntimeOptions(**{**OPTS, "blob_slots": 2}))
    rt.declare(Leaker, 4).start()
    a = rt.spawn(Leaker, n=0)
    for _ in range(3):
        rt.send(a, Leaker.leak)
    with pytest.raises(BlobCapacityError, match="blob_slots"):
        rt.run(max_steps=10)


def test_budget_exhaustion_names_blob_dispatches():
    # BLOB_DISPATCHES exhaustion with a half-empty pool must blame the
    # BUDGET knob: 2 allocating dispatches in one tick against
    # BLOB_DISPATCHES=1, 16 free slots.
    @actor
    class Hungry(Actor):
        n: I32
        MAX_BLOBS = 1
        BLOB_DISPATCHES = 1

        @behaviour
        def grab(self, st):
            self.blob_alloc(length=1)
            return st

    rt = Runtime(RuntimeOptions(**OPTS))       # batch=2: both msgs in
    rt.declare(Hungry, 4).start()              # one tick's drain
    a = rt.spawn(Hungry, n=0)
    rt.send(a, Hungry.grab)
    rt.send(a, Hungry.grab)
    with pytest.raises(BlobCapacityError, match="BLOB_DISPATCHES"):
        rt.run(max_steps=10)


def test_host_iso_blob_double_send_raises():
    # ADVICE round 5: the host moving an iso blob it does not own must
    # be LOUD (matching HostHeap.send_iso and the device trace), not a
    # silent null-read downstream.
    from ponyc_tpu.hostmem import CapabilityError
    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Consumer, 2).start()
    c = rt.spawn(Consumer, total=0, seen=0)
    h = rt.blob_store([5])
    rt.send(c, Consumer.take, h)               # legal move
    with pytest.raises(CapabilityError, match="aliased move"):
        rt.send(c, Consumer.take, h)           # double-send of an iso
    with pytest.raises(CapabilityError, match="aliased move"):
        rt.send(c, Consumer.take, 12345)       # never-owned forged int
    rt.run(max_steps=6)
    assert rt.state_of(c)["seen"] == 1


def test_max_blobs_budget_rejects_at_build():
    @actor
    class Greedy(Actor):
        n: I32
        MAX_BLOBS = 1

        @behaviour
        def two(self, st):
            self.blob_alloc()
            self.blob_alloc()
            return st

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Greedy, 4).start()
    with pytest.raises(RuntimeError, match="MAX_BLOBS"):
        rt.run(max_steps=1)            # behaviours trace at first run


def test_send_is_a_move_use_after_rejects():
    @actor
    class BadSender(Actor):
        out: Ref["Consumer"]
        MAX_BLOBS = 1
        MAX_SENDS = 1

        @behaviour
        def go(self, st):
            h = self.blob_alloc()
            self.send(st["out"], Consumer.take, h)
            self.blob_set(h, 0, 1)              # use-after-move
            return st

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(BadSender, 4).declare(Consumer, 4).start()
    with pytest.raises(TypeError, match="use-after-move"):
        rt.run(max_steps=1)


def test_free_then_use_rejects():
    @actor
    class FreeUse(Actor):
        n: I32
        MAX_BLOBS = 1

        @behaviour
        def go(self, st):
            h = self.blob_alloc()
            self.blob_free(h)
            st["n"] = st["n"] + self.blob_get(h, 0)
            return st

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(FreeUse, 4).start()
    with pytest.raises(TypeError, match="use-after-move"):
        rt.run(max_steps=1)


def test_blob_requires_pool_enabled():
    rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=2, max_sends=1,
                                msg_words=2))
    rt.declare(Producer, 4).declare(Consumer, 4)
    with pytest.raises(TypeError, match="blob"):
        rt.start()


def test_host_actor_cannot_hold_blobs():
    @actor
    class HostEater(Actor):
        HOST = True
        n: I32

        @behaviour
        def eat(self, st, h: Blob):
            return st

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(HostEater, 2)
    with pytest.raises(TypeError, match="host"):
        rt.start()


def test_host_store_device_reads_and_frees():
    @actor
    class Summer(Actor):
        total: I32

        @behaviour
        def add(self, st, h: Blob):
            s = jnp.int32(0)
            for i in range(3):
                s = s + self.blob_get(h, i)
            st["total"] = st["total"] + s
            self.blob_free(h)
            return st

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Summer, 4).start()
    a = rt.spawn(Summer, total=0)
    h = rt.blob_store([5, 6, 7])
    assert rt.blobs_in_use == 1
    np.testing.assert_array_equal(rt.blob_fetch(h), [5, 6, 7])
    rt.send(a, Summer.add, h)                   # host moves it to the actor
    rt.run(max_steps=10)
    assert rt.state_of(a)["total"] == 18
    assert rt.blobs_in_use == 0
    with pytest.raises(KeyError):
        rt.blob_fetch(h)                        # freed device-side


def test_blob_send_coexists_with_host_heap():
    # Blob shares the iso MODE with HostHeap handles but lives in the
    # device pool: a host send of a Blob arg must NOT run the HostHeap
    # send_iso discipline (a pool slot id is not a heap handle).
    @actor
    class Summer(Actor):
        total: I32

        @behaviour
        def add(self, st, h: Blob):
            st["total"] = st["total"] + self.blob_get(h, 0)
            self.blob_free(h)
            return st

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Summer, 4).start()
    a = rt.spawn(Summer, total=0)
    rt.heap.box([1, 2, 3])           # materialise the HostHeap
    h = rt.blob_store([41])          # pool slot 0 — NOT a heap handle
    rt.send(a, Summer.add, h)        # must not touch heap.send_iso
    rt.run(max_steps=8)
    assert rt.state_of(a)["total"] == 41
    assert rt.blobs_in_use == 0


def test_generic_actor_keeps_max_blobs():
    from ponyc_tpu import TypeParam
    T = TypeParam("T")

    @actor
    class Box_(Actor):
        n: I32
        MAX_BLOBS = 1

        @behaviour
        def put(self, st, v: T):
            h = self.blob_alloc(length=1)
            self.blob_set(h, 0, 1)
            self.blob_free(h)
            return st

    BoxI = Box_[I32]
    assert getattr(BoxI, "MAX_BLOBS", 0) == 1   # survives reification
    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(BoxI, 2).start()
    a = rt.spawn(BoxI, n=0)
    rt.send(a, BoxI.put, 5)
    rt.run(max_steps=6)
    assert rt.counter("n_blob_alloc") == 1
    assert rt.counter("n_blob_free") == 1


def test_host_free_rejects_double_free_and_bad_length():
    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Consumer, 2).start()
    h = rt.blob_store([1, 2])
    rt.blob_free_host(h)
    with pytest.raises(KeyError):
        rt.blob_free_host(h)                    # double free
    with pytest.raises(ValueError):
        rt.blob_store([1], length=100)          # length > blob_words


def test_stale_handle_reads_zero_not_leftovers():
    # A freed slot keeps its words until the next alloc zeroes them; a
    # stale/forged in-range handle must read 0, not the previous blob's
    # payload (cross-actor data leak).
    @actor
    class Reader(Actor):
        got: I32

        @behaviour
        def probe(self, st, k: Blob):
            # k is a STALE handle: freed host-side after the send, so by
            # dispatch time the slot is unallocated (words still there).
            return {**st, "got": st["got"] + self.blob_get(k, 0)}

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Reader, 2).start()
    a = rt.spawn(Reader, got=0)
    h = rt.blob_store([777])
    rt.send(a, Reader.probe, h)         # legal move (host owns h here)
    rt.blob_free_host(h)                # freed before dispatch: by the
    #   time probe runs the slot is unallocated (words still there)
    rt.run(max_steps=6)
    assert rt.state_of(a)["got"] == 0   # used-gate: no leftover leak


def test_recycled_slot_stale_handle_reads_zero():
    # ABA guard: free a blob, let the SLOT be re-allocated to a new
    # owner, then read through the old handle — generation mismatch
    # must yield 0, never the new owner's words. (The used-gate alone
    # cannot catch this: the slot IS allocated, just not to you.)
    @actor
    class Reader(Actor):
        got: I32

        @behaviour
        def probe(self, st, h: Blob):
            return {**st, "got": st["got"] + self.blob_get(h, 0)}

    rt = Runtime(RuntimeOptions(**{**OPTS, "blob_slots": 1}))
    rt.declare(Reader, 2).start()
    a = rt.spawn(Reader, got=0)
    h_old = rt.blob_store([111])
    rt.send(a, Reader.probe, h_old)     # legal move (host owns h_old)
    rt.blob_free_host(h_old)            # ...then freed before dispatch
    h_new = rt.blob_store([222])        # 1-slot pool: SAME slot, new gen
    from ponyc_tpu.ops import pack
    assert pack.blob_slot(h_old) == pack.blob_slot(h_new)
    assert h_old != h_new               # generations differ: the
    #   in-flight message now carries a stale handle
    rt.run(max_steps=6)
    assert rt.state_of(a)["got"] == 0   # gen mismatch → null read
    with pytest.raises(KeyError, match="STALE"):
        rt.blob_fetch(h_old)            # host side rejects it too
    np.testing.assert_array_equal(rt.blob_fetch(h_new), [222])


def test_blob_store_near_targets_receiver_shard():
    opts = RuntimeOptions(**{**OPTS, "mesh_shards": 2})
    rt = Runtime(opts)
    rt.declare(Consumer, 4).start()
    c_sh0 = rt.spawn(Consumer, total=0, seen=0)   # slot 0 → shard 0
    c_sh1 = rt.spawn(Consumer, total=0, seen=0)   # slot 1 → shard 1
    from ponyc_tpu.ops import pack
    h0 = rt.blob_store([7, 7, 7, 7], near=int(c_sh0))
    h1 = rt.blob_store([9, 9, 9, 9], near=int(c_sh1))
    assert pack.blob_slot(h0) // opts.blob_slots == 0
    assert pack.blob_slot(h1) // opts.blob_slots == 1   # receiver's shard
    rt.send(int(c_sh0), Consumer.take, h0)
    rt.send(int(c_sh1), Consumer.take, h1)
    rt.run(max_steps=10)
    assert rt.state_of(c_sh0)["total"] == 28
    assert rt.state_of(c_sh1)["total"] == 36
    assert rt.counter("n_blob_remote") == 0       # both landed local


def test_snapshot_preserves_host_blob_roots():
    from ponyc_tpu import serialise
    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Consumer, 2).start()
    h = rt.blob_store([5])
    path = "/tmp/test_blob_snapshot.npz"
    serialise.save(rt, path)
    rt2 = Runtime(RuntimeOptions(**OPTS))
    rt2.declare(Consumer, 2).start()
    serialise.restore(rt2, path)
    rt2.gc()                            # must NOT sweep the host's blob
    assert rt2.blobs_in_use == 1
    np.testing.assert_array_equal(rt2.blob_fetch(h), [5])


def test_records_model_oracle():
    # The records pipeline (models/records.py): variable-length blob
    # payloads through source → worker → fan-in sink, word-for-word
    # against the NumPy oracle, every blob freed by its consumer.
    from ponyc_tpu.models import records
    rt, st = records.run_records(n_sources=8, n_records=6)
    assert st["n"] == 48
    assert rt.counter("n_blob_alloc") == 48
    assert rt.counter("n_blob_free") == 48


def test_mesh_blob_migrates_with_routed_message():
    # 2-shard world: Producer on shard 0 allocates and sends to a
    # Consumer row on shard 1 — the blob MIGRATES with the routed
    # message (payload rides the all_to_all; fresh local slot +
    # generation at the receiver), so the consumer reads it like any
    # local blob and frees it normally.
    opts = RuntimeOptions(**{**OPTS, "mesh_shards": 2})
    rt = Runtime(opts)
    rt.declare(Producer, 4).declare(Consumer, 4).start()
    # slot_to_gid: even slots shard 0, odd slots shard 1.
    c1 = rt.spawn(Consumer, total=0, seen=0)    # slot 0 → shard 0
    c2 = rt.spawn(Consumer, total=0, seen=0)    # slot 1 → shard 1
    p1 = rt.spawn(Producer, out=c2)             # slot 0 → shard 0: routes!
    rt.send(p1, Producer.go, 3)
    rt.run(max_steps=10)
    assert rt.state_of(c2)["total"] == 30 + 31 + 32 + 33
    assert rt.state_of(c2)["seen"] == 4         # full logical length
    assert rt.counter("n_blob_moved") == 1      # one cross-shard hop
    assert rt.counter("n_blob_remote") == 0     # nothing arrived dead
    assert rt.blobs_in_use == 0                 # freed at the receiver
    # Same-shard delivery migrates nothing (off-shard blocks only).
    p2 = rt.spawn(Producer, out=c2)             # slot 1 → shard 1: local
    rt.send(p2, Producer.go, 5)
    rt.run(max_steps=10)
    assert rt.state_of(c2)["total"] == 126 + 50 + 51 + 52 + 53
    assert rt.counter("n_blob_moved") == 1      # unchanged
    assert rt.blobs_in_use == 0
def test_gc_sweeps_dead_actor_field_blobs():
    # An actor holding a blob in a Blob FIELD dies unreachable → the
    # next collection frees both the actor and its blob (≙ the actor's
    # heap dying with it). A live holder keeps its blob alive.
    @actor
    class Holder(Actor):
        stash: Blob
        MAX_BLOBS = 1

        @behaviour
        def keep(self, st):
            h = self.blob_alloc(length=2)
            self.blob_set(h, 0, 9)
            return {**st, "stash": h}

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Holder, 4).start()
    a = rt.spawn(Holder)
    b = rt.spawn(Holder)
    rt.send(a, Holder.keep)
    rt.send(b, Holder.keep)
    rt.run(max_steps=6)
    assert rt.blobs_in_use == 2
    assert rt.gc() == 0                 # both pinned (host refs) → live
    assert rt.blobs_in_use == 2         # field-held blobs marked live
    rt.release(b)                       # unpin: b becomes garbage
    assert rt.gc() == 1
    assert rt.blobs_in_use == 1         # b's blob swept with it
    assert rt.counter("n_blob_free") == 1


def test_blob_dispatches_bounds_reservation_footprint():
    # Without the bound each runnable actor reserves batch×MAX_BLOBS
    # windows, so 4 allocators × batch=2 would outsize a 4-slot pool
    # even though only 4 slots get used; BLOB_DISPATCHES=1 shrinks the
    # static window to 1 per actor and the same program fits exactly.
    @actor
    class Lean(Actor):
        stash: Blob
        MAX_BLOBS = 1
        BLOB_DISPATCHES = 1

        @behaviour
        def fill(self, st, v: I32):
            h = self.blob_alloc(length=1)
            self.blob_set(h, 0, v)
            return {**st, "stash": h}

    rt = Runtime(RuntimeOptions(**{**OPTS, "blob_slots": 4}))
    rt.declare(Lean, 4).start()
    ids = [rt.spawn(Lean) for _ in range(4)]
    for i, a in enumerate(ids):
        rt.send(a, Lean.fill, i)
    rt.run(max_steps=8)                 # must NOT raise BlobCapacityError
    assert rt.blobs_in_use == 4
    assert sorted(int(rt.blob_fetch(int(rt.state_of(a)["stash"]))[0])
                  for a in ids) == [0, 1, 2, 3]


def test_gc_keeps_host_held_and_inflight_blobs():
    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Consumer, 2).start()
    c = rt.spawn(Consumer, total=0, seen=0)
    h_held = rt.blob_store([1])         # host-owned root
    h_sent = rt.blob_store([2, 3, 4, 5])
    rt.send(c, Consumer.take, h_sent)   # in-flight (inject queue)
    rt.gc()                             # must sweep NEITHER
    assert rt.blobs_in_use == 2
    rt.run(max_steps=8)                 # take() frees h_sent
    assert rt.blobs_in_use == 1
    rt.blob_free_host(h_held)
    assert rt.blobs_in_use == 0


def test_freeze_shares_one_payload_with_many_readers():
    # ≙ Pony's `String val` broadcast: freeze once, send the SAME
    # handle to two readers in one dispatch (an iso handle would reject
    # the second send as an aliased move); nobody frees — the GC mark
    # pass reclaims the slot once the readers have consumed it.
    from ponyc_tpu import BlobVal

    @actor
    class Caster(Actor):
        a: Ref["ValReader"]
        b: Ref["ValReader"]
        MAX_BLOBS = 1
        MAX_SENDS = 2

        @behaviour
        def cast(self, st, x: I32):
            h = self.blob_alloc(length=2)
            self.blob_set(h, 0, x)
            self.blob_set(h, 1, x * 2)
            v = self.blob_freeze(h)
            self.send(st["a"], ValReader.read, v)
            self.send(st["b"], ValReader.read, v)   # alias: legal for val
            return st

    @actor
    class ValReader(Actor):
        got: I32

        @behaviour
        def read(self, st, v: BlobVal):
            return {**st, "got": st["got"] + self.blob_get(v, 0)
                    + self.blob_get(v, 1)}

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Caster, 2).declare(ValReader, 4).start()
    r1 = rt.spawn(ValReader, got=0)
    r2 = rt.spawn(ValReader, got=0)
    c = rt.spawn(Caster, a=r1, b=r2)
    rt.send(c, Caster.cast, 7)
    rt.run(max_steps=10)
    assert rt.state_of(r1)["got"] == 7 + 14
    assert rt.state_of(r2)["got"] == 7 + 14
    assert rt.blobs_in_use == 1          # nobody freed (val has no owner)
    rt.gc()                              # ...but nothing references it now
    assert rt.blobs_in_use == 0


def test_frozen_blob_rejects_write_and_free():
    @actor
    class BadFreezer(Actor):
        n: I32
        MAX_BLOBS = 1

        @behaviour
        def w(self, st):
            h = self.blob_freeze(self.blob_alloc(length=1))
            self.blob_set(h, 0, 1)               # write-after-freeze
            return st

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(BadFreezer, 2).start()
    with pytest.raises(TypeError, match="frozen"):
        rt.run(max_steps=1)

    @actor
    class BadFreer(Actor):
        n: I32
        MAX_BLOBS = 1

        @behaviour
        def f(self, st):
            h = self.blob_freeze(self.blob_alloc(length=1))
            self.blob_free(h)                    # free-after-freeze
            return st

    rt2 = Runtime(RuntimeOptions(**OPTS))
    rt2.declare(BadFreer, 2).start()
    with pytest.raises(TypeError, match="val"):
        rt2.run(max_steps=1)


def test_frozen_handle_rejects_iso_parameter():
    @actor
    class Smuggler(Actor):
        out: Ref["Consumer"]
        MAX_BLOBS = 1
        MAX_SENDS = 1

        @behaviour
        def go(self, st):
            v = self.blob_freeze(self.blob_alloc(length=1))
            self.send(st["out"], Consumer.take, v)   # Consumer.take: Blob
            return st

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Smuggler, 2).declare(Consumer, 2).start()
    with pytest.raises(TypeError, match="val"):
        rt.run(max_steps=1)


def test_mesh_val_blob_copies_not_moves():
    # A frozen blob broadcast to readers on BOTH shards: the off-shard
    # reader gets a COPY (migration does not free the source), the
    # local reader reads the original; gc reclaims both replicas.
    from ponyc_tpu import BlobVal

    @actor
    class Caster(Actor):
        a: Ref["VReader"]
        b: Ref["VReader"]
        MAX_BLOBS = 1
        MAX_SENDS = 2

        @behaviour
        def cast(self, st, x: I32):
            h = self.blob_alloc(length=1)
            self.blob_set(h, 0, x)
            v = self.blob_freeze(h)
            self.send(st["a"], VReader.read, v)
            self.send(st["b"], VReader.read, v)
            return st

    @actor
    class VReader(Actor):
        got: I32

        @behaviour
        def read(self, st, v: BlobVal):
            return {**st, "got": st["got"] + self.blob_get(v, 0)}

    opts = RuntimeOptions(**{**OPTS, "mesh_shards": 2})
    rt = Runtime(opts)
    rt.declare(Caster, 2).declare(VReader, 4).start()
    r_local = rt.spawn(VReader, got=0)   # slot 0 → shard 0
    r_remote = rt.spawn(VReader, got=0)  # slot 1 → shard 1
    c = rt.spawn(Caster, a=r_local, b=r_remote)   # slot 0 → shard 0
    rt.send(c, Caster.cast, 41)
    rt.run(max_steps=10)
    assert rt.state_of(r_local)["got"] == 41      # original
    assert rt.state_of(r_remote)["got"] == 41     # replica
    assert rt.counter("n_blob_moved") == 1        # the copy that crossed
    assert rt.blobs_in_use == 2                   # original + replica
    rt.gc()
    assert rt.blobs_in_use == 0                   # both reclaimed


def test_string_payload_roundtrip():
    # The `String val` payload path: host stores UTF-8 text as a blob,
    # a device actor forwards the handle, the host reads it back.
    @actor
    class Fwd(Actor):
        sink: Ref["Keeper"]
        MAX_SENDS = 1

        @behaviour
        def fwd(self, st, h: Blob):
            self.send(st["sink"], Keeper.keep, h)
            return st

    @actor
    class Keeper(Actor):
        held: Blob

        @behaviour
        def keep(self, st, h: Blob):
            return {**st, "held": h}

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Fwd, 2).declare(Keeper, 2).start()
    k = rt.spawn(Keeper, held=-1)
    f = rt.spawn(Fwd, sink=k)
    h = rt.blob_store_str("héllo, pony→tpu")
    rt.send(f, Fwd.fwd, h)
    rt.run(max_steps=8)
    h2 = int(rt.state_of(k)["held"])
    assert h2 == h                        # same-chip: handle unchanged
    assert rt.blob_fetch_str(h2) == "héllo, pony→tpu"


def test_verify_marks_blob_allocs():
    from ponyc_tpu.verify import behaviour_effects

    @actor
    class A(Actor):
        n: I32
        MAX_BLOBS = 2

        @behaviour
        def go(self, st):
            self.blob_alloc()
            self.blob_alloc(length=1)
            return st

    eff = behaviour_effects(A.go)
    assert eff.blob_allocs == 2
    assert "allocs blobs×2" in eff.marks()


def test_snapshot_resumes_midflight_blob_pipeline():
    # Checkpoint while blob messages are QUEUED (allocated, unread),
    # restore into a fresh runtime, run to completion: totals exact,
    # pool leak-free — the blob arrays ride the generic state pytree.
    from ponyc_tpu import serialise
    from ponyc_tpu.models import records

    opts = RuntimeOptions(mailbox_cap=8, batch=2, max_sends=2,
                          msg_words=2, inject_slots=8,
                          blob_slots=128, blob_words=records.W)
    rt, sink, sources = records.build(8, 6, opts)
    for s in sources:
        rt.send(int(s), records.RecSource.emit, 0)
    rt.run(max_steps=3)                     # mid-flight: blobs queued
    assert rt.blobs_in_use > 0
    path = "/tmp/test_blob_midflight.npz"
    serialise.save(rt, path)

    rt2, sink2, _ = records.build(8, 6, opts)
    serialise.restore(rt2, path)
    rt2.run()
    want_n, want_total = records.oracle(8, 6)
    st = rt2.state_of(int(sink2))
    assert st["n"] == want_n
    assert np.int32(st["total"]) == np.int32(want_total)
    assert rt2.blobs_in_use == 0
