"""Serving front door tests (ISSUE 9, PROFILE.md §13): framing
round-trips incl. split reads and malformed frames, admission shed
under synthetic qw_p99 pressure, graceful-drain-loses-nothing, slow
consumers not stalling neighbours, the net-pending-bytes health flip,
and (slow, subprocess) SIGTERM drain + supervisor-restart reconnect."""

import json
import os
import signal
import socket
import struct
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from ponyc_tpu import loadgen, serve
from ponyc_tpu.errors import ERROR_CODES
from ponyc_tpu.serve import (ST_BADFRAME, ST_BUSY, ST_DEADLINE, ST_OK,
                             AdmissionController, FrameError, Framer,
                             encode_reply, encode_request)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---- framing ------------------------------------------------------------

def test_frame_roundtrip_and_split_reads():
    """Frames survive arbitrary chunking: byte-by-byte feeds and many
    frames coalesced into one chunk both decode to the same words."""
    frames = [encode_request(i, 50 * i, [i * 3, -i]) for i in range(9)]
    blob = b"".join(frames)
    # One-byte drip.
    f = Framer(max_words=8)
    got = []
    for i in range(len(blob)):
        got += [w.tolist() for w in f.feed(blob[i:i + 1])]
    assert got == [[i, 50 * i, i * 3, -i] for i in range(9)]
    # All at once.
    f2 = Framer(max_words=8)
    got2 = [w.tolist() for w in f2.feed(blob)]
    assert got2 == got
    # Replies too, incl. negative words (i32).
    f3 = Framer()
    (w,) = f3.feed(encode_reply(7, ST_OK, [-5]))
    assert w.tolist() == [7, 0, -5]


@pytest.mark.parametrize("body_len", [0, 3, 5, 4 * 100])
def test_framer_rejects_malformed(body_len):
    """Zero-length, non-word and oversized bodies raise FrameError
    (the stream is desynced; the server closes the connection)."""
    f = Framer(max_words=64)
    raw = struct.pack(">I", body_len) + b"\x00" * body_len
    with pytest.raises(FrameError):
        f.feed(raw)


def test_status_codes_are_error_codes():
    """Wire statuses ARE the append-only ERROR_CODES values — one
    numbering for alerts, postmortems and replies."""
    assert ST_BADFRAME == ERROR_CODES["FrameError"] == 12
    assert ST_BUSY == ERROR_CODES["ServeBusyError"] == 13
    assert ST_DEADLINE == ERROR_CODES["ServeDeadlineError"] == 14
    assert serve.FrameError.code == 12
    assert serve.ServeBusyError.code == 13
    assert serve.ServeDeadlineError.code == 14


# ---- admission controller (pure decision logic) -------------------------

def test_admission_controller_mimd():
    ac = AdmissionController(lo=2, hi=64, initial=16)
    # qw_p99 past the window: shrink x1/2 per observation, floored.
    for expect in (8, 4, 2, 2):
        ac.observe(qw_p99=100, window=8, muted=0, spill_frac=0.0,
                   used=16)
        assert ac.limit == expect and ac.state == "shrink"
    # Quiet + fully used: grow x2 toward hi.
    for expect in (4, 8, 16, 32, 64, 64):
        ac.observe(qw_p99=0, window=8, muted=0, spill_frac=0.0,
                   used=ac.limit)
        assert ac.limit == expect
    assert ac.state == "steady"       # at hi: hold
    # Mute pressure and spill occupancy shrink too.
    ac.observe(qw_p99=0, window=8, muted=3, spill_frac=0.0, used=1)
    assert ac.limit == 32 and ac.state == "shrink"
    ac.observe(qw_p99=0, window=8, muted=0, spill_frac=0.9, used=1)
    assert ac.limit == 16
    # Quiet but under-used: hold (no evidence the edge is the limit).
    ac.observe(qw_p99=0, window=8, muted=0, spill_frac=0.0, used=3)
    assert ac.limit == 16 and ac.state == "steady"
    snap = ac.snapshot()
    assert snap["shrinks"] == 6 and snap["grows"] == 5


def test_admission_controller_validates_bounds():
    with pytest.raises(ValueError):
        AdmissionController(lo=0, hi=4)
    with pytest.raises(ValueError):
        AdmissionController(lo=8, hi=4)


# ---- end-to-end over real sockets ---------------------------------------

def _run_with_client(rt, server, client_fn, timeout_s=60.0):
    """Run rt.run() on this thread while client_fn drives sockets from
    a worker thread; begin_drain() fires when the client finishes (so
    run() exits via the drain path)."""
    out = {}

    def body():
        try:
            out["result"] = client_fn()
        except Exception as e:              # noqa: BLE001
            out["error"] = e
        finally:
            server.begin_drain()

    t = threading.Thread(target=body, daemon=True)
    t.start()
    code = rt.run()
    t.join(timeout=timeout_s)
    assert not t.is_alive(), "client thread wedged"
    if "error" in out:
        raise out["error"]
    return code, out.get("result")


def _build(n_workers=8, **server_kw):
    opts = serve.default_options(n_workers)
    rt, server = serve.build(n_workers, opts, **server_kw)
    port = server.listen("127.0.0.1", 0)
    return rt, server, port


def test_request_reply_roundtrip_and_values():
    """ACCEPTANCE: socket → frame → admission → bulk_send batch →
    device worker → egress → framed reply, values verified (2*x+1),
    every request answered, nothing shed at gentle load."""
    rt, server, port = _build(8)
    code, res = _run_with_client(
        rt, server, lambda: loadgen.run_load(
            "127.0.0.1", port, conns=2, depth=2, requests=40))
    assert code == 0
    assert res["ok"] == res["sent"] == 80
    assert res["bad_value"] == 0 and res["unanswered"] == 0
    st = server.stats()
    assert st["replied"] == 80 and st["shed_total"] == 0
    assert st["batches"] >= 1 and st["submitted"] == 80
    # Worker-side evidence: the device cohort really served them.
    served = int(rt.cohort_state(serve.ServeWorker)["served"].sum())
    assert served == 80
    rt.stop()


def test_malformed_frame_gets_badframe_and_close():
    """A non-word body draws a BADFRAME(-1) reply, counts in
    rt._error_counts under code 12, and the connection closes; a well-
    framed wrong-arity request draws BADFRAME and KEEPS the conn."""
    rt, server, port = _build(4)

    def client():
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        s.sendall(struct.pack(">I", 3) + b"\xff\xff\xff")
        f = Framer()
        words = None
        while words is None:
            data = s.recv(4096)
            if not data:
                break
            for w in f.feed(data):
                words = w
        eof = s.recv(4096) if words is not None else b""
        s.close()
        # Arity error on a fresh conn: reply carries the req id, conn
        # survives for a follow-up valid request.
        s2 = socket.create_connection(("127.0.0.1", port), timeout=10)
        s2.sendall(encode_request(5, 0, [1, 2, 3]))   # 3 words != 1
        f2 = Framer()
        got = []
        while len(got) < 1:
            got += [w.tolist() for w in f2.feed(s2.recv(4096))]
        s2.sendall(encode_request(6, 0, [10]))
        while len(got) < 2:
            got += [w.tolist() for w in f2.feed(s2.recv(4096))]
        s2.close()
        return words.tolist(), eof, got

    code, (bad, eof, got) = _run_with_client(rt, server, client)
    assert code == 0
    assert bad == [-1, ST_BADFRAME]
    assert eof == b""                      # server closed the stream
    assert got[0] == [5, ST_BADFRAME]
    assert got[1] == [6, ST_OK, 21]
    assert rt._error_counts[("FrameError", 12)] >= 2
    assert server.stats()["badframe"] == 2
    rt.stop()


def test_admission_shed_under_synthetic_qw_pressure():
    """Synthetic qw_p99 pressure (the device's vote, injected in place
    of the retired aux) collapses the admission limit to lo; offered
    concurrency past the limit sheds BUSY at the edge while admitted
    requests still complete — the rings never see the overload."""
    rt, server, port = _build(8, admit_lo=1)

    class FakeAux:
        qw_p99 = np.int32(1 << 20)        # astronomically past window
        n_muted_now = np.int32(0)

    orig_observe = server._observe

    def pressured_observe(rt_, now):
        rt_._last_aux = FakeAux()
        orig_observe(rt_, now)
    server._observe = pressured_observe

    code, res = _run_with_client(
        rt, server, lambda: loadgen.run_load(
            "127.0.0.1", port, conns=2, depth=16, requests=60,
            busy_backoff_s=0.002))
    assert code == 0
    assert server.admission.limit == 1            # collapsed to lo
    assert server.admission.shrinks >= 3
    assert res["busy"] > 0, "nothing shed under pressure"
    assert res["ok"] > 0, "admitted requests must still complete"
    assert res["bad_value"] == 0 and res["unanswered"] == 0
    st = server.stats()
    assert st["shed"]["busy"] == res["busy"]
    # The device never saw more than the collapsed limit at once.
    assert rt._error_counts.get(("SpillOverflowError", 2), 0) == 0
    rt.stop()


def test_deadline_shed_and_expiry():
    """A deadline the measured service rate cannot meet sheds at the
    edge; a queued request whose deadline lapses is answered DEADLINE
    without touching a worker."""
    rt, server, port = _build(2)
    # Pin the admission limit high but make the service look slow.
    server._rate_ema = 10.0                # 10 rps measured

    def client():
        s = socket.create_connection(("127.0.0.1", port), timeout=10)
        f = Framer()
        # A 1 ms deadline with ~0 queue: est wait 0 — admitted. Then
        # stack enough 1 ms-deadline requests that est_wait > deadline.
        for i in range(30):
            s.sendall(encode_request(100 + i, 1, [i]))
        got = []
        t0 = time.monotonic()
        while len(got) < 30 and time.monotonic() - t0 < 30:
            data = s.recv(65536)
            if not data:
                break
            got += [w.tolist() for w in f.feed(data)]
        s.close()
        return got

    code, got = _run_with_client(rt, server, client)
    assert code == 0
    statuses = {w[1] for w in got}
    assert len(got) == 30                  # every request answered
    # With a 10 rps estimate and 1 ms deadlines, the queue beyond the
    # first request sheds (BUSY at admission or DEADLINE at expiry).
    assert statuses <= {ST_OK, ST_BUSY, ST_DEADLINE}
    assert statuses & {ST_BUSY, ST_DEADLINE}
    st = server.stats()
    assert st["shed"]["deadline"] + st["shed"]["busy"] > 0
    rt.stop()


def test_graceful_drain_loses_nothing():
    """ACCEPTANCE: begin_drain() mid-load — every request sent before
    the drain answered (OK for admitted, BUSY for post-drain frames),
    zero unanswered, the world exits 0 and the server reports
    drained."""
    rt, server, port = _build(8, drain_grace_s=0.3)
    drain_at = threading.Event()

    def client():
        stats = {}

        def stream():
            # stop_on_busy: the first BUSY (= the drain announcing
            # itself) quiesces the offered load, so every frame the
            # client sent is answered before the server closes. The
            # offered concurrency (3x2) stays under the admission
            # limit (8 workers) so no BUSY fires BEFORE the drain.
            stats["r"] = loadgen.run_load(
                "127.0.0.1", port, conns=3, depth=2,
                requests=1 << 30, duration_s=30.0, stop_on_busy=True)
        t = threading.Thread(target=stream, daemon=True)
        t.start()
        # Wait until traffic is demonstrably flowing (the first window
        # pays the XLA compile), then drain mid-stream.
        deadline = time.monotonic() + 25.0
        while server.c["replied"] < 20 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert server.c["replied"] >= 20, "no traffic before drain"
        drain_at.set()
        server.begin_drain()
        t.join(timeout=30.0)
        assert not t.is_alive()
        return stats["r"]

    code, res = _run_with_client(rt, server, client)
    assert code == 0
    assert res["ok"] > 0, "no requests served before the drain"
    assert res["busy"] > 0, "post-drain frames must get BUSY replies"
    # Zero lost replies: every sent request was answered.
    assert res["unanswered"] == 0
    assert res["ok"] + res["busy"] + res["deadline"] == res["sent"]
    st = server.stats()
    assert st["drained"] and st["draining"]
    assert st["inflight"] == 0 and st["queue"] == 0
    assert st["accepted"] == st["replied"] + st["reclaimed"] \
        + st["abandoned"] + st["shed"]["deadline"]
    rt.stop()


def test_slow_consumer_does_not_stall_neighbours():
    """One connection stops reading (tiny SO_RCVBUF + huge request
    burst) while another runs a normal closed loop: the normal client
    completes everything; the slow one is choked/backpressured, never
    the world."""
    rt, server, port = _build(8, pending_limit=2048)
    t0 = time.monotonic()

    def client():
        slow_done = threading.Event()

        def slow():
            try:
                s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
                s.setsockopt(socket.SOL_SOCKET, socket.SO_RCVBUF, 2048)
                s.settimeout(10.0)
                s.connect(("127.0.0.1", port))
                for i in range(800):
                    s.sendall(encode_request(i + 1, 0, [i]))
                time.sleep(2.0)            # never reads its replies
                s.close()
            except OSError:
                pass                       # server may kill the conn
            finally:
                slow_done.set()

        ts = threading.Thread(target=slow, daemon=True)
        ts.start()
        fast = loadgen.run_load("127.0.0.1", port, conns=1, depth=2,
                                requests=60, busy_backoff_s=0.002)
        slow_done.wait(timeout=30.0)
        return fast

    code, fast = _run_with_client(rt, server, client)
    assert code == 0
    assert fast["ok"] + fast["busy"] == fast["sent"] == 60
    assert fast["ok"] > 0 and fast["unanswered"] == 0
    # The fast lane stayed responsive while the slow conn backed up.
    assert time.monotonic() - t0 < 45.0
    st = server.stats()
    assert st["net_pending_bytes"] >= 0
    assert st["shed"]["choked"] > 0 or st["conns_killed_slow"] > 0 \
        or st["shed"]["busy"] > 0
    rt.stop()


# ---- metrics / health satellites ----------------------------------------

def test_net_pending_bytes_exported_and_degrades_health(tmp_path):
    """pony_tpu_net_pending_bytes rides /metrics; /healthz flips to
    degraded when the egress backlog grows monotonically across
    PENDING_WINDOW snapshots."""
    from ponyc_tpu import metrics as metrics_mod
    from ponyc_tpu.metrics import (PENDING_WINDOW, health,
                                   parse_prometheus, prometheus_text)
    rt, server, port = _build(4)
    rt2 = rt                   # metrics server rides the same runtime
    from ponyc_tpu.metrics import MetricsServer
    mx = MetricsServer(rt2, 0)
    rt2._metrics = mx
    mx.update_now(rt2)
    snap = mx._snap
    assert "net" in snap and snap["net"]["pending_bytes"] == 0
    assert "serving" in snap and snap["serving"]["conns"] == 0
    text = prometheus_text(snap, health(rt2))
    parsed = parse_prometheus(text)
    assert parsed[("pony_tpu_net_pending_bytes", ())] == 0
    assert parsed[("pony_tpu_serve_admit_limit", ())] \
        == server.admission.limit
    assert health(rt2)["status"] == "ok"
    # Fabricate a monotone backlog trail: degraded with the reason.
    mx._pending_hist.clear()
    for v in range(1, PENDING_WINDOW + 1):
        mx._pending_hist.append(v * 1024)
    hz = health(rt2)
    assert hz["status"] == "degraded"
    assert "egress backpressure" in hz["reason"]
    # A non-monotone trail recovers.
    mx._pending_hist.append(0)
    assert health(rt2)["status"] == "ok"
    mx.close()
    rt.stop()


def test_serving_block_in_postmortem():
    """Flight-recorder dumps carry the serving block and the doctor's
    verdict mentions shed rate for a crashed serving world."""
    from ponyc_tpu.flight import diagnose_postmortem
    rt, server, port = _build(2)
    server.c["frames"] += 10
    server.c["shed_busy"] += 4
    pm = rt._flight.postmortem("crash: test")
    assert pm["serving"]["frames"] == 10
    assert pm["serving"]["shed"]["busy"] == 4
    line, detail = diagnose_postmortem(pm)
    assert "serving:" in line and "shed_rate" in line
    assert "serving: frames=10" in detail
    rt.stop()


# ---- bridge satellite ----------------------------------------------------

def test_bridge_poll_survives_raising_callback():
    """A raising fd/timer callback is counted per (class, code) and
    recorded in the flight recorder instead of killing the run loop
    (ISSUE 9 satellite: the ingress tier lives on these callbacks)."""
    from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour

    @actor
    class Quiet:
        HOST = True
        n: I32

        @behaviour
        def tick(self, st, kind: I32, arg: I32, flags: I32):
            return {**st, "n": st["n"] + 1}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                msg_words=3, inject_slots=8))
    rt.declare(Quiet, 1).start()
    rt.spawn(Quiet)
    br = rt.attach_bridge()
    fired = []

    def boom(ev):
        fired.append(ev)
        raise ValueError("callback exploded")

    sid = br.timer_callback(boom, 0.01, noisy=True)
    deadline = time.monotonic() + 20.0
    while not fired and time.monotonic() < deadline:
        rt.run(max_steps=5)
    br.unsubscribe(sid)
    assert fired, "timer callback never fired"
    assert rt._error_counts[("ValueError", 0)] >= 1
    kinds = [e["kind"] for e in rt._flight.events]
    assert "bridge_callback_error" in kinds
    # The loop survived: further runs still work.
    assert rt.run(max_steps=5) == 0
    rt.stop()


# ---- subprocess acceptance (SIGTERM drain; supervisor restart) ----------

SERVE_SCRIPT = """\
import os, sys
sys.path.insert(0, {root!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from ponyc_tpu import serve
sys.exit(serve.main(sys.argv[1:]))
"""


def _spawn_server(tmp_path, extra_args=(), env_extra=None):
    script = tmp_path / "serve_script.py"
    script.write_text(SERVE_SCRIPT.format(root=ROOT))
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = ROOT
    env["JAX_PLATFORMS"] = "cpu"
    env.update(env_extra or {})
    proc = subprocess.Popen(
        [sys.executable, str(script), "--workers", "8",
         *map(str, extra_args)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(tmp_path))
    # Wait for the "serving on host:port" line.
    line = proc.stdout.readline()
    assert line.startswith("serving on"), (line, proc.stderr.read()
                                           if proc.poll() else "")
    port = int(line.strip().rsplit(":", 1)[1].split()[0])
    return proc, port


@pytest.mark.slow
def test_sigterm_drains_every_admitted_request(tmp_path):
    """CHAOS ACCEPTANCE: SIGTERM mid-load — the subprocess server
    answers every request sent before the drain (OK or BUSY), exits 0,
    and reports drained stats on stderr. Zero lost replies."""
    proc, port = _spawn_server(tmp_path, ["--drain-grace", "0.5"])
    try:
        # Warm probe: the first window pays the XLA compile — require
        # end-to-end service before measuring the drain.
        warm = loadgen.run_load("127.0.0.1", port, conns=1, depth=1,
                                requests=5, timeout_s=60.0)
        assert warm["ok"] == 5, warm
        res = {}

        def stream():
            # 3x2 concurrent stays under the 8-worker admission limit,
            # so the first BUSY is the SIGTERM drain announcing itself.
            res["r"] = loadgen.run_load(
                "127.0.0.1", port, conns=3, depth=2,
                requests=1 << 30, duration_s=30.0, stop_on_busy=True)

        t = threading.Thread(target=stream, daemon=True)
        t.start()
        time.sleep(1.5)                    # traffic flowing
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=60)
        t.join(timeout=30.0)
        assert not t.is_alive()
        r = res["r"]
        assert proc.returncode == 0, err
        assert r["ok"] > 0
        assert r["unanswered"] == 0, r     # zero lost replies
        assert r["ok"] + r["busy"] + r["deadline"] == r["sent"]
        assert r["bad_value"] == 0
        drained = [ln for ln in err.splitlines()
                   if ln.startswith("serve: drained ")]
        assert drained, err
        st = json.loads(drained[-1][len("serve: drained "):])
        assert st["drained"] and st["inflight"] == 0
        assert st["accepted"] == st["replied"]
    finally:
        if proc.poll() is None:
            proc.kill()


WEDGE_SCRIPT = """\
import os, sys
sys.path.insert(0, {root!r})
os.environ.setdefault("JAX_PLATFORMS", "cpu")
from ponyc_tpu import serve, testing
marker = {marker!r}
if not os.path.exists(marker):
    # First life only: wedge the egress behaviour after a few replies
    # so the watchdog (code 7) fires and the supervisor restarts us.
    open(marker, "w").write("wedged")
    testing.wedge_behaviour(serve.Egress.done, at_dispatch=5,
                            sleep_s=600.0)
sys.exit(serve.main(sys.argv[1:]))
"""


@pytest.mark.slow
def test_supervisor_restart_reaccepts_connections(tmp_path):
    """CHAOS ACCEPTANCE: a wedged world trips the watchdog (code 7),
    `ponyc_tpu supervise` restarts the service from the checkpoint
    ring, the fixed port is re-bound and a reconnecting client is
    served by the second life."""
    port = 0
    with socket.socket() as s:             # reserve a fixed free port
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    marker = tmp_path / "wedged.marker"
    script = tmp_path / "wedge_serve.py"
    script.write_text(WEDGE_SCRIPT.format(root=ROOT,
                                          marker=str(marker)))
    prefix = str(tmp_path / "ring")
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["PYTHONPATH"] = ROOT
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "ponyc_tpu", "supervise",
         "--prefix", prefix, "--retries", "3", "--backoff", "0.1",
         str(script), "--port", str(port), "--workers", "4",
         "--ponywatchdog_s", "3", "--ponycheckpoint_every_s", "0.2",
         f"--ponycheckpoint_path={prefix}"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=str(tmp_path), start_new_session=True)
    try:
        # Probe state machine: wait for life 1 to serve (up), drive it
        # into the wedge (replies stop mid-probe), then keep
        # reconnecting until life 2 serves a full round again.
        deadline = time.monotonic() + 240.0
        phase = "wait_up"
        while time.monotonic() < deadline and phase != "recovered":
            if proc.poll() is not None:
                break
            r = loadgen.run_load("127.0.0.1", port, conns=1, depth=1,
                                 requests=3, timeout_s=3.0)
            full = r["ok"] == 3 and r["bad_value"] == 0
            if phase == "wait_up" and full:
                phase = "up"
            elif phase == "up" and not full:
                phase = "wedged"           # the 5th egress dispatch hung
            elif phase == "wedged" and full:
                phase = "recovered"        # life 2 answered end to end
                break
            time.sleep(0.5)
        assert marker.exists(), "the wedge never armed"
        assert phase == "recovered", \
            f"no round-trip after the wedged life (stuck at {phase})"
        # Stop the whole tree (supervisor + supervised child share a
        # fresh session; the supervisor does not forward signals).
        os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
        try:
            out, err = proc.communicate(timeout=60)
        except subprocess.TimeoutExpired:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
            out, err = proc.communicate(timeout=30)
        # The supervisor logged the code-7 wedged life's restart.
        assert "restarting" in err or "recovered after" in err, err
    finally:
        try:
            os.killpg(os.getpgid(proc.pid), signal.SIGKILL)
        except (ProcessLookupError, OSError):
            pass
