"""Metrics/health export tests (PROFILE.md §11): Prometheus text that
parses and equals Runtime.profile(), the /healthz ok→stalled flip, the
scrape-during-run HTTP round-trip, observability-options jaxpr identity
(PR-4 style), and the doctor CLI against a live endpoint."""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from ponyc_tpu import Runtime, RuntimeOptions
from ponyc_tpu import metrics
from ponyc_tpu.metrics import parse_prometheus, prometheus_text
from ponyc_tpu.models import ring


def _opts(**kw):
    base = dict(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8)
    base.update(kw)
    return RuntimeOptions(**base)


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5.0) as r:
        return r.read().decode(), r.headers.get("Content-Type", "")


# ----------------------------------------------- counters == profile()

def test_prometheus_counters_match_profile(tmp_path):
    """ACCEPTANCE: scraping the metrics port of a live runtime yields
    Prometheus text whose counters equal Runtime.profile() — totals,
    per-behaviour runs, per-cohort queue-wait percentiles."""
    rt, ids = ring.build(8, _opts(analysis=1, metrics_port=0,
                                  analysis_path=str(tmp_path / "a.csv")))
    port = rt._metrics.port
    rt.send(int(ids[0]), ring.RingNode.token, 120)
    assert rt.run() == 0
    body, ctype = _get(port, "/metrics")
    assert ctype.startswith("text/plain")
    p = parse_prometheus(body)
    prof = rt.profile()
    t = prof["totals"]
    assert p[("pony_tpu_processed_total", ())] == t["processed"] == 120
    assert p[("pony_tpu_delivered_total", ())] == t["delivered"]
    assert p[("pony_tpu_rejected_total", ())] == t["rejected"]
    assert p[("pony_tpu_badmsg_total", ())] == t["badmsg"]
    assert p[("pony_tpu_deadletter_total", ())] == t["deadletter"]
    assert p[("pony_tpu_mutes_total", ())] == t["mutes"]
    assert p[("pony_tpu_behaviour_runs_total",
              (("behaviour", "RingNode.token"),))] \
        == prof["behaviours"]["RingNode.token"]["runs"]
    c = prof["cohorts"]["RingNode"]
    assert p[("pony_tpu_queue_wait_ticks",
              (("cohort", "RingNode"), ("quantile", "0.5")))] \
        == c["queue_wait_p50"]
    assert p[("pony_tpu_queue_wait_ticks",
              (("cohort", "RingNode"), ("quantile", "0.99")))] \
        == c["queue_wait_p99"]
    rl = rt.run_loop_stats()
    assert p[("pony_tpu_windows_total", ())] == rl["windows"]
    assert p[("pony_tpu_health", ())] == 1      # ok
    rt.stop()


def test_scrape_during_live_run(tmp_path):
    """/metrics and /healthz answer OVER HTTP while Runtime.run() is
    executing (the run loop pushes snapshots; the HTTP thread never
    touches the device)."""
    rt, ids = ring.build(8, _opts(analysis=1, metrics_port=0,
                                  analysis_path=str(tmp_path / "a.csv")))
    port = rt._metrics.port
    rt.send(int(ids[0]), ring.RingNode.token, 20000)
    got = []

    def scraper():
        while not done.is_set():
            try:
                hz = json.loads(_get(port, "/healthz")[0])
                mx = parse_prometheus(_get(port, "/metrics")[0])
                got.append((hz["status"], mx))
            except (OSError, urllib.error.URLError):
                pass
            time.sleep(0.01)

    done = threading.Event()
    t = threading.Thread(target=scraper, daemon=True)
    t.start()
    assert rt.run() == 0
    done.set()
    t.join(timeout=5.0)
    assert got, "no successful scrape during the run"
    statuses = {s for s, _ in got}
    assert statuses <= {"ok"}                  # a healthy run stays ok
    final = parse_prometheus(_get(port, "/metrics")[0])
    assert final[("pony_tpu_processed_total", ())] \
        == rt.profile()["totals"]["processed"] == 20000
    # mid-run scrapes are monotone prefixes of the final truth
    mid = [m.get(("pony_tpu_processed_total", ()), 0) for _, m in got]
    assert all(0 <= v <= 20000 for v in mid)
    rt.stop()


def test_healthz_flips_ok_to_stalled(tmp_path):
    """The /healthz verdict flips ok → stalled when the watchdog trips
    (and carries the reason), without the HTTP surface going down."""
    rt, ids = ring.build(8, _opts(analysis=1, metrics_port=0,
                                  watchdog_s=30.0,
                                  analysis_path=str(tmp_path / "a.csv")))
    port = rt._metrics.port
    rt.send(int(ids[0]), ring.RingNode.token, 10)
    rt.run()
    hz = json.loads(_get(port, "/healthz")[0])
    assert hz["status"] == "ok" and hz["watchdog"] is not None
    # Simulate the trip the monitor thread would record for a wedged
    # phase (trip() itself also interrupts the main thread — us).
    rt._wd_stamp = ("in-flight", 99, time.monotonic() - 120.0)
    trip = rt._watchdog.check()
    assert trip is not None
    rt._watchdog.tripped = trip
    hz2 = json.loads(_get(port, "/healthz")[0])
    assert hz2["status"] == "stalled"
    assert "in-flight" in hz2["reason"]
    mx = parse_prometheus(_get(port, "/metrics")[0])
    assert mx[("pony_tpu_health", ())] == 0
    rt._watchdog.tripped = None                # un-wedge: flips back
    rt._wd_stamp = ("idle", 100, time.monotonic())
    assert json.loads(_get(port, "/healthz")[0])["status"] == "ok"
    rt.stop()


def test_healthz_degraded_on_coded_errors(tmp_path):
    rt, ids = ring.build(8, _opts(analysis=1, metrics_port=0,
                                  analysis_path=str(tmp_path / "a.csv")))
    port = rt._metrics.port
    rt.send(int(ids[0]), ring.RingNode.token, 10)
    rt.run()
    rt._error_counts[("SpillOverflowError", 2)] += 1
    rt._metrics.update_now(rt)
    hz = json.loads(_get(port, "/healthz")[0])
    assert hz["status"] == "degraded"
    assert "SpillOverflowError" in hz["reason"]
    mx = parse_prometheus(_get(port, "/metrics")[0])
    assert mx[("pony_tpu_errors_total",
               (("class", "SpillOverflowError"), ("code", "2")))] == 1
    assert mx[("pony_tpu_health", ())] == 0.5
    rt.stop()


# ----------------------------------------------------- server plumbing

def test_http_surface_shapes(tmp_path):
    rt, _ids = ring.build(8, _opts(metrics_port=0,
                                   analysis_path=str(tmp_path / "a.csv")))
    port = rt._metrics.port
    body, ctype = _get(port, "/healthz")
    assert ctype.startswith("application/json")
    hz = json.loads(body)
    assert set(hz) >= {"status", "reason", "phase", "steps"}
    # the root path serves metrics (scrape-config convenience)
    assert "# TYPE pony_tpu_steps_total counter" in _get(port, "/")[0]
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(port, "/nope")
    assert ei.value.code == 404
    rt.stop()
    # stop() tears the server down: the port stops answering
    with pytest.raises((OSError, urllib.error.URLError)):
        _get(port, "/healthz")
    assert rt._metrics is None


def test_snapshot_degrades_at_analysis0(tmp_path):
    """metrics_port works at analysis=0: totals come from host-side
    accounting (no profiler lanes to read)."""
    rt, ids = ring.build(8, _opts(analysis=0, metrics_port=0,
                                  analysis_path=str(tmp_path / "a.csv")))
    rt.send(int(ids[0]), ring.RingNode.token, 40)
    rt.run()
    p = parse_prometheus(_get(rt._metrics.port, "/metrics")[0])
    assert p[("pony_tpu_processed_total", ())] == 40
    assert ("pony_tpu_behaviour_runs_total",
            (("behaviour", "RingNode.token"),)) not in p
    rt.stop()


def test_parse_prometheus_and_escaping():
    snap = {"totals": {"processed": 3}, "steps": 7,
            "behaviours": {'T"x\\y.beh': {"runs": 2, "rejected": 0}},
            "errors": [{"class": "PonyError", "code": 9, "count": 4}]}
    text = prometheus_text(snap, {"status": "degraded"})
    p = parse_prometheus(text)
    assert p[("pony_tpu_processed_total", ())] == 3
    assert p[("pony_tpu_errors_total",
              (("class", "PonyError"), ("code", "9")))] == 4
    assert p[("pony_tpu_health", ())] == 0.5
    # label values round-trip through the escaper
    assert any(k[0] == "pony_tpu_behaviour_runs_total" for k in p)


# ------------------------------------------------------- jaxpr identity

def test_observability_options_keep_jaxpr_identity():
    """ACCEPTANCE (PR-4 style): with metrics_port=None and analysis=0,
    a build with the observability knobs set (flight ring size,
    watchdog deadline) lowers to a step jaxpr BIT-IDENTICAL to the
    default build — the whole layer is host-side."""
    import jax
    import jax.numpy as jnp

    from ponyc_tpu.program import Program
    from ponyc_tpu.runtime import engine
    from ponyc_tpu.runtime.state import init_state

    def build(**kw):
        opts = _opts(analysis=0, **kw)
        prog = Program(opts)
        prog.declare(ring.RingNode, 8)
        prog.finalize()
        st = init_state(prog, opts)
        step = engine.build_step(prog, opts)
        k = opts.inject_slots
        inj_t = jnp.full((k,), -1, jnp.int32)
        inj_w = jnp.zeros((1 + opts.msg_words, k), jnp.int32)
        return str(jax.make_jaxpr(step)(st, inj_t, inj_w))

    baseline = build()
    assert build(flight_windows=4, watchdog_s=2.5) == baseline


# ----------------------------------------------------------- doctor CLI

def test_doctor_cli_live_endpoint(tmp_path, capsys):
    from ponyc_tpu.__main__ import main as cli_main
    rt, ids = ring.build(8, _opts(analysis=1, metrics_port=0,
                                  analysis_path=str(tmp_path / "a.csv")))
    port = rt._metrics.port
    rt.send(int(ids[0]), ring.RingNode.token, 15)
    rt.run()
    assert cli_main(["doctor", f"127.0.0.1:{port}"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("OK:")
    assert "pony_tpu_processed_total = 15" in out
    # stalled verdict exits 1
    rt._wd_stamp = ("in-flight", 1, time.monotonic() - 1e5)
    rt._watchdog_dummy = None
    rt.stop()
    # unreachable endpoint is a usage-ish failure (2)
    assert cli_main(["doctor", f"127.0.0.1:{port}"]) == 2


def test_metrics_option_validation():
    with pytest.raises(ValueError, match="metrics_port"):
        RuntimeOptions(metrics_port=70000)
    with pytest.raises(ValueError, match="metrics_port"):
        RuntimeOptions(metrics_port=-1)
