"""Name resolution (net/dns.py ≙ socket.c's addrinfo/nameinfo/host_ip
surface + packages/net/dns.pony) — loopback-only, no egress."""

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.net.dns import DNS


def test_literal_detection():
    assert DNS.is_ip4("127.0.0.1")
    assert not DNS.is_ip4("::1")
    assert not DNS.is_ip4("localhost")
    assert DNS.is_ip6("::1")
    assert not DNS.is_ip6("127.0.0.1")


def test_resolve_loopback():
    addrs = DNS.resolve("127.0.0.1", 80)
    assert (4, "127.0.0.1", 80) in addrs
    assert DNS.ip4("127.0.0.1", 5) == [(4, "127.0.0.1", 5)]
    v6 = DNS.ip6("::1", 7)
    assert all(f == 6 for f, _ip, _p in v6)
    assert DNS.resolve("definitely-not-a-host.invalid.") == []


def test_nameinfo_roundtrip():
    ni = DNS.nameinfo("127.0.0.1", 80)
    assert ni is not None and len(ni) == 2
    assert DNS.nameinfo("256.256.256.256") is None


def test_async_resolver_delivers_actor_message():
    got = []

    @actor
    class Wants:
        HOST = True
        n: I32

        @behaviour
        def on_resolved(self, st, token: I32, h: I32, n: I32):
            got.append((int(token), self.rt.heap.unbox(int(h)), int(n)))
            self.rt.request_exit(0)
            return {**st, "n": st["n"] + 1}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=0,
                                msg_words=3, inject_slots=8))
    rt.declare(Wants, 1).start()
    w = rt.spawn(Wants)
    res = rt.attach_resolver()
    res.resolve("127.0.0.1", 443, w, on_resolved=Wants.on_resolved,
                token=9)
    rt.run(max_steps=200_000)
    assert len(got) == 1
    token, addrs, n = got[0]
    assert token == 9 and n == len(addrs) >= 1
    assert (4, "127.0.0.1", 443) in addrs


def test_async_resolver_failure_is_empty_list():
    got = []

    @actor
    class Wants2:
        HOST = True
        n: I32

        @behaviour
        def on_resolved(self, st, token: I32, h: I32, n: I32):
            got.append((self.rt.heap.unbox(int(h)), int(n)))
            self.rt.request_exit(0)
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=0,
                                msg_words=3, inject_slots=8))
    rt.declare(Wants2, 1).start()
    w = rt.spawn(Wants2)
    rt.attach_resolver().resolve("no-such-host.invalid.", 1, w,
                                 on_resolved=Wants2.on_resolved)
    rt.run(max_steps=200_000)
    assert len(got) == 1
    addrs, n = got[0]
    assert addrs == [] and n < 0, (addrs, n)   # negative resolver error


def test_async_resolver_survives_hostile_hostname():
    """An overlong IDNA label raises UnicodeError inside getaddrinfo;
    the lookup must still deliver (n=-1) and release the noisy hold so
    the world quiesces (review finding)."""
    got = []

    @actor
    class Wants3:
        HOST = True
        n: I32

        @behaviour
        def on_resolved(self, st, token: I32, h: I32, n: I32):
            got.append(int(n))
            self.rt.heap.drop(int(h))
            self.rt.request_exit(0)
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=0,
                                msg_words=3, inject_slots=8))
    rt.declare(Wants3, 1).start()
    w = rt.spawn(Wants3)
    rt.attach_resolver().resolve("a" * 300 + ".com", 1, w,
                                 on_resolved=Wants3.on_resolved)
    rt.run(max_steps=200_000)
    assert got and got[0] < 0


def test_async_resolver_validates_owner_eagerly():
    import pytest

    @actor
    class Wants4:
        HOST = True
        n: I32

        @behaviour
        def on_resolved(self, st, token: I32, h: I32, n: I32):
            return st

    @actor
    class Other4:
        HOST = True
        n: I32

        @behaviour
        def noop(self, st, a: I32, b: I32, c: I32):
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=0,
                                msg_words=3, inject_slots=8))
    rt.declare(Wants4, 1).declare(Other4, 1).start()
    rt.spawn(Wants4)
    o = rt.spawn(Other4)
    # wrong-cohort owner fails AT THE CALL SITE, not inside a later poll
    with pytest.raises(TypeError, match="sendability"):
        rt.attach_resolver().resolve("127.0.0.1", 1, int(o),
                                     on_resolved=Wants4.on_resolved)
