"""Multi-shard semantics on the 8-device virtual CPU mesh (≙ the missing
multi-node test layer called out in SURVEY.md §4: JAX CPU devices are the
"fake cluster")."""

import numpy as np
import pytest

from ponyc_tpu import Runtime, RuntimeOptions, actor, behaviour, I32, Ref
from ponyc_tpu.models import ring


MESH_OPTS = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                           mesh_shards=4, spill_cap=64)


def test_ring_across_shards():
    # With shard-major round-robin slots, node i+1 lives on shard
    # (i+1) % 4 — every hop crosses the mesh.
    n, hops = 16, 64
    rt = ring.run(n_nodes=n, hops=hops, opts=MESH_OPTS)
    st = rt.cohort_state(ring.RingNode)
    assert st["passes"].sum() == hops
    base = hops // n
    extra = hops % n
    expect = np.full(n, base)
    expect[:extra] += 1
    assert (st["passes"] == expect).all()


def test_fanout_across_shards_and_counters():
    @actor
    class Bcast:
        a: Ref
        b: Ref

        MAX_SENDS = 2

        @behaviour
        def go(self, st, n: I32):
            self.send(st["a"], Sink.recv, n)
            self.send(st["b"], Sink.recv, n + 1)
            return st

    @actor
    class Sink:
        total: I32

        @behaviour
        def recv(self, st, v: I32):
            return {**st, "total": st["total"] + v}

    rt = Runtime(MESH_OPTS)
    rt.declare(Bcast, 4).declare(Sink, 8)
    rt.start()
    sinks = rt.spawn_many(Sink, 8)
    srcs = rt.spawn_many(Bcast, 4, a=sinks[:4], b=sinks[4:])
    for i, s in enumerate(srcs):
        rt.send(int(s), Bcast.go, 10 * (i + 1))
    rt.run(max_steps=50)
    st = rt.cohort_state(Sink)
    assert st["total"].sum() == sum(10 * (i + 1) for i in range(4)) * 2 + 4
    assert rt.totals["processed"] == 12  # 4 go + 8 recv
    assert rt.totals["delivered"] == 12
