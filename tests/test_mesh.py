"""Multi-shard semantics on the 8-device virtual CPU mesh (≙ the missing
multi-node test layer called out in SURVEY.md §4: JAX CPU devices are the
"fake cluster")."""

import dataclasses

import numpy as np
import pytest

from ponyc_tpu import Runtime, RuntimeOptions, actor, behaviour, I32, Ref
from ponyc_tpu.models import gups, ring


MESH_OPTS = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                           mesh_shards=4, spill_cap=64)


def test_ring_across_shards():
    # With shard-major round-robin slots, node i+1 lives on shard
    # (i+1) % 4 — every hop crosses the mesh.
    n, hops = 16, 64
    rt = ring.run(n_nodes=n, hops=hops, opts=MESH_OPTS)
    st = rt.cohort_state(ring.RingNode)
    assert st["passes"].sum() == hops
    base = hops // n
    extra = hops % n
    expect = np.full(n, base)
    expect[:extra] += 1
    assert (st["passes"] == expect).all()


def test_fanout_across_shards_and_counters():
    @actor
    class Bcast:
        a: Ref
        b: Ref

        MAX_SENDS = 2

        @behaviour
        def go(self, st, n: I32):
            self.send(st["a"], Sink.recv, n)
            self.send(st["b"], Sink.recv, n + 1)
            return st

    @actor
    class Sink:
        total: I32

        @behaviour
        def recv(self, st, v: I32):
            return {**st, "total": st["total"] + v}

    rt = Runtime(MESH_OPTS)
    rt.declare(Bcast, 4).declare(Sink, 8)
    rt.start()
    sinks = rt.spawn_many(Sink, 8)
    srcs = rt.spawn_many(Bcast, 4, a=sinks[:4], b=sinks[4:])
    for i, s in enumerate(srcs):
        rt.send(int(s), Bcast.go, 10 * (i + 1))
    rt.run(max_steps=50)
    st = rt.cohort_state(Sink)
    assert st["total"].sum() == sum(10 * (i + 1) for i in range(4)) * 2 + 4
    assert rt.totals["processed"] == 12  # 4 go + 8 recv
    assert rt.totals["delivered"] == 12


def test_host_drain_across_shards():
    # Host-cohort rows live at each shard's tail range — NOT a suffix of the
    # global head array. This drains host actors on a 4-shard mesh and then
    # re-runs, which fails if _drain_host writes heads at the wrong rows
    # (regression: round-2 `.at[fh:]` bug, and the `fh` NameError).
    @actor
    class DevSrc:
        out: Ref
        MAX_SENDS = 1

        @behaviour
        def go(self, st, n: I32):
            self.send(st["out"], HostSink.recv, n)
            return st

    @actor
    class HostSink:
        HOST = True

        @behaviour
        def recv(self, st, v: I32):
            st = dict(st)
            st["got"] = st.get("got", 0) + int(v)
            return st

    opts = dataclasses.replace(MESH_OPTS, msg_words=2)
    rt = Runtime(opts)
    rt.declare(DevSrc, 8).declare(HostSink, 8)
    rt.start()
    sinks = rt.spawn_many(HostSink, 8)
    srcs = rt.spawn_many(DevSrc, 8, out=sinks)
    for rnd in range(3):  # repeated drains: stale heads double-deliver
        for i, s in enumerate(srcs):
            rt.send(int(s), DevSrc.go, 10 * (i + 1))
        rt.run(max_steps=40)
    total = sum(rt.state_of(int(h)).get("got", 0) for h in sinks)
    assert total == 3 * sum(10 * (i + 1) for i in range(8))
    assert rt.totals["badmsg"] == 0


def test_gups_across_shards():
    # Updates land on cells scattered over 4 shards; xor-conservation holds
    # only if every update reached the cell its slot arithmetic names.
    opts = RuntimeOptions(mailbox_cap=16, batch=2, max_sends=2, msg_words=1,
                          mesh_shards=4, spill_cap=256)
    rt = gups.run(table_size=64, n_updaters=8, updates_each=16, opts=opts)
    st_u = rt.cohort_state(gups.Updater)
    assert st_u["done"].sum() == 8 * 16
    # Replay the xorshift stream host-side: xor of all cells must equal the
    # xor of every value ever sent.
    rng0 = np.random.default_rng(7).integers(1, 2**31 - 1, 8).astype(np.int64)
    expect = 0
    for x in rng0:
        for _ in range(16):
            x = np.int32(x ^ (x << 13))
            x = np.int32(x ^ ((x >> 17) & 0x7FFF))
            x = np.int32(x ^ (x << 5))
            expect ^= int(np.uint32(x))
    got = 0
    for v in rt.cohort_state(gups.TableCell)["value"]:
        got ^= int(np.uint32(v))
    assert got == expect
