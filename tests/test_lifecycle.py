"""Device-side actor lifecycle: ctx.spawn / ctx.destroy.

≙ pony_create from behaviour code (src/libponyrt/actor/actor.c:688-734 —
in Pony every actor is created by another actor at runtime) and actor
destruction (ponyint_actor_destroy, actor.c:570-664). The reference has no
isolated unit tests for these (SURVEY.md §4 — exercised via stdlib tests);
we add the missing layer here.
"""

import numpy as np
import pytest

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions, actor, behaviour)
from ponyc_tpu.runtime.runtime import SpawnCapacityError


@actor
class Worker:
    boss: Ref
    value: I32

    @behaviour
    def init(self, st, boss: Ref, value: I32):
        # Constructor behaviour (≙ Pony's `new create(...)` — itself the
        # actor's first message). Report back so the parent learns our ref.
        self.send(boss, Boss.started, self.actor_id)
        return {**st, "boss": boss, "value": value}

    @behaviour
    def stop(self, st):
        self.destroy()
        return st


@actor
class Boss:
    n_started: I32
    last_child: Ref

    SPAWNS = {"Worker": 2}
    MAX_SENDS = 2

    @behaviour
    def go(self, st, count: I32):
        a = self.spawn(Worker.init, self.actor_id, 11, when=count >= 1)
        self.spawn(Worker.init, self.actor_id, 22, when=count >= 2)
        return {**st, "last_child": a}

    @behaviour
    def started(self, st, child: Ref):
        return {**st, "n_started": st["n_started"] + 1}


def _mk(worker_cap=8, boss_cap=2, **kw):
    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=2, msg_words=2,
                          spill_cap=64, inject_slots=8, **kw)
    rt = Runtime(opts).declare(Worker, worker_cap).declare(Boss, boss_cap)
    rt.start()
    return rt


def test_spawn_creates_and_constructs():
    rt = _mk()
    boss = rt.spawn(Boss)
    rt.send(boss, Boss.go, 2)
    rt.run(max_steps=20)
    assert rt.counter("n_spawned") == 2
    assert rt.state_of(boss)["n_started"] == 2
    ws = rt.cohort_state(Worker)
    alive = np.asarray(rt.state.alive)
    assert alive.sum() == 3  # boss + two workers
    assert sorted(v for v in ws["value"] if v) == [11, 22]
    # Parent held the first child's ref at spawn time (same dispatch).
    assert rt.state_of(boss)["last_child"] >= 0
    assert rt.state_of(rt.state_of(boss)["last_child"])["value"] == 11


def test_masked_spawn_does_not_claim():
    rt = _mk()
    boss = rt.spawn(Boss)
    rt.send(boss, Boss.go, 1)   # second site masked out
    rt.run(max_steps=20)
    assert rt.counter("n_spawned") == 1
    assert rt.state_of(boss)["n_started"] == 1


def test_destroy_frees_and_deadletters():
    rt = _mk()
    boss = rt.spawn(Boss)
    rt.send(boss, Boss.go, 2)
    rt.run(max_steps=20)
    child = rt.state_of(boss)["last_child"]
    rt.send(child, Worker.stop)
    rt.run(max_steps=20)
    assert rt.counter("n_destroyed") == 1
    assert not bool(np.asarray(rt.state.alive)[child])
    # Sends to the destroyed actor dead-letter (≙ impossible in Pony —
    # ORCA keeps referenced actors alive; here it's a counted drop).
    before = rt.counter("n_deadletter")
    rt.send(child, Worker.stop)
    rt.run(max_steps=20)
    assert rt.counter("n_deadletter") == before + 1


def test_destroyed_slot_is_reused():
    rt = _mk(worker_cap=2, boss_cap=1)
    boss = rt.spawn(Boss)
    rt.send(boss, Boss.go, 2)     # fills both worker slots
    rt.run(max_steps=20)
    assert rt.counter("n_spawned") == 2
    child = rt.state_of(boss)["last_child"]
    rt.send(child, Worker.stop)   # free one slot
    rt.run(max_steps=20)
    rt.send(boss, Boss.go, 1)     # must reuse the freed slot
    rt.run(max_steps=20)
    assert rt.counter("n_spawned") == 3
    assert rt.state_of(boss)["n_started"] == 3
    assert np.asarray(rt.state.alive).sum() == 3


def test_spawn_capacity_exhaustion_raises():
    rt = _mk(worker_cap=1, boss_cap=1)
    boss = rt.spawn(Boss)
    rt.send(boss, Boss.go, 2)     # wants 2 slots, only 1 exists
    with pytest.raises(SpawnCapacityError):
        rt.run(max_steps=20)


def test_host_spawn_sees_device_claims():
    rt = _mk(worker_cap=3, boss_cap=1)
    boss = rt.spawn(Boss)
    rt.send(boss, Boss.go, 2)
    rt.run(max_steps=20)
    # Host-side spawn must not hand out the two device-claimed slots.
    w = rt.spawn(Worker, value=99)
    assert rt.state_of(w)["value"] == 99
    alive = np.asarray(rt.state.alive)
    assert alive.sum() == 4
    with pytest.raises(RuntimeError):
        rt.spawn(Worker)          # cohort genuinely full now


def test_host_spawn_after_device_destroy_reclaims():
    rt = _mk(worker_cap=2, boss_cap=1)
    ws = rt.spawn_many(Worker, 2)
    for w in ws:
        rt.send(int(w), Worker.stop)
    rt.run(max_steps=20)
    assert rt.counter("n_destroyed") == 2
    # The host freelist re-syncs from device truth: both slots are free
    # again even though host-side spawns had popped them.
    w = rt.spawn(Worker, value=7)
    assert rt.state_of(w)["value"] == 7


def test_spawn_on_mesh_stays_shard_local():
    opts = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=2, msg_words=2,
                          spill_cap=64, inject_slots=8, mesh_shards=4)
    rt = Runtime(opts).declare(Worker, 16).declare(Boss, 4)
    rt.start()
    bosses = rt.spawn_many(Boss, 4)
    for b in bosses:
        rt.send(int(b), Boss.go, 2)
    rt.run(max_steps=30)
    assert rt.counter("n_spawned") == 8
    # Every child lives on its parent's shard (≙ pony_create allocating on
    # the creating scheduler's own thread).
    nl = rt.program.n_local
    for b in bosses:
        child = rt.state_of(int(b))["last_child"]
        assert child // nl == int(b) // nl
        assert rt.state_of(int(b))["n_started"] == 2


def test_spawn_sync_constructs_fields_synchronously():
    """≙ the fork's pony_sendv_synchronous_constructor (actor.c:836-848):
    the constructor runs inside the spawning dispatch and the newborn's
    fields are set at claim time — a same-step probe message dispatched
    next tick must see constructed state, with no constructor-message
    ordering involved."""
    from ponyc_tpu import F32

    @actor
    class Kid2:
        tag: I32
        frac: F32
        boss: Ref

        @behaviour
        def init(self, st, tag: I32, frac: F32):
            return {**st, "tag": tag, "frac": frac,
                    "boss": self.actor_id * 0 - 1}

        @behaviour
        def probe(self, st, bump: I32):
            return {**st, "tag": st["tag"] + bump}

    @actor
    class Maker2:
        made: Ref
        MAX_SENDS = 1
        SPAWNS = {"Kid2": 1}

        @behaviour
        def make(self, st, v: I32):
            ref = self.spawn_sync(Kid2.init, v, 0.5)
            # Same-step send to the newborn: arrives AFTER construction
            # by definition (fields written at claim time this tick).
            self.send(ref, Kid2.probe, 100, when=ref >= 0)
            return {**st, "made": ref}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=2, max_sends=1,
                                msg_words=3, inject_slots=8))
    rt.declare(Maker2, 1).declare(Kid2, 2).start()
    m = rt.spawn(Maker2)
    rt.send(m, Maker2.make, 7)
    assert rt.run(max_steps=10) == 0
    kid = rt.state_of(m)["made"]
    assert kid >= 0
    st = rt.state_of(int(kid))
    assert st["tag"] == 7 + 100        # constructed, then probed
    assert st["frac"] == 0.5
    assert st["boss"] == -1


def test_spawn_sync_rejects_effectful_constructor():
    @actor
    class Kid3:
        x: I32

        @behaviour
        def init(self, st, v: I32):
            self.exit(1)                # effect: not a pure constructor
            return {**st, "x": v}

    @actor
    class Maker3:
        MAX_SENDS = 1
        SPAWNS = {"Kid3": 1}

        @behaviour
        def make(self, st, v: I32):
            self.spawn_sync(Kid3.init, v)
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                msg_words=2, inject_slots=8))
    rt.declare(Maker3, 1).declare(Kid3, 1).start()
    m = rt.spawn(Maker3)
    rt.send(m, Maker3.make, 1)
    with pytest.raises(TypeError, match="effects"):
        rt.run(max_steps=4)


def test_spawn_destroy_churn_conserves_against_oracle():
    """Chain relays spawn ephemeral Workers that log and self-destroy:
    spawn + destroy + messaging interacting under churn, with exact
    conservation vs a closed-form oracle (≙ pony_create/destroy driven
    from behaviour code at rate, actor.c:688-734, 570-664)."""
    import numpy as np

    from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, \
        behaviour

    @actor
    class Relay:
        nxt: Ref["Relay"]
        sink: Ref["Collector"]
        forwarded: I32

        MAX_SENDS = 2
        SPAWNS = {"Worker": 1}

        @behaviour
        def chain(self, st, v: I32):
            w = self.spawn(Worker.init, v, st["sink"], when=v > 0)
            self.send(st["nxt"], Relay.chain, v - 1, when=v > 0)
            return {**st, "forwarded": st["forwarded"] + (w >= 0)}

    @actor
    class Worker:
        MAX_SENDS = 1

        @behaviour
        def init(self, st, v: I32, sink: I32):
            self.send(sink, Collector.log, v)
            self.destroy()
            return st

    @actor
    class Collector:
        total: I32
        hits: I32

        BATCH = 8

        @behaviour
        def log(self, st, v: I32):
            return {**st, "total": st["total"] + v,
                    "hits": st["hits"] + 1}

    for seed in (301, 307):
        rng = np.random.default_rng(seed)
        n_r = int(rng.integers(6, 16))
        starts = [(int(rng.integers(0, n_r)), int(rng.integers(1, 10)))
                  for _ in range(5)]
        nxt = rng.integers(0, n_r, n_r)
        total = sum(v - k for _, v in starts for k in range(v))
        hits = sum(v for _, v in starts)
        rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=2, msg_words=2,
                                    max_sends=2, spill_cap=2048,
                                    inject_slots=32, cd_interval=16))
        rt.declare(Relay, n_r).declare(Worker, 4 * (hits + 8)).declare(
            Collector, 1)
        rt.start()
        sink = rt.spawn(Collector)
        rids = rt.spawn_many(Relay, n_r)
        rt.set_fields(Relay, rids, nxt=rids[np.asarray(nxt)],
                      sink=np.full(n_r, sink))
        for i, v in starts:
            rt.send(int(rids[i]), Relay.chain, v)
        assert rt.run(max_steps=100_000) == 0
        st = rt.state_of(sink)
        assert st["total"] == total and st["hits"] == hits
        assert rt.counter("n_destroyed") == hits


def test_spawn_destroy_churn_on_mesh():
    """The churn scenario sharded over 4 devices: same-shard spawn slots,
    cross-shard constructor/report messages, exact conservation."""
    import numpy as np

    from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, \
        behaviour

    @actor
    class MRelay:
        nxt: Ref["MRelay"]
        sink: Ref["MCollector"]

        MAX_SENDS = 2
        SPAWNS = {"MWorker": 1}

        @behaviour
        def chain(self, st, v: I32):
            self.spawn(MWorker.init, v, st["sink"], when=v > 0)
            self.send(st["nxt"], MRelay.chain, v - 1, when=v > 0)
            return st

    @actor
    class MWorker:
        MAX_SENDS = 1

        @behaviour
        def init(self, st, v: I32, sink: I32):
            self.send(sink, MCollector.log, v)
            self.destroy()
            return st

    @actor
    class MCollector:
        total: I32
        hits: I32

        BATCH = 16

        @behaviour
        def log(self, st, v: I32):
            return {**st, "total": st["total"] + v,
                    "hits": st["hits"] + 1}

    rng = np.random.default_rng(5)
    n_r = 16
    starts = [(int(rng.integers(0, n_r)), int(rng.integers(4, 12)))
              for _ in range(8)]
    nxt = rng.integers(0, n_r, n_r)
    total = sum(v - k for _, v in starts for k in range(v))
    hits = sum(v for _, v in starts)
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=2, msg_words=2,
                                max_sends=2, spill_cap=4096,
                                inject_slots=64, mesh_shards=4,
                                quiesce_interval=2, cd_interval=16))
    rt.declare(MRelay, n_r).declare(MWorker, 512).declare(MCollector, 4)
    rt.start()
    sink = rt.spawn(MCollector)
    rids = rt.spawn_many(MRelay, n_r)
    rt.set_fields(MRelay, rids, nxt=rids[np.asarray(nxt)],
                  sink=np.full(n_r, sink))
    for i, v in starts:
        rt.send(int(rids[i]), MRelay.chain, v)
    assert rt.run(max_steps=100_000) == 0
    st = rt.state_of(sink)
    assert st["total"] == total and st["hits"] == hits
    assert rt.counter("n_spawned") == rt.counter("n_destroyed") == hits
