"""Stdlib package tests — ≙ the per-package _test.pony files aggregated
by packages/stdlib/_test.pony (the reference's de-facto runtime
integration suite, SURVEY.md §4)."""

import pytest

from ponyc_tpu.stdlib import persistent
from ponyc_tpu.stdlib.buffered import IncompleteError, Reader, Writer
from ponyc_tpu.stdlib.cli import (ArgSpec, CliSyntaxError, Command,
                                  CommandHelp, CommandParser, CommandSpec,
                                  EnvVars, OptionSpec)
from ponyc_tpu.stdlib.collections import (BinaryHeap, Flags, List, MaxHeap,
                                          MinHeap, Range, Reverse,
                                          RingBuffer, Sort)
from ponyc_tpu.stdlib.encode import Base64
from ponyc_tpu.stdlib.format import (AlignCenter, AlignRight, Format,
                                     FormatBinary, FormatFix, FormatHex,
                                     FormatHexSmall, PrefixSign)
from ponyc_tpu.stdlib.ini import IniMap
from ponyc_tpu.stdlib.itertools import Iter
from ponyc_tpu.stdlib.json import (JsonArray, JsonDoc, JsonObject,
                                   JsonParseError)
from ponyc_tpu.stdlib.math import Fibonacci
from ponyc_tpu.stdlib.strings import CommonPrefix


# ---- collections (≙ packages/collections/_test.pony) ----

def test_flags():
    A, B, C = 1, 2, 4
    f = Flags().set(A).set(B)
    assert f(A) and f(B) and not f(C)
    f.unset(A)
    assert not f(A)
    g = Flags().set(A).set(C)
    assert (f | g).value() == (B | A | C)
    assert (f & g).value() == 0
    assert Flags(A) <= Flags(A | B)
    assert Flags(A) < Flags(A | B)
    assert not (Flags(A | B) < Flags(A | B))


def test_range():
    assert list(Range(0, 5)) == [0, 1, 2, 3, 4]
    assert list(Range(10, -5, -5)) == [10, 5, 0]
    assert Range(0, 1, 0).is_infinite()
    assert Range(0, 10, -1).is_infinite()
    assert Range(0, 10, float("nan")).is_infinite()
    assert list(Range(3, 3)) == []
    r = Range(0, 3)
    assert [r.next() for _ in range(2)] == [0, 1]
    r.rewind()
    assert r.next() == 0


def test_heaps():
    mn, mx = MinHeap(), MaxHeap()
    for v in [5, 1, 4, 1, 9]:
        mn.push(v)
        mx.push(v)
    assert [mn.pop() for _ in range(len(mn))] == [1, 1, 4, 5, 9]
    assert [mx.pop() for _ in range(len(mx))] == [9, 5, 4, 1, 1]
    with pytest.raises(IndexError):
        BinaryHeap().pop()


def test_ring_buffer():
    rb = RingBuffer(4)
    assert not any(rb.push(i) for i in range(4))
    assert rb.push(4)            # overwrites 0
    assert rb.head() == 1
    assert rb(4) == 4 and rb(1) == 1
    with pytest.raises(IndexError):
        rb(0)                    # fell off
    with pytest.raises(IndexError):
        rb(5)                    # not yet written


def test_sort_and_reverse():
    a = [3, 1, 2, 9, 7, 7, 0]
    assert Sort.apply(a) == sorted(a)
    b = ["bb", "a", "ccc"]
    assert Sort.by(b, len) == ["a", "bb", "ccc"]
    assert list(Reverse(10, 2, 2)) == [10, 8, 6, 4, 2]


def test_linked_list():
    lst = List([1, 2, 3])
    assert list(lst) == [1, 2, 3] and len(lst) == 3
    node = lst.head().next()
    node.remove()
    assert list(lst) == [1, 3]
    lst.unshift(0)
    assert lst.shift() == 0
    assert lst.pop() == 3
    assert list(lst) == [1]


# ---- persistent (≙ packages/collections/persistent/_test.pony) ----

def test_persistent_map_basic():
    m0 = persistent.Map()
    m1 = m0.update("a", 1)
    m2 = m1.update("b", 2)
    m3 = m2.update("a", 10)
    assert m0.size() == 0 and m1.size() == 1 and m2.size() == 2
    assert m3.size() == 2
    assert m1("a") == 1 and m3("a") == 10 and m2("a") == 1  # old intact
    with pytest.raises(KeyError):
        m0("a")
    m4 = m3.remove("a")
    assert not m4.contains("a") and m3.contains("a")
    with pytest.raises(KeyError):
        m4.remove("nope")
    assert m2.get_or_else("zz", 42) == 42


def test_persistent_map_stress():
    n = 2000
    m = persistent.Map()
    for i in range(n):
        m = m.update(f"k{i}", i)
    assert m.size() == n
    assert all(m(f"k{i}") == i for i in range(0, n, 97))
    assert sorted(m.values()) == list(range(n))
    for i in range(0, n, 2):
        m = m.remove(f"k{i}")
    assert m.size() == n // 2
    assert m("k1") == 1 and not m.contains("k0")


def test_persistent_vec():
    v = persistent.Vec()
    n = 1100                       # crosses the 32-wide tail + root split
    for i in range(n):
        v = v.push(i)
    assert v.size() == n and v(0) == 0 and v(n - 1) == n - 1
    v2 = v.update(500, -1)
    assert v2(500) == -1 and v(500) == 500
    for want in reversed(range(n)):
        v, got = v.pop()
        assert got == want
    assert v.size() == 0
    with pytest.raises(IndexError):
        v.pop()
    assert list(persistent.Vec.of("abc")) == ["a", "b", "c"]


def test_persistent_list_and_set():
    lst = persistent.List.of([1, 2, 3])
    assert list(lst) == [1, 2, 3]
    assert list(lst.prepend(0)) == [0, 1, 2, 3]
    assert list(lst) == [1, 2, 3]                 # old unchanged
    assert lst.map(lambda x: x * 2).fold(lambda a, b: a + b, 0) == 12
    s = persistent.Set.of([1, 2, 3])
    assert 2 in s and 9 not in s
    assert sorted(s.union(persistent.Set.of([3, 4]))) == [1, 2, 3, 4]
    assert sorted(s.intersect(persistent.Set.of([2, 3, 9]))) == [2, 3]
    assert sorted(s.difference(persistent.Set.of([1]))) == [2, 3]


# ---- json (≙ packages/json/_test.pony) ----

def test_json_parse_basic():
    d = JsonDoc()
    d.parse('{"a": 1, "b": [true, null, 2.5, "x\\n"], "c": {"d": -3e2}}')
    obj = d.data
    assert isinstance(obj, JsonObject)
    assert obj.data["a"] == 1 and isinstance(obj.data["a"], int)
    arr = obj.data["b"]
    assert isinstance(arr, JsonArray)
    assert arr.data == [True, None, 2.5, "x\n"]
    assert obj.data["c"].data["d"] == -300.0


def test_json_roundtrip_and_pretty():
    src = '{"k": [1, 2], "s": "hi"}'
    d = JsonDoc()
    d.parse(src)
    assert d.string() == src
    pretty = d.string(indent="  ", pretty_print=True)
    assert pretty == '{\n  "k": [\n    1,\n    2\n  ],\n  "s": "hi"\n}'
    d2 = JsonDoc()
    d2.parse(pretty)
    assert d2.data == d.data


def test_json_unicode_escapes():
    d = JsonDoc()
    d.parse('"\\u0041\\ud83d\\ude00"')
    assert d.data == "A\U0001F600"
    with pytest.raises(JsonParseError):
        d.parse('"\\ud83d"')     # lone high surrogate


def test_json_errors_report_line():
    d = JsonDoc()
    with pytest.raises(JsonParseError):
        d.parse('{"a": 1,\n "b": }')
    line, msg = d.parse_report()
    assert line == 2 and msg
    for bad in ("{", "[1,]", "tru", '{"a" 1}', "01x", '"\\q"', "1 2"):
        with pytest.raises(JsonParseError):
            d.parse(bad)


# ---- cli (≙ packages/cli/_test.pony) ----

def _spec():
    spec = CommandSpec.parent("tool", "A tool", options=[
        OptionSpec.bool("verbose", "Noisy", short="v", default=False),
        OptionSpec.string("name", "Name", short="n", default="anon"),
    ])
    spec.add_command(CommandSpec.leaf("run", "Run", options=[
        OptionSpec.i64("count", "How many", short="c", default=1),
        OptionSpec.string_seq("tag", "Tags", short="t"),
    ], args=[ArgSpec.string("target", "Target"),
             ArgSpec.f64("scale", "Scale", default=1.0)]))
    spec.add_help()
    return spec


def test_cli_leaf_parse():
    cmd = CommandParser(_spec()).parse(
        ["tool", "-v", "run", "--count=3", "-t", "a", "-t", "b", "x",
         "2.5"])
    assert isinstance(cmd, Command)
    assert cmd.full_name() == "tool/run"
    assert cmd.option("verbose") is True
    assert cmd.option("name") == "anon"
    assert cmd.option("count") == 3
    assert cmd.option("tag") == ("a", "b")
    assert cmd.arg("target") == "x" and cmd.arg("scale") == 2.5


def test_cli_short_combining_and_value():
    spec = CommandSpec.leaf("t", options=[
        OptionSpec.bool("a", short="a", default=False),
        OptionSpec.bool("b", short="b", default=False),
        OptionSpec.i64("n", short="n", default=0)])
    cmd = CommandParser(spec).parse(["t", "-abn5"])
    assert cmd.option("a") and cmd.option("b") and cmd.option("n") == 5


def test_cli_errors():
    p = CommandParser(_spec())
    assert isinstance(p.parse(["tool", "nope"]), CliSyntaxError)
    assert isinstance(p.parse(["tool", "--bogus", "run", "x"]),
                      CliSyntaxError)
    assert isinstance(p.parse(["tool", "run"]), CliSyntaxError)  # no target
    assert isinstance(p.parse(["tool", "run", "--count=zz", "x"]),
                      CliSyntaxError)
    e = p.parse(["tool", "run", "x", "1.0", "extra"])
    assert isinstance(e, CliSyntaxError) and "extra" in e.string()


def test_cli_help_and_env():
    p = CommandParser(_spec())
    h = p.parse(["tool"])
    assert isinstance(h, CommandHelp) and "Commands:" in h.help_string()
    h2 = p.parse(["tool", "help", "run"])
    assert isinstance(h2, CommandHelp) and "--count" in h2.help_string()
    h3 = p.parse(["tool", "run", "x", "--help"])
    assert isinstance(h3, CommandHelp)
    env = EnvVars({"TOOL_NAME": "from-env"})
    cmd = CommandParser(_spec(), env).parse(["tool", "run", "x"])
    assert cmd.option("name") == "from-env"
    # double dash ends option parsing
    cmd2 = CommandParser(_spec()).parse(["tool", "run", "--", "-v"])
    assert isinstance(cmd2, Command) and cmd2.arg("target") == "-v"


# ---- buffered (≙ packages/buffered/_test.pony) ----

def test_buffered_reader():
    r = Reader()
    w = Writer()
    w.u8(7).u16_be(0x0102).u32_le(0x01020304).f32_be(1.5)
    w.write(b"hello\r\nrest")
    data = b"".join(w.done())
    # Feed in awkward chunk boundaries.
    r.append(data[:3])
    r.append(data[3:8])
    r.append(data[8:])
    assert r.u8() == 7
    assert r.u16_be() == 0x0102
    assert r.u32_le() == 0x01020304
    assert r.f32_be() == 1.5
    assert r.line() == "hello"
    assert r.block(4) == b"rest"
    with pytest.raises(IncompleteError):
        r.u8()


def test_buffered_reader_peek_and_until():
    r = Reader()
    r.append(b"ab:cd")
    assert r.peek_u8(0) == ord("a") and r.peek_u8(3) == ord("c")
    assert r.read_until(ord(":")) == b"ab"
    assert r.block(2) == b"cd"
    assert r.size() == 0
    r.append(b"no-newline")
    with pytest.raises(IncompleteError):
        r.line()
    assert r.size() == 10        # failed read consumed nothing


def test_buffered_signed_and_64():
    w = Writer()
    w.i32_be(-2).u64_le(2**63 + 5).i64_be(-(2**40)).f64_be(0.25)
    r = Reader()
    r.append(b"".join(w.done()))
    assert r.i32_be() == -2
    assert r.u64_le() == 2**63 + 5
    assert r.i64_be() == -(2**40)
    assert r.f64_be() == 0.25


# ---- base64 (≙ packages/encode/base64/_test.pony) ----

def test_base64_rfc_vectors():
    vec = {"": "", "f": "Zg==", "fo": "Zm8=", "foo": "Zm9v",
           "foob": "Zm9vYg==", "fooba": "Zm9vYmE=", "foobar": "Zm9vYmFy"}
    for plain, enc in vec.items():
        assert Base64.encode(plain) == enc
        assert Base64.decode(enc) == plain.encode()


def test_base64_url_and_lines():
    data = bytes(range(256))
    assert Base64.decode_url(Base64.encode_url(data)) == data
    assert "+" not in Base64.encode_url(data)
    pem = Base64.encode_pem(b"x" * 100)
    first = pem.split("\r\n")[0]
    assert len(first) == 64
    assert Base64.decode(pem) == b"x" * 100
    with pytest.raises(ValueError):
        Base64.decode("a!b")


# ---- format (≙ packages/format/_test.pony) ----

def test_format_int():
    assert Format.int(255, FormatHex) == "0xFF"
    assert Format.int(255, FormatHexSmall) == "0xff"
    assert Format.int(5, FormatBinary) == "0b101"
    assert Format.int(42, width=6) == "    42"
    assert Format.int(42, width=6, fill="0") == "000042"
    assert Format.int(42, prefix=PrefixSign) == "+42"
    assert Format.int(-42, FormatHex) == "-0x2A"
    assert Format.int(7, precision=3) == "007"


def test_format_float_and_string():
    assert Format.float(1234.5678, FormatFix, precision=2) == "1234.57"
    assert Format.float(1234.5678, "exp", precision=1) == "1.2e+03"
    assert Format("hi", width=6, align=AlignCenter, fill=".") == "..hi.."
    assert Format("truncated", precision=4) == "trun"
    assert Format(true_val := True) == "true" and true_val


# ---- itertools (≙ packages/itertools/_test.pony) ----

def test_iter_combinators():
    assert Iter(range(10)).filter(lambda x: x % 2 == 0).map(
        lambda x: x * x).collect() == [0, 4, 16, 36, 64]
    assert Iter("abc").enum().collect() == [(0, "a"), (1, "b"), (2, "c")]
    assert Iter([1, 1, 2, 1]).unique().collect() == [1, 2, 1]
    assert Iter([1, 1, 2, 1]).dedup().collect() == [1, 2]
    assert Iter(range(100)).skip(95).take(3).collect() == [95, 96, 97]
    assert Iter([1, 2, 3]).fold(0, lambda a, b: a + b) == 6
    assert Iter([[1], [2, 3]]).flat_map(lambda x: x).collect() == [1, 2, 3]
    assert Iter.chain([[1], [], [2]]).collect() == [1, 2]
    assert Iter([1, 2]).zip("ab").collect() == [(1, "a"), (2, "b")]
    assert Iter(range(5)).step_by(2).collect() == [0, 2, 4]
    assert Iter([1, 2]).interleave([10, 20, 30]).collect() == \
        [1, 10, 2, 20, 30]
    assert Iter(range(5)).nth(2) == 1
    assert Iter(Iter.repeat_value(7).take(3)).collect() == [7, 7, 7]
    assert Iter([1, 2, 3]).last() == 3
    assert Iter([]).count() == 0
    it = Iter([1])
    assert it.has_next() and it.next() == 1 and not it.has_next()
    with pytest.raises(IndexError):
        Iter([1]).find(lambda x: x > 5)


# ---- ini (≙ packages/ini/_test.pony) ----

def test_ini_map():
    src = """
; comment
top = 1
[sec]
a = hello ; trailing comment
b: colon-delimited
# another comment
[empty]
""".splitlines()
    m = IniMap.apply(src)
    assert m[""]["top"] == "1"
    assert m["sec"]["a"] == "hello"
    assert m["sec"]["b"] == "colon-delimited"
    assert m["empty"] == {}
    with pytest.raises(ValueError):
        IniMap.apply(["[unclosed"])
    with pytest.raises(ValueError):
        IniMap.apply(["keywithoutvalue"])


# ---- strings / math ----

def test_common_prefix_and_fibonacci():
    assert CommonPrefix(["doable", "doing", "dock"]) == "do"
    assert CommonPrefix(["a", "b"]) == ""
    assert CommonPrefix([]) == ""
    assert CommonPrefix([123, 124]) == "12"
    assert Iter(Fibonacci()).take(8).collect() == [0, 1, 1, 2, 3, 5, 8, 13]
    assert Fibonacci.apply(10) == 55
