"""Device-cost observatory + perf scoreboard (costs.py, ISSUE 19).

Four layers under test, matching the tentpole:
1. measured capture — XLA's cost/memory analysis of the runtime's REAL
   compiled executables (capture / Runtime.measured_costs /
   opts.cost_capture), memoized, never advancing the world;
2. modelled vs measured — on CPU the record-move probe's bytes/msg must
   agree with megakernel.modelled_bytes_per_msg's unpacked bytes within
   the divergence tolerance, and a seeded mismatch must trip the loud
   model_divergence flag;
3. the scoreboard — BENCH_HISTORY.jsonl + BENCH_r*.json ingestion,
   like-for-like grouping, the --check regression gate (an injected
   regression fails, the repo's real trajectory passes);
4. the operational surfaces — /metrics gauges, the flight-recorder
   postmortem's measured section (gracefully absent on pre-PR-19
   dumps), and `ponyc_tpu perf` / `doctor --postmortem` exit codes.
"""

import json
import os

import pytest

from ponyc_tpu import RuntimeOptions, costs
from ponyc_tpu.models import ring

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _opts(**kw):
    base = dict(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8)
    base.update(kw)
    return RuntimeOptions(**base)


def _ring(**kw):
    rt, ids = ring.build(8, _opts(**kw))
    return rt, ids


@pytest.fixture(scope="module")
def plain_rt():
    """One started ring world shared by the capture-path tests below —
    each test stays independently runnable (capture compiles on demand)
    but a full-file run pays the build + AOT compiles once."""
    rt, ids = _ring()
    yield rt, ids
    rt.stop()


@pytest.fixture(scope="module")
def cc_rt():
    """One cost_capture=True world shared by the eager-capture /
    postmortem / doctor surface tests."""
    rt, ids = _ring(cost_capture=True)
    yield rt, ids
    rt.stop()


# ------------------------------------------------------ measured capture

def test_capture_reads_real_executables_and_memoizes(plain_rt):
    rt, _ = plain_rt
    steps0 = rt.steps_run
    cap = costs.capture(rt)
    # AOT lowering must not advance the world.
    assert rt.steps_run == steps0
    assert cap["version"] == costs.COST_VERSION
    assert set(cap["executables"]) == {"step", "window"}
    for rec in cap["executables"].values():
        assert "error" not in rec
        # CPU reports both analyses on jaxlib 0.4.x; every field is
        # at worst None, never missing.
        assert {"flops", "bytes_accessed", "peak_bytes"} <= set(rec)
        assert rec["bytes_accessed"] and rec["bytes_accessed"] > 0
    # Memoized: same object back, and measured_costs() is the accessor.
    assert costs.capture(rt) is cap
    assert rt.measured_costs() is cap
    assert rt.measured_costs(force=True) is not cap


def test_cost_capture_option_runs_at_start(cc_rt):
    rt, _ = cc_rt
    assert rt._costs is not None
    # start()'s eager capture goes all the way to the judged block.
    assert "model_divergence" in rt._costs


def test_capture_requires_started_runtime():
    from ponyc_tpu import Runtime
    rt = Runtime(_opts())
    rt.declare(ring.RingNode, 8)
    with pytest.raises(RuntimeError, match="start"):
        costs.capture(rt)


def test_profile_device_writes_trace(plain_rt, tmp_path):
    rt, ids = plain_rt
    rt.send(int(ids[0]), ring.RingNode.token, 500)
    path = rt.profile_device(windows=2, path=str(tmp_path / "xp"),
                             ticks=8)
    assert path == str(tmp_path / "xp")
    assert os.path.isdir(path)
    # the traced windows really advanced the world
    assert rt.steps_run > 0


# -------------------------------------------------- modelled vs measured

def test_record_probe_agrees_with_model_on_cpu():
    """Acceptance: the measured bytes/msg of the canonical record move
    lands on the model's unpacked bytes within tolerance on CPU."""
    opts = _opts()
    probe = costs.record_move_probe(opts)
    from ponyc_tpu.ops.megakernel import (modelled_bytes_per_msg,
                                          record_words)
    assert probe["record_words"] == record_words(opts)
    modelled = modelled_bytes_per_msg(opts, 0.0)["unpacked_bytes"]
    assert probe["bytes_per_msg"] is not None
    assert (abs(probe["bytes_per_msg"] - modelled) / modelled
            <= costs.DIVERGENCE_TOLERANCE)


def test_measured_block_clean_world_does_not_diverge(plain_rt, capsys):
    rt, _ = plain_rt
    blk = costs.measured_block(rt)
    div = blk["model_divergence"]
    assert div["diverged"] is False
    assert div["ratio"] == pytest.approx(1.0, rel=0.5)
    assert blk["modelled"]["unpacked_bytes"] > 0
    assert "MODEL DIVERGENCE" not in capsys.readouterr().err
    # the judged block replaces the bare capture memo
    assert rt._costs is blk


def test_seeded_divergence_trips_the_flag(plain_rt, capsys):
    """A model that prices the record at 10x reality must be called
    out — loudly (stderr) and in the block itself."""
    rt, _ = plain_rt
    fake = {"record_words": 2, "unpacked_bytes": 80.0,
            "packed_bytes": 40.0, "ratio": 2.0, "escape_rate": 0.0}
    blk = costs.measured_block(rt, modelled=fake)
    assert blk["model_divergence"]["diverged"] is True
    assert "MODEL DIVERGENCE" in capsys.readouterr().err


def test_divergence_verdict_edges():
    assert costs.divergence(8.0, 8.1)["diverged"] is False
    assert costs.divergence(8.0, 20.0)["diverged"] is True
    # absence of evidence is not divergence
    none = costs.divergence(8.0, None)
    assert none["diverged"] is False and none["ratio"] is None
    assert costs.divergence(0.0, 8.0)["diverged"] is False


# -------------------------------------------------- operational surfaces

def test_metrics_exports_phases_and_measured_gauges():
    from ponyc_tpu import metrics
    rt, ids = _ring(analysis=1, cost_capture=True)
    rt.send(int(ids[0]), ring.RingNode.token, 20)
    rt.run()
    snap = metrics.snapshot(rt)
    assert snap["phases"]["dispatch"] == 20
    text = metrics.prometheus_text(snap)
    parsed = metrics.parse_prometheus(text)
    assert parsed[("pony_tpu_phase_work_total",
                   (("phase", "delivery"),))] == 20
    assert parsed[("pony_tpu_measured_bytes_accessed",
                   (("executable", "step"),))] > 0
    assert parsed[("pony_tpu_model_divergence", ())] == 0
    rt.stop()


def test_postmortem_carries_and_renders_measured(cc_rt):
    from ponyc_tpu.flight import render_postmortem
    rt, _ = cc_rt
    pm = rt._flight.postmortem("manual")
    assert pm["measured"] is rt._costs
    text = render_postmortem(pm)
    assert "measured [step]" in text
    assert "model vs measured" in text
    # Pre-PR-19 postmortems have no "measured" key: render degrades.
    del pm["measured"]
    assert "measured [" not in render_postmortem(pm)


def test_doctor_renders_measured_from_postmortem_file(cc_rt, tmp_path,
                                                      capsys):
    from ponyc_tpu.__main__ import cmd_doctor
    rt, _ = cc_rt
    path = str(tmp_path / "w.postmortem.json")
    rt._flight.dump("manual", path=path, out=open(os.devnull, "w"))
    assert cmd_doctor(["--postmortem", path]) == 0
    assert "measured [step]" in capsys.readouterr().out


# --------------------------------------------------------- the scoreboard

def _hist_row(value, **kw):
    row = {"metric": "ubench_actor_messages_per_sec",
           "unit": "msgs/sec/chip", "value": value,
           "vs_baseline": round(value / 3.0e8, 3), "platform": "cpu",
           "delivery": "plan", "actors": 256}
    row.update(kw)
    return row


def _write_history(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_perf_check_detects_injected_regression(tmp_path):
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    _write_history(hist, [_hist_row(1.0e6), _hist_row(1.1e6),
                          _hist_row(4.0e5)])
    rows = costs.load_history(str(tmp_path))
    assert len(rows) == 3
    verdict = costs.perf_check(rows)
    assert not verdict["ok"]
    assert verdict["regressions"][0]["latest"] == 4.0e5
    text = costs.render_perf(rows, verdict)
    assert "REGRESSION" in text and "check: FAIL" in text


def test_perf_check_groups_like_with_like(tmp_path):
    """A CPU-fallback round after a TPU round is NOT a regression —
    and neither is a small smoke run after a 1M-actor headline."""
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    _write_history(hist, [
        _hist_row(1.7e7, platform="tpu", actors=1 << 20),
        _hist_row(4.0e6, platform="cpu", actors=131072,
                  tpu_init_error="probe timeout"),
        _hist_row(9.0e5, platform="cpu", actors=256),
    ])
    verdict = costs.perf_check(costs.load_history(str(tmp_path)))
    assert verdict["ok"], verdict["regressions"]


def test_perf_check_flags_model_divergence(tmp_path):
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    _write_history(hist, [_hist_row(1.0e6, model_divergence=True,
                                    divergence_ratio=3.2)])
    verdict = costs.perf_check(costs.load_history(str(tmp_path)))
    assert not verdict["ok"] and verdict["divergent"]


def test_load_history_reads_bench_round_wrappers(tmp_path):
    """BENCH_r*.json is the driver wrapper {n, cmd, rc, tail, parsed}
    — rows come from `parsed`; a failed round (parsed null) skips."""
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "cmd": "x", "rc": 1, "tail": "boom", "parsed": None}))
    (tmp_path / "BENCH_r02.json").write_text(json.dumps(
        {"n": 2, "cmd": "x", "rc": 0, "parsed": {
            "metric": "ubench_actor_messages_per_sec",
            "value": 1.7e7, "unit": "msgs/sec/chip",
            "vs_baseline": 0.058,
            "detail": {"platform": "tpu", "actors": 1 << 20,
                       "delivery": "plan"},
            "measured": {"executables": {"step": {
                "bytes_accessed": 123.0}},
                "model_divergence": {"ratio": 1.0,
                                     "diverged": False}}}}))
    rows = costs.load_history(str(tmp_path))
    assert len(rows) == 1
    assert rows[0]["source"] == "BENCH_r02.json"
    assert rows[0]["platform"] == "tpu"
    assert rows[0]["measured_step_bytes"] == 123.0


def test_perf_check_passes_repo_real_trajectory():
    """Acceptance: the committed BENCH_r*.json rounds (plus any real
    BENCH_HISTORY.jsonl) must pass the gate — the TPU round and the
    CPU-fallback rounds are different groups, and the CPU trajectory
    is monotone."""
    rows = costs.load_history(ROOT)
    assert rows, "committed BENCH_r*.json rounds should parse"
    verdict = costs.perf_check(rows)
    assert verdict["ok"], verdict["regressions"]


def test_perf_cli_exit_codes(tmp_path, capsys):
    from ponyc_tpu.__main__ import cmd_perf
    # no history at all → 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cmd_perf(["--root", str(empty)]) == 2
    # injected regression → 1 with --check, 0 without
    _write_history(tmp_path / "BENCH_HISTORY.jsonl",
                   [_hist_row(1.0e6), _hist_row(4.0e5)])
    assert cmd_perf(["--root", str(tmp_path)]) == 0
    assert cmd_perf(["--root", str(tmp_path), "--check"]) == 1
    # a loose tolerance waves the same history through
    assert cmd_perf(["--root", str(tmp_path), "--check",
                     "--tolerance", "0.9"]) == 0
    # real repo trajectory passes the CI gate
    assert cmd_perf(["--root", ROOT, "--check"]) == 0
    out = capsys.readouterr().out
    assert "scoreboard" in out and "north star" in out
    # usage errors → 2
    assert cmd_perf(["--frobnicate"]) == 2
    assert cmd_perf(["--tolerance"]) == 2
