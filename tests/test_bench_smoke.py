"""Fast bench-wiring smoke test: the fused measurement window driven
through delivery="auto" at toy scale, so bench.py's harness (counter
verification + the tuning record every run publishes) can never silently
rot between the rare on-chip campaigns (the round-3→5 lesson: the A/B
machinery sat unmeasured for three rounds because nothing cheap
exercised it)."""

import argparse

import pytest


def _args(**kw):
    base = dict(actors=64, ticks=8, fuse=4, warmup=1, cap=4, pings=2,
                delivery="auto", fused="off", pallas="off",
                lat_actors=64, lat_ticks=40)
    base.update(kw)
    return argparse.Namespace(**base)


@pytest.fixture()
def bench_mod(tmp_path, monkeypatch):
    monkeypatch.setenv("PONY_TPU_TUNING_CACHE", str(tmp_path / "tuning"))
    monkeypatch.setenv("PONY_TPU_COMPILE_CACHE", "off")
    import bench
    return bench


def test_bench_ubench_auto_smoke(bench_mod):
    # --skip-measured: this test is about tuning, not the observatory
    # (covered below) — skip the capture to keep the smoke fast.
    ub = bench_mod.bench_ubench(_args(skip_measured=True))
    assert ub["measured"] == {"skipped": True}
    # The fused window really advanced the world: every tick dispatched
    # actors×pings behaviours (the headline metric's denominator).
    assert ub["processed_counter_ok"]
    assert ub["msgs_per_sec"] > 0
    assert ub["ticks"] == 8 and ub["fuse"] == 4
    # auto resolved to a concrete formulation...
    assert ub["delivery"] in ("plan", "cosort")
    # ...and published a well-formed tuning record: every eligible
    # variant measured in-executable, the minimum selected.
    rec = ub["tuning"]
    assert rec["source"] in ("calibrated", "cache")
    assert set(rec["table"]) == {"plan", "cosort"}
    timed = {k: v for k, v in rec["table"].items() if v is not None}
    assert timed, "no variant produced a timing"
    assert all(v > 0 for v in timed.values())
    assert rec["winner"] in timed
    assert rec["table"][rec["winner"]] == min(timed.values())
    assert rec["chosen"]["delivery"] == ub["delivery"]


def test_bench_forced_delivery_skips_tuning(bench_mod):
    ub = bench_mod.bench_ubench(_args(delivery="plan",
                                      skip_measured=True))
    assert ub["processed_counter_ok"]
    assert ub["delivery"] == "plan"
    # No formulation was "auto" → no calibration record. (The default
    # quiesce_interval="auto" still resolves its initial window through
    # the cache machinery — a lookup, not a calibration — and is the
    # only key allowed to appear.)
    rec = ub["tuning"]
    assert rec is None or set(rec) == {"quiesce_interval"}, rec
    if rec is not None:
        assert rec["quiesce_interval"]["source"] in ("default", "cache")


def test_bench_latency_uses_resolved_formulation(bench_mod):
    lat = bench_mod.bench_latency(_args(), delivery="cosort", fused=False)
    assert lat["hops_ok"]
    assert lat["p50_us"] > 0


def test_bench_telemetry_block(bench_mod):
    """The BENCH json's attribution block (per-behaviour profiler at
    analysis=1): runs attribute exactly, queue-wait percentiles and gc
    stats ride along."""
    t = bench_mod.bench_telemetry(_args(), delivery="plan", fused=False)
    assert t["attribution_ok"]
    # actors × pings × ticks behaviours dispatched, all attributed
    assert t["behaviours"]["Pinger.ping"]["runs"] \
        == t["actors"] * 2 * t["ticks"]
    assert t["queue_wait_ticks"]["Pinger"]["p50"] >= 1
    assert "gc_passes" in t and "mute_ticks" in t


def test_bench_ubench_emits_measured_block(bench_mod):
    """Every BENCH json carries a `measured` block (ISSUE 19): XLA's
    cost/memory analysis of the run's real executables, the record
    probe, and the model_divergence verdict against the modelled
    bytes/msg."""
    ub = bench_mod.bench_ubench(_args(xprof=0))
    m = ub["measured"]
    assert "error" not in m
    assert m["executables"]["step"]["bytes_accessed"] > 0
    assert m["executables"]["window"]["bytes_accessed"] > 0
    assert m["modelled"] == ub["bytes_model"]
    assert m["model_divergence"]["diverged"] is False


def test_bench_perf_smoke_scoreboard_row(bench_mod, tmp_path, capsys,
                                         monkeypatch):
    """--perf-smoke (ISSUE 19): the observatory end-to-end — json with
    the measured block on stdout, one flattened scoreboard row
    appended to BENCH_HISTORY.jsonl, exit code 0."""
    import json
    hist = tmp_path / "BENCH_HISTORY.jsonl"
    monkeypatch.setattr(bench_mod, "HISTORY_PATH", str(hist))
    rc = bench_mod.bench_perf_smoke(_args(xprof=0, platform="cpu"))
    assert rc == 0
    result = json.loads(capsys.readouterr().out.splitlines()[-1])
    assert result["detail"]["perf_smoke"] is True
    assert result["measured"]["model_divergence"]["diverged"] is False
    assert result["history_path"] == str(hist)
    rows = [json.loads(ln) for ln in hist.read_text().splitlines()]
    assert len(rows) == 1
    assert rows[0]["value"] == result["value"]
    assert rows[0]["measured_step_bytes"] \
        == result["measured"]["executables"]["step"]["bytes_accessed"]
    # and the perf CLI ingests the row it just wrote
    from ponyc_tpu import costs
    loaded = costs.load_history(str(tmp_path))
    assert len(loaded) == 1 and loaded[0]["value"] == result["value"]
    assert costs.perf_check(loaded)["ok"]


def test_bench_trace_smoke_block(bench_mod):
    """The --trace-smoke `tracing` block (causal tracing, PROFILE.md
    §10): one sampled injection reassembles with consistent span
    ticks — attribution_ok style, recorded by every bench that opts
    in."""
    t = bench_mod.bench_trace_smoke(_args(), delivery="plan",
                                    fused=False)
    assert t["spans_ok"] and t["span_count_ok"]
    assert t["traces"] == 1
    assert t["spans"] == 25              # inject + one span per hop
    assert t["max_latency_ticks"] >= 24
    assert t["analysis"] == 3 and t["trace_sample"] == 1


def test_bench_metrics_smoke_block(bench_mod):
    """The --metrics-smoke `metrics` block (PROFILE.md §11): a real
    HTTP scrape-under-load round-trip — /healthz answers mid-run and
    the final Prometheus counters equal Runtime.profile()."""
    m = bench_mod.bench_metrics_smoke(_args(), delivery="plan",
                                      fused=False)
    assert m["scrape_ok"], m
    assert m["counters_match"], m
    assert m["live_scrapes"] >= 1
    assert m["final_status"] == "ok"
    assert m["port"] == 0                # ephemeral requested


def test_bench_checkpoint_smoke_block(bench_mod):
    """The --checkpoint-smoke `checkpoint` block (durable worlds,
    PROFILE.md §12): a cadence-checkpointed run keeps the unfaulted
    outcome, the ring stays intact+bounded, and a restore-fast-start
    reproduces the soaked world."""
    c = bench_mod.bench_checkpoint_smoke(_args(checkpoint_hops=5000),
                                         delivery="plan", fused=False)
    assert c["equal_ok"], c
    assert c["ring_intact_ok"], c
    assert c["checkpoints"] >= 1
    assert 1 <= c["ring_files"] <= 3
    assert c["write_failures"] == 0
    assert c["capture_ms_mean"] >= 0
    assert c["restore_fast_start_s"] < 30


def test_bench_serve_smoke_block(bench_mod):
    """The --serve-smoke `serving` block (ISSUE 9, PROFILE.md §13):
    the real socket front door under ~2x-capacity concurrent demand —
    requests are shed at the edge with BUSY, p50/p99 of ADMITTED
    requests recorded, every frame answered, and the mailbox rings
    never hit a sticky-fail state."""
    s = bench_mod.bench_serve_smoke(_args(), delivery="plan",
                                    fused=False)
    assert s["rings_ok"], s              # no SpillOverflow/SpawnFail
    assert s["rings_sticky_fail"] == {}
    assert s["drained_ok"], s
    assert s["shed_ok"], s               # overload really shed BUSY
    assert s["replies_accounted"], s     # zero unanswered requests
    assert s["ok"] > 0 and s["busy"] > 0
    assert s["bad_value"] == 0
    assert s["p99_us"] > s["p50_us"] > 0
    assert s["goodput_rps"] > 0
    assert s["overload_x"] >= 2.0        # sustained >= 2x overload
    assert s["admission"]["limit"] >= 1
    assert s["batches"] >= 1 and s["submitted"] >= s["ok"]


def test_tpu_env_details_shape(bench_mod):
    """The tpu_init_error env snapshot: JSON-serialisable, secrets
    filtered, libtpu presence probed."""
    import json as _json
    d = bench_mod.tpu_env_details()
    _json.dumps(d)                       # must serialise
    assert "libtpu_importable" in d
    assert all("KEY" not in k and "TOKEN" not in k for k in d["env"])


def test_tpu_init_postmortem_embeds_and_diagnoses(bench_mod, capsys):
    """On tpu_init_error the BENCH json carries the flight-recorder
    postmortem (probe timeline + env snapshot) and the doctor's
    one-line diagnosis lands on stderr — CPU-fallback rounds carry
    their stall evidence."""
    import json as _json
    tl = [{"attempt": 1, "timeout_s": 180.0, "t_s": 181.0,
           "error": "jax.devices() did not return within 180s"}]
    pm = bench_mod.tpu_init_postmortem(tl)
    _json.dumps(pm)                      # BENCH json embeddable
    assert pm["reason"] == "tpu_init_failed"
    assert pm["probe_timeline"] == tl
    assert "libtpu_importable" in pm["env"]
    err = capsys.readouterr().err
    assert "doctor: STALLED: TPU backend init failed" in err


def test_tristate_parsing(bench_mod):
    assert bench_mod.tristate("auto") == "auto"
    assert bench_mod.tristate("on") is True
    assert bench_mod.tristate("1") is True
    assert bench_mod.tristate("off") is False
    assert bench_mod.tristate("0") is False


def test_bench_kernel_smoke_block(bench_mod, monkeypatch):
    """The --kernel-smoke `kernel` block (PR 11): the same seeded world
    through the XLA window and the persistent megakernel must agree
    bit-for-bit, both variants must produce a timing, and the bandwidth
    diet must hit the ISSUE acceptance bar (ratio >= 1.8) on the
    smoke's clean-payload traffic. On CPU the kernel runs interpreted
    and the block says so."""
    monkeypatch.delenv("PONY_TPU_MEGA_AUTO", raising=False)
    k = bench_mod.bench_kernel_smoke(_args(actors=16, ticks=4, fuse=2))
    assert k["equal_ok"], k["mismatched"]
    assert k["tick_ms"]["plan"] > 0
    assert k["tick_ms"]["pallas_mega"] > 0
    bm = k["bytes_per_msg"]
    assert bm["ratio"] >= 1.8
    assert bm["packed_bytes"] < bm["unpacked_bytes"]
    import jax
    if jax.default_backend() != "tpu":
        assert k["interpret"] is True


def test_bench_ubench_records_packed_bytes(bench_mod):
    """Every run — not just --kernel-smoke ones — carries the packed
    record width so the standing telemetry can price msgs/s in bytes."""
    ub = bench_mod.bench_ubench(_args(ticks=4, fuse=2,
                                      skip_measured=True))
    bm = ub["bytes_model"]
    assert ub["packed_bytes_per_msg"] == bm["packed_bytes"] > 0
    assert bm["record_words"] == 2          # 1 target + msg_words=1
    # ubench's ~2^30 hops counters escape the int16 lanes: the model
    # must report the honest measured rate, not assume clean traffic.
    assert 0.0 <= bm["escape_rate"] <= 1.0


def test_cpu_fallback_policy(bench_mod, monkeypatch):
    """--no-fallback beats the legacy env kill switch; default stays
    allow (a degraded-but-recorded run beats no record at all)."""
    monkeypatch.delenv("PONY_TPU_BENCH_ALLOW_CPU", raising=False)
    assert bench_mod.cpu_fallback_allowed(False) is True
    assert bench_mod.cpu_fallback_allowed(True) is False
    monkeypatch.setenv("PONY_TPU_BENCH_ALLOW_CPU", "0")
    assert bench_mod.cpu_fallback_allowed(False) is False
