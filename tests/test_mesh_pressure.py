"""Cross-shard pressure paths on the 8-virtual-device mesh.

≙ the reference's backpressure invariants under contention
(mute/unmute walks, scheduler.c:1478-1635; bounded queues are the
divergence — overflow spills are finite and their exhaustion is fatal).
These tests force the paths a quiet mesh never takes: all_to_all bucket
overflow → route spill → sender mute → retry → unmute; receiver-side
overflow spill across shards; and the spill-overflow abort.
"""

import dataclasses

import numpy as np
import pytest

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.runtime.runtime import SpillOverflowError


@actor
class Burst:
    """Sends one message per tick to a fixed target, `left` times."""
    out: Ref
    left: I32
    MAX_SENDS = 2

    @behaviour
    def go(self, st, _: I32):
        alive = st["left"] > 0
        self.send(st["out"], Sink.recv, 1, when=alive)
        self.send(self.actor_id, Burst.go, 0, when=st["left"] > 1)
        return {**st, "left": st["left"] - 1}


@actor
class Sink:
    got: I32

    @behaviour
    def recv(self, st, v: I32):
        return {**st, "got": st["got"] + v}


def _run_pressure(opts, n_src=48, items=4):
    """n_src senders spread over all shards flood ONE sink on shard 0."""
    rt = Runtime(opts)
    rt.declare(Burst, n_src).declare(Sink, 4)
    rt.start()
    sink = rt.spawn(Sink)
    srcs = rt.spawn_many(Burst, n_src, out=int(sink), left=items)
    for s in srcs:
        rt.send(int(s), Burst.go, 0)
    return rt, sink, srcs


def test_route_bucket_overflow_spills_mutes_and_recovers():
    # Worst-case fan-in across the mesh: every shard's senders target one
    # shard; per-tick emissions exceed the all_to_all bucket, so messages
    # park in route-spill and their senders mute (engine._route pressure
    # branch). Everything must still arrive exactly once.
    opts = RuntimeOptions(mailbox_cap=4, batch=1, max_sends=2, msg_words=2,
                          mesh_shards=4, spill_cap=256, inject_slots=64,
                          quiesce_interval=1, route_bucket=8)
    rt, sink, srcs = _run_pressure(opts, n_src=48, items=4)
    saw_rspill = False
    saw_muted = False
    for _ in range(400):
        rt.run(max_steps=1)
        saw_rspill = saw_rspill or rt.counter("rspill_count") > 0
        saw_muted = saw_muted or bool(np.asarray(rt.state.muted).any())
        if rt.state_of(int(sink))["got"] == 48 * 4:
            break
    assert rt.state_of(int(sink))["got"] == 48 * 4
    assert saw_rspill, "bucket overflow never engaged the route spill"
    assert saw_muted, "pressure never muted a sender"
    assert rt.counter("n_mutes") > 0
    # Quiescent end state: every sender released again (unmute pass).
    rt.run(max_steps=50)
    assert not np.asarray(rt.state.muted).any()
    assert rt.counter("rspill_count") == 0


def test_receiver_spill_crosses_shards_and_drains():
    # Bucket large enough (big spill_cap ⇒ big bucket) that routing
    # passes everything through; the RECEIVER mailbox (cap 4) overflows
    # instead, exercising the delivery spill + mute on a mesh.
    opts = RuntimeOptions(mailbox_cap=4, batch=2, max_sends=2, msg_words=2,
                          mesh_shards=4, spill_cap=2048, inject_slots=64)
    rt, sink, srcs = _run_pressure(opts, n_src=32, items=4)
    saw_dspill = False
    for _ in range(400):
        rt.run(max_steps=1)
        saw_dspill = saw_dspill or rt.counter("dspill_count") > 0
        if rt.state_of(int(sink))["got"] == 32 * 4:
            break
    assert rt.state_of(int(sink))["got"] == 32 * 4
    assert saw_dspill, "receiver overflow never engaged the delivery spill"
    rt.run(max_steps=50)
    assert not np.asarray(rt.state.muted).any()
    assert rt.counter("dspill_count") == 0


def test_spill_overflow_aborts_on_mesh():
    # spill_cap far below the one-tick reject volume: the bounded spill
    # exhausts and the runtime must fail loudly (SpillOverflowError),
    # not drop messages.
    opts = RuntimeOptions(mailbox_cap=4, batch=1, max_sends=2, msg_words=2,
                          mesh_shards=4, spill_cap=4, inject_slots=256,
                          overload_threshold=10.0)  # mute never triggers
    rt = Runtime(opts)
    rt.declare(Burst, 64).declare(Sink, 4)
    rt.start()
    sink = rt.spawn(Sink)
    srcs = rt.spawn_many(Burst, 64, out=int(sink), left=8)
    for s in srcs:
        rt.send(int(s), Burst.go, 0)
    with pytest.raises(SpillOverflowError):
        rt.run(max_steps=200)


def test_mesh_serialise_roundtrip_under_pressure(tmp_path):
    # Snapshot mid-pressure (spills populated, senders muted), restore
    # into a fresh runtime, and finish: nothing lost, nothing doubled.
    from ponyc_tpu import serialise

    opts = RuntimeOptions(mailbox_cap=4, batch=1, max_sends=2, msg_words=2,
                          mesh_shards=4, spill_cap=256, inject_slots=64)
    rt, sink, srcs = _run_pressure(opts, n_src=48, items=4)
    for _ in range(6):
        rt.run(max_steps=1)
    got_mid = rt.state_of(int(sink))["got"]
    assert got_mid < 48 * 4
    path = str(tmp_path / "mesh_pressure.npz")
    serialise.save(rt, path)

    rt2 = Runtime(opts)
    rt2.declare(Burst, 48).declare(Sink, 4)
    rt2.start()
    serialise.restore(rt2, path)
    assert rt2.state_of(int(sink))["got"] == got_mid
    rt2.run(max_steps=400)
    assert rt2.state_of(int(sink))["got"] == 48 * 4
    assert not np.asarray(rt2.state.muted).any()


def test_programmatic_backpressure_on_mesh():
    """apply_backpressure on a sharded world: senders on EVERY shard mute
    when their sends target the pressured (remote) receiver, and release
    after the host clears it (the pressured column shards with the actor
    axis). mailbox_cap is large enough that occupancy muting
    (overload_occ) can never fire — any mute is the programmatic path."""
    opts = RuntimeOptions(mailbox_cap=64, batch=4, max_sends=2,
                          msg_words=2, mesh_shards=4, spill_cap=512,
                          inject_slots=64, quiesce_interval=1)
    rt, sink, srcs = _run_pressure(opts, n_src=16, items=40)
    inj = rt._drain_inject()
    st, aux = rt._step(rt.state, *inj)
    inj = rt._empty_inject
    st, aux = rt._step(st, *inj)
    rt.state = st
    assert not np.asarray(st.muted).any(), "no pressure yet"

    rt.apply_backpressure([int(sink)])
    st = rt.state
    for _ in range(3):
        st, aux = rt._step(st, *inj)
    rt.state = st
    muted = np.asarray(st.muted)
    occ = int(np.asarray(st.tail - st.head)[int(sink)])
    assert muted.any(), "pressured receiver must mute senders"
    assert occ <= rt.opts.overload_occ, \
        "mute was pressure-driven, not occupancy-driven"
    # The pressure signal must cross the mesh: some muted sender lives on
    # a different shard than the sink (ids are shard-major: shard = id //
    # n_local).
    n_local = rt.program.n_local
    sink_shard = int(sink) // n_local
    muted_shards = set(int(i) // n_local for i in np.nonzero(muted)[0])
    assert muted_shards - {sink_shard}, \
        f"only shard {sink_shard} muted: {muted_shards}"

    rt.release_backpressure([int(sink)])
    assert rt.run(max_steps=4000) == 0
    assert rt.state_of(int(sink))["got"] == 16 * 40
    assert not np.asarray(rt.state.muted).any()
