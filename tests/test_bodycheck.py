"""The behaviour-body source analyzer (ponyc_tpu/lint/bodycheck.py ≙
the reference's syntactic body checks: safeto.c + verify/fun.c):
AST rules R6–R9 with source-precise findings, the broken-fixture
corpus, the three suppression levels, path/dir CLI targets, the
github output format, and the full-lint selftest sweep over examples/
and ponyc_tpu/models/ (zero findings — tier-1)."""

import importlib
import json
import os
import subprocess
import sys
import time

import pytest

from ponyc_tpu.lint import (check_path, check_paths, check_source,
                            lint_module, lint_types)
from ponyc_tpu.lint.bodycheck import check_types, parse_module

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXDIR = os.path.join(ROOT, "tests", "fixtures", "bodycheck")
BROKEN = os.path.join(FIXDIR, "broken_bodies.py")
SUPPRESSED = os.path.join(FIXDIR, "suppressed_ok.py")


def marks_of(path):
    """{mark id: 1-based line} from `# MARK:<id>` fixture comments."""
    out = {}
    with open(path) as f:
        for i, line in enumerate(f, 1):
            if "MARK:" in line:
                out[line.split("MARK:")[1].strip()] = i
    return out


def by_rule(findings):
    out = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


# ---- the broken-fixture corpus: exact rule ids + line numbers ------------

EXPECTED_MARKS = {
    "r6-if": "R6", "r6-and": "R6", "r6-ternary": "R6", "r6-not": "R6",
    "r6-chain": "R6", "r6-assert": "R6", "r6-for": "R6",
    "r6-while": "R6",
    "r7-for-send": "R7", "r7-while-exit": "R7", "r7-falloff": "R7",
    "r8-read-typo": "R8", "r8-write-typo": "R8", "r8-val-write": "R8",
    "r8-mut-dropped": "R8", "r8-missing": "R8", "r8-self-attr": "R8",
    "r9-print": "R9", "r9-nprandom": "R9", "r9-time": "R9",
    "r9-capture": "R9", "r9-move": "R9", "r9-free-use": "R9",
}


def test_fixture_corpus_flags_every_seeded_defect_at_exact_lines():
    marks = marks_of(BROKEN)
    assert set(EXPECTED_MARKS) <= set(marks), "fixture marks drifted"
    findings = check_path(BROKEN)
    got = {(f.rule, f.line) for f in findings}
    for mark, rule in EXPECTED_MARKS.items():
        assert (rule, marks[mark]) in got, (
            f"{mark}: expected {rule} at {BROKEN}:{marks[mark]}; got "
            + "\n".join(str(f) for f in findings))
    assert all(f.file == BROKEN for f in findings)
    assert all(f.col and f.col >= 1 for f in findings)


def test_fixture_corpus_is_pure_ast_no_import_no_jax():
    # The fixture imports a module that does not exist: importing it
    # can only raise — the analyzer must never try.
    with pytest.raises(ImportError):
        importlib.import_module("a_module_that_does_not_exist_anywhere")
    t0 = time.perf_counter()
    findings = check_path(BROKEN)
    dt = time.perf_counter() - t0
    assert findings, "corpus produced no findings"
    assert "broken_bodies" not in sys.modules
    assert dt < 0.1, f"pure-AST analysis took {dt * 1000:.1f} ms"


def test_severities_split_error_vs_warning():
    sev = {(f.rule, f.severity) for f in check_path(BROKEN)}
    assert ("R6", "error") in sev            # dies at trace
    assert ("R7", "error") in sev            # non-static send count
    assert ("R7", "warning") in sev          # while-loop effect
    assert ("R8", "error") in sev            # key typo
    assert ("R8", "warning") in sev          # val write / dropped mut
    assert ("R9", "error") in sev            # use-after-move
    assert ("R9", "warning") in sev          # host impurity


def test_unparseable_source_reports_r0_not_crash():
    fs = check_source("def broken(:\n", "bad.py")
    assert len(fs) == 1 and fs[0].rule == "R0"
    assert fs[0].severity == "error" and fs[0].line == 1


# ---- suppressions (all three levels, both fixture and API) ---------------

def test_suppressed_fixture_reports_zero_findings():
    assert check_path(SUPPRESSED) == []


def test_suppressions_visible_with_include_suppressed():
    with open(SUPPRESSED) as f:
        src = f.read()
    kept = check_source(src, SUPPRESSED, include_suppressed=True)
    assert any(f.rule == "R6" for f in kept)
    assert any(f.rule == "R9" for f in kept)     # the bare line ignore


def test_line_level_suppression_scopes_to_named_rules():
    src = (
        "from ponyc_tpu import I32, actor, behaviour\n"
        "@actor\n"
        "class A:\n"
        "    n: I32\n"
        "    @behaviour\n"
        "    def go(self, st, v: I32):\n"
        "        if v > 0:              # lint: ignore[R8]\n"
        "            return st\n"
        "        return st\n")
    # The comment names R8 only: the R6 on that line survives.
    fs = check_source(src, "scoped.py")
    assert [f.rule for f in fs] == ["R6"]


# ---- R6 details ----------------------------------------------------------

def _one_type(body, fields="n: I32", host=False, extra=""):
    return (
        "from ponyc_tpu import Blob, BlobVal, I32, Iso, Ref, Val, "
        "actor, behaviour\n"
        "@actor\n"
        "class T:\n"
        + (f"    HOST = True\n" if host else "")
        + f"    {fields}\n"
        + extra
        + "    @behaviour\n"
        + body)


def test_r6_host_behaviours_branch_freely():
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        if v > 0:\n"
        "            print('host actors run real python')\n"
        "        return st\n", host=True)
    assert check_source(src, "h.py") == []


def test_r6_untainted_python_control_flow_is_fine():
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        acc = st['n']\n"
        "        for i in range(4):\n"
        "            acc = acc + i\n"
        "        return {**st, 'n': acc}\n")
    assert check_source(src, "ok.py") == []


def test_r6_taint_flows_through_assignment():
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        derived = st['n'] * 2 + v\n"
        "        if derived:\n"
        "            return st\n"
        "        return st\n")
    fs = check_source(src, "t.py")
    assert [f.rule for f in fs] == ["R6"] and fs[0].line == 8


def test_r6_rebinding_clears_taint():
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        k = st['n']\n"
        "        k = 3\n"
        "        if k:\n"
        "            return st\n"
        "        return st\n")
    assert check_source(src, "t.py") == []


# ---- R7 details ----------------------------------------------------------

def test_r7_static_range_effects_are_fine():
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        for i in range(3):\n"
        "            self.send(st['n'], T.go, v, when=v > i)\n"
        "        return st\n")
    assert [f.rule for f in check_source(src, "t.py")] == []


def test_r7_effect_in_nested_function_warns():
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        def body(i, carry):\n"
        "            self.send(st['n'], T.go, carry)\n"
        "            return carry\n"
        "        return st\n")
    fs = check_source(src, "t.py")
    assert [f.rule for f in fs] == ["R7"]
    assert fs[0].severity == "warning" and "nested" in fs[0].message


def test_r7_bare_return_is_flagged():
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        return\n")
    fs = check_source(src, "t.py")
    assert [f.rule for f in fs] == ["R7"] and fs[0].severity == "error"


def test_r7_branchy_termination_analysis():
    # if/else with both arms returning: fine.
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        if True:\n"
        "            return st\n"
        "        else:\n"
        "            return st\n")
    assert check_source(src, "t.py") == []
    # if without else falling through: flagged.
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        if True:\n"
        "            return st\n")
    fs = check_source(src, "t.py")
    assert [f.rule for f in fs] == ["R7"]


# ---- R8 details ----------------------------------------------------------

def test_r8_did_you_mean_names_the_close_field():
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        return {**st, 'count': v}\n", fields="counter: I32")
    fs = check_source(src, "t.py")
    assert len(fs) == 1 and fs[0].rule == "R8"
    assert "did you mean 'counter'" in fs[0].message


def test_r8_st_get_reads_are_checked():
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        x = st.get('bogus')\n"
        "        return st\n")
    fs = check_source(src, "t.py")
    assert [f.rule for f in fs] == ["R8"] and "bogus" in fs[0].message


def test_r8_unknown_base_class_disables_key_checks():
    # Inherited fields are invisible to the AST: no false positives.
    src = ("from ponyc_tpu import I32, actor, behaviour\n"
           "from somewhere import BaseActor\n"
           "class Sub(BaseActor):\n"
           "    @behaviour\n"
           "    def go(self, st, v: I32):\n"
           "        return {**st, 'inherited_field': v}\n")
    assert check_source(src, "t.py") == []


# ---- R9 details ----------------------------------------------------------

def test_r9_freeze_then_broadcast_is_legal():
    # The blob_pipeline idiom: alloc (iso), write, freeze to val, then
    # alias the SAME handle into two sends — legal, val aliases freely;
    # and freeing the consumed iso input is not a use-after-move.
    src = (
        "from ponyc_tpu import Blob, BlobVal, I32, actor, behaviour\n"
        "@actor\n"
        "class T:\n"
        "    n: I32\n"
        "    @behaviour\n"
        "    def go(self, st, b: Blob):\n"
        "        h = self.blob_alloc(length=2)\n"
        "        self.blob_set(h, 0, 1)\n"
        "        s = self.blob_freeze(h)\n"
        "        self.send(st['n'], T.recv, s)\n"
        "        self.send(st['n'], T.recv, s)\n"
        "        self.blob_free(b)\n"
        "        return st\n"
        "    @behaviour\n"
        "    def recv(self, st, s: BlobVal):\n"
        "        return st\n")
    assert check_source(src, "t.py") == []


def test_r9_val_blob_write_flagged():
    src = _one_type(
        "    def go(self, st, b: BlobVal):\n"
        "        self.blob_set(b, 0, 1)\n"
        "        return st\n")
    fs = check_source(src, "t.py")
    assert [f.rule for f in fs] == ["R9"]
    assert "frozen (val)" in fs[0].message


def test_r9_conditional_exclusive_moves_do_not_poison():
    # A move on only ONE arm of a Python-level branch is not a
    # definite move (branch join intersects move sets).
    src = _one_type(
        "    def go(self, st, p: Iso, flag: I32):\n"
        "        cold = 1\n"
        "        if cold:\n"
        "            self.send(st['n'], T.go, p, 0)\n"
        "        else:\n"
        "            self.send(st['n'], T.go, p, 1)\n"
        "        return st\n")
    assert check_source(src, "t.py") == []


def test_r9_global_statement_flagged():
    src = _one_type(
        "    def go(self, st, v: I32):\n"
        "        global W\n"
        "        return st\n")
    fs = check_source(src, "t.py")
    assert [f.rule for f in fs] == ["R9"] and "global" in fs[0].message


# ---- live-type integration (lint_types / lint_module pick R6–R9 up) -----

def _write_mod(tmp_path, name, text):
    p = tmp_path / f"{name}.py"
    p.write_text(text)
    sys.path.insert(0, str(tmp_path))
    return p


def test_check_types_and_lint_types_agree(tmp_path):
    _write_mod(tmp_path, "livemod", _one_type(
        "    def go(self, st, v: I32):\n"
        "        if v > 0:\n"
        "            return st\n"
        "        return st\n"))
    try:
        mod = importlib.import_module("livemod")
        direct = check_types(mod.T)
        merged = lint_types(mod.T)
        assert [f.rule for f in direct] == ["R6"]
        assert direct[0].line == 7 and direct[0].file.endswith(
            "livemod.py")
        # lint_types folds the same finding in with the graph rules
        # (the probe also fails on the branch: R0 reports alongside).
        assert {("R6", 7)} <= {(f.rule, f.line) for f in merged}
        assert any(f.rule == "R0" and f.line for f in merged)
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("livemod", None)


def test_graph_rule_findings_carry_locations(tmp_path):
    _write_mod(tmp_path, "locmod", (
        "from ponyc_tpu import I32, Ref, actor, behaviour\n"
        "@actor\n"
        "class Away:\n"
        "    x: I32\n"
        "    @behaviour\n"
        "    def put(self, st, v: I32):\n"
        "        return {**st, 'x': v}\n"
        "@actor\n"
        "class Alone:\n"
        "    out: Ref\n"
        "    MAX_SENDS = 1\n"
        "    @behaviour\n"
        "    def go(self, st, v: I32):\n"
        "        self.send(st['out'], Away.put, v)\n"
        "        return st\n"))
    try:
        mod = importlib.import_module("locmod")
        fs = lint_types(mod.Alone)          # Away outside the world: R2
        r2 = [f for f in fs if f.rule == "R2"]
        assert r2 and r2[0].file.endswith("locmod.py")
        assert r2[0].line == 12             # the @behaviour def site
        obj = json.loads(r2[0].json_line())
        assert obj["file"].endswith("locmod.py") and obj["line"] == 12
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("locmod", None)


def test_behaviour_level_ignore_on_live_types(tmp_path):
    _write_mod(tmp_path, "bmutedmod", (
        "from ponyc_tpu import I32, actor, behaviour\n"
        "@actor\n"
        "class M:\n"
        "    n: I32\n"
        "    @behaviour(lint_ignore=('R6', 'R0'))\n"
        "    def go(self, st, v: I32):\n"
        "        if v > 0:\n"
        "            return st\n"
        "        return st\n"
        "    @behaviour\n"
        "    def loud(self, st, v: I32):\n"
        "        if v > 0:\n"
        "            return st\n"
        "        return st\n"))
    try:
        mod = importlib.import_module("bmutedmod")
        fs = lint_types(mod.M)
        # Suppression is per-behaviour: go quiet, loud still flagged.
        assert {f.behaviour for f in fs if f.rule == "R6"} == {"loud"}
        kept = lint_types(mod.M, include_suppressed=True)
        assert {f.behaviour for f in kept if f.rule == "R6"} == {
            "go", "loud"}
    finally:
        sys.path.remove(str(tmp_path))
        sys.modules.pop("bmutedmod", None)


# ---- CLI: paths, directories, output formats ----------------------------

def _run_cli(args, cwd=ROOT):
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = ROOT
    return subprocess.run([sys.executable, "-m", "ponyc_tpu"] + args,
                          cwd=str(cwd), env=env, capture_output=True,
                          text=True, timeout=240)


def test_cli_lint_accepts_files_dirs_and_formats(tmp_path):
    rel = os.path.relpath(BROKEN, ROOT)
    # A single broken file: findings, exit 1, file:line in the text.
    r = _run_cli(["lint", rel])
    assert r.returncode == 1, r.stderr[-500:]
    assert f"{rel}:" in r.stdout and "R6" in r.stdout
    assert "lint:" in r.stdout          # summary line
    # JSON: stable keys incl. file/line.
    r = _run_cli(["lint", rel, "--json"])
    objs = [json.loads(line) for line in r.stdout.splitlines()]
    assert all(o["file"] == rel for o in objs)
    assert any(o["rule"] == "R6" and o["line"] for o in objs)
    # GitHub annotations.
    r = _run_cli(["lint", rel, "--format", "github"])
    assert r.returncode == 1
    assert any(line.startswith(f"::error file={rel},line=")
               for line in r.stdout.splitlines()), r.stdout[:400]
    # A directory target sweeps the tree (suppressed fixture rides
    # along clean; the broken one keeps the exit code at 1).
    r = _run_cli(["lint", os.path.relpath(FIXDIR, ROOT)])
    assert r.returncode == 1 and "type(s)" in r.stdout
    # No actor types anywhere: exit 3.
    (tmp_path / "plain.py").write_text("x = 1\n")
    r = _run_cli(["lint", str(tmp_path)])
    assert r.returncode == 3, (r.returncode, r.stderr)
    # Clean actor file: exit 0.
    (tmp_path / "cleanmod.py").write_text(
        "from ponyc_tpu import I32, actor, behaviour\n"
        "@actor\n"
        "class C:\n"
        "    n: I32\n"
        "    @behaviour\n"
        "    def go(self, st, v: I32):\n"
        "        return {**st, 'n': v}\n")
    r = _run_cli(["lint", str(tmp_path / "cleanmod.py")])
    assert r.returncode == 0, (r.returncode, r.stdout, r.stderr)
    assert "clean" in r.stdout


def test_cli_verify_json_carries_locations(tmp_path):
    (tmp_path / "vloc.py").write_text(
        "from ponyc_tpu import I32, Ref, actor, behaviour\n"
        "@actor\n"
        "class S:\n"
        "    x: I32\n"
        "    @behaviour\n"
        "    def put(self, st, v: I32):\n"
        "        return {**st, 'x': v}\n"
        "@actor\n"
        "class Over:\n"
        "    out: Ref['S']\n"
        "    MAX_SENDS = 1\n"
        "    @behaviour\n"
        "    def go(self, st, v: I32):\n"
        "        self.send(st['out'], S.put, v)\n"
        "        self.send(st['out'], S.put, v + 1)\n"
        "        return st\n")
    r = _run_cli(["verify", "vloc", "--json"], cwd=tmp_path)
    assert r.returncode == 1, r.stderr[-500:]
    obj = json.loads(r.stdout.splitlines()[0])
    assert obj["file"].endswith("vloc.py") and obj["line"] == 12


# ---- the selftest sweep: R0–R9 over everything we ship (tier-1) ---------

MODEL_MODULES = ["ring", "ubench", "fanin", "gups", "nbody",
                 "mandelbrot", "records"]


def test_shipped_trees_lint_clean_pure_ast():
    t0 = time.perf_counter()
    findings, n_types, n_beh = check_paths(
        [os.path.join(ROOT, "examples"),
         os.path.join(ROOT, "ponyc_tpu", "models"),
         # host-side observability modules ride the sweep too (CI
         # satellites, PRs 6–7): no behaviours, but the parse + rule
         # walk must stay clean as they grow
         os.path.join(ROOT, "ponyc_tpu", "tracing.py"),
         os.path.join(ROOT, "ponyc_tpu", "flight.py"),
         os.path.join(ROOT, "ponyc_tpu", "metrics.py"),
         # durability layer (ISSUE 8): snapshot/checkpoint machinery,
         # the supervisor, and the chaos harness
         os.path.join(ROOT, "ponyc_tpu", "serialise.py"),
         os.path.join(ROOT, "ponyc_tpu", "supervise.py"),
         os.path.join(ROOT, "ponyc_tpu", "testing.py"),
         # serving front door (ISSUE 9): the ingress tier's actor
         # types (Egress/FrontDoor/ServeWorker) and the load generator
         os.path.join(ROOT, "ponyc_tpu", "serve.py"),
         os.path.join(ROOT, "ponyc_tpu", "loadgen.py"),
         # window megakernel + record codec (PR 11): pure ops module,
         # no behaviours, but the sweep keeps its AST clean as it grows
         os.path.join(ROOT, "ponyc_tpu", "ops", "megakernel.py"),
         # device-cost observatory + perf scoreboard (ISSUE 19)
         os.path.join(ROOT, "ponyc_tpu", "costs.py")])
    dt = time.perf_counter() - t0
    assert findings == [], "\n".join(str(f) for f in findings)
    assert n_types >= 25 and n_beh >= 35
    assert dt < 2.0, f"AST sweep took {dt:.2f}s"


@pytest.mark.parametrize("name", MODEL_MODULES)
def test_models_full_lint_r0_to_r9_clean(name):
    mod = importlib.import_module(f"ponyc_tpu.models.{name}")
    findings = lint_module(mod)
    errors = [f for f in findings if f.severity == "error"]
    assert errors == [], "\n".join(str(f) for f in errors)
    assert findings == [], "\n".join(str(f) for f in findings)
