"""The delivery/dispatch autotuner (ponyc_tpu/tuning.py).

Three properties are pinned:

- "auto" never changes semantics, only speed: a seeded ubench run under
  delivery="auto" produces exactly the totals and per-actor columns of
  the forced formulations (which the differential suite already proves
  agree with the sequential oracle);
- the decision is a deterministic pure function of the timing table
  (minimum tick_ms, ties to the earlier/safer variant, failed variants
  never win);
- the on-disk tuning cache hits on an identical (platform, layout,
  geometry) key, misses on a different one, and a corrupt cache file
  recalibrates instead of erroring the start.
"""

import json

import numpy as np
import pytest

from ponyc_tpu import Runtime, RuntimeOptions, actor, behaviour, I32
from ponyc_tpu import tuning
from ponyc_tpu.models import ubench


def _ub_opts(**kw):
    base = dict(mailbox_cap=4, batch=4, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8, compile_cache="off",
                tuning_cache="off", tuning_ticks=2, tuning_repeats=1)
    base.update(kw)
    return RuntimeOptions(**base)


def _run_ubench(delivery, n=64, pings=2, ticks=5, **kw):
    rt, ids = ubench.build(n, _ub_opts(delivery=delivery, **kw),
                           pings=pings)
    ubench.seed_all(rt, ids, hops=1 << 30, pings=pings)
    st, inj = rt.state, rt._empty_inject
    for _ in range(ticks):
        st, _aux = rt._step(st, *inj)
    rt.state = st
    cols = rt.cohort_state(ubench.Pinger)
    return rt, {"processed": rt.counter("n_processed"),
                "delivered": rt.counter("n_delivered"),
                "pings": np.asarray(cols["pings"])}


# ---------------------------------------------------------------------------
# decision function


def test_decide_picks_minimum():
    assert tuning.decide({"plan": 2.0, "cosort": 1.0}) == "cosort"
    assert tuning.decide({"plan": 0.5, "cosort": 1.0}) == "plan"


def test_decide_breaks_ties_toward_baseline():
    # Equal timings: the EARLIER entry (the safe baseline) wins, so
    # measurement noise can never flip a dead heat to the exotic path.
    assert tuning.decide({"plan": 1.0, "cosort": 1.0}) == "plan"
    assert tuning.decide({"plan": 1.0, "plan+fused": 1.0,
                          "cosort": 1.0}) == "plan"


def test_decide_never_picks_failed_variants():
    assert tuning.decide({"plan": 3.0, "cosort": None}) == "plan"
    assert tuning.decide({"plan": None, "cosort": 2.0}) == "cosort"
    assert tuning.decide({"plan": None, "cosort": None}) is None


def test_decide_is_deterministic_given_injected_timings():
    table = {"plan": 1.7, "cosort": 1.1, "plan+pallas": None,
             "cosort+pallas": 1.1000001}
    for _ in range(5):
        assert tuning.decide(table) == "cosort"


# ---------------------------------------------------------------------------
# variant enumeration


def test_variants_fixed_delivery_is_single():
    rt = Runtime(_ub_opts(delivery="plan"))
    rt.declare(ubench.Pinger, 8)
    rt.program.finalize()
    assert tuning.variants(rt.program, rt.opts) == [
        ("plan", {"delivery": "plan", "pallas": False,
                  "pallas_fused": False})]


def test_variants_auto_delivery_baseline_first():
    rt = Runtime(_ub_opts(delivery="auto"))
    rt.declare(ubench.Pinger, 8)
    rt.program.finalize()
    names = [n for n, _ in tuning.variants(rt.program, rt.opts)]
    assert names == ["plan", "cosort"]


def test_variants_auto_enumerates_megakernel_when_gated_on(monkeypatch):
    """PR 11: with PONY_TPU_MEGA_AUTO=1 (bench.py sets it) delivery=auto
    races the window megakernel too — as a pure-delivery variant, never
    combined with the per-pass pallas kernels it replaces."""
    monkeypatch.setenv("PONY_TPU_MEGA_AUTO", "1")
    rt = Runtime(_ub_opts(delivery="auto"))
    rt.declare(ubench.Pinger, 8)
    rt.program.finalize()
    vs = tuning.variants(rt.program, rt.opts)
    assert [n for n, _ in vs] == ["plan", "cosort", "pallas_mega"]
    mega = dict(vs)["pallas_mega"]
    assert mega == {"delivery": "pallas_mega", "pallas": False,
                    "pallas_fused": False}


def test_tuning_key_version_pinned_v2():
    """The cache-key version must be bumped whenever the variant space
    changes (v2: pallas_mega joined) — a stale v1 record transferring a
    two-way decision into the three-way race would silently skip the
    megakernel forever. Pin it so the bump is a conscious act."""
    rt = Runtime(_ub_opts(delivery="auto"))
    rt.declare(ubench.Pinger, 8)
    rt.program.finalize()
    assert tuning.tuning_key(rt.program, rt.opts)["v"] == 2


def test_variants_fused_auto_skips_ineligible_programs():
    # A blob-pool cohort is ineligible for the fused kernel; with every
    # cohort ineligible, pallas_fused="auto" must not enumerate (or
    # silently measure) a variant that would fall back to the baseline.
    @actor
    class BlobUser:
        n: I32
        MAX_BLOBS = 1

        @behaviour
        def grab(self, st):
            self.blob_alloc(length=1)
            return st

    rt = Runtime(_ub_opts(delivery="plan", pallas_fused="auto",
                          msg_words=2, blob_slots=8, blob_words=4))
    rt.declare(BlobUser, 8)
    rt.program.finalize()
    names = [n for n, _ in tuning.variants(rt.program, rt.opts)]
    assert names == ["plan"]


# ---------------------------------------------------------------------------
# forced-variant equivalence (the "auto never changes semantics" oracle)


def test_auto_matches_forced_variants():
    _, plan = _run_ubench("plan")
    _, cosort = _run_ubench("cosort")
    _, auto = _run_ubench("auto")
    assert plan["processed"] == cosort["processed"] == auto["processed"]
    assert plan["delivered"] == cosort["delivered"] == auto["delivered"]
    np.testing.assert_array_equal(plan["pings"], cosort["pings"])
    np.testing.assert_array_equal(plan["pings"], auto["pings"])


def test_auto_resolves_to_concrete_opts():
    rt, _ = _run_ubench("auto")
    assert rt.opts.delivery in ("plan", "cosort")
    rec = rt.tuning_record
    assert rec["source"] == "calibrated"           # cache is off here
    assert set(rec["table"]) == {"plan", "cosort"}
    assert all(isinstance(v, float) for v in rec["table"].values())
    assert rec["winner"] == tuning.decide(rec["table"],
                                          order=rec["variants"])
    assert rec["chosen"]["delivery"] == rt.opts.delivery


def test_calibration_leaves_runtime_state_untouched():
    # Calibration runs on throwaway copies: a freshly started world must
    # still be empty (no live actors, no queued messages, zero counters).
    rt = Runtime(_ub_opts(delivery="auto"))
    rt.declare(ubench.Pinger, 32)
    rt.start()
    assert rt.counter("n_processed") == 0
    assert rt.counter("n_delivered") == 0
    assert not bool(np.asarray(rt.state.alive).any())
    assert int(np.asarray(rt.state.tail).sum()) == 0
    assert int(np.asarray(rt.state.dspill_count).sum()) == 0


# ---------------------------------------------------------------------------
# tuning cache


def test_cache_miss_then_hit_then_corrupt(tmp_path):
    cdir = str(tmp_path / "tuning")

    _, rec1 = tuning_record_for(cdir)
    assert rec1["source"] == "calibrated"
    path = rec1["cache_path"]
    with open(path) as f:
        stored = json.load(f)
    assert stored["chosen"] == rec1["chosen"]

    _, rec2 = tuning_record_for(cdir)
    assert rec2["source"] == "cache"
    assert rec2["chosen"] == rec1["chosen"]
    assert rec2["table"] == rec1["table"]

    with open(path, "w") as f:
        f.write("{corrupt json!")
    _, rec3 = tuning_record_for(cdir)
    assert rec3["source"] == "calibrated"       # corruption recalibrates
    with open(path) as f:
        assert json.load(f)["chosen"] == rec3["chosen"]   # and rewrites


def tuning_record_for(cdir):
    rt, _ = _run_ubench("auto", tuning_cache=cdir)
    return rt, rt.tuning_record


def test_cache_key_separates_layouts(tmp_path):
    cdir = str(tmp_path / "tuning")
    rt1, _ = _run_ubench("auto", n=64, tuning_cache=cdir)
    assert rt1.tuning_record["source"] == "calibrated"
    rt2, _ = _run_ubench("auto", n=128, tuning_cache=cdir)
    assert rt2.tuning_record["source"] == "calibrated"   # different key
    rt3, _ = _run_ubench("auto", n=64, tuning_cache=cdir)
    assert rt3.tuning_record["source"] == "cache"


def test_cache_off_never_writes(tmp_path):
    rt, _ = _run_ubench("auto", tuning_cache="off")
    assert rt.tuning_record["source"] == "calibrated"
    assert "cache_path" not in rt.tuning_record


# ---------------------------------------------------------------------------
# workload construction


def test_workload_is_busy_on_real_shapes():
    rt = Runtime(_ub_opts(delivery="plan"))
    rt.declare(ubench.Pinger, 32)
    rt.start()
    wl, sustain = tuning.make_workload(rt.program, rt.opts, rt.state)
    assert sustain >= 1
    assert bool(np.asarray(wl.alive).any())
    occ = np.asarray(wl.tail) - np.asarray(wl.head)
    assert (occ[np.asarray(wl.alive)] == rt.opts.mailbox_cap).all()
    assert int(np.asarray(wl.dspill_count).sum()) \
        == rt.opts.spill_cap * rt.program.shards


def test_host_only_program_skips_calibration():
    @actor
    class H:
        HOST = True
        n: I32

        @behaviour
        def tick(self, st):
            return {**st, "n": st["n"] + 1}

    rt = Runtime(_ub_opts(delivery="auto"))
    rt.declare(H, 4)
    rt.start()                      # must not raise, must resolve
    assert rt.opts.delivery in ("plan", "cosort")
