"""Plugin hooks + docgen (≙ src/libponyc/plugin/plugin.c hook protocol
and pass/docgen.c output)."""

import os

import pytest

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu import docgen, plugin


@actor
class Worker:
    """Crunches numbers for the supervisor."""
    boss: Ref
    done: I32

    @behaviour
    def init(self, st, boss: Ref, job: I32):
        """Constructor: remember the boss."""
        return {**st, "boss": boss}


@pytest.fixture(autouse=True)
def _clean_plugins():
    plugin.unregister_all()
    yield
    plugin.unregister_all()


def test_plugin_hooks_run_in_order():
    calls = []

    class P:
        name = "probe"

        def init(self, program):
            calls.append(("init", program.total))

        def visit_cohort(self, program, cohort):
            calls.append(("visit", cohort.atype.__name__))

        def finalize(self, program):
            calls.append(("finalize", len(program.behaviour_table)))

        def help(self):
            return "records build phases"

        def parse_options(self, argv):
            return [a for a in argv if a != "--probe"]

    plugin.register(P())
    rt = Runtime(RuntimeOptions(msg_words=2)).declare(Worker, 4)
    rt.start()
    assert calls == [("init", 4), ("visit", "Worker"), ("finalize", 1)]
    assert plugin.parse_options(["x", "--probe", "y"]) == ["x", "y"]
    assert "records build phases" in plugin.help_text()


def test_plugin_load_by_import_path(tmp_path, monkeypatch):
    (tmp_path / "fake_plug.py").write_text(
        "class Plugin:\n"
        "    name = 'fake'\n"
        "    seen = []\n"
        "    def finalize(self, program):\n"
        "        Plugin.seen.append(program.total)\n")
    monkeypatch.syspath_prepend(str(tmp_path))
    p = plugin.load("fake_plug")
    Runtime(RuntimeOptions(msg_words=2)).declare(Worker, 2).start()
    assert type(p).seen == [2]


def test_docgen_program_and_tree(tmp_path):
    rt = Runtime(RuntimeOptions(msg_words=2)).declare(Worker, 4)
    rt.start()
    md = docgen.document(rt.program, title="Demo")
    assert "# Demo" in md
    assert "## actor Worker" in md
    assert "Crunches numbers" in md
    assert "be init(boss: Ref, job: I32)" in md
    assert "Constructor: remember the boss." in md
    assert "| boss | Ref |" in md
    files = docgen.write_tree(rt.program, str(tmp_path / "docs"))
    assert os.path.exists(tmp_path / "docs" / "Worker.md")
    assert os.path.exists(tmp_path / "docs" / "index.md")
    idx = (tmp_path / "docs" / "index.md").read_text()
    assert "[Worker](Worker.md)" in idx
    assert len(files) == 2
