"""Deliberately-broken behaviour bodies — the R6–R9 fixture corpus.

This file DOES NOT IMPORT (the first import below names a module that
does not exist): it exists to prove the body analyzer is pure AST —
`check_path` must produce every seeded finding anyway. Each defect
line carries a `MARK:<id>` comment; tests/test_bodycheck.py asserts
the exact rule id + line number for every mark.
"""

import a_module_that_does_not_exist_anywhere  # noqa: F401

from ponyc_tpu import Blob, I32, Iso, Ref, Val, actor, behaviour

SEEN = []          # module-level mutable: closure-capture bait


@actor
class Peer:
    x: I32

    @behaviour
    def take(self, st, p: Iso):
        return st


@actor
class Branchy:
    out: Ref["Peer"]
    count: I32

    @behaviour
    def go(self, st, v: I32):
        if st["count"] > 0:                        # MARK:r6-if
            return st
        flag = v > 0 and st["count"] < 9           # MARK:r6-and
        pick = 1 if v else 2                       # MARK:r6-ternary
        ok = not (v > 0)                           # MARK:r6-not
        band = 0 < v < 9                           # MARK:r6-chain
        assert v >= 0                              # MARK:r6-assert
        return {**st, "count": st["count"] + pick + ok + band + flag}


@actor
class Loopy:
    out: Ref["Peer"]
    n: I32

    @behaviour
    def emit(self, st, n: I32):
        for i in range(n):                         # MARK:r6-for
            self.send(st["out"], Peer.take, i)     # MARK:r7-for-send
        return st

    @behaviour
    def spin(self, st, v: I32):
        while v < 4:                               # MARK:r6-while
            self.exit(0)                           # MARK:r7-while-exit
            v = v + 1
        return st

    @behaviour
    def drops(self, st, v: I32):                   # MARK:r7-falloff
        self.send(st["out"], Peer.take, v)


@actor
class Keys:
    total: I32
    frozen: Val

    @behaviour
    def tally(self, st, v: I32):
        acc = st["totl"] + v                       # MARK:r8-read-typo
        return {**st, "tote": acc}                 # MARK:r8-write-typo

    @behaviour
    def freeze_write(self, st, v: I32):
        return {**st, "frozen": v}                 # MARK:r8-val-write

    @behaviour
    def drop_mut(self, st, v: I32):
        st["total"] = v                            # MARK:r8-mut-dropped
        return {"total": v}

    @behaviour
    def narrow(self, st, v: I32):
        return {"total": v}                        # MARK:r8-missing

    @behaviour
    def selfish(self, st, v: I32):
        self.total = v                             # MARK:r8-self-attr
        return st


@actor
class Impure:
    out: Ref["Peer"]
    rng: I32

    @behaviour
    def noisy(self, st, v: I32):
        print("dispatching", v)                    # MARK:r9-print
        import numpy as np
        r = np.random.randint(9)                   # MARK:r9-nprandom
        import time
        t = time.time()                            # MARK:r9-time
        SEEN.append(v)                             # MARK:r9-capture
        return {**st, "rng": st["rng"] + r + int(t)}

    @behaviour
    def twice(self, st, p: Iso):
        self.send(st["out"], Peer.take, p)
        self.send(st["out"], Peer.take, p)         # MARK:r9-move
        return st

    @behaviour
    def freed(self, st, b: Blob):
        self.blob_free(b)
        ln = self.blob_length(b)                   # MARK:r9-free-use
        return {**st, "rng": ln}
