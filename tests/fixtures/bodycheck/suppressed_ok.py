"""The same defect shapes as broken_bodies.py, silenced through every
suppression channel — type-level LINT_IGNORE, behaviour-level
@behaviour(lint_ignore=...), and trailing line comments. check_path
must report ZERO findings here (tests/test_bodycheck.py)."""

import a_module_that_does_not_exist_anywhere  # noqa: F401

from ponyc_tpu import I32, Ref, actor, behaviour


@actor
class Sink:
    x: I32

    @behaviour
    def put(self, st, v: I32):
        return {**st, "x": v}


@actor
class TypeMuted:
    out: Ref["Sink"]
    LINT_IGNORE = ("R6",)

    @behaviour
    def go(self, st, v: I32):
        if v > 0:
            self.send(st["out"], Sink.put, v)
        return st


@actor
class BehaviourMuted:
    out: Ref["Sink"]

    @behaviour(lint_ignore=("R6",))
    def go(self, st, v: I32):
        if v > 0:
            self.send(st["out"], Sink.put, v)
        return st


@actor
class LineMuted:
    out: Ref["Sink"]

    @behaviour
    def go(self, st, v: I32):
        if v > 0:                      # lint: ignore[R6]
            self.send(st["out"], Sink.put, v)
        print("traced once")           # lint: ignore
        return st
