"""API-surface semantics: behaviour identity under inheritance, repeated
run() calls, dead-letter accounting, flag parsing."""

import numpy as np
import pytest

from ponyc_tpu import (Actor, I32, Ref, Runtime, RuntimeOptions, actor,
                       behaviour, strip_runtime_flags)

OPTS = RuntimeOptions(mailbox_cap=8, batch=2, max_sends=1, msg_words=1)


class Base(Actor):
    count: I32

    @behaviour
    def bump(self, st, by: I32):
        return {**st, "count": st["count"] + by}


class A(Base):
    pass


class B(Base):
    @behaviour
    def bump(self, st, by: I32):   # override: doubles
        return {**st, "count": st["count"] + 2 * by}


def test_inherited_behaviours_get_distinct_dispatch_slots():
    rt = Runtime(OPTS)
    rt.declare(A, 2).declare(B, 2)
    rt.start()
    a = rt.spawn(A)
    b = rt.spawn(B)
    assert A.bump is not B.bump and A.bump is not Base.bump
    rt.send(a, A.bump, 5)
    rt.send(b, B.bump, 5)
    rt.run(max_steps=20)
    assert rt.state_of(a)["count"] == 5
    assert rt.state_of(b)["count"] == 10
    assert rt.totals["processed"] == 2


def test_run_twice_and_counter_totals():
    rt = Runtime(OPTS)
    rt.declare(A, 1)
    rt.start()
    a = rt.spawn(A)
    for _ in range(3):
        rt.send(a, A.bump, 1)
    rt.run(max_steps=50)
    first = rt.steps_run
    assert rt.state_of(a)["count"] == 3
    # Second run must not be starved by the lifetime step counter.
    for _ in range(3):
        rt.send(a, A.bump, 1)
    rt.run(max_steps=50)
    assert rt.state_of(a)["count"] == 6
    assert rt.steps_run > first
    assert rt.totals["processed"] == 6


def test_deadletter_counted():
    rt = Runtime(OPTS)
    rt.declare(A, 2)
    rt.start()
    a = rt.spawn(A)          # second slot never spawned
    dead = a + 1 if a + 1 < 2 else a - 1
    rt.send(dead, A.bump, 1)
    rt.run(max_steps=10)
    assert rt.counter("n_deadletter") == 1


def test_out_of_world_send_drops_and_quiesces():
    # Sends stay permissive past the world's edge (_check_send_target):
    # the message must DROP on device and the program must still
    # quiesce — the inject path once crashed looking up the cohort of
    # an id no cohort owns.
    rt = Runtime(OPTS)
    rt.declare(A, 2)
    rt.start()
    a = rt.spawn(A)
    rt.send(10_000_000, A.bump, 1)       # far out of [0, total)
    rt.send(a, A.bump, 1)                # a real message rides along
    assert rt.run(max_steps=20) == 0
    assert rt.state_of(a)["count"] == 1


def test_strip_runtime_flags():
    opts, rest = strip_runtime_flags(
        ["prog", "--pony_mailbox_cap", "128", "--ponybatch=16",
         "--ponynoyield", "user-arg"])
    assert opts.mailbox_cap == 128
    assert opts.batch == 16
    assert opts.noyield is True
    assert rest == ["prog", "user-arg"]
    with pytest.raises(ValueError):
        strip_runtime_flags(["prog", "--pony_batch"])


def test_runtime_defaults_override():
    # ≙ Main_runtime_override_defaults_oo (start.c:99,214): a declared
    # type's RUNTIME_DEFAULTS apply when the caller passed no options;
    # explicit options win.
    from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour

    @actor
    class Tuned:
        RUNTIME_DEFAULTS = {"mailbox_cap": 32, "batch": 3}
        x: I32

        @behaviour
        def nop(self, st):
            return st

    rt = Runtime().declare(Tuned, 2).start()
    assert rt.opts.mailbox_cap == 32 and rt.opts.batch == 3
    rt2 = Runtime(RuntimeOptions(mailbox_cap=8, msg_words=1,
                                 batch=1, max_sends=1))
    rt2.declare(Tuned, 2).start()
    assert rt2.opts.mailbox_cap == 8      # explicit options win


def test_inject_flood_conserves_through_bounded_slots():
    """Thousands of queued host sends drain through the bounded
    per-step inject slots with per-target flow control, exactly once
    (≙ external pony_sendv bursts through the scheduler inject queue,
    actor.c:773 from non-actor context)."""
    import numpy as np

    from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour

    @actor
    class FloodCnt:
        n: I32
        s: I32
        BATCH = 2

        @behaviour
        def hit(self, st, v: I32):
            return {**st, "n": st["n"] + 1, "s": st["s"] + v}

    rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=2, msg_words=1,
                                max_sends=1, spill_cap=64,
                                inject_slots=8))
    rt.declare(FloodCnt, 4).start()
    ids = rt.spawn_many(FloodCnt, 4)
    rng = np.random.default_rng(0)
    total = 0
    for _ in range(2000):
        v = int(rng.integers(1, 7))
        rt.send(int(ids[rng.integers(0, 4)]), FloodCnt.hit, v)
        total += v
    assert rt.run(max_steps=50_000) == 0
    st = rt.cohort_state(FloodCnt)
    assert int(st["n"].sum()) == 2000
    assert int(st["s"].sum()) == total
