"""Checkpoint/resume tests (≙ the serialise subsystem, gc/serialise.c,
promoted to whole-world snapshots; reference parity check = the
round-trip guarantees packages/serialise tests assert)."""

import numpy as np
import pytest

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions, actor, behaviour,
                       serialise)
from ponyc_tpu.models import ring


def _opts(**kw):
    base = dict(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8)
    base.update(kw)
    return RuntimeOptions(**base)


def _build_ring(n, opts):
    rt = Runtime(opts).declare(ring.RingNode, n).start()
    ids = rt.spawn_many(ring.RingNode, n)
    rt.set_fields(ring.RingNode, ids, next_ref=np.roll(ids, -1))
    return rt, ids


def test_snapshot_mid_flight_resume_matches(tmp_path):
    # Run A: 300 hops straight through.
    rt_a, ids_a = _build_ring(8, _opts())
    rt_a.send(int(ids_a[0]), ring.RingNode.token, 300)
    rt_a.run()
    want = rt_a.cohort_state(ring.RingNode)["passes"]

    # Run B: same program, checkpointed mid-flight, resumed elsewhere.
    rt_b, ids_b = _build_ring(8, _opts())
    rt_b.send(int(ids_b[0]), ring.RingNode.token, 300)
    rt_b.run(max_steps=57)                       # part-way: token in flight
    serialise.save(rt_b, str(tmp_path / "w.npz"))

    rt_c, _ = _build_ring(8, _opts())
    serialise.restore(rt_c, str(tmp_path / "w.npz"))
    assert rt_c.steps_run == rt_b.steps_run
    rt_c.run()
    got = rt_c.cohort_state(ring.RingNode)["passes"]
    np.testing.assert_array_equal(got, want)


def test_snapshot_preserves_queued_host_sends(tmp_path):
    rt, ids = _build_ring(4, _opts())
    rt.send(int(ids[0]), ring.RingNode.token, 7)   # still in _inject_q
    serialise.save(rt, str(tmp_path / "w.npz"))

    rt2, _ = _build_ring(4, _opts())
    serialise.restore(rt2, str(tmp_path / "w.npz"))
    assert len(rt2._inject_q) == 1
    rt2.run()
    assert rt2.cohort_state(ring.RingNode)["passes"].sum() == 7


def test_fingerprint_rejects_different_program(tmp_path):
    rt, _ = _build_ring(4, _opts())
    serialise.save(rt, str(tmp_path / "w.npz"))

    @actor
    class Other:
        x: I32

        @behaviour
        def go(self, st, v: I32):
            return st

    rt2 = Runtime(_opts()).declare(Other, 4).start()
    with pytest.raises(serialise.FingerprintMismatch):
        serialise.restore(rt2, str(tmp_path / "w.npz"))


def test_geometry_mismatch_rejected(tmp_path):
    rt, _ = _build_ring(4, _opts())
    serialise.save(rt, str(tmp_path / "w.npz"))
    rt2, _ = _build_ring(4, _opts(mailbox_cap=16))
    with pytest.raises(serialise.FingerprintMismatch):
        serialise.restore(rt2, str(tmp_path / "w.npz"))


def test_host_actor_state_round_trips(tmp_path):
    @actor
    class Keeper:
        HOST = True
        total: I32

        @behaviour
        def add(self, st, v: I32):
            st["total"] = st["total"] + v
            return st

    def build():
        return Runtime(_opts(msg_words=2, batch=4)).declare(
            Keeper, 1).start()

    rt = build()
    kid = rt.spawn(Keeper)
    rt.send(kid, Keeper.add, 5)
    rt.run(max_steps=50)
    assert rt.state_of(kid)["total"] == 5
    serialise.save(rt, str(tmp_path / "w.npz"))

    rt2 = build()
    rt2.spawn(Keeper)
    serialise.restore(rt2, str(tmp_path / "w.npz"))
    assert rt2.state_of(kid)["total"] == 5
    rt2.send(kid, Keeper.add, 3)
    rt2.run(max_steps=50)
    assert rt2.state_of(kid)["total"] == 8
