"""Checkpoint/resume tests (≙ the serialise subsystem, gc/serialise.c,
promoted to whole-world snapshots; reference parity check = the
round-trip guarantees packages/serialise tests assert)."""

import numpy as np
import pytest

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions, actor, behaviour,
                       serialise)
from ponyc_tpu.models import ring


def _opts(**kw):
    base = dict(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8)
    base.update(kw)
    return RuntimeOptions(**base)


def _build_ring(n, opts):
    rt = Runtime(opts).declare(ring.RingNode, n).start()
    ids = rt.spawn_many(ring.RingNode, n)
    rt.set_fields(ring.RingNode, ids, next_ref=np.roll(ids, -1))
    return rt, ids


def test_snapshot_mid_flight_resume_matches(tmp_path):
    # Run A: 300 hops straight through.
    rt_a, ids_a = _build_ring(8, _opts())
    rt_a.send(int(ids_a[0]), ring.RingNode.token, 300)
    rt_a.run()
    want = rt_a.cohort_state(ring.RingNode)["passes"]

    # Run B: same program, checkpointed mid-flight, resumed elsewhere.
    rt_b, ids_b = _build_ring(8, _opts())
    rt_b.send(int(ids_b[0]), ring.RingNode.token, 300)
    rt_b.run(max_steps=57)                       # part-way: token in flight
    serialise.save(rt_b, str(tmp_path / "w.npz"))

    rt_c, _ = _build_ring(8, _opts())
    serialise.restore(rt_c, str(tmp_path / "w.npz"))
    assert rt_c.steps_run == rt_b.steps_run
    rt_c.run()
    got = rt_c.cohort_state(ring.RingNode)["passes"]
    np.testing.assert_array_equal(got, want)


def test_snapshot_preserves_queued_host_sends(tmp_path):
    rt, ids = _build_ring(4, _opts())
    rt.send(int(ids[0]), ring.RingNode.token, 7)   # still in _inject_q
    serialise.save(rt, str(tmp_path / "w.npz"))

    rt2, _ = _build_ring(4, _opts())
    serialise.restore(rt2, str(tmp_path / "w.npz"))
    assert len(rt2._inject_q) == 1
    rt2.run()
    assert rt2.cohort_state(ring.RingNode)["passes"].sum() == 7


def test_fingerprint_rejects_different_program(tmp_path):
    rt, _ = _build_ring(4, _opts())
    serialise.save(rt, str(tmp_path / "w.npz"))

    @actor
    class Other:
        x: I32

        @behaviour
        def go(self, st, v: I32):
            return st

    rt2 = Runtime(_opts()).declare(Other, 4).start()
    with pytest.raises(serialise.FingerprintMismatch):
        serialise.restore(rt2, str(tmp_path / "w.npz"))


def test_geometry_change_relayouts_since_v3(tmp_path):
    """Since format v3 a geometry difference is NOT a mismatch: the
    restore re-lays-out the SoA arrays (ISSUE 8 tentpole; the deep
    differential coverage lives in tests/test_durability.py). Mid-
    flight token crosses a mailbox_cap change and still completes to
    the synchronous oracle."""
    rt_a, ids_a = _build_ring(8, _opts())
    rt_a.send(int(ids_a[0]), ring.RingNode.token, 300)
    rt_a.run()
    want = rt_a.cohort_state(ring.RingNode)["passes"]

    rt, ids = _build_ring(8, _opts())
    rt.send(int(ids[0]), ring.RingNode.token, 300)
    rt.run(max_steps=57)                       # token in flight
    serialise.save(rt, str(tmp_path / "w.npz"))
    rt2, _ = _build_ring(8, _opts(mailbox_cap=16, spill_cap=128))
    serialise.restore(rt2, str(tmp_path / "w.npz"))
    assert rt2.steps_run == rt.steps_run
    rt2.run()
    np.testing.assert_array_equal(
        rt2.cohort_state(ring.RingNode)["passes"], want)


def test_host_actor_state_round_trips(tmp_path):
    @actor
    class Keeper:
        HOST = True
        total: I32

        @behaviour
        def add(self, st, v: I32):
            st["total"] = st["total"] + v
            return st

    def build():
        return Runtime(_opts(msg_words=2, batch=4)).declare(
            Keeper, 1).start()

    rt = build()
    kid = rt.spawn(Keeper)
    rt.send(kid, Keeper.add, 5)
    rt.run(max_steps=50)
    assert rt.state_of(kid)["total"] == 5
    serialise.save(rt, str(tmp_path / "w.npz"))

    rt2 = build()
    rt2.spawn(Keeper)
    serialise.restore(rt2, str(tmp_path / "w.npz"))
    assert rt2.state_of(kid)["total"] == 5
    rt2.send(kid, Keeper.add, 3)
    rt2.run(max_steps=50)
    assert rt2.state_of(kid)["total"] == 8


def test_snapshot_under_mute_pressure_resumes_to_oracle(tmp_path):
    """Checkpoint taken MID-DEADLOCK-PRESSURE (muted senders, live spill,
    aged mute counters) and restored into a fresh runtime must finish to
    the exact oracle state — proving every backpressure column
    (muted/mute_refs/mute_age/mute_ovf/pressured/spills/plan cache)
    round-trips (≙ the serialise subsystem being the checkpoint/resume
    building block, gc/serialise.c; SURVEY.md §5)."""
    import sys as _sys
    _sys.path.insert(0, str(__import__("pathlib").Path(__file__).parent))
    import numpy as np
    import test_differential as td

    from ponyc_tpu import Runtime, RuntimeOptions
    from ponyc_tpu import serialise

    n_w, n_s = 24, 8
    w_nxt, s_w, s_s, seeds = td._case(23, n_w, n_s)   # the deadlock seed
    want = td.oracle(n_w, n_s, w_nxt, s_w, s_s, seeds)

    def build():
        rt = Runtime(RuntimeOptions(mailbox_cap=2, batch=1, msg_words=1,
                                    max_sends=2, spill_cap=512,
                                    inject_slots=16))
        rt.declare(td.Walker, n_w).declare(td.Splitter, n_s)
        rt.start()
        return rt

    rt = build()
    wids = rt.spawn_many(td.Walker, n_w)
    sids = rt.spawn_many(td.Splitter, n_s)
    rt.set_fields(td.Walker, wids, nxt=wids[np.asarray(w_nxt)])
    rt.set_fields(td.Splitter, sids, w_ref=wids[np.asarray(s_w)],
                  s_ref=sids[np.asarray(s_s)])
    for kind, i, v in seeds:
        rt.send(int(wids[i] if kind == "w" else sids[i]),
                td.Walker.step if kind == "w" else td.Splitter.burst, v)
    # run into the thick of it: mutes + spill live at snapshot time
    inj = rt._drain_inject()
    st, aux = rt._step(rt.state, *inj)
    inj = rt._empty_inject
    for _ in range(7):
        st, aux = rt._step(st, *inj)
    rt.state = st
    assert np.asarray(st.muted).any(), "snapshot must land mid-pressure"
    path = str(tmp_path / "mid_pressure.npz")
    serialise.save(rt, path)

    rt2 = build()                     # fresh runtime, same program
    serialise.restore(rt2, path)
    assert np.asarray(rt2.state.muted).any()
    assert rt2.run(max_steps=50_000) == 0
    wst = rt2.cohort_state(td.Walker)
    sst = rt2.cohort_state(td.Splitter)
    assert (wst["acc"].astype(np.int64) == want[0]).all()
    assert (wst["hits"].astype(np.int64) == want[1]).all()
    assert (sst["acc"].astype(np.int64) == want[2]).all()
    assert not np.asarray(rt2.state.muted).any()
