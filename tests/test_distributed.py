"""Multi-host smoke test: two real OS processes join one jax.distributed
job over loopback and run a collective (the DCN tier of the
communication backend, parallel/distributed.py).

The reference has no multi-process story at all (SURVEY.md §2.4); this
is the layer built in its place, so the test proves the wiring is real:
process 0 is the coordinator, both call initialize(), see the global
device count, and agree on a psum across processes.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, {root!r})
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PYTHONPATH", None)
    import ponyc_tpu.parallel.distributed as dist
    dist.initialize(coordinator={coord!r}, num_processes=2,
                    process_id={rank})
    import jax
    import jax.numpy as jnp
    assert jax.process_count() == 2, jax.process_count()
    assert dist.process_index() == {rank}
    assert dist.is_leader() == ({rank} == 0)
    # One cross-process collective over the global mesh: each process
    # contributes its (rank+1) as its shard of a global [2] array; the
    # psum must see both across the process boundary.
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()
    mesh = Mesh(devs, ("actors",))
    sharding = NamedSharding(mesh, P("actors"))
    local = jax.device_put(jnp.full((1,), {rank} + 1, jnp.int32),
                           jax.local_devices()[0])
    garr = jax.make_array_from_single_device_arrays(
        (len(devs),), sharding, [local])
    from ponyc_tpu.compat import shard_map
    total = jax.jit(
        shard_map(lambda x: jax.lax.psum(x, "actors"),
                      mesh=mesh, in_specs=P("actors"), out_specs=P()),
    )(garr)
    assert int(total[0]) == 3, total     # 1 + 2
    print("RANK{rank}_OK", flush=True)
""")


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_engine_across_two_processes():
    """The ACTOR ENGINE (not just a collective) over a real process
    boundary: 2 OS processes × 4 virtual devices = an 8-shard mesh
    running ubench traffic and a ring whose every hop crosses shards
    (every 4th hop crosses the process boundary), with dryrun-style
    exact conservation counters asserted on BOTH ranks
    (tests/_dist_worker.py).

    CPU gate: multiprocess computations are unsupported by this
    jaxlib's CPU backend (its refusal is literal: "Multiprocess
    computations aren't implemented on the CPU backend"); forcing the
    gloo collectives implementation (distributed.initialize) gets the
    single-collective smoke above through reliably, but under the
    engine's many-collectives-per-tick mix gloo aborts
    NONDETERMINISTICALLY with mismatched-op errors
    (gloo/transport/tcp/pair.cc `op.preamble.length <= op.nbytes`) —
    the CPU thunk executor issues collectives in racy order across
    ranks. The engine's sharded semantics are covered single-process
    by test_mesh*/test_mesh_pressure; this test is for real multi-host
    backends (force an attempt here with PONY_TPU_DIST_ENGINE=1)."""
    if os.environ.get("PONY_TPU_DIST_ENGINE", "0") != "1":
        pytest.skip("engine-over-processes needs a non-CPU backend: "
                    "XLA:CPU gloo collectives abort nondeterministically "
                    "(see docstring); PONY_TPU_DIST_ENGINE=1 forces")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    worker = os.path.join(root, "tests", "_dist_worker.py")
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    procs = [subprocess.Popen(
        [sys.executable, "-u", worker, coord, str(r), "2"], env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
        for r in range(2)]
    try:
        for rank, p in enumerate(procs):
            out, _ = p.communicate(timeout=420)
            assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"
            assert f"RANK{rank}_UBENCH_OK" in out
            assert f"RANK{rank}_RING_OK" in out
            # Stage 3 self-skips on xla:cpu (gloo collective mismatch
            # aborts — see _dist_worker.py); on real multi-host
            # backends it must pass.
            assert (f"RANK{rank}_PRESSURE_OK" in out
                    or f"RANK{rank}_PRESSURE_SKIPPED" in out)
            assert f"RANK{rank}_ALL_OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def test_two_process_distributed_psum(tmp_path):
    # (bounded by the communicate(timeout=150) below — workers that
    # never rendezvous are killed and fail the assert)
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    coord = f"127.0.0.1:{_free_port()}"
    env = {k: v for k, v in os.environ.items()
           if k not in ("PYTHONPATH", "XLA_FLAGS")}   # 1 CPU dev per proc
    env["JAX_PLATFORMS"] = "cpu"
    procs = []
    for rank in range(2):
        src = _WORKER.format(root=root, coord=coord, rank=rank)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", src], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = []
    try:
        for rank, p in enumerate(procs):
            out, _ = p.communicate(timeout=150)
            outs.append(out)
            assert p.returncode == 0, f"rank {rank} failed:\n{out}"
            assert f"RANK{rank}_OK" in out
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
