"""Native host-runtime layer tests (pool, MPSC queue, ASIO epoll loop).

≙ the reference's runtime unit tests (test/libponyrt/mem/pool.cc and the
asio paths exercised via stdlib socket/timer tests) — here driven through
the ctypes bindings, no device involved.
"""

import os
import signal
import threading
import time

import numpy as np
import pytest

from ponyc_tpu import native


def test_pool_class_index():
    l = native.lib()
    assert l.ponyx_pool_index(1) == 0
    assert l.ponyx_pool_index(32) == 0
    assert l.ponyx_pool_index(33) == 1
    assert l.ponyx_pool_index(64) == 1
    assert l.ponyx_pool_index(1 << 20) == 15


def test_pool_alloc_recycles():
    l = native.lib()
    a = l.ponyx_pool_alloc(100)
    assert a
    l.ponyx_pool_free(100, a)
    b = l.ponyx_pool_alloc(100)   # same class → same block back
    assert b == a
    l.ponyx_pool_free(100, b)


def test_hostq_fifo_roundtrip():
    q = native.HostQueue()
    for i in range(100):
        q.push([i, i * 2, i * 3])
    assert len(q) == 100
    for i in range(100):
        m = q.pop()
        assert m is not None and list(m) == [i, i * 2, i * 3]
    assert q.pop() is None
    q.close()


def test_hostq_variable_width_and_regrow_pop():
    q = native.HostQueue()
    q.push(np.arange(80, dtype=np.int32))
    m = q.pop(max_words=16)   # too small → internally retried with 80
    assert m is not None and m.size == 80
    q.close()


def test_hostq_concurrent_producers():
    q = native.HostQueue()
    n_threads, per = 8, 500

    def produce(t):
        for i in range(per):
            q.push([t, i])

    ts = [threading.Thread(target=produce, args=(t,))
          for t in range(n_threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    seen = {t: [] for t in range(n_threads)}
    while (m := q.pop()) is not None:
        seen[int(m[0])].append(int(m[1]))
    # MPSC guarantee: per-producer FIFO survives interleaving
    for t in range(n_threads):
        assert seen[t] == list(range(per))
    q.close()


def test_asio_timer_fires():
    loop = native.AsioLoop()
    loop.timer(2_000_000, 2_000_000, owner=7, behaviour=3)  # 2ms period
    deadline = time.time() + 2.0
    events = []
    while time.time() < deadline and len(events) < 3:
        events.extend(loop.drain())
        time.sleep(0.005)
    assert len(events) >= 3
    ev = events[0]
    assert (ev.owner, ev.behaviour, ev.kind) == (7, 3, native.TIMER)
    assert ev.arg >= 1          # expiration count
    assert loop.noisy >= 1      # periodic timer holds liveness
    loop.close()


def test_asio_oneshot_timer_unsubscribes_itself():
    loop = native.AsioLoop()
    loop.timer(1_000_000, 0, owner=1, behaviour=0, oneshot=True)
    time.sleep(0.1)
    evs = loop.drain()
    assert len(evs) == 1 and evs[0].kind == native.TIMER
    assert loop.noisy == 0      # oneshot released its noisy hold
    time.sleep(0.05)
    assert loop.drain() == []   # never fires again
    loop.close()


def test_asio_fd_readable_pipe():
    loop = native.AsioLoop()
    r, w = os.pipe()
    os.set_blocking(r, False)
    loop.fd(r, owner=42, behaviour=9)
    os.write(w, b"x")
    deadline = time.time() + 2.0
    events = []
    while time.time() < deadline and not events:
        events = loop.drain()
        time.sleep(0.005)
    assert events and events[0].kind == native.FD_READ
    assert events[0].arg == r and events[0].owner == 42
    os.read(r, 1)               # level-triggered: clear readability
    os.close(r)
    os.close(w)
    loop.close()


def test_asio_signal_delivery():
    loop = native.AsioLoop()
    loop.signal(signal.SIGUSR1, owner=5, behaviour=2)
    os.kill(os.getpid(), signal.SIGUSR1)
    deadline = time.time() + 2.0
    events = []
    while time.time() < deadline and not events:
        events = loop.drain()
        time.sleep(0.005)
    assert events and events[0].kind == native.SIGNAL
    assert events[0].arg == signal.SIGUSR1 and events[0].owner == 5
    loop.close()


def test_asio_unsubscribe_stops_events():
    loop = native.AsioLoop()
    sid = loop.timer(1_000_000, 1_000_000, owner=1, behaviour=1)
    time.sleep(0.05)
    assert loop.unsubscribe(sid)
    loop.drain(1024)
    time.sleep(0.05)
    assert loop.drain() == []
    assert loop.noisy == 0
    loop.close()


def test_asio_noisy_manual_holds():
    loop = native.AsioLoop()
    assert loop.noisy == 0
    loop.noisy_add()
    loop.noisy_add()
    assert loop.noisy == 2
    loop.noisy_remove()
    assert loop.noisy == 1
    loop.noisy_remove()
    assert loop.noisy == 0
    loop.close()


def test_socket_writev_scatter_gather():
    """One sendmsg carries a chunk list (≙ the reference's iovec writev
    path, lang/socket.c); short writes consume mid-chunk."""
    from ponyc_tpu.native import sockets as S

    lfd = S.listen_tcp("127.0.0.1", 0)
    port = S.sockname_port(lfd)
    cfd = S.connect_tcp("127.0.0.1", port)
    for _ in range(200):
        afd = S.accept(lfd)
        if afd is not None:
            break
        time.sleep(0.005)
    assert afd is not None
    assert S.connect_result(cfd) == 0
    chunks = [b"alpha-", b"", b"beta-", b"gamma"]
    total = sum(len(c) for c in chunks)
    sent = 0
    for _ in range(100):
        sent += S.writev(cfd, _remaining(chunks, sent))
        if sent == total:
            break
        time.sleep(0.005)
    assert sent == total
    got = b""
    for _ in range(200):
        d = S.recv(afd)
        if d:
            got += d
        if got == b"alpha-beta-gamma":
            break
        time.sleep(0.005)
    assert got == b"alpha-beta-gamma"
    for fd in (cfd, afd, lfd):
        S.close(fd)


def _remaining(chunks, sent):
    out = []
    for c in chunks:
        if sent >= len(c):
            sent -= len(c)
        else:
            out.append(c[sent:])
            sent = 0
    return out


def test_socket_names_and_options():
    from ponyc_tpu.native import sockets as S
    import socket as pysock

    lfd = S.listen_tcp("127.0.0.1", 0)
    addr, port = S.sockname(lfd)
    assert addr == "127.0.0.1" and port > 0
    cfd = S.connect_tcp("127.0.0.1", port)
    for _ in range(200):
        afd = S.accept(lfd)
        if afd is not None:
            break
        time.sleep(0.005)
    paddr, pport = S.peername(cfd)
    assert paddr == "127.0.0.1" and pport == port
    # Generic option surface (≙ the reference's pony_os_getsockopt):
    S.set_option(cfd, pysock.SOL_SOCKET, pysock.SO_RCVBUF, 65536)
    assert S.get_option(cfd, pysock.SOL_SOCKET, pysock.SO_RCVBUF) >= 65536
    assert S.get_option(cfd, pysock.SOL_SOCKET, pysock.SO_ERROR) == 0
    for fd in (cfd, afd, lfd):
        S.close(fd)


def test_udp_multicast_and_broadcast_options():
    from ponyc_tpu.native import sockets as S

    fd = S.udp("0.0.0.0", 0)
    S.multicast_ttl(fd, 2)
    S.multicast_loopback(fd, True)
    S.broadcast(fd, True)
    try:
        S.multicast_join(fd, "239.255.12.34")
        S.multicast_leave(fd, "239.255.12.34")
    except OSError:
        pass   # containers without multicast routes: option path is the
        #        thing under test, join errno comes from the kernel
    import pytest
    with pytest.raises(OSError):
        S.multicast_join(fd, "not-an-address")
    S.close(fd)


def test_native_microbench_sane():
    """The in-C++ microbench suite (≙ benchmark/libponyrt) runs and
    returns plausible steady-state costs (pool hit path and MPSC
    round-trip are tens of ns, never µs-scale)."""
    from ponyc_tpu import native
    res = native.microbench(scale=0.05)
    assert set(res) == {"pool_alloc_free_64B_ns", "pool_alloc_free_4KB_ns",
                        "pool_burst32_64B_ns", "mpscq_push_pop_4w_ns",
                        "mpscq_mt_4prod_4w_ns"}
    for k, v in res.items():
        assert 0.5 < v < 100_000, (k, v)


def test_affinity_pinning():
    """≙ --ponypin / --ponypinasio (start.c:75-94, cpu.c:278): the host
    driver thread and the native event-loop thread pin to cores."""
    import os

    from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour

    @actor
    class P:
        n: I32

        @behaviour
        def tick(self, st, v: I32):
            return {**st, "n": st["n"] + v}

    before = os.sched_getaffinity(0)
    core = min(before)             # a core this cgroup actually allows
    try:
        rt = Runtime(RuntimeOptions(msg_words=1, pin=core,
                                    pin_asio=core))
        rt.declare(P, 1).start()
        assert os.sched_getaffinity(0) == {core}
        b = rt.attach_bridge()           # pins the asio thread (no raise)
        a = rt.spawn(P)
        rt.send(a, P.tick, 5)
        assert rt.run(max_steps=50) == 0
        assert rt.state_of(a)["n"] == 5
        b.close()
    finally:
        os.sched_setaffinity(0, before)


def test_affinity_bad_core_raises():
    from ponyc_tpu import Runtime, RuntimeOptions

    rt = Runtime(RuntimeOptions(msg_words=1, pin=4096))
    try:
        rt.start()
        raise AssertionError("pin to absurd core did not raise")
    except ValueError:
        pass
