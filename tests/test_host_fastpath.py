"""Host fast lane (RuntimeOptions.host_fastpath): host→host messages
bypass the device mailbox table (≙ inject_main keeping main-thread
actors on the main-thread scheduler, scheduler.c:47,179-190) with
identical semantics — per-sender-pair FIFO, quiescence, checkpointing,
dead-letter on unspawned targets."""

import numpy as np

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour

OPTS = dict(mailbox_cap=8, batch=2, max_sends=2, msg_words=2,
            inject_slots=16)


@actor
class HostCounter:
    HOST = True
    n: I32
    last: I32

    @behaviour
    def hit(self, st, v: I32):
        return {"n": st["n"] + 1, "last": v}


@actor
class HostChain:
    HOST = True
    nxt: Ref
    hops: I32

    @behaviour
    def pass_(self, st, k: I32):
        if k > 0:
            self.send(st["nxt"], HostChain.pass_, k - 1)
        return {**st, "hops": st["hops"] + 1}


@actor
class DevPing:
    out: Ref
    fired: I32
    MAX_SENDS = 1

    @behaviour
    def go(self, st, v: I32):
        self.send(st["out"], HostCounter.hit, v)
        return {**st, "fired": st["fired"] + 1}


def test_fast_lane_preserves_order_and_count():
    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(HostCounter, 2).start()
    a = rt.spawn(HostCounter)
    for v in range(50):
        rt.send(a, HostCounter.hit, v)
    assert len(rt._host_fast_q) == 50          # took the fast lane
    assert rt.run(max_steps=50) == 0
    st = rt.state_of(a)
    assert st["n"] == 50 and st["last"] == 49  # FIFO: last send last


def test_host_chain_completes_within_few_boundaries():
    """A host→host relay chain drains at host boundaries without one
    device window per hop — the whole chain fits one run() in far
    fewer steps than hops."""
    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(HostChain, 2).start()
    a = rt.spawn(HostChain)
    b = rt.spawn(HostChain, nxt=a)
    rt.set_fields(HostChain, np.asarray([a]), nxt=b)
    rt.send(a, HostChain.pass_, 100)
    assert rt.run(max_steps=20) == 0
    total = sum(rt.state_of(x)["hops"] for x in (a, b))
    assert total == 101
    assert rt.steps_run < 20                   # not one window per hop


def test_device_to_host_still_rides_the_device_lane():
    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(DevPing, 1).declare(HostCounter, 1).start()
    h = rt.spawn(HostCounter)
    d = rt.spawn(DevPing, out=h)
    rt.send(d, DevPing.go, 7)                  # device target: inject lane
    assert rt.run(max_steps=16) == 0
    assert rt.state_of(h) == {"n": 1, "last": 7}


def test_fastpath_opt_out_matches():
    res = {}
    for fast in (True, False):
        rt = Runtime(RuntimeOptions(host_fastpath=fast, **OPTS))
        rt.declare(HostCounter, 1).start()
        a = rt.spawn(HostCounter)
        for v in range(20):
            rt.send(a, HostCounter.hit, v)
        if not fast:
            assert not rt._host_fast_q
        rt.run(max_steps=64)
        res[fast] = dict(rt.state_of(a))
    assert res[True] == res[False] == {"n": 20, "last": 19}


def test_checkpoint_carries_queued_fast_messages(tmp_path):
    from ponyc_tpu import serialise
    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(HostCounter, 1).start()
    a = rt.spawn(HostCounter)
    for v in range(5):
        rt.send(a, HostCounter.hit, v)
    path = str(tmp_path / "w.npz")
    serialise.save(rt, path)
    rt2 = Runtime(RuntimeOptions(**OPTS))
    rt2.declare(HostCounter, 1).start()
    serialise.restore(rt2, path)
    assert len(rt2._host_fast_q) == 5
    rt2.run(max_steps=16)
    assert rt2.state_of(a) == {"n": 5, "last": 4}


def test_unspawned_host_target_dead_letters():
    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(HostCounter, 2).start()
    a = rt.spawn(HostCounter)
    ghost = a + 1 if rt.program.cohort_of(a + 1).host else a - 1
    rt.send(int(ghost), HostCounter.hit, 1)
    rt.run(max_steps=8)
    assert rt.totals["deadletter_host"] == 1
    assert rt.state_of(a)["n"] == 0


def test_yield_stops_fast_lane_batch():
    """yield_() on the fast lane stops that actor's batch for the
    boundary, exactly like the device-mailbox drain (actor.c:675-679) —
    round-5 review regression."""
    @actor
    class Yielding:
        HOST = True
        n: I32

        @behaviour
        def hit(self, st, v: I32):
            self.yield_()                 # one message per boundary
            return {**st, "n": st["n"] + 1}

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(Yielding, 1).start()
    a = rt.spawn(Yielding)
    for v in range(4):
        rt.send(a, Yielding.hit, v)
    # Count dispatches per host boundary (steps_run is no proxy: a
    # host-only boundary skips the device window entirely).
    per_boundary = []
    orig = rt._drain_host_fast

    def counted(budget):
        before = rt.totals["host_processed"]
        r = orig(budget)
        d = rt.totals["host_processed"] - before
        if d:
            per_boundary.append(d)
        return r

    rt._drain_host_fast = counted
    rt.run(max_steps=64)
    assert rt.state_of(a)["n"] == 4       # all arrive eventually...
    assert per_boundary == [1, 1, 1, 1]   # ...but one per boundary


def test_bulk_send_from_host_behaviour_is_not_stranded():
    """bulk_send writes device mailboxes directly (no inject queue); a
    host behaviour doing it mid-run must still get a device window —
    the host-only-boundary skip may not trust stale quiescence
    (round-5 review regression: _device_dirty)."""
    @actor
    class DevCounter:
        n: I32
        MAX_SENDS = 0

        @behaviour
        def bump(self, st, v: I32):
            return {**st, "n": st["n"] + v}

    @actor
    class HostKick:
        HOST = True
        done: I32

        @behaviour
        def kick(self, st, tgt: I32):
            self.rt.bulk_send(np.asarray([tgt]), DevCounter.bump,
                              np.asarray([5]))
            return {**st, "done": 1}

    rt = Runtime(RuntimeOptions(**OPTS))
    rt.declare(DevCounter, 1).declare(HostKick, 1).start()
    d = rt.spawn(DevCounter)
    h = rt.spawn(HostKick)
    assert rt.run(max_steps=8) == 0       # device quiesces empty
    rt.send(h, HostKick.kick, d)          # fast lane → bulk_send mid-run
    assert rt.run(max_steps=32) == 0
    assert int(rt.cohort_state(DevCounter)["n"][0]) == 5
    assert rt.state_of(h)["done"] == 1
