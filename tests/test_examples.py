"""Smoke-run the self-contained example programs (≙ the reference's
examples/ being part of its CI surface): each main() must complete its
own asserts. Net/terminal examples need sockets/tty and are exercised
by their dedicated suites (test_net*, test_bridge) instead."""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples"))


def test_spreader_tree():
    import spreader
    assert spreader.main(4) == 0


def test_heartbeat_timers():
    import heartbeat
    assert heartbeat.main() == 0


def test_blob_pipeline():
    import blob_pipeline
    assert blob_pipeline.main() == 0
