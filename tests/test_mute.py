"""Multi-receiver mute semantics (≙ mutemap.c + scheduler.c:1478-1635:
a receiver→set-of-muted-senders map; a sender unmutes only when *every*
muting receiver recovers).

The device design: each sender tracks up to K muting-receiver refs in
ref%K hash slots (state.mute_refs) with a sticky overflow bit for
collisions; the unmute pass releases a sender only when all tracked refs
have recovered (overflowed senders wait for a shard-quiet tick).
"""

import jax.numpy as jnp
import numpy as np

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.runtime.delivery import empty_mute_slots, mute_ref_slots


def test_mute_ref_slots_distinct_refs():
    n, k = 4, 4
    trig = jnp.array([True, True, False])
    rows = jnp.array([1, 1, 0], jnp.int32)
    refs = jnp.array([5, 6, 7], jnp.int32)      # 5%4=1, 6%4=2: no collision
    table, ovf = mute_ref_slots(trig, rows, refs, n=n, k=k)
    # table is [K slots, n senders] (planar; state.py layout note)
    assert table[1, 1] == 5 and table[2, 1] == 6
    assert not bool(ovf.any())
    assert (np.asarray(table)[:, 0] == -1).all()  # untriggered sender empty


def test_mute_ref_slots_collision_sets_overflow():
    n, k = 2, 4
    trig = jnp.array([True, True])
    rows = jnp.array([0, 0], jnp.int32)
    refs = jnp.array([3, 7], jnp.int32)         # both % 4 == 3: collide
    table, ovf = mute_ref_slots(trig, rows, refs, n=n, k=k)
    assert bool(ovf[0]) and not bool(ovf[1])
    assert table[3, 0] == 7                     # max kept


def test_mute_ref_slots_same_ref_twice_no_overflow():
    n, k = 2, 4
    trig = jnp.array([True, True])
    rows = jnp.array([0, 0], jnp.int32)
    refs = jnp.array([7, 7], jnp.int32)         # same receiver twice
    table, ovf = mute_ref_slots(trig, rows, refs, n=n, k=k)
    assert not bool(ovf.any())
    assert table[3, 0] == 7


@actor
class Slow:
    total: I32

    BATCH = 1          # deliberately slow consumer

    @behaviour
    def consume(self, st, v: I32):
        return {**st, "total": st["total"] + v}


@actor
class Fast:
    total: I32

    BATCH = 4          # recovers sooner than Slow

    @behaviour
    def consume(self, st, v: I32):
        return {**st, "total": st["total"] + v}


@actor
class Pusher:
    slow: Ref
    fast: Ref
    left: I32

    MAX_SENDS = 3

    @behaviour
    def produce(self, st, n: I32):
        self.send(st["slow"], Slow.consume, 1, when=n > 0)
        self.send(st["fast"], Fast.consume, 1, when=n > 0)
        self.send(self.actor_id, Pusher.produce, n - 1, when=n > 0)
        return {**st, "left": n - 1}


def _build(n_pushers=12, items=40):
    opts = RuntimeOptions(mailbox_cap=8, batch=2, msg_words=1,
                          max_sends=3, spill_cap=512, inject_slots=16)
    rt = Runtime(opts)
    rt.declare(Pusher, n_pushers).declare(Slow, 1).declare(Fast, 1)
    rt.start()
    slow = rt.spawn(Slow)
    fast = rt.spawn(Fast)
    ids = rt.spawn_many(Pusher, n_pushers, slow=slow, fast=fast)
    rt.bulk_send(ids, Pusher.produce, [items] * n_pushers)
    return rt, ids, slow, fast


def test_fanin_two_receivers_conservation_and_bounded_mutes():
    n_pushers, items = 12, 40
    rt, ids, slow, fast = _build(n_pushers, items)
    rt.run(max_steps=items * n_pushers * 8 + 200)
    assert rt.state_of(slow)["total"] == n_pushers * items
    assert rt.state_of(fast)["total"] == n_pushers * items
    assert not np.asarray(rt.state.muted).any(), "drained world still muted"
    # Mute volume sanity: release→burst→re-mute cycles are inherent to
    # lockstep backpressure (≙ the reference releasing a recovered
    # receiver's whole mutemap set at once), so mutes scale with items —
    # but never more than ~one mute per produced item. The *churn* the
    # multi-ref design eliminates (release while another muting receiver
    # is still hot) is checked exactly in
    # test_release_only_after_all_refs_recover.
    assert rt.counter("n_mutes") <= 2 * n_pushers * items, \
        rt.counter("n_mutes")


def test_release_only_after_all_refs_recover():
    """Step manually; any sender released between ticks must have had
    every tracked muting receiver already recovered (or overflow+quiet)."""
    rt, ids, slow, fast = _build(8, 30)
    opts = rt.opts
    inj = rt._empty_inject
    state = rt.state
    prev = None
    releases_checked = 0
    for _ in range(300):
        muted = np.asarray(state.muted)
        occ = np.asarray(state.tail) - np.asarray(state.head)
        refs = np.asarray(state.mute_refs)
        ovf = np.asarray(state.mute_ovf)
        dsp = np.asarray(state.dspill_tgt)
        dsp_pending = np.zeros(rt.program.total, bool)
        dsp_pending[dsp[dsp >= 0]] = True
        if prev is not None:
            released = prev["muted"] & ~muted
            for a in np.nonzero(released)[0]:
                rs = prev["refs"][:, a]
                rs = rs[rs >= 0]
                if prev["ovf"][a]:
                    assert (prev["occ"] <= opts.unmute_occ).all()
                else:
                    assert (prev["occ"][rs] <= opts.unmute_occ).all(), \
                        (a, rs, prev["occ"][rs])
                    assert not prev["dsp_pending"][rs].any()
                releases_checked += 1
        prev = dict(muted=muted, occ=occ, refs=refs, ovf=ovf,
                    dsp_pending=dsp_pending)
        state, aux = rt._step(state, *inj)
        if not bool(aux.device_pending):
            break
    rt.state = state
    assert releases_checked > 0, "scenario never exercised a release"
    assert rt.state_of(slow)["total"] == 8 * 30