"""Multi-receiver mute semantics (≙ mutemap.c + scheduler.c:1478-1635:
a receiver→set-of-muted-senders map; a sender unmutes only when *every*
muting receiver recovers).

The device design: each sender tracks up to K muting-receiver refs in
ref%K hash slots (state.mute_refs) with a sticky overflow bit for
collisions; the unmute pass releases a sender only when all tracked refs
have recovered (overflowed senders wait for a shard-quiet tick).
"""

import jax.numpy as jnp
import numpy as np

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.runtime.delivery import empty_mute_slots, mute_ref_slots


def test_mute_ref_slots_distinct_refs():
    n, k = 4, 4
    trig = jnp.array([True, True, False])
    rows = jnp.array([1, 1, 0], jnp.int32)
    refs = jnp.array([5, 6, 7], jnp.int32)      # 5%4=1, 6%4=2: no collision
    table, ovf = mute_ref_slots(trig, rows, refs, n=n, k=k)
    # table is [K slots, n senders] (planar; state.py layout note)
    assert table[1, 1] == 5 and table[2, 1] == 6
    assert not bool(ovf.any())
    assert (np.asarray(table)[:, 0] == -1).all()  # untriggered sender empty


def test_mute_ref_slots_collision_sets_overflow():
    n, k = 2, 4
    trig = jnp.array([True, True])
    rows = jnp.array([0, 0], jnp.int32)
    refs = jnp.array([3, 7], jnp.int32)         # both % 4 == 3: collide
    table, ovf = mute_ref_slots(trig, rows, refs, n=n, k=k)
    assert bool(ovf[0]) and not bool(ovf[1])
    assert table[3, 0] == 7                     # max kept


def test_mute_ref_slots_same_ref_twice_no_overflow():
    n, k = 2, 4
    trig = jnp.array([True, True])
    rows = jnp.array([0, 0], jnp.int32)
    refs = jnp.array([7, 7], jnp.int32)         # same receiver twice
    table, ovf = mute_ref_slots(trig, rows, refs, n=n, k=k)
    assert not bool(ovf.any())
    assert table[3, 0] == 7


@actor
class Slow:
    total: I32

    BATCH = 1          # deliberately slow consumer

    @behaviour
    def consume(self, st, v: I32):
        return {**st, "total": st["total"] + v}


@actor
class Fast:
    total: I32

    BATCH = 4          # recovers sooner than Slow

    @behaviour
    def consume(self, st, v: I32):
        return {**st, "total": st["total"] + v}


@actor
class Pusher:
    slow: Ref
    fast: Ref
    left: I32

    MAX_SENDS = 3

    @behaviour
    def produce(self, st, n: I32):
        self.send(st["slow"], Slow.consume, 1, when=n > 0)
        self.send(st["fast"], Fast.consume, 1, when=n > 0)
        self.send(self.actor_id, Pusher.produce, n - 1, when=n > 0)
        return {**st, "left": n - 1}


def _build(n_pushers=12, items=40):
    opts = RuntimeOptions(mailbox_cap=8, batch=2, msg_words=1,
                          max_sends=3, spill_cap=512, inject_slots=16)
    rt = Runtime(opts)
    rt.declare(Pusher, n_pushers).declare(Slow, 1).declare(Fast, 1)
    rt.start()
    slow = rt.spawn(Slow)
    fast = rt.spawn(Fast)
    ids = rt.spawn_many(Pusher, n_pushers, slow=slow, fast=fast)
    rt.bulk_send(ids, Pusher.produce, [items] * n_pushers)
    return rt, ids, slow, fast


def test_fanin_two_receivers_conservation_and_bounded_mutes():
    n_pushers, items = 12, 40
    rt, ids, slow, fast = _build(n_pushers, items)
    rt.run(max_steps=items * n_pushers * 8 + 200)
    assert rt.state_of(slow)["total"] == n_pushers * items
    assert rt.state_of(fast)["total"] == n_pushers * items
    assert not np.asarray(rt.state.muted).any(), "drained world still muted"
    # Mute volume sanity: release→burst→re-mute cycles are inherent to
    # lockstep backpressure (≙ the reference releasing a recovered
    # receiver's whole mutemap set at once), so mutes scale with items —
    # but never more than ~one mute per produced item. The *churn* the
    # multi-ref design eliminates (release while another muting receiver
    # is still hot) is checked exactly in
    # test_release_only_after_all_refs_recover.
    assert rt.counter("n_mutes") <= 2 * n_pushers * items, \
        rt.counter("n_mutes")


@actor
class Flooder:
    """Flood generator: each received ping fans two pings back at the
    peer (amplification 2, BATCH 1), keeping both mailboxes full with
    real traffic."""

    peer: Ref
    got: I32

    BATCH = 1
    MAX_SENDS = 2

    @behaviour
    def ping(self, st, v: I32):
        self.send(st["peer"], Flooder.ping, v - 1, when=v > 0)
        self.send(st["peer"], Flooder.ping, v - 1, when=v > 0)
        return {**st, "got": st["got"] + 1}


def _deadlocked_pair(mute_age_limit):
    """Build the TRUE mutual-mute deadlock: two actors with genuinely
    full mailboxes, each muted with the other as its (unrecovered,
    congested) muting ref. No release path exists except aging: each
    muter's occ stays above unmute_occ because the muter itself is
    muted and can never run to drain — the mute-cycle deadlock class
    the round-2 differential hunt found (ROUND3_NOTES.md), which the
    reference's pre-0.36 backpressure shares.

    Live sends can't assemble this state directly (the reference's
    !OVERLOADED sender guard, delivery.py `~sender_hot`, keeps two
    mutually-hot actors from muting each other), so the flood runs
    until both queues are full of real traffic and the mute tables are
    then set to the cycle — a unit fixture for the unmute pass.
    """
    opts = RuntimeOptions(mailbox_cap=4, batch=1, msg_words=1,
                          max_sends=2, spill_cap=2048, inject_slots=8,
                          mute_age_limit=mute_age_limit)
    rt = Runtime(opts)
    rt.declare(Flooder, 2)
    rt.start()
    a = rt.spawn(Flooder)
    b = rt.spawn(Flooder, peer=a)
    rt.set_fields(Flooder, np.asarray([a]), peer=np.asarray([b]))
    rt.bulk_send(np.asarray([a, b]), Flooder.ping, np.asarray([8, 8]))
    inj = rt._empty_inject
    state = rt.state
    for _ in range(40):   # fill both rings with real messages
        state, aux = rt._step(state, *inj)
    occ = np.asarray(state.tail) - np.asarray(state.head)
    assert (occ > rt.opts.unmute_occ).all(), occ
    refs = np.full_like(np.asarray(state.mute_refs), -1)
    refs[b % rt.opts.mute_slots, a] = b       # a muted by b
    refs[a % rt.opts.mute_slots, b] = a       # b muted by a
    import dataclasses
    rt.state = dataclasses.replace(
        state,
        muted=jnp.ones_like(state.muted),
        mute_refs=jnp.asarray(refs),
        mute_age=jnp.zeros_like(state.mute_age))
    return rt, a, b


def test_aging_breaks_true_mute_cycle():
    """With aging on, the mutual-mute deadlock drains to completion."""
    rt, a, b = _deadlocked_pair(mute_age_limit=4)
    rt.run(max_steps=6000)
    assert not np.asarray(rt.state.muted).any(), "cycle never broken"
    occ = np.asarray(rt.state.tail) - np.asarray(rt.state.head)
    assert (occ == 0).all(), "queues not drained after release"


def test_mute_age_limit_zero_disables_aging():
    """mute_age_limit <= 0 = exact reference semantics: the mutual-mute
    cycle deadlocks forever (documented divergence opt-out)."""
    rt, a, b = _deadlocked_pair(mute_age_limit=0)
    got0 = int(np.asarray(rt.state.type_state["Flooder"]["got"]).sum())
    rt.run(max_steps=400)
    assert np.asarray(rt.state.muted).all(), \
        "deadlocked pair released with aging disabled"
    got = int(np.asarray(rt.state.type_state["Flooder"]["got"]).sum())
    assert got == got0, "deadlocked world advanced with aging disabled"


def _flood_pair_mesh(mute_age_limit):
    """Cross-shard twin of _deadlocked_pair, formed NATURALLY: the two
    Flooders live on different shards (1 row each) and the tiny route
    bucket's rejections route-mute BOTH of them against each other
    within a few ticks (full mailboxes, cross mute refs, route spill
    oscillating) — the cross-shard mutual-mute cycle, no state surgery
    required."""
    opts = RuntimeOptions(mailbox_cap=4, batch=1, msg_words=1,
                          max_sends=2, spill_cap=2048, inject_slots=8,
                          mute_age_limit=mute_age_limit, mesh_shards=2,
                          route_bucket=1, quiesce_interval=1)
    rt = Runtime(opts)
    rt.declare(Flooder, 2)
    rt.start()
    a = rt.spawn(Flooder)
    b = rt.spawn(Flooder, peer=a)
    rt.set_fields(Flooder, np.asarray([a]), peer=np.asarray([b]))
    rt.bulk_send(np.asarray([a, b]), Flooder.ping, np.asarray([8, 8]))
    inj = rt._empty_inject
    state = rt.state
    for _ in range(10):
        state, aux = rt._step(state, *inj)
    muted = np.asarray(state.muted)
    refs = np.asarray(state.mute_refs)
    assert muted.all(), f"pair not mutually route-muted: {muted}"
    assert b in refs[:, a] and a in refs[:, b], refs
    occ = np.asarray(state.tail) - np.asarray(state.head)
    assert (occ > rt.opts.unmute_occ).all(), occ
    rt.state = state
    return rt, a, b


def test_aging_breaks_cross_shard_mute_cycle():
    """A mutual-mute cycle SPANNING SHARDS (route-muted, undeliverable
    route spill) still drains under aging: a remote muter that can never
    recover gives no in-flight hold."""
    rt, a, b = _flood_pair_mesh(mute_age_limit=4)
    rt.run(max_steps=8000)
    assert not np.asarray(rt.state.muted).any(), \
        "cross-shard cycle never broken (rspill hold deadlock)"
    occ = np.asarray(rt.state.tail) - np.asarray(rt.state.head)
    assert (occ == 0).all(), "queues not drained after release"
    assert int(np.asarray(rt.state.rspill_count).sum()) == 0
    # All flood work ran to exhaustion: 2 seeds × (2^9 - 1) dispatches.
    got = int(np.asarray(rt.state.type_state["Flooder"]["got"]).sum())
    assert got == 2 * (2 ** 9 - 1), got


def test_cross_shard_cycle_self_heals_without_aging():
    """Unlike the single-shard cycle (which freezes,
    test_mute_age_limit_zero_disables_aging), the CROSS-shard cycle
    self-heals even with aging disabled: the remote-ref release path
    (engine.py remote_ok — release once the local route spill drains)
    periodically frees each side, so the pair grinds to completion.
    Pinning this down documents that aging is only load-bearing for
    same-shard cycles."""
    rt, a, b = _flood_pair_mesh(mute_age_limit=0)
    rt.run(max_steps=20_000)
    got = int(np.asarray(rt.state.type_state["Flooder"]["got"]).sum())
    assert got == 2 * (2 ** 9 - 1), got
    assert not np.asarray(rt.state.muted).any()


def test_aged_release_waits_for_live_congested_muter():
    """Sustained fan-in against a slow-but-runnable receiver: aging must
    NOT fire while the muting receiver shows live congestion evidence
    and can still run (advisor round-3 medium: unconditional aged
    release grows the bounded spill until overflow). The workload must
    throttle to completion under muting, exactly as the reference does."""
    n_pushers, items = 16, 50
    opts = RuntimeOptions(mailbox_cap=8, batch=2, msg_words=1,
                          max_sends=3, spill_cap=256, inject_slots=16,
                          mute_age_limit=2)   # aggressive aging
    rt = Runtime(opts)
    rt.declare(Pusher, n_pushers).declare(Slow, 1).declare(Fast, 1)
    rt.start()
    slow = rt.spawn(Slow)
    fast = rt.spawn(Fast)
    ids = rt.spawn_many(Pusher, n_pushers, slow=slow, fast=fast)
    rt.bulk_send(ids, Pusher.produce, [items] * n_pushers)
    inj = rt._empty_inject
    state = rt.state
    prev = None
    max_age_seen = 0
    for _ in range(3000):
        muted = np.asarray(state.muted)
        occ = np.asarray(state.tail) - np.asarray(state.head)
        refs = np.asarray(state.mute_refs)
        alive = np.asarray(state.alive)
        dsp = np.asarray(state.dspill_tgt)
        dsp_pending = np.zeros(rt.program.total, bool)
        dsp_pending[dsp[dsp >= 0]] = True
        if prev is not None:
            released = prev["muted"] & ~muted
            for s in np.nonzero(released)[0]:
                rs = prev["refs"][:, s]
                rs = rs[rs >= 0]
                live_congested = [
                    r for r in rs
                    if (prev["occ"][r] > opts.unmute_occ
                        or prev["dsp_pending"][r])
                    and prev["alive"][r] and not prev["muted"][r]]
                assert not live_congested, (
                    f"sender {s} released while muter(s) {live_congested} "
                    f"were runnable and still congested")
        max_age_seen = max(max_age_seen,
                           int(np.asarray(state.mute_age).max()))
        prev = dict(muted=muted, occ=occ, refs=refs, alive=alive,
                    dsp_pending=dsp_pending)
        state, aux = rt._step(state, *inj)
        assert not bool(aux.spill_overflow), \
            "aged releases blew the bounded spill"
        if not bool(aux.device_pending):
            break
    rt.state = state
    assert rt.state_of(slow)["total"] == n_pushers * items
    assert rt.state_of(fast)["total"] == n_pushers * items
    # Not vacuous: senders stayed muted well past the aging threshold
    # (limit=2 staggers thresholds into [2, 4)), i.e. aging was
    # age-eligible and the live-congestion veto is what held it.
    assert max_age_seen >= 2 * opts.mute_age_limit, max_age_seen


def test_aged_release_waits_cross_shard():
    """The mesh twin of the live-congestion aging veto: senders mute
    against a slow-but-runnable receiver on ANOTHER shard, whose
    congestion they can only see through the all-gathered live_cong
    bits. With aggressive aging (limit=2), no sender may be released
    while any of its tracked muters — local or remote — is alive,
    unmuted, and still congested (occ or pending spill)."""
    n_pushers, items = 32, 40
    opts = RuntimeOptions(mailbox_cap=8, batch=2, msg_words=1,
                          max_sends=3, spill_cap=4096, inject_slots=64,
                          mute_age_limit=2, mesh_shards=4,
                          quiesce_interval=1, route_bucket=8)
    rt = Runtime(opts)
    rt.declare(Pusher, n_pushers).declare(Slow, 1).declare(Fast, 1)
    rt.start()
    slow = rt.spawn(Slow)
    fast = rt.spawn(Fast)
    ids = rt.spawn_many(Pusher, n_pushers, slow=slow, fast=fast)
    rt.bulk_send(ids, Pusher.produce, [items] * n_pushers)
    p, nl = rt.program.shards, rt.program.n_local
    prev = None
    max_age_seen = 0
    cross_shard_mutes = 0
    for _ in range(4000):
        st = rt.state
        muted = np.asarray(st.muted)
        occ = np.asarray(st.tail) - np.asarray(st.head)
        refs = np.asarray(st.mute_refs)          # global ref ids
        alive = np.asarray(st.alive)
        ovf = np.asarray(st.mute_ovf)
        dsp = np.asarray(st.dspill_tgt).reshape(p, -1)
        pending = np.zeros(rt.program.total, bool)
        for s in range(p):
            loc = dsp[s][dsp[s] >= 0]
            pending[s * nl + loc] = True
        if prev is not None:
            released = prev["muted"] & ~muted
            for g in np.nonzero(released)[0]:
                if prev["ovf"][g]:
                    continue
                rs = prev["refs"][:, g]
                rs = rs[rs >= 0]
                local = rs[rs // nl == g // nl]
                remote = rs[rs // nl != g // nl]
                live = [r for r in rs
                        if (prev["occ"][r] > opts.unmute_occ
                            or prev["pending"][r])
                        and prev["alive"][r] and not prev["muted"][r]]
                # A live-congested LOCAL muter blocks every release path
                # (normal local_ok and the aged veto alike).
                assert not [r for r in live if r in local], (
                    f"sender {g} released past live local muter(s)")
                # With a remote ref and a non-empty local route spill,
                # neither remote_ok (spill not drained) nor aging (the
                # has_remote hold) may release. With the spill drained,
                # remote_ok releases even into a still-congested remote
                # receiver — the documented divergence (engine.py
                # remote_ok comment: routing re-mutes if it persists) —
                # so that case is allowed.
                if len(remote) and prev["rspill"][g // nl] > 0:
                    raise AssertionError(
                        f"sender {g} released while its shard's route "
                        f"spill held {prev['rspill'][g // nl]} messages "
                        "(cross-shard aging veto hole)")
        for g in np.nonzero(muted)[0]:
            rs = refs[:, g]
            if any(r >= 0 and r // nl != g // nl for r in rs):
                cross_shard_mutes += 1
        max_age_seen = max(max_age_seen, int(np.asarray(st.mute_age).max()))
        prev = dict(muted=muted, occ=occ, refs=refs, alive=alive,
                    pending=pending, ovf=ovf,
                    rspill=np.asarray(st.rspill_count))
        rt.run(max_steps=1)
        if (rt.state_of(slow)["total"] == n_pushers * items
                and rt.state_of(fast)["total"] == n_pushers * items):
            break
    assert rt.state_of(slow)["total"] == n_pushers * items
    assert rt.state_of(fast)["total"] == n_pushers * items
    assert cross_shard_mutes > 0, "never saw a cross-shard mute ref"
    assert max_age_seen >= 2 * opts.mute_age_limit, max_age_seen
    rt.run(max_steps=100)
    assert not np.asarray(rt.state.muted).any()


def test_release_only_after_all_refs_recover():
    """Step manually; any sender released between ticks must have had
    every tracked muting receiver already recovered (or overflow+quiet)."""
    rt, ids, slow, fast = _build(8, 30)
    opts = rt.opts
    inj = rt._empty_inject
    state = rt.state
    prev = None
    releases_checked = 0
    for _ in range(300):
        muted = np.asarray(state.muted)
        occ = np.asarray(state.tail) - np.asarray(state.head)
        refs = np.asarray(state.mute_refs)
        ovf = np.asarray(state.mute_ovf)
        dsp = np.asarray(state.dspill_tgt)
        dsp_pending = np.zeros(rt.program.total, bool)
        dsp_pending[dsp[dsp >= 0]] = True
        if prev is not None:
            released = prev["muted"] & ~muted
            for a in np.nonzero(released)[0]:
                rs = prev["refs"][:, a]
                rs = rs[rs >= 0]
                if prev["ovf"][a]:
                    assert (prev["occ"] <= opts.unmute_occ).all()
                else:
                    assert (prev["occ"][rs] <= opts.unmute_occ).all(), \
                        (a, rs, prev["occ"][rs])
                    assert not prev["dsp_pending"][rs].any()
                releases_checked += 1
        prev = dict(muted=muted, occ=occ, refs=refs, ovf=ovf,
                    dsp_pending=dsp_pending)
        state, aux = rt._step(state, *inj)
        if not bool(aux.device_pending):
            break
    rt.state = state
    assert releases_checked > 0, "scenario never exercised a release"
    assert rt.state_of(slow)["total"] == 8 * 30