"""The verify pass (ponyc_tpu/verify.py ≙ verify/fun.c): per-behaviour
effect signatures by probe tracing, budget enforcement, docgen marks,
and the CLI command."""

import pytest

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions, actor,
                       behaviour)
from ponyc_tpu.verify import (VerifyError, behaviour_effects,
                              verify_behaviour, verify_program)


@actor
class Quiet:
    x: I32

    @behaviour
    def set(self, st, v: I32):
        return {**st, "x": v}


@actor
class Busy:
    out: Ref["Quiet"]
    MAX_SENDS = 2
    SPAWNS = {"Quiet": 1}

    @behaviour
    def go(self, st, v: I32):
        self.send(st["out"], Quiet.set, v)
        self.spawn(Quiet.set, v, when=v > 0)
        self.error_int(7, when=v < 0)
        self.exit(0, when=v == 0)
        return st

    @behaviour
    def lazy(self, st, v: I32):
        self.yield_(when=v > 3)
        self.destroy(when=v > 9)
        return st


def test_effect_signatures():
    eff = behaviour_effects(Quiet.set)
    assert eff.sends == 0 and not eff.can_error and not eff.can_exit
    assert eff.marks() == ""

    eff = behaviour_effects(Busy.go)
    assert eff.sends == 2          # explicit send + the spawn's ctor msg
    assert eff.can_error and eff.can_exit
    assert eff.spawns == (("Quiet", 1),)
    assert "may error" in eff.marks() and "spawns Quiet×1" in eff.marks()

    eff = behaviour_effects(Busy.lazy)
    assert eff.can_yield and eff.can_destroy and eff.sends == 0


def test_budget_violation_fails_verify():
    @actor
    class OverBudget:
        out: Ref["Quiet"]
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            self.send(st["out"], Quiet.set, v)
            self.send(st["out"], Quiet.set, v + 1)
            return st

    with pytest.raises(VerifyError, match="MAX_SENDS=1"):
        verify_behaviour(OverBudget.go)


def test_verify_program_reports_all_device_cohorts():
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=2,
                                msg_words=2, inject_slots=8))
    rt.declare(Busy, 1).declare(Quiet, 4).start()
    report = verify_program(rt.program)
    assert set(report) == {"Busy", "Quiet"}
    assert report["Busy"]["go"].can_error
    assert not report["Quiet"]["set"].can_error


def test_docgen_carries_effect_marks():
    from ponyc_tpu.docgen import document_type
    md = document_type(Busy)
    assert "may error" in md and "spawns Quiet×1" in md


def test_host_behaviours_report_no_device_effects():
    @actor
    class H:
        HOST = True
        n: I32

        @behaviour
        def tick(self, st, v: I32):
            return {**st, "n": st["n"] + 1}

    eff = behaviour_effects(H.tick)
    assert eff.marks() == ""


def test_budget_matches_engine_resolution():
    """Budgets resolve exactly as program build does (review finding):
    opts.max_sends is the fallback, MAX_SENDS=0 is falsy -> fallback."""
    @actor
    class ThreeSends:
        out: Ref["Quiet"]

        @behaviour
        def go(self, st, v: I32):
            self.send(st["out"], Quiet.set, v)
            self.send(st["out"], Quiet.set, v)
            self.send(st["out"], Quiet.set, v)
            return st

    # opts.max_sends=3 -> fine, exactly like the engine
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=3,
                                msg_words=2, inject_slots=8))
    rt.declare(ThreeSends, 1).declare(Quiet, 1).start()
    report = verify_program(rt.program)
    assert report["ThreeSends"]["go"].sends == 3
    # standalone default (2) rejects the same behaviour
    with pytest.raises(VerifyError):
        verify_behaviour(ThreeSends.go)
    # ... unless told the real default
    assert verify_behaviour(ThreeSends.go, default_max_sends=3).sends == 3


def test_string_spawns_target_probes_clean():
    """String-form SPAWNS targets with spawn_sync must probe without a
    bogus state-dict error (review finding: the ctor is claim-only in
    the probe)."""
    @actor
    class SKid:
        x: I32

        @behaviour
        def init(self, st, v: I32):
            return {**st, "x": v}

    @actor
    class SParent:
        MAX_SENDS = 1
        SPAWNS = {"SKid": 1}

        @behaviour
        def make(self, st, v: I32):
            self.spawn_sync(SKid.init, v)
            return st

    eff = behaviour_effects(SParent.make)
    assert eff.sync_spawns == ("SKid",)


def test_cli_verify_reports_fail_lines(tmp_path):
    import os
    import subprocess
    import sys
    mod = tmp_path / "vmod.py"
    mod.write_text(
        "from ponyc_tpu import I32, Ref, actor, behaviour\n"
        "@actor\n"
        "class Sink:\n"
        "    x: I32\n"
        "    @behaviour\n"
        "    def put(self, st, v: I32):\n"
        "        return {**st, 'x': v}\n"
        "@actor\n"
        "class Bad:\n"
        "    out: Ref['Sink']\n"
        "    MAX_SENDS = 1\n"
        "    @behaviour\n"
        "    def go(self, st, v: I32):\n"
        "        self.send(st['out'], Sink.put, v)\n"
        "        self.send(st['out'], Sink.put, v)\n"
        "        return st\n")
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = root
    r = subprocess.run([sys.executable, "-m", "ponyc_tpu", "verify",
                        "vmod"], cwd=str(tmp_path), env=env,
                       capture_output=True, text=True, timeout=240)
    assert r.returncode == 1, r.stderr[-500:]
    assert "FAIL Bad.go" in r.stdout and "ok   Sink.put" in r.stdout
