"""Host-API property fuzz: random op sequences against a live Runtime
with queue/flag invariants checked throughout (≙ the reference's
debug-build invariant checkers, actor.c:57-92 + messageq_size_debug,
exercised here through the public host surface instead of C asserts)."""

import numpy as np
import pytest

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu import serialise
from ponyc_tpu.stdlib import backpressure as bp


@actor
class Node:
    acc: I32
    peer: Ref["Node"]

    MAX_SENDS = 1

    @behaviour
    def poke(self, st, v: I32):
        self.send(st["peer"], Node.poke, v - 1, when=(v > 0)
                  & (st["peer"] >= 0))
        return {**st, "acc": st["acc"] + v}


@pytest.mark.parametrize("seed", [3, 11, 42])
def test_random_host_op_sequences_keep_invariants(seed, tmp_path):
    rng = np.random.default_rng(seed)
    cap = 24
    rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=2, msg_words=1,
                                max_sends=1, spill_cap=256,
                                inject_slots=16, debug_checks=True))
    rt.declare(Node, cap).start()
    live = list(rt.spawn_many(Node, 8))
    for a in live:
        rt.set_fields(Node, np.asarray([a]),
                      peer=np.asarray([int(rng.choice(live))]))
    auth = bp.ApplyReleaseBackpressureAuth(rt.ambient_auth())
    pressured = set()
    sent = 0
    for step in range(120):
        op = rng.integers(0, 8)
        if op == 0 and len(live) < cap:                 # spawn
            a = rt.spawn(Node, peer=int(rng.choice(live)))
            live.append(a)
        elif op == 1:                                   # send
            v = int(rng.integers(1, 9))
            rt.send(int(rng.choice(live)), Node.poke, v)
            sent += 1
        elif op == 2:                                   # advance
            rt.run(max_steps=int(rng.integers(1, 6)))
        elif op == 3 and live:                          # pressure on/off
            t = int(rng.choice(live))
            if t in pressured:
                bp.release(auth, t)
                pressured.discard(t)
            else:
                bp.apply(auth, t)
                pressured.add(t)
        elif op == 4:                                   # gc
            rt.gc()
        elif op == 5 and len(live) > 4:                 # release a ref
            t = live[int(rng.integers(0, len(live)))]
            rt.release([t])
            # released-but-referenced actors stay alive via peers; the
            # id may still be messaged until collected — keep using it
            # only if still alive after a gc
            rt.gc()
            if not bool(np.asarray(rt.state.alive)[t]):
                live.remove(t)
        elif op == 6:                                   # introspection
            t = int(rng.choice(live))
            assert rt.queue_depth(t) >= 0
            rt.last_error(t)
            rt.total_memory()
        elif op == 7 and step % 40 == 20:               # checkpoint trip
            p = str(tmp_path / f"fuzz_{seed}_{step}.npz")
            serialise.save(rt, p)
            serialise.restore(rt, p)
        rt.check_invariants()
    # quiesce fully: everything sent must be conserved into acc sums
    for t in list(pressured):
        bp.release(auth, t)
    assert rt.run(max_steps=50_000) == 0
    rt.check_invariants()
    assert not np.asarray(rt.state.muted).any()


@pytest.mark.parametrize("seed", [5, 23, 91])
def test_random_host_blob_op_sequences_match_model(seed):
    """Host blob surface fuzz: random store/fetch/free/send/run
    sequences against a python MODEL of the pool; stale fetches and
    double frees must reject exactly when the model says the handle is
    dead (even after slot recycling — generation mismatch), gc must
    reclaim exactly the unrooted unreferenced slots, and counters must
    reconcile."""
    from ponyc_tpu import Blob

    @actor
    class Sink:
        total: I32

        @behaviour
        def eat(self, st, h: Blob):
            st["total"] = st["total"] + self.blob_get(h, 0)
            self.blob_free(h)
            return st

    rng = np.random.default_rng(seed)
    BSL = 6
    rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=2, msg_words=2,
                                max_sends=1, spill_cap=64,
                                inject_slots=8,
                                blob_slots=BSL, blob_words=2))
    rt.declare(Sink, 2).start()
    sink = rt.spawn(Sink, total=0)
    model = {}           # handle -> word0 (host-rooted, alive)
    dead = []            # handles the model says are gone (moved/freed)
    eaten = 0
    for _ in range(120):
        op = rng.random()
        if op < 0.35:                      # store (may exhaust)
            v = int(rng.integers(0, 1000))
            in_use = rt.blobs_in_use
            from ponyc_tpu import BlobCapacityError
            try:
                h = rt.blob_store([v])
                assert in_use < BSL, "store succeeded on a full pool"
                model[h] = v
            except BlobCapacityError:
                assert in_use == BSL, (in_use, BSL)
        elif op < 0.50 and model:          # fetch a live handle
            h = int(rng.choice(list(model)))
            assert int(rt.blob_fetch(h)[0]) == model[h]
        elif op < 0.60 and dead:           # poke a DEAD handle: both
            h = int(rng.choice(dead))      # fetch and double-free must
            if h not in model:             # reject, even after the slot
                #                            recycled (gen mismatch)
                with pytest.raises((KeyError, IndexError)):
                    rt.blob_fetch(h)
                with pytest.raises((KeyError, IndexError)):
                    rt.blob_free_host(h)
        elif op < 0.72 and model:          # free
            h = int(rng.choice(list(model)))
            rt.blob_free_host(h)
            del model[h]
            dead.append(h)
        elif op < 0.85 and model:          # send to the sink (move)
            h = int(rng.choice(list(model)))
            rt.send(sink, Sink.eat, h)
            rt.run(max_steps=6)            # sink eats + frees
            with pytest.raises((KeyError, IndexError)):
                rt.blob_fetch(h)           # consumed: handle now dead
            eaten += model.pop(h)
            dead.append(h)
        else:                              # settle + audit
            rt.run(max_steps=4)
            rt.gc()
            # Exactly the rooted handles survive collection.
            assert rt.blobs_in_use == len(model), (
                rt.blobs_in_use, model)
            for h, v in model.items():
                assert int(rt.blob_fetch(h)[0]) == v
    rt.run(max_steps=10)
    assert rt.state_of(sink)["total"] == eaten
    stats = (rt.counter("n_blob_alloc"), rt.counter("n_blob_free"))
    assert stats[0] - stats[1] == rt.blobs_in_use
