"""Host-API property fuzz: random op sequences against a live Runtime
with queue/flag invariants checked throughout (≙ the reference's
debug-build invariant checkers, actor.c:57-92 + messageq_size_debug,
exercised here through the public host surface instead of C asserts)."""

import numpy as np
import pytest

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu import serialise
from ponyc_tpu.stdlib import backpressure as bp


@actor
class Node:
    acc: I32
    peer: Ref["Node"]

    MAX_SENDS = 1

    @behaviour
    def poke(self, st, v: I32):
        self.send(st["peer"], Node.poke, v - 1, when=(v > 0)
                  & (st["peer"] >= 0))
        return {**st, "acc": st["acc"] + v}


@pytest.mark.parametrize("seed", [3, 11, 42])
def test_random_host_op_sequences_keep_invariants(seed, tmp_path):
    rng = np.random.default_rng(seed)
    cap = 24
    rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=2, msg_words=1,
                                max_sends=1, spill_cap=256,
                                inject_slots=16, debug_checks=True))
    rt.declare(Node, cap).start()
    live = list(rt.spawn_many(Node, 8))
    for a in live:
        rt.set_fields(Node, np.asarray([a]),
                      peer=np.asarray([int(rng.choice(live))]))
    auth = bp.ApplyReleaseBackpressureAuth(rt.ambient_auth())
    pressured = set()
    sent = 0
    for step in range(120):
        op = rng.integers(0, 8)
        if op == 0 and len(live) < cap:                 # spawn
            a = rt.spawn(Node, peer=int(rng.choice(live)))
            live.append(a)
        elif op == 1:                                   # send
            v = int(rng.integers(1, 9))
            rt.send(int(rng.choice(live)), Node.poke, v)
            sent += 1
        elif op == 2:                                   # advance
            rt.run(max_steps=int(rng.integers(1, 6)))
        elif op == 3 and live:                          # pressure on/off
            t = int(rng.choice(live))
            if t in pressured:
                bp.release(auth, t)
                pressured.discard(t)
            else:
                bp.apply(auth, t)
                pressured.add(t)
        elif op == 4:                                   # gc
            rt.gc()
        elif op == 5 and len(live) > 4:                 # release a ref
            t = live[int(rng.integers(0, len(live)))]
            rt.release([t])
            # released-but-referenced actors stay alive via peers; the
            # id may still be messaged until collected — keep using it
            # only if still alive after a gc
            rt.gc()
            if not bool(np.asarray(rt.state.alive)[t]):
                live.remove(t)
        elif op == 6:                                   # introspection
            t = int(rng.choice(live))
            assert rt.queue_depth(t) >= 0
            rt.last_error(t)
            rt.total_memory()
        elif op == 7 and step % 40 == 20:               # checkpoint trip
            p = str(tmp_path / f"fuzz_{seed}_{step}.npz")
            serialise.save(rt, p)
            serialise.restore(rt, p)
        rt.check_invariants()
    # quiesce fully: everything sent must be conserved into acc sums
    for t in list(pressured):
        bp.release(auth, t)
    assert rt.run(max_steps=50_000) == 0
    rt.check_invariants()
    assert not np.asarray(rt.state.muted).any()
