#!/usr/bin/env python
"""Extended differential bug hunt — the long-running version of
tests/test_differential.py, run as a one-off (not under pytest):

    python tests/hunt.py [n_seeds] [first_seed] [--fifo|--blob]

--fifo runs the order-sensitive per-edge FIFO marathon (test_fifo.py
scenarios) instead of the commutative-outcome differential; --blob
runs randomized blob-chain worlds (device payload pool: alloc/free
churn per hop, iso moves, cross-shard migration) against the
sequential oracle.

Random world sizes and traffic per seed, rotating configurations
(tiny-cap single chip, cosort, fused kernel, 4/8-shard meshes with tiny
route buckets). Any mismatch against the sequential oracle or failure to
quiesce prints FAIL lines and exits nonzero. The round-3 campaign ran
30 single-chip + 12 mesh seeds clean after fixing the mute-cycle
deadlock this harness found (ROUND3_NOTES.md)."""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ponyc_tpu.platforms import force_cpu  # noqa: E402

force_cpu(8)

import numpy as np  # noqa: E402

from ponyc_tpu import RuntimeOptions  # noqa: E402
import test_differential as td  # noqa: E402

CONFIGS = {
    "tiny": dict(mailbox_cap=2, batch=1, msg_words=1, max_sends=2,
                 spill_cap=2048, inject_slots=16),
    "cosort": dict(mailbox_cap=4, batch=2, msg_words=1, max_sends=2,
                   spill_cap=2048, inject_slots=16, delivery="cosort"),
    "fused": dict(mailbox_cap=4, batch=2, msg_words=1, max_sends=2,
                  spill_cap=2048, inject_slots=16, pallas_fused=True),
    "gated": dict(mailbox_cap=4, batch=2, msg_words=1, max_sends=2,
                  spill_cap=2048, inject_slots=16, dispatch_gating=True),
    "mesh4": dict(mailbox_cap=2, batch=1, msg_words=1, max_sends=2,
                  spill_cap=4096, inject_slots=64, mesh_shards=4,
                  quiesce_interval=2),
    "mesh8-bucket": dict(mailbox_cap=4, batch=2, msg_words=1,
                         max_sends=2, spill_cap=4096, inject_slots=64,
                         mesh_shards=8, route_bucket=8,
                         quiesce_interval=1),
}


BLOB_CONFIGS = {
    "tiny": dict(mailbox_cap=2, batch=1, max_sends=1, spill_cap=1024,
                 inject_slots=16),
    "cosort": dict(mailbox_cap=4, batch=2, max_sends=1, spill_cap=1024,
                   inject_slots=16, delivery="cosort"),
    "mesh2": dict(mailbox_cap=2, batch=1, max_sends=1, spill_cap=2048,
                  inject_slots=16, mesh_shards=2, quiesce_interval=2),
    "mesh4-bucket": dict(mailbox_cap=2, batch=1, max_sends=1,
                         spill_cap=4096, inject_slots=32, mesh_shards=4,
                         route_bucket=4, quiesce_interval=1),
    "aged": dict(mailbox_cap=2, batch=1, max_sends=1, spill_cap=1024,
                 inject_slots=16, mute_age_limit=2),
}


def _marathon(n_seeds, first, configs, run_seed, label):
    """Shared per-seed driver for the call-one-function marathons
    (fifo/blob): rotate configs, record failures, summarise."""
    fails = []
    t0 = time.time()
    names = list(configs)
    for n, seed in enumerate(range(first, first + n_seeds)):
        cfg = names[n % len(names)]
        try:
            detail = run_seed(seed, cfg, configs[cfg])
        except Exception as e:                  # noqa: BLE001
            fails.append((seed, cfg, repr(e)[:200]))
            detail = ""
        print(f"{label} seed {seed} ({cfg}{detail}): "
              f"{'FAIL' if fails and fails[-1][0] == seed else 'ok'}",
              flush=True)
    print(f"\n{n_seeds - len(fails)}/{n_seeds} {label} ok "
          f"in {time.time() - t0:.0f}s")
    for f in fails:
        print("FAIL:", f)
    return 1 if fails else 0


def main_blob(n_seeds, first):
    """Blob-chain marathon: randomized worlds through td.run_blob_chain
    (alloc/free churn every hop, generation recycling, migration under
    tiny route buckets); any oracle mismatch, leak, or dead arrival
    fails the seed."""
    def run_seed(seed, _cfg, kw):
        td.run_blob_chain(seed, kw)
        return ""
    return _marathon(n_seeds, first, BLOB_CONFIGS, run_seed, "blob")


FIFO_CONFIGS = {
    "tiny": dict(mailbox_cap=2, batch=1, max_sends=3, spill_cap=4096,
                 inject_slots=16),
    "cosort": dict(mailbox_cap=4, batch=2, max_sends=3, spill_cap=4096,
                   inject_slots=16, delivery="cosort"),
    "aged": dict(mailbox_cap=2, batch=1, max_sends=3, spill_cap=4096,
                 inject_slots=16, mute_age_limit=2),
    "fused": dict(mailbox_cap=4, batch=2, max_sends=3, spill_cap=4096,
                  inject_slots=16, pallas_fused=True),
    "gated": dict(mailbox_cap=4, batch=2, max_sends=3, spill_cap=4096,
                  inject_slots=16, dispatch_gating=True),
    "mesh4-bucket": dict(mailbox_cap=2, batch=1, max_sends=3,
                         spill_cap=8192, inject_slots=32, mesh_shards=4,
                         route_bucket=8, quiesce_interval=2),
    # blob-bind:* rows run the payload<->message BINDING fifo variant
    # (run_blob_fifo): stamps ride both a word and the blob.
    "blob-bind:tiny": dict(mailbox_cap=2, batch=1, max_sends=2,
                           spill_cap=4096, inject_slots=16),
    "blob-bind:mesh4": dict(mailbox_cap=2, batch=1, max_sends=2,
                            spill_cap=8192, inject_slots=32,
                            mesh_shards=4, route_bucket=4,
                            quiesce_interval=2),
}


def main_fifo(n_seeds, first):
    """Order-sensitive marathon: random fan-in wiring + stream lengths,
    per-edge sequence stamps verified on device (test_fifo.run_fifo) —
    a single FIFO inversion anywhere in delivery/spill/route/aged-unmute
    fails the seed."""
    import test_fifo as tf

    def run_seed(seed, cfg, kw):
        rng = np.random.default_rng(seed)
        n_cons = int(rng.integers(3, 12))
        items = int(rng.integers(20, 90))
        if cfg.startswith("blob-bind:"):
            tf.run_blob_fifo(seed, kw, n_cons=n_cons, items=items)
        else:
            tf.run_fifo(seed, kw, n_cons=n_cons, items=items)
        return f", n_cons={n_cons}, items={items}"
    return _marathon(n_seeds, first, FIFO_CONFIGS, run_seed, "fifo")


def main():
    argv = [a for a in sys.argv[1:] if a not in ("--fifo", "--blob")]
    fifo = "--fifo" in sys.argv[1:]
    blob = "--blob" in sys.argv[1:]
    n_seeds = int(argv[0]) if len(argv) > 0 else 10
    first = int(argv[1]) if len(argv) > 1 else 1000
    if fifo:
        return main_fifo(n_seeds, first)
    if blob:
        return main_blob(n_seeds, first)
    fails = []
    t0 = time.time()
    names = list(CONFIGS)
    for n, seed in enumerate(range(first, first + n_seeds)):
        rng = np.random.default_rng(seed)
        n_w = int(rng.integers(12, 80))
        n_s = int(rng.integers(4, 24))
        w_nxt, s_w, s_s, seeds = td._case(seed, n_w, n_s,
                                          n_seeds=12, vmax=16)
        want = td.oracle(n_w, n_s, w_nxt, s_w, s_s, seeds)
        cfg = names[n % len(names)]
        try:
            got = td.run_device(n_w, n_s, w_nxt, s_w, s_s, seeds,
                                RuntimeOptions(**CONFIGS[cfg]))
            if not all((g == w).all() for g, w in zip(got, want)):
                fails.append((seed, cfg, "MISMATCH"))
        except Exception as e:                  # noqa: BLE001
            fails.append((seed, cfg, repr(e)[:160]))
        print(f"seed {seed} ({cfg}, n_w={n_w}, n_s={n_s}): "
              f"{'FAIL' if fails and fails[-1][0] == seed else 'ok'}",
              flush=True)
    print(f"\n{n_seeds - len(fails)}/{n_seeds} ok "
          f"in {time.time() - t0:.0f}s")
    for f in fails:
        print("FAIL:", f)
    return 1 if fails else 0


if __name__ == "__main__":
    sys.exit(main())
