"""Worker for test_distributed's engine-across-processes tests.

Run identically on every rank (argv: coordinator rank nprocs); each rank
owns 4 virtual CPU devices, the global mesh has nprocs*4 shards, and the
ACTOR ENGINE itself (not just a bare psum) runs over the process
boundary: ubench traffic and a cross-shard ring, with the same
conservation counters dryrun_multichip checks (__graft_entry__.py).

Host-side determinism contract: every rank performs the SAME host calls
(spawns, seeds, run loop) so the replicated inject buffers and jit
dispatch counts stay in lockstep — the multi-controller SPMD programming
model (one controller per host, identical traces), which is how every
multi-host JAX program is driven.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

coord, rank, nprocs = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PYTHONPATH", None)
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")

import ponyc_tpu.parallel.distributed as dist          # noqa: E402

dist.initialize(coordinator=coord, num_processes=nprocs, process_id=rank)

import jax                                             # noqa: E402
import numpy as np                                     # noqa: E402

assert jax.process_count() == nprocs, jax.process_count()
assert len(jax.devices()) == 4 * nprocs

from ponyc_tpu import RuntimeOptions                   # noqa: E402
from ponyc_tpu.models import ring, ubench              # noqa: E402

shards = 4 * nprocs

# --- 1. ubench: sustained all-to-all traffic over the process boundary.
n, pings, hops = 64, 2, 40
opts = RuntimeOptions(mailbox_cap=4, batch=pings, max_sends=1,
                      msg_words=1, spill_cap=512, inject_slots=8,
                      mesh_shards=shards, quiesce_interval=2)
rt, ids = ubench.build(n, opts, pings=pings)
ubench.seed_all(rt, ids, hops=hops, pings=pings)
rc = rt.run(max_steps=20_000)
assert rc == 0, rc
# Conservation (≙ dryrun_multichip): every seeded chain ran to
# exhaustion — hops+1 dispatches per seed, none lost, none duplicated.
done = rt.counter("n_processed")
assert done == n * pings * (hops + 1), (done, n * pings * (hops + 1))
print(f"RANK{rank}_UBENCH_OK processed={done}", flush=True)

# --- 2. ring whose every hop crosses a shard (and every 4th hop crosses
# the PROCESS boundary): one node per shard.
ring_hops = 64
opts2 = RuntimeOptions(mailbox_cap=4, batch=1, max_sends=1, msg_words=1,
                       spill_cap=64, inject_slots=8, mesh_shards=shards,
                       quiesce_interval=2)
rt2, ids2 = ring.build(shards, opts2)
rt2.send(int(ids2[0]), ring.RingNode.token, ring_hops)
rc2 = rt2.run(max_steps=20_000)
assert rc2 == 0, rc2
done2 = rt2.counter("n_processed")
assert done2 == ring_hops, (done2, ring_hops)
print(f"RANK{rank}_RING_OK hops={done2}", flush=True)

# --- 3. pressure fan-in across the boundary: every shard's producers
# flood one aggregator on shard 0 through a tiny route bucket, so the
# route-spill → mute → retry → unmute machinery itself crosses
# processes (the dryrun_multichip pressure scenario, but with the muted
# senders spread over BOTH OS processes). Reuses the shared fan-in
# model (ponyc_tpu/models/fanin.py) — one protocol definition for the
# bench, the dryrun, and this worker.
#
# XLA:CPU limitation: cross-process CPU collectives (gloo — enabled by
# distributed.initialize; the backend refuses multiprocess computations
# without it) abort with mismatched-op errors
# (`gloo/transport/tcp/pair.cc op.preamble.length <= op.nbytes`) under
# this stage's fetch-heavy pressure loop, where process_allgather
# fetches interleave with step collectives. Stages 1-2 prove the engine
# across the process boundary; the pressure machinery itself is covered
# single-process by tests/test_mesh_pressure.py. Run stage 3 on real
# multi-host backends (or force with PONY_TPU_DIST_PRESSURE=1).
if jax.default_backend() == "cpu" and os.environ.get(
        "PONY_TPU_DIST_PRESSURE", "0") != "1":
    print(f"RANK{rank}_PRESSURE_SKIPPED xla:cpu gloo", flush=True)
    print(f"RANK{rank}_ALL_OK", flush=True)
    sys.exit(0)
from ponyc_tpu import Runtime                       # noqa: E402
from ponyc_tpu.models.fanin import (Aggregator,     # noqa: E402
                                    Producer)

n_src, items = 6 * shards, 4
opts3 = RuntimeOptions(mailbox_cap=4, batch=1, max_sends=2, msg_words=2,
                       mesh_shards=shards, spill_cap=4096,
                       inject_slots=64, quiesce_interval=1,
                       route_bucket=8)
rt3 = Runtime(opts3)
rt3.declare(Producer, n_src).declare(Aggregator, 4)
rt3.start()
agg = rt3.spawn(Aggregator)
srcs = rt3.spawn_many(Producer, n_src, out=int(agg))
rt3.bulk_send(srcs, Producer.produce, np.full(n_src, items, np.int64))
saw_rspill = saw_muted = False
got = 0
for _ in range(75 * shards):
    rt3.run(max_steps=1)
    saw_rspill = saw_rspill or rt3.counter("rspill_count") > 0
    saw_muted = saw_muted or bool(rt3._fetch(rt3.state.muted).any())
    got = rt3.state_of(int(agg))["total"]
    if got == n_src * items:
        break
assert got == n_src * items, (got, n_src * items)
assert saw_rspill, "route spill never engaged across processes"
assert saw_muted, "pressure never muted a sender across processes"
rt3.run(max_steps=80)
assert not bool(rt3._fetch(rt3.state.muted).any())
assert rt3.counter("rspill_count") == 0
print(f"RANK{rank}_PRESSURE_OK got={got}", flush=True)
print(f"RANK{rank}_ALL_OK", flush=True)
