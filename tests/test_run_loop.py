"""Adaptive run loop tests (PROFILE.md §9): the window controller's
decision rules, the on-device tick-0 gate of the pipelined dispatch
(engine.build_multi_step_gated), pipelined-vs-synchronous differential
equivalence (message-for-message, exit-code-equal), adaptive
convergence on the quiet ubench, quiesce_interval="auto" resolution
through the tuning cache, and interrupt safety of an in-flight
pipelined window (SIGINT/SIGTERM subprocess tests)."""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions, actor,
                       behaviour)
from ponyc_tpu.runtime import engine
from ponyc_tpu.runtime.controller import WindowController

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The controller/gap tests must not read or publish converged windows.
NO_CACHE = dict(tuning_cache="off")


def _opts(**kw):
    base = dict(mailbox_cap=4, batch=1, max_sends=1, msg_words=1,
                spill_cap=256, inject_slots=8, **NO_CACHE)
    base.update(kw)
    return RuntimeOptions(**base)


# ------------------------------------------------ controller decisions

def test_controller_grows_geometrically_on_quiet_budget_exits():
    c = WindowController(8, 4, 128)
    seen = []
    for _ in range(6):
        seen.append(c.observe(ran=c.window, budget=c.window,
                              attention=False))
    assert seen == [16, 32, 64, 128, 128, 128]   # ×2 then clamped at hi
    assert c.state in ("grow", "steady")


def test_controller_shrinks_on_host_attention():
    c = WindowController(64, 4, 128)
    assert c.observe(ran=10, budget=64, attention=True) == 32
    assert c.state == "shrink"
    assert c.observe(ran=5, budget=32, attention=True) == 16
    for _ in range(10):
        c.observe(ran=1, budget=c.window, attention=True)
    assert c.window == 4                          # clamped at lo


def test_controller_shrinks_on_queue_wait_pressure():
    c = WindowController(64, 4, 128)
    # p99 queue wait longer than the whole window: latency pressure.
    assert c.observe(ran=64, budget=64, attention=False,
                     qw_p99=256) == 32
    assert c.state == "shrink"
    # At the floor, pressure cannot shrink further (and is not counted
    # as a shrink decision).
    c2 = WindowController(4, 4, 128)
    before = c2.shrinks
    nxt = c2.observe(ran=4, budget=4, attention=False, qw_p99=1024)
    assert nxt == 8 and c2.shrinks == before     # grew instead (quiet
    #                                              full-budget exit)


def test_controller_holds_on_early_quiescence():
    c = WindowController(32, 4, 128)
    assert c.observe(ran=7, budget=32, attention=False) == 32
    assert c.observe(ran=1, budget=32, attention=False) == 32
    assert c.holds == 2


def test_controller_reaches_steady_at_cap():
    c = WindowController(32, 4, 64)
    for _ in range(8):
        c.observe(ran=c.window, budget=c.window, attention=False)
    assert c.window == 64 and c.state == "steady"


def test_controller_fixed_mode_lo_eq_hi():
    c = WindowController(16, 16, 16)
    for att in (False, True, False):
        assert c.observe(ran=16, budget=16, attention=att) == 16
    assert c.window == 16


def test_controller_deterministic_from_recorded_trace():
    trace = [(64, 64, False, 0), (64, 64, False, 0), (10, 128, True, 0),
             (64, 64, False, 300), (3, 32, False, 0), (32, 32, False, 0)]
    def replay():
        c = WindowController(64, 4, 256)
        return [c.observe(r, b, att, qw) for r, b, att, qw in trace], \
            c.snapshot()
    d1, s1 = replay()
    d2, s2 = replay()
    assert d1 == d2 and s1 == s2                 # pure + deterministic


def test_controller_bounds_validated():
    with pytest.raises(ValueError):
        WindowController(8, 0, 4)
    with pytest.raises(ValueError):
        WindowController(8, 16, 4)
    with pytest.raises(ValueError):
        RuntimeOptions(quiesce_interval="sometimes")
    with pytest.raises(ValueError):
        RuntimeOptions(quiesce_interval_min=8, quiesce_interval_max=4)


# ------------------------------------------------ the on-device gate

@actor
class Node:
    acc: I32
    nxt: Ref["Node"]

    MAX_SENDS = 1

    @behaviour
    def step(self, st, v: I32):
        self.send(st["nxt"], Node.step, v - 1, when=v > 0)
        return {**st, "acc": st["acc"] + v}


def _ring(n=8, hops=100, **okw):
    rt = Runtime(_opts(**okw))
    rt.declare(Node, n)
    rt.start()
    ids = rt.spawn_many(Node, n)
    rt.set_fields(Node, ids, nxt=np.roll(ids, -1))
    rt.send(int(ids[0]), Node.step, hops)
    return rt, ids


def test_gate_closes_on_stale_attention_aux():
    """A window dispatched behind a 'host attention' aux must be an
    identity pass: zero ticks, aux passed through unchanged."""
    import jax
    import jax.numpy as jnp
    rt, _ids = _ring()
    inj = rt._drain_inject()
    # Real first window: runs (force himself is not even needed — the
    # inject makes zero_aux's device_pending=True gate pass).
    st, aux, k = rt._multi_g(rt.state, *inj, jnp.int32(4),
                             np.bool_(True), engine.zero_aux())
    rt.state = st
    assert int(k) == 4
    # Forge a stale attention vote: same aux but host_pending=True.
    stale = jax.device_get(aux)._replace(host_pending=np.bool_(True))
    st2, aux2, k2 = rt._multi_g(rt.state, *rt._empty_inject,
                                jnp.int32(8), np.bool_(False), stale)
    rt.state = st2
    assert int(k2) == 0                      # gated out entirely
    a2 = jax.device_get(aux2)
    assert bool(a2.host_pending)             # prev aux passed through
    assert int(a2.n_processed) == int(jax.device_get(aux).n_processed)


def test_gate_closes_on_stale_quiet_aux_keeps_quiescence_exact():
    import jax
    import jax.numpy as jnp
    rt, _ids = _ring(hops=2)
    rt.run(max_steps=100)                    # quiesce for real
    quiet = engine.zero_aux()._replace(device_pending=np.bool_(False))
    st, aux, k = rt._multi_g(rt.state, *rt._empty_inject, jnp.int32(8),
                             np.bool_(False), quiet)
    rt.state = st
    # A stale "quiet" vote runs nothing — termination is only ever
    # declared from an aux no later tick has invalidated.
    assert int(k) == 0
    assert not bool(jax.device_get(aux).device_pending)


def test_gated_out_window_requeues_injections():
    """_retire_window puts a gated-out window's consumed injections
    back at the FRONT of the queue, order preserved."""
    rt, ids = _ring(hops=0)
    rt.run(max_steps=50)
    rt.send(int(ids[0]), Node.step, 5)
    rt.send(int(ids[1]), Node.step, 7)
    inj_t, inj_w, consumed = rt._drain_inject_tracked()
    assert len(consumed) == 2 and not rt._inject_q
    import jax.numpy as jnp
    quiet = engine.zero_aux()._replace(device_pending=np.bool_(False))
    st, aux, k = rt._multi_g(rt.state, inj_t, inj_w, jnp.int32(4),
                             np.bool_(False), quiet)
    rt.state = st
    win = {"aux": aux, "k": k, "budget": 4, "consumed": consumed,
           "gap_ns": 0, "epoch": rt._state_epoch}
    k2, _a = rt._retire_window(win)
    assert k2 == 0
    assert [t for t, _w in rt._inject_q] == [int(ids[0]), int(ids[1])]
    # And the loop delivers them on the next real run.
    assert rt.run(max_steps=100) == 0
    acc = np.asarray(rt.cohort_state(Node)["acc"])
    assert acc.sum() == sum(range(6)) + sum(range(8))


# ------------------------------------ pipelined vs synchronous oracle

@actor
class HostLog:
    HOST = True
    ends: I32
    total: I32

    @behaviour
    def done(self, st, tail: I32):
        return {**st, "ends": st["ends"] + 1, "total": st["total"] + tail}


@actor
class WalkerH:
    acc: I32
    nxt: Ref["WalkerH"]
    log: Ref["HostLog"]

    MAX_SENDS = 2

    @behaviour
    def step(self, st, v: I32):
        self.send(st["nxt"], WalkerH.step, v - 1, when=v > 0)
        self.send(st["log"], HostLog.done, st["acc"] + v, when=v == 0)
        return {**st, "acc": st["acc"] + v}


@actor
class Exiter:
    n: I32

    MAX_SENDS = 1

    @behaviour
    def count(self, st, v: I32):
        self.send(self.actor_id, Exiter.count, v - 1, when=v > 0)
        self.exit(code=42, when=v == 0)
        return {**st, "n": st["n"] + 1}


def _mode_opts(pipelined: bool, **kw):
    if pipelined:
        return _opts(pipeline=True, quiesce_interval="auto",
                     quiesce_interval_min=4, quiesce_interval_max=64,
                     **kw)
    return _opts(pipeline=False, quiesce_interval=16, **kw)


def _run_walker_world(seed: int, pipelined: bool):
    """Random functional-graph walk + device→host reporting: the same
    corpus shape as the fuzz differential (commutative outcomes, so any
    correct schedule must agree column-for-column)."""
    rng = np.random.default_rng(seed)
    n, chains = 12, 6
    rt = Runtime(_mode_opts(pipelined, mailbox_cap=4, max_sends=2,
                            msg_words=1))
    rt.declare(WalkerH, n).declare(HostLog, 1)
    rt.start()
    log = rt.spawn(HostLog, ends=0, total=0)
    ids = rt.spawn_many(WalkerH, n, log=log)
    rt.set_fields(WalkerH, ids, nxt=ids[rng.integers(0, n, n)])
    starts = rng.choice(n, chains, replace=False)
    vals = rng.integers(1, 40, chains)
    for s, v in zip(starts, vals):
        rt.send(int(ids[s]), WalkerH.step, int(v))
    code = rt.run(max_steps=200_000)
    st = rt.cohort_state(WalkerH)
    return {
        "code": code,
        "acc": np.asarray(st["acc"]).tolist(),
        "host": rt.state_of(log),
        "processed": rt.counter("n_processed"),
        "delivered": rt.counter("n_delivered"),
        "host_processed": rt.totals.get("host_processed", 0),
    }


@pytest.mark.parametrize("seed", [7, 23, 91])
def test_differential_pipelined_matches_synchronous(seed):
    """The tentpole oracle: the pipelined adaptive loop and the forced
    synchronous fixed-window loop agree message-for-message (equal
    processed/delivered totals, equal per-actor columns, equal host
    actor state) and exit-code-equal on the fuzz corpus shape."""
    sync = _run_walker_world(seed, pipelined=False)
    pipe = _run_walker_world(seed, pipelined=True)
    assert sync == pipe


def test_differential_fifo_order_under_pipelined_loop():
    """Per-edge FIFO (the order-sensitive oracle of test_fifo) holds
    under the pipelined adaptive loop: reuse that suite's harness with
    pipelining forced on and the window adaptive."""
    from test_fifo import run_fifo
    run_fifo(seed=101, okw=dict(
        mailbox_cap=2, batch=1, max_sends=3, spill_cap=2048,
        inject_slots=16, pipeline=True, quiesce_interval="auto",
        quiesce_interval_min=4, quiesce_interval_max=64, **NO_CACHE))


def test_differential_exit_code_equal():
    for pipelined in (False, True):
        rt = Runtime(_mode_opts(pipelined))
        rt.declare(Exiter, 1)
        rt.start()
        eid = rt.spawn(Exiter, n=0)
        rt.send(eid, Exiter.count, 30)
        assert rt.run(max_steps=10_000) == 42
        assert int(rt.state_of(eid)["n"]) == 31


# ----------------------------------------- adaptive loop integration

def test_adaptive_converges_to_steady_on_quiet_ubench():
    """Acceptance: on the never-quiescing, zero-host-attention ubench
    the controller grows geometrically to its cap and reports steady."""
    from ponyc_tpu.models import ubench
    opts = RuntimeOptions(
        mailbox_cap=4, batch=1, max_sends=1, msg_words=1,
        spill_cap=256, inject_slots=8, pipeline=True,
        quiesce_interval="auto", quiesce_interval_min=4,
        quiesce_interval_max=256, **NO_CACHE)
    rt, ids = ubench.build(64, opts)
    ubench.seed_all(rt, ids, hops=1 << 30)
    rt.run(max_steps=1600)
    rl = rt.run_loop_stats()
    c = rl["controller"]
    assert c["state"] == "steady" and c["window"] == 256, rl
    assert c["grows"] >= 2                       # geometric ascent ran
    assert rl["pipelined_dispatches"] > 0        # the bridge pipelined
    assert rl["windows"] >= 8
    assert rt.steps_run == 1600                  # max_steps exact


def test_run_loop_stats_host_gap_accounting():
    rt, _ids = _ring(hops=400, pipeline=False, quiesce_interval=8)
    assert rt.run(max_steps=2_000) == 0
    rl = rt.run_loop_stats()
    assert rl["pipelined_dispatches"] == 0       # sync mode never rides
    assert rl["windows"] > 1
    assert rl["host_gap_us_total"] >= 0
    assert sum(rl["window_hist"]) == rl["windows"]
    assert rl["controller"]["window"] == 8       # fixed mode holds


def test_quiesce_auto_resolves_and_persists_through_tuning_cache(
        tmp_path, monkeypatch):
    from ponyc_tpu import tuning
    monkeypatch.setenv("PONY_TPU_TUNING_CACHE", str(tmp_path))
    from ponyc_tpu.models import ubench
    opts = RuntimeOptions(
        mailbox_cap=4, batch=1, max_sends=1, msg_words=1,
        spill_cap=256, inject_slots=8, quiesce_interval="auto",
        quiesce_interval_min=4, quiesce_interval_max=128)
    rt, ids = ubench.build(64, opts)
    assert rt.opts.quiesce_interval == tuning.DEFAULT_QUIESCE_INTERVAL
    assert rt.tuning_record["quiesce_interval"]["source"] == "default"
    ubench.seed_all(rt, ids, hops=1 << 30)
    rt.run(max_steps=1024)                       # grows 64→128, steady
    assert rt._controller.state == "steady"
    assert rt._controller.window == 128
    # Second start of the same layout resolves to the CONVERGED window.
    rt2, _ids2 = ubench.build(64, opts)
    rec = rt2.tuning_record["quiesce_interval"]
    assert rec["source"] == "cache" and rec["initial"] == 128, rec
    assert rt2.opts.quiesce_interval == 128


def test_qw_p99_aux_lane():
    """The queue-wait p99 rides the aux at analysis>=1 (the controller's
    pressure signal) and stays a folded zero at level 0."""
    import jax
    import jax.numpy as jnp
    for level, expect_pos in ((1, True), (0, False)):
        rt, ids = _ring(hops=20, analysis=level, mailbox_cap=8)
        st, aux, _k = rt._multi(rt.state, *rt._drain_inject(),
                                jnp.int32(8))
        rt.state = st
        a = jax.device_get(aux)
        if expect_pos:
            assert int(a.qw_p99) >= 1, a.qw_p99
        else:
            assert int(a.qw_p99) == 0


# ------------------------------------------------- interrupt safety

def test_keyboard_interrupt_mid_pipeline_is_clean(tmp_path):
    """SIGINT while pipelined windows are in flight: run() must sync the
    in-flight window, keep host-outbox messages, and leave the runtime
    restartable (no donated-buffer reuse)."""
    code = f"""
import os, signal, sys, threading
sys.path.insert(0, {ROOT!r})
from ponyc_tpu.platforms import force_cpu
force_cpu()
import numpy as np
from ponyc_tpu import I32, Ref, RuntimeOptions, Runtime, actor, behaviour

@actor
class Pinger:
    nxt: Ref["Pinger"]
    MAX_SENDS = 1
    @behaviour
    def ping(self, st, v: I32):
        self.send(st["nxt"], Pinger.ping, v, when=True)
        return st

@actor
class Sink:
    HOST = True
    got: I32
    @behaviour
    def hit(self, st, v: I32):
        return {{**st, "got": st["got"] + 1}}

rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=1, max_sends=1,
                            msg_words=1, inject_slots=8,
                            quiesce_interval="auto", tuning_cache="off"))
rt.declare(Pinger, 16).declare(Sink, 1)
rt.start()
sink = rt.spawn(Sink, got=0)
ids = rt.spawn_many(Pinger, 16)
rt.set_fields(Pinger, ids, nxt=np.roll(ids, -1))
for i in ids:                       # endless device traffic
    rt.send(int(i), Pinger.ping, 1)
rt.send(sink, Sink.hit, 7)          # one host-outbox message in flight
threading.Timer(1.0, lambda: os.kill(os.getpid(), signal.SIGINT)).start()
try:
    rt.run()                        # runs until the SIGINT
    print("NO-INTERRUPT")
except KeyboardInterrupt:
    # Clean stop: state consistent, host message delivered, restart OK.
    rt.check_invariants()
    assert rt.state_of(sink)["got"] == 1, rt.state_of(sink)
    rt.run(max_steps=32)            # donated buffers must still be live
    rt.check_invariants()
    print("INTERRUPT-CLEAN got", rt.state_of(sink)["got"],
          "steps", rt.steps_run)
"""
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu",
                          "PONY_TPU_TUNING_CACHE": "off"})
    assert p.returncode == 0, (p.returncode, p.stdout, p.stderr)
    assert "INTERRUPT-CLEAN got 1" in p.stdout, (p.stdout, p.stderr)
    assert "NO-INTERRUPT" not in p.stdout


def test_sigterm_mid_pipeline_dumps_and_terminates(tmp_path):
    """SIGTERM during an in-flight pipelined window (analysis=1): the
    dump handler must observe a consistent world (the dispatch critical
    section defers delivery) and the process still dies of SIGTERM —
    alongside test_profiler's quiescent-world SIGTERM test."""
    code = f"""
import os, signal, sys, threading
sys.path.insert(0, {ROOT!r})
from ponyc_tpu.platforms import force_cpu
force_cpu()
import numpy as np
from ponyc_tpu import I32, Ref, RuntimeOptions, Runtime, actor, behaviour

@actor
class Pinger:
    nxt: Ref["Pinger"]
    MAX_SENDS = 1
    @behaviour
    def ping(self, st, v: I32):
        self.send(st["nxt"], Pinger.ping, v, when=True)
        return st

rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=1, max_sends=1,
                            msg_words=1, inject_slots=8, analysis=1,
                            quiesce_interval="auto", tuning_cache="off"))
rt.declare(Pinger, 16)
rt.start()
ids = rt.spawn_many(Pinger, 16)
rt.set_fields(Pinger, ids, nxt=np.roll(ids, -1))
for i in ids:
    rt.send(int(i), Pinger.ping, 1)
threading.Timer(1.0, lambda: os.kill(os.getpid(), signal.SIGTERM)).start()
rt.run()
print("SURVIVED-SIGTERM")
"""
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu",
                          "PONY_TPU_TUNING_CACHE": "off"})
    assert p.returncode == -signal.SIGTERM, (p.returncode, p.stderr)
    assert "ponyc_tpu analysis dump" in p.stderr, p.stderr
    assert "run_loop window=" in p.stderr, p.stderr
    assert "SURVIVED-SIGTERM" not in p.stdout
    assert "Traceback" not in p.stderr, p.stderr


def test_window_constants_ride_optimization_barrier():
    """Compile-time regression guard (PR 11 satellite, BENCH_r05): the
    gated window's loop-invariant operands (injections, limit, force
    bit) must sit behind lax.optimization_barrier in the lowered HLO.
    Without it XLA constant-folds them INTO the while body and the
    r05-style constant-propagation sweep re-runs per window compile —
    the multi-minute stall BENCH_r05 recorded. The barrier's presence
    in the StableHLO text is the cheapest stable proxy for "the hoist
    survived lowering"."""
    import jax
    import jax.numpy as jnp
    from ponyc_tpu.models import ubench
    opts = RuntimeOptions(mailbox_cap=4, batch=1, max_sends=1,
                          msg_words=1, spill_cap=64, inject_slots=8,
                          **NO_CACHE)
    rt, _ids = ubench.build(8, opts)
    gated = engine.build_multi_step_gated(rt.program, rt.opts)
    text = jax.jit(gated).lower(
        rt.state, *rt._empty_inject, jnp.int32(4), jnp.bool_(True),
        engine.zero_aux()).as_text()
    assert "optimization_barrier" in text
