"""Bridge tests: OS events (timers, signals, fds) driving actors.

≙ how the reference exercises ASIO through stdlib tests over real OS
resources (packages/net, packages/time run under ponytest; SURVEY.md §4).
"""

import os
import signal
import time

import pytest

from ponyc_tpu import (I32, Runtime, RuntimeOptions, actor, behaviour)


@actor
class Ticker:
    """Device-resident actor counting timer events."""
    ticks: I32

    @behaviour
    def on_event(self, st, kind: I32, arg: I32, flags: I32):
        st["ticks"] = st["ticks"] + arg   # arg = expirations
        return st


@actor
class HostWatcher:
    """Host-resident actor recording the last event (≙ a main-thread
    actor observing signals)."""
    HOST = True
    kind: I32
    arg: I32

    @behaviour
    def on_event(self, st, kind: I32, arg: I32, flags: I32):
        st["kind"] = kind
        st["arg"] = arg
        return st

    @behaviour
    def stop(self, st):
        self.exit(0)
        return st


def _mk_rt(*decls):
    rt = Runtime(RuntimeOptions(mailbox_cap=16, batch=4, max_sends=1,
                                msg_words=3, spill_cap=64, inject_slots=32,
                                max_steps=20000))
    for atype, cap in decls:
        rt.declare(atype, cap)
    return rt.start()


def test_timer_drives_device_actor():
    rt = _mk_rt((Ticker, 1))
    tid = rt.spawn(Ticker)
    br = rt.attach_bridge()
    sid = br.timer(tid, Ticker.on_event, 0.01)
    t0 = time.time()
    while time.time() - t0 < 5.0:
        rt.run(max_steps=50)
        if rt.state_of(tid)["ticks"] >= 3:
            break
    assert rt.state_of(tid)["ticks"] >= 3
    br.unsubscribe(sid)
    br.poll(rt)                      # release the noisy hold
    assert br.loop.noisy == 0
    br.close()


def test_oneshot_timer_then_quiesce():
    rt = _mk_rt((Ticker, 1))
    tid = rt.spawn(Ticker)
    br = rt.attach_bridge()
    br.timer(tid, Ticker.on_event, 0.01, oneshot=True)
    t0 = time.time()
    while time.time() - t0 < 5.0 and rt.state_of(tid)["ticks"] < 1:
        rt.run(max_steps=50)
    assert rt.state_of(tid)["ticks"] == 1
    # After the oneshot fired there are no noisy subs: run() terminates
    # on its own (quiescence with an attached but silent bridge).
    br.poll(rt)
    assert br.loop.noisy == 0
    code = rt.run(max_steps=5000)
    assert code == 0
    br.close()


def test_signal_to_host_actor():
    rt = _mk_rt((HostWatcher, 1))
    wid = rt.spawn(HostWatcher)
    br = rt.attach_bridge()
    br.signal(wid, HostWatcher.on_event, signal.SIGUSR2)
    os.kill(os.getpid(), signal.SIGUSR2)
    t0 = time.time()
    while time.time() - t0 < 5.0:
        rt.run(max_steps=20)
        if rt.state_of(wid)["arg"] == signal.SIGUSR2:
            break
    st = rt.state_of(wid)
    assert st["kind"] == 2 and st["arg"] == signal.SIGUSR2
    br.close()


def test_fd_readiness_to_host_actor():
    rt = _mk_rt((HostWatcher, 1))
    wid = rt.spawn(HostWatcher)
    br = rt.attach_bridge()
    r, w = os.pipe()
    os.set_blocking(r, False)
    br.fd(wid, HostWatcher.on_event, r)
    os.write(w, b"!")
    t0 = time.time()
    while time.time() - t0 < 5.0:
        rt.run(max_steps=20)
        if rt.state_of(wid)["arg"] == r:
            break
    st = rt.state_of(wid)
    assert st["kind"] == 3 and st["arg"] == r   # FD_READ
    os.read(r, 1)
    os.close(r)
    os.close(w)
    br.close()


def test_subscribe_requires_event_signature():
    @actor
    class Bad:
        x: I32

        @behaviour
        def nope(self, st, v: I32):
            return st

    rt = _mk_rt((Bad, 1))
    bid = rt.spawn(Bad)
    br = rt.attach_bridge()
    with pytest.raises(TypeError):
        br.timer(bid, Bad.nope, 0.01)
    br.close()
