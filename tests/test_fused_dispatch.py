"""Fused Pallas dispatch kernel (ops/fused_dispatch.py) equivalence vs
the XLA path — same models, same counters/results, interpret mode on CPU
(≙ exercising the north-star dispatch kernel the way genjit.cc runs
compiled behaviour bodies in-process, SURVEY.md §4)."""

import numpy as np
import pytest

from ponyc_tpu import (F32, I32, Ref, Runtime, RuntimeOptions, actor,
                       behaviour)


def test_ubench_sustained_equivalence():
    from ponyc_tpu.models import ubench
    counts = {}
    for fused in (False, True):
        opts = RuntimeOptions(mailbox_cap=4, batch=4, max_sends=1,
                              msg_words=1, spill_cap=256, inject_slots=8,
                              pallas_fused=fused)
        rt, ids = ubench.build(256, opts, pings=4)
        ubench.seed_all(rt, ids, hops=1 << 30, pings=4)
        st, inj = rt.state, rt._empty_inject
        for _ in range(5):
            st, aux = rt._step(st, *inj)
        rt.state = st
        counts[fused] = rt.counter("n_processed")
    assert counts[True] == counts[False] == 5 * 256 * 4


def test_nbody_float_vec_payloads_equivalence():
    from ponyc_tpu.models import nbody
    res = {}
    for fused in (False, True):
        rt = nbody.run_round(96, RuntimeOptions(
            mailbox_cap=16, batch=4, max_sends=1, msg_words=4,
            spill_cap=1024, pallas_fused=fused))
        st = rt.cohort_state(nbody.Body)
        res[fused] = (st["ax"].copy(), st["ay"].copy())
    assert np.allclose(res[True][0], res[False][0], rtol=1e-6)
    assert np.allclose(res[True][1], res[False][1], rtol=1e-6)


@actor
class Yielder:
    n: I32

    BATCH = 4
    MAX_SENDS = 0

    @behaviour
    def tick(self, st, v: I32):
        # yield after the first message of each batch (fork hint,
        # actor.c:675-679): consumption must stop mid-batch identically.
        self.yield_(when=st["n"] % 2 == 0)
        return {**st, "n": st["n"] + 1}


@actor
class Exiter:
    n: I32
    MAX_SENDS = 0

    @behaviour
    def go(self, st, code: I32):
        self.exit(code, when=code > 0)
        return {**st, "n": st["n"] + 1}


@pytest.mark.parametrize("fused", [False, True])
def test_yield_and_exit_semantics(fused):
    opts = RuntimeOptions(mailbox_cap=8, batch=4, max_sends=0,
                          msg_words=1, spill_cap=64, inject_slots=16,
                          pallas_fused=fused)
    rt = Runtime(opts)
    rt.declare(Yielder, 2).declare(Exiter, 1).start()
    y = rt.spawn(Yielder)
    for _ in range(6):
        rt.send(y, Yielder.tick, 1)
    rt.run()
    assert rt.state_of(y)["n"] == 6          # all consumed eventually

    ex = rt.spawn(Exiter)
    rt.send(ex, Exiter.go, 7)
    assert rt.run() == 7                     # exit code propagates


def test_multi_behaviour_cohort_under_fused_kernel():
    """nb > 1: the kernel evaluates every behaviour on the lanes and
    selects per lane by message id — results equal the XLA path on a
    mixed add/mul/ping workload."""
    @actor
    class TriF:
        acc: I32
        count: I32
        buddy: Ref["TriF"]
        MAX_SENDS = 1

        @behaviour
        def add(self, st, v: I32):
            # a SENDING behaviour among non-senders: the nb>1 per-branch
            # send-plane select must route only add's sends
            self.send(st["buddy"], TriF.ping, when=v % 2 == 1)
            return {**st, "acc": st["acc"] + v,
                    "count": st["count"] + 1}

        @behaviour
        def scale(self, st, v: I32):
            return {**st, "acc": st["acc"] * 2 + v,
                    "count": st["count"] + 1}

        @behaviour
        def ping(self, st):
            return {**st, "count": st["count"] + 1}

    res = {}
    for fused in (False, True):
        rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=4, max_sends=1,
                                    msg_words=1, spill_cap=64,
                                    inject_slots=32,
                                    pallas_fused=fused))
        rt.declare(TriF, 3).start()
        ids = rt.spawn_many(TriF, 3)
        import numpy as _np
        rt.set_fields(TriF, ids, buddy=_np.roll(ids, -1))
        seq = [(0, TriF.add, (5,)), (1, TriF.scale, (3,)),
               (0, TriF.ping, ()), (2, TriF.add, (7,)),
               (1, TriF.add, (2,)), (0, TriF.scale, (1,)),
               (2, TriF.ping, ()), (1, TriF.ping, ())]
        for i, b, args in seq:
            rt.send(int(ids[i]), b, *args)
        assert rt.run() == 0
        st = rt.cohort_state(TriF)
        res[fused] = (list(st["acc"][:3]), list(st["count"][:3]))
    assert res[True] == res[False]
    # adds with odd v (5 at actor0, 7 at actor2) ping their buddies
    assert res[True][1] == [4, 4, 2]


def test_destroy_under_fused_kernel():
    """destroy() rides out of the fused kernel as a lane plane: slots
    free identically to the XLA path (round-4 eligibility extension —
    real programs with lifecycle now qualify for the north-star
    kernel)."""
    @actor
    class Ephemeral:
        n: I32
        MAX_SENDS = 0

        @behaviour
        def die(self, st, v: I32):
            self.destroy(when=v > 0)
            return {**st, "n": st["n"] + 1}

    res = {}
    for fused in (False, True):
        rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=2, max_sends=0,
                                    msg_words=1, spill_cap=64,
                                    inject_slots=16, pallas_fused=fused))
        rt.declare(Ephemeral, 4).start()
        ids = rt.spawn_many(Ephemeral, 4)
        for i in ids:
            rt.send(int(i), Ephemeral.die, 1 if int(i) % 2 == 0 else 0)
        assert rt.run() == 0
        alive = np.asarray(rt.state.alive)[:4]
        res[fused] = list(alive)
    assert res[True] == res[False]
    assert sum(res[True]) == 2               # odd ids survived


def test_error_int_under_fused_kernel():
    """error_int() codes/locs ride out of the fused kernel exactly as
    on the XLA path (fork int-coded errors, pony.h:622-665)."""
    @actor
    class Errs:
        n: I32
        MAX_SENDS = 0

        @behaviour
        def go(self, st, v: I32):
            self.error_int(v, when=v > 0)
            return {**st, "n": st["n"] + 1}

    res = {}
    for fused in (False, True):
        rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=2, max_sends=0,
                                    msg_words=1, spill_cap=64,
                                    inject_slots=16, pallas_fused=fused))
        rt.declare(Errs, 2).start()
        a, b = rt.spawn_many(Errs, 2)
        rt.send(int(a), Errs.go, 41)
        rt.send(int(a), Errs.go, 42)     # latest error wins
        rt.send(int(b), Errs.go, 0)      # no error
        assert rt.run() == 0
        res[fused] = (rt.last_error(int(a)), rt.last_error(int(b)),
                      rt.state_of(int(a))["n"], rt.state_of(int(b))["n"])
    assert res[True] == res[False]
    assert res[True][0] == 42 and res[True][2] == 2


@pytest.mark.parametrize("fused", [False, True])
def test_gups_xor_conservation_under_fused(fused):
    """The gups random-access workload (two cohorts, one sending into a
    table of cells) conserves its xor under the fused kernel exactly as
    under the XLA path."""
    from ponyc_tpu.models import gups
    rt = gups.run(table_size=256, n_updaters=16, updates_each=12,
                  opts=RuntimeOptions(mailbox_cap=16, batch=4,
                                      max_sends=2, msg_words=2,
                                      spill_cap=2048, inject_slots=32,
                                      pallas_fused=fused))
    cells = rt.cohort_state(gups.TableCell)
    import numpy as np
    x = np.bitwise_xor.reduce(cells["value"].astype(np.int64)[:256])
    # xor of all applied updates is deterministic for fixed seed
    assert rt.counter("n_processed") > 0
    upd = rt.cohort_state(gups.Updater)
    assert int(upd["done"].sum()) == 16 * 12
    globals().setdefault("_gups_xor", {})[fused] = int(x)
    if len(globals()["_gups_xor"]) == 2:
        assert (globals()["_gups_xor"][True]
                == globals()["_gups_xor"][False])


@actor
class SpawnChild:
    boss: Ref
    val: I32

    @behaviour
    def init(self, st, boss: Ref, v: I32):
        return {**st, "boss": boss, "val": v}


@actor
class Spawner:
    made: I32
    SPAWNS = {"SpawnChild": 1}
    MAX_SENDS = 1

    @behaviour
    def make(self, st, v: I32):
        self.spawn(SpawnChild.init, self.actor_id, v)
        return {**st, "made": st["made"] + 1}


def test_spawning_cohort_under_fused_kernel():
    """Round-5 extension (VERDICT item 4): cohorts that spawn now run
    the fused kernel too — reservation planes in, claim planes out —
    with identical lifecycle results to the XLA path."""
    res = {}
    for fused in (False, True):
        opts = RuntimeOptions(mailbox_cap=8, batch=2, max_sends=1,
                              msg_words=2, spill_cap=256, inject_slots=8,
                              pallas_fused=fused)
        rt = Runtime(opts)
        rt.declare(Spawner, 8).declare(SpawnChild, 64).start()
        sp = rt.spawn_many(Spawner, 8)
        for k, s in enumerate(sp):
            rt.send(int(s), Spawner.make, 10 + k)
            rt.send(int(s), Spawner.make, 50 + k)
        rt.run(max_steps=32)
        cs = rt.cohort_state(SpawnChild)
        alive = rt.counter("n_spawned")
        res[fused] = (int(rt.cohort_state(Spawner)["made"].sum()),
                      int(alive),
                      sorted(int(v) for v in np.asarray(cs["val"])
                             if v != 0))
    assert res[True] == res[False]
    made, spawned, vals = res[True]
    assert made == 16 and spawned == 16
    assert vals == sorted([10 + k for k in range(8)]
                          + [50 + k for k in range(8)])


def test_spawn_budget_exhaustion_matches_under_fused():
    """Exceeding the per-step spawn window raises SpawnCapacityError on
    both paths (sticky spawn_fail from the kernel's sfail plane)."""
    from ponyc_tpu import SpawnCapacityError
    for fused in (False, True):
        opts = RuntimeOptions(mailbox_cap=8, batch=2, max_sends=1,
                              msg_words=2, spill_cap=256, inject_slots=8,
                              pallas_fused=fused)
        rt = Runtime(opts)
        # Child capacity 2: the third spawn finds no slot.
        rt.declare(Spawner, 4).declare(SpawnChild, 2).start()
        sp = rt.spawn_many(Spawner, 4)
        for s in sp:
            rt.send(int(s), Spawner.make, 1)
        with pytest.raises(SpawnCapacityError):
            rt.run(max_steps=16)
