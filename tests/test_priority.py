"""Actor priorities under delivery contention (≙ the fork's priority
hint, actor.h priority field + the scheduler's priority-inject preemption
scheduler.c:1053-1078 — reinterpreted for lockstep dispatch: when a
mailbox can't take everything in a tick, higher-priority senders win the
slots and lower-priority traffic spills behind them)."""

import numpy as np

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour


@actor
class HiSender:
    PRIORITY = 1
    MAX_SENDS = 4
    sink: Ref

    @behaviour
    def burst(self, st, v: I32):
        for _ in range(4):
            self.send(st["sink"], Rx.item, v)
        return st


@actor
class LoSender:
    PRIORITY = 0
    MAX_SENDS = 4
    sink: Ref

    @behaviour
    def burst(self, st, v: I32):
        for _ in range(4):
            self.send(st["sink"], Rx.item, v)
        return st


@actor
class Rx:
    BATCH = 4
    seen: I32
    first4: I32

    @behaviour
    def item(self, st, v: I32):
        import jax.numpy as jnp
        first = st["seen"] < 4
        return {**st, "seen": st["seen"] + 1,
                "first4": st["first4"] + jnp.where(first, v, 0)}


import pytest


@pytest.mark.parametrize("mode", ["plan", "cosort"])
def test_higher_priority_wins_contended_slots(mode):
    rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=4, max_sends=4,
                                msg_words=2, spill_cap=64,
                                inject_slots=8, delivery=mode))
    rt.declare(HiSender, 1).declare(LoSender, 1).declare(Rx, 1)
    rt.start()
    rx = rt.spawn(Rx)
    hi = rt.spawn(HiSender, sink=int(rx))
    lo = rt.spawn(LoSender, sink=int(rx))
    # Both bursts dispatch in the same tick: 8 messages race for 4 slots.
    rt.send(lo, LoSender.burst, 100)     # enqueued first…
    rt.send(hi, HiSender.burst, 1)       # …but higher priority
    rt.run(max_steps=50)
    st = rt.state_of(rx)
    assert st["seen"] == 8               # nothing lost (spill drained)
    assert st["first4"] == 4             # hi's messages landed first
    assert rt.counter("n_rejected") == 4  # lo's burst took the spill path


def test_equal_priority_keeps_arrival_order():
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=8, max_sends=4,
                                msg_words=2, spill_cap=64,
                                inject_slots=8))
    rt.declare(HiSender, 2).declare(Rx, 1)
    rt.start()
    rx = rt.spawn(Rx)
    a = rt.spawn(HiSender, sink=int(rx))
    b = rt.spawn(HiSender, sink=int(rx))
    rt.send(a, HiSender.burst, 1)
    rt.send(b, HiSender.burst, 1)
    rt.run(max_steps=50)
    st = rt.state_of(rx)
    assert st["seen"] == 8
    assert rt.counter("n_rejected") == 0
