"""Transpilers (≙ the fork's translate/ subsystem; the reference tests
these by compiling packages containing .h/.schema.json/.md resources)."""

import ctypes
import ctypes.util
import os
import subprocess
import sys

from ponyc_tpu.translate import (translate_c_header, translate_dir,
                                 translate_json_schema,
                                 translate_text_resource)

HDR = """
// demo header
#define MAX_THINGS 32
#define SCALE 2.5
enum Mode { MODE_OFF, MODE_ON = 5, MODE_AUTO };
typedef unsigned int u32;

int add_numbers(int a, int b);
double scale_value(double v);
size_t buf_len(const char *s);
void reset(void);
u32 mask_bits(u32 x, unsigned shift);
int printf(const char *fmt, ...);   // variadic → skipped
"""


def _load_generated(src: str, name: str, tmp_path):
    path = tmp_path / (name + ".py")
    path.write_text(src)
    sys.path.insert(0, str(tmp_path))
    try:
        import importlib
        mod = importlib.import_module(name)
        importlib.reload(mod)
        return mod
    finally:
        sys.path.pop(0)


def test_c_header_bindings_run_against_real_lib(tmp_path):
    src = translate_c_header(HDR, name="demo.h")
    mod = _load_generated(src, "demo_ffi", tmp_path)
    # constants from #define and enum
    assert mod.MAX_THINGS == 32
    assert mod.SCALE == 2.5
    assert mod.MODE_OFF == 0 and mod.MODE_ON == 5 and mod.MODE_AUTO == 6
    # variadic printf was skipped, not bound
    assert not hasattr(mod, "printf")
    # Compile the implementation and call through the bindings.
    c = tmp_path / "demo.c"
    c.write_text("""
#include <stddef.h>
#include <string.h>
int add_numbers(int a, int b) { return a + b; }
double scale_value(double v) { return v * 2.5; }
size_t buf_len(const char *s) { return strlen(s); }
void reset(void) {}
unsigned mask_bits(unsigned x, unsigned s) { return x >> s; }
""")
    so = tmp_path / "libdemo.so"
    subprocess.run(["gcc", "-shared", "-fPIC", "-o", str(so), str(c)],
                   check=True)
    mod.bind(str(so))
    assert mod.add_numbers(2, 40) == 42
    assert abs(mod.scale_value(2.0) - 5.0) < 1e-9
    assert mod.buf_len(b"hello") == 5
    assert mod.mask_bits(0xF0, 4) == 0x0F
    mod.reset()


SCHEMA = """
{
  "title": "job",
  "description": "A queued job.",
  "type": "object",
  "required": ["id"],
  "properties": {
    "id": {"type": "integer"},
    "name": {"type": "string"},
    "weight": {"type": "number"},
    "urgent": {"type": "boolean"},
    "tags": {"type": "array", "items": {"type": "string"}},
    "owner": {
      "type": "object",
      "title": "owner",
      "properties": {
        "uid": {"type": "integer"},
        "email": {"type": "string"}
      }
    }
  }
}
"""


def test_json_schema_roundtrip(tmp_path):
    src = translate_json_schema(SCHEMA, name="job.schema.json")
    mod = _load_generated(src, "job_schema", tmp_path)
    j = mod.Job.from_json(
        '{"id": 7, "name": "x", "weight": 1.5, "urgent": true,'
        ' "tags": ["a","b"], "owner": {"uid": 3, "email": "e@x"}}')
    assert j.id == 7 and j.urgent is True and j.tags == ["a", "b"]
    assert j.owner.uid == 3
    back = mod.Job.from_json(j.to_json())
    assert back.to_dict() == j.to_dict()
    # defaults for non-required fields
    k = mod.Job.from_json('{"id": 1}')
    assert k.name == "" and k.weight == 0.0 and k.tags == []
    # device-actor field specs derived from flat scalars
    assert mod.Job.ACTOR_FIELDS == {"id": "I32", "weight": "F32",
                                    "urgent": "Bool"}


def test_text_resource_and_dir_dispatch(tmp_path):
    src_dir = tmp_path / "resources"
    out_dir = tmp_path / "generated"
    src_dir.mkdir()
    (src_dir / "notes.md").write_text("# Title\nBody ≥ stuff\n")
    (src_dir / "config.json").write_text('{"a": 1}')
    (src_dir / "job.schema.json").write_text(SCHEMA)
    (src_dir / "demo.h").write_text(HDR)
    (src_dir / "ignored.bin").write_text("xx")
    paths = translate_dir(str(src_dir), str(out_dir))
    names = sorted(os.path.basename(p) for p in paths)
    assert names == ["config.py", "demo.py", "job.py", "notes.py"]
    sys.path.insert(0, str(tmp_path))
    try:
        from generated import config, notes  # noqa
        assert notes.TEXT.startswith("# Title")
        assert config.DATA == {"a": 1}
    finally:
        sys.path.pop(0)


def test_text_resource_unicode():
    out = translate_text_resource("héllo ≙ wörld", name="x.txt")
    ns = {}
    exec(out, ns)
    assert ns["TEXT"] == "héllo ≙ wörld"
