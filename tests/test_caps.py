"""Reference-capability modes on payload handles: Iso / Val / Tag.

≙ src/libponyc/type/cap.c:1, safeto.c:1, alias.c:1 — the qualifiers
that make a payload sendable, re-expressed at this framework's two
enforcement points: the TRACE (device behaviours — aliased move,
use-after-move, retained-after-move all fail the build) and the host
heap (dynamic move/read rules, use-after-send in-flight tracking).

The round-3 verdict's acceptance test: programs today's Ref-lite
accepts that the new checker rejects — see
test_ref_lite_passed_this_yesterday below.
"""

import numpy as np
import pytest

from ponyc_tpu import (I32, Iso, Ref, Runtime, RuntimeOptions, Tag, Val,
                       actor, behaviour)
from ponyc_tpu.hostmem import CapabilityError, HostHeap

OPTS = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=2, msg_words=2,
                      inject_slots=8)


@actor
class Holder:
    payload: Iso
    got: I32

    @behaviour
    def take(self, st, h: Iso):
        return {**st, "payload": h, "got": st["got"] + 1}


@actor
class Reader:
    seen: I32

    @behaviour
    def look(self, st, h: Val):
        return {**st, "seen": st["seen"] + 1}


# ---------------- trace-time (device) discipline ----------------

def test_ref_lite_passed_this_yesterday():
    """Forwarding one iso payload to TWO receivers — an aliased move.
    With I32 annotations (Ref-lite) this traced clean; declaring the
    parameter Iso makes the same program fail the BUILD."""

    @actor
    class BadFanout:
        a: Ref["Holder"]
        b: Ref["Holder"]
        MAX_SENDS = 2

        @behaviour
        def fan(self, st, h: Iso):
            self.send(st["a"], Holder.take, h)
            self.send(st["b"], Holder.take, h)     # second move of h!
            return st

    rt = Runtime(OPTS)
    rt.declare(BadFanout, 1).declare(Holder, 2).start()
    f = rt.spawn(BadFanout)
    rt.send(f, BadFanout.fan, 7)
    with pytest.raises(TypeError, match="use-after-move|aliased move"):
        rt.run(max_steps=4)


def test_retained_after_move_rejected():
    @actor
    class BadKeep:
        out: Ref["Holder"]
        stash: Iso
        MAX_SENDS = 1

        @behaviour
        def keep(self, st, h: Iso):
            self.send(st["out"], Holder.take, h)
            return {**st, "stash": h}              # retain after move!

    rt = Runtime(OPTS)
    rt.declare(BadKeep, 1).declare(Holder, 1).start()
    k = rt.spawn(BadKeep)
    rt.send(k, BadKeep.keep, 7)
    with pytest.raises(TypeError, match="retains a moved iso"):
        rt.run(max_steps=4)


def test_iso_field_left_in_state_after_move_rejected():
    """Moving an Iso FIELD and leaving it untouched in state is the
    sneaky retain (the field still holds the handle)."""

    @actor
    class BadField:
        out: Ref["Holder"]
        payload: Iso
        MAX_SENDS = 1

        @behaviour
        def flush(self, st, _: I32):
            self.send(st["out"], Holder.take, st["payload"])
            return st                              # payload still there!

    rt = Runtime(OPTS)
    rt.declare(BadField, 1).declare(Holder, 1).start()
    b = rt.spawn(BadField)
    rt.send(b, BadField.flush, 0)
    with pytest.raises(TypeError, match="retains a moved iso"):
        rt.run(max_steps=4)


def test_use_after_move_as_other_arg_rejected():
    @actor
    class BadReuse:
        out: Ref["Holder"]
        log: Ref["Reader"]
        MAX_SENDS = 2

        @behaviour
        def go(self, st, h: Iso):
            self.send(st["out"], Holder.take, h)
            self.send(st["log"], Reader.look, h)   # use after move
            return st

    rt = Runtime(OPTS)
    rt.declare(BadReuse, 1).declare(Holder, 1).declare(Reader, 1).start()
    b = rt.spawn(BadReuse)
    rt.send(b, BadReuse.go, 7)
    with pytest.raises(TypeError, match="use-after-move"):
        rt.run(max_steps=4)


def test_move_once_and_clear_is_legal():
    """The CORRECT iso protocol: move once, clear the field. Runs."""

    @actor
    class GoodMove:
        out: Ref["Holder"]
        payload: Iso
        MAX_SENDS = 1

        @behaviour
        def flush(self, st, _: I32):
            self.send(st["out"], Holder.take, st["payload"])
            return {**st, "payload": np.int32(-1)}   # consumed

    rt = Runtime(OPTS)
    rt.declare(GoodMove, 1).declare(Holder, 1).start()
    h = rt.spawn(Holder)
    g = rt.spawn(GoodMove, out=int(h), payload=42)
    rt.send(g, GoodMove.flush, 0)
    assert rt.run(max_steps=16) == 0
    assert rt.state_of(int(h))["got"] == 1
    assert rt.state_of(int(h))["payload"] == 42
    assert rt.state_of(int(g))["payload"] == -1


def test_val_aliases_freely():
    """Shared-immutable payloads fan out without restriction."""

    @actor
    class GoodFan:
        a: Ref["Reader"]
        b: Ref["Reader"]
        MAX_SENDS = 2

        @behaviour
        def fan(self, st, h: Val):
            self.send(st["a"], Reader.look, h)
            self.send(st["b"], Reader.look, h)     # fine: val
            return st

    rt = Runtime(OPTS)
    rt.declare(GoodFan, 1).declare(Reader, 2).start()
    r1, r2 = rt.spawn(Reader), rt.spawn(Reader)
    f = rt.spawn(GoodFan, a=int(r1), b=int(r2))
    rt.send(f, GoodFan.fan, 9)
    assert rt.run(max_steps=16) == 0
    assert rt.state_of(int(r1))["seen"] == 1
    assert rt.state_of(int(r2))["seen"] == 1


def test_val_cannot_be_passed_as_iso_parameter():
    """The store lattice (≙ is_cap_sub_cap): a shared val cannot grant
    the unique ownership an Iso parameter requires."""

    @actor
    class BadUpgrade:
        out: Ref["Holder"]
        MAX_SENDS = 1

        @behaviour
        def go(self, st, h: Val):
            self.send(st["out"], Holder.take, h)   # Val -> Iso param!
            return st

    rt = Runtime(OPTS)
    rt.declare(BadUpgrade, 1).declare(Holder, 1).start()
    b = rt.spawn(BadUpgrade)
    rt.send(b, BadUpgrade.go, 7)
    with pytest.raises(TypeError, match="cannot grant"):
        rt.run(max_steps=4)


def test_val_cannot_be_stored_into_iso_field():
    @actor
    class BadStore:
        stash: Iso

        @behaviour
        def keep(self, st, h: Val):
            return {**st, "stash": h}              # Val -> Iso field!

    rt = Runtime(OPTS)
    rt.declare(BadStore, 1).start()
    b = rt.spawn(BadStore)
    rt.send(b, BadStore.keep, 7)
    with pytest.raises(TypeError, match="cannot grant"):
        rt.run(max_steps=4)


def test_iso_downgrades_to_val_field():
    """iso → val is the legal downgrade (unique consumed into shared),
    and tag accepts anything readable it came from... iso→tag too."""

    @actor
    class Downgrade:
        shared: Val
        opaque: Tag

        @behaviour
        def keep(self, st, h: Iso, t: Iso):
            return {**st, "shared": h, "opaque": t}

    rt = Runtime(OPTS)
    rt.declare(Downgrade, 1).start()
    d = rt.spawn(Downgrade)
    rt.send(d, Downgrade.keep, 5, 6)
    assert rt.run(max_steps=16) == 0
    assert rt.state_of(d)["shared"] == 5
    assert rt.state_of(d)["opaque"] == 6


def test_tag_cannot_become_readable():
    @actor
    class BadRead:
        shared: Val

        @behaviour
        def keep(self, st, t: Tag):
            return {**st, "shared": t}             # Tag -> Val field!

    rt = Runtime(OPTS)
    rt.declare(BadRead, 1).start()
    b = rt.spawn(BadRead)
    rt.send(b, BadRead.keep, 7)
    with pytest.raises(TypeError, match="cannot grant"):
        rt.run(max_steps=4)


def test_iso_stored_into_two_fields_is_aliasing():
    @actor
    class TwoOwners:
        a: Iso
        b: Iso

        @behaviour
        def keep(self, st, h: Iso):
            return {**st, "a": h, "b": h}          # two owners!

    rt = Runtime(OPTS)
    rt.declare(TwoOwners, 1).start()
    t = rt.spawn(TwoOwners)
    rt.send(t, TwoOwners.keep, 7)
    with pytest.raises(TypeError, match="exactly one owner"):
        rt.run(max_steps=4)


def test_iso_downgrade_send_is_a_move():
    """Shipping an iso through a Val parameter is still a MOVE: the
    sender cannot also retain it (review finding — two owners across
    actors otherwise)."""

    @actor
    class BadShare:
        log: Ref["Reader"]
        stash: Iso
        MAX_SENDS = 1

        @behaviour
        def go(self, st, h: Iso):
            self.send(st["log"], Reader.look, h)   # iso -> Val param
            return {**st, "stash": h}              # ...and retains it!

    rt = Runtime(OPTS)
    rt.declare(BadShare, 1).declare(Reader, 1).start()
    b = rt.spawn(BadShare)
    rt.send(b, BadShare.go, 7)
    with pytest.raises(TypeError, match="retains a moved iso"):
        rt.run(max_steps=4)


def test_spawn_sync_obeys_cap_lattice():
    """The sync-constructor path enforces the same lattice (review
    finding): a Val payload cannot initialise an Iso field through
    spawn_sync."""

    @actor
    class Kid:
        stash: Iso

        @behaviour
        def create(self, st, h: Iso):
            return {**st, "stash": h}

    @actor
    class BadParent:
        MAX_SENDS = 1
        SPAWNS = {"Kid": 1}

        @behaviour
        def make(self, st, h: Val):
            self.spawn_sync(Kid.create, h)         # Val -> Iso ctor arg
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                msg_words=2, inject_slots=8))
    rt.declare(BadParent, 1).declare(Kid, 2).start()
    p = rt.spawn(BadParent)
    rt.send(p, BadParent.make, 7)
    with pytest.raises(TypeError, match="cannot grant"):
        rt.run(max_steps=4)


def test_spawn_sync_iso_arg_moves_to_newborn():
    """Handing an iso to a sync constructor moves it: the spawner
    cannot retain it afterwards."""

    @actor
    class Kid2:
        stash: Iso

        @behaviour
        def create(self, st, h: Iso):
            return {**st, "stash": h}

    @actor
    class BadKeeper:
        mine: Iso
        MAX_SENDS = 1
        SPAWNS = {"Kid2": 1}

        @behaviour
        def make(self, st, h: Iso):
            self.spawn_sync(Kid2.create, h)
            return {**st, "mine": h}               # retained after move

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                msg_words=2, inject_slots=8))
    rt.declare(BadKeeper, 1).declare(Kid2, 2).start()
    k = rt.spawn(BadKeeper)
    rt.send(k, BadKeeper.make, 7)
    with pytest.raises(TypeError, match="retains a moved iso"):
        rt.run(max_steps=4)


# ---------------- dynamic (host heap) discipline ----------------

def test_heap_iso_unbox_consumes_and_double_take_raises():
    h = HostHeap()
    hd = h.box({"payload": 1})
    assert h.mode(hd) == "iso"
    assert h.peek(hd) == {"payload": 1}
    assert h.unbox(hd) == {"payload": 1}
    with pytest.raises(KeyError):
        h.unbox(hd)                    # double-take = use-after-send
    assert h.live == 0


def test_heap_val_is_read_only_shared():
    h = HostHeap()
    hd = h.box_val((1, 2, 3))
    assert h.peek(hd) == (1, 2, 3)
    assert h.peek(hd) == (1, 2, 3)     # shared: peek forever
    with pytest.raises(CapabilityError, match="shared-immutable"):
        h.unbox(hd)
    h.drop(hd)
    assert h.live == 0


def test_heap_tag_is_opaque():
    h = HostHeap()
    hd = h.box_tag(object())
    with pytest.raises(CapabilityError, match="opaque"):
        h.peek(hd)
    with pytest.raises(CapabilityError, match="opaque"):
        h.unbox(hd)
    h.drop(hd)


def test_in_flight_iso_rejects_peek_and_resend():
    """Use-after-send: once an iso handle rides an Iso parameter, the
    sender may neither read it nor send it again until delivery."""
    logs = []

    @actor
    class HSink:
        HOST = True
        got: I32

        @behaviour
        def recv(self, st, h: Iso):
            logs.append(int(h))
            return {**st, "got": st["got"] + 1}

    rt = Runtime(OPTS)
    rt.declare(HSink, 1).start()
    sink = rt.spawn(HSink)
    hd = rt.heap.box(b"bytes")
    rt.send(sink, HSink.recv, hd)
    with pytest.raises(CapabilityError, match="use-after-send"):
        rt.heap.peek(hd)
    with pytest.raises(CapabilityError, match="aliased move"):
        rt.send(sink, HSink.recv, hd)
    assert rt.run(max_steps=32) == 0
    assert logs == [hd]
    # Delivery completed the move: the receiver's side may unbox now.
    assert rt.heap.unbox(hd) == b"bytes"


def test_null_sentinel_is_exempt_from_move_discipline():
    """-1/0 'no handle' sentinels may ride Iso parameters repeatedly
    (small-int interning must not fake an aliased move), including the
    clear-to-minus-one consume idiom alongside a sentinel send."""

    @actor
    class NullFan:
        a: Ref["Holder"]
        b: Ref["Holder"]
        payload: Iso
        MAX_SENDS = 2

        @behaviour
        def fan(self, st, _: I32):
            self.send(st["a"], Holder.take, np.int32(-1))
            self.send(st["b"], Holder.take, np.int32(-1))
            return {**st, "payload": np.int32(-1)}

    rt = Runtime(OPTS)
    rt.declare(NullFan, 1).declare(Holder, 2).start()
    f = rt.spawn(NullFan)
    rt.send(f, NullFan.fan, 0)
    assert rt.run(max_steps=16) == 0


def test_failed_send_does_not_poison_handle():
    """A send that fails validation must leave the handle usable (the
    in-flight mark happens only after packing succeeds)."""

    @actor
    class HSink3:
        HOST = True
        got: I32

        @behaviour
        def recv(self, st, h: Iso):
            return {**st, "got": st["got"] + 1}

    rt = Runtime(OPTS)
    rt.declare(HSink3, 1).start()
    sink = rt.spawn(HSink3)
    hd = rt.heap.box("precious")
    with pytest.raises(TypeError):
        rt.send(sink, HSink3.recv, hd, 123)   # wrong arg count
    assert rt.heap.peek(hd) == "precious"     # NOT poisoned
    rt.send(sink, HSink3.recv, hd)            # corrected retry works
    assert rt.run(max_steps=32) == 0
    assert rt.state_of(sink)["got"] == 1


def test_request_exit_before_run_is_honoured():
    @actor
    class Idle:
        HOST = True
        n: I32

        @behaviour
        def tick(self, st, v: I32):
            return {**st, "n": st["n"] + 1}

    rt = Runtime(OPTS)
    rt.declare(Idle, 1).start()
    rt.spawn(Idle)
    rt.request_exit(42)
    assert rt.run(max_steps=100) == 42


def test_val_handle_rides_message_and_stays_peekable():
    @actor
    class HSink2:
        HOST = True
        got: I32

        @behaviour
        def recv(self, st, h: Val):
            return {**st, "got": st["got"] + 1}

    rt = Runtime(OPTS)
    rt.declare(HSink2, 1).start()
    sink = rt.spawn(HSink2)
    hd = rt.heap.box_val("shared")
    rt.send(sink, HSink2.recv, hd)
    assert rt.heap.peek(hd) == "shared"   # still readable in flight
    assert rt.run(max_steps=32) == 0
    assert rt.state_of(sink)["got"] == 1
    assert rt.heap.peek(hd) == "shared"
