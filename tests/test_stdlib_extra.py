"""Stdlib breadth round 2: backpressure, signals, bureaucracy, debug,
assert, capsicum (≙ packages/{backpressure,signals,bureaucracy,debug,
assert,capsicum}; SURVEY.md §2.3)."""

import io
import os
import signal as _os_signal
import time

import numpy as np
import pytest

from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.errors import PonyError
from ponyc_tpu.stdlib import backpressure as bp
from ponyc_tpu.stdlib import bureaucracy, capsicum, signals
from ponyc_tpu.stdlib.assertion import Assert, Fact
from ponyc_tpu.stdlib.debug import Debug


# ---------- backpressure (≙ pony_apply/release_backpressure) ----------

@actor
class Sink:
    total: I32

    BATCH = 4

    @behaviour
    def consume(self, st, v: I32):
        return {**st, "total": st["total"] + v}


@actor
class Producer:
    sink: Ref
    left: I32

    MAX_SENDS = 2

    @behaviour
    def produce(self, st, n: I32):
        self.send(st["sink"], Sink.consume, 1, when=n > 0)
        self.send(self.actor_id, Producer.produce, n - 1, when=n > 0)
        return {**st, "left": n - 1}


def _bp_build(items=64):
    opts = RuntimeOptions(mailbox_cap=8, batch=2, msg_words=1,
                          max_sends=2, spill_cap=128, inject_slots=8)
    rt = Runtime(opts)
    rt.declare(Producer, 1).declare(Sink, 1)
    rt.start()
    sink = rt.spawn(Sink)
    prod = rt.spawn(Producer, sink=sink)
    rt.send(prod, Producer.produce, items)
    return rt, prod, sink


def test_apply_backpressure_mutes_sender_and_release_recovers():
    rt, prod, sink = _bp_build()
    inj = rt._drain_inject()
    st, aux = rt._step(rt.state, *inj)
    inj = rt._empty_inject
    for _ in range(3):
        st, aux = rt._step(st, *inj)
    rt.state = st
    assert not bool(np.asarray(st.muted)[prod]), "no pressure yet"

    auth = bp.ApplyReleaseBackpressureAuth(rt.ambient_auth())
    bp.apply(auth, sink)
    st = rt.state
    for _ in range(3):
        st, aux = rt._step(st, *inj)
    rt.state = st
    occ = int(np.asarray(st.tail - st.head)[sink])
    assert bool(np.asarray(st.muted)[prod]), \
        "sender must mute on send to a pressured receiver"
    assert occ <= rt.opts.overload_occ, \
        "mute was pressure-driven, not occupancy-driven"

    bp.release(auth, sink)
    st = rt.state
    for _ in range(3):
        st, aux = rt._step(st, *inj)
    rt.state = st
    assert not bool(np.asarray(st.muted)[prod]), "release must unmute"
    assert rt.run() == 0
    assert rt.state_of(sink)["total"] == 64


def test_backpressure_auth_requires_ambient():
    rt, _, _ = _bp_build(items=1)
    with pytest.raises(TypeError):
        bp.ApplyReleaseBackpressureAuth(object())
    with pytest.raises(TypeError):
        bp.apply(object(), 0)
    rt.run()


# ---------- signals (≙ packages/signals SignalHandler) ----------

@actor
class SigWatcher:
    HOST = True
    hits: I32

    @behaviour
    def on_event(self, st, kind: I32, arg: I32, flags: I32):
        return {**st, "hits": st["hits"] + 1}


def test_signal_handler_delivers_and_disposes():
    rt = Runtime(RuntimeOptions(mailbox_cap=16, batch=4, max_sends=1,
                                msg_words=3, spill_cap=64,
                                inject_slots=32))
    rt.declare(SigWatcher, 1).start()
    w = rt.spawn(SigWatcher)
    # Park the prior disposition at ignore: dispose() restores it
    # (≙ _dispose restoring the event), making the post-dispose raise
    # below a safe no-op instead of the terminating default action.
    prev = _os_signal.signal(_os_signal.SIGUSR1, _os_signal.SIG_IGN)
    h = signals.SignalHandler(rt, w, SigWatcher.on_event,
                              signals.Sig.usr1())
    h.raise_()
    deadline = time.time() + 5.0
    while time.time() < deadline:
        rt.run(max_steps=50)
        if rt.state_of(w)["hits"] >= 1:
            break
        time.sleep(0.02)
    assert rt.state_of(w)["hits"] >= 1
    h.dispose()
    hits = rt.state_of(w)["hits"]
    os.kill(os.getpid(), _os_signal.SIGUSR1)   # ignored: disposition
    time.sleep(0.05)                           # restored to SIG_IGN
    rt.run(max_steps=50)
    assert rt.state_of(w)["hits"] == hits
    _os_signal.signal(_os_signal.SIGUSR1, prev)
    rt.stop()


# ---------- bureaucracy (≙ Custodian + Registrar) ----------

def test_custodian_disposes_objects_and_actors():
    rt, prod, sink = _bp_build(items=0)
    closed = []

    class Thing:
        def dispose(self):
            closed.append("thing")

    cust = bureaucracy.Custodian()
    cust.apply(Thing())
    cust.apply_actor(rt, prod, Producer.produce, 2)
    cust.dispose()
    rt.run()
    assert closed == ["thing"]
    assert rt.state_of(sink)["total"] == 2      # dispose sent the msg
    cust.dispose()                               # set cleared: no resend
    rt.run()
    assert rt.state_of(sink)["total"] == 2


def test_registrar_lookup_fulfils_and_rejects():
    reg = bureaucracy.Registrar()
    obj = object()
    reg.update("db", obj)
    got = []
    reg.apply("db").next(got.append)
    assert got == [obj]
    rejected = []
    reg.apply("absent").next(got.append, lambda _r: rejected.append(True))
    assert rejected == [True]
    reg.remove("db", object())        # wrong value: keeps mapping
    reg.apply("db").next(got.append)
    assert got == [obj, obj]
    reg.remove("db", obj)             # right value: removes
    reg.apply("db").next(got.append, lambda _r: rejected.append(True))
    assert rejected == [True, True]


# ---------- debug / assert (≙ packages/debug, packages/assert) ----------

def test_debug_prints_when_enabled(monkeypatch):
    monkeypatch.setenv("PONY_TPU_DEBUG", "1")
    buf = io.StringIO()
    Debug(["a", "b"], sep="/", stream=buf)
    assert buf.getvalue() == "a/b\n"
    monkeypatch.setenv("PONY_TPU_DEBUG", "0")
    buf2 = io.StringIO()
    Debug("hidden", stream=buf2)
    assert buf2.getvalue() == ""


def test_fact_raises_pony_error_and_assert_follows_debug(monkeypatch):
    Fact(True)
    with pytest.raises(PonyError):
        Fact(False, "nope")
    monkeypatch.setenv("PONY_TPU_DEBUG", "0")
    Assert(False, "ignored when debug off")
    monkeypatch.setenv("PONY_TPU_DEBUG", "1")
    with pytest.raises(PonyError):
        Assert(False, "caught when debug on")


# ---------- capsicum (≙ packages/capsicum rights algebra) ----------

def test_cap_rights_algebra():
    r = capsicum.CapRights.from_caps({"read", "seek"})
    assert r.contains(capsicum.CapRights().set(capsicum.Cap.read()))
    assert r.contains(capsicum.CapRights().set(capsicum.Cap.mmap()))
    assert not r.contains(capsicum.CapRights().set(capsicum.Cap.write()))
    r.set(capsicum.Cap.write())
    assert r.contains(capsicum.CapRights().set(capsicum.Cap.write()))
    other = capsicum.CapRights().set(capsicum.Cap.write())
    r.remove(other)
    assert not r.contains(other)
    merged = capsicum.CapRights().merge(r)
    assert merged.contains(r) and r.contains(merged)
    r.clear()
    assert capsicum.CapRights().contains(r)
    assert r.limit(0) is True
