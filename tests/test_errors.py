"""Int-coded errors + debug invariants (≙ the fork's pony_error_int/
pony_error_code machinery, test/libponyrt/lang/error.cc, and the
debug-build queue checkers actor.c:57-92)."""

import pytest

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.errors import PonyError, pony_try


@actor
class Div:
    ok: I32

    @behaviour
    def div(self, st, a: I32, b: I32):
        # Errors are values under vmap: record the code, skip the work.
        bad = b == 0
        self.error_int(7, when=bad)
        import jax.numpy as jnp
        q = a // jnp.where(bad, 1, b)
        return {**st, "ok": jnp.where(bad, st["ok"], q)}


@actor
class HostDiv:
    HOST = True
    ok: I32

    @behaviour
    def div(self, st, a: I32, b: I32):
        if b == 0:
            raise PonyError(9, "divide by zero")
        return {**st, "ok": a // b}


def _mk():
    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=2, max_sends=1,
                                msg_words=2, inject_slots=16,
                                debug_checks=True))
    rt.declare(Div, 4).declare(HostDiv, 2)
    return rt.start()


def test_device_error_int_records_and_continues():
    rt = _mk()
    a = rt.spawn(Div)
    rt.send(a, Div.div, 10, 2)
    rt.send(a, Div.div, 10, 0)     # errors with code 7
    rt.send(a, Div.div, 9, 3)      # still alive, keeps dispatching
    rt.run(max_steps=20)
    assert rt.state_of(a)["ok"] == 3
    assert rt.last_error(a) == 7
    assert rt.counter("n_errors") == 1
    b = rt.spawn(Div)
    assert rt.last_error(b) == 0


def test_host_pony_error_is_caught_per_behaviour():
    rt = _mk()
    h = rt.spawn(HostDiv)
    rt.send(h, HostDiv.div, 12, 3)
    rt.send(h, HostDiv.div, 12, 0)   # raises PonyError(9) — swallowed
    rt.send(h, HostDiv.div, 20, 5)   # actor continues
    rt.run(max_steps=20)
    assert rt.state_of(h)["ok"] == 4
    assert rt.last_error(h) == 9
    assert rt.totals["host_errors"] == 1


def test_pony_try_shape():
    ok, v = pony_try(lambda: 42)
    assert ok and v == 42
    ok, code = pony_try(lambda: (_ for _ in ()).throw(PonyError(5)))
    assert not ok and code == 5
    e = PonyError(3, "msg")
    assert e.code == 3 and ":" in e.loc   # carries a raise location
    with pytest.raises(ValueError):
        pony_try(lambda: (_ for _ in ()).throw(ValueError()))  # not caught


def test_invariants_hold_through_pressure():
    # Overflow a mailbox so spill/mute machinery engages, with
    # debug_checks validating every aux fetch along the way.
    from ponyc_tpu import Ref

    @actor
    class Flood:
        sink: Ref

        @behaviour
        def go(self, st, n: I32):
            self.send(st["sink"], Flood.rx, n)
            return st

        @behaviour
        def rx(self, st, n: I32):
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=1, max_sends=1,
                                msg_words=2, inject_slots=64, spill_cap=64,
                                debug_checks=True))
    rt.declare(Flood, 16).start()
    ids = rt.spawn_many(Flood, 16)
    rt.set_fields(Flood, ids, sink=int(ids[0]))
    for i in ids[1:]:
        for k in range(3):
            rt.send(int(i), Flood.go, k)
    rt.run(max_steps=200)
    rt.check_invariants()
    assert rt.counter("n_delivered") > 0


def test_device_error_location_resolves_to_call_site():
    """last_error_loc resolves to the ctx.error_int call site's
    file:line (≙ the fork's __error_loc, DIVERGENCE.md)."""
    from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour

    @actor
    class Erring:
        n: I32

        @behaviour
        def go(self, st, v: I32):
            self.error_int(42, when=v > 10)      # <- the site under test
            return {**st, "n": st["n"] + 1}

    rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=1, msg_words=1,
                                max_sends=1, spill_cap=16, inject_slots=4))
    rt.declare(Erring, 2).start()
    a, b = rt.spawn(Erring), rt.spawn(Erring)
    rt.send(a, Erring.go, 99)
    rt.send(b, Erring.go, 1)
    rt.run()
    assert rt.last_error(a) == 42
    loc = rt.last_error_loc(a)
    assert loc.endswith(".py:" + loc.rsplit(":", 1)[1])
    assert "test_errors" in loc
    assert rt.last_error(b) == 0
    assert rt.last_error_loc(b) == "?"


def test_host_error_location_from_pony_error():
    from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
    from ponyc_tpu.errors import PonyError

    @actor
    class H:
        HOST = True
        n: I32

        @behaviour
        def go(self, st, v: I32):
            if v > 5:
                raise PonyError(7, "boom")
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=4, batch=1, msg_words=1,
                                max_sends=1, spill_cap=16, inject_slots=4))
    rt.declare(H, 1).start()
    h = rt.spawn(H)
    rt.send(h, H.go, 9)
    rt.run()
    assert rt.last_error(h) == 7
    assert "test_errors" in rt.last_error_loc(h)


def test_total_memory_accounting():
    """≙ @ponyint_total_memory (fork): the runtime reports its memory."""
    from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour

    @actor
    class M:
        n: I32

        @behaviour
        def go(self, st, v: I32):
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, msg_words=2,
                                max_sends=1, spill_cap=32, inject_slots=4))
    rt.declare(M, 256).start()
    mem = rt.total_memory()
    assert mem["host_rss_bytes"] > 1 << 20
    # buf alone is cap*w1*N*4 bytes
    assert mem["device_state_bytes"] >= 8 * 3 * 256 * 4
    assert mem["pool_live_blocks"] >= 0
