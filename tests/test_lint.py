"""The whole-program lint pass (ponyc_tpu/lint ≙ reach/paint +
type/safeto run program-wide): message-flow graph assembly from probe
facts, rule passes R1–R5, suppressions, the CLI surfaces, and the
examples/ sweep (every shipped example must lint clean — this test IS
the tier-1 regression net for probe tracing and the graph builder)."""

import importlib
import json
import os
import sys
import time

import pytest

from ponyc_tpu import (Blob, BlobVal, I32, Iso, Program, Ref, Runtime,
                       RuntimeOptions, actor, behaviour)
from ponyc_tpu.lint import (Finding, findings_to_json, format_findings,
                            lint_module, lint_program, lint_types)
from ponyc_tpu.verify import (SendFact, VerifyError, behaviour_effects,
                              probe_behaviour, verify_program,
                              when_const)

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples"))


# ---- shared fixture types ------------------------------------------------

@actor
class Sink:
    x: I32

    @behaviour
    def put(self, st, v: I32):
        return {**st, "x": v}


@actor
class Feeder:
    out: Ref["Sink"]
    MAX_SENDS = 2
    SPAWNS = {"Sink": 1}

    @behaviour
    def go(self, st, v: I32):
        self.send(st["out"], Sink.put, v)
        self.spawn(Sink.put, v, when=v > 0)
        return st


def rules_of(findings):
    return {f.rule for f in findings}


# ---- probe facts (the tentpole's raw material) ---------------------------

def test_when_const_classification():
    import jax.numpy as jnp
    assert when_const(True) is True
    assert when_const(False) is False
    assert when_const(1) is True
    assert when_const(jnp.bool_(False)) is False   # concrete array


def test_probe_records_send_and_spawn_facts():
    ctx = probe_behaviour(Feeder.go)
    kinds = [(f.kind, f.dst_type, f.dst_behaviour, f.when)
             for f in ctx.send_facts]
    # Unconditional send to Sink.put; data-dependent spawn (when=v>0)
    # recorded as kind "spawn" with the USER's mask constness (None).
    assert ("send", "Sink", "put", True) in kinds
    assert ("spawn", "Sink", "put", None) in kinds
    fact = ctx.send_facts[0]
    assert isinstance(fact, SendFact) and fact.target_ref == "Sink"


def test_marks_show_budget_not_observed_count():
    eff = behaviour_effects(Feeder.go)
    assert "sends 2/2" in eff.marks()
    assert "sends≤" not in eff.marks()


# ---- R1 reachability -----------------------------------------------------

def test_r1_unreachable_type_and_behaviour():
    @actor
    class Lonely:
        y: I32

        @behaviour
        def idle(self, st, v: I32):
            return st

    # Un-rooted: any behaviour may be host-injected -> quiet.
    assert lint_types(Feeder, Sink, Lonely) == []
    # Rooted: Lonely is unreachable from Feeder.go.
    fs = lint_types(Feeder, Sink, Lonely, roots=[Feeder.go])
    r1 = [f for f in fs if f.rule == "R1"]
    assert len(r1) == 1 and r1[0].type_name == "Lonely"
    assert r1[0].behaviour is None and r1[0].severity == "warning"

    @actor
    class HalfDead:
        o: Ref["Sink"]
        MAX_SENDS = 1

        @behaviour
        def used(self, st, v: I32):
            self.send(st["o"], Sink.put, v)
            return st

        @behaviour
        def never(self, st, v: I32):
            return st

    fs = lint_types(HalfDead, Sink, roots=[HalfDead.used])
    r1 = [f for f in fs if f.rule == "R1"]
    assert [(f.type_name, f.behaviour) for f in r1] == [
        ("HalfDead", "never")]


def test_r1_quiet_when_cycle_reached_from_root():
    # spawn_tree shape: the root reaches a self-cycle; nothing flagged.
    @actor
    class Tree:
        parent: Ref
        SPAWNS = {"Tree": 2}
        MAX_SENDS = 3

        @behaviour
        def grow(self, st, d: I32, parent: Ref):
            leaf = d <= 0
            self.spawn(Tree.grow, d - 1, self.actor_id, when=~leaf)
            self.spawn(Tree.grow, d - 1, self.actor_id, when=~leaf)
            self.send(parent, Tree.up, when=leaf)
            return st

        @behaviour
        def up(self, st):
            return st

    assert lint_types(Tree, roots=[Tree.grow]) == []


# ---- R2 dead-letter ------------------------------------------------------

def test_r2_send_to_absent_type_is_error():
    fs = lint_types(Feeder)          # Sink NOT in the analysed world
    errs = [f for f in fs if f.rule == "R2" and f.severity == "error"]
    assert len(errs) >= 1
    assert errs[0].type_name == "Feeder" and errs[0].behaviour == "go"
    assert "Sink" in errs[0].message


def test_r2_constant_false_send_is_dead_site():
    @actor
    class DeadSend:
        o: Ref["Sink"]
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            self.send(st["o"], Sink.put, v, when=False)
            return st

    fs = lint_types(DeadSend, Sink)
    assert any(f.rule == "R2" and "when=False" in f.message
               for f in fs)


def test_r2_never_spawned_only_in_rooted_mode():
    @actor
    class Orphaned:
        x: I32

        @behaviour
        def take(self, st, v: I32):
            return {**st, "x": v}

    @actor
    class Talker:
        o: Ref["Orphaned"]
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            self.send(st["o"], Orphaned.take, v)
            return st

    assert lint_types(Talker, Orphaned) == []       # un-rooted: quiet
    fs = lint_types(Talker, Orphaned, roots=[Talker.go])
    r2 = [f for f in fs if f.rule == "R2" and f.type_name == "Orphaned"]
    assert len(r2) == 1 and "no spawn site" in r2[0].message
    assert "Talker.go" in r2[0].message


# ---- R3 capability/race --------------------------------------------------

def test_r3_iso_aliased_into_two_sends():
    @actor
    class Taker:
        x: I32

        @behaviour
        def take(self, st, p: Iso):
            return st

    @actor
    class Aliaser:
        a: Ref["Taker"]
        b: Ref["Taker"]
        MAX_SENDS = 2

        @behaviour
        def go(self, st, p: Iso):
            self.send(st["a"], Taker.take, p)
            self.send(st["b"], Taker.take, p)      # aliased move
            return st

    fs = lint_types(Taker, Aliaser)
    r3 = [f for f in fs if f.rule == "R3"]
    assert len(r3) == 1 and r3[0].severity == "error"
    assert (r3[0].type_name, r3[0].behaviour) == ("Aliaser", "go")
    assert "use-after-move" in r3[0].message


def test_r3_write_to_val_frozen_blob_downstream():
    @actor
    class Scribbler:
        x: I32

        @behaviour
        def scribble(self, st, b: BlobVal):
            self.blob_set(b, 0, 1)        # write to shared-immutable
            return st

    fs = lint_types(Scribbler)
    r3 = [f for f in fs if f.rule == "R3"]
    assert len(r3) == 1 and "frozen (val) blob" in r3[0].message


def test_r3_host_cohort_declares_blob():
    @actor
    class HostReader:
        HOST = True
        n: I32

        @behaviour
        def read(self, st, b: Blob):
            return st

    fs = lint_types(HostReader)
    r3 = [f for f in fs if f.rule == "R3"]
    assert len(r3) == 1 and r3[0].severity == "error"
    assert "HOST" in r3[0].message and r3[0].behaviour == "read"


# ---- R4 amplification ----------------------------------------------------

def _pingpong(yields):
    @actor
    class Ping:
        o: Ref["Pong"]
        MAX_SENDS = 2

        @behaviour
        def ping(self, st, v: I32):
            self.send(st["o"], Pong.pong, v)
            self.send(st["o"], Pong.pong, v)
            return st

    @actor
    class Pong:
        o: Ref["Ping"]
        MAX_SENDS = 1

        @behaviour
        def pong(self, st, v: I32):
            if yields:
                self.yield_(when=v > 7)
            self.send(st["o"], Ping.ping, v)
            return st

    return Ping, Pong


def test_r4_amplifying_cycle_flagged():
    Ping, Pong = _pingpong(yields=False)
    fs = lint_types(Ping, Pong)
    r4 = [f for f in fs if f.rule == "R4"]
    assert len(r4) == 1
    assert (r4[0].type_name, r4[0].behaviour) == ("Ping", "ping")
    assert "2 unconditional messages" in r4[0].message


def test_r4_yield_on_cycle_is_pressure_point():
    Ping, Pong = _pingpong(yields=True)
    assert [f for f in lint_types(Ping, Pong) if f.rule == "R4"] == []


def test_r4_conditional_cycle_not_flagged():
    @actor
    class Careful:
        o: Ref["Careful"]
        MAX_SENDS = 2

        @behaviour
        def go(self, st, v: I32):
            self.send(st["o"], Careful.go, v - 1, when=v > 0)
            self.send(st["o"], Careful.go, v - 2, when=v > 1)
            return st

    assert [f for f in lint_types(Careful) if f.rule == "R4"] == []


# ---- R5 budget feasibility ----------------------------------------------

def test_r5_unconditional_spawn_on_cycle():
    @actor
    class Fork:
        x: I32
        SPAWNS = {"Fork": 1}
        MAX_SENDS = 2

        @behaviour
        def boom(self, st, v: I32):
            self.spawn(Fork.boom, v)
            self.send(self.actor_id, Fork.boom, v)
            return st

    fs = lint_types(Fork)
    r5 = [f for f in fs if f.rule == "R5" and f.severity == "warning"]
    assert len(r5) == 1 and "unconditional spawn" in r5[0].message


def test_r5_blob_leak_on_cycle():
    @actor
    class Leaker:
        x: I32
        MAX_BLOBS = 1
        MAX_SENDS = 1

        @behaviour
        def churn(self, st, v: I32):
            self.blob_alloc(length=1)          # never freed, not frozen
            self.send(self.actor_id, Leaker.churn, v)
            return st

    fs = lint_types(Leaker)
    r5 = [f for f in fs if f.rule == "R5" and f.severity == "warning"]
    assert len(r5) == 1 and "blob" in r5[0].message


def test_r5_unused_budgets_are_info():
    @actor
    class Hoarder:
        x: I32
        SPAWNS = {"Sink": 2}
        MAX_BLOBS = 3

        @behaviour
        def idle(self, st, v: I32):
            return st

    fs = lint_types(Hoarder, Sink)
    infos = [f for f in fs if f.rule == "R5" and f.severity == "info"]
    assert len(infos) == 2          # unused SPAWNS + unused MAX_BLOBS
    # info-severity findings are advisory: the CLI still exits 0.
    assert all(f.severity == "info" for f in fs)


# ---- suppressions --------------------------------------------------------

def test_lint_ignore_suppresses_by_rule():
    @actor
    class Muted:
        x: I32
        SPAWNS = {"Muted": 1}
        MAX_SENDS = 2
        LINT_IGNORE = ("R5",)

        @behaviour
        def boom(self, st, v: I32):
            self.spawn(Muted.boom, v)
            self.send(self.actor_id, Muted.boom, v)
            return st

    assert lint_types(Muted) == []
    kept = lint_types(Muted, include_suppressed=True)
    assert any(f.rule == "R5" for f in kept)


# ---- program-level surfaces ---------------------------------------------

def test_lint_program_and_verify_program_report_host_nodes():
    @actor
    class HostEnd:
        HOST = True
        seen: I32

        @behaviour
        def result(self, st, v: I32):
            return {**st, "seen": st["seen"] + v}

    @actor
    class Dev:
        out: Ref["HostEnd"]
        MAX_SENDS = 1

        @behaviour
        def fin(self, st, v: I32):
            self.send(st["out"], HostEnd.result, v)
            return st

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, msg_words=2,
                                inject_slots=8))
    rt.declare(Dev, 1).declare(HostEnd, 1).start()
    assert lint_program(rt.program) == []
    report = verify_program(rt.program)
    # Host cohorts are reported (zero-effect entries), not skipped.
    assert "HostEnd" in report and "result" in report["HostEnd"]
    assert report["HostEnd"]["result"].sends == 0
    assert report["Dev"]["fin"].sends == 1


def test_verify_program_raises_on_lint_error_findings():
    @actor
    class MisWired:
        out: Ref                     # untyped: build cannot catch it
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            self.send(st["out"], Sink.put, v)    # Sink never declared
            return st

    p = Program(RuntimeOptions(msg_words=2)).declare(MisWired, 1)
    p.finalize()
    with pytest.raises(VerifyError, match="R2"):
        verify_program(p)
    # ... and lint=False restores the per-behaviour-only pass.
    assert "MisWired" in verify_program(p, lint=False)


def test_program_lint_method_pre_and_post_finalize():
    @actor
    class Bad:
        out: Ref
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            self.send(st["out"], Sink.put, v)    # Sink not declared
            return st

    p = Program(RuntimeOptions(msg_words=2)).declare(Bad, 1)
    assert any(f.rule == "R2" for f in p.lint())     # before finalize
    p.finalize()
    assert any(f.rule == "R2" for f in p.lint())     # and after


def test_docgen_marks_dead_letter_behaviours():
    @actor
    class Wrong:
        out: Ref
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            self.send(st["out"], Sink.put, v)
            return st

    from ponyc_tpu.docgen import document
    p = Program(RuntimeOptions(msg_words=2)).declare(Wrong, 1)
    p.finalize()
    md = document(p)
    assert "> **lint:** R2" in md and "dead-letter" in md
    assert "lint:" not in document(p, lint=False)


# ---- output formats ------------------------------------------------------

def test_finding_formats_are_stable():
    f = Finding("R2", "error", "A", "go", "boom")
    assert str(f).startswith("R2 error")
    obj = json.loads(f.json_line())
    # The stable schema: file/line are null when unknown (col stays
    # internal — the github format uses it).
    assert obj == {"rule": "R2", "severity": "error", "type": "A",
                   "behaviour": "go", "message": "boom",
                   "file": None, "line": None}
    assert format_findings([f]).count("\n") == 0
    assert json.loads(findings_to_json([f, f]).splitlines()[1])
    # Located findings render compiler-style and annotate for GitHub.
    g = Finding("R6", "warning", "A", "go", "boo%m", file="a/b.py",
                line=7, col=3)
    assert str(g).startswith("a/b.py:7: R6 warning")
    assert json.loads(g.json_line())["line"] == 7
    gh = g.github_line()
    assert gh.startswith("::warning file=a/b.py,line=7,col=3,")
    assert gh.endswith("::R6 A.go: boo%25m")


# ---- the examples sweep (tier-1 regression net) -------------------------

EXAMPLES_WITHOUT_MODULE_TYPES = {"mandelbrot", "spreader"}
EXPECTED_EXAMPLE_FINDINGS: dict = {}    # none today; pin regressions here


def _example_names():
    exdir = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "examples")
    return sorted(f[:-3] for f in os.listdir(exdir)
                  if f.endswith(".py") and not f.startswith("_"))


@pytest.mark.parametrize("name", _example_names())
def test_examples_lint_clean(name):
    mod = importlib.import_module(name)
    if name in EXAMPLES_WITHOUT_MODULE_TYPES:
        with pytest.raises(ValueError, match="no concrete actor types"):
            lint_module(mod)
        return
    t0 = time.monotonic()
    findings = lint_module(mod)     # honours the module's LINT_ROOTS
    dt = time.monotonic() - t0
    expected = EXPECTED_EXAMPLE_FINDINGS.get(name, [])
    got = [(f.rule, f.type_name, f.behaviour) for f in findings]
    assert got == expected, format_findings(findings)
    assert dt < 2.0, f"lint of examples/{name}.py took {dt:.2f}s"


def test_spawn_tree_declares_its_root():
    import spawn_tree
    assert spawn_tree.LINT_ROOTS == (spawn_tree.Node.grow,)


# ---- CLI -----------------------------------------------------------------

def _run_cli(args, cwd):
    import subprocess
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    return subprocess.run([sys.executable, "-m", "ponyc_tpu"] + args,
                          cwd=str(cwd), env=env, capture_output=True,
                          text=True, timeout=240)


def test_cli_lint_json_findings_and_exit_codes(tmp_path):
    (tmp_path / "away_mod.py").write_text(
        "from ponyc_tpu import I32, Ref, actor, behaviour\n"
        "@actor\n"
        "class Away:\n"
        "    x: I32\n"
        "    @behaviour\n"
        "    def put(self, st, v: I32):\n"
        "        return {**st, 'x': v}\n"
        "@actor\n"
        "class Alone:\n"
        "    out: Ref\n"
        "    MAX_SENDS = 1\n"
        "    @behaviour\n"
        "    def go(self, st, v: I32):\n"
        "        self.send(st['out'], Away.put, v)\n"
        "        return st\n")
    # Linting a module that only re-exports Alone: Away is outside the
    # analysed world, so Alone.go's send is a guaranteed dead letter.
    (tmp_path / "lmod.py").write_text("from away_mod import Alone\n")
    r = _run_cli(["lint", "lmod", "--json"], tmp_path)
    assert r.returncode == 1, r.stderr[-500:]
    objs = [json.loads(line) for line in r.stdout.splitlines()]
    assert any(o["rule"] == "R2" and o["severity"] == "error"
               and o["type"] == "Alone" for o in objs)
    # Human mode prints the summary line and the same exit code.
    r2 = _run_cli(["lint", "lmod"], tmp_path)
    assert r2.returncode == 1 and "lint:" in r2.stdout
    assert "R2" in r2.stdout


def test_cli_verify_distinct_exit_codes_and_json(tmp_path):
    (tmp_path / "empty_mod.py").write_text("X = 1\n")
    (tmp_path / "over_mod.py").write_text(
        "from ponyc_tpu import I32, Ref, actor, behaviour\n"
        "@actor\n"
        "class S:\n"
        "    x: I32\n"
        "    @behaviour\n"
        "    def put(self, st, v: I32):\n"
        "        return {**st, 'x': v}\n"
        "@actor\n"
        "class Over:\n"
        "    out: Ref['S']\n"
        "    MAX_SENDS = 1\n"
        "    @behaviour\n"
        "    def go(self, st, v: I32):\n"
        "        self.send(st['out'], S.put, v)\n"
        "        self.send(st['out'], S.put, v + 1)\n"
        "        return st\n")
    r = _run_cli(["verify", "empty_mod"], tmp_path)
    assert r.returncode == 3, (r.returncode, r.stderr[-300:])
    assert "no concrete actor types" in r.stderr
    r = _run_cli(["verify", "over_mod", "--json"], tmp_path)
    assert r.returncode == 1, r.stderr[-500:]
    objs = [json.loads(line) for line in r.stdout.splitlines()]
    assert len(objs) == 1 and objs[0]["rule"] == "VERIFY"
    assert objs[0]["type"] == "Over" and objs[0]["severity"] == "error"
