"""Persistent fused-window megakernel + mailbox bandwidth diet (PR 11).

Three layers under test, matching the tentpole:
1. the record codec — int16 lanes with an int32 escape plane
   (ops/megakernel.pack_words/unpack_words) must be LOSSLESS for every
   int32, including the sentinel collision at -32768 and both int16
   boundary edges, in the jnp form and its np twin;
2. the kernel itself — the whole gated window replayed inside one
   pallas_call (interpret mode on CPU) must be bit-for-bit equal to the
   XLA while-loop window over every state leaf, including worlds whose
   payloads live entirely in the escape plane;
3. the modelled bandwidth diet — ≥1.8x fewer bytes per ring record
   while the escape rate stays under ~5%, the acceptance number every
   BENCH json records in its `kernel` block.

The full differential/FIFO corpora also run the kernel via their
pallas-mega configs (test_differential.py / test_fifo.py); this file
owns the codec edges, the forced-window spelling, and the fallbacks.
"""

import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import jax
import jax.numpy as jnp

from ponyc_tpu import RuntimeOptions, serialise
from ponyc_tpu.models import ubench
from ponyc_tpu.ops import megakernel
from ponyc_tpu.runtime import engine

BOUNDARY = np.array(
    [0, 1, -1, 32767, -32767, -32768, 32768, -32769, 65535, -65536,
     2**31 - 1, -(2**31), 12345, -12345],
    np.int32)


def _opts(**kw):
    base = dict(mailbox_cap=4, batch=2, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8)
    base.update(kw)
    return RuntimeOptions(**base)


# ============================================================ the codec

def test_pack_roundtrip_boundary_values_np():
    lo16, esc32 = megakernel.pack_words_np(BOUNDARY)
    assert lo16.dtype == np.int16 and esc32.dtype == np.int32
    out = megakernel.unpack_words_np(lo16, esc32)
    np.testing.assert_array_equal(out, BOUNDARY)
    # -32768 collides with the sentinel: it MUST ride the escape plane
    # even though it fits int16 (the one value the naive range check
    # gets wrong).
    i = int(np.where(BOUNDARY == -32768)[0][0])
    assert esc32[i] == -32768
    # In-range values leave the escape plane zero (that plane is what
    # the diet models as nearly-all-zeros traffic).
    j = int(np.where(BOUNDARY == 12345)[0][0])
    assert lo16[j] == 12345 and esc32[j] == 0


def test_pack_roundtrip_jnp_matches_np_twin():
    rng = np.random.default_rng(7)
    w = np.concatenate([
        BOUNDARY,
        rng.integers(-(2**31), 2**31 - 1, 512).astype(np.int32),
        rng.integers(-1000, 1000, 512).astype(np.int32)])
    lo_j, esc_j = jax.jit(megakernel.pack_words)(jnp.asarray(w))
    lo_n, esc_n = megakernel.pack_words_np(w)
    np.testing.assert_array_equal(np.asarray(lo_j), lo_n)
    np.testing.assert_array_equal(np.asarray(esc_j), esc_n)
    out = jax.jit(megakernel.unpack_words)(lo_j, esc_j)
    np.testing.assert_array_equal(np.asarray(out), w)


def test_modelled_bytes_ratio():
    opts = _opts()          # record = 1 target + 1 payload word
    clean = megakernel.modelled_bytes_per_msg(opts, 0.0)
    assert clean["record_words"] == 2
    assert clean["unpacked_bytes"] == 8.0
    assert clean["ratio"] == 2.0
    # The ISSUE acceptance number: >= 1.8x while escapes stay rare.
    assert megakernel.modelled_bytes_per_msg(opts, 0.05)["ratio"] >= 1.8
    # And the model is honest about escape-heavy traffic: at 100%
    # escapes the packed form costs MORE (lanes + full plane).
    assert megakernel.modelled_bytes_per_msg(opts, 1.0)["ratio"] < 1.0


def test_escape_rate_measures_state_tables():
    rt, ids = ubench.build(8, _opts(), pings=1)
    ubench.seed_all(rt, ids, hops=100, pings=1)          # fits int16
    assert megakernel.escape_rate_state(rt.state) == 0.0
    rt2, ids2 = ubench.build(8, _opts(), pings=1)
    ubench.seed_all(rt2, ids2, hops=1 << 20, pings=1)    # escapes
    assert megakernel.escape_rate_state(rt2.state) > 0.0


# ================================ the kernel vs the XLA window, bitwise

def _window_states(delivery, hops, windows=3, ticks=4, **okw):
    """Advance a seeded 16-pinger world `windows` windows of `ticks`
    gated ticks through rt._multi and return its named state arrays
    plus the total ticks the windows reported."""
    rt, ids = ubench.build(16, _opts(delivery=delivery, **okw), pings=2)
    ubench.seed_all(rt, ids, hops=hops, pings=2)
    st, inj = rt.state, rt._empty_inject
    ran = 0
    for _ in range(windows):
        st, aux, k = rt._multi(st, *inj, jnp.int32(ticks))
        ran += int(k)
    rt.state = st
    return serialise._named_state_arrays(rt.state), ran


def _assert_bitwise_equal(a, b):
    mismatched = [k for k in a
                  if not np.array_equal(np.asarray(a[k]),
                                        np.asarray(b[k]))]
    assert mismatched == []


def test_mega_window_bitwise_equals_xla_window():
    plan, ticks_p = _window_states("plan", hops=1000)
    mega, ticks_m = _window_states("pallas_mega", hops=1000)
    assert ticks_p == ticks_m > 0
    _assert_bitwise_equal(plan, mega)


def test_mega_window_phase_lanes_match_xla():
    """Per-phase window telemetry (ISSUE 19): the tick-cost lanes
    (delivery/drain/dispatch/gc_mark work units) are computed once in
    local_step and ride the jaxpr replay into the megakernel, so the
    two formulations must agree exactly — and actually count."""
    plan, ticks_p = _window_states("plan", hops=1000, analysis=1)
    mega, ticks_m = _window_states("pallas_mega", hops=1000, analysis=1)
    assert ticks_p == ticks_m > 0
    ph_p = np.asarray(plan["st.phase_cost"])
    ph_m = np.asarray(mega["st.phase_cost"])
    assert ph_p.size > 0 and int(ph_p.sum()) > 0
    assert np.array_equal(ph_p, ph_m)


def test_mega_window_escape_plane_payloads():
    """Payloads that can NOT fit the int16 lanes — every in-flight hops
    counter stays ≥ 2^15 for the whole run (one world barely past the
    int16 edge, one far past it) — must cross the kernel boundary
    losslessly via the escape plane."""
    for hops in (32800, 1 << 20):
        plan, _ = _window_states("plan", hops=hops)
        mega, _ = _window_states("pallas_mega", hops=hops)
        _assert_bitwise_equal(plan, mega)
        # The escape plane was genuinely exercised:
        assert megakernel.escape_rate(
            [v for k, v in mega.items() if k.startswith("st.buf")]) > 0.0


def test_forced_window_mega_matches_plan():
    """The calibration spelling (build_forced_window → fori_loop inside
    the kernel) — the tuner times THIS, so it must compute the same
    world as the XLA forced window."""
    states = {}
    for delivery in ("plan", "pallas_mega"):
        rt, ids = ubench.build(16, _opts(delivery=delivery), pings=2)
        ubench.seed_all(rt, ids, hops=1000, pings=2)
        forced = jax.jit(
            engine.build_forced_window(rt.program, rt.opts))
        st, _aux, k = forced(rt.state, *rt._empty_inject, jnp.int32(5))
        assert int(k) == 5
        rt.state = st
        states[delivery] = serialise._named_state_arrays(rt.state)
    _assert_bitwise_equal(states["plan"], states["pallas_mega"])


def test_run_loop_end_to_end_with_mega():
    """The real Runtime.run() (pipelined gated windows, quiescence
    detection) on the megakernel path: a finite ubench world must
    drain to quiescence with the exact same processed counter."""
    totals = {}
    for delivery in ("plan", "pallas_mega"):
        rt, ids = ubench.build(8, _opts(delivery=delivery), pings=1)
        ubench.seed_all(rt, ids, hops=50, pings=1)
        assert rt.run() == 0
        totals[delivery] = rt.counter("n_processed")
    assert totals["plan"] == totals["pallas_mega"] > 0


# ============================================== eligibility + fallbacks

def test_sharded_world_falls_back_to_xla():
    """mesh_shards > 1 is outside the kernel's single-shard contract:
    eligible() is False and the engine silently runs the XLA plan
    formulation — same answers, no crash."""
    okw = dict(mailbox_cap=4, batch=2, max_sends=1, msg_words=1,
               spill_cap=256, inject_slots=16, mesh_shards=4,
               quiesce_interval=2)
    rt, ids = ubench.build(16, _opts(**okw, delivery="pallas_mega"),
                           pings=2)
    assert not megakernel.eligible(rt.program, rt.opts)
    ubench.seed_all(rt, ids, hops=40, pings=2)
    assert rt.run() == 0
    rt2, ids2 = ubench.build(16, _opts(**okw), pings=2)
    ubench.seed_all(rt2, ids2, hops=40, pings=2)
    assert rt2.run() == 0
    assert rt.counter("n_processed") == rt2.counter("n_processed") > 0


def test_explicit_pallas_kernels_exclude_mega():
    """pallas=True / pallas_fused=True force the PR-era per-pass
    kernels; the megakernel declines rather than nesting pallas_call
    inside its staged window."""
    rt, _ = ubench.build(8, _opts(pallas_fused=True), pings=1)
    import dataclasses
    mega_opts = dataclasses.replace(rt.opts, delivery="pallas_mega")
    assert not megakernel.eligible(rt.program, mega_opts)


def test_auto_enumeration_is_env_gated(monkeypatch):
    """On CPU the megakernel joins delivery=auto candidates only under
    PONY_TPU_MEGA_AUTO=1 (bench.py sets it; the unit suite's many
    auto-starts stay lean without it)."""
    rt, _ = ubench.build(8, _opts(), pings=1)
    monkeypatch.delenv("PONY_TPU_MEGA_AUTO", raising=False)
    if jax.default_backend() != "tpu":
        assert not megakernel.auto_enumerable(rt.program, rt.opts)
    monkeypatch.setenv("PONY_TPU_MEGA_AUTO", "1")
    assert megakernel.auto_enumerable(rt.program, rt.opts)


def test_delivery_option_validation():
    assert RuntimeOptions(delivery="pallas_mega").delivery == \
        "pallas_mega"
    with pytest.raises(ValueError):
        RuntimeOptions(delivery="pallas_megaa")
