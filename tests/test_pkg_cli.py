"""Safe-package capability control (stdlib/pkg.py ≙ package.c
safe-packages / allow_ffi) and the unified CLI driver (__main__.py ≙
src/ponyc/main.c)."""

import os
import subprocess
import sys

import pytest

from ponyc_tpu.stdlib import pkg

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def teardown_function(_fn):
    pkg.set_safe_packages(None)
    os.environ.pop("PONY_TPU_SAFE", None)


def test_use_resolves_known_packages():
    js = pkg.use("json")
    assert hasattr(js, "JsonDoc")
    col = pkg.use("collections")
    assert col is pkg.use("collections")


def test_use_unknown_package_errors():
    with pytest.raises(ImportError, match="unknown package"):
        pkg.use("nonexistent")


def test_safe_list_blocks_unlisted_ffi_packages():
    pkg.set_safe_packages(["files"])
    pkg.use("files")                       # listed: ok
    pkg.use("json")                        # pure: always ok
    with pytest.raises(PermissionError, match="safe list"):
        pkg.use("net")
    with pytest.raises(PermissionError, match="safe list"):
        pkg.use("process")


def test_empty_safe_list_is_maximal_restriction():
    pkg.set_safe_packages([])
    with pytest.raises(PermissionError):
        pkg.use("term")
    pkg.use("itertools")                   # pure packages unaffected


def test_unrestricted_by_default():
    assert pkg.safe_packages() is None
    pkg.use("net")
    pkg.use("files")


def test_env_var_activates_restriction():
    os.environ["PONY_TPU_SAFE"] = "net"
    try:
        pkg.use("net")
        with pytest.raises(PermissionError):
            pkg.use("files")
    finally:
        os.environ.pop("PONY_TPU_SAFE")


def _cli(*args, timeout=120):
    env = {k: v for k, v in os.environ.items() if k != "PYTHONPATH"}
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m", "ponyc_tpu", *args], cwd=ROOT, env=env,
        capture_output=True, text=True, timeout=timeout)


def test_cli_version():
    r = _cli("version")
    assert r.returncode == 0 and "ponyc_tpu" in r.stdout


def test_cli_unknown_command():
    r = _cli("frobnicate")
    assert r.returncode == 2 and "unknown command" in r.stderr


def test_cli_run_strips_runtime_flags():
    r = _cli("run", "examples/helloworld.py", "--ponybatch=4")
    assert r.returncode == 0, r.stderr[-800:]
    assert "Hello, world!" in r.stdout
    assert "--ponybatch" not in r.stdout


def test_cli_run_safe_flag_reaches_program(tmp_path):
    script = tmp_path / "prog.py"
    script.write_text(
        "from ponyc_tpu.stdlib import pkg\n"
        "pkg.use('files')\n"
        "try:\n"
        "    pkg.use('net')\n"
        "    print('NET_ALLOWED')\n"
        "except PermissionError:\n"
        "    print('NET_BLOCKED')\n")
    r = _cli("run", "--safe", "files", str(script))
    assert r.returncode == 0, r.stderr[-800:]
    assert "NET_BLOCKED" in r.stdout


def test_cli_run_safe_equals_form(tmp_path):
    script = tmp_path / "p.py"
    script.write_text(
        "from ponyc_tpu.stdlib import pkg\n"
        "try:\n"
        "    pkg.use('net'); print('NET_ALLOWED')\n"
        "except PermissionError:\n"
        "    print('NET_BLOCKED')\n")
    r = _cli("run", f"--safe=files", str(script))
    assert r.returncode == 0, r.stderr[-500:]
    assert "NET_BLOCKED" in r.stdout


def test_cli_run_safe_missing_value_is_usage_error():
    r = _cli("run", "x.py", "--safe")
    assert r.returncode == 2 and "--safe needs a value" in r.stderr


def test_cli_run_flags_only_is_usage_error():
    r = _cli("run", "--ponybatch", "4")
    assert r.returncode == 2 and "missing script path" in r.stderr


def test_cli_doc_generates_markdown(tmp_path):
    r = _cli("doc", "ponyc_tpu.models.ring", "-o", str(tmp_path))
    assert r.returncode == 0, r.stderr[-500:]
    out = r.stdout.strip()
    assert os.path.exists(out)
    with open(out) as f:
        assert "RingNode" in f.read()
