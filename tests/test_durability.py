"""Durable worlds (ISSUE 8): crash-safe checkpointing, geometry-changing
restore, supervised auto-recovery under fault injection.

Four layers under test, matching the tentpole:
1. the checkpoint ring — cadence-driven crash-consistent snapshots with
   per-array + header checksums, fsync + atomic rename, bounded
   retention, and corruption that is DETECTED (coded errors), never
   silently restored;
2. geometry-changing restore — the differential/FIFO corpus crossing a
   snapshot boundary into grown/shrunk capacity, changed mailbox/spill
   rings and a different mesh shard count, with per-edge FIFO, counters
   and quiescence equal to the synchronous oracle;
3. the supervisor (supervise.py) — coded fatals and SIGKILL answered by
   restore-newest-intact + resume, bounded retries, and the
   deterministic-poison refusal;
4. zero-cost-when-off: checkpoint options never touch the step jaxpr.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from ponyc_tpu import Runtime, RuntimeOptions, serialise, supervise, testing
from ponyc_tpu.errors import ERROR_CODES, PonyError
from ponyc_tpu.models import ring

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _opts(**kw):
    base = dict(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8)
    base.update(kw)
    return RuntimeOptions(**base)


# ======================================================= checkpoint ring

def test_periodic_checkpoint_ring_bounded_and_restorable(tmp_path):
    """The run loop writes cadence checkpoints without changing the
    run's observable outcome; the ring stays bounded by
    checkpoint_keep; the newest restores into a fresh runtime with the
    exact final world."""
    hops = 4_000
    rt_off, ids_off = ring.build(16, _opts())   # checkpointing off
    rt_off.send(int(ids_off[0]), ring.RingNode.token, hops)
    assert rt_off.run() == 0
    want = np.asarray(rt_off.cohort_state(ring.RingNode)["passes"])

    prefix = str(tmp_path / "ring")
    opts = _opts(checkpoint_every_s=0.01, checkpoint_path=prefix,
                 checkpoint_keep=3)
    rt, ids = ring.build(16, opts)
    rt.send(int(ids[0]), ring.RingNode.token, hops)
    assert rt.run() == 0
    stats = rt.checkpoint_stats()
    assert stats["checkpoints"] >= 2          # cadence fired mid-run
    assert stats["failures"] == 0
    # capture only READS the world: outcome equals the unarmed run
    np.testing.assert_array_equal(
        np.asarray(rt.cohort_state(ring.RingNode)["passes"]), want)
    rt.stop()                                  # + final fast-start ckpt
    files = serialise.list_checkpoints(prefix)
    assert files and len(files) <= 3           # ring rotated
    seqs = [s for s, _ in files]
    assert seqs == sorted(seqs)
    newest = serialise.newest_intact(prefix)
    assert newest == files[-1][1]

    rt2, _ = ring.build(16, opts)
    serialise.restore(rt2, newest)
    np.testing.assert_array_equal(
        np.asarray(rt2.cohort_state(ring.RingNode)["passes"]), want)
    assert rt2.steps_run == rt.steps_run
    rt2.stop()


def test_checkpoint_options_keep_jaxpr_identity():
    """ACCEPTANCE (PR-4 style): the whole durability layer is host-side
    — with checkpointing configured the step jaxpr is BIT-IDENTICAL to
    the default build."""
    import jax
    import jax.numpy as jnp

    from ponyc_tpu.program import Program
    from ponyc_tpu.runtime import engine
    from ponyc_tpu.runtime.state import init_state

    def build(**kw):
        opts = _opts(analysis=0, **kw)
        prog = Program(opts)
        prog.declare(ring.RingNode, 8)
        prog.finalize()
        st = init_state(prog, opts)
        step = engine.build_step(prog, opts)
        k = opts.inject_slots
        inj_t = jnp.full((k,), -1, jnp.int32)
        inj_w = jnp.zeros((1 + opts.msg_words, k), jnp.int32)
        return str(jax.make_jaxpr(step)(st, inj_t, inj_w))

    baseline = build()
    assert build(checkpoint_every_s=0.5, checkpoint_path="/tmp/x",
                 checkpoint_keep=7) == baseline


def test_checkpoint_option_validation():
    with pytest.raises(ValueError, match="checkpoint_every_s"):
        RuntimeOptions(checkpoint_every_s=0.0)
    with pytest.raises(ValueError, match="checkpoint_keep"):
        RuntimeOptions(checkpoint_keep=0)


# =============================================== corruption detection

def test_corruption_detected_and_fallen_back_past(tmp_path):
    """Truncation and bit flips surface as the coded
    SnapshotCorruptError (code 8), never a raw numpy/zlib traceback;
    newest_intact() walks the ring past them (one shared source/target
    runtime pair — a rejected restore touches no state)."""
    path = str(tmp_path / "w.npz")
    rt, ids = ring.build(8, _opts())
    rt.send(int(ids[0]), ring.RingNode.token, 50)
    rt.run()
    serialise.save(rt, path)
    serialise.verify_snapshot(path)            # intact baseline

    rt2, _ = ring.build(8, _opts())
    for mode in ("truncate", "bitflip"):
        dmg = str(tmp_path / f"{mode}.npz")
        serialise.save(rt, dmg)
        testing.corrupt_snapshot(dmg, mode)
        with pytest.raises(serialise.SnapshotCorruptError):
            serialise.restore(rt2, dmg)
        assert serialise.SnapshotCorruptError.code \
            == ERROR_CODES["SnapshotCorruptError"] == 8

    # ring fallback: corrupt files are skipped newest-first
    prefix = str(tmp_path / "r")
    for seq in range(3):
        serialise.save(rt, serialise.checkpoint_file(prefix, seq))
    files = serialise.list_checkpoints(prefix)
    assert [s for s, _ in files] == [0, 1, 2]
    testing.corrupt_snapshot(files[-1][1], "truncate")
    assert serialise.newest_intact(prefix) == files[1][1]
    testing.corrupt_snapshot(files[1][1], "bitflip")
    assert serialise.newest_intact(prefix) == files[0][1]
    testing.corrupt_snapshot(files[0][1], "truncate")
    assert serialise.newest_intact(prefix) is None
    # the intact one still restores on the shared target
    serialise.restore(rt2, path)


# ================================================== format version gate

def test_unknown_future_format_is_loud(tmp_path):
    path = str(tmp_path / "future.npz")
    serialise.write_snapshot({"format": 99}, {}, path)
    # restore() and verify_snapshot() share the gate (_load_raw), so
    # the verify-side assertion covers both without building a runtime
    with pytest.raises(serialise.SnapshotFormatError):
        serialise.verify_snapshot(path)
    assert serialise.SnapshotFormatError.code == 9
    # the format error is still a FingerprintMismatch for old callers
    assert issubclass(serialise.SnapshotFormatError,
                      serialise.FingerprintMismatch)


def _save_legacy_v2(rt, path):
    """The exact PR-6-era v2 writer (index-named leaves, geometry-full
    fingerprint, no checksums) — the compatibility corpus."""
    import io
    import jax
    arrays = {}
    flat, _ = jax.tree_util.tree_flatten(rt.state)
    for i, leaf in enumerate(flat):
        arrays[f"state_{i}"] = np.asarray(jax.device_get(leaf))
    inject = list(rt._inject_q)
    arrays["inject_tgt"] = np.asarray([t for t, _ in inject], np.int32)
    arrays["inject_words"] = (np.stack([w for _, w in inject]) if inject
                              else np.zeros((0, 1 + rt.opts.msg_words),
                                            np.int32))
    fast = list(rt._host_fast_q)
    arrays["fastq_tgt"] = np.asarray([e[0] for e in fast], np.int32)
    arrays["fastq_words"] = (np.stack([e[1] for e in fast]) if fast
                             else np.zeros((0, 1 + rt.opts.msg_words),
                                           np.int32))
    header = {
        "format": 2,
        "fingerprint": serialise.fingerprint(rt.program, geometry=True),
        "opts": {}, "n_state_leaves": len(flat),
        "free": rt._free,
        "host_state": {str(k): v for k, v in rt._host_state.items()},
        "totals": dict(rt.totals), "last_counters": rt._last_counters,
        "steps_run": rt.steps_run, "exit_code": rt._exit_code,
        "noisy": rt._noisy, "host_blobs": sorted(rt._host_blobs),
    }
    buf = io.BytesIO()
    np.savez_compressed(buf, header=np.frombuffer(
        json.dumps(header).encode(), np.uint8), **arrays)
    open(path, "wb").write(buf.getvalue())


def test_v2_snapshot_still_restores_same_geometry(tmp_path):
    """The FORMAT_VERSION gate keeps accepting v2 (legacy index path,
    exact geometry only); a geometry change on a v2 snapshot stays a
    loud mismatch (legacy snapshots cannot re-layout)."""
    path = str(tmp_path / "v2.npz")
    rt, ids = ring.build(8, _opts())
    rt.send(int(ids[0]), ring.RingNode.token, 120)
    rt.run(max_steps=37)
    _save_legacy_v2(rt, path)
    rt.run()
    want = np.asarray(rt.cohort_state(ring.RingNode)["passes"])

    rt2, _ = ring.build(8, _opts())
    serialise.restore(rt2, path)
    rt2.run()
    np.testing.assert_array_equal(
        np.asarray(rt2.cohort_state(ring.RingNode)["passes"]), want)

    rt3, _ = ring.build(8, _opts(mailbox_cap=16))
    with pytest.raises(serialise.FingerprintMismatch):
        serialise.restore(rt3, path)


def test_v3_restore_keeps_telemetry(tmp_path):
    """Snapshot format v3 carries the PR 4/7 state (profiler lanes,
    error counters) — a restored world keeps its telemetry."""
    path = str(tmp_path / "t.npz")
    rt, ids = ring.build(8, _opts(analysis=1,
                                  analysis_path=str(tmp_path / "a.csv")))
    rt.send(int(ids[0]), ring.RingNode.token, 300)
    rt.run()
    rt._error_counts[("PonyError", 1)] += 2
    prof = rt.profile()
    serialise.save(rt, path)
    rt.stop()

    rt2, _ = ring.build(8, _opts(analysis=1,
                                 analysis_path=str(tmp_path / "b.csv")))
    serialise.restore(rt2, path)
    prof2 = rt2.profile()
    assert prof2["behaviours"] == prof["behaviours"]
    assert prof2["totals"] == prof["totals"]
    assert rt2._error_counts[("PonyError", 1)] == 2
    rt2.stop()


def test_packed_snapshot_cross_dtype_restore(tmp_path):
    """PR 11 bandwidth diet, snapshot spelling: save(packed=True)
    stores the word tables as int16 lanes + an int32 escape plane; a
    mid-flight world whose payloads do NOT fit int16 must restore
    bit-identically to the plain-int32 snapshot of the same instant,
    and a packed snapshot missing its escape plane must be a coded
    corruption, never a silent zero-fill."""
    from ponyc_tpu.models import ubench
    okw = dict(mailbox_cap=4, batch=2, max_sends=1, spill_cap=64,
               inject_slots=8)
    rt, ids = ubench.build(8, _opts(**okw), pings=2)
    # Payloads past the int16 edge: every in-flight hops counter rides
    # the escape plane across the save/restore boundary.
    ubench.seed_all(rt, ids, hops=70_000, pings=2)
    rt.run(max_steps=6)
    p_packed = str(tmp_path / "packed.npz")
    p_plain = str(tmp_path / "plain.npz")
    serialise.save(rt, p_packed, packed=True)
    serialise.save(rt, p_plain)

    with np.load(p_packed, allow_pickle=False) as z:
        lo = [n for n in z.files if n.endswith(".lo16")]
        assert lo, "packed snapshot stored no narrow planes"
        assert all(z[n].dtype == np.int16 for n in lo)
        esc = [n[:-len(".lo16")] + ".esc32" for n in lo]
        assert all(n in z.files and z[n].dtype == np.int32 for n in esc)
        # the escape plane genuinely carries the wide payloads
        assert any(np.any(np.asarray(z[n]) != 0) for n in esc)

    restored = {}
    for path in (p_packed, p_plain):
        rt2, _ = ubench.build(8, _opts(**okw), pings=2)
        serialise.restore(rt2, path)
        restored[path] = {
            k: np.asarray(v) for k, v in
            serialise._named_state_arrays(rt2.state).items()}
    for k, v in restored[p_plain].items():
        np.testing.assert_array_equal(restored[p_packed][k], v, err_msg=k)

    # A torn packed snapshot (escape plane gone) is DETECTED:
    header, arrays = serialise.capture(rt)
    packed = serialise.pack_snapshot_arrays(arrays)
    victim = next(n for n in packed if n.endswith(".esc32"))
    del packed[victim]
    p_torn = str(tmp_path / "torn.npz")
    serialise.write_snapshot(header, packed, p_torn)
    rt3, _ = ubench.build(8, _opts(**okw), pings=2)
    with pytest.raises(serialise.SnapshotCorruptError):
        serialise.restore(rt3, p_torn)


# ============================================= geometry-changing restore

def test_grown_capacity_restore_spawns_into_new_room(tmp_path):
    """Restore into a BIGGER cohort: old actors keep their slots, the
    grown slots are immediately spawnable."""
    path = str(tmp_path / "w.npz")
    rt, ids = ring.build(8, _opts())
    rt.send(int(ids[0]), ring.RingNode.token, 100)
    rt.run(max_steps=17)
    serialise.save(rt, path)

    rt2 = Runtime(_opts()).declare(ring.RingNode, 16).start()
    serialise.restore(rt2, path)
    fresh = rt2.spawn_many(ring.RingNode, 8)     # the grown room
    assert len(fresh) == 8
    rt2.run()
    passes = np.asarray(rt2.cohort_state(ring.RingNode)["passes"])
    assert passes[:8].sum() == 100 and passes[8:].sum() == 0


def test_shrunk_capacity_live_rejects_dead_tail_accepts(tmp_path):
    """Shrinking below a LIVE occupant is a loud SnapshotGeometryError;
    shrinking away a never-spawned tail restores fine (one shared
    4-slot target runtime serves both verdicts — a rejected restore
    touches no state)."""
    live8 = str(tmp_path / "live8.npz")
    rt, _ids = ring.build(8, _opts())             # 8 live actors
    serialise.save(rt, live8)
    dead_tail = str(tmp_path / "dead_tail.npz")
    rt_b = Runtime(_opts()).declare(ring.RingNode, 16).start()
    ids = rt_b.spawn_many(ring.RingNode, 4)       # slots 4..15 never live
    rt_b.set_fields(ring.RingNode, ids, next_ref=np.roll(ids, -1))
    rt_b.send(int(ids[0]), ring.RingNode.token, 60)
    rt_b.run(max_steps=11)
    serialise.save(rt_b, dead_tail)

    rt2 = Runtime(_opts()).declare(ring.RingNode, 4).start()
    with pytest.raises(serialise.SnapshotGeometryError):
        serialise.restore(rt2, live8)
    assert serialise.SnapshotGeometryError.code == 10
    serialise.restore(rt2, dead_tail)
    rt2.run()
    assert np.asarray(
        rt2.cohort_state(ring.RingNode)["passes"]).sum() == 60


def test_mailbox_too_deep_for_new_ring_rejected(tmp_path):
    path = str(tmp_path / "w.npz")
    rt, ids = ring.build(8, _opts())
    for _ in range(4):                 # occupancy 4 on one mailbox
        rt.bulk_send(ids[:1], ring.RingNode.token, np.asarray([0]))
    serialise.save(rt, path)
    rt2, _ = ring.build(8, _opts(mailbox_cap=2))
    with pytest.raises(serialise.SnapshotGeometryError,
                       match="mailbox"):
        serialise.restore(rt2, path)
    rt3, _ = ring.build(8, _opts(mailbox_cap=4))   # exactly fits
    # restore(opts=...) spells the intended target geometry at the
    # restore site: it must match what the runtime was started with
    with pytest.raises(ValueError, match="different geometry"):
        serialise.restore(rt3, path, opts=_opts())
    serialise.restore(rt3, path, opts=_opts(mailbox_cap=4))


def test_blob_pool_relayout(tmp_path):
    """Host-owned blobs cross a blob_slots change: handles re-encode,
    contents and ownership survive; live blobs into a pool-less target
    reject."""
    path = str(tmp_path / "w.npz")
    opts = _opts(blob_slots=8, blob_words=4)
    rt, _ids = ring.build(8, opts)
    h1 = rt.blob_store([1, 2, 3])
    h2 = rt.blob_store_str("hi")
    serialise.save(rt, path)

    rt2, _ = ring.build(8, _opts(blob_slots=16, blob_words=8))
    serialise.restore(rt2, path)
    assert len(rt2._host_blobs) == 2
    fetched = {tuple(rt2.blob_fetch(h).tolist())
               for h in rt2._host_blobs}
    assert (1, 2, 3) in fetched
    hs = [h for h in rt2._host_blobs
          if tuple(rt2.blob_fetch(h).tolist()) != (1, 2, 3)]
    assert rt2.blob_fetch_str(hs[0]) == "hi"
    assert rt2.blobs_in_use == 2

    rt3, _ = ring.build(8, _opts())                # blob_slots=0
    with pytest.raises(serialise.SnapshotGeometryError, match="blob"):
        serialise.restore(rt3, path)
    del h1, h2


def _mid_pressure_snapshot(tmp_path):
    """Walker/Splitter deadlock seed run into live backpressure mutes,
    snapshotted — the differential source world, shared by the tier-1
    grown-geometry crossing and the slow mesh crossing. Returns
    (path, oracle, n_w, n_s)."""
    import test_differential as td

    n_w, n_s = 24, 8
    w_nxt, s_w, s_s, seeds = td._case(23, n_w, n_s)  # the deadlock seed
    want = td.oracle(n_w, n_s, w_nxt, s_w, s_s, seeds)
    rt = Runtime(RuntimeOptions(msg_words=1, mailbox_cap=2, batch=1,
                                max_sends=2, spill_cap=512,
                                inject_slots=16))
    rt.declare(td.Walker, n_w).declare(td.Splitter, n_s)
    rt.start()
    wids = rt.spawn_many(td.Walker, n_w)
    sids = rt.spawn_many(td.Splitter, n_s)
    rt.set_fields(td.Walker, wids, nxt=wids[np.asarray(w_nxt)])
    rt.set_fields(td.Splitter, sids, w_ref=wids[np.asarray(s_w)],
                  s_ref=sids[np.asarray(s_s)])
    for kind, i, v in seeds:
        rt.send(int(wids[i] if kind == "w" else sids[i]),
                td.Walker.step if kind == "w" else td.Splitter.burst, v)
    # into the thick of it: backpressure mutes live at snapshot time
    inj = rt._drain_inject()
    st, _aux = rt._step(rt.state, *inj)
    for _ in range(7):
        st, _aux = rt._step(st, *rt._empty_inject)
    rt.state = st
    assert np.asarray(st.muted).any(), "snapshot must land mid-pressure"
    path = str(tmp_path / "midp.npz")
    serialise.save(rt, path)
    return path, want, n_w, n_s


def _assert_crossing(path, want, n_w, n_s, okw, cap_w, cap_s):
    import test_differential as td
    rt2 = Runtime(RuntimeOptions(msg_words=1, **okw))
    rt2.declare(td.Walker, cap_w).declare(td.Splitter, cap_s)
    rt2.start()
    serialise.restore(rt2, path)
    assert rt2.run(max_steps=50_000) == 0
    wst = rt2.cohort_state(td.Walker)
    sst = rt2.cohort_state(td.Splitter)
    assert (wst["acc"][:n_w].astype(np.int64) == want[0]).all()
    assert (wst["hits"][:n_w].astype(np.int64) == want[1]).all()
    assert (sst["acc"][:n_s].astype(np.int64) == want[2]).all()
    assert not np.asarray(rt2.state.muted).any()


def test_differential_corpus_crosses_grown_restore(tmp_path):
    """ROADMAP item 5's named gap: the differential corpus crossing a
    snapshot/restore boundary mid-workload into a GROWN geometry,
    asserting counters and quiescence equal the sequential oracle.
    (The SAME-geometry crossing is pinned by test_serialise.
    test_snapshot_under_mute_pressure_resumes_to_oracle.)"""
    path, want, n_w, n_s = _mid_pressure_snapshot(tmp_path)
    _assert_crossing(path, want, n_w, n_s,
                     dict(mailbox_cap=4, batch=1, max_sends=2,
                          spill_cap=256, inject_slots=16),
                     n_w + 16, n_s + 8)


@pytest.mark.slow
def test_differential_corpus_crosses_mesh_restore(tmp_path):
    """The same mid-pressure world restored ONTO A 2-SHARD MESH (and
    the routing/collective machinery under it) — the elastic-resize
    direction of ROADMAP items 1/5."""
    path, want, n_w, n_s = _mid_pressure_snapshot(tmp_path)
    _assert_crossing(path, want, n_w, n_s,
                     dict(mailbox_cap=4, batch=1, max_sends=2,
                          spill_cap=1024, inject_slots=32,
                          mesh_shards=2, quiesce_interval=2),
                     n_w, n_s)


def test_per_edge_fifo_crosses_restore_boundary(tmp_path):
    """Order-SENSITIVE crossing: the on-device per-edge FIFO detector
    (test_fifo harness) runs a tiny-cap world into mid-stream spill
    pressure, snapshots, restores into a grown geometry and finishes —
    zero violations and full completeness prove the parked-spill →
    inject-lane conversion preserves causal order exactly."""
    import test_fifo as tf

    n_cons, items = 4, 40
    n_prod, e1, e2 = tf._wire(101, n_cons)
    src = RuntimeOptions(msg_words=2, mailbox_cap=2, batch=1,
                         max_sends=3, spill_cap=2048, inject_slots=16)
    rt = Runtime(src)
    rt.declare(tf.Prod, n_prod).declare(tf.Cons, n_cons)
    rt.start()
    cids = rt.spawn_many(tf.Cons, n_cons,
                         last0=np.full(n_cons, -1, np.int32),
                         last1=np.full(n_cons, -1, np.int32),
                         last2=np.full(n_cons, -1, np.int32),
                         last3=np.full(n_cons, -1, np.int32))
    pids = rt.spawn_many(tf.Prod, n_prod,
                         c1=cids[np.asarray([c for c, _ in e1])],
                         c2=cids[np.asarray([c for c, _ in e2])],
                         slot1=np.asarray([s for _, s in e1], np.int32),
                         slot2=np.asarray([s for _, s in e2], np.int32))
    rt.bulk_send(pids, tf.Prod.produce, np.full(n_prod, items, np.int32))
    rt.run(max_steps=40)                      # mid-stream
    assert (np.asarray(rt.state.tail) - np.asarray(rt.state.head)).any()
    path = str(tmp_path / "fifo.npz")
    serialise.save(rt, path)

    # a same-geometry restore is a bit-identical array copy (cannot
    # reorder anything); the FIFO-critical path is the RELAYOUT —
    # grown rings + converted spill entries:
    for okw in (dict(msg_words=2, mailbox_cap=8, batch=2, max_sends=3,
                     spill_cap=512, inject_slots=32),):      # grown
        rt2 = Runtime(RuntimeOptions(**okw))
        rt2.declare(tf.Prod, n_prod + 4).declare(tf.Cons, n_cons + 2)
        rt2.start()
        serialise.restore(rt2, path)
        assert rt2.run(max_steps=500_000) == 0
        st = rt2.cohort_state(tf.Cons)
        bad = st["bad"][:n_cons]
        assert not bad.any(), f"FIFO violations after restore: {bad}"
        for s in range(tf.IN_SLOTS):
            assert (np.asarray(st[f"last{s}"][:n_cons])
                    == items - 1).all()
        assert (np.asarray(st["got"][:n_cons])
                == tf.IN_SLOTS * items).all()
        pst = rt2.cohort_state(tf.Prod)
        assert (np.asarray(pst["seq"][:n_prod]) == items).all()


# ======================================================= the supervisor

def test_supervisor_inprocess_recovers_coded_fatal(tmp_path):
    """A chaos-injected coded fatal mid-run: the supervisor restores
    the newest intact checkpoint into a fresh runtime and the workload
    completes with the unfaulted outcome."""
    prefix = str(tmp_path / "sup")
    attempt = {"n": 0}

    def build():
        attempt["n"] += 1
        rt, ids = ring.build(8, _opts(checkpoint_every_s=60.0,
                                      checkpoint_path=prefix))
        build.ids = ids
        if attempt["n"] == 1:
            testing.fatal_at_boundary(rt, boundary=3, code=42)
        return rt

    def seed(rt):
        rt.send(int(build.ids[0]), ring.RingNode.token, 400)
        rt.checkpoint()                 # the recovery floor

    sup = supervise.Supervisor(build, prefix=prefix, seed=seed,
                               retries=3, backoff_s=0.01)
    assert sup.run() == 0
    assert sup.restarts == 1
    assert sup.failures[0]["code"] == 42
    assert sup.restored_from is not None
    # unfaulted-outcome equality: a clean 400-hop walk over 8 nodes
    # lands exactly 50 passes per node (the analytic oracle); read the
    # recovered terminal world back from its final checkpoint.
    rt_chk, _ = ring.build(8, _opts(checkpoint_every_s=60.0,
                                    checkpoint_path=prefix))
    serialise.restore(rt_chk, serialise.newest_intact(prefix))
    np.testing.assert_array_equal(
        np.asarray(rt_chk.cohort_state(ring.RingNode)["passes"]),
        np.full(8, 50, np.int32))
    rt_chk.stop()


def test_supervisor_refuses_deterministic_poison(tmp_path):
    """The poison rule: the same code at the same world position twice
    in a row raises PoisonError instead of restart-looping."""
    prefix = str(tmp_path / "poison")

    def build():
        rt, ids = ring.build(8, _opts(quiesce_interval=4,
                                      pipeline=False))
        build.ids = ids
        testing.fatal_at_boundary(rt, boundary=1, code=13, every=True)
        return rt

    def seed(rt):
        rt.send(int(build.ids[0]), ring.RingNode.token, 400)

    sup = supervise.Supervisor(build, prefix=prefix, seed=seed,
                               retries=10, backoff_s=0.0)
    with pytest.raises(supervise.PoisonError) as ei:
        sup.run()
    assert ei.value.code == ERROR_CODES["PoisonError"] == 11
    assert len(sup.failures) == 2              # refused on the repeat
    assert sup.failures[0]["code"] == 13


def test_supervisor_noncoded_errors_are_not_swallowed(tmp_path):
    def build():
        rt, _ = ring.build(8, _opts())
        raise RuntimeError("builder exploded")

    sup = supervise.Supervisor(build, prefix=str(tmp_path / "x"))
    with pytest.raises(RuntimeError, match="builder exploded"):
        sup.run()
    with pytest.raises(ValueError):
        supervise.Supervisor(prefix="x")       # neither build nor argv


# ------------------------- subprocess acceptance (kill -> restore -> =)

ACCEPT_SCRIPT = """
import json, os, sys
sys.path.insert(0, {root!r})
from ponyc_tpu.platforms import force_cpu
force_cpu()
import numpy as np
from ponyc_tpu import I32, Ref, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu import supervise, testing
from ponyc_tpu.errors import error_code

@actor
class Reporter:
    HOST = True
    n: I32

    @behaviour
    def report(self, st, v: I32):
        return {{**st, "n": st["n"] + v}}

@actor
class Node:
    nxt: Ref["Node"]
    rep: Ref["Reporter"]
    passes: I32

    MAX_SENDS = 2

    @behaviour
    def token(self, st, hops: I32):
        self.send(st["nxt"], Node.token, hops - 1, when=hops > 1)
        self.send(st["rep"], Reporter.report, 1, when=(hops % 128) == 0)
        self.exit(0, when=hops <= 1)
        return {{**st, "passes": st["passes"] + 1}}

MODE = {mode!r}
rt = Runtime(RuntimeOptions(
    mailbox_cap=8, batch=1, max_sends=2, msg_words=1, spill_cap=64,
    inject_slots=8, quiesce_interval=64,
    checkpoint_every_s=0.01, checkpoint_path={prefix!r},
    checkpoint_keep=4,
    watchdog_s=(0.6 if MODE == "wedge" else None),
    analysis_path={apath!r}))
rt.declare(Node, 16).declare(Reporter, 2)
rt.start()
restored = supervise.maybe_restore(rt)
if restored is None:
    ids = rt.spawn_many(Node, 16)
    rep = rt.spawn(Reporter)
    rt.set_fields(Node, ids, nxt=np.roll(ids, -1), rep=rep)
    rt.send(int(ids[0]), Node.token, {hops})
    rt.checkpoint()                    # deterministic recovery floor
    if MODE == "wedge":
        testing.wedge_behaviour(Reporter.report, at_dispatch=3,
                                sleep_s=600.0)
else:
    # faults are one-shot: the recovered child runs clean
    os.environ.pop("PONY_TPU_CHAOS", None)
    testing.chaos.reset()
try:
    code = rt.run()
except Exception as e:
    c = error_code(e)
    if c:
        sys.exit(c)                    # the coded-failure exit contract
    raise
passes = [int(x) for x in rt.cohort_state(Node)["passes"]]
reporter = int(sum(st.get("n", 0) for st in rt._host_state.values()))
rt.stop()
json.dump({{"exit": code, "passes": passes, "reporter": reporter,
           "restored": restored is not None}}, open({out!r}, "w"))
sys.exit(code)
"""


ACCEPT_HOPS = 3000


def _accept_script(tmp_path, mode):
    prefix = str(tmp_path / f"{mode}-ring")
    out = str(tmp_path / f"{mode}-out.json")
    code = ACCEPT_SCRIPT.format(
        root=ROOT, mode=mode, prefix=prefix, out=out, hops=ACCEPT_HOPS,
        apath=str(tmp_path / f"{mode}-an.csv"))
    path = str(tmp_path / f"{mode}.py")
    open(path, "w").write(code)
    return path, prefix, out


# the acceptance workload's actor types, mirrored in-process for the
# unfaulted oracle run (same structure as ACCEPT_SCRIPT's)
from ponyc_tpu import I32, Ref, actor, behaviour  # noqa: E402


@actor
class _Reporter:
    HOST = True
    n: I32

    @behaviour
    def report(self, st, v: I32):
        return {**st, "n": st["n"] + v}


@actor
class _Node:
    nxt: Ref["_Node"]
    rep: Ref["_Reporter"]
    passes: I32

    MAX_SENDS = 2

    @behaviour
    def token(self, st, hops: I32):
        self.send(st["nxt"], _Node.token, hops - 1, when=hops > 1)
        self.send(st["rep"], _Reporter.report, 1, when=(hops % 128) == 0)
        self.exit(0, when=hops <= 1)
        return {**st, "passes": st["passes"] + 1}


@pytest.fixture(scope="module")
def clean_baseline():
    """The unfaulted oracle run, in-process (deterministic outcome:
    the subprocess scripts run the structurally identical program)."""
    rt = Runtime(RuntimeOptions(
        mailbox_cap=8, batch=1, max_sends=2, msg_words=1, spill_cap=64,
        inject_slots=8, quiesce_interval=64))
    rt.declare(_Node, 16).declare(_Reporter, 2)
    rt.start()
    ids = rt.spawn_many(_Node, 16)
    rep = rt.spawn(_Reporter)
    rt.set_fields(_Node, ids, nxt=np.roll(ids, -1), rep=rep)
    rt.send(int(ids[0]), _Node.token, ACCEPT_HOPS)
    code = rt.run()
    return {
        "exit": code,
        "passes": [int(x) for x in rt.cohort_state(_Node)["passes"]],
        "reporter": int(sum(st.get("n", 0)
                            for st in rt._host_state.values())),
    }


def test_acceptance_wedged_run_supervised_to_completion(
        tmp_path, clean_baseline):
    """ACCEPTANCE: a wedged behaviour (watchdog code-7 stall) is
    restarted by the supervisor from the last intact checkpoint and
    completes the workload with results equal to the unfaulted run,
    within a seconds-scale deadline."""
    script, prefix, out = _accept_script(tmp_path, "wedge")
    sup = supervise.Supervisor(
        argv=[sys.executable, script], prefix=prefix, retries=3,
        backoff_s=0.05)
    t0 = time.monotonic()
    code = sup.run()
    elapsed = time.monotonic() - t0
    assert code == 0, sup.failures
    assert sup.restarts >= 1
    assert sup.failures[0]["code"] == ERROR_CODES["PonyStallError"] == 7
    assert sup.restored_from is not None
    assert elapsed < 120            # seconds-scale, not the 600s sleep
    got = json.load(open(out))
    assert got["restored"] is True
    assert got["exit"] == clean_baseline["exit"] == 0
    assert got["passes"] == clean_baseline["passes"]
    assert got["reporter"] == clean_baseline["reporter"]


def test_acceptance_sigkill_mid_flush_supervised_to_completion(
        tmp_path, clean_baseline):
    """ACCEPTANCE: the process is SIGKILLed MID-FLUSH inside a
    checkpoint write (the serialise.py chaos point). The torn write
    never surfaces (tmp + fsync + rename), the supervisor restores the
    newest intact ring snapshot, and the workload completes with the
    unfaulted outcomes."""
    script, prefix, out = _accept_script(tmp_path, "kill")
    env_before = os.environ.get("PONY_TPU_CHAOS")
    os.environ["PONY_TPU_CHAOS"] = "snapshot-mid-flush@3"
    try:
        sup = supervise.Supervisor(
            argv=[sys.executable, script], prefix=prefix, retries=5,
            backoff_s=0.05)
        code = sup.run()
    finally:
        if env_before is None:
            os.environ.pop("PONY_TPU_CHAOS", None)
        else:
            os.environ["PONY_TPU_CHAOS"] = env_before
    assert code == 0, sup.failures
    assert sup.restarts >= 1
    assert sup.failures[0]["code"] == -9       # SIGKILL
    # every surviving ring file is intact (the torn one never renamed)
    for _seq, f in serialise.list_checkpoints(prefix):
        serialise.verify_snapshot(f)
    got = json.load(open(out))
    assert got["restored"] is True
    assert got["passes"] == clean_baseline["passes"]
    assert got["reporter"] == clean_baseline["reporter"]


# =========================================== observability integration

def test_postmortem_doctor_and_healthz_show_restore_point(tmp_path):
    from ponyc_tpu import flight, metrics
    prefix = str(tmp_path / "pm")
    rt, ids = ring.build(8, _opts(
        checkpoint_every_s=30.0, checkpoint_path=prefix,
        analysis_path=str(tmp_path / "an.csv")))
    hz = metrics.health(rt)
    assert hz["last_checkpoint_age_s"] is None   # nothing written yet
    rt.send(int(ids[0]), ring.RingNode.token, 50)
    rt.run()
    rt.checkpoint()
    rt._ckpt.flush()
    # /healthz: how stale a crash-restore would be
    hz = metrics.health(rt)
    assert hz["last_checkpoint_age_s"] is not None
    assert hz["last_checkpoint_age_s"] < 60
    assert hz["last_checkpoint_path"].startswith(prefix)
    # postmortem block + doctor verdict lead to the restore point
    pm = rt._flight.postmortem("manual")
    assert pm["checkpoint"]["path"]
    assert pm["checkpoint"]["verified"] is True
    assert "restorable from:" in flight.render_postmortem(pm)
    pm["errors"] = [{"class": "SpillOverflowError", "code": 2,
                     "count": 1}]
    line, _detail = flight.diagnose_postmortem(pm)
    assert line.startswith("CRASHED")
    assert "restorable from " + pm["checkpoint"]["path"] in line
    rt.stop()
    # checkpointing off -> the healthz field is None, not absent
    rt2, _ = ring.build(8, _opts())
    hz2 = metrics.health(rt2)
    assert "last_checkpoint_age_s" in hz2
    assert hz2["last_checkpoint_age_s"] is None


# ========================================================== CLI surface

def test_cli_snapshot_and_restore_verdicts(tmp_path, capsys):
    from ponyc_tpu.__main__ import main as cli_main
    path = str(tmp_path / "w.npz")
    rt, ids = ring.build(8, _opts())
    rt.send(int(ids[0]), ring.RingNode.token, 40)
    rt.run()
    serialise.save(rt, path)

    assert cli_main(["snapshot", path]) == 0
    out = capsys.readouterr().out
    assert "INTACT" in out and "RingNode[8]" in out
    assert cli_main(["snapshot", path, "--json"]) == 0
    info = json.loads(capsys.readouterr().out)
    assert info["intact"] and info["format"] == 3
    assert info["steps_run"] == rt.steps_run

    assert cli_main(["restore", path]) == 0
    assert "RESTORABLE" in capsys.readouterr().out

    testing.corrupt_snapshot(path, "bitflip")
    assert cli_main(["snapshot", path]) == 1
    assert cli_main(["restore", path]) == 1
    capsys.readouterr()

    # a RING PREFIX target resolves to the newest intact file
    prefix = str(tmp_path / "r")
    for seq in range(2):
        serialise.save(rt, serialise.checkpoint_file(prefix, seq))
    testing.corrupt_snapshot(serialise.checkpoint_file(prefix, 1),
                             "truncate")
    assert cli_main(["snapshot", prefix]) == 0   # falls back to seq 0
    assert "00000000.ckpt" in capsys.readouterr().out


def test_cli_usage_error_exit_codes(tmp_path, capsys):
    from ponyc_tpu.__main__ import main as cli_main
    assert cli_main(["snapshot"]) == 2                    # no target
    assert cli_main(["snapshot", "a", "b"]) == 2          # two targets
    assert cli_main(["restore"]) == 2
    assert cli_main(["snapshot", str(tmp_path / "nope")]) == 2
    assert cli_main(["supervise"]) == 2                   # no prefix
    assert cli_main(["supervise", "--prefix"]) == 2       # no value
    assert cli_main(["supervise", "--prefix", "p"]) == 2  # no script
    assert cli_main(["supervise", "--retries", "x", "--prefix", "p",
                     "s.py"]) == 2                        # bad int
    assert cli_main(["supervise", "--prefix", "p",
                     str(tmp_path / "nope.py")]) == 2     # no script
    capsys.readouterr()


# =============================================== chaos harness selftest

def test_chaos_hooks_arm_and_disarm():
    c = testing.ChaosHooks()
    fired = []
    c.arm("p", action=lambda: fired.append(1), after=2)
    c.fire("p")
    assert not fired
    c.fire("p")
    assert fired == [1]
    c.fire("p")                       # one-shot: disarmed after firing
    assert fired == [1]
    with pytest.raises(ValueError):
        c.arm("p", after=0)
    with pytest.raises(ValueError):
        c.arm("p", action="explode")
    c.arm("q", action=lambda: fired.append(2))
    c.reset()
    c.fire("q")
    assert fired == [1]


def test_chaos_fatal_poller_fires_once():
    rt, ids = ring.build(8, _opts())
    hook = testing.fatal_at_boundary(rt, boundary=2, code=77)
    rt.send(int(ids[0]), ring.RingNode.token, 500)
    with pytest.raises(PonyError) as ei:
        rt.run()
    assert ei.value.code == 77
    assert hook.fired == 1
