"""Per-behaviour profiler tests (≙ the fork's per-actor --ponyanalysis
records, analysis.h:16-31): the on-device telemetry matrix
(engine.profile_lanes), queue-wait latency histograms, GC window stats,
Runtime.profile(), the window CSV's dynamic columns, per-behaviour
chrome-trace tracks, the `top` view, and the zero-cost-at-level-0
guarantee."""

import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from ponyc_tpu import (I32, Ref, Runtime, RuntimeOptions, actor,
                       analysis, behaviour)
from ponyc_tpu.models import ring

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _opts(**kw):
    base = dict(mailbox_cap=8, batch=1, max_sends=1, msg_words=1,
                spill_cap=64, inject_slots=8)
    base.update(kw)
    return RuntimeOptions(**base)


# ---------------------------------------------------------------- matrix

@actor
class Worker:
    done: I32

    @behaviour
    def work(self, st, v: I32):
        return {**st, "done": st["done"] + v}

    @behaviour
    def reset(self, st, v: I32):
        return {**st, "done": v}


@actor
class Driver:
    out: Ref[Worker]
    left: I32
    MAX_SENDS = 2

    @behaviour
    def tick(self, st, _: I32):
        self.send(st["out"], Worker.work, 1, when=st["left"] > 0)
        self.send(self.actor_id, Driver.tick, 0, when=st["left"] > 1)
        return {**st, "left": st["left"] - 1}


def test_profile_sums_to_mesh_totals():
    """Acceptance: per-(cohort, behaviour) runs/deliveries and the
    queue-wait histograms sum to the mesh-wide n_processed/n_delivered
    on a multi-behaviour, multi-cohort example."""
    rt = Runtime(_opts(max_sends=2, msg_words=1, analysis=1,
                       spill_cap=256, inject_slots=32))
    rt.declare(Driver, 4).declare(Worker, 2).start()
    ws = rt.spawn_many(Worker, 2)
    ds = rt.spawn_many(Driver, 4, out=int(ws[0]), left=10)
    rt.set_fields(Driver, ds[2:], out=int(ws[1]))
    for w in ws:
        rt.send(int(w), Worker.reset, 0)
    for d in ds:
        rt.send(int(d), Driver.tick, 0)
    assert rt.run(max_steps=5000) == 0
    prof = rt.profile()
    beh = prof["behaviours"]
    assert set(beh) == {"Worker.work", "Worker.reset", "Driver.tick"}
    assert beh["Driver.tick"]["runs"] == 4 * 10
    assert beh["Worker.work"]["runs"] == 4 * 10
    assert beh["Worker.reset"]["runs"] == 2
    assert sum(b["runs"] for b in beh.values()) \
        == prof["totals"]["processed"] == rt.counter("n_processed")
    assert sum(b["delivered"] for b in beh.values()) \
        == prof["totals"]["delivered"] == rt.counter("n_delivered")
    hist_total = sum(sum(c["queue_wait_hist"])
                     for c in prof["cohorts"].values())
    assert hist_total == prof["totals"]["processed"]
    assert set(prof["cohorts"]) == {"Driver", "Worker"}


def test_queue_wait_single_token_ring():
    """A single-token ring dispatches every message exactly one tick
    after delivery: the whole histogram lands in bucket 0 (wait 1)."""
    rt, ids = ring.build(8, _opts(analysis=1))
    rt.send(int(ids[0]), ring.RingNode.token, 50)
    rt.run()
    c = rt.profile()["cohorts"]["RingNode"]
    assert c["queue_wait_hist"][0] == 50
    assert sum(c["queue_wait_hist"][1:]) == 0
    assert c["queue_wait_p50"] == 1 and c["queue_wait_p99"] == 1


def test_backpressure_attribution():
    """A flooded slow consumer shows up in the matrix: rejects blame
    the flooded behaviour, mute-ticks blame the muted senders' cohort,
    and the consumer's queue-wait spreads past bucket 0."""

    @actor
    class SlowP:
        n: I32
        BATCH = 1

        @behaviour
        def eat(self, st, v: I32):
            return {**st, "n": st["n"] + 1}

    @actor
    class FastP:
        out: Ref[SlowP]
        left: I32
        MAX_SENDS = 2

        @behaviour
        def go(self, st, _: I32):
            self.send(st["out"], SlowP.eat, 1, when=st["left"] > 0)
            self.send(self.actor_id, FastP.go, 0, when=st["left"] > 1)
            return {**st, "left": st["left"] - 1}

    rt = Runtime(RuntimeOptions(mailbox_cap=2, batch=1, msg_words=1,
                                max_sends=2, spill_cap=512,
                                inject_slots=16, analysis=1))
    rt.declare(FastP, 12).declare(SlowP, 1).start()
    s = rt.spawn(SlowP)
    fs = rt.spawn_many(FastP, 12, out=s, left=30)
    rt.bulk_send(fs, FastP.go, np.zeros(12, np.int64))
    assert rt.run(max_steps=30_000) == 0
    prof = rt.profile()
    assert prof["behaviours"]["SlowP.eat"]["rejected"] > 0
    assert prof["behaviours"]["FastP.go"]["rejected"] == 0
    assert prof["cohorts"]["FastP"]["mute_ticks"] > 0
    slow = prof["cohorts"]["SlowP"]
    assert sum(slow["queue_wait_hist"][1:]) > 0, \
        "a flooded mailbox must show waits > 1 tick"
    assert slow["queue_wait_p99"] >= slow["queue_wait_p50"]
    # rejected attribution matches the per-tick mesh counter semantics
    assert sum(b["rejected"] for b in prof["behaviours"].values()) \
        == rt.counter("n_rejected")


def test_host_behaviour_runs_counted():
    """Host-cohort behaviours dispatch host-side; profile() merges the
    host dispatch counts into the same matrix."""

    @actor
    class DevSrc:
        out: Ref
        MAX_SENDS = 1

        @behaviour
        def emit(self, st, v: I32):
            self.send(st["out"], HostSink.take, v)
            return st

    @actor
    class HostSink:
        HOST = True
        seen: I32

        @behaviour
        def take(self, st, v: I32):
            return {**st, "seen": st["seen"] + v}

    rt = Runtime(_opts(msg_words=2, analysis=1))
    rt.declare(DevSrc, 2).declare(HostSink, 1).start()
    sink = rt.spawn(HostSink)
    srcs = rt.spawn_many(DevSrc, 2, out=sink)
    for s in srcs:
        rt.send(int(s), DevSrc.emit, 3)
    rt.run()
    prof = rt.profile()
    assert prof["behaviours"]["HostSink.take"]["runs"] == 2
    assert prof["behaviours"]["DevSrc.emit"]["runs"] == 2
    assert rt.state_of(sink)["seen"] == 6


# -------------------------------------------------- zero-cost at level 0

def test_level0_state_carries_no_lanes():
    rt, _ = ring.build(8, _opts(analysis=0))
    assert rt.state.beh_runs.size == 0
    assert rt.state.beh_delivered.size == 0
    assert rt.state.beh_rejected.size == 0
    assert rt.state.coh_mute_ticks.size == 0
    assert rt.state.qwait_hist.size == 0
    assert rt.state.qwait_enq == {}
    with pytest.raises(RuntimeError, match="analysis >= 1"):
        rt.profile()


def test_level0_lanes_compile_to_baseline(monkeypatch):
    """Acceptance: at analysis=0 the step's jaxpr is IDENTICAL to a
    baseline built with the profiler lanes physically unreachable
    (profile_lanes trapped), proving level 0 traces zero telemetry ops;
    at analysis>=1 the same trap fires, proving the helper is the only
    source of the lanes."""
    import jax
    import jax.numpy as jnp

    from ponyc_tpu.program import Program
    from ponyc_tpu.runtime import engine
    from ponyc_tpu.runtime.state import init_state

    def build(analysis):
        opts = _opts(analysis=analysis, spill_cap=16, inject_slots=4)
        prog = Program(opts)
        prog.declare(ring.RingNode, 8)
        prog.finalize()
        st = init_state(prog, opts)
        step = engine.build_step(prog, opts)
        k = opts.inject_slots
        inj_t = jnp.full((k,), -1, jnp.int32)
        inj_w = jnp.zeros((1 + opts.msg_words, k), jnp.int32)
        return str(jax.make_jaxpr(step)(st, inj_t, inj_w))

    baseline = build(0)

    def boom(*_a, **_k):
        raise AssertionError("profiler lanes traced at analysis=0")

    monkeypatch.setattr(engine, "profile_lanes", boom)
    assert build(0) == baseline     # trap unreached, jaxpr bit-identical
    with pytest.raises(AssertionError, match="lanes traced"):
        build(1)                    # and it IS the only lane source
    monkeypatch.undo()

    # Same guarantee for the per-phase tick-cost lanes (ISSUE 19): the
    # observatory must be jaxpr-bit-identical when off, and
    # phase_cost_lanes must be the lanes' only source when on.
    def boom2(*_a, **_k):
        raise AssertionError("phase lanes traced at analysis=0")

    monkeypatch.setattr(engine, "phase_cost_lanes", boom2)
    assert build(0) == baseline
    with pytest.raises(AssertionError, match="phase lanes traced"):
        build(1)


def test_phase_lanes_count_ring_work():
    """Per-phase window telemetry (ISSUE 19): a 50-hop single-token
    ring delivers/drains/dispatches exactly one work unit per hop and
    marks nothing (no spawns or exits until the last hop's self.exit),
    and the phases ride Runtime.profile()."""
    rt, ids = ring.build(8, _opts(analysis=1))
    rt.send(int(ids[0]), ring.RingNode.token, 50)
    rt.run()
    ph = rt.profile()["phases"]
    assert ph["delivery"] == ph["drain"] == ph["dispatch"] == 50
    # exit(0) requests world exit — no device spawn/destroy happened
    assert ph["gc_mark"] == 0
    rt.stop()


# ------------------------------------------------------- GC window stats

def test_gc_window_stats_thread_into_profile_and_csv(tmp_path):
    @actor
    class Kid:
        x: I32

        @behaviour
        def init(self, st, v: I32):
            return {**st, "x": v}

    @actor
    class Boss:
        SPAWNS = {"Kid": 1}
        made: I32

        @behaviour
        def make(self, st, v: I32):
            self.spawn(Kid.init, v)
            return {**st, "made": st["made"] + 1}

    path = str(tmp_path / "gc.csv")
    rt = Runtime(_opts(msg_words=2, analysis=2, analysis_path=path))
    rt.declare(Boss, 1).declare(Kid, 8).start()
    boss = rt.spawn(Boss)
    for v in range(3):
        rt.send(boss, Boss.make, v)
    rt.run()
    collected = rt.gc()     # spawned Kids are unreferenced → collected
    assert collected == 3
    # One more window so the CSV sees the gc deltas.
    rt.send(boss, Boss.make, 9)
    rt.run()
    prof = rt.profile()
    assert prof["gc"]["passes"] >= 1
    assert prof["gc"]["collected"] >= 3
    assert "blob_slots_reclaimed" in prof["gc"]
    rt.stop()
    lines = open(path).read().strip().split("\n")
    header = lines[0].split(",")
    for col in ("gc_runs", "gc_collected", "gc_swept", "ev_dropped"):
        assert col in header
    rows = [dict(zip(header, l.split(","))) for l in lines[1:]]
    assert sum(int(r["gc_runs"]) for r in rows) >= 1
    assert sum(int(r["gc_collected"]) for r in rows) >= 3


# ------------------------------------------- chrome trace / CLI surfaces

def test_chrome_trace_per_behaviour_tracks(tmp_path):
    """Acceptance: chrome_trace output carries one counter track per
    hot behaviour and validates against the Chrome-trace JSON schema
    Perfetto loads."""
    path = str(tmp_path / "an.csv")
    rt, ids = ring.build(8, _opts(analysis=2, analysis_path=path))
    rt.send(int(ids[0]), ring.RingNode.token, 40)
    rt.run()
    rt.stop()
    out = str(tmp_path / "t.json")
    analysis.chrome_trace(path, out)
    doc = json.load(open(out))
    assert set(doc) == {"traceEvents", "displayTimeUnit"}
    evs = doc["traceEvents"]
    for e in evs:        # minimal Perfetto/Chrome-trace event schema
        assert e["ph"] in ("M", "C", "i")
        assert isinstance(e["pid"], int)
        assert isinstance(e["name"], str)
        if e["ph"] != "M":
            assert isinstance(e["ts"], float)
        if e["ph"] == "C":
            assert all(isinstance(v, int) for v in e["args"].values())
    beh = [e for e in evs
           if e["ph"] == "C" and e["name"] == "behaviour RingNode.token"]
    assert beh, "no per-behaviour counter track"
    assert sum(e["args"]["runs"] for e in beh) == 40
    qw = [e for e in evs
          if e["ph"] == "C" and e["name"] == "queue-wait RingNode"]
    assert qw and all(set(e["args"]) == {"p50", "p99"} for e in qw)


def test_chrome_trace_pre_profiler_csv(tmp_path):
    """Old CSVs (no dynamic columns) still convert — the trace CLI must
    work on files written by earlier runtimes."""
    path = str(tmp_path / "old.csv")
    cols = ["time_ms", "step", "processed", "delivered", "rejected",
            "badmsg", "deadletter", "mutes", "occ_sum", "occ_max",
            "muted_now", "overloaded_now", "host_processed",
            "inject_queue", "fast_queue", "rss_kb", "cpu_ms"]
    with open(path, "w") as f:
        f.write(",".join(cols) + "\n")
        f.write(",".join(["1.0", "1"] + ["2"] * (len(cols) - 2)) + "\n")
    out = str(tmp_path / "old.json")
    analysis.chrome_trace(path, out)
    doc = json.load(open(out))
    assert any(e["name"] == "window throughput"
               for e in doc["traceEvents"])


def test_trace_cli(tmp_path):
    """The `ponyc_tpu trace` subcommand: conversion + usage errors."""
    from ponyc_tpu.__main__ import main as cli_main
    path = str(tmp_path / "an.csv")
    rt, ids = ring.build(8, _opts(analysis=2, analysis_path=path))
    rt.send(int(ids[0]), ring.RingNode.token, 10)
    rt.run()
    rt.stop()
    out = str(tmp_path / "cli.json")
    assert cli_main(["trace", path, "-o", out]) == 0
    assert json.load(open(out))["traceEvents"]
    assert cli_main(["trace"]) == 2            # missing csv
    assert cli_main(["trace", "-o"]) == 2      # -o without a path


def test_top_frame_and_cli(tmp_path, capsys):
    path = str(tmp_path / "an.csv")
    rt, ids = ring.build(8, _opts(analysis=2, analysis_path=path))
    rt.send(int(ids[0]), ring.RingNode.token, 30)
    rt.run()
    rt.stop()
    frame = analysis.top_frame(path)
    assert "RingNode.token" in frame
    assert "queue-wait" in frame
    assert "step " in frame and "gc:" in frame
    from ponyc_tpu.__main__ import main as cli_main
    assert cli_main(["top", path, "--once"]) == 0
    out = capsys.readouterr().out
    assert "RingNode.token" in out
    # usage errors
    assert cli_main(["top", "--interval"]) == 2
    assert cli_main(["top", "--interval", "nope"]) == 2
    assert cli_main(["top", "a.csv", "b.csv"]) == 2
    # a missing file waits rather than crashing
    assert cli_main(["top", str(tmp_path / "absent.csv"),
                     "--once"]) == 0
    assert "waiting" in capsys.readouterr().out


def test_top_frame_empty_csv(tmp_path):
    path = str(tmp_path / "empty.csv")
    with open(path, "w") as f:
        f.write(",".join(analysis.CSV_COLUMNS) + "\n")
    assert "no windows" in analysis.top_frame(path)


# ------------------------------------------------- signal / CLI smokes

def test_sigterm_dumps_then_terminates(tmp_path):
    """Satellite fix: after a level-1 dump on SIGTERM the handler
    restores the default disposition and re-raises, so the process
    actually dies of SIGTERM (the old lambda swallowed it forever)."""
    code = f"""
import os, signal, sys
sys.path.insert(0, {ROOT!r})
from ponyc_tpu.platforms import force_cpu
force_cpu()
from ponyc_tpu import RuntimeOptions, analysis
from ponyc_tpu.models import ring
rt, ids = ring.build(4, RuntimeOptions(
    mailbox_cap=8, batch=1, max_sends=1, msg_words=1, analysis=1))
rt.send(int(ids[0]), ring.RingNode.token, 5)
rt.run()
a = analysis.attach(rt)
os.kill(os.getpid(), signal.SIGTERM)
print("SURVIVED-SIGTERM")
"""
    p = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=300, env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == -signal.SIGTERM, (p.returncode, p.stderr)
    assert "ponyc_tpu analysis dump" in p.stderr
    assert "SURVIVED-SIGTERM" not in p.stdout


@pytest.mark.parametrize("flush_ms", [-1])
def test_analysis_flush_ms_validated(flush_ms):
    with pytest.raises(ValueError, match="analysis_flush_ms"):
        RuntimeOptions(analysis_flush_ms=flush_ms)


def test_example_smoke_analysis2(tmp_path):
    """Tier-1 smoke: run a shipped example through the CLI at
    analysis=2 and validate the window CSV schema end to end,
    including the per-behaviour columns (satellite)."""
    path = str(tmp_path / "counter.csv")
    p = subprocess.run(
        [sys.executable, "-m", "ponyc_tpu", "run",
         os.path.join(ROOT, "examples", "counter.py"),
         "--ponyanalysis=2", f"--ponyanalysis_path={path}"],
        capture_output=True, text=True, timeout=300, cwd=ROOT,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert p.returncode == 0, (p.stdout, p.stderr)
    lines = open(path).read().strip().split("\n")
    header = lines[0].split(",")
    assert header[:len(analysis.CSV_COLUMNS)] == analysis.CSV_COLUMNS
    for col in ("run:Counter.increment", "run:Counter.report",
                "run:Reporter.result", "qw50:Counter", "qw99:Counter"):
        assert col in header, col
    rows = [dict(zip(header, l.split(","))) for l in lines[1:]]
    # 8 counters × (100 increments sent as 25 messages of +4) = 200
    assert sum(int(r["run:Counter.increment"]) for r in rows) == 200
    assert sum(int(r["run:Counter.report"]) for r in rows) == 8
    # the dump summary (level >= 1) ran on exit too
    assert "analysis dump" in p.stderr
