"""Test env: force an 8-device virtual CPU mesh before any JAX use.

≙ the reference's fake-stdlib/PassTest fixture strategy (test/libponyc/
util.h:32-82): tests run against a controllable substrate rather than the
real target. Multi-chip sharding tests use these 8 virtual devices; the
real TPU is exercised only by bench.py. The forcing dance (env var +
post-import config knob, needed because the axon TPU plugin re-asserts
itself over JAX_PLATFORMS) lives in ponyc_tpu.platforms.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from ponyc_tpu.platforms import force_cpu  # noqa: E402

force_cpu(8)


def pytest_configure(config):
    # Tier-1 runs with `-m 'not slow'` (ROADMAP); register the marker
    # so opting a heavyweight test out of the budget is warning-free.
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 budgeted run")
