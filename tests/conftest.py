"""Test env: force an 8-device virtual CPU mesh before JAX import.

≙ the reference's fake-stdlib/PassTest fixture strategy (test/libponyc/
util.h:32-82): tests run against a controllable substrate rather than the
real target. Multi-chip sharding tests use these 8 virtual devices; the
real TPU is exercised only by bench.py.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"   # override the env's axon default
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin re-asserts itself over JAX_PLATFORMS at import time;
# the config knob set after import is authoritative.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
