"""Parametric actor types + reification (≙ reference generics,
src/libponyc/type/reify.c: formal type parameters substituted at
instantiation; codegen only ever sees concrete reifications)."""

import numpy as np
import pytest

from ponyc_tpu import (F32, I32, Ref, Runtime, RuntimeOptions, TypeParam,
                       actor, behaviour)

T = TypeParam("T")

OPTS = RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1, msg_words=2,
                      inject_slots=8)


@actor
class Cell:
    """A generic storage cell: Cell[I32], Cell[F32]."""
    value: T

    @behaviour
    def put(self, st, v: T):
        return {**st, "value": v}


@actor
class Pair:
    """Two parameters."""
    a: TypeParam("A")
    b: TypeParam("B")

    @behaviour
    def set_both(self, st, x: TypeParam("A"), y: TypeParam("B")):
        return {**st, "a": x, "b": y}


def test_generic_type_cannot_be_declared():
    rt = Runtime(OPTS)
    with pytest.raises(TypeError, match="generic over"):
        rt.declare(Cell, 2)


def test_reifications_are_cached_and_distinct():
    assert Cell[I32] is Cell[I32]
    assert Cell[I32] is not Cell[F32]
    assert Cell[I32].__name__ == "Cell[I32]"
    assert Cell[F32].field_specs["value"].__name__ == "F32"
    # behaviour specs substituted per reification
    assert Cell[I32].put.arg_specs[0].__name__ == "I32"
    assert Cell[F32].put.arg_specs[0].__name__ == "F32"
    # the generic template is untouched
    assert Cell._type_params and Cell.put.arg_specs[0] is T


def test_wrong_arity_rejected():
    with pytest.raises(TypeError, match="takes 1 type argument"):
        Cell[I32, F32]
    with pytest.raises(TypeError, match="not generic"):
        Cell[I32][I32]


def test_two_reifications_run_side_by_side():
    IntCell, FloatCell = Cell[I32], Cell[F32]
    rt = Runtime(OPTS)
    rt.declare(IntCell, 2).declare(FloatCell, 2).start()
    ic = rt.spawn(IntCell)
    fc = rt.spawn(FloatCell)
    rt.send(ic, IntCell.put, 41)
    rt.send(fc, FloatCell.put, 2.5)
    assert rt.run(max_steps=16) == 0
    assert rt.state_of(ic)["value"] == 41
    assert rt.state_of(fc)["value"] == 2.5


def test_multi_param_reification():
    PIF = Pair[I32, F32]
    rt = Runtime(OPTS)
    rt.declare(PIF, 1).start()
    p = rt.spawn(PIF)
    rt.send(p, PIF.set_both, 7, 1.5)
    assert rt.run(max_steps=16) == 0
    st = rt.state_of(p)
    assert st["a"] == 7 and st["b"] == 1.5


def test_ref_of_reified_type_is_wiring_checked():
    """Ref[Cell[I32]] participates in the sendability checker like any
    concrete type: sending the wrong reification's behaviour fails the
    build."""
    IntCell, FloatCell = Cell[I32], Cell[F32]

    @actor
    class User:
        out: Ref[Cell[I32]]
        MAX_SENDS = 1

        @behaviour
        def go(self, st, v: I32):
            self.send(st["out"], FloatCell.put, 1.0)   # wrong reif.
            return st

    rt = Runtime(OPTS)
    rt.declare(User, 1).declare(IntCell, 1).declare(FloatCell, 1).start()
    u = rt.spawn(User)
    rt.send(u, User.go, 0)
    with pytest.raises(TypeError, match="sendability"):
        rt.run(max_steps=4)


def test_generic_over_ref_target():
    """Ref[T]: the parameter is an ACTOR type — a generic forwarder
    reified per target type (the actor-typed half of reify.c)."""
    R = TypeParam("R")

    @actor
    class Sink1:
        got: I32

        @behaviour
        def hit(self, st, v: I32):
            return {**st, "got": st["got"] + v}

    @actor
    class Fwd:
        out: Ref[R]
        MAX_SENDS = 1

        @behaviour
        def fwd(self, st, v: I32):
            self.send(st["out"], Sink1.hit, v)
            return st

    FS = Fwd[Sink1]
    assert FS.field_specs["out"].target_name == "Sink1"
    rt = Runtime(OPTS)
    rt.declare(FS, 1).declare(Sink1, 1).start()
    s = rt.spawn(Sink1)
    f = rt.spawn(FS, out=int(s))
    rt.send(f, FS.fwd, 9)
    assert rt.run(max_steps=16) == 0
    assert rt.state_of(s)["got"] == 9


def test_partial_application_stays_generic():
    """Cell[U] with U itself a TypeParam is still generic: it must
    refuse declare() exactly like the template (review finding)."""
    U = TypeParam("U")
    CU = Cell[U]
    assert CU._type_params == (U,)
    rt = Runtime(OPTS)
    with pytest.raises(TypeError, match="generic over"):
        rt.declare(CU, 1)
    # and completing the application works
    CI = CU[I32]
    assert CI.field_specs["value"].__name__ == "I32"


def test_same_name_type_args_do_not_collide():
    """Two distinct actor classes sharing a __name__ must reify to
    DISTINCT types (cache keys by class object, review finding)."""
    R = TypeParam("R")

    @actor
    class Box:
        out: Ref[R]

        @behaviour
        def poke(self, st, v: I32):
            return st

    def make_worker(tag):
        @actor
        class Worker:
            x: I32

            @behaviour
            def go(self, st, v: I32):
                return {**st, "x": v + tag}
        return Worker

    W1, W2 = make_worker(1), make_worker(2)
    assert W1.__name__ == W2.__name__ == "Worker"
    B1, B2 = Box[W1], Box[W2]
    assert B1 is not B2
    assert B1.field_specs["out"].target is W1
    assert B2.field_specs["out"].target is W2


def test_spawn_state_defaults_per_reification():
    IntCell = Cell[I32]
    rt = Runtime(OPTS)
    rt.declare(IntCell, 3).start()
    ids = rt.spawn_many(IntCell, 3, value=np.asarray([1, 2, 3]))
    assert rt.run(max_steps=4) == 0
    st = rt.cohort_state(IntCell)
    assert list(st["value"][:3]) == [1, 2, 3]
