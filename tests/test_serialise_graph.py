"""Selective object-graph serialisation (stdlib/serialise.py).

≙ src/libponyrt/gc/serialise.c:33-47 (single object-graph flatten with
an offset object map) + packages/serialise (auth-token surface). The
world-checkpoint tests live in test_serialise.py; these cover the
per-graph sibling: shared substructure, cycles, capability-aware handle
walking, payload round trips through a real actor send, and the auth
gates."""

import pytest

from ponyc_tpu import I32, Iso, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.hostmem import CapabilityError, HostHeap
from ponyc_tpu.stdlib.serialise import (DeserialiseAuth, HandleRef,
                                        OutputSerialisedAuth,
                                        SerialiseAuth, Serialised,
                                        SerialiseError,
                                        deserialise_from_handle,
                                        serialise_to_handle)


def roundtrip(obj, heap_out=None, heap_in=None):
    s = Serialised(SerialiseAuth(), obj, heap=heap_out)
    data = s.output(OutputSerialisedAuth())
    return Serialised.from_bytes(data).apply(DeserialiseAuth(),
                                             heap=heap_in)


def test_scalars_and_containers():
    obj = {"a": [1, 2.5, "three", b"\x00\xff", None, True],
           "b": (7, 8), "c": {"nested": {1: "one"}},
           "big": 2 ** 80, "s": {3, 1, 2}}
    got = roundtrip(obj)
    assert got == obj
    assert isinstance(got["b"], tuple) and isinstance(got["s"], set)


def test_shared_substructure_is_preserved():
    shared = [1, 2, 3]
    obj = {"x": shared, "y": shared}
    got = roundtrip(obj)
    assert got["x"] == [1, 2, 3]
    assert got["x"] is got["y"], "diamond collapsed to two copies"


def test_cycles_roundtrip():
    a = {"name": "a"}
    b = {"name": "b", "peer": a}
    a["peer"] = b                       # 2-cycle
    lst = [1]
    lst.append(lst)                     # self-cycle
    got = roundtrip({"pair": a, "loop": lst})
    assert got["pair"]["peer"]["peer"] is got["pair"]
    assert got["loop"][1] is got["loop"]


def test_handle_walk_iso_moves_val_copies_tag_rejects():
    h = HostHeap()
    iso_h = h.box({"kind": "iso-payload"})
    val_h = h.box_val("shared-text")
    obj = {"moved": HandleRef(iso_h), "copied": HandleRef(val_h)}
    s = Serialised(SerialiseAuth(), obj, heap=h)
    # iso target was CONSUMED by the walk (the move rides serialisation)
    with pytest.raises(KeyError):
        h.peek(iso_h)
    # val target survives (shared-immutable copy)
    assert h.peek(val_h) == "shared-text"
    h2 = HostHeap()
    got = s.output(OutputSerialisedAuth())
    got = Serialised.from_bytes(got).apply(DeserialiseAuth(), heap=h2)
    assert h2.unbox(got["moved"].handle) == {"kind": "iso-payload"}
    assert h2.unbox(got["copied"].handle) == "shared-text"
    # tag refuses: opaque addresses have no readable content
    tag_h = h.box_tag(object())
    with pytest.raises(CapabilityError, match="opaque"):
        Serialised(SerialiseAuth(), HandleRef(tag_h), heap=h)


def test_failed_walk_leaves_heap_untouched():
    """A serialisation error must not half-destroy the caller's graph:
    iso moves commit only after the whole walk succeeds."""

    class Bad:
        pass

    h = HostHeap()
    iso_h = h.box({"keep": "me"})
    with pytest.raises(SerialiseError):
        Serialised(SerialiseAuth(), [HandleRef(iso_h), Bad()], heap=h)
    assert h.peek(iso_h) == {"keep": "me"}    # survived the failure


def test_aliased_iso_in_one_graph_rejected():
    h = HostHeap()
    iso_h = h.box("x")
    with pytest.raises(CapabilityError, match="aliased move"):
        Serialised(SerialiseAuth(),
                   [HandleRef(iso_h), HandleRef(iso_h)], heap=h)
    assert h.peek(iso_h) == "x"               # untouched


def test_auth_tokens_gate_every_operation():
    with pytest.raises(TypeError, match="SerialiseAuth"):
        Serialised(object(), [1])
    s = Serialised(SerialiseAuth(), [1])
    with pytest.raises(TypeError, match="OutputSerialisedAuth"):
        s.output(object())
    with pytest.raises(TypeError, match="DeserialiseAuth"):
        s.apply(object())


def test_unserialisable_object_rejected():
    class Custom:
        pass

    with pytest.raises(SerialiseError, match="unserialisable"):
        Serialised(SerialiseAuth(), {"bad": Custom()})


def test_hostile_buffer_rejected():
    with pytest.raises(SerialiseError):
        Serialised.from_bytes(b"XXXX\x01\x00\x00\x00...")
    with pytest.raises(SerialiseError):
        Serialised.from_bytes(b"PTSG" + b"\x01\x00\x00\x00"
                              + b"\x01\x00\x00\x00" + b"not json")


def test_graph_rides_actor_message():
    """The payload use case end to end: serialise a graph, box it iso,
    send the handle through the runtime to a host actor, receiver
    deserialises — exactly serialise.c's IPC role."""
    received = []

    @actor
    class GraphSink:
        HOST = True
        got: I32

        @behaviour
        def recv(self, st, h: Iso):
            obj = deserialise_from_handle(DeserialiseAuth(), int(h),
                                          self.rt.heap)
            received.append(obj)
            return {**st, "got": st["got"] + 1}

    rt = Runtime(RuntimeOptions(mailbox_cap=8, batch=1, max_sends=1,
                                msg_words=2, inject_slots=8))
    rt.declare(GraphSink, 1).start()
    sink = rt.spawn(GraphSink)
    inner = {"deep": [1, 2, {"x": "y"}]}
    graph = {"payload": inner, "alias": inner}
    hd = serialise_to_handle(SerialiseAuth(), graph, rt.heap)
    rt.send(sink, GraphSink.recv, hd)
    assert rt.run(max_steps=64) == 0
    assert rt.state_of(sink)["got"] == 1
    got = received[0]
    assert got == graph
    assert got["payload"] is got["alias"]
    assert rt.heap.live == 0            # bytes handle consumed
