"""Delivery formulation equivalence: the cached-plan path and the
co-sort path (RuntimeOptions.delivery) must produce identical behaviour —
same totals under sustained traffic and under backpressure/spill
(delivery.py's two formulations of the same sort+segment semantics)."""

import pytest

from ponyc_tpu import RuntimeOptions


@pytest.mark.parametrize("mode", ["plan", "cosort"])
def test_ubench_sustained(mode):
    from ponyc_tpu.models import ubench
    opts = RuntimeOptions(mailbox_cap=4, batch=4, max_sends=1, msg_words=1,
                          spill_cap=256, inject_slots=8, delivery=mode)
    rt, ids = ubench.build(256, opts, pings=4)
    ubench.seed_all(rt, ids, hops=1 << 30, pings=4)
    st, inj = rt.state, rt._empty_inject
    for _ in range(6):
        st, aux = rt._step(st, *inj)
    rt.state = st
    assert rt.counter("n_processed") == 6 * 256 * 4
    assert not bool(aux.spill_overflow)


@pytest.mark.parametrize("mode", ["plan", "cosort"])
def test_fanin_pressure(mode):
    from ponyc_tpu.models import fanin
    rt = fanin.run(n_producers=24, items_each=30, opts=RuntimeOptions(
        mailbox_cap=8, batch=2, msg_words=1, max_sends=2, spill_cap=512,
        inject_slots=16, delivery=mode))
    assert int(rt.cohort_state(fanin.Aggregator)["total"].sum()) == 24 * 30


def test_bad_delivery_mode_rejected():
    with pytest.raises(ValueError):
        RuntimeOptions(delivery="nope")
