"""Per-cohort mailbox word widths (≙ per-type pony_msg_t sizes —
src/libponyc/codegen/genfun.c packs exactly each behaviour's params;
no type pays another type's message width).

RuntimeOptions.msg_words stays the program-wide declared max (outbox/
spill/inject width); each cohort's mailbox TABLE narrows to its own
widest behaviour — the dominant HBM array (cap × w1 × N) stops paying
the widest type's footprint for narrow types.
"""

import jax.numpy as jnp
import numpy as np

from ponyc_tpu import (F32, I32, Ref, Runtime, RuntimeOptions, VecF32,
                       actor, behaviour)

OPTS = RuntimeOptions(mailbox_cap=4, batch=2, max_sends=1, msg_words=7,
                      inject_slots=8)


@actor
class Wide:
    acc: F32
    hits: I32

    @behaviour
    def take(self, st, v: VecF32[6], scale: F32):
        return {"acc": st["acc"] + scale * jnp.sum(v, axis=0),
                "hits": st["hits"] + 1}


@actor
class Narrow:
    out: Ref["Wide"]
    fired: I32
    MAX_SENDS = 1

    @behaviour
    def fire(self, st):                      # zero payload words
        self.send(st["out"], Wide.take,
                  jnp.arange(6, dtype=jnp.float32), 2.0)
        return {**st, "fired": st["fired"] + 1}


def _build():
    rt = Runtime(OPTS)
    rt.declare(Wide, 4).declare(Narrow, 4).start()
    return rt


def test_cohort_tables_have_their_own_width():
    rt = _build()
    # Wide.take needs 6 (vector) + 1 (scale) = 7 words; Narrow.fire 0.
    assert rt.state.buf["Wide"].shape[1] == 1 + 7
    assert rt.state.buf["Narrow"].shape[1] == 1      # gid word only
    # Spills keep the global width (messages for ANY target park there).
    assert rt.state.dspill_words.shape[0] == 1 + OPTS.msg_words


def test_cross_width_messaging_roundtrip():
    rt = _build()
    w = rt.spawn(Wide)
    n = rt.spawn(Narrow, out=w)
    for _ in range(3):
        rt.send(n, Narrow.fire)
    rt.run(max_steps=16)
    ws = rt.cohort_state(Wide)
    col = rt.program.by_type_name("Wide").gid_to_col(w)
    assert int(ws["hits"][col]) == 3
    # sum(0..5) * 2.0 * 3 fires = 90.
    assert float(ws["acc"][col]) == 90.0
    ns = rt.cohort_state(Narrow)
    ncol = rt.program.by_type_name("Narrow").gid_to_col(n)
    assert int(ns["fired"][ncol]) == 3


def test_host_send_into_wide_cohort_packs_full_width():
    rt = _build()
    w = rt.spawn(Wide)
    rt.send(w, Wide.take, np.arange(6, dtype=np.float32), 0.5)
    rt.run(max_steps=8)
    col = rt.program.by_type_name("Wide").gid_to_col(w)
    assert float(rt.cohort_state(Wide)["acc"][col]) == 7.5


def test_bulk_send_into_narrow_cohort():
    rt = _build()
    w = rt.spawn(Wide)
    ids = [rt.spawn(Narrow, out=w) for _ in range(3)]
    rt.bulk_send(np.asarray(ids), Narrow.fire)
    rt.run(max_steps=16)
    assert int(rt.cohort_state(Wide)["hits"].sum()) == 3
