"""TLS over the net actor layer (net/tls.py ≙ lang/ssl.c hooks + the
SSL-connection filter the reference stdlib layers over them): a real
encrypted loopback echo between two actors in one runtime, deferred
on_connect-after-handshake semantics, pre-handshake write buffering,
and handshake failure surfacing."""

import datetime
import os

import pytest

# The self-signed test certificates come from the optional `cryptography`
# package (README: optional extras). The TLS layer itself is stdlib-ssl
# only; without the cert generator these tests skip rather than error.
pytest.importorskip("cryptography")

from ponyc_tpu import I32, Runtime, RuntimeOptions, actor, behaviour
from ponyc_tpu.net.tls import TLSClientConfig, TLSServerConfig


@pytest.fixture(scope="module")
def certpair(tmp_path_factory):
    """Self-signed localhost cert via the cryptography package."""
    from cryptography import x509
    from cryptography.hazmat.primitives import hashes, serialization
    from cryptography.hazmat.primitives.asymmetric import rsa
    from cryptography.x509.oid import NameOID

    d = tmp_path_factory.mktemp("tls")
    key = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    name = x509.Name(
        [x509.NameAttribute(NameOID.COMMON_NAME, "localhost")])
    now = datetime.datetime.now(datetime.timezone.utc)
    cert = (x509.CertificateBuilder()
            .subject_name(name).issuer_name(name)
            .public_key(key.public_key())
            .serial_number(x509.random_serial_number())
            .not_valid_before(now - datetime.timedelta(minutes=5))
            .not_valid_after(now + datetime.timedelta(days=1))
            .add_extension(
                x509.SubjectAlternativeName(
                    [x509.DNSName("localhost")]), critical=False)
            .sign(key, hashes.SHA256()))
    certfile = str(d / "cert.pem")
    keyfile = str(d / "key.pem")
    with open(certfile, "wb") as f:
        f.write(cert.public_bytes(serialization.Encoding.PEM))
    with open(keyfile, "wb") as f:
        f.write(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.TraditionalOpenSSL,
            serialization.NoEncryption()))
    return certfile, keyfile


def _opts():
    return RuntimeOptions(mailbox_cap=16, batch=4, max_sends=0,
                          msg_words=3, inject_slots=64)


def test_tls_echo_roundtrip(certpair):
    certfile, keyfile = certpair
    state = {"server_got": [], "client_got": [], "connect_err": None}

    @actor
    class Server:
        HOST = True
        n: I32

        @behaviour
        def on_accept(self, st, cid: I32):
            return st

        @behaviour
        def on_data(self, st, cid: I32, h: I32, n: I32):
            data = self.rt.heap.unbox(int(h))
            state["server_got"].append(data)
            self.rt.net.send(int(cid), b"echo:" + data)   # encrypted
            return st

        @behaviour
        def on_closed(self, st, cid: I32):
            return st

    @actor
    class Client:
        HOST = True
        n: I32

        @behaviour
        def on_connect(self, st, cid: I32, err: I32):
            state["connect_err"] = int(err)
            return st

        @behaviour
        def on_data(self, st, cid: I32, h: I32, n: I32):
            state["client_got"].append(self.rt.heap.unbox(int(h)))
            self.rt.request_exit(0)
            return st

        @behaviour
        def on_closed(self, st, cid: I32):
            return st

    rt = Runtime(_opts())
    rt.declare(Server, 1).declare(Client, 1).start()
    srv = rt.spawn(Server)
    cli = rt.spawn(Client)
    net = rt.attach_net()
    lid = net.listen_tcp("127.0.0.1", 0, srv,
                         on_accept=Server.on_accept,
                         on_data=Server.on_data,
                         on_closed=Server.on_closed,
                         tls=TLSServerConfig(certfile, keyfile))
    port = net.listen_port(lid)
    cid = net.connect_tcp("127.0.0.1", port, cli,
                          on_connect=Client.on_connect,
                          on_data=Client.on_data,
                          on_closed=Client.on_closed,
                          tls=TLSClientConfig("localhost",
                                              cafile=certfile))
    # Pre-handshake write: buffered plaintext, flushed post-handshake.
    net.send(cid, b"hello-tls")
    rt.run(max_steps=100_000)
    assert state["connect_err"] == 0, "handshake did not complete"
    assert state["server_got"] == [b"hello-tls"]
    assert state["client_got"] == [b"echo:hello-tls"]
    net.close_all()


def test_tls_handshake_failure_surfaces(certpair):
    """A VERIFYING client against a self-signed server it does not
    trust: on_connect must deliver err=-1, not hang or deliver data."""
    certfile, keyfile = certpair
    state = {"err": None, "data": []}

    @actor
    class Srv2:
        HOST = True
        n: I32

        @behaviour
        def on_accept(self, st, cid: I32):
            return st

        @behaviour
        def on_data(self, st, cid: I32, h: I32, n: I32):
            self.rt.heap.drop(int(h))
            return st

        @behaviour
        def on_closed(self, st, cid: I32):
            return st

    @actor
    class Cli2:
        HOST = True
        n: I32

        @behaviour
        def on_connect(self, st, cid: I32, err: I32):
            state["err"] = int(err)
            self.rt.request_exit(0)
            return st

        @behaviour
        def on_data(self, st, cid: I32, h: I32, n: I32):
            state["data"].append(self.rt.heap.unbox(int(h)))
            return st

        @behaviour
        def on_closed(self, st, cid: I32):
            return st

    rt = Runtime(_opts())
    rt.declare(Srv2, 1).declare(Cli2, 1).start()
    srv = rt.spawn(Srv2)
    cli = rt.spawn(Cli2)
    net = rt.attach_net()
    lid = net.listen_tcp("127.0.0.1", 0, srv,
                         on_accept=Srv2.on_accept, on_data=Srv2.on_data,
                         on_closed=Srv2.on_closed,
                         tls=TLSServerConfig(certfile, keyfile))
    port = net.listen_port(lid)
    net.connect_tcp("127.0.0.1", port, cli,
                    on_connect=Cli2.on_connect, on_data=Cli2.on_data,
                    on_closed=Cli2.on_closed,
                    tls=TLSClientConfig("localhost"))   # system CAs: fails
    rt.run(max_steps=100_000)
    assert state["err"] == -1
    assert state["data"] == []
    net.close_all()


def test_plain_tcp_still_works_alongside():
    """tls=None path unchanged (regression guard for the integration)."""
    state = {"got": []}

    @actor
    class P:
        HOST = True
        n: I32

        @behaviour
        def on_accept(self, st, cid: I32):
            return st

        @behaviour
        def on_data(self, st, cid: I32, h: I32, n: I32):
            state["got"].append(self.rt.heap.unbox(int(h)))
            self.rt.request_exit(0)
            return st

        @behaviour
        def on_closed(self, st, cid: I32):
            return st

        @behaviour
        def on_connect(self, st, cid: I32, err: I32):
            self.rt.net.send(int(cid), b"plain")
            return st

    rt = Runtime(_opts())
    rt.declare(P, 2).start()
    a, b = rt.spawn_many(P, 2)
    net = rt.attach_net()
    lid = net.listen_tcp("127.0.0.1", 0, int(a),
                         on_accept=P.on_accept, on_data=P.on_data,
                         on_closed=P.on_closed)
    net.connect_tcp("127.0.0.1", net.listen_port(lid), int(b),
                    on_connect=P.on_connect, on_data=P.on_data,
                    on_closed=P.on_closed)
    rt.run(max_steps=100_000)
    assert state["got"] == [b"plain"]
    net.close_all()
